"""Node/edge IR and the validating graph builder.

An :class:`Edge` is a named artifact with a placement declaring where its
value lives between producer and consumer:

- ``hbm``  — device-resident (arrays stay on the accelerator; the
  executor keeps the value alive only from producer to last consumer and
  drops it immediately after, which is what makes buffer donation safe);
- ``host`` — host RAM (plain Python values);
- ``disk`` — a filesystem artifact (paths; the only placement that can
  survive a process restart, hence the resume-boundary rules below).

A :class:`Node` is a stage: a callable ``fn(ctx, inputs) -> outputs``
plus declared input/output edge names, workload ``units`` (int or
``callable(ctx, inputs)``) feeding the watchdog's scaled deadlines, an
optional ``commit`` hook that must run on the main thread (log writes
for overlapped stages), a ``checkpoint`` flag (all pending off-critical-
path work is committed before the node body runs, so its manifest mark
covers a consistent state), and optional resume fields: ``resume_key``
names the manifest-v2 stage entry, ``resume_probe(ctx)`` returns the
disk artifact to sha256-verify (or None when absent), ``resume_reload``
rebuilds the values of ``resume_provides`` edges from disk.

:class:`GraphBuilder.build` validates the whole declaration and raises
:class:`GraphValidationError` carrying every named problem at once —
cycles (with member names), undeclared/dangling edges, duplicate
producers, unknown placements, and resume boundaries: an ``hbm`` edge
may not cross a disk-resume boundary (device memory cannot survive a
restart), and every crossing edge must be covered by the resume node's
``resume_provides``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Iterable

PLACEMENTS = ("hbm", "host", "disk")


@dataclasses.dataclass(frozen=True)
class Edge:
    """A named, placement-typed artifact flowing between nodes.

    ``sharding`` is an optional device-layout spec name (ROADMAP item 2
    groundwork): a label like ``"data"`` naming how an ``hbm`` value is
    laid out across the mesh.  The executor ignores it for now; graftcheck
    pairs producer-side and consumer-side specs and reports any node whose
    hbm inputs and outputs disagree as a reshard site.  Only ``hbm`` edges
    may carry one — host/disk values have no device layout.

    ``meta`` marks a ``host`` edge as orchestration metadata: stats,
    groupings, index selections — small coordination values whose bytes
    are negligible next to the bulk stores and whose host residency is by
    design, not an accident of the data plane.  graftcheck's round-trip
    analysis skips meta edges (they are not re-uploaded payload), while
    the transfer ledger still measures their bytes per edge, so the
    declaration is auditable rather than a blind waiver.  Only ``host``
    edges may carry it — an hbm/disk value cannot be "metadata at rest".
    """

    name: str
    placement: str
    sharding: str | None = None
    meta: bool = False


@dataclasses.dataclass
class Node:
    """One stage of the graph; see module docstring for field semantics."""

    name: str
    fn: Callable[..., dict] | None
    inputs: tuple[str, ...]
    outputs: tuple[str, ...]
    units: int | Callable[..., int] = 0
    commit: Callable[..., None] | None = None
    checkpoint: bool = False
    resume_key: str | None = None
    resume_probe: Callable[[Any], str | None] | None = None
    resume_reload: Callable[[Any], dict] | None = None
    resume_provides: tuple[str, ...] = ()

    def eval_units(self, ctx: Any, inputs: dict) -> int:
        u = self.units
        return int(u(ctx, inputs)) if callable(u) else int(u)


class GraphValidationError(ValueError):
    """Raised by :meth:`GraphBuilder.build`; ``problems`` is the full list
    of human-readable validation failures (``--validate`` prints each)."""

    def __init__(self, problems: Iterable[str]):
        self.problems = list(problems)
        super().__init__(
            "invalid stage graph:\n"
            + "\n".join(f"  - {p}" for p in self.problems)
        )


class GraphSpec:
    """A validated, schedulable graph (only :class:`GraphBuilder` builds
    these)."""

    def __init__(self, name: str, nodes: list[Node], edges: dict[str, Edge],
                 inputs: frozenset[str], results: tuple[str, ...],
                 schedule: list[Node]):
        self.name = name
        self.nodes = {n.name: n for n in nodes}
        self.edges = edges
        self.inputs = inputs
        self.results = results
        self.schedule = schedule
        self.producer: dict[str, str] = {}
        self.consumers: dict[str, list[str]] = {}
        for n in nodes:
            for e in n.outputs:
                self.producer[e] = n.name
            for e in n.inputs:
                self.consumers.setdefault(e, []).append(n.name)

    def is_side_sink(self, node: Node) -> bool:
        """True when the node is off the critical path purely by edge
        declaration: nothing consumes its outputs, none of them are graph
        results, and it carries no checkpoint/resume responsibility."""
        if node.checkpoint or node.resume_key is not None:
            return False
        return all(
            not self.consumers.get(e) and e not in self.results
            for e in node.outputs
        )

    def side_sinks(self) -> list[str]:
        return [n.name for n in self.schedule if self.is_side_sink(n)]

    def ancestors(self, name: str) -> set[str]:
        """Transitive producers of ``name``'s inputs (node names)."""
        out: set[str] = set()
        frontier = [name]
        while frontier:
            node = self.nodes[frontier.pop()]
            for e in node.inputs:
                p = self.producer.get(e)
                if p is not None and p not in out:
                    out.add(p)
                    frontier.append(p)
        return out

    def skip_closure(self, name: str) -> set[str]:
        """Node names skippable when ``name`` resumes from disk: its
        ancestors plus itself, then — iteratively — every node whose
        inputs are all produced inside the set (side sinks hanging off
        skipped producers, which the imperative resume path never ran
        either)."""
        closure = self.ancestors(name) | {name}
        grew = True
        while grew:
            grew = False
            for n in self.schedule:
                if n.name in closure or not self.is_side_sink(n):
                    # only side sinks are absorbable: any other node's
                    # outputs feed nodes OUTSIDE the closure, and a reload
                    # only reconstructs the resume node's own provides
                    continue
                if n.inputs and all(
                    self.producer.get(e) in closure for e in n.inputs
                ):
                    closure.add(n.name)
                    grew = True
        return closure

    def crossing_edges(self, name: str) -> list[str]:
        """Edges produced inside ``skip_closure(name)`` but consumed
        outside it — the values a resume reload must reconstruct."""
        closure = self.skip_closure(name)
        crossing = []
        for e, producer in self.producer.items():
            if producer not in closure:
                continue
            if any(c not in closure for c in self.consumers.get(e, ())):
                crossing.append(e)
        return sorted(crossing)

    def describe(self) -> dict:
        """Summary for telemetry/reporting (jax-free, JSON-safe)."""
        return {
            "name": self.name,
            "nodes": [n.name for n in self.schedule],
            "edges": {e.name: e.placement for e in self.edges.values()},
            "shardings": {e.name: e.sharding for e in self.edges.values()
                          if e.sharding is not None},
            "side_sinks": self.side_sinks(),
            "results": list(self.results),
        }


class GraphBuilder:
    """Accumulates edge/node declarations, then :meth:`build` validates
    everything at once and returns a :class:`GraphSpec`."""

    def __init__(self, name: str = "pipeline"):
        self.name = name
        self._nodes: list[Node] = []
        self._edges: dict[str, Edge] = {}
        self._inputs: set[str] = set()
        self._results: list[str] = []
        self._problems: list[str] = []

    def edge(self, name: str, placement: str,
             sharding: str | None = None, meta: bool = False) -> None:
        if name in self._edges:
            self._problems.append(f"edge {name!r} declared twice")
            return
        if placement not in PLACEMENTS:
            self._problems.append(
                f"edge {name!r}: unknown placement {placement!r} "
                f"(expected one of {'|'.join(PLACEMENTS)})"
            )
        if sharding is not None:
            if not isinstance(sharding, str) or not sharding:
                self._problems.append(
                    f"edge {name!r}: sharding spec must be a non-empty "
                    f"string, got {sharding!r}"
                )
                sharding = None
            elif placement != "hbm":
                self._problems.append(
                    f"edge {name!r}: sharding {sharding!r} declared on a "
                    f"{placement!r} edge (only hbm values have a device "
                    "layout)"
                )
                sharding = None
        if meta and placement != "host":
            self._problems.append(
                f"edge {name!r}: meta declared on a {placement!r} edge "
                "(only host-placed orchestration values can be metadata)"
            )
            meta = False
        self._edges[name] = Edge(name, placement, sharding, meta)

    def input(self, name: str, placement: str = "disk") -> None:
        self.edge(name, placement)
        self._inputs.add(name)

    def add_node(self, name: str, fn: Callable[..., dict] | None = None, *,
                 inputs: Iterable[str] = (), outputs: Iterable[str] = (),
                 units: int | Callable[..., int] = 0,
                 commit: Callable[..., None] | None = None,
                 checkpoint: bool = False,
                 resume_key: str | None = None,
                 resume_probe: Callable[[Any], str | None] | None = None,
                 resume_reload: Callable[[Any], dict] | None = None,
                 resume_provides: Iterable[str] = ()) -> None:
        if any(n.name == name for n in self._nodes):
            self._problems.append(f"node {name!r} declared twice")
            return
        self._nodes.append(Node(
            name=name, fn=fn, inputs=tuple(inputs), outputs=tuple(outputs),
            units=units, commit=commit,
            # a resume node is always a checkpoint barrier: pending
            # off-critical-path work must land before its manifest mark
            checkpoint=checkpoint or resume_key is not None,
            resume_key=resume_key, resume_probe=resume_probe,
            resume_reload=resume_reload,
            resume_provides=tuple(resume_provides),
        ))

    def result(self, *names: str) -> None:
        self._results.extend(names)

    def build(self) -> GraphSpec:
        problems = list(self._problems)
        producer: dict[str, str] = {}
        consumed: dict[str, list[str]] = {}
        node_names = {n.name for n in self._nodes}
        for e in self._edges:
            if e in node_names:
                problems.append(
                    f"edge {e!r} collides with a node of the same name — "
                    "schedules, telemetry and resume keys could not tell "
                    "them apart"
                )
        for n in self._nodes:
            for e in n.inputs:
                if e not in self._edges:
                    problems.append(f"node {n.name!r}: undeclared input edge {e!r}")
                consumed.setdefault(e, []).append(n.name)
            for e in n.outputs:
                if e not in self._edges:
                    problems.append(f"node {n.name!r}: undeclared output edge {e!r}")
                if e in self._inputs:
                    problems.append(
                        f"edge {e!r} is a graph input but node {n.name!r} "
                        "also produces it"
                    )
                elif e in producer:
                    problems.append(
                        f"edge {e!r} produced by both {producer[e]!r} "
                        f"and {n.name!r}"
                    )
                producer.setdefault(e, n.name)
            for e in n.resume_provides:
                if e not in self._edges:
                    problems.append(
                        f"node {n.name!r}: resume_provides names "
                        f"undeclared edge {e!r}"
                    )
        for e, users in consumed.items():
            if e not in producer and e not in self._inputs and e in self._edges:
                problems.append(
                    f"edge {e!r} consumed by {users[0]!r} has no producer "
                    "and is not a graph input"
                )
        for e in self._edges:
            if e not in producer and e not in consumed and e not in self._inputs:
                problems.append(
                    f"edge {e!r} is dangling (declared but never produced "
                    "or consumed)"
                )
        for e in self._inputs:
            if e not in consumed:
                problems.append(f"graph input {e!r} is never consumed")
        for e in self._results:
            if e not in self._edges:
                problems.append(f"result edge {e!r} is not declared")
            elif e not in producer:
                problems.append(f"result edge {e!r} is never produced")

        schedule, cycle = _toposort(self._nodes, producer)
        if cycle:
            problems.append(
                "dependency cycle among nodes: " + " -> ".join(cycle)
            )

        spec = GraphSpec(
            self.name, self._nodes, dict(self._edges),
            frozenset(self._inputs), tuple(self._results), schedule,
        )
        if not cycle:
            problems.extend(_check_resume_boundaries(spec))
        if problems:
            raise GraphValidationError(problems)
        return spec


def _toposort(nodes: list[Node], producer: dict[str, str],
              ) -> tuple[list[Node], list[str]]:
    """Kahn's algorithm with declaration-order tie-break, so the schedule
    is deterministic and mirrors the imperative stage order.  Returns
    (schedule, cycle_member_names); on a cycle the schedule is partial."""
    index = {n.name: i for i, n in enumerate(nodes)}
    deps: dict[str, set[str]] = {}
    for n in nodes:
        deps[n.name] = {
            producer[e] for e in n.inputs
            if e in producer and producer[e] != n.name
        }
    done: set[str] = set()
    order: list[Node] = []
    remaining = list(nodes)
    while remaining:
        ready = [n for n in remaining if deps[n.name] <= done]
        if not ready:
            cycle = sorted((n.name for n in remaining), key=index.get)
            return order, cycle
        nxt = min(ready, key=lambda n: index[n.name])
        order.append(nxt)
        done.add(nxt.name)
        remaining.remove(nxt)
    return order, []


def _check_resume_boundaries(spec: GraphSpec) -> list[str]:
    """Resume-boundary rules for every node carrying a ``resume_key``."""
    problems: list[str] = []
    for node in spec.schedule:
        if node.resume_key is None:
            continue
        if not any(
            spec.edges[e].placement == "disk" for e in node.outputs
            if e in spec.edges
        ):
            problems.append(
                f"resume node {node.name!r} produces no disk-placed edge "
                "to checkpoint"
            )
        if node.resume_reload is None and node.resume_provides:
            problems.append(
                f"resume node {node.name!r} declares resume_provides but "
                "no resume_reload to rebuild them"
            )
        for e in spec.crossing_edges(node.name):
            placement = spec.edges[e].placement if e in spec.edges else "?"
            if placement == "hbm" and e not in node.resume_provides:
                # an hbm crossing edge IS allowed when the reload rebuilds
                # it (re-encode + re-upload from the disk artifact) — that
                # is how the device-resident round1→round2 hand-off
                # coexists with the round-1 checkpoint. Uncovered device
                # memory still cannot survive a restart.
                problems.append(
                    f"hbm edge {e!r} crosses the disk-resume boundary of "
                    f"node {node.name!r} but its reload does not provide "
                    "it (device memory cannot survive a restart)"
                )
            elif e not in node.resume_provides:
                problems.append(
                    f"edge {e!r} crosses the resume boundary of node "
                    f"{node.name!r} but its reload does not provide it"
                )
    return problems
