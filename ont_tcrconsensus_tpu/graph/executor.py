"""Topological executor for a validated :class:`~.ir.GraphSpec`.

The executor walks the schedule and attaches every cross-cutting layer
per node instead of per call site:

- **obs / timing** — each critical node body runs inside
  ``timer.stage(node.name)`` (one clock read feeds the trace span, the
  metrics stage table, and the stage-timing TSV) and per-node
  critical-vs-overlapped seconds land in the telemetry ``graph`` section;
- **watchdog** — ``watchdog.guard(node.name, units=...)`` with units
  evaluated from the node's declaration, so deadlines scale with the
  declared workload;
- **chaos** — ``faults.inject("graph.node")`` fires at every critical
  node body (the per-node generalization of the hand-placed sites);
- **overlap** — any node the spec derives as a *side sink* (nothing
  consumes its outputs; see :meth:`GraphSpec.is_side_sink`) is submitted
  to the shared :class:`~..pipeline.overlap.StageExecutor` worker pool
  and committed at the next checkpoint barrier, with the imperative
  path's transient-recovery semantics (classify → rerun on the main
  thread → record recovered);
- **resume** — the deepest completed resume node is verified against the
  manifest (sha256, honoring ``verify_resume``), its skip closure is
  recorded as skipped, and its reload reconstructs every crossing edge
  from disk;
- **residency** — edge values are dropped from the executor's table the
  moment their last consumer finishes, so ``hbm``-placed edges stay
  device-resident exactly from producer to last consumer and become
  donation-safe immediately after.
"""

from __future__ import annotations

import sys
import time
from typing import Any

from ont_tcrconsensus_tpu.graph import check as graph_check
from ont_tcrconsensus_tpu.graph.ir import GraphSpec, Node
from ont_tcrconsensus_tpu.obs import live as obs_live
from ont_tcrconsensus_tpu.obs import metrics as obs_metrics
from ont_tcrconsensus_tpu.obs import transfers as obs_transfers
from ont_tcrconsensus_tpu.robustness import faults, retry, watchdog


def _log(*parts: object) -> None:
    print(*parts, file=sys.stderr)


def verify_resume_stage(lay, stage: str, cfg) -> bool:
    """Manifest-v2 verification gate shared by both executors: returns
    True when the stage's recorded artifacts check out under
    ``cfg.verify_resume``; on failure records an integrity event and
    tells the caller to re-run instead of trusting the artifact."""
    ok, why = lay.verify_stage(stage, cfg.verify_resume)
    if ok:
        return True
    retry.recorder().record(
        "resume.verify", classification="integrity", outcome="rerun",
        error=why or "",
        detail={"library": lay.library, "stage": stage,
                "mode": cfg.verify_resume},
    )
    _log(f"WARNING: resume verification failed for {lay.library} stage "
         f"{stage!r} ({why}); re-running instead of trusting the artifact")
    return False


class GraphExecutor:
    """Runs one :class:`GraphSpec` over a context object.

    ``ctx`` must expose ``cfg`` (the run config), ``timer`` (a
    :class:`~..qc.timing.StageTimer`) and ``lay`` (a library layout, or
    None outside a library run); node bodies may require more.
    ``side_exec`` is an optional :class:`StageExecutor` — without one,
    side sinks run synchronously at their schedule position, which is
    exactly the imperative ``overlap_qc: false`` behavior.
    """

    def __init__(self, spec: GraphSpec, ctx: Any, side_exec=None):
        self.spec = spec
        self.ctx = ctx
        self.side_exec = side_exec
        self._pending: list[tuple[Node, Any]] = []
        # host-placed edges on graftcheck's round-trip paths; filled per
        # run() when telemetry is armed (obs/transfers.py data plane)
        self._rt_edges: set[str] = set()

    def run(self, inputs: dict) -> dict:
        spec, ctx = self.spec, self.ctx
        missing = sorted(e for e in spec.inputs if e not in inputs)
        if missing:
            raise ValueError(f"graph {spec.name!r}: missing inputs {missing}")
        for name in sorted(spec.edges):
            obs_metrics.graph_edge_set(name, spec.edges[name].placement)
        for node in spec.schedule:
            # declared structure into telemetry: obs/critical_path.py
            # rebuilds the executed DAG (slack, what-if) from the artifact
            obs_metrics.graph_node_declare(
                node.name, inputs=node.inputs, outputs=node.outputs)

        # live /progress denominator: every scheduled node, before any
        # skip accounting, so done/total is stable across resume paths
        obs_live.progress_plan([n.name for n in spec.schedule])

        # data-plane tap: edges whose values leave the device and come
        # back (graftcheck's static round-trip paths) charge the
        # run-level host_round_trip_bytes ledger as they materialize
        self._rt_edges = (graph_check.round_trip_edges(spec)
                          if obs_metrics.armed() else set())

        # donation plan from the liveness proof: per node, the hbm input
        # edges this node is the last consumer of.  Node bodies read
        # ``ctx.donate_edges`` to decide which jitted entries may take
        # ``donate_argnums`` — the static proof drives the runtime
        # discipline, so adding a second consumer to an edge silently
        # and safely withdraws its donation.
        self._donation_plan = graph_check.donation_plan(spec)

        # sharded execution (ROADMAP-2): when the context's engine carries
        # a device mesh, the declared Edge.sharding specs become the
        # executable plan — paired in/out shardings per node, derived once
        # here and published per node as ``ctx.node_shardings``.  The
        # reshard-pairing proof is a hard gate: a graph whose declared
        # shardings disagree across any node would make the "stage
        # boundaries never reshard" discipline a lie, so the executor
        # refuses it outright instead of letting XLA insert the shuffle.
        self._shard_plan = self._mesh_setup()

        skip, resume_node = self._resume_scan()
        values = dict(inputs)
        refs: dict[str, int] = {}
        for node in spec.schedule:
            if node.name in skip:
                continue
            for e in node.inputs:
                refs[e] = refs.get(e, 0) + 1

        for node in spec.schedule:
            if node.name in skip:
                obs_metrics.graph_node_skip(node.name)
                obs_live.progress_node_skip(node.name)
                continue
            if node is resume_node:
                # reload crossing edges from disk instead of running
                values.update(node.resume_reload(ctx) if node.resume_reload
                              else {})
                obs_metrics.graph_node_skip(node.name)
                obs_live.progress_node_skip(node.name)
                continue
            node_inputs = {e: values[e] for e in node.inputs}
            units = node.eval_units(ctx, node_inputs)
            obs_metrics.graph_node_declare(node.name, units=units)
            if self.side_exec is not None and spec.is_side_sink(node):
                deferred = self.side_exec.submit(
                    node.name, node.fn, ctx, node_inputs, units=units,
                )
                self._pending.append((node, deferred))
                continue
            if node.checkpoint:
                self._commit_pending(values, refs)
            audit = self._donation_probe(node, values, refs)
            self._set_donate_edges(node)
            self._set_node_shardings(node)
            outputs = self._run_node_degradable(node, node_inputs, units)
            if audit:
                out_probe = obs_transfers.buffer_probe(outputs)
                for e, probe in audit.items():
                    # re-probe the input AFTER the call: a buffer that
                    # now reads deleted was taken by XLA (donated) even
                    # when the output landed at a different address
                    post = obs_transfers.buffer_probe(values.get(e))
                    obs_transfers.audit_donation(e, node.name, probe,
                                                 out_probe, post)
            self._absorb(node, outputs, values, refs)
        self._commit_pending(values, refs)
        return {e: values[e] for e in spec.results}

    # -- internals ---------------------------------------------------------

    def _resume_scan(self) -> tuple[set[str], Node | None]:
        """Deepest completed+verified resume node → (skip closure, node);
        the resume node itself stays in the closure set but is handled
        specially in :meth:`run` (reload instead of skip)."""
        ctx = self.ctx
        cfg, lay = ctx.cfg, ctx.lay
        if lay is None or not getattr(cfg, "resume", False):
            return set(), None
        for node in reversed(self.spec.schedule):
            if node.resume_key is None or not lay.stage_done(node.resume_key):
                continue
            probe = node.resume_probe(ctx) if node.resume_probe else None
            if node.resume_probe is not None and probe is None:
                continue  # recorded done but artifact is gone: re-run
            if probe:
                faults.corrupt_artifact("resume.verify", probe)
            if verify_resume_stage(lay, node.resume_key, cfg):
                closure = self.spec.skip_closure(node.name)
                closure.discard(node.name)
                return closure, node
        return set(), None

    def _donation_probe(self, node: Node, values: dict,
                        refs: dict[str, int]) -> dict:
        """Buffer-identity probes for this node's hbm inputs at their
        drop point (live ref count 1: this node is the last consumer —
        the same eligibility rule graftcheck derives statically), taken
        BEFORE the node runs so a donated-then-reused pointer is still
        readable. Empty when telemetry is off."""
        if not obs_metrics.armed():
            return {}
        spec = self.spec
        return {
            e: obs_transfers.buffer_probe(values.get(e))
            for e in node.inputs
            if (refs.get(e, 0) == 1 and e in spec.edges
                and spec.edges[e].placement == "hbm"
                and e not in spec.results)
        }

    def _set_donate_edges(self, node: Node) -> None:
        """Publish the node's donation-eligible hbm input edges on the
        context as ``ctx.donate_edges`` before its body runs.  Best
        effort: a context that rejects attribute assignment (slots,
        frozen test doubles) simply runs without donation."""
        try:
            self.ctx.donate_edges = self._donation_plan.get(
                node.name, frozenset())
        except Exception:
            pass

    def _mesh_setup(self):
        """The per-node sharding plan when the run is mesh-armed, else
        ``None``.  jax stays un-imported on unsharded runs: the lazy
        import only happens once a mesh actually exists on the engine."""
        mesh = getattr(getattr(self.ctx, "engine", None), "mesh", None)
        if mesh is None:
            return None
        bad = graph_check.reshard_sites(self.spec)
        if bad:
            raise RuntimeError(
                f"graph {self.spec.name!r} cannot run sharded: "
                + "; ".join(f.format() for f in bad)
            )
        from ont_tcrconsensus_tpu.parallel import mesh as mesh_mod

        return mesh_mod.node_sharding_plan(self.spec, mesh)

    def _set_node_shardings(self, node: Node) -> None:
        """Publish the node's paired in/out sharding axes on the context
        (``ctx.node_shardings``) before its body runs — the pjit
        discipline's runtime face: producers place outputs with exactly
        the consumer's declared in-spec, so stage boundaries never
        reshard. Best effort, like :meth:`_set_donate_edges`."""
        if self._shard_plan is None:
            return
        try:
            self.ctx.node_shardings = self._shard_plan.get(node.name)
        except Exception:
            pass

    def _run_node_degradable(self, node: Node, inputs: dict,
                             units: int) -> dict:
        """:meth:`_run_node` plus the degraded-mesh survival loop.

        A ``device_lost`` escaping a node body means a mesh slice died
        mid-dispatch: no same-mesh retry can succeed.  When the context
        offers a ``remesh`` hook (pipeline/run.py installs one on sharded
        runs), the executor shrinks the world instead of dying — the hook
        re-meshes the engines onto the survivors, rescales the HBM budget
        and batch quantization, and this loop re-runs the WHOLE node on
        the degraded mesh (node bodies are pure up to their ``commit``,
        which only runs on success, so the re-run is safe).  Each
        degradation is recorded as a ``mesh.degraded`` event in the
        robustness report and counted in telemetry; when the data axis
        cannot shrink further, the fault propagates and the run dies
        honestly.
        """
        while True:
            try:
                return self._run_node(node, inputs, units)
            except Exception as exc:
                if retry.classify(exc) != "device_lost":
                    raise
                remesh = getattr(self.ctx, "remesh", None)
                detail = remesh(node.name, exc) if remesh is not None else None
                if detail is None:
                    raise
                rec = retry.recorder()
                rec.record("mesh.degraded", classification="device_lost",
                           outcome="degraded", error=repr(exc),
                           detail={"node": node.name, **detail})
                obs_metrics.counter_add("mesh.degraded")
                obs_metrics.mesh_degraded_add("mesh.device_lost")
                self._set_node_shardings(node)
                _log(f"WARNING: mesh slice lost in node {node.name!r} "
                     f"({exc!r}); re-dispatching on degraded mesh "
                     f"data={detail.get('data_from')}→"
                     f"{detail.get('data_to')}")

    def _run_node(self, node: Node, inputs: dict, units: int) -> dict:
        ctx = self.ctx
        t0 = time.monotonic()
        obs_live.progress_node_start(node.name, units=units)
        try:
            with ctx.timer.stage(node.name), \
                    watchdog.guard(node.name, units=units):
                faults.inject("graph.node")
                outputs = node.fn(ctx, inputs)
                if node.commit is not None:
                    node.commit(ctx, outputs)
        finally:
            dt = time.monotonic() - t0
            obs_metrics.graph_node_add(node.name, critical_s=dt)
            obs_live.progress_node_finish(node.name, dt, units=units)
            # node-boundary HBM sample for the --report --memory
            # reconciler (no-op off-telemetry / without memory stats)
            obs_transfers.node_hbm_boundary(node.name)
        return outputs

    def _commit_pending(self, values: dict, refs: dict[str, int]) -> None:
        if not self._pending:
            return
        ctx = self.ctx
        pending, self._pending = self._pending, []
        for node, deferred in pending:
            t0 = time.monotonic()
            try:
                outputs = self.side_exec.commit(deferred, ctx.timer)
            except Exception as exc:
                classification = retry.classify(exc)
                rec = retry.recorder()
                if classification == "fatal":
                    rec.record("overlap.worker", classification=classification,
                               outcome="fatal", error=repr(exc))
                    raise
                rec.record("overlap.worker", classification=classification,
                           outcome="retried", error=repr(exc))
                _log(f"WARNING: overlapped node {node.name} hit a "
                     f"{classification} fault ({exc!r}); recomputing on the "
                     "main thread")
                with ctx.timer.stage(node.name):
                    outputs = deferred.rerun_sync()
                rec.record("overlap.worker", classification=classification,
                           outcome="recovered", attempt=2)
            if node.commit is not None:
                node.commit(ctx, outputs)
            obs_metrics.graph_node_add(
                node.name, critical_s=time.monotonic() - t0,
                overlapped_s=deferred.worker_seconds)
            obs_live.progress_node_finish(node.name, deferred.worker_seconds)
            _log(f"graph: {node.name} computed off the critical path "
                 f"({deferred.worker_seconds:.1f}s overlapped)")
            self._absorb(node, outputs, values, refs)

    def _absorb(self, node: Node, outputs: dict, values: dict,
                refs: dict[str, int]) -> None:
        if outputs is None:
            outputs = {}
        got, want = set(outputs), set(node.outputs)
        if got != want:
            raise RuntimeError(
                f"node {node.name!r} returned edges {sorted(got)}, "
                f"declared {sorted(want)}"
            )
        values.update(outputs)
        if obs_metrics.armed():
            for e, v in outputs.items():
                if e in self.spec.edges:
                    obs_transfers.edge_materialized(
                        e, self.spec.edges[e].placement, v,
                        round_trip=e in self._rt_edges)
        for e in node.inputs:
            refs[e] = refs.get(e, 1) - 1
            if refs[e] <= 0 and e not in self.spec.results:
                # last consumer done: drop the value so hbm edges free
                # device memory (donation-safe) as early as possible
                values.pop(e, None)
