"""Stage bodies for the production library graph.

Each node function is a faithful transcription of the corresponding
segment of the imperative ``run._run_library_impl`` / ``run._run_round2``
path — same calls, same artifacts, same chaos plants, same degradation
semantics — minus the scheduling concerns (timing, watchdog guards,
overlap submission, checkpoint barriers), which the graph executor now
attaches from the node declarations instead.

Module scope here is jax-free (``--validate`` builds and validates the
graph without an accelerator stack); the heavy pipeline modules are
imported lazily inside the bodies.  Pipeline functions are called as
``stages.<fn>`` module attributes, not from-imports, so test monkeypatches
on :mod:`~..pipeline.stages` intercept both executors identically.

The context object (``graph.pipeline.LibraryContext``) carries the
per-library invariants the imperative functions passed positionally:
config, layout, reference panel, engines, thresholds, polisher, batching
and the shared ``failed_groups`` / ``failed_regions`` degradation lists.
"""

from __future__ import annotations

import os
import sys


def _log(*parts: object) -> None:
    print(*parts, file=sys.stderr)


# -- round 1 ---------------------------------------------------------------


def round1_fused_assign(ctx, inputs: dict) -> dict:
    """ONE fused device pass per batch (trim -> EE -> align -> UMI
    locate), transient-retried, with ingest quarantine when configured."""
    from ont_tcrconsensus_tpu.io import validate as validate_mod
    from ont_tcrconsensus_tpu.pipeline import stages
    from ont_tcrconsensus_tpu.qc import artifacts
    from ont_tcrconsensus_tpu.robustness import faults, retry

    cfg, lay = ctx.cfg, ctx.lay
    library = lay.library
    _log("Preprocessing, aligning and UMI-tagging nanopore reads:", library)
    fastq = faults.mutate_input("ingest.library_fastq", inputs["library_fastq"])
    guard = None
    if cfg.on_bad_record != "fail":
        guard = validate_mod.IngestGuard(
            cfg.on_bad_record, source=os.fspath(fastq),
            quarantine_path=lay.quarantine_path,
        )
    try:
        store, astats = retry.call_with_retry(
            "assign.round1",
            lambda: stages.run_assign(
                fastq, ctx.engine,
                max_ee_rate=cfg.max_ee_rate_base,
                min_len=cfg.minimal_length,
                minimal_region_overlap=cfg.minimal_region_overlap,
                max_softclip_5_end=cfg.max_softclip_5_end,
                max_softclip_3_end=cfg.max_softclip_3_end,
                batch_size=ctx.read_batch,
                max_read_length=cfg.max_read_length,
                subsample=cfg.dorado_trim_subsample_fastq,
                guard=guard,
            ),
            reset=guard.reset if guard is not None else None,
        )
    finally:
        # finalize even when the library fails: the quarantine gzip must
        # gain its trailer and the ingest events must reach the report
        if guard is not None:
            qsummary = guard.finalize(retry.recorder())
            if qsummary["n_bad"]:
                verb = ("quarantined" if guard.policy == "quarantine"
                        else "dropped")
                _log(f"ingest: {qsummary['n_bad']} bad record(s) in "
                     f"{library} {verb} ({qsummary['by_reason']})")
    with open(os.path.join(lay.logs, "ee_filter.log"), "w") as fh:
        fh.write(
            f"reads passing EE/length filter: {astats.n_total - astats.n_ee_fail}\n"
        )
        fh.write(f"reads with primer trim: {astats.n_trimmed}\n")
    from ont_tcrconsensus_tpu.pipeline import run as run_mod

    run_mod._write_align_log(
        astats, os.path.join(lay.logs, f"{library}_region_cluster_split.log")
    )
    artifacts.write_fastq_stats_log(
        astats, os.path.join(lay.logs, f"{library}_fastq_stats.log")
    )
    artifacts.write_flagstat_log(
        astats, os.path.join(lay.logs, f"{library}_flagstat.log")
    )
    return {"read_store": store, "align_stats": astats}


def round1_error_profile(ctx, inputs: dict) -> dict:
    from ont_tcrconsensus_tpu.qc import error_profile

    counters = error_profile.profile_store(
        inputs["read_store"], ctx.panel,
        sample_size=ctx.cfg.error_profile_sample,
    )
    return {"r1_qc_profile": counters}


def commit_round1_error_profile(ctx, outputs: dict) -> None:
    from ont_tcrconsensus_tpu.qc import error_profile

    error_profile.write_error_profile_log(
        *outputs["r1_qc_profile"],
        os.path.join(ctx.lay.logs, f"{ctx.lay.library}_align_error_profile.log"),
    )


def round1_region_split(ctx, inputs: dict) -> dict:
    from ont_tcrconsensus_tpu.cluster import regions as regions_mod
    from ont_tcrconsensus_tpu.pipeline import stages
    from ont_tcrconsensus_tpu.qc import artifacts

    store, astats = inputs["read_store"], inputs["align_stats"]
    groups = stages.group_by_region_cluster(store, ctx.panel)
    artifacts.write_region_split_log(
        astats, groups, store, ctx.panel.names,
        {n: len(s) for n, s in ctx.panel.seqs.items()},
        regions_mod.NEGATIVE_CONTROL_SUFFIXES,
        os.path.join(
            ctx.lay.logs,
            f"{ctx.lay.library}_filter_and_split_reads_by_region_cluster.err",
        ),
    )
    return {"region_groups": groups}


def write_region_fastas(ctx, inputs: dict) -> dict:
    from ont_tcrconsensus_tpu.pipeline import stages

    stages.write_region_fastas(
        inputs["region_groups"], inputs["read_store"],
        ctx.lay.region_cluster_fasta, "region_cluster",
    )
    return {"region_cluster_fastas": ctx.lay.region_cluster_fasta}


def round1_umi_records(ctx, inputs: dict) -> dict:
    from ont_tcrconsensus_tpu.pipeline import stages

    store, groups = inputs["read_store"], inputs["region_groups"]
    cfg = ctx.cfg
    records_by_group: list[tuple[str, list]] = []
    for cluster_key in sorted(groups):
        group_name = f"region_cluster{cluster_key}"
        try:
            umis = stages.build_umi_records(
                store, groups[cluster_key], cfg.max_pattern_dist
            )
            if not umis:
                continue
            if cfg.write_intermediate_fastas:
                stages.write_umi_fasta(
                    umis, store,
                    os.path.join(
                        ctx.lay.umi_fasta, f"{group_name}_detected_umis.fasta"
                    ),
                )
            records_by_group.append((group_name, umis))
        except Exception as exc:
            ctx.failed_groups.append((group_name, repr(exc)))
            _log(f"WARNING: {group_name} failed and is skipped: {exc!r}")
    return {"records_by_group": records_by_group}


def round1_umi_cluster(ctx, inputs: dict) -> dict:
    """ONE library-wide batched clustering pass; a deterministic batched
    failure degrades to per-group retries so one bad group cannot poison
    its peers."""
    from ont_tcrconsensus_tpu.pipeline import stages
    from ont_tcrconsensus_tpu.robustness import faults, retry

    cfg = ctx.cfg
    records_by_group = inputs["records_by_group"]

    def _batched_r1():
        faults.inject("cluster.batched_round1")
        return stages.cluster_and_select_grouped(
            records_by_group,
            identity=cfg.vsearch_identity,
            min_umi_length=cfg.min_umi_length,
            max_umi_length=cfg.max_umi_length,
            min_reads_per_cluster=cfg.min_reads_per_cluster,
            max_reads_per_cluster=cfg.max_reads_per_cluster,
            balance_strands=cfg.balance_strands,
            mesh=ctx.engine.mesh,
        )

    grouped = None
    try:
        grouped = retry.call_with_retry("cluster.batched_round1", _batched_r1)
    except Exception as exc:
        retry.recorder().record(
            "cluster.batched_round1", classification=retry.classify(exc),
            outcome="degraded", error=repr(exc),
        )
        _log(f"WARNING: batched UMI clustering failed ({exc!r}); "
             "retrying each region cluster individually")
    selected_by_group: list[tuple[str, list]] = []
    for group_name, umis in records_by_group:
        try:
            if grouped is not None:
                selected, stat_rows = grouped[group_name]
            else:
                selected, stat_rows = stages.cluster_and_select(
                    umis,
                    identity=cfg.vsearch_identity,
                    min_umi_length=cfg.min_umi_length,
                    max_umi_length=cfg.max_umi_length,
                    min_reads_per_cluster=cfg.min_reads_per_cluster,
                    max_reads_per_cluster=cfg.max_reads_per_cluster,
                    balance_strands=cfg.balance_strands,
                    mesh=ctx.engine.mesh,
                )
            cdir = os.path.join(ctx.lay.clustering, group_name)
            os.makedirs(cdir, exist_ok=True)
            stages.write_cluster_stats_tsv(
                stat_rows, os.path.join(cdir, "vsearch_cluster_stats.tsv")
            )
            if selected:
                selected_by_group.append((group_name, selected))
        except Exception as exc:
            ctx.failed_groups.append((group_name, repr(exc)))
            _log(f"WARNING: {group_name} failed and is skipped: {exc!r}")
    return {"selected_by_group": selected_by_group}


def round1_polish(ctx, inputs: dict) -> dict:
    from ont_tcrconsensus_tpu.pipeline import stages

    selected_by_group = inputs["selected_by_group"]
    n_clusters = sum(len(s) for _, s in selected_by_group)
    _log(f"Polishing clusters: {ctx.lay.library} "
         f"({n_clusters} clusters over {len(selected_by_group)} region clusters)")
    # the executor publishes its liveness-proof donation plan as
    # ctx.donate_edges: read_store dropping at this node is the proof
    # that the polish dispatches may donate their per-round uploads
    donate = "read_store" in getattr(ctx, "donate_edges", ())
    by_group, polish_failed = stages.polish_clusters_all(
        selected_by_group, inputs["read_store"],
        max_read_length=ctx.cfg.max_read_length,
        polisher=ctx.polisher,
        budget=ctx.budget,
        cluster_batch=ctx.cfg.cluster_batch_size,
        mesh=ctx.engine.mesh,
        keep_codes=True,
        donate=donate,
    )
    return {"r1_polished": (by_group, polish_failed)}


def round1_consensus(ctx, inputs: dict) -> dict:
    """Merged consensus assembly + the round-1 resume checkpoint: an
    incomplete round 1 is NOT checkpointed so resume retries the failed
    groups instead of reusing a consensus missing them."""
    from ont_tcrconsensus_tpu.io import bucketing, fastx
    from ont_tcrconsensus_tpu.ops import encode
    from ont_tcrconsensus_tpu.robustness import contracts, faults, shutdown

    lay = ctx.lay
    by_group, polish_failed = inputs["r1_polished"]
    # r1_polished carries (header, uint8 code vector) pairs — the
    # device-resident hand-off; strings materialize ONLY at the fasta
    # artifact boundary below (decode∘encode is bijective on codes 0..4,
    # so the artifact is byte-identical to the string-path one)
    merged: list[tuple[str, object]] = []
    for group_name, selected in inputs["selected_by_group"]:
        if group_name in polish_failed:
            ctx.failed_groups.append((group_name, polish_failed[group_name]))
            _log(f"WARNING: {group_name} polish failed and is skipped: "
                 f"{polish_failed[group_name]}")
        else:
            # conservation: every selected cluster of a non-failed group
            # must have produced exactly one consensus record
            contracts.check_equal(
                "consensus", f"{group_name} consensus records",
                len(by_group[group_name]), "selected clusters", len(selected),
                detail={"library": lay.library, "group": group_name},
            )
            merged.extend(by_group[group_name])
    cons_codes = bucketing.EncodedRecords(
        headers=[h for h, _ in merged],
        codes=[c for _, c in merged],
    )
    merged_consensus = [
        (h, encode.decode_seq(c, int(c.size)))
        for h, c in zip(cons_codes.headers, cons_codes.codes)
    ]
    if ctx.failed_groups:
        _log(
            "Not all umi cluster region fastas were successfully polished! "
            f"Incomplete: {[g for g, _ in ctx.failed_groups]}"
        )
        with open(os.path.join(lay.logs, "incomplete_region_clusters.log"), "w") as fh:
            for group_name, err in ctx.failed_groups:
                fh.write(f"{group_name}\t{err}\n")
    merged_path = os.path.join(lay.fasta, "merged_consensus.fasta")
    n_written = fastx.write_fasta(merged_path, merged_consensus)
    contracts.check_equal(
        "consensus", "merged_consensus.fasta records written", n_written,
        "in-memory consensus entries", len(merged_consensus),
        detail={"library": lay.library},
    )
    if not ctx.failed_groups:
        lay.mark_stage_done("round1_consensus", artifacts=[merged_path])
    # chaos site + preemption checkpoint at the round-1 commit: the
    # canonical mid-stage death — the manifest just committed, so a kill
    # here resumes into round 2 only, byte-identically
    faults.inject("run.round1_checkpoint")
    shutdown.checkpoint("run.round1_checkpoint")
    return {"merged_consensus": merged_consensus, "merged_fasta": merged_path,
            "cons_codes": cons_codes}


def round1_resume_probe(ctx):
    path = os.path.join(ctx.lay.fasta, "merged_consensus.fasta")
    return path if os.path.exists(path) else None


def round1_resume_reload(ctx) -> dict:
    from ont_tcrconsensus_tpu.io import bucketing, fastx
    from ont_tcrconsensus_tpu.ops import encode

    merged_path = os.path.join(ctx.lay.fasta, "merged_consensus.fasta")
    _log("Resuming from round-1 consensus:", ctx.lay.library)
    merged_consensus = [
        (rec.header, rec.sequence) for rec in fastx.read_fastx(merged_path)
    ]
    # re-encode the checkpointed fasta into the hbm hand-off the resume
    # boundary promises (resume_provides): encode∘decode round-trips the
    # 0..4 alphabet exactly, so a resumed round 2 sees byte-identical
    # batches to the un-resumed run
    cons_codes = bucketing.EncodedRecords(
        headers=[h for h, _ in merged_consensus],
        codes=[encode.encode_seq(s) for _, s in merged_consensus],
    )
    return {"merged_consensus": merged_consensus, "cons_codes": cons_codes}


# -- round 2 ---------------------------------------------------------------


def round2_fused_assign(ctx, inputs: dict) -> dict:
    from ont_tcrconsensus_tpu.pipeline import run as run_mod
    from ont_tcrconsensus_tpu.pipeline import stages
    from ont_tcrconsensus_tpu.qc import artifacts
    from ont_tcrconsensus_tpu.robustness import retry

    cfg, lay = ctx.cfg, ctx.lay
    # consume the device-resident hand-off: round 1's polished codes
    # arrive pre-encoded (EncodedRecords), so batching skips the
    # decode→re-encode round trip entirely — encode/decode are bijective
    # over the 0..4 alphabet, so the batches are byte-identical to the
    # string path
    cons_codes = inputs["cons_codes"]
    _log("Aligning unique molecule consensus TCR sequences:", lay.library)
    qc_rows: list[dict] = []
    dispatch = None
    if cfg.round2_targeted_assign:
        dispatch, why_not = run_mod._targeted_round2_dispatch(
            ctx.panel, ctx.engine_notrim, iter(cons_codes.headers)
        )
        if dispatch is None:
            _log(f"round 2: targeted assign unavailable ({why_not}); "
                 "falling back to the full fused assign")
    cons_store, cstats = retry.call_with_retry(
        "assign.round2",
        lambda: stages.run_assign(
            cons_codes, ctx.engine_notrim,
            max_ee_rate=1.0,  # no quality data on consensus sequences
            min_len=1,
            minimal_region_overlap=ctx.overlap_consensus,
            max_softclip_5_end=cfg.max_softclip_5_end,
            max_softclip_3_end=cfg.max_softclip_3_end,
            batch_size=ctx.read_batch,
            max_read_length=cfg.max_read_length,
            blast_id_threshold=ctx.blast_id_threshold,
            collect_qc=qc_rows,
            dispatch=dispatch,
        ),
        reset=qc_rows.clear,
    )
    artifacts.write_consensus_filter_artifacts(
        qc_rows,
        {n: len(s) for n, s in ctx.panel.seqs.items()},
        lay.logs,
        "merged_consensus",
        blast_id_threshold=ctx.blast_id_threshold,
        minimal_region_overlap=ctx.overlap_consensus,
    )
    artifacts.write_flagstat_log(
        cstats, os.path.join(lay.logs, "merged_consensus_flagstat.log")
    )
    return {"cons_store": cons_store}


def round2_error_profile(ctx, inputs: dict) -> dict:
    from ont_tcrconsensus_tpu.qc import error_profile

    counters = error_profile.profile_store(
        inputs["cons_store"], ctx.panel,
        sample_size=ctx.cfg.error_profile_sample,
    )
    return {"r2_qc_profile": counters}


def commit_round2_error_profile(ctx, outputs: dict) -> None:
    from ont_tcrconsensus_tpu.qc import error_profile

    error_profile.write_error_profile_log(
        *outputs["r2_qc_profile"],
        os.path.join(ctx.lay.logs, "merged_consensus_align_error_profile.log"),
    )


def round2_umi_records(ctx, inputs: dict) -> dict:
    from ont_tcrconsensus_tpu.pipeline import stages

    cfg = ctx.cfg
    cons_store = inputs["cons_store"]
    region_groups = stages.group_by_region(cons_store, ctx.panel)
    if cfg.write_intermediate_fastas:
        stages.write_region_fastas(
            region_groups, cons_store, ctx.lay.region_fasta, "region_"
        )
    region_records: list[tuple[str, list]] = []
    for region, parts in sorted(region_groups.items()):
        try:
            umis = stages.build_umi_records(
                cons_store, parts, cfg.max_pattern_dist
            )
            if not umis:
                continue
            if cfg.write_intermediate_fastas:
                stages.write_umi_fasta(
                    umis, cons_store,
                    os.path.join(
                        ctx.lay.consensus_umi_fasta,
                        f"region_{region}_detected_umis.fasta",
                    ),
                )
            region_records.append((region, umis))
        except Exception as exc:
            ctx.failed_regions.append((region, repr(exc)))
            _log(f"WARNING: round-2 region {region} failed and is skipped: {exc!r}")
    return {"region_records": region_records}


def round2_umi_cluster(ctx, inputs: dict) -> dict:
    from ont_tcrconsensus_tpu.pipeline import stages
    from ont_tcrconsensus_tpu.robustness import faults, retry

    cfg = ctx.cfg
    region_records = inputs["region_records"]

    def _batched_r2():
        faults.inject("cluster.batched_round2")
        return stages.cluster_and_select_grouped(
            region_records,
            identity=cfg.vsearch_identity_consensus,
            min_umi_length=cfg.min_umi_length,
            max_umi_length=cfg.max_umi_length,
            min_reads_per_cluster=1,
            max_reads_per_cluster=cfg.max_reads_per_cluster,
            balance_strands=False,
            mesh=ctx.engine_notrim.mesh,
        )

    grouped2 = None
    try:
        grouped2 = retry.call_with_retry("cluster.batched_round2", _batched_r2)
    except Exception as exc:
        retry.recorder().record(
            "cluster.batched_round2", classification=retry.classify(exc),
            outcome="degraded", error=repr(exc),
        )
        _log(f"WARNING: batched round-2 UMI clustering failed ({exc!r}); "
             "retrying each region individually")
    selected_by_region: list[tuple[str, list, list]] = []
    for region, umis in region_records:
        try:
            if grouped2 is not None:
                selected, stat_rows = grouped2[region]
            else:
                selected, stat_rows = stages.cluster_and_select(
                    umis,
                    identity=cfg.vsearch_identity_consensus,
                    min_umi_length=cfg.min_umi_length,
                    max_umi_length=cfg.max_umi_length,
                    min_reads_per_cluster=1,
                    max_reads_per_cluster=cfg.max_reads_per_cluster,
                    balance_strands=False,
                    mesh=ctx.engine_notrim.mesh,
                )
            selected_by_region.append((region, selected, stat_rows))
        except Exception as exc:
            ctx.failed_regions.append((region, repr(exc)))
            _log(f"WARNING: round-2 region {region} failed and is skipped: {exc!r}")
    return {"selected_by_region": selected_by_region}


def round2_counts(ctx, inputs: dict) -> dict:
    """Per-region artifacts + counts CSV + the counts manifest mark;
    incomplete counts are not checkpointed so resume retries."""
    import shutil

    from ont_tcrconsensus_tpu.pipeline import run as run_mod
    from ont_tcrconsensus_tpu.pipeline import stages
    from ont_tcrconsensus_tpu.qc import umi_overlap
    from ont_tcrconsensus_tpu.robustness import contracts

    cfg, lay = ctx.cfg, ctx.lay
    cons_store = inputs["cons_store"]
    region_counts: dict[str, int] = {}
    region_cluster_umis: dict[str, list[str]] = {}
    for region, selected, stat_rows in inputs["selected_by_region"]:
        try:
            run_mod._finish_round2_region(
                region, selected, stat_rows, cons_store, lay, cfg,
                region_counts, region_cluster_umis,
            )
        except Exception as exc:
            ctx.failed_regions.append((region, repr(exc)))
            _log(f"WARNING: round-2 region {region} failed and is skipped: {exc!r}")
    if ctx.failed_regions:
        with open(os.path.join(lay.logs, "incomplete_regions.log"), "w") as fh:
            for region, err in ctx.failed_regions:
                fh.write(f"{region}\t{err}\n")

    counts_csv = stages.write_counts_csv(region_counts, lay.counts)
    contracts.check_equal(
        "counts", "counts CSV readback", run_mod._read_counts_csv(counts_csv),
        "in-memory region counts", region_counts,
        detail={"library": lay.library},
    )
    if cfg.compare_umi_overlap_between_regions:
        _log("Testing for consensus umi matches between regions:", lay.library)
        umi_overlap.count_overlapping_umis(
            region_cluster_umis, lay.logs, cfg.overlapping_umi_edit_threshold
        )
    # the stage-timing artifact lands before the counts manifest mark,
    # like the imperative path: a crash in between leaves counts unmarked
    # and resume regenerates both
    ctx.timer.write_tsv(os.path.join(lay.logs, "stage_timing.tsv"))
    if not ctx.failed_groups and not ctx.failed_regions:
        lay.mark_stage_done("counts", artifacts=[counts_csv])

    if cfg.delete_tmp_files:
        for d in (lay.region_cluster_fasta, lay.clustering, lay.umi_fasta,
                  lay.fasta, lay.clustering_consensus, lay.region_fasta,
                  lay.consensus_umi_fasta):
            shutil.rmtree(d, ignore_errors=True)

    return {"region_counts": region_counts, "counts_csv": counts_csv}
