"""The production library graph: round1→round2 declared as nodes/edges.

:func:`build_library_graph` is the single place the pipeline's dataflow
shape lives.  Placements encode the port's memory story: the two read
stores are ``hbm`` (columnar blocks stay device-resident from the fused
assign through polish / counting — the executor drops them right after
their last consumer, making donation safe), orchestration values are
``host``, and the two checkpoint artifacts (merged consensus fasta,
counts CSV) are ``disk`` — the only placement a resume can reload.

Which nodes run off the critical path is *derived*, not configured: the
error-profile passes and the intermediate region fastas produce edges
nothing consumes, so :meth:`GraphSpec.side_sinks` routes them through the
shared worker pool automatically.  ``overlap_qc`` only decides whether a
worker pool exists at all.

Conditional stages (error profiling, intermediate fastas) are included
or excluded at build time from the config, so the built graph never
contains dangling edges.  Module scope is jax-free: ``--validate``
builds and validates this graph without an accelerator stack.
"""

from __future__ import annotations

import dataclasses
from typing import Any

from ont_tcrconsensus_tpu.graph import nodes as N
from ont_tcrconsensus_tpu.graph.ir import GraphBuilder, GraphSpec
from ont_tcrconsensus_tpu.pipeline.config import RunConfig


@dataclasses.dataclass
class LibraryContext:
    """Per-library invariants shared by every node (the values the
    imperative path threaded positionally), plus the degradation lists
    the graceful-skip paths append to."""

    cfg: Any
    lay: Any
    timer: Any
    panel: Any = None
    engine: Any = None
    engine_notrim: Any = None
    blast_id_threshold: float = 0.0
    overlap_consensus: int = 0
    polisher: Any = None
    read_batch: int = 0
    budget: Any = None
    failed_groups: list = dataclasses.field(default_factory=list)
    failed_regions: list = dataclasses.field(default_factory=list)
    # sharded-execution hooks (filled by run.py on mesh-armed runs): the
    # executor publishes each node's paired in/out sharding axes here
    # before the body runs, and calls ``remesh(node, exc)`` when a
    # device_lost escapes a node — the hook shrinks both engines' mesh to
    # the survivors, rescales the HBM budget, and returns the degradation
    # detail (or None when the data axis is already 1)
    node_shardings: Any = None
    remesh: Any = None


def build_library_graph(cfg: RunConfig) -> GraphSpec:
    b = GraphBuilder("library")
    b.input("library_fastq", "disk")
    # Both device stores are batch-sharded over the mesh's data axis
    # (ROADMAP item 2): on mesh-armed runs the executor compiles these
    # declarations into the per-node sharding plan it publishes as
    # ``ctx.node_shardings`` — producer out specs equal consumer in specs
    # by construction, so stage boundaries never reshard; graftcheck's
    # reshard-site lint is the hard gate (the executor refuses a graph
    # whose declared shardings disagree across any node).
    b.edge("read_store", "hbm", sharding="data")
    # meta host edges carry orchestration values (stats, groupings,
    # selections) whose host residency is by design: graftcheck's
    # round-trip analysis skips them, the transfer ledger still measures
    # their bytes per edge — an auditable declaration, not a waiver
    b.edge("align_stats", "host", meta=True)
    b.edge("region_groups", "host", meta=True)
    b.edge("records_by_group", "host", meta=True)
    b.edge("selected_by_group", "host", meta=True)
    # the round1→round2 data plane stays device-resident: polished
    # consensus codes flow as hbm edges (r1_polished -> cons_codes ->
    # round2's fused assign) and only the merged-fasta artifact boundary
    # decodes to strings
    b.edge("r1_polished", "hbm", sharding="data")
    b.edge("merged_consensus", "host")
    b.edge("merged_fasta", "disk")
    b.edge("cons_codes", "hbm", sharding="data")
    b.edge("cons_store", "hbm", sharding="data")
    b.edge("region_records", "host", meta=True)
    b.edge("selected_by_region", "host", meta=True)
    b.edge("region_counts", "host")
    b.edge("counts_csv", "disk")
    if cfg.error_profile_sample:
        b.edge("r1_qc_profile", "host")
        b.edge("r2_qc_profile", "host")
    if cfg.write_intermediate_fastas:
        b.edge("region_cluster_fastas", "disk")

    b.add_node(
        "round1_fused_assign", N.round1_fused_assign,
        inputs=("library_fastq",), outputs=("read_store", "align_stats"),
    )
    if cfg.error_profile_sample:
        b.add_node(
            "round1_error_profile", N.round1_error_profile,
            inputs=("read_store",), outputs=("r1_qc_profile",),
            commit=N.commit_round1_error_profile,
            units=lambda ctx, inputs: ctx.cfg.error_profile_sample,
        )
    b.add_node(
        "round1_region_split", N.round1_region_split,
        inputs=("read_store", "align_stats"), outputs=("region_groups",),
    )
    if cfg.write_intermediate_fastas:
        b.add_node(
            "write_region_fastas", N.write_region_fastas,
            inputs=("read_store", "region_groups"),
            outputs=("region_cluster_fastas",),
        )
    b.add_node(
        "round1_umi_records", N.round1_umi_records,
        inputs=("read_store", "region_groups"), outputs=("records_by_group",),
    )
    b.add_node(
        "round1_umi_cluster", N.round1_umi_cluster,
        inputs=("records_by_group",), outputs=("selected_by_group",),
        units=lambda ctx, inputs: sum(
            len(u) for _, u in inputs["records_by_group"]
        ),
    )
    b.add_node(
        "round1_polish", N.round1_polish,
        inputs=("read_store", "selected_by_group"), outputs=("r1_polished",),
        units=lambda ctx, inputs: sum(
            len(s) for _, s in inputs["selected_by_group"]
        ),
    )
    b.add_node(
        "round1_consensus", N.round1_consensus,
        inputs=("selected_by_group", "r1_polished"),
        outputs=("merged_consensus", "merged_fasta", "cons_codes"),
        resume_key="round1_consensus",
        resume_probe=N.round1_resume_probe,
        resume_reload=N.round1_resume_reload,
        # the hbm hand-off may cross the resume boundary BECAUSE the
        # reload re-encodes it from the checkpointed fasta (ir.py's
        # resume relaxation); merged_consensus rides along for the
        # artifact writers
        resume_provides=("merged_consensus", "cons_codes"),
    )
    b.add_node(
        "round2_fused_assign", N.round2_fused_assign,
        inputs=("cons_codes",), outputs=("cons_store",),
        units=lambda ctx, inputs: len(inputs["cons_codes"]),
    )
    if cfg.error_profile_sample:
        b.add_node(
            "round2_error_profile", N.round2_error_profile,
            inputs=("cons_store",), outputs=("r2_qc_profile",),
            commit=N.commit_round2_error_profile,
            units=lambda ctx, inputs: ctx.cfg.error_profile_sample,
        )
    b.add_node(
        "round2_umi_records", N.round2_umi_records,
        inputs=("cons_store",), outputs=("region_records",),
    )
    b.add_node(
        "round2_umi_cluster", N.round2_umi_cluster,
        inputs=("region_records",), outputs=("selected_by_region",),
        units=lambda ctx, inputs: sum(
            len(u) for _, u in inputs["region_records"]
        ),
    )
    b.add_node(
        "round2_counts", N.round2_counts,
        inputs=("cons_store", "selected_by_region"),
        outputs=("region_counts", "counts_csv"),
        checkpoint=True,
    )
    b.result("region_counts")
    return b.build()
