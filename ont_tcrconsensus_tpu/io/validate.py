"""Record-level input validation + quarantine policy (data-plane hardening).

The reference pipeline inherits input tolerance from battle-hardened native
tools (seqkit/minimap2 silently skip bad records); this framework's
first-party data plane was all-or-nothing — one malformed FASTQ record
raised ValueError and killed the whole library. This module is the data-
fault half of the robustness subsystem:

- The ``on_bad_record`` config key (:data:`POLICIES`) selects
  ``fail`` (legacy: first bad record raises), ``quarantine`` (bad records
  land in a per-library ``quarantine.fastq.gz`` with machine-readable
  reasons in ``robustness_report.json``) or ``drop`` (count + report only).
- :func:`parse_bytes_tolerant` is the pure-Python TWIN of the native C++
  tolerant parser (io/native/fastx_parser.cpp parse_stream_tol): the same
  resync algorithm, the same canonical reason strings, the same byte
  offsets. The differential ingest fuzzer (scripts/fuzz_ingest.py) asserts
  they agree record-for-record and rejection-for-rejection, so any change
  here must be mirrored there.
- :class:`IngestGuard` routes bad records per the policy and feeds the
  robustness report.
- :func:`validate_inputs` backs the ``tcr-consensus-tpu --validate``
  dry-run: config + input scan with no device work.

No jax imports anywhere in this module — the --validate path must run on a
host with a wedged device tunnel.
"""

from __future__ import annotations

import dataclasses
import gzip
import os
import sys
import zlib
from collections.abc import Iterator

import numpy as np

from ont_tcrconsensus_tpu.robustness import lockcheck

# Canonical malformation reasons — byte-for-byte identical to the kReason*
# strings in io/native/fastx_parser.cpp (the fuzzer pins this).
R_GZIP = "truncated or corrupt gzip stream"
R_NOT_FASTX = "not FASTA/FASTQ"
R_BAD_HEADER = "malformed FASTQ header"
R_MISSING_PLUS = "malformed FASTQ record (missing +)"
R_LEN_MISMATCH = "FASTQ qual length != seq length"
R_BAD_QUAL = "quality below Phred-33 '!'"
R_TRUNCATED = "truncated FASTQ record"

POLICIES = ("fail", "quarantine", "drop")

# base -> dense code LUT, mirroring ops/encode._CODE_LUT (A=0 C=1 G=2 T=3,
# N/other=4) without importing ops.encode (which pulls in jax-adjacent
# modules); tests pin the two tables equal.
CODE_LUT = np.full(256, 4, dtype=np.uint8)
for _b, _c in ((b"Aa", 0), (b"Cc", 1), (b"Gg", 2), (b"TtUu", 3)):
    for _ch in _b:
        CODE_LUT[_ch] = _c


@dataclasses.dataclass
class BadRecord:
    """One quarantined region of an input file."""

    offset: int    # absolute byte offset into the DECOMPRESSED stream
    reason: str    # canonical reason string (R_* above)
    raw: bytes     # the raw bytes of the region (quarantine payload)
    path: str = ""


@dataclasses.dataclass
class RawFastxRecord:
    """A record as raw bytes (full header, no name/comment split) — the
    representation the differential fuzzer compares against the native
    parser's columnar output."""

    header: bytes  # full header after the '@'/'>' marker
    seq: bytes
    qual: bytes | None  # None for FASTA
    offset: int         # byte offset of the record's header line


def _split_lines(data: bytes) -> list[tuple[int, int, int]]:
    """(line_start, content_end, next_line_start) per line; content_end
    excludes the '\\n' and one trailing '\\r' — the native next_line_t rule."""
    out: list[tuple[int, int, int]] = []
    pos, n = 0, len(data)
    while pos < n:
        nl = data.find(b"\n", pos)
        if nl == -1:
            start, end, nxt = pos, n, n
        else:
            start, end, nxt = pos, nl, nl + 1
        if end > start and data[end - 1] == 0x0D:  # '\r'
            end -= 1
        out.append((start, end, nxt))
        pos = nxt
    return out


def parse_bytes_tolerant(
    data: bytes, path: str = "",
) -> tuple[list[RawFastxRecord], list[BadRecord]]:
    """Tolerant parse of a whole decompressed buffer.

    The Python twin of the native ``parse_stream_tol`` at EOF: malformed
    regions become :class:`BadRecord` entries and parsing resynchronizes at
    the next candidate record start — a line starting with ``@`` whose
    line+2 starts with ``+`` (the structure check keeps a quality line that
    happens to begin with '@' from being mistaken for a header).
    """
    records: list[RawFastxRecord] = []
    bads: list[BadRecord] = []
    lines = _split_lines(data)
    n = len(lines)

    def content_first(i: int) -> int | None:
        s, e, _ = lines[i]
        return data[s] if e > s else None

    def candidate_from(i: int) -> int | None:
        """Smallest j >= i where line j starts '@' and line j+2 starts '+'."""
        j = i
        while j < n:
            if content_first(j) == 0x40 and j + 2 < n:  # '@'
                if content_first(j + 2) == 0x2B:  # '+'
                    return j
            j += 1
        return None

    # kind detection: skip blanks, quarantine leading junk
    i = 0
    kind = 0
    while i < n:
        s, e, _ = lines[i]
        if e == s:
            i += 1
            continue
        first = data[s]
        if first in (0x40, 0x3E):  # '@' '>'
            kind = first
            break
        # junk prefix: scan for the first record-start line
        j = i + 1
        while j < n:
            cf = content_first(j)
            if cf in (0x40, 0x3E):
                break
            j += 1
        junk_start = lines[i][0]
        junk_end = lines[j][0] if j < n else len(data)
        bads.append(BadRecord(junk_start, R_NOT_FASTX,
                              data[junk_start:junk_end], path))
        if j == n:
            return records, bads
        kind = content_first(j)
        i = j
        break
    if kind == 0:
        return records, bads  # empty / blanks only

    if kind == 0x3E:  # FASTA
        header: bytes | None = None
        hoff = 0
        seq_parts: list[bytes] = []
        while i < n:
            s, e, _ = lines[i]
            i += 1
            if e == s:
                continue
            if data[s] == 0x3E:
                if header is not None:
                    records.append(RawFastxRecord(
                        header, b"".join(seq_parts), None, hoff))
                header = data[s + 1:e]
                hoff = s
                seq_parts = []
            else:
                seq_parts.append(data[s:e])
        if header is not None:
            records.append(RawFastxRecord(header, b"".join(seq_parts), None, hoff))
        return records, bads

    # FASTQ
    while True:
        while i < n and lines[i][1] == lines[i][0]:  # skip blanks
            i += 1
        if i >= n:
            break
        rec_start = lines[i][0]
        hs, he, _ = lines[i]
        if data[hs] != 0x40:  # '@'
            j = candidate_from(i)
            end = lines[j][0] if j is not None else len(data)
            bads.append(BadRecord(rec_start, R_BAD_HEADER,
                                  data[rec_start:end], path))
            if j is None:
                break
            i = j
            continue
        if i + 3 >= n:
            bads.append(BadRecord(rec_start, R_TRUNCATED,
                                  data[rec_start:], path))
            break
        ss, se, _ = lines[i + 1]
        ps, pe, _ = lines[i + 2]
        qs, qe, _ = lines[i + 3]
        if pe == ps or data[ps] != 0x2B:  # '+'
            j = candidate_from(i + 1)
            end = lines[j][0] if j is not None else len(data)
            bads.append(BadRecord(rec_start, R_MISSING_PLUS,
                                  data[rec_start:end], path))
            if j is None:
                break
            i = j
            continue
        rec_end = lines[i + 3][2]
        if se - ss != qe - qs:
            bads.append(BadRecord(rec_start, R_LEN_MISMATCH,
                                  data[rec_start:rec_end], path))
            i += 4
            continue
        qual = data[qs:qe]
        if qual and min(qual) < 33:
            bads.append(BadRecord(rec_start, R_BAD_QUAL,
                                  data[rec_start:rec_end], path))
            i += 4
            continue
        records.append(RawFastxRecord(
            data[hs + 1:he], data[ss:se], qual, rec_start))
        i += 4
    return records, bads


def read_bytes_tolerant(path: str | os.PathLike[str]) -> tuple[bytes, bool]:
    """(decompressed bytes, gzip_error) with gzread-compatible semantics.

    Mirrors zlib's ``gzopen`` transparency: content without the gzip magic
    is returned verbatim regardless of the file extension; a truncated or
    corrupt gzip stream yields the decodable prefix plus ``gzip_error=True``
    instead of an exception. Multi-member (concatenated) gzip is handled.
    """
    with open(path, "rb") as fh:
        raw = fh.read()
    if raw[:2] != b"\x1f\x8b":
        return raw, False
    out = bytearray()
    buf = raw
    while buf:
        d = zlib.decompressobj(31)
        try:
            out += d.decompress(buf)
        except zlib.error:
            return bytes(out), True
        if not d.eof:
            return bytes(out), True  # truncated member
        if d.unused_data[:2] == b"\x1f\x8b":
            buf = d.unused_data
        else:
            break  # trailing non-gzip garbage: stop like gzread
    return bytes(out), False


def parse_path_tolerant(
    path: str | os.PathLike[str],
) -> tuple[list[RawFastxRecord], list[BadRecord]]:
    """Tolerant parse of a file (gzip-transparent): the pure-Python ingest
    path under ``on_bad_record != fail`` and the fuzzer's reference."""
    p = os.fspath(path)
    data, gz_error = read_bytes_tolerant(p)
    records, bads = parse_bytes_tolerant(data, p)
    if gz_error:
        bads.append(BadRecord(len(data), R_GZIP, b"", p))
    return records, bads


def iter_records_tolerant(
    path: str | os.PathLike[str], guard: "IngestGuard",
) -> Iterator:
    """FastxRecord stream with bad records routed through ``guard`` — the
    pure-Python fallback for the pipeline's quarantine/drop ingest path.

    Reached only when the native toolchain is absent (the native parser,
    when available, streams and reports bads per chunk). This fallback
    MATERIALIZES the decompressed file: the tolerant resync algorithm is
    whole-buffer, and keeping it byte-identical to the native twin (the
    fuzzer's contract) outweighs streaming on the no-toolchain path —
    lane-scale quarantine ingest requires the native parser.
    """
    from ont_tcrconsensus_tpu.io import fastx

    records, bads = parse_path_tolerant(path)
    for bad in bads:
        guard.handle(bad)
    for rec in records:
        header = rec.header.decode("utf-8", "replace")
        parts = header.split(None, 1)
        name = parts[0] if parts else ""
        comment = parts[1] if len(parts) > 1 else ""
        yield fastx.FastxRecord(
            name, comment,
            rec.seq.decode("utf-8", "replace"),
            rec.qual.decode("utf-8", "replace") if rec.qual is not None else None,
        )


class IngestGuard:
    """Routes bad records per the ``on_bad_record`` policy.

    ``quarantine``: raw bytes of every bad region are appended to
    ``quarantine_path`` (a gzip member stream) and machine-readable reasons
    land in ``robustness_report.json`` via the recorder at
    :func:`finalize`. ``drop``: count + report only. The guard is created
    per library and per attempt-scope: :func:`reset` rewinds it so a
    transient-retry of the whole ingest pass cannot double-count or
    double-append.
    """

    MAX_DETAIL_EVENTS = 20  # per-record report entries; the rest summarize

    def __init__(self, policy: str, source: str = "",
                 quarantine_path: str | None = None):
        if policy not in ("quarantine", "drop"):
            raise ValueError(
                f"IngestGuard policy must be quarantine|drop, got {policy!r}"
            )
        self.policy = policy
        self.source = source
        self.quarantine_path = quarantine_path if policy == "quarantine" else None
        self._fh = None
        self._finalized = False
        # bad records arrive on the ingest prefetch worker thread while
        # reset() (the transient-retry hook) runs on the main thread
        self._lock = lockcheck.make_lock()
        self.reset()

    def reset(self) -> None:
        """Rewind for a retry: drop counters and truncate the artifact."""
        with self._lock:
            self._close_locked()
            self.n_bad = 0
            self.by_reason: dict[str, int] = {}
            self.events: list[BadRecord] = []
            if self.quarantine_path and os.path.exists(self.quarantine_path):
                os.remove(self.quarantine_path)
            self._finalized = False

    def handle(self, bad: BadRecord) -> None:
        with self._lock:
            self.n_bad += 1
            self.by_reason[bad.reason] = self.by_reason.get(bad.reason, 0) + 1
            if len(self.events) < self.MAX_DETAIL_EVENTS:
                self.events.append(bad)
            if self.quarantine_path and bad.raw:
                if self._fh is None:
                    self._fh = gzip.open(self.quarantine_path, "wb")
                self._fh.write(bad.raw)

    def handle_native(self, parsed_bad: list[tuple[int, str, bytes]]) -> None:
        """Consume a native chunk's ``ParsedFastx.bad`` list."""
        for offset, reason, raw in parsed_bad:
            self.handle(BadRecord(offset, reason, raw, self.source))

    def _close_locked(self) -> None:
        lockcheck.assert_held(self._lock, "IngestGuard._close_locked")
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def close(self) -> None:
        with self._lock:
            self._close_locked()

    def finalize(self, recorder=None) -> dict:
        """Close the artifact, push report events, return the summary."""
        self.close()
        summary = {
            "source": self.source,
            "policy": self.policy,
            "n_bad": self.n_bad,
            "by_reason": dict(self.by_reason),
            # only name the artifact when it was actually written — a
            # zero-raw-bytes event set (e.g. a lone gzip-truncation at a
            # record boundary) creates no file to point an operator at
            "quarantine_path": (
                self.quarantine_path
                if self.quarantine_path and os.path.exists(self.quarantine_path)
                else None
            ),
        }
        if self._finalized or recorder is None or not self.n_bad:
            self._finalized = True
            return summary
        outcome = "quarantined" if self.policy == "quarantine" else "dropped"
        for bad in self.events:
            recorder.record(
                "ingest.quarantine", classification="data_fault",
                outcome=outcome,
                detail={"file": bad.path or self.source, "offset": bad.offset,
                        "reason": bad.reason, "bytes": len(bad.raw)},
            )
        recorder.record(
            "ingest.quarantine", classification="data_fault",
            outcome="summary", detail=summary,
        )
        self._finalized = True
        return summary


# ---------------------------------------------------------------------------
# --validate dry-run (config + input scan, no device work)


def scan_file(path: str | os.PathLike[str]) -> dict:
    """Record-count/size scan of one input file via the tolerant parser
    (native when it builds, pure Python otherwise). The native path streams
    in O(chunk) host memory — a --validate dry-run over lane-scale fastqs
    must never materialize a whole file."""
    p = os.fspath(path)
    out = {
        "path": p,
        "size_bytes": os.path.getsize(p),
        "records": 0,
        "bases": 0,
        "bad_records": 0,
        "bad_reasons": {},
    }
    from ont_tcrconsensus_tpu.io import native

    bads: list[tuple[int, str]] = []
    if native.available():
        for chunk in native.parse_chunks(p, tolerant=True):
            out["records"] += int(chunk.num_records)
            out["bases"] += int(chunk.lengths.sum()) if chunk.num_records else 0
            bads.extend((o, r) for o, r, _ in chunk.bad)
    else:
        records, bad_list = parse_path_tolerant(p)
        out["records"] = len(records)
        out["bases"] = sum(len(r.seq) for r in records)
        bads = [(b.offset, b.reason) for b in bad_list]
    out["bad_records"] = len(bads)
    for _, reason in bads:
        out["bad_reasons"][reason] = out["bad_reasons"].get(reason, 0) + 1
    if bads:
        out["first_bad"] = {"offset": bads[0][0], "reason": bads[0][1]}
    return out


def scan_manifests(fastq_pass_dir: str) -> list[dict]:
    """Integrity scan of an EXISTING workdir's stage manifests (--validate).

    For every ``nano_tcr/<library>/stage_manifest.json``: classify the
    manifest (``v2`` / ``v1`` / ``torn``) and, for v2, verify every
    completed stage's recorded artifacts with FULL sha256 checking — the
    dry-run twin of ``verify_resume=full``, so an operator can audit a
    workdir for silent corruption before committing compute to a resume.
    Returns one dict per manifest: ``{library, path, status,
    stages: {stage: reason|None}}`` (reason None = verified clean).
    """
    import glob
    import json

    from ont_tcrconsensus_tpu.io import layout

    out: list[dict] = []
    pattern = os.path.join(fastq_pass_dir, "nano_tcr", "*", "stage_manifest.json")
    for mpath in sorted(glob.glob(pattern)):
        lib_dir = os.path.dirname(mpath)
        library = os.path.basename(lib_dir)
        entry: dict = {"library": library, "path": mpath, "stages": {}}
        try:
            with open(mpath) as fh:
                raw = json.load(fh)
        except ValueError:
            entry["status"] = "torn"
            out.append(entry)
            continue
        except OSError as exc:
            entry["status"] = "unreadable"
            entry["error"] = str(exc)
            out.append(entry)
            continue
        if not isinstance(raw, dict):
            entry["status"] = "torn"
            out.append(entry)
            continue
        if "version" in raw and not isinstance(raw.get("stages"), dict):
            # valid JSON wearing a v2 header over a broken body: exactly
            # the torn state this scan exists to flag (resume would redo
            # the whole library) — never "v2, 0 stages, all clean"
            entry["status"] = "torn"
            out.append(entry)
            continue
        entry["status"] = "v2" if "version" in raw else "v1"
        lay = layout.LibraryLayout(library=library, library_dir=lib_dir)
        readable = lay.completed_stages()
        # raw keys read_manifest() dropped are damaged entries — the
        # operator should see them, not an undercount that looks clean
        # (a v1 manifest is flat {stage: time}, so its own keys diff the
        # same way as a v2 stages map)
        raw_stages = raw["stages"] if entry["status"] == "v2" else raw
        for stage in raw_stages:
            if stage not in readable:
                entry["stages"][str(stage)] = (
                    "malformed manifest entry (resume will redo it)"
                )
        for stage in readable:
            if entry["status"] == "v1":
                entry["stages"][stage] = "v1 entry — no checksums recorded"
                continue
            ok, why = lay.verify_stage(stage, "full")
            entry["stages"][stage] = None if ok else why
        out.append(entry)
    return out


def _find_fastqs(fastq_pass_dir: str) -> list[str]:
    # same two-pattern discovery as pipeline/run.py (duplicated so the
    # dry-run never imports the jax-bearing pipeline modules)
    import glob

    found = sorted(glob.glob(os.path.join(fastq_pass_dir, "barcode*", "*fastq*")))
    if not found:
        found = sorted(glob.glob(os.path.join(fastq_pass_dir, "*.fastq*")))
    return found


def validate_inputs(config_path: str, out=None, as_json: bool = False) -> int:
    """``tcr-consensus-tpu --validate``: parse the config, scan every input
    file (record counts/sizes only — no device work), print a validation
    report, return 0 when clean / 1 on any problem.  ``as_json`` swaps the
    human lines for one machine-readable body (problems + the graftcheck
    semantic report) with the same exit code."""
    import json as json_mod

    out = out if out is not None else sys.stdout
    problems: list[str] = []
    graftcheck_body: dict | None = None
    compile_cache: dict | None = None

    def p(*parts):
        if not as_json:
            print(*parts, file=out)

    def finish(rc: int) -> int:
        if as_json:
            print(json_mod.dumps({
                "config": config_path,
                "ok": rc == 0,
                "problems": problems,
                "compile_cache": compile_cache,
                "graftcheck": graftcheck_body,
            }, indent=2), file=out)
        return rc

    p(f"validate: config {config_path}")
    from ont_tcrconsensus_tpu.pipeline.config import RunConfig

    try:
        cfg = RunConfig.from_json(config_path)
    except (OSError, ValueError, TypeError) as exc:  # TypeError: missing keys
        problems.append(f"config failed to load/validate: {exc}")
        p(f"PROBLEM: {problems[0]}")
        p("validate: FAIL (1 problem)")
        return finish(1)

    # persistent XLA compilation cache resolution (same rules as
    # pipeline/run.py enable_compilation_cache, without importing jax):
    # "off" disables, null means the default user-cache path. Surfaced so
    # an operator can see where warm-start executables will land — and
    # whether a daemon restart will find them — before any device work.
    if cfg.compile_cache_dir == "off":
        compile_cache = {"enabled": False, "dir": None}
        p("validate: compile cache: disabled (compile_cache_dir=\"off\")")
    else:
        resolved = cfg.compile_cache_dir or os.path.join(
            os.path.expanduser("~"), ".cache", "ont_tcrconsensus_tpu_xla")
        compile_cache = {"enabled": True, "dir": resolved,
                         "exists": os.path.isdir(resolved)}
        p(f"validate: compile cache: {resolved}"
          f"{' (will be created)' if not compile_cache['exists'] else ''}")

    # executor knob: a graph-executor config must declare a graph that
    # passes builder validation (cycles, undeclared/dangling edges, hbm
    # edges crossing a disk-resume boundary, ...) — each named problem is
    # surfaced here, BEFORE a run wastes device time. graph/ is jax-free,
    # so this stays safe on a machine without an accelerator stack.
    if cfg.executor == "graph":
        from ont_tcrconsensus_tpu.graph import pipeline as graph_pipeline
        from ont_tcrconsensus_tpu.graph.ir import GraphValidationError

        try:
            spec = graph_pipeline.build_library_graph(cfg)
        except GraphValidationError as exc:
            problems.extend(f"stage graph: {prob}" for prob in exc.problems)
        else:
            p(f"validate: stage graph: {len(spec.schedule)} nodes, "
              f"{len(spec.edges)} edges, "
              f"{len(spec.side_sinks())} off-critical-path")
            # graftcheck: semantic analysis of the built graph (liveness /
            # donation / placement flow / sharding pairing — graph/check.py,
            # jax-free). Violations are validation problems; advisories
            # (the known host round-trips) are informational. Never-crash:
            # an analyzer bug must not block a run an operator could start.
            try:
                from ont_tcrconsensus_tpu.graph import check as graph_check

                report = graph_check.analyze(
                    spec, graph_check.production_byte_model(cfg))
                graftcheck_body = report.to_dict()
                s = report.summary()
                p(f"validate: graftcheck: {s['verdict']} "
                  f"({s['violations']} violation(s), "
                  f"{s['advisories']} advisory(ies)); hbm high-water "
                  f"~{s['hbm_high_water_bytes_est']} bytes at "
                  f"{s['hbm_high_water_node']}")
                for f in report.advisories:
                    p(f"validate:   graftcheck advisory: {f.kind}: "
                      f"{f.message}")
                problems.extend(
                    f"graftcheck: {f.kind}: {f.message}"
                    for f in report.violations
                )
            except Exception as exc:
                p(f"validate: WARNING: graftcheck analysis failed: {exc!r}")

    from ont_tcrconsensus_tpu.io import fastx

    try:
        reference = fastx.read_fasta_dict(cfg.reference_file)
        p(f"validate: reference {cfg.reference_file}: {len(reference)} regions")
        if not reference:
            problems.append(f"reference {cfg.reference_file} has no sequences")
    except (OSError, ValueError) as exc:
        problems.append(f"reference {cfg.reference_file} unreadable: {exc}")
    if cfg.trim_primers:
        try:
            n_primers = len(cfg.primer_sequences())
            p(f"validate: primers: {n_primers} sequences")
            if not n_primers:
                problems.append("primer trimming enabled but primer set is empty")
        except (OSError, ValueError) as exc:
            problems.append(f"primers fasta unreadable: {exc}")

    fastqs = _find_fastqs(cfg.fastq_pass_dir)
    if not fastqs:
        problems.append(f"no fastq files under {cfg.fastq_pass_dir}")
    total_records = 0
    for fq in fastqs:
        try:
            scan = scan_file(fq)
        except OSError as exc:
            problems.append(f"{fq}: unreadable: {exc}")
            continue
        total_records += scan["records"]
        line = (f"validate: {fq}: {scan['records']} records, "
                f"{scan['bases']} bases, {scan['size_bytes']} bytes")
        if scan["bad_records"]:
            line += f", {scan['bad_records']} BAD"
            first = scan["first_bad"]
            problems.append(
                f"{fq}: {scan['bad_records']} malformed record(s); first at "
                f"byte offset {first['offset']}: {first['reason']} "
                f"(reasons: {scan['bad_reasons']})"
            )
        p(line)
    if fastqs and not total_records:
        problems.append("input files contain zero parseable records")

    # existing-workdir integrity: stage manifests + completed-artifact
    # checksums (the --validate twin of verify_resume=full). A v1 manifest
    # is informational (legacy runs are not an error — resume under
    # fast/full will warn and re-run); torn manifests and checksum
    # mismatches are problems an operator should see BEFORE a resume.
    for m in scan_manifests(cfg.fastq_pass_dir):
        if m["status"] in ("torn", "unreadable"):
            p(f"validate: manifest {m['path']}: {m['status'].upper()}")
            problems.append(
                f"{m['path']}: {m['status']} stage manifest (resume will "
                "redo the library; a crash mid-write or disk fault)"
            )
            continue
        bad = {s: why for s, why in m["stages"].items() if why is not None}
        n_ok = len(m["stages"]) - len(bad)
        line = (f"validate: manifest {m['path']} ({m['status']}): "
                f"{len(m['stages'])} stage(s), {n_ok} verified")
        if m["status"] == "v1":
            p(line + " — v1 (no checksums; verified resume will re-run)")
            # legacy-ness is informational, but a DROPPED (malformed) v1
            # entry is the same damage a v2 audit flags — same verdict
            for stage, why in bad.items():
                if "malformed" in why:
                    problems.append(f"{m['path']}: stage {stage!r}: {why}")
            continue
        p(line)
        for stage, why in bad.items():
            if "no checksums recorded" in why:
                # a migrated manifest's v1-era entries (artifacts: null):
                # legacy, not damage — same informational verdict as a
                # pure-v1 manifest; verified resume will warn and re-run
                p(f"validate:   stage {stage!r}: v1-era entry (no "
                  "checksums; verified resume will re-run)")
                continue
            problems.append(
                f"{m['path']}: stage {stage!r} failed artifact "
                f"verification: {why}"
            )

    if problems:
        for prob in problems:
            p(f"PROBLEM: {prob}")
        p(f"validate: FAIL ({len(problems)} problem(s))")
        return finish(1)
    p(f"validate: OK ({len(fastqs)} input file(s), {total_records} records)")
    return finish(0)
