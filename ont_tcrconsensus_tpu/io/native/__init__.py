"""ctypes loader for the native fastx parser (build-on-first-use).

The shared library is compiled from ``fastx_parser.cpp`` with the system
g++ on first import (cached next to the source); when no compiler/zlib is
available every consumer silently falls back to the pure-Python parser in
:mod:`..fastx`, which has identical semantics (the native parser's contract
is pinned by tests that compare the two).

Sanitized builds: ``GRAFT_SANITIZE=address,undefined`` (any
``-fsanitize=`` value) switches every build — install-time (setup.py) and
build-on-first-use alike — to ``-O1 -g -fsanitize=... -fno-omit-frame-
pointer``. An ASan library only loads into a process that preloaded the
ASan runtime, so the sanitized fuzz replay re-execs itself under
``LD_PRELOAD=libasan.so`` (scripts/fuzz_ingest.py --sanitized) with
``GRAFT_FASTX_LIB`` pointing the loader at the sanitized artifact.
"""

from __future__ import annotations

import ctypes
import dataclasses
import os
import subprocess
import threading

import numpy as np

_SRC = os.path.join(os.path.dirname(__file__), "fastx_parser.cpp")
_LIB = os.path.join(os.path.dirname(__file__), "libfastx.so")
_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_build_failed = False

#: the native build is first-party C++ now, not vendored glue: it compiles
#: warning-clean and stays that way (tools/graftlint's native complement)
WARN_FLAGS = ("-Wall", "-Wextra")

SANITIZE_ENV = "GRAFT_SANITIZE"  # e.g. "address,undefined"
LIB_OVERRIDE_ENV = "GRAFT_FASTX_LIB"  # load exactly this .so, never build


def build_command(src: str, out: str, sanitize: str | None = None) -> list[str]:
    """The g++ command line for ``src`` -> ``out`` (shared with setup.py).

    ``sanitize`` is a ``-fsanitize=`` value ("address,undefined"); it
    drops -O3 to -O1 and keeps frame pointers so reports carry usable
    stacks.
    """
    if sanitize:
        opt = ["-O1", "-g", f"-fsanitize={sanitize}", "-fno-omit-frame-pointer"]
    else:
        opt = ["-O3"]
    return ["g++", *opt, *WARN_FLAGS, "-shared", "-fPIC", src, "-lz", "-o", out]


def build_library(out_path: str, sanitize: str | None = None,
                  timeout: int = 240) -> tuple[bool, str]:
    """Compile the parser to ``out_path``; returns (ok, compiler output)."""
    cmd = build_command(_SRC, out_path, sanitize=sanitize)
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=timeout)
        return proc.returncode == 0, (proc.stderr or proc.stdout or "")
    except (OSError, subprocess.TimeoutExpired) as exc:
        return False, repr(exc)


def asan_runtime_path() -> str | None:
    """Path to g++'s libasan.so (to LD_PRELOAD); None when unavailable."""
    try:
        proc = subprocess.run(
            ["g++", "-print-file-name=libasan.so"],
            capture_output=True, text=True, timeout=30,
        )
    except (OSError, subprocess.TimeoutExpired):
        return None
    path = proc.stdout.strip()
    return path if proc.returncode == 0 and os.path.isabs(path) else None


def _build() -> bool:
    ok, _ = build_library(_LIB, sanitize=os.environ.get(SANITIZE_ENV) or None)
    return ok


#: path the cached _lib was loaded from (override authority check)
_lib_path: str | None = None


def load() -> ctypes.CDLL | None:
    """The shared library, building it if needed; None when unavailable."""
    global _lib, _lib_path, _build_failed
    with _lock:
        # The override is consulted BEFORE any cached state: an explicit
        # artifact (sanitized fuzz child) must load exactly that .so or
        # fail loudly, even when an earlier in-process load() already
        # cached the default build or recorded a build failure — a silent
        # fallback would turn the sanitizer gate into a no-op.
        override = os.environ.get(LIB_OVERRIDE_ENV)
        if _lib is not None and (not override or _lib_path == override):
            return _lib
        if override:
            lib_path = override
        else:
            if _build_failed:
                return None
            lib_path = _LIB
            if (not os.path.exists(_LIB)
                    or os.path.getmtime(_LIB) < os.path.getmtime(_SRC)):
                if not _build():
                    _build_failed = True
                    return None
        lib = ctypes.CDLL(lib_path)
        lib.fastx_parse.restype = ctypes.c_void_p
        lib.fastx_parse.argtypes = [ctypes.c_char_p]
        lib.fastx_error.restype = ctypes.c_char_p
        lib.fastx_error.argtypes = [ctypes.c_void_p]
        for fn in ("fastx_num_records", "fastx_total_bases", "fastx_names_size"):
            getattr(lib, fn).restype = ctypes.c_int64
            getattr(lib, fn).argtypes = [ctypes.c_void_p]
        lib.fastx_has_qual.restype = ctypes.c_int
        lib.fastx_has_qual.argtypes = [ctypes.c_void_p]
        lib.fastx_copy.restype = None
        lib.fastx_copy.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_char_p,
        ]
        lib.fastx_free.restype = None
        lib.fastx_free.argtypes = [ctypes.c_void_p]
        # tolerant (quarantine-mode) API: bad-record accessors + the
        # tolerant open/parse variants (PR 3 data-plane hardening)
        lib.fastx_parse2.restype = ctypes.c_void_p
        lib.fastx_parse2.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.fastx_num_bad.restype = ctypes.c_int64
        lib.fastx_num_bad.argtypes = [ctypes.c_void_p]
        lib.fastx_bad_offset.restype = ctypes.c_int64
        lib.fastx_bad_offset.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.fastx_bad_reason.restype = ctypes.c_char_p
        lib.fastx_bad_reason.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.fastx_bad_raw_size.restype = ctypes.c_int64
        lib.fastx_bad_raw_size.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.fastx_bad_raw_copy.restype = None
        lib.fastx_bad_raw_copy.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_char_p,
        ]
        lib.fastx_open2.restype = ctypes.c_void_p
        lib.fastx_open2.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.fastx_open.restype = ctypes.c_void_p
        lib.fastx_open.argtypes = [ctypes.c_char_p]
        lib.fastx_stream_error.restype = ctypes.c_char_p
        lib.fastx_stream_error.argtypes = [ctypes.c_void_p]
        lib.fastx_next_chunk.restype = ctypes.c_void_p
        lib.fastx_next_chunk.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.fastx_close.restype = None
        lib.fastx_close.argtypes = [ctypes.c_void_p]
        _lib, _lib_path = lib, lib_path
        return _lib


@dataclasses.dataclass
class ParsedFastx:
    """Columnar parse result: dense codes ready for the device batcher."""

    codes: np.ndarray     # (total_bases,) uint8 dense codes
    quals: np.ndarray | None  # (total_bases,) uint8 phred, None for FASTA
    lengths: np.ndarray   # (N,) int32
    offsets: np.ndarray   # (N+1,) int64 into codes/quals
    names: list[str]      # full headers
    # tolerant mode: (absolute byte offset, canonical reason, raw bytes)
    # per quarantined region; always [] under the strict (default) parse
    bad: list[tuple[int, str, bytes]] = dataclasses.field(default_factory=list)

    @property
    def num_records(self) -> int:
        return len(self.lengths)

    def record(self, i: int) -> tuple[str, np.ndarray, np.ndarray | None]:
        s, e = self.offsets[i], self.offsets[i + 1]
        return (
            self.names[i],
            self.codes[s:e],
            self.quals[s:e] if self.quals is not None else None,
        )


def _copy_out(lib, handle, path) -> ParsedFastx:
    """Copy a native ParsedFile handle into numpy arrays (then free it)."""
    try:
        err = lib.fastx_error(handle)
        if err:
            raise ValueError(f"{path}: {err.decode()}")
        n = lib.fastx_num_records(handle)
        total = lib.fastx_total_bases(handle)
        has_qual = bool(lib.fastx_has_qual(handle))
        codes = np.zeros(total, np.uint8)
        quals = np.zeros(total, np.uint8) if has_qual else None
        lengths = np.zeros(n, np.int32)
        offsets = np.zeros(n + 1, np.int64)
        names_buf = ctypes.create_string_buffer(int(lib.fastx_names_size(handle)))
        lib.fastx_copy(
            handle,
            codes.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            quals.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)) if has_qual else None,
            lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            names_buf,
        )
        names = names_buf.raw.decode("utf-8", "replace").split("\n")[:n]
        bad: list[tuple[int, str, bytes]] = []
        for i in range(int(lib.fastx_num_bad(handle))):
            size = int(lib.fastx_bad_raw_size(handle, i))
            raw_buf = ctypes.create_string_buffer(size) if size else None
            if raw_buf is not None:
                lib.fastx_bad_raw_copy(handle, i, raw_buf)
            bad.append((
                int(lib.fastx_bad_offset(handle, i)),
                lib.fastx_bad_reason(handle, i).decode("utf-8", "replace"),
                raw_buf.raw if raw_buf is not None else b"",
            ))
        return ParsedFastx(codes=codes, quals=quals, lengths=lengths,
                           offsets=offsets, names=names, bad=bad)
    finally:
        lib.fastx_free(handle)


def parse_file(
    path: str | os.PathLike[str], tolerant: bool = False,
) -> ParsedFastx | None:
    """Parse with the native library; None when the library is unavailable.

    Strict (default): raises ValueError on malformed input (same contract as
    fastx.read_fastx). ``tolerant=True``: malformed records/regions land in
    ``ParsedFastx.bad`` (offset, canonical reason, raw bytes) and parsing
    resynchronizes at the next record — the quarantine-policy ingest path.
    Materializes the WHOLE file — fine for references and tests; lane-scale
    read files go through :func:`parse_chunks` (SURVEY §7 hard-part 5).
    """
    lib = load()
    if lib is None:
        return None
    handle = lib.fastx_parse2(os.fspath(path).encode(), 1 if tolerant else 0)
    return _copy_out(lib, handle, path)


def parse_chunks(
    path: str | os.PathLike[str], chunk_bases: int = 32 << 20,
    tolerant: bool = False,
):
    """Generator of ParsedFastx chunks with O(chunk) host memory.

    Yields nothing (and returns) when the native library is unavailable —
    callers must check :func:`available` first or fall back themselves.
    Raises ValueError on malformed input, like :func:`parse_file`; with
    ``tolerant=True`` malformed regions ride along in each chunk's ``bad``
    list instead (a chunk may carry bad entries and zero records).
    """
    lib = load()
    if lib is None:
        return
    stream = lib.fastx_open2(os.fspath(path).encode(), 1 if tolerant else 0)
    try:
        err = lib.fastx_stream_error(stream)
        if err:
            raise ValueError(f"{path}: {err.decode()}")
        while True:
            handle = lib.fastx_next_chunk(stream, chunk_bases)
            if not handle:
                err = lib.fastx_stream_error(stream)
                if err:
                    raise ValueError(f"{path}: {err.decode()}")
                return
            yield _copy_out(lib, handle, path)
    finally:
        lib.fastx_close(stream)


def available() -> bool:
    """True when the native parser builds/loads on this host."""
    return load() is not None
