// Fast FASTQ/FASTA parser: the native host-IO component of the data plane.
//
// The reference pipeline leans on pysam/htslib (C) and external tools for
// sequence IO (SURVEY §2.2); this framework's equivalent is a first-party
// C++ parser that decodes records straight into the dense uint8 code / Phred
// arrays the device batcher consumes, skipping Python string round-trips.
// Loaded via ctypes (io/native/__init__.py); the pure-Python parser in
// io/fastx.py remains the semantic reference and fallback.
//
// Build: g++ -O3 -shared -fPIC fastx_parser.cpp -lz -o libfastx.so

#include <zlib.h>

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

namespace {

// Canonical malformation reasons. These exact strings are mirrored by the
// pure-Python tolerant parser (io/validate.py) — the differential ingest
// fuzzer asserts rejection-for-rejection agreement on them, so any edit
// here must be made in both places.
const char* kReasonGzip = "truncated or corrupt gzip stream";
const char* kReasonNotFastx = "not FASTA/FASTQ";
const char* kReasonBadHeader = "malformed FASTQ header";
const char* kReasonMissingPlus = "malformed FASTQ record (missing +)";
const char* kReasonLenMismatch = "FASTQ qual length != seq length";
const char* kReasonBadQual = "quality below Phred-33 '!'";
const char* kReasonTruncated = "truncated FASTQ record";

struct BadRec {
  int64_t offset;      // absolute decompressed byte offset of the bad region
  std::string reason;  // one of the kReason* strings above
  std::string raw;     // the raw bytes of the bad region (quarantine payload)
};

struct ParsedFile {
  // flat record storage
  std::vector<uint8_t> codes;      // dense codes, concatenated
  std::vector<uint8_t> quals;      // phred (0-based), concatenated; empty for FASTA
  std::vector<int64_t> offsets;    // per-record start into codes/quals (n+1 entries)
  std::vector<int32_t> lengths;    // per-record length
  std::string names;               // '\n'-joined full headers
  bool has_qual = false;
  std::string error;
  std::vector<BadRec> bad;         // tolerant mode: quarantined regions
};

void add_bad(ParsedFile* out, int64_t off, const char* reason,
             const std::string& data, size_t a, size_t b) {
  BadRec r;
  r.offset = off;
  r.reason = reason;
  r.raw = data.substr(a, b - a);
  out->bad.push_back(std::move(r));
}

// base -> dense code (A=0 C=1 G=2 T=3 N/other=4), matching ops/encode.py
const uint8_t* code_lut() {
  static uint8_t lut[256];
  static bool init = false;
  if (!init) {
    memset(lut, 4, sizeof(lut));
    lut['A'] = lut['a'] = 0;
    lut['C'] = lut['c'] = 1;
    lut['G'] = lut['g'] = 2;
    lut['T'] = lut['t'] = lut['U'] = lut['u'] = 3;
    init = true;
  }
  return lut;
}

// A truncated gzip stream makes gzread return 0 (like clean EOF) with the
// error only visible through gzerror (Z_BUF_ERROR "unexpected end of
// file") — checking the return value alone silently accepts truncated
// input (the ingest fuzzer caught exactly that in the original read_all).
bool gz_stream_bad(gzFile fh, int n) {
  if (n < 0) return true;
  int errnum = 0;
  gzerror(fh, &errnum);
  return errnum < 0;
}

bool read_all(const char* path, std::string* out, std::string* err) {
  gzFile fh = gzopen(path, "rb");  // transparently handles plain files too
  if (!fh) {
    *err = "cannot open file";
    return false;
  }
  char buf[1 << 16];
  int n;
  while ((n = gzread(fh, buf, sizeof(buf))) > 0) out->append(buf, n);
  bool ok = !gz_stream_bad(fh, n);
  if (!ok) *err = kReasonGzip;
  gzclose(fh);
  return ok;
}

// Tolerant whole-file read: a mid-stream gzip truncation/corruption keeps
// the decodable prefix and sets *gz_error instead of failing the file.
bool read_all_tol(const char* path, std::string* out, std::string* err,
                  bool* gz_error) {
  gzFile fh = gzopen(path, "rb");
  if (!fh) {
    *err = "cannot open file";
    return false;
  }
  char buf[1 << 16];
  int n;
  while ((n = gzread(fh, buf, sizeof(buf))) > 0) out->append(buf, n);
  *gz_error = gz_stream_bad(fh, n);
  gzclose(fh);
  return true;
}

// next line [start, end) exclusive of newline; returns false at EOF
bool next_line(const std::string& s, size_t* pos, size_t* start, size_t* end) {
  if (*pos >= s.size()) return false;
  *start = *pos;
  size_t nl = s.find('\n', *pos);
  if (nl == std::string::npos) {
    *end = s.size();
    *pos = s.size();
  } else {
    *end = nl;
    *pos = nl + 1;
  }
  if (*end > *start && s[*end - 1] == '\r') --*end;
  return true;
}

// next line, also reporting whether the line is TERMINATED (a '\n' was
// seen) — a streaming chunk may end mid-line, and an unterminated line is
// only trustworthy at EOF
bool next_line_t(const std::string& s, size_t* pos, size_t* start, size_t* end,
                 bool* terminated) {
  if (*pos >= s.size()) return false;
  *start = *pos;
  size_t nl = s.find('\n', *pos);
  if (nl == std::string::npos) {
    *end = s.size();
    *pos = s.size();
    *terminated = false;
  } else {
    *end = nl;
    *pos = nl + 1;
    *terminated = true;
  }
  if (*end > *start && s[*end - 1] == '\r') --*end;
  return true;
}

void emit_record(ParsedFile* out, const std::string& data, size_t ha, size_t hb,
                 const std::string& seq) {
  const uint8_t* lut = code_lut();
  for (char c : seq) out->codes.push_back(lut[(uint8_t)c]);
  out->lengths.push_back((int32_t)seq.size());
  out->offsets.push_back((int64_t)out->codes.size());
  out->names.append(data, ha, hb - ha);
  out->names += '\n';
}

// Incremental parse: consume COMPLETE records from data into out, set
// *consumed to the byte offset after the last fully-parsed record (the
// caller carries the tail into the next chunk). When at_eof, a trailing
// partial record is an error (FASTQ) or final record (FASTA) exactly like
// the whole-file parser.
bool parse_stream_buffer(const std::string& data, bool at_eof, char* kind_io,
                         ParsedFile* out, size_t* consumed) {
  const uint8_t* lut = code_lut();
  size_t pos = 0, a, b;
  bool term;
  *consumed = 0;
  out->offsets.push_back(0);
  // skip leading blank lines
  size_t scan = 0;
  bool any = false;
  while (next_line_t(data, &scan, &a, &b, &term)) {
    if (a == b) { *consumed = scan; continue; }
    any = true;
    break;
  }
  if (!any) { *consumed = data.size(); return true; }  // blanks only
  if (*kind_io == 0) {
    char kind = data[a];
    if (kind != '@' && kind != '>') {
      out->error = "not FASTA/FASTQ";
      return false;
    }
    *kind_io = kind;
  }
  out->has_qual = *kind_io == '@';
  pos = a;  // first record header start

  if (*kind_io == '>') {
    std::string seq;
    size_t ha = 0, hb = 0;
    size_t rec_start = pos;
    bool have = false;
    while (true) {
      size_t line_pos = pos;
      if (!next_line_t(data, &pos, &a, &b, &term)) break;
      if (a == b) continue;
      if (data[a] == '>') {
        if (have) {
          emit_record(out, data, ha, hb, seq);
          *consumed = line_pos;
        }
        rec_start = line_pos;
        if (!term && !at_eof) { have = false; break; }  // partial header
        ha = a + 1;
        hb = b;
        seq.clear();
        have = true;
      } else {
        if (!term && !at_eof) break;  // possibly split sequence line
        seq.append(data, a, b - a);
      }
    }
    if (at_eof) {
      if (have) emit_record(out, data, ha, hb, seq);
      *consumed = data.size();
    }
    // non-EOF: the record from rec_start onward stays in the carry (a
    // FASTA record is only known complete at the next header/EOF)
    (void)rec_start;
    return true;
  }

  // FASTQ: strict 4-line records, blank lines tolerated between records
  while (true) {
    size_t rec_start;
    bool got = false;
    while (next_line_t(data, &pos, &a, &b, &term)) {
      if (a == b) continue;
      rec_start = a;
      got = true;
      break;
    }
    if (!got) { *consumed = data.size(); break; }
    if (data[a] != '@') {
      out->error = "malformed FASTQ header";
      return false;
    }
    if (!term && !at_eof) { *consumed = rec_start; break; }
    size_t ha = a + 1, hb = b;
    size_t sa, sb, pa, pb, qa, qb;
    bool t2, t3, t4;
    if (!next_line_t(data, &pos, &sa, &sb, &t2) ||
        !next_line_t(data, &pos, &pa, &pb, &t3) ||
        !next_line_t(data, &pos, &qa, &qb, &t4)) {
      if (at_eof) {
        out->error = "truncated FASTQ record";
        return false;
      }
      *consumed = rec_start;
      break;
    }
    if (!at_eof && !t4) { *consumed = rec_start; break; }  // quals may grow
    if (pa == pb || data[pa] != '+') {
      out->error = "malformed FASTQ record (missing +)";
      return false;
    }
    size_t slen = sb - sa, qlen = qb - qa;
    if (slen != qlen) {
      out->error = "FASTQ qual length != seq length";
      return false;
    }
    for (size_t i = sa; i < sb; ++i) out->codes.push_back(lut[(uint8_t)data[i]]);
    for (size_t i = qa; i < qb; ++i) {
      uint8_t q = (uint8_t)data[i];
      if (q < 33) {
        out->error = "quality below Phred-33 '!'";
        return false;
      }
      out->quals.push_back(q - 33);
    }
    out->lengths.push_back((int32_t)slen);
    out->offsets.push_back((int64_t)out->codes.size());
    out->names.append(data, ha, hb - ha);
    out->names += '\n';
    *consumed = pos;
  }
  return true;
}

// --- tolerant (quarantine-mode) parsing ----------------------------------
//
// Instead of failing the whole buffer on the first malformed record, the
// tolerant parser records the bad region (offset + reason + raw bytes) and
// resynchronizes at the next plausible FASTQ record start. The resync
// candidate rule — a line starting with '@' whose line+2 starts with '+' —
// is what keeps a quality line that happens to begin with '@' from being
// mistaken for a header. The pure-Python twin in io/validate.py implements
// the SAME algorithm; the differential fuzzer pins them together.

// Find the next resync candidate at/after byte `from`. Returns true with
// *cand = candidate line start. On false: *incomplete=true means the scan
// hit possibly-growing data (!at_eof) and the caller must carry; false
// means no candidate exists up to EOF.
bool find_candidate(const std::string& data, size_t from, bool at_eof,
                    size_t* cand, bool* incomplete) {
  size_t pos = from, a, b;
  bool term;
  *incomplete = false;
  while (true) {
    size_t line_start = pos;
    if (!next_line_t(data, &pos, &a, &b, &term)) {
      *incomplete = !at_eof;
      return false;
    }
    if (!term && !at_eof) {  // line may still grow; first char of a
      // nonempty line is fixed, but its role depends on lines after it
      *incomplete = true;
      return false;
    }
    if (b > a && data[a] == '@') {
      size_t p2 = pos, a2, b2, a3, b3;
      bool t2, t3;
      if (!next_line_t(data, &p2, &a2, &b2, &t2)) {
        if (!at_eof) { *incomplete = true; return false; }
        continue;  // no seq line at EOF: not a candidate
      }
      if (!t2 && !at_eof) { *incomplete = true; return false; }
      if (!next_line_t(data, &p2, &a3, &b3, &t3)) {
        if (!at_eof) { *incomplete = true; return false; }
        continue;  // no plus line at EOF: not a candidate
      }
      if (a3 == b3 && !t3 && !at_eof) { *incomplete = true; return false; }
      if (b3 > a3 && data[a3] == '+') {
        *cand = line_start;
        return true;
      }
      // not a candidate; keep scanning from the line after the '@' line
    }
  }
}

// Tolerant incremental parse: complete records and fully-resolved bad
// regions are consumed; `*consumed` stops before anything whose extent is
// still ambiguous (the caller carries it into the next chunk). `base` is
// the absolute decompressed offset of data[0] (bad offsets are absolute).
bool parse_stream_tol(const std::string& data, bool at_eof, char* kind_io,
                      ParsedFile* out, size_t* consumed, int64_t base) {
  const uint8_t* lut = code_lut();
  size_t pos = 0, a, b;
  bool term;
  *consumed = 0;
  out->offsets.push_back(0);

  // kind detection: skip blanks, quarantine any leading junk before the
  // first line starting with '@' or '>'
  while (*kind_io == 0) {
    size_t line_start = pos;
    if (!next_line_t(data, &pos, &a, &b, &term)) {
      *consumed = data.size();  // empty / blanks only
      return true;
    }
    if (a == b) {
      if (!term && !at_eof) { *consumed = line_start; return true; }
      *consumed = pos;
      continue;
    }
    if (data[a] == '@' || data[a] == '>') {
      *kind_io = data[a];
      pos = line_start;  // reparse this line below
      break;
    }
    // junk prefix: scan for the first record-start line
    size_t scan = pos, ja, jb;
    bool jterm;
    size_t junk_end = 0;
    bool found = false;
    while (next_line_t(data, &scan, &ja, &jb, &jterm)) {
      size_t jstart = ja;
      if (ja == jb) continue;
      if (data[ja] == '@' || data[ja] == '>') {
        junk_end = jstart;
        found = true;
        break;
      }
      (void)jterm;
    }
    if (!found) {
      if (!at_eof) { *consumed = line_start; return true; }  // junk may grow
      add_bad(out, base + line_start, kReasonNotFastx, data, line_start,
              data.size());
      *consumed = data.size();
      return true;
    }
    add_bad(out, base + line_start, kReasonNotFastx, data, line_start,
            junk_end);
    *kind_io = data[junk_end];
    pos = junk_end;
    *consumed = junk_end;
    break;
  }
  out->has_qual = *kind_io == '@';

  if (*kind_io == '>') {
    // FASTA: the only malformation class is pre-kind junk (handled above)
    // — every non-'>' line is sequence, and a truncated final record is a
    // final record. Mirrors parse_stream_buffer's '>' branch.
    std::string seq;
    size_t ha = 0, hb = 0;
    bool have = false;
    while (true) {
      size_t line_pos = pos;
      if (!next_line_t(data, &pos, &a, &b, &term)) break;
      if (a == b) continue;
      if (data[a] == '>') {
        if (have) {
          emit_record(out, data, ha, hb, seq);
          *consumed = line_pos;
        }
        if (!term && !at_eof) { have = false; break; }  // partial header
        ha = a + 1;
        hb = b;
        seq.clear();
        have = true;
      } else {
        if (!term && !at_eof) break;  // possibly split sequence line
        seq.append(data, a, b - a);
      }
    }
    if (at_eof) {
      if (have) emit_record(out, data, ha, hb, seq);
      *consumed = data.size();
    }
    return true;
  }

  // FASTQ
  while (true) {
    size_t rec_start = 0;
    bool got = false;
    while (true) {
      size_t line_start = pos;
      if (!next_line_t(data, &pos, &a, &b, &term)) break;
      if (a == b) {
        if (!term && !at_eof) { *consumed = line_start; return true; }
        *consumed = pos;
        continue;
      }
      rec_start = line_start;
      got = true;
      break;
    }
    if (!got) { *consumed = data.size(); return true; }
    if (data[a] != '@') {
      size_t cand;
      bool inc;
      if (find_candidate(data, rec_start, at_eof, &cand, &inc)) {
        add_bad(out, base + rec_start, kReasonBadHeader, data, rec_start, cand);
        pos = cand;
        *consumed = cand;
        continue;
      }
      if (inc) { *consumed = rec_start; return true; }
      add_bad(out, base + rec_start, kReasonBadHeader, data, rec_start,
              data.size());
      *consumed = data.size();
      return true;
    }
    if (!term && !at_eof) { *consumed = rec_start; return true; }
    size_t ha = a + 1, hb = b;
    size_t sa, sb, pa, pb, qa, qb;
    bool t2, t3, t4;
    if (!next_line_t(data, &pos, &sa, &sb, &t2) ||
        !next_line_t(data, &pos, &pa, &pb, &t3) ||
        !next_line_t(data, &pos, &qa, &qb, &t4)) {
      if (at_eof) {
        add_bad(out, base + rec_start, kReasonTruncated, data, rec_start,
                data.size());
        *consumed = data.size();
        return true;
      }
      *consumed = rec_start;
      return true;
    }
    if (pa == pb || data[pa] != '+') {
      size_t cand;
      bool inc;
      if (find_candidate(data, sa, at_eof, &cand, &inc)) {
        add_bad(out, base + rec_start, kReasonMissingPlus, data, rec_start,
                cand);
        pos = cand;
        *consumed = cand;
        continue;
      }
      if (inc) { *consumed = rec_start; return true; }
      add_bad(out, base + rec_start, kReasonMissingPlus, data, rec_start,
              data.size());
      *consumed = data.size();
      return true;
    }
    if (!t4 && !at_eof) { *consumed = rec_start; return true; }  // quals may grow
    size_t rec_end = pos;
    if (sb - sa != qb - qa) {
      add_bad(out, base + rec_start, kReasonLenMismatch, data, rec_start,
              rec_end);
      *consumed = rec_end;
      continue;
    }
    bool badq = false;
    for (size_t i = qa; i < qb; ++i) {
      if ((uint8_t)data[i] < 33) { badq = true; break; }
    }
    if (badq) {
      add_bad(out, base + rec_start, kReasonBadQual, data, rec_start, rec_end);
      *consumed = rec_end;
      continue;
    }
    for (size_t i = sa; i < sb; ++i) out->codes.push_back(lut[(uint8_t)data[i]]);
    for (size_t i = qa; i < qb; ++i) out->quals.push_back((uint8_t)data[i] - 33);
    out->lengths.push_back((int32_t)(sb - sa));
    out->offsets.push_back((int64_t)out->codes.size());
    out->names.append(data, ha, hb - ha);
    out->names += '\n';
    *consumed = rec_end;
  }
}

bool parse_buffer(const std::string& data, ParsedFile* out) {
  const uint8_t* lut = code_lut();
  size_t pos = 0, a = 0, b = 0;
  out->offsets.push_back(0);
  // skip leading blank lines; an empty/blank-only buffer must return
  // BEFORE the data[a] kind probe below (a/b were read uninitialized on
  // empty input before — an out-of-bounds probe the ingest fuzzer caught)
  bool any = false;
  while (next_line(data, &pos, &a, &b)) {
    if (a == b) continue;
    any = true;
    break;
  }
  if (!any) return true;  // empty file / blank lines only
  char kind = data[a];
  if (kind != '@' && kind != '>') {
    out->error = "not FASTA/FASTQ";
    return false;
  }
  out->has_qual = kind == '@';
  // rewind to the first record line
  size_t first = a;
  pos = first;
  if (kind == '>') {
    std::string seq;
    std::string name;
    bool have = false;
    while (next_line(data, &pos, &a, &b)) {
      if (a == b) continue;
      if (data[a] == '>') {
        if (have) {
          for (char c : seq) out->codes.push_back(lut[(uint8_t)c]);
          out->lengths.push_back((int32_t)seq.size());
          out->offsets.push_back((int64_t)out->codes.size());
          out->names += name;
          out->names += '\n';
        }
        name.assign(data, a + 1, b - a - 1);
        seq.clear();
        have = true;
      } else {
        seq.append(data, a, b - a);
      }
    }
    if (have) {
      for (char c : seq) out->codes.push_back(lut[(uint8_t)c]);
      out->lengths.push_back((int32_t)seq.size());
      out->offsets.push_back((int64_t)out->codes.size());
      out->names += name;
      out->names += '\n';
    }
    return true;
  }
  // FASTQ: strict 4-line records, blank lines tolerated between records
  while (true) {
    // header
    bool got = false;
    while (next_line(data, &pos, &a, &b)) {
      if (a == b) continue;
      got = true;
      break;
    }
    if (!got) break;
    if (data[a] != '@') {
      out->error = "malformed FASTQ header";
      return false;
    }
    size_t ha = a + 1, hb = b;
    size_t sa, sb, pa, pb, qa, qb;
    if (!next_line(data, &pos, &sa, &sb) || !next_line(data, &pos, &pa, &pb) ||
        !next_line(data, &pos, &qa, &qb)) {
      out->error = "truncated FASTQ record";
      return false;
    }
    if (pa == pb || data[pa] != '+') {
      out->error = "malformed FASTQ record (missing +)";
      return false;
    }
    size_t slen = sb - sa, qlen = qb - qa;
    if (slen != qlen) {
      out->error = "FASTQ qual length != seq length";
      return false;
    }
    for (size_t i = sa; i < sb; ++i) out->codes.push_back(lut[(uint8_t)data[i]]);
    for (size_t i = qa; i < qb; ++i) {
      uint8_t q = (uint8_t)data[i];
      if (q < 33) {
        out->error = "quality below Phred-33 '!'";
        return false;
      }
      out->quals.push_back(q - 33);
    }
    out->lengths.push_back((int32_t)slen);
    out->offsets.push_back((int64_t)out->codes.size());
    out->names.append(data, ha, hb - ha);
    out->names += '\n';
  }
  return true;
}

}  // namespace

extern "C" {

// Opaque handle API: parse once, copy out, free.
void* fastx_parse(const char* path) {
  auto* out = new ParsedFile();
  std::string data;
  if (!read_all(path, &data, &out->error)) return out;
  if (!parse_buffer(data, out)) {
    out->codes.clear();
    out->quals.clear();
    out->lengths.clear();
    out->offsets.assign(1, 0);
    out->names.clear();
  }
  return out;
}

// Tolerant whole-file parse: malformed records become bad entries (offset +
// reason + raw bytes) instead of failing the file; a truncated/corrupt gzip
// stream parses the decodable prefix and records a gzip bad entry at its
// end. Only "cannot open file" still sets the handle error.
void* fastx_parse2(const char* path, int tolerant) {
  if (!tolerant) return fastx_parse(path);
  auto* out = new ParsedFile();
  std::string data;
  bool gz_error = false;
  if (!read_all_tol(path, &data, &out->error, &gz_error)) return out;
  char kind = 0;
  size_t consumed = 0;
  parse_stream_tol(data, /*at_eof=*/true, &kind, out, &consumed, 0);
  if (gz_error) {
    BadRec r;
    r.offset = (int64_t)data.size();
    r.reason = kReasonGzip;
    out->bad.push_back(std::move(r));
  }
  return out;
}

int64_t fastx_num_bad(void* h) { return (int64_t)((ParsedFile*)h)->bad.size(); }

int64_t fastx_bad_offset(void* h, int64_t i) {
  return ((ParsedFile*)h)->bad[i].offset;
}

const char* fastx_bad_reason(void* h, int64_t i) {
  return ((ParsedFile*)h)->bad[i].reason.c_str();
}

int64_t fastx_bad_raw_size(void* h, int64_t i) {
  return (int64_t)((ParsedFile*)h)->bad[i].raw.size();
}

void fastx_bad_raw_copy(void* h, int64_t i, char* buf) {
  const std::string& raw = ((ParsedFile*)h)->bad[i].raw;
  if (!raw.empty()) memcpy(buf, raw.data(), raw.size());
}

const char* fastx_error(void* h) {
  auto* p = (ParsedFile*)h;
  return p->error.empty() ? nullptr : p->error.c_str();
}

int64_t fastx_num_records(void* h) { return (int64_t)((ParsedFile*)h)->lengths.size(); }
int64_t fastx_total_bases(void* h) { return (int64_t)((ParsedFile*)h)->codes.size(); }
int64_t fastx_names_size(void* h) { return (int64_t)((ParsedFile*)h)->names.size(); }
int fastx_has_qual(void* h) { return ((ParsedFile*)h)->has_qual ? 1 : 0; }

void fastx_copy(void* h, uint8_t* codes, uint8_t* quals, int32_t* lengths,
                int64_t* offsets, char* names) {
  auto* p = (ParsedFile*)h;
  if (!p->codes.empty()) memcpy(codes, p->codes.data(), p->codes.size());
  if (quals && !p->quals.empty()) memcpy(quals, p->quals.data(), p->quals.size());
  if (!p->lengths.empty())
    memcpy(lengths, p->lengths.data(), p->lengths.size() * sizeof(int32_t));
  memcpy(offsets, p->offsets.data(), p->offsets.size() * sizeof(int64_t));
  if (!p->names.empty()) memcpy(names, p->names.data(), p->names.size());
}

void fastx_free(void* h) { delete (ParsedFile*)h; }

// --- streaming API: O(chunk) host memory for lane-scale files ------------
//
// fastx_open -> repeated fastx_next_chunk(target_bases) -> fastx_close.
// Each chunk is a ParsedFile handle consumed with the same accessors as
// fastx_parse; nullptr means clean EOF. A 100+ GB lane (SURVEY §7
// hard-part 5) streams through a fixed-size carry buffer instead of being
// materialized whole.

struct FastxStream {
  gzFile fh = nullptr;
  std::string carry;
  bool eof = false;
  char kind = 0;  // '@' or '>', discovered on first chunk
  std::string error;
  bool tolerant = false;
  bool gz_pending = false;  // tolerant: gzip error seen, event not yet emitted
  int64_t base = 0;         // absolute decompressed offset of carry[0]
};

void* fastx_open(const char* path) {
  auto* s = new FastxStream();
  s->fh = gzopen(path, "rb");
  if (!s->fh) s->error = "cannot open file";
  return s;
}

void* fastx_open2(const char* path, int tolerant) {
  auto* s = (FastxStream*)fastx_open(path);
  s->tolerant = tolerant != 0;
  return s;
}

const char* fastx_stream_error(void* h) {
  auto* s = (FastxStream*)h;
  return s->error.empty() ? nullptr : s->error.c_str();
}

void* fastx_next_chunk(void* h, int64_t target_bases) {
  auto* s = (FastxStream*)h;
  if (!s->error.empty()) return nullptr;
  if (s->eof && s->carry.empty() && !s->gz_pending) return nullptr;
  // FASTQ carries ~2 bytes per base (seq+qual) plus headers; aim the raw
  // buffer at ~2.5x the requested decoded bases. If no complete record
  // fits (one record larger than the buffer), double and retry — progress
  // is guaranteed, so the loop terminates.
  size_t want = (size_t)(target_bases > 0 ? target_bases : (16 << 20)) * 5 / 2;
  char buf[1 << 16];
  ParsedFile* out = nullptr;
  while (true) {
    while (!s->eof && s->carry.size() < want) {
      int n = gzread(s->fh, buf, sizeof(buf));
      if (n > 0) {
        s->carry.append(buf, n);
      } else if (!gz_stream_bad(s->fh, n)) {
        s->eof = true;
      } else if (s->tolerant) {
        // keep the decodable prefix; the gzip event is emitted with the
        // final chunk once the carry fully drains
        s->eof = true;
        s->gz_pending = true;
      } else {
        s->error = kReasonGzip;
        return nullptr;
      }
    }
    out = new ParsedFile();
    size_t consumed = 0;
    bool ok = s->tolerant
                  ? parse_stream_tol(s->carry, s->eof, &s->kind, out,
                                     &consumed, s->base)
                  : parse_stream_buffer(s->carry, s->eof, &s->kind, out,
                                        &consumed);
    if (!ok) {
      s->error = out->error;  // surface via the chunk handle too
      return out;
    }
    s->carry.erase(0, consumed);
    s->base += (int64_t)consumed;
    if (!out->lengths.empty() || !out->bad.empty() || s->eof) break;
    delete out;
    want *= 2;
  }
  if (!s->tolerant && out->lengths.empty() && s->eof && !s->carry.empty()) {
    // EOF but unconsumed bytes and no records: malformed tail
    out->error = "trailing unparseable data";
    s->error = out->error;
    return out;
  }
  if (s->gz_pending && s->eof && s->carry.empty()) {
    BadRec r;
    r.offset = s->base;
    r.reason = kReasonGzip;
    out->bad.push_back(std::move(r));
    s->gz_pending = false;
  }
  if (out->lengths.empty() && out->bad.empty() && s->eof) {
    delete out;
    return nullptr;
  }
  return out;
}

void fastx_close(void* h) {
  auto* s = (FastxStream*)h;
  if (s->fh) gzclose(s->fh);
  delete s;
}

}  // extern "C"
