// Fast FASTQ/FASTA parser: the native host-IO component of the data plane.
//
// The reference pipeline leans on pysam/htslib (C) and external tools for
// sequence IO (SURVEY §2.2); this framework's equivalent is a first-party
// C++ parser that decodes records straight into the dense uint8 code / Phred
// arrays the device batcher consumes, skipping Python string round-trips.
// Loaded via ctypes (io/native/__init__.py); the pure-Python parser in
// io/fastx.py remains the semantic reference and fallback.
//
// Build: g++ -O3 -shared -fPIC fastx_parser.cpp -lz -o libfastx.so

#include <zlib.h>

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

namespace {

struct ParsedFile {
  // flat record storage
  std::vector<uint8_t> codes;      // dense codes, concatenated
  std::vector<uint8_t> quals;      // phred (0-based), concatenated; empty for FASTA
  std::vector<int64_t> offsets;    // per-record start into codes/quals (n+1 entries)
  std::vector<int32_t> lengths;    // per-record length
  std::string names;               // '\n'-joined full headers
  bool has_qual = false;
  std::string error;
};

// base -> dense code (A=0 C=1 G=2 T=3 N/other=4), matching ops/encode.py
const uint8_t* code_lut() {
  static uint8_t lut[256];
  static bool init = false;
  if (!init) {
    memset(lut, 4, sizeof(lut));
    lut['A'] = lut['a'] = 0;
    lut['C'] = lut['c'] = 1;
    lut['G'] = lut['g'] = 2;
    lut['T'] = lut['t'] = lut['U'] = lut['u'] = 3;
    init = true;
  }
  return lut;
}

bool read_all(const char* path, std::string* out, std::string* err) {
  gzFile fh = gzopen(path, "rb");  // transparently handles plain files too
  if (!fh) {
    *err = "cannot open file";
    return false;
  }
  char buf[1 << 16];
  int n;
  while ((n = gzread(fh, buf, sizeof(buf))) > 0) out->append(buf, n);
  bool ok = n == 0;
  if (!ok) *err = "read/decompress error";
  gzclose(fh);
  return ok;
}

// next line [start, end) exclusive of newline; returns false at EOF
bool next_line(const std::string& s, size_t* pos, size_t* start, size_t* end) {
  if (*pos >= s.size()) return false;
  *start = *pos;
  size_t nl = s.find('\n', *pos);
  if (nl == std::string::npos) {
    *end = s.size();
    *pos = s.size();
  } else {
    *end = nl;
    *pos = nl + 1;
  }
  if (*end > *start && s[*end - 1] == '\r') --*end;
  return true;
}

bool parse_buffer(const std::string& data, ParsedFile* out) {
  const uint8_t* lut = code_lut();
  size_t pos = 0, a, b;
  out->offsets.push_back(0);
  // skip leading blank lines
  while (next_line(data, &pos, &a, &b)) {
    if (a == b) continue;
    break;
  }
  if (pos == 0 && a == b) return true;  // empty file
  char kind = data[a];
  if (kind != '@' && kind != '>') {
    out->error = "not FASTA/FASTQ";
    return false;
  }
  out->has_qual = kind == '@';
  // rewind to the first record line
  size_t first = a;
  pos = first;
  if (kind == '>') {
    std::string seq;
    std::string name;
    bool have = false;
    while (next_line(data, &pos, &a, &b)) {
      if (a == b) continue;
      if (data[a] == '>') {
        if (have) {
          for (char c : seq) out->codes.push_back(lut[(uint8_t)c]);
          out->lengths.push_back((int32_t)seq.size());
          out->offsets.push_back((int64_t)out->codes.size());
          out->names += name;
          out->names += '\n';
        }
        name.assign(data, a + 1, b - a - 1);
        seq.clear();
        have = true;
      } else {
        seq.append(data, a, b - a);
      }
    }
    if (have) {
      for (char c : seq) out->codes.push_back(lut[(uint8_t)c]);
      out->lengths.push_back((int32_t)seq.size());
      out->offsets.push_back((int64_t)out->codes.size());
      out->names += name;
      out->names += '\n';
    }
    return true;
  }
  // FASTQ: strict 4-line records, blank lines tolerated between records
  while (true) {
    // header
    bool got = false;
    while (next_line(data, &pos, &a, &b)) {
      if (a == b) continue;
      got = true;
      break;
    }
    if (!got) break;
    if (data[a] != '@') {
      out->error = "malformed FASTQ header";
      return false;
    }
    size_t ha = a + 1, hb = b;
    size_t sa, sb, pa, pb, qa, qb;
    if (!next_line(data, &pos, &sa, &sb) || !next_line(data, &pos, &pa, &pb) ||
        !next_line(data, &pos, &qa, &qb)) {
      out->error = "truncated FASTQ record";
      return false;
    }
    if (pa == pb || data[pa] != '+') {
      out->error = "malformed FASTQ record (missing +)";
      return false;
    }
    size_t slen = sb - sa, qlen = qb - qa;
    if (slen != qlen) {
      out->error = "FASTQ qual length != seq length";
      return false;
    }
    for (size_t i = sa; i < sb; ++i) out->codes.push_back(lut[(uint8_t)data[i]]);
    for (size_t i = qa; i < qb; ++i) {
      uint8_t q = (uint8_t)data[i];
      if (q < 33) {
        out->error = "quality below Phred-33 '!'";
        return false;
      }
      out->quals.push_back(q - 33);
    }
    out->lengths.push_back((int32_t)slen);
    out->offsets.push_back((int64_t)out->codes.size());
    out->names.append(data, ha, hb - ha);
    out->names += '\n';
  }
  return true;
}

}  // namespace

extern "C" {

// Opaque handle API: parse once, copy out, free.
void* fastx_parse(const char* path) {
  auto* out = new ParsedFile();
  std::string data;
  if (!read_all(path, &data, &out->error)) return out;
  if (!parse_buffer(data, out)) {
    out->codes.clear();
    out->quals.clear();
    out->lengths.clear();
    out->offsets.assign(1, 0);
    out->names.clear();
  }
  return out;
}

const char* fastx_error(void* h) {
  auto* p = (ParsedFile*)h;
  return p->error.empty() ? nullptr : p->error.c_str();
}

int64_t fastx_num_records(void* h) { return (int64_t)((ParsedFile*)h)->lengths.size(); }
int64_t fastx_total_bases(void* h) { return (int64_t)((ParsedFile*)h)->codes.size(); }
int64_t fastx_names_size(void* h) { return (int64_t)((ParsedFile*)h)->names.size(); }
int fastx_has_qual(void* h) { return ((ParsedFile*)h)->has_qual ? 1 : 0; }

void fastx_copy(void* h, uint8_t* codes, uint8_t* quals, int32_t* lengths,
                int64_t* offsets, char* names) {
  auto* p = (ParsedFile*)h;
  if (!p->codes.empty()) memcpy(codes, p->codes.data(), p->codes.size());
  if (quals && !p->quals.empty()) memcpy(quals, p->quals.data(), p->quals.size());
  if (!p->lengths.empty())
    memcpy(lengths, p->lengths.data(), p->lengths.size() * sizeof(int32_t));
  memcpy(offsets, p->offsets.data(), p->offsets.size() * sizeof(int64_t));
  if (!p->names.empty()) memcpy(names, p->names.data(), p->names.size());
}

void fastx_free(void* h) { delete (ParsedFile*)h; }

}  // extern "C"
