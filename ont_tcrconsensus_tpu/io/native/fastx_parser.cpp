// Fast FASTQ/FASTA parser: the native host-IO component of the data plane.
//
// The reference pipeline leans on pysam/htslib (C) and external tools for
// sequence IO (SURVEY §2.2); this framework's equivalent is a first-party
// C++ parser that decodes records straight into the dense uint8 code / Phred
// arrays the device batcher consumes, skipping Python string round-trips.
// Loaded via ctypes (io/native/__init__.py); the pure-Python parser in
// io/fastx.py remains the semantic reference and fallback.
//
// Build: g++ -O3 -shared -fPIC fastx_parser.cpp -lz -o libfastx.so

#include <zlib.h>

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

namespace {

struct ParsedFile {
  // flat record storage
  std::vector<uint8_t> codes;      // dense codes, concatenated
  std::vector<uint8_t> quals;      // phred (0-based), concatenated; empty for FASTA
  std::vector<int64_t> offsets;    // per-record start into codes/quals (n+1 entries)
  std::vector<int32_t> lengths;    // per-record length
  std::string names;               // '\n'-joined full headers
  bool has_qual = false;
  std::string error;
};

// base -> dense code (A=0 C=1 G=2 T=3 N/other=4), matching ops/encode.py
const uint8_t* code_lut() {
  static uint8_t lut[256];
  static bool init = false;
  if (!init) {
    memset(lut, 4, sizeof(lut));
    lut['A'] = lut['a'] = 0;
    lut['C'] = lut['c'] = 1;
    lut['G'] = lut['g'] = 2;
    lut['T'] = lut['t'] = lut['U'] = lut['u'] = 3;
    init = true;
  }
  return lut;
}

bool read_all(const char* path, std::string* out, std::string* err) {
  gzFile fh = gzopen(path, "rb");  // transparently handles plain files too
  if (!fh) {
    *err = "cannot open file";
    return false;
  }
  char buf[1 << 16];
  int n;
  while ((n = gzread(fh, buf, sizeof(buf))) > 0) out->append(buf, n);
  bool ok = n == 0;
  if (!ok) *err = "read/decompress error";
  gzclose(fh);
  return ok;
}

// next line [start, end) exclusive of newline; returns false at EOF
bool next_line(const std::string& s, size_t* pos, size_t* start, size_t* end) {
  if (*pos >= s.size()) return false;
  *start = *pos;
  size_t nl = s.find('\n', *pos);
  if (nl == std::string::npos) {
    *end = s.size();
    *pos = s.size();
  } else {
    *end = nl;
    *pos = nl + 1;
  }
  if (*end > *start && s[*end - 1] == '\r') --*end;
  return true;
}

// next line, also reporting whether the line is TERMINATED (a '\n' was
// seen) — a streaming chunk may end mid-line, and an unterminated line is
// only trustworthy at EOF
bool next_line_t(const std::string& s, size_t* pos, size_t* start, size_t* end,
                 bool* terminated) {
  if (*pos >= s.size()) return false;
  *start = *pos;
  size_t nl = s.find('\n', *pos);
  if (nl == std::string::npos) {
    *end = s.size();
    *pos = s.size();
    *terminated = false;
  } else {
    *end = nl;
    *pos = nl + 1;
    *terminated = true;
  }
  if (*end > *start && s[*end - 1] == '\r') --*end;
  return true;
}

void emit_record(ParsedFile* out, const std::string& data, size_t ha, size_t hb,
                 const std::string& seq) {
  const uint8_t* lut = code_lut();
  for (char c : seq) out->codes.push_back(lut[(uint8_t)c]);
  out->lengths.push_back((int32_t)seq.size());
  out->offsets.push_back((int64_t)out->codes.size());
  out->names.append(data, ha, hb - ha);
  out->names += '\n';
}

// Incremental parse: consume COMPLETE records from data into out, set
// *consumed to the byte offset after the last fully-parsed record (the
// caller carries the tail into the next chunk). When at_eof, a trailing
// partial record is an error (FASTQ) or final record (FASTA) exactly like
// the whole-file parser.
bool parse_stream_buffer(const std::string& data, bool at_eof, char* kind_io,
                         ParsedFile* out, size_t* consumed) {
  const uint8_t* lut = code_lut();
  size_t pos = 0, a, b;
  bool term;
  *consumed = 0;
  out->offsets.push_back(0);
  // skip leading blank lines
  size_t scan = 0;
  bool any = false;
  while (next_line_t(data, &scan, &a, &b, &term)) {
    if (a == b) { *consumed = scan; continue; }
    any = true;
    break;
  }
  if (!any) { *consumed = data.size(); return true; }  // blanks only
  if (*kind_io == 0) {
    char kind = data[a];
    if (kind != '@' && kind != '>') {
      out->error = "not FASTA/FASTQ";
      return false;
    }
    *kind_io = kind;
  }
  out->has_qual = *kind_io == '@';
  pos = a;  // first record header start

  if (*kind_io == '>') {
    std::string seq;
    size_t ha = 0, hb = 0;
    size_t rec_start = pos;
    bool have = false;
    while (true) {
      size_t line_pos = pos;
      if (!next_line_t(data, &pos, &a, &b, &term)) break;
      if (a == b) continue;
      if (data[a] == '>') {
        if (have) {
          emit_record(out, data, ha, hb, seq);
          *consumed = line_pos;
        }
        rec_start = line_pos;
        if (!term && !at_eof) { have = false; break; }  // partial header
        ha = a + 1;
        hb = b;
        seq.clear();
        have = true;
      } else {
        if (!term && !at_eof) break;  // possibly split sequence line
        seq.append(data, a, b - a);
      }
    }
    if (at_eof) {
      if (have) emit_record(out, data, ha, hb, seq);
      *consumed = data.size();
    }
    // non-EOF: the record from rec_start onward stays in the carry (a
    // FASTA record is only known complete at the next header/EOF)
    (void)rec_start;
    return true;
  }

  // FASTQ: strict 4-line records, blank lines tolerated between records
  while (true) {
    size_t rec_start;
    bool got = false;
    while (next_line_t(data, &pos, &a, &b, &term)) {
      if (a == b) continue;
      rec_start = a;
      got = true;
      break;
    }
    if (!got) { *consumed = data.size(); break; }
    if (data[a] != '@') {
      out->error = "malformed FASTQ header";
      return false;
    }
    if (!term && !at_eof) { *consumed = rec_start; break; }
    size_t ha = a + 1, hb = b;
    size_t sa, sb, pa, pb, qa, qb;
    bool t2, t3, t4;
    if (!next_line_t(data, &pos, &sa, &sb, &t2) ||
        !next_line_t(data, &pos, &pa, &pb, &t3) ||
        !next_line_t(data, &pos, &qa, &qb, &t4)) {
      if (at_eof) {
        out->error = "truncated FASTQ record";
        return false;
      }
      *consumed = rec_start;
      break;
    }
    if (!at_eof && !t4) { *consumed = rec_start; break; }  // quals may grow
    if (pa == pb || data[pa] != '+') {
      out->error = "malformed FASTQ record (missing +)";
      return false;
    }
    size_t slen = sb - sa, qlen = qb - qa;
    if (slen != qlen) {
      out->error = "FASTQ qual length != seq length";
      return false;
    }
    for (size_t i = sa; i < sb; ++i) out->codes.push_back(lut[(uint8_t)data[i]]);
    for (size_t i = qa; i < qb; ++i) {
      uint8_t q = (uint8_t)data[i];
      if (q < 33) {
        out->error = "quality below Phred-33 '!'";
        return false;
      }
      out->quals.push_back(q - 33);
    }
    out->lengths.push_back((int32_t)slen);
    out->offsets.push_back((int64_t)out->codes.size());
    out->names.append(data, ha, hb - ha);
    out->names += '\n';
    *consumed = pos;
  }
  return true;
}

bool parse_buffer(const std::string& data, ParsedFile* out) {
  const uint8_t* lut = code_lut();
  size_t pos = 0, a, b;
  out->offsets.push_back(0);
  // skip leading blank lines
  while (next_line(data, &pos, &a, &b)) {
    if (a == b) continue;
    break;
  }
  if (pos == 0 && a == b) return true;  // empty file
  char kind = data[a];
  if (kind != '@' && kind != '>') {
    out->error = "not FASTA/FASTQ";
    return false;
  }
  out->has_qual = kind == '@';
  // rewind to the first record line
  size_t first = a;
  pos = first;
  if (kind == '>') {
    std::string seq;
    std::string name;
    bool have = false;
    while (next_line(data, &pos, &a, &b)) {
      if (a == b) continue;
      if (data[a] == '>') {
        if (have) {
          for (char c : seq) out->codes.push_back(lut[(uint8_t)c]);
          out->lengths.push_back((int32_t)seq.size());
          out->offsets.push_back((int64_t)out->codes.size());
          out->names += name;
          out->names += '\n';
        }
        name.assign(data, a + 1, b - a - 1);
        seq.clear();
        have = true;
      } else {
        seq.append(data, a, b - a);
      }
    }
    if (have) {
      for (char c : seq) out->codes.push_back(lut[(uint8_t)c]);
      out->lengths.push_back((int32_t)seq.size());
      out->offsets.push_back((int64_t)out->codes.size());
      out->names += name;
      out->names += '\n';
    }
    return true;
  }
  // FASTQ: strict 4-line records, blank lines tolerated between records
  while (true) {
    // header
    bool got = false;
    while (next_line(data, &pos, &a, &b)) {
      if (a == b) continue;
      got = true;
      break;
    }
    if (!got) break;
    if (data[a] != '@') {
      out->error = "malformed FASTQ header";
      return false;
    }
    size_t ha = a + 1, hb = b;
    size_t sa, sb, pa, pb, qa, qb;
    if (!next_line(data, &pos, &sa, &sb) || !next_line(data, &pos, &pa, &pb) ||
        !next_line(data, &pos, &qa, &qb)) {
      out->error = "truncated FASTQ record";
      return false;
    }
    if (pa == pb || data[pa] != '+') {
      out->error = "malformed FASTQ record (missing +)";
      return false;
    }
    size_t slen = sb - sa, qlen = qb - qa;
    if (slen != qlen) {
      out->error = "FASTQ qual length != seq length";
      return false;
    }
    for (size_t i = sa; i < sb; ++i) out->codes.push_back(lut[(uint8_t)data[i]]);
    for (size_t i = qa; i < qb; ++i) {
      uint8_t q = (uint8_t)data[i];
      if (q < 33) {
        out->error = "quality below Phred-33 '!'";
        return false;
      }
      out->quals.push_back(q - 33);
    }
    out->lengths.push_back((int32_t)slen);
    out->offsets.push_back((int64_t)out->codes.size());
    out->names.append(data, ha, hb - ha);
    out->names += '\n';
  }
  return true;
}

}  // namespace

extern "C" {

// Opaque handle API: parse once, copy out, free.
void* fastx_parse(const char* path) {
  auto* out = new ParsedFile();
  std::string data;
  if (!read_all(path, &data, &out->error)) return out;
  if (!parse_buffer(data, out)) {
    out->codes.clear();
    out->quals.clear();
    out->lengths.clear();
    out->offsets.assign(1, 0);
    out->names.clear();
  }
  return out;
}

const char* fastx_error(void* h) {
  auto* p = (ParsedFile*)h;
  return p->error.empty() ? nullptr : p->error.c_str();
}

int64_t fastx_num_records(void* h) { return (int64_t)((ParsedFile*)h)->lengths.size(); }
int64_t fastx_total_bases(void* h) { return (int64_t)((ParsedFile*)h)->codes.size(); }
int64_t fastx_names_size(void* h) { return (int64_t)((ParsedFile*)h)->names.size(); }
int fastx_has_qual(void* h) { return ((ParsedFile*)h)->has_qual ? 1 : 0; }

void fastx_copy(void* h, uint8_t* codes, uint8_t* quals, int32_t* lengths,
                int64_t* offsets, char* names) {
  auto* p = (ParsedFile*)h;
  if (!p->codes.empty()) memcpy(codes, p->codes.data(), p->codes.size());
  if (quals && !p->quals.empty()) memcpy(quals, p->quals.data(), p->quals.size());
  if (!p->lengths.empty())
    memcpy(lengths, p->lengths.data(), p->lengths.size() * sizeof(int32_t));
  memcpy(offsets, p->offsets.data(), p->offsets.size() * sizeof(int64_t));
  if (!p->names.empty()) memcpy(names, p->names.data(), p->names.size());
}

void fastx_free(void* h) { delete (ParsedFile*)h; }

// --- streaming API: O(chunk) host memory for lane-scale files ------------
//
// fastx_open -> repeated fastx_next_chunk(target_bases) -> fastx_close.
// Each chunk is a ParsedFile handle consumed with the same accessors as
// fastx_parse; nullptr means clean EOF. A 100+ GB lane (SURVEY §7
// hard-part 5) streams through a fixed-size carry buffer instead of being
// materialized whole.

struct FastxStream {
  gzFile fh = nullptr;
  std::string carry;
  bool eof = false;
  char kind = 0;  // '@' or '>', discovered on first chunk
  std::string error;
};

void* fastx_open(const char* path) {
  auto* s = new FastxStream();
  s->fh = gzopen(path, "rb");
  if (!s->fh) s->error = "cannot open file";
  return s;
}

const char* fastx_stream_error(void* h) {
  auto* s = (FastxStream*)h;
  return s->error.empty() ? nullptr : s->error.c_str();
}

void* fastx_next_chunk(void* h, int64_t target_bases) {
  auto* s = (FastxStream*)h;
  if (!s->error.empty()) return nullptr;
  if (s->eof && s->carry.empty()) return nullptr;
  // FASTQ carries ~2 bytes per base (seq+qual) plus headers; aim the raw
  // buffer at ~2.5x the requested decoded bases. If no complete record
  // fits (one record larger than the buffer), double and retry — progress
  // is guaranteed, so the loop terminates.
  size_t want = (size_t)(target_bases > 0 ? target_bases : (16 << 20)) * 5 / 2;
  char buf[1 << 16];
  ParsedFile* out = nullptr;
  while (true) {
    while (!s->eof && s->carry.size() < want) {
      int n = gzread(s->fh, buf, sizeof(buf));
      if (n > 0) {
        s->carry.append(buf, n);
      } else if (n == 0) {
        s->eof = true;
      } else {
        s->error = "read/decompress error";
        return nullptr;
      }
    }
    out = new ParsedFile();
    size_t consumed = 0;
    if (!parse_stream_buffer(s->carry, s->eof, &s->kind, out, &consumed)) {
      s->error = out->error;  // surface via the chunk handle too
      return out;
    }
    s->carry.erase(0, consumed);
    if (!out->lengths.empty() || s->eof) break;
    delete out;
    want *= 2;
  }
  if (out->lengths.empty() && s->eof && !s->carry.empty()) {
    // EOF but unconsumed bytes and no records: malformed tail
    out->error = "trailing unparseable data";
    s->error = out->error;
    return out;
  }
  if (out->lengths.empty() && s->eof) {
    delete out;
    return nullptr;
  }
  return out;
}

void fastx_close(void* h) {
  auto* s = (FastxStream*)h;
  if (s->fh) gzclose(s->fh);
  delete s;
}

}  // extern "C"
