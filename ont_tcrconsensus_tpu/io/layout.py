"""Per-library analysis directory layout.

Mirrors the reference's 11-dir tree (/root/reference/ont_tcr_consensus/
utils.py:5-43) so downstream tooling (the analysis notebook, users' scripts)
finds artifacts in the same places, but adds a stage-resume manifest: the
reference refuses to run if the output dir exists (tcr_consensus.py:84-86);
here an existing dir is resumable when ``resume=True``.

Manifest v2 (verified resume): ``mark_stage_done`` records sha256 + byte
size for every artifact the stage produced, and :meth:`verify_stage`
checks them before resume skips the stage (config ``verify_resume``:
``off`` = blind trust/legacy, ``fast`` = size check, ``full`` = sha256 —
the Check-N-Run discipline from PAPERS.md). A v1 manifest (flat
``{stage: time}``) still reads fine but its stages carry no checksums:
under ``fast``/``full`` they are UNVERIFIABLE — warn and re-run. Torn or
corrupt manifests keep reading as "no stages done" (never a crash).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import sys
import time

from ont_tcrconsensus_tpu.robustness import faults

MANIFEST_VERSION = 2

VERIFY_MODES = ("off", "fast", "full")


def sha256_file(path: str | os.PathLike[str]) -> tuple[str, int]:
    """(hex sha256, byte size) of a file, streamed in 1 MiB chunks."""
    h = hashlib.sha256()
    n = 0
    with open(path, "rb") as fh:
        while True:
            block = fh.read(1 << 20)
            if not block:
                break
            h.update(block)
            n += len(block)
    return h.hexdigest(), n

SUBDIRS = (
    "logs",
    "align",
    "region_cluster_fasta",
    "umi_fasta",
    "clustering",
    "fasta",
    "clustering_consensus",
    "region_fasta",
    "consensus_umi_fasta",
    "counts",
)


@dataclasses.dataclass(frozen=True)
class LibraryLayout:
    library: str
    library_dir: str

    @property
    def logs(self) -> str:
        return os.path.join(self.library_dir, "logs")

    @property
    def align(self) -> str:
        return os.path.join(self.library_dir, "align")

    @property
    def region_cluster_fasta(self) -> str:
        return os.path.join(self.library_dir, "region_cluster_fasta")

    @property
    def umi_fasta(self) -> str:
        return os.path.join(self.library_dir, "umi_fasta")

    @property
    def clustering(self) -> str:
        return os.path.join(self.library_dir, "clustering")

    @property
    def fasta(self) -> str:
        return os.path.join(self.library_dir, "fasta")

    @property
    def clustering_consensus(self) -> str:
        return os.path.join(self.library_dir, "clustering_consensus")

    @property
    def region_fasta(self) -> str:
        return os.path.join(self.library_dir, "region_fasta")

    @property
    def consensus_umi_fasta(self) -> str:
        return os.path.join(self.library_dir, "consensus_umi_fasta")

    @property
    def counts(self) -> str:
        return os.path.join(self.library_dir, "counts")

    @property
    def quarantine_path(self) -> str:
        """Per-library quarantine artifact (on_bad_record=quarantine): the
        raw bytes of every malformed input region, gzip-compressed, with
        machine-readable reasons in robustness_report.json."""
        return os.path.join(self.library_dir, "quarantine.fastq.gz")

    @property
    def manifest_path(self) -> str:
        return os.path.join(self.library_dir, "stage_manifest.json")

    # --- stage-level resume -------------------------------------------------

    def read_manifest(self) -> dict[str, dict]:
        """Normalized manifest: ``{stage: {"t": float, "artifacts": dict|None}}``.

        Handles both versions: v2 (``{"version": 2, "stages": {...}}``,
        per-artifact ``{"sha256", "bytes"}`` maps) and v1 (flat
        ``{stage: time}`` — normalized with ``artifacts=None``, the
        "unverifiable" marker :meth:`verify_stage` warns about).

        Corruption-tolerant: a torn/invalid manifest (the process was
        killed mid-write by a preemption, or the disk lied) means "no
        stages done" with a warning — resume then redoes the library's
        work, which is always safe, instead of crashing the whole run on
        a ``JSONDecodeError`` and bricking ``resume=true``.
        """
        try:
            with open(self.manifest_path) as fh:
                raw = fh.read()
        except FileNotFoundError:
            return {}
        except OSError as exc:
            print(f"WARNING: cannot read stage manifest {self.manifest_path} "
                  f"({exc!r}); treating as no stages done", file=sys.stderr)
            return {}
        try:
            done = json.loads(raw)
        except ValueError:
            print(f"WARNING: stage manifest {self.manifest_path} is "
                  "torn/corrupt; treating as no stages done (resume will "
                  "redo this library)", file=sys.stderr)
            return {}
        if not isinstance(done, dict):
            print(f"WARNING: stage manifest {self.manifest_path} has "
                  f"unexpected shape {type(done).__name__}; treating as no "
                  "stages done", file=sys.stderr)
            return {}
        if "version" in done:  # v2
            stages = done.get("stages")
            if not isinstance(stages, dict):
                print(f"WARNING: stage manifest {self.manifest_path} v2 has "
                      "no valid 'stages' map; treating as no stages done",
                      file=sys.stderr)
                return {}
            out: dict[str, dict] = {}
            for stage, info in stages.items():
                if (not isinstance(info, dict)
                        or not isinstance(info.get("t"), (int, float))):
                    print(f"WARNING: stage manifest {self.manifest_path} "
                          f"entry {stage!r} is malformed; dropping it "
                          "(resume will redo that stage)", file=sys.stderr)
                    continue
                out[stage] = {"t": float(info["t"]),
                              "artifacts": info.get("artifacts")}
            return out
        # v1: flat {stage: time}; artifacts unknown -> unverifiable (None).
        # Same per-entry tolerance as v2: a valid-JSON-but-garbage value
        # ({"counts": "x"}) drops that entry, never crashes resume.
        out = {}
        for stage, t in done.items():
            if not isinstance(t, (int, float)):
                print(f"WARNING: stage manifest {self.manifest_path} v1 "
                      f"entry {stage!r} is malformed; dropping it "
                      "(resume will redo that stage)", file=sys.stderr)
                continue
            out[stage] = {"t": float(t), "artifacts": None}
        return out

    def completed_stages(self) -> dict[str, float]:
        """Stage -> completion time (both manifest versions)."""
        return {stage: info["t"] for stage, info in self.read_manifest().items()}

    def mark_stage_done(self, stage: str, artifacts=()) -> None:
        """Record ``stage`` complete, checksumming its ``artifacts``.

        ``artifacts`` are the stage's output files (paths under the
        library dir); each is recorded with sha256 + byte size so a later
        resume can verify before skipping. Marking on top of a v1
        manifest upgrades the file to v2; the pre-existing stages keep
        ``artifacts: null`` ("completed by an older version — no
        checksums") and stay readable.
        """
        done = self.read_manifest()
        art: dict[str, dict] = {}
        for p in artifacts:
            p = os.fspath(p)
            sha, nbytes = sha256_file(p)
            art[os.path.relpath(p, self.library_dir)] = {
                "sha256": sha, "bytes": nbytes,
            }
        done[stage] = {"t": time.time(), "artifacts": art}
        payload = json.dumps(
            {"version": MANIFEST_VERSION, "stages": done}, indent=1
        )
        if faults.tear_write("layout.manifest_write", self.manifest_path, payload):
            return  # chaos: the "crash mid-write" already happened
        tmp = self.manifest_path + ".tmp"
        with open(tmp, "w") as fh:
            fh.write(payload)
            fh.flush()
            # fsync BEFORE the rename: os.replace is atomic in the
            # namespace but not in the page cache — without the sync a
            # power cut can leave the new name pointing at zero-length
            # data, exactly the torn state read_manifest() tolerates
            os.fsync(fh.fileno())
        os.replace(tmp, self.manifest_path)

    def stage_done(self, stage: str) -> bool:
        return stage in self.read_manifest()

    def verify_stage(self, stage: str, mode: str = "fast") -> tuple[bool, str | None]:
        """Is ``stage``'s completion trustworthy enough to skip on resume?

        Returns ``(ok, reason)``. ``off`` trusts the manifest mark alone
        (legacy blind-trust behavior); ``fast`` checks each recorded
        artifact's byte size (catches truncation/missing files for free);
        ``full`` additionally re-hashes every artifact (catches any bit
        rot). A v1 entry carries no checksums: unverifiable under
        ``fast``/``full`` — the caller warns and re-runs the stage.
        """
        if mode not in VERIFY_MODES:
            raise ValueError(f"verify_resume mode {mode!r} not in {VERIFY_MODES}")
        info = self.read_manifest().get(stage)
        if info is None:
            return False, f"stage {stage!r} not marked done"
        if mode == "off":
            return True, None
        arts = info.get("artifacts")
        if arts is None:
            return False, (f"stage {stage!r} was completed by a v1 manifest "
                           "(no checksums recorded) — unverifiable")
        if not isinstance(arts, dict):
            # bit rot INSIDE valid JSON: same never-crash discipline as
            # read_manifest — unverifiable, the caller warns and re-runs
            return False, (f"stage {stage!r} artifacts record is malformed "
                           "— unverifiable")
        for rel, meta in arts.items():
            if not isinstance(meta, dict):
                return False, (f"artifact {rel} checksum record is malformed "
                               "— unverifiable")
            path = os.path.join(self.library_dir, rel)
            try:
                size = os.path.getsize(path)
            except OSError:
                return False, f"artifact {rel} is missing"
            if size != meta.get("bytes"):
                return False, (f"artifact {rel} size {size} != recorded "
                               f"{meta.get('bytes')}")
            if mode == "full":
                sha, _ = sha256_file(path)
                if sha != meta.get("sha256"):
                    return False, (f"artifact {rel} sha256 {sha[:12]}... != "
                                   f"recorded {str(meta.get('sha256'))[:12]}...")
        return True, None


def library_name_from_fastq(fastq: str | os.PathLike[str]) -> str:
    """'/path/barcode01.fastq.gz' -> 'barcode01' (utils.py:6)."""
    return os.path.basename(os.fspath(fastq)).split(".")[0]


def init_library_dir(
    fastq: str | os.PathLike[str],
    nano_dir: str | os.PathLike[str],
    resume: bool = False,
) -> LibraryLayout:
    """Create (or, with resume, reuse) the per-library tree."""
    library = library_name_from_fastq(fastq)
    library_dir = os.path.join(os.fspath(nano_dir), library)
    if os.path.exists(library_dir) and not resume:
        raise FileExistsError(
            f"{library_dir} exists; pass resume=True to continue a previous run"
        )
    os.makedirs(library_dir, exist_ok=True)
    for sub in SUBDIRS:
        os.makedirs(os.path.join(library_dir, sub), exist_ok=True)
    return LibraryLayout(library=library, library_dir=library_dir)
