"""Per-library analysis directory layout.

Mirrors the reference's 11-dir tree (/root/reference/ont_tcr_consensus/
utils.py:5-43) so downstream tooling (the analysis notebook, users' scripts)
finds artifacts in the same places, but adds a stage-resume manifest: the
reference refuses to run if the output dir exists (tcr_consensus.py:84-86);
here an existing dir is resumable when ``resume=True``.
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import time

from ont_tcrconsensus_tpu.robustness import faults

SUBDIRS = (
    "logs",
    "align",
    "region_cluster_fasta",
    "umi_fasta",
    "clustering",
    "fasta",
    "clustering_consensus",
    "region_fasta",
    "consensus_umi_fasta",
    "counts",
)


@dataclasses.dataclass(frozen=True)
class LibraryLayout:
    library: str
    library_dir: str

    @property
    def logs(self) -> str:
        return os.path.join(self.library_dir, "logs")

    @property
    def align(self) -> str:
        return os.path.join(self.library_dir, "align")

    @property
    def region_cluster_fasta(self) -> str:
        return os.path.join(self.library_dir, "region_cluster_fasta")

    @property
    def umi_fasta(self) -> str:
        return os.path.join(self.library_dir, "umi_fasta")

    @property
    def clustering(self) -> str:
        return os.path.join(self.library_dir, "clustering")

    @property
    def fasta(self) -> str:
        return os.path.join(self.library_dir, "fasta")

    @property
    def clustering_consensus(self) -> str:
        return os.path.join(self.library_dir, "clustering_consensus")

    @property
    def region_fasta(self) -> str:
        return os.path.join(self.library_dir, "region_fasta")

    @property
    def consensus_umi_fasta(self) -> str:
        return os.path.join(self.library_dir, "consensus_umi_fasta")

    @property
    def counts(self) -> str:
        return os.path.join(self.library_dir, "counts")

    @property
    def quarantine_path(self) -> str:
        """Per-library quarantine artifact (on_bad_record=quarantine): the
        raw bytes of every malformed input region, gzip-compressed, with
        machine-readable reasons in robustness_report.json."""
        return os.path.join(self.library_dir, "quarantine.fastq.gz")

    @property
    def manifest_path(self) -> str:
        return os.path.join(self.library_dir, "stage_manifest.json")

    # --- stage-level resume -------------------------------------------------

    def completed_stages(self) -> dict[str, float]:
        """Stage -> completion time from the manifest.

        Corruption-tolerant: a torn/invalid manifest (the process was
        killed mid-write by a preemption, or the disk lied) means "no
        stages done" with a warning — resume then redoes the library's
        work, which is always safe, instead of crashing the whole run on
        a ``JSONDecodeError`` and bricking ``resume=true``.
        """
        try:
            with open(self.manifest_path) as fh:
                raw = fh.read()
        except FileNotFoundError:
            return {}
        except OSError as exc:
            print(f"WARNING: cannot read stage manifest {self.manifest_path} "
                  f"({exc!r}); treating as no stages done", file=sys.stderr)
            return {}
        try:
            done = json.loads(raw)
        except ValueError:
            print(f"WARNING: stage manifest {self.manifest_path} is "
                  "torn/corrupt; treating as no stages done (resume will "
                  "redo this library)", file=sys.stderr)
            return {}
        if not isinstance(done, dict):
            print(f"WARNING: stage manifest {self.manifest_path} has "
                  f"unexpected shape {type(done).__name__}; treating as no "
                  "stages done", file=sys.stderr)
            return {}
        return done

    def mark_stage_done(self, stage: str) -> None:
        done = self.completed_stages()
        done[stage] = time.time()
        payload = json.dumps(done, indent=1)
        if faults.tear_write("layout.manifest_write", self.manifest_path, payload):
            return  # chaos: the "crash mid-write" already happened
        tmp = self.manifest_path + ".tmp"
        with open(tmp, "w") as fh:
            fh.write(payload)
            fh.flush()
            # fsync BEFORE the rename: os.replace is atomic in the
            # namespace but not in the page cache — without the sync a
            # power cut can leave the new name pointing at zero-length
            # data, exactly the torn state completed_stages() tolerates
            os.fsync(fh.fileno())
        os.replace(tmp, self.manifest_path)

    def stage_done(self, stage: str) -> bool:
        return stage in self.completed_stages()


def library_name_from_fastq(fastq: str | os.PathLike[str]) -> str:
    """'/path/barcode01.fastq.gz' -> 'barcode01' (utils.py:6)."""
    return os.path.basename(os.fspath(fastq)).split(".")[0]


def init_library_dir(
    fastq: str | os.PathLike[str],
    nano_dir: str | os.PathLike[str],
    resume: bool = False,
) -> LibraryLayout:
    """Create (or, with resume, reuse) the per-library tree."""
    library = library_name_from_fastq(fastq)
    library_dir = os.path.join(os.fspath(nano_dir), library)
    if os.path.exists(library_dir) and not resume:
        raise FileExistsError(
            f"{library_dir} exists; pass resume=True to continue a previous run"
        )
    os.makedirs(library_dir, exist_ok=True)
    for sub in SUBDIRS:
        os.makedirs(os.path.join(library_dir, sub), exist_ok=True)
    return LibraryLayout(library=library, library_dir=library_dir)
