"""Length bucketing: ragged reads -> fixed-shape device batches.

The TPU wants static shapes; ONT reads are ragged (1.4-2.3 kb typical for TCR
amplicons, with outliers). This is the rebuild's answer to SURVEY §7 "ragged
everything": reads are grouped into a small set of power-of-two-ish padded
widths so XLA compiles one kernel per bucket and padding waste stays bounded,
and each bucket is emitted in fixed-size batches (a final partial batch is
padded up with dummy rows, masked out by ``valid``).

No reference analogue — the reference streams through per-read Python loops;
batching IS the TPU execution model.
"""

from __future__ import annotations

import dataclasses
from collections.abc import Iterable, Iterator, Sequence

import numpy as np

from ont_tcrconsensus_tpu.ops import encode

DEFAULT_WIDTHS = (256, 512, 1024, 2048, 3072, 4096)


@dataclasses.dataclass
class IngestCounters:
    """Accounting for the ingest conservation contract
    (robustness/contracts.py): every record drawn from the parser is either
    emitted into a batch or counted into a drop bucket, so
    ``n_records - n_dropped_short - n_dropped_long`` must equal the number
    of valid batch rows the device pass sees."""

    n_records: int = 0       # records drawn from the parser (post-subsample)
    n_dropped_short: int = 0  # below the batcher's min_len gate
    n_dropped_long: int = 0   # above the largest configured width


@dataclasses.dataclass
class ReadBatch:
    """One padded device-ready batch.

    codes: (B, W) uint8 dense codes; quals: (B, W) uint8 Phred or None;
    lengths: (B,) int32; valid: (B,) bool (False rows are padding);
    ids: the per-read identifiers (headers), length B (padding rows '').
    """

    codes: np.ndarray
    quals: np.ndarray | None
    lengths: np.ndarray
    valid: np.ndarray
    ids: list[str]
    width: int

    @property
    def batch_size(self) -> int:
        return self.codes.shape[0]

    @property
    def num_valid(self) -> int:
        return int(self.valid.sum())


def bucket_width(length: int, widths: Sequence[int] = DEFAULT_WIDTHS) -> int | None:
    """Smallest configured width that fits; None if the read is too long."""
    for w in widths:
        if length <= w:
            return w
    return None


def pow2_ceil(n: int, lo: int = 1) -> int:
    """Smallest power of two >= max(n, lo) — the shared padding-size policy
    (bounded compile-shape classes for device batches)."""
    p = lo
    while p < n:
        p *= 2
    return p


def batch_reads(
    records: Iterable,
    batch_size: int = 2048,
    widths: Sequence[int] = DEFAULT_WIDTHS,
    with_quals: bool = True,
    min_len: int = 1,
    counters: IngestCounters | None = None,
) -> Iterator[ReadBatch]:
    """Group FastxRecords into per-width padded batches.

    Reads longer than the largest width (or shorter than ``min_len``) are
    dropped — mirroring the pipeline's hard length gates
    (/root/reference/configs/run_config.json: minimal_length) — and tallied
    into ``counters`` when given (the ingest conservation contract).
    Emission order within a bucket preserves input order; buckets flush when
    full and at end-of-stream.
    """
    pending: dict[int, list] = {w: [] for w in widths}

    def flush(w: int) -> ReadBatch:
        recs = pending[w]
        pending[w] = []
        return _make_batch(recs, w, batch_size, with_quals)

    for rec in records:
        ln = len(rec.sequence)
        if counters is not None:
            counters.n_records += 1
        if ln < min_len:
            if counters is not None:
                counters.n_dropped_short += 1
            continue
        w = bucket_width(ln, widths)
        if w is None:
            if counters is not None:
                counters.n_dropped_long += 1
            continue
        pending[w].append(rec)
        if len(pending[w]) == batch_size:
            yield flush(w)
    for w in widths:
        if pending[w]:
            yield flush(w)


def _rows_to_batch(
    rows: list, w: int, batch_size: int, has_quals: bool,
) -> ReadBatch:
    """Materialize one padded batch from (codes, quals|None, name) rows.

    THE single place that owns the padded-batch policy (pow2-of-real-count
    floor 64, PAD_CODE fill, QUAL_FILL qual filler, ''-padded ids) for the
    columnar ingest paths — batch_parsed_reads and batch_parsed_chunks
    must stay byte-identical with the record path (_make_batch) on the
    same data (tests/test_native.py pins this).

    A final partial batch pads to the pow2 of its REAL count (floor 64
    keeps mesh divisibility and compile classes bounded): the round-2
    consensus pass and tail batches otherwise pay full-batch compute for a
    handful of rows (CPU breakdown: round2 ~= round1 cost).
    """
    B = min(batch_size, pow2_ceil(len(rows), 64))
    codes = np.full((B, w), encode.PAD_CODE, dtype=np.uint8)
    if has_quals:
        from ont_tcrconsensus_tpu.ops.consensus import QUAL_FILL

        quals = np.full((B, w), QUAL_FILL, dtype=np.uint8)
    else:
        quals = None
    blens = np.zeros((B,), dtype=np.int32)
    valid = np.zeros((B,), dtype=bool)
    ids: list[str] = []
    for i, (c, q, nm) in enumerate(rows):
        codes[i, : c.size] = c
        if has_quals and q is not None:
            quals[i, : q.size] = q
        blens[i] = c.size
        valid[i] = True
        ids.append(nm)
    ids.extend([""] * (B - len(rows)))
    return ReadBatch(codes=codes, quals=quals, lengths=blens, valid=valid,
                     ids=ids, width=w)


def batch_parsed_reads(
    parsed,
    batch_size: int = 2048,
    widths: Sequence[int] = DEFAULT_WIDTHS,
    min_len: int = 1,
) -> Iterator[ReadBatch]:
    """Batches straight from a columnar :class:`..native.ParsedFastx` parse.

    The native C++ parser returns dense codes + offsets; bucketing becomes a
    vectorized ``searchsorted`` and each batch is filled by row slicing —
    no per-read Python record objects on the ingest path (the pysam-loop
    replacement the reference cannot have, SURVEY §7 hard-part 5).
    Emission order matches :func:`batch_reads` on the same file: input order
    within a bucket, buckets flushed when full and at end-of-stream in
    first-seen order.
    """
    lens = np.asarray(parsed.lengths)
    widths_arr = np.asarray(widths)
    bucket_idx = np.searchsorted(widths_arr, lens)  # widths[i-1] < len <= widths[i]
    eligible = (lens >= min_len) & (bucket_idx < len(widths_arr))
    has_quals = parsed.quals is not None

    pending: dict[int, list[int]] = {int(w): [] for w in widths}

    def flush(w: int) -> ReadBatch:
        rows = pending[w]
        pending[w] = []
        return _rows_to_batch(
            [
                (
                    parsed.codes[parsed.offsets[r]:parsed.offsets[r + 1]],
                    parsed.quals[parsed.offsets[r]:parsed.offsets[r + 1]]
                    if has_quals else None,
                    parsed.names[r],
                )
                for r in rows
            ],
            w, batch_size, has_quals,
        )

    for r in np.where(eligible)[0]:
        w = int(widths_arr[bucket_idx[r]])
        pending[w].append(int(r))
        if len(pending[w]) == batch_size:
            yield flush(w)
    for w in widths:
        if pending[int(w)]:
            yield flush(int(w))


def batch_parsed_chunks(
    chunks,
    batch_size: int = 2048,
    widths: Sequence[int] = DEFAULT_WIDTHS,
    min_len: int = 1,
    subsample: int | None = None,
    counters: IngestCounters | None = None,
) -> Iterator[ReadBatch]:
    """:func:`batch_parsed_reads` over a STREAM of ParsedFastx chunks.

    Buckets carry across chunk boundaries so batch shapes are identical to
    a whole-file parse of the same data (no partial flush per chunk — the
    compile-class story is unchanged). Pending rows are copied out of a
    finished chunk (<= batch_size rows/bucket, a few MB) so each chunk's
    big columnar arrays free as soon as it is consumed: peak host memory
    is O(chunk + pending), not O(file) — SURVEY §7 hard-part 5.
    """
    widths_arr = np.asarray(widths)
    # pending entries: (codes_row, quals_row_or_None, name)
    pending: dict[int, list[tuple]] = {int(w): [] for w in widths}
    has_quals = False
    taken = 0

    def flush(w: int) -> ReadBatch:
        rows = pending[w]
        pending[w] = []
        return _rows_to_batch(rows, w, batch_size, has_quals)

    for parsed in chunks:
        if parsed.quals is not None:
            has_quals = True
        n_raw = parsed.num_records
        # head-subsample counts RAW records (dorado trim --max-reads
        # semantics, preprocessing.py:41-57) — ineligible reads spend
        # quota too, matching the pure-Python fallback path exactly
        if subsample is not None:
            n_raw = min(n_raw, subsample - taken)
            taken += n_raw
        lens = np.asarray(parsed.lengths)[:n_raw]
        bucket_idx = np.searchsorted(widths_arr, lens)
        if counters is not None:  # vectorized drop accounting (contracts)
            counters.n_records += int(n_raw)
            short = lens < min_len
            counters.n_dropped_short += int(short.sum())
            counters.n_dropped_long += int(
                (~short & (bucket_idx >= len(widths_arr))).sum()
            )
        eligible = np.where((lens >= min_len) & (bucket_idx < len(widths_arr)))[0]
        for r in eligible:
            w = int(widths_arr[bucket_idx[r]])
            s, e = parsed.offsets[r], parsed.offsets[r + 1]
            pending[w].append((
                parsed.codes[s:e],
                parsed.quals[s:e] if parsed.quals is not None else None,
                parsed.names[r],
            ))
            if len(pending[w]) == batch_size:
                yield flush(w)
        # copy leftover VIEWS (base is the chunk's big array) so the chunk
        # can free; rows copied at earlier boundaries are already owned
        for w in widths:
            pending[int(w)] = [
                (c if c.base is None else c.copy(),
                 q if q is None or q.base is None else q.copy(), nm)
                for c, q, nm in pending[int(w)]
            ]
        if subsample is not None and taken >= subsample:
            break
    for w in widths:
        if pending[int(w)]:
            yield flush(int(w))


@dataclasses.dataclass
class EncodedRecords:
    """Pre-encoded reads: parallel header/code-vector lists.

    The device-resident hand-off type: a producer that already holds
    uint8 code vectors (round-1 consensus output under ``keep_codes``)
    passes them straight to :func:`batch_encoded` instead of decoding to
    strings and re-encoding through the parser path. Code vectors are
    1-d uint8 in 0..4; decode∘encode bijectivity on that alphabet makes
    the resulting batches byte-identical to string-path batches of the
    same sequences.
    """

    headers: list[str]
    codes: list[np.ndarray]

    def __len__(self) -> int:
        return len(self.headers)


def batch_encoded(
    records: EncodedRecords,
    batch_size: int = 2048,
    widths: Sequence[int] = DEFAULT_WIDTHS,
    min_len: int = 1,
    counters: IngestCounters | None = None,
) -> Iterator[ReadBatch]:
    """:func:`batch_reads` over :class:`EncodedRecords` — no string pass.

    Same bucketing, same drop gates and counter accounting, same flush
    policy; batches materialize through :func:`_rows_to_batch` (the
    single padded-batch policy owner), with no qualities — consensus
    sequences carry none, exactly like the FASTA record path.
    """
    pending: dict[int, list] = {w: [] for w in widths}

    def flush(w: int) -> ReadBatch:
        rows = pending[w]
        pending[w] = []
        return _rows_to_batch(rows, w, batch_size, has_quals=False)

    for header, codes in zip(records.headers, records.codes):
        codes = np.asarray(codes, dtype=np.uint8)
        ln = int(codes.size)
        if counters is not None:
            counters.n_records += 1
        if ln < min_len:
            if counters is not None:
                counters.n_dropped_short += 1
            continue
        w = bucket_width(ln, widths)
        if w is None:
            if counters is not None:
                counters.n_dropped_long += 1
            continue
        pending[w].append((codes, None, header))
        if len(pending[w]) == batch_size:
            yield flush(w)
    for w in widths:
        if pending[w]:
            yield flush(w)


def _make_batch(recs: list, width: int, batch_size: int, with_quals: bool) -> ReadBatch:
    n = len(recs)
    # partial batches pad to the pow2 of the real count (see batch_parsed_reads)
    B = min(batch_size, pow2_ceil(n, 64))
    codes = np.full((B, width), encode.PAD_CODE, dtype=np.uint8)
    # FASTA records carry no quality: quals must be None, not the filler —
    # a 93-filled array would sail through the EE filter (10^-9.3) but
    # poison the v4 polisher's quality channels (code-review r5), and the
    # None contract is what routes the QUAL_FILL fallback downstream. In a
    # MIXED stream (concatenated fastq+fasta) the quality-less rows get the
    # same QUAL_FILL the polisher's fallback and training qual-dropout use
    # (in-distribution), not 93 — they then face the EE filter at that
    # mid-range quality like any other read.
    with_quals = with_quals and any(
        getattr(rec, "quality", None) for rec in recs
    )
    if with_quals:
        from ont_tcrconsensus_tpu.ops.consensus import QUAL_FILL

        quals = np.full((B, width), QUAL_FILL, dtype=np.uint8)
    else:
        quals = None
    lengths = np.zeros((B,), dtype=np.int32)
    valid = np.zeros((B,), dtype=bool)
    ids: list[str] = []
    for i, rec in enumerate(recs):
        seq = rec.sequence
        codes[i, : len(seq)] = encode.encode_seq(seq)
        lengths[i] = len(seq)
        valid[i] = True
        if with_quals and getattr(rec, "quality", None):
            raw = np.frombuffer(rec.quality.encode("ascii"), dtype=np.uint8)
            if raw.size and raw.min() < 33:
                raise ValueError(
                    f"read {rec.name!r}: quality below Phred-33 '!'"
                )
            quals[i, : raw.size] = raw - 33
        ids.append(rec.header if hasattr(rec, "header") else rec.name)
    ids.extend([""] * (B - n))
    return ReadBatch(codes=codes, quals=quals, lengths=lengths, valid=valid, ids=ids, width=width)
