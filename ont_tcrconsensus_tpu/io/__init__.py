"""io subpackage."""
