"""Host-side FASTA/FASTQ streaming.

The reference leans on pysam.FastxFile + external tools for all sequence IO
(e.g. /root/reference/ont_tcr_consensus/extract_umis.py:216,
region_split.py:241). Here IO is a first-party streaming layer that feeds the
device batcher: gzip-transparent record iteration, zero intermediate files,
and batched emission sized for padded device arrays. A C fast path
(:mod:`.native`) accelerates parsing when the compiled extension is present;
this module is the always-available pure-Python fallback with identical
semantics.
"""

from __future__ import annotations

import dataclasses
import gzip
import os
from collections.abc import Iterable, Iterator
from typing import IO


@dataclasses.dataclass
class FastxRecord:
    name: str        # first whitespace-delimited token of the header
    comment: str     # remainder of the header ('' if none)
    sequence: str
    quality: str | None = None  # None for FASTA

    @property
    def header(self) -> str:
        return f"{self.name} {self.comment}".rstrip()


def _open_text(path: str | os.PathLike[str]) -> IO[str]:
    p = os.fspath(path)
    if p.endswith(".gz"):
        return gzip.open(p, "rt")
    return open(p)


def _split_header(line: str) -> tuple[str, str]:
    parts = line[1:].rstrip("\n").split(None, 1)
    if not parts:
        return "", ""
    return parts[0], parts[1] if len(parts) > 1 else ""


def _gzip_context(path, fh, exc) -> ValueError:
    """Wrap a gzip decode failure with file + byte-offset context.

    ``gzip.BadGzipFile``/``EOFError`` out of a streaming read used to
    surface as a raw traceback with no hint of WHICH file died WHERE; the
    quarantine path (io/validate.py) turns these into events, but even
    under ``on_bad_record=fail`` the error must name the file and the
    decompressed offset reached.
    """
    try:
        offset = fh.buffer.tell() if hasattr(fh, "buffer") else fh.tell()
    except (OSError, ValueError):
        offset = -1
    return ValueError(
        f"{os.fspath(path)}: truncated or corrupt gzip stream near "
        f"decompressed byte offset {offset} ({exc}); with "
        "on_bad_record=quarantine the decodable prefix is kept and this "
        "becomes a quarantine event"
    )


def read_fastx(path: str | os.PathLike[str]) -> Iterator[FastxRecord]:
    """Iterate records from a FASTA/FASTQ file (.gz transparent).

    Format is sniffed from the first record character. FASTA sequences may be
    multi-line; FASTQ records must be 4-line (the only form ONT emits).
    A truncated/corrupt ``.gz`` raises ValueError with file + offset context
    instead of a bare gzip traceback.
    """
    with _open_text(path) as fh:
        try:
            yield from _read_fastx_body(path, fh)
        except (gzip.BadGzipFile, EOFError) as exc:
            raise _gzip_context(path, fh, exc) from exc


def _read_fastx_body(path, fh) -> Iterator[FastxRecord]:
    first = fh.read(1)
    if not first:
        return
    if first == ">":
        name, comment = _split_header(">" + fh.readline())
        seq_parts: list[str] = []
        for line in fh:
            if line.startswith(">"):
                yield FastxRecord(name, comment, "".join(seq_parts))
                name, comment = _split_header(line)
                seq_parts = []
            else:
                seq_parts.append(line.strip())
        yield FastxRecord(name, comment, "".join(seq_parts))
    elif first == "@":
        header = "@" + fh.readline()
        while header:
            if not header.strip():  # tolerate blank lines between records
                header = fh.readline()
                continue
            name, comment = _split_header(header)
            seq = fh.readline().strip()
            plus = fh.readline()
            qual = fh.readline().strip()
            if not plus.startswith("+"):
                raise ValueError(f"malformed FASTQ record near {name!r} in {path}")
            if not qual and seq:
                raise ValueError(f"truncated FASTQ record {name!r} in {path}")
            if len(qual) != len(seq):
                raise ValueError(
                    f"FASTQ record {name!r} in {path}: qual length "
                    f"{len(qual)} != seq length {len(seq)}"
                )
            yield FastxRecord(name, comment, seq, qual)
            header = fh.readline()
    else:
        raise ValueError(f"{path}: not FASTA/FASTQ (starts with {first!r})")


def read_fasta_dict(path: str | os.PathLike[str]) -> dict[str, str]:
    """FASTA -> {name: sequence} (reference region_split.py:29-58 analogue)."""
    out: dict[str, str] = {}
    for rec in read_fastx(path):
        if rec.name in out:
            raise ValueError(f"duplicate sequence name {rec.name!r} in {path}")
        out[rec.name] = rec.sequence
    return out


def write_fasta(
    path: str | os.PathLike[str],
    records: Iterable[tuple[str, str]],
    append: bool = False,
    width: int = 0,
) -> int:
    """Write (header, seq) pairs; returns the number written.

    ``width=0`` writes single-line sequences (what every downstream stage of
    the pipeline expects).
    """
    n = 0
    mode = "a" if append else "w"
    p = os.fspath(path)
    opener = gzip.open(p, mode + "t") if p.endswith(".gz") else open(p, mode)
    with opener as fh:
        for header, seq in records:
            fh.write(f">{header}\n")
            if width and len(seq) > width:
                for i in range(0, len(seq), width):
                    fh.write(seq[i : i + width] + "\n")
            else:
                fh.write(seq + "\n")
            n += 1
    return n


def write_fastq(
    path: str | os.PathLike[str],
    records: Iterable[tuple[str, str, str]],
    append: bool = False,
) -> int:
    """Write (header, seq, qual) triples; returns the number written."""
    n = 0
    mode = "a" if append else "w"
    p = os.fspath(path)
    opener = gzip.open(p, mode + "t") if p.endswith(".gz") else open(p, mode)
    with opener as fh:
        for header, seq, qual in records:
            fh.write(f"@{header}\n{seq}\n+\n{qual}\n")
            n += 1
    return n


def count_fasta_records(path: str | os.PathLike[str]) -> int:
    """Header count — the reference shells out to ``grep -c '^>'``
    (/root/reference/ont_tcr_consensus/count.py:9-20)."""
    n = 0
    with _open_text(path) as fh:
        try:
            for line in fh:
                if line.startswith(">"):
                    n += 1
        except (gzip.BadGzipFile, EOFError) as exc:
            raise _gzip_context(path, fh, exc) from exc
    return n


def fastq_stats(path: str | os.PathLike[str]) -> dict[str, float]:
    """Summary stats equivalent to the reference's ``seqkit stat -a`` QC dumps
    (/root/reference/ont_tcr_consensus/preprocessing.py:82-99): record count,
    total bases, min/mean/max length, mean quality (if FASTQ)."""
    n = 0
    total = 0
    mn = None
    mx = 0
    qsum = 0.0
    qn = 0
    for rec in read_fastx(path):
        ln = len(rec.sequence)
        n += 1
        total += ln
        mn = ln if mn is None else min(mn, ln)
        mx = max(mx, ln)
        if rec.quality:
            qsum += sum(rec.quality.encode("ascii")) - 33 * len(rec.quality)
            qn += len(rec.quality)
    return {
        "num_seqs": n,
        "sum_len": total,
        "min_len": mn or 0,
        "avg_len": (total / n) if n else 0.0,
        "max_len": mx,
        "avg_qual": (qsum / qn) if qn else 0.0,
    }
