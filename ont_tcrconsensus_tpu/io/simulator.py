"""Synthetic ONT TCR-amplicon read simulator.

The reference repo has no tests and no simulator (SURVEY §4); its behavioral
spec is empirical QC on real PromethION runs. This module is the rebuild's
test bed (SURVEY §7 M0): generate a toy reference library plus reads with
*known* per-molecule UMIs and a controllable error model, so every stage —
EE filtering, alignment, region split, UMI extraction, clustering, consensus,
counting — can be asserted against ground truth, up to bit-exact UMI counts.

Amplicon structure mirrors what the reference pipeline assumes
(/root/reference/ont_tcr_consensus/extract_umis.py:110-126: fwd UMI within
the first ~81 nt of the oriented read, rev UMI within the last ~76 nt;
configs/run_config.json:9-12):

    5'- left_flank . UMI_fwd . region_sequence . UMI_rev . right_flank -3'

Reads are emitted in + or - orientation with ONT-like errors
(sub/ins/del, qualities consistent with the error rate).
"""

from __future__ import annotations

import dataclasses

import numpy as np

_BASES = np.array(list("ACGT"))
_IUPAC_CHOICES = {
    "A": "A", "C": "C", "G": "G", "T": "T",
    "R": "AG", "Y": "CT", "S": "CG", "W": "AT", "K": "GT", "M": "AC",
    "B": "CGT", "D": "AGT", "H": "ACT", "V": "ACG", "N": "ACGT",
}

# Short fixed flanks standing in for the sequencing adapters/primers that
# dorado trim leaves behind; lengths chosen so UMIs sit inside the default
# 81/76 nt softclip windows (run_config.json:9-10).
LEFT_FLANK = "CAAGCAGAAGACGGCATACGAGAT"
RIGHT_FLANK = "AATGATACGGCGACCACCGAGATC"

# Full UVP primers (adapter+GSP) for untrimmed-read simulation: the amplicon
# carries the forward primer at its 5' end and the reverse complement of the
# reverse primer at its 3' end, exactly what the trim stage must remove
# (dorado trim --primer-sequences analogue; reference primers/primers.fasta).
PRIMER_FWD = "CAAGCAGAAGACGGCATACGAGATGTATCGTGTAGAGACTGCGTAGG"
PRIMER_REV = "AATGATACGGCGACCACCGAGATCAGTGATCGAGTCAGTGCGAGTG"


def _rand_seq(rng: np.random.Generator, n: int) -> str:
    return "".join(_BASES[rng.integers(0, 4, size=n)])


def instantiate_iupac(rng: np.random.Generator, pattern: str) -> str:
    """Draw a concrete sequence from a degenerate IUPAC pattern."""
    return "".join(
        c if len(_IUPAC_CHOICES[c]) == 1 else _IUPAC_CHOICES[c][rng.integers(len(_IUPAC_CHOICES[c]))]
        for c in pattern.upper()
    )


def revcomp(seq: str) -> str:
    """Delegates to the pipeline's own encoding so semantics never diverge."""
    from ont_tcrconsensus_tpu.ops import encode

    return encode.revcomp_str(seq)


def mutate(
    rng: np.random.Generator,
    seq: str,
    sub_rate: float,
    ins_rate: float,
    del_rate: float,
) -> tuple[str, str]:
    """Apply iid sub/ins/del errors; return (read, phred33 quality string).

    Quality is drawn around the Q implied by the total error rate, so the
    expected-error filter sees realistic values.
    """
    total = max(sub_rate + ins_rate + del_rate, 1e-6)
    q_mid = int(np.clip(-10.0 * np.log10(total), 5, 40))
    out: list[str] = []
    quals: list[int] = []
    for ch in seq:
        r = rng.random()
        if r < del_rate:
            continue
        if r < del_rate + ins_rate:
            out.append(str(_BASES[rng.integers(4)]))
            quals.append(max(2, q_mid - 6))
        if rng.random() < sub_rate:
            choices = [b for b in "ACGT" if b != ch]
            out.append(choices[rng.integers(3)])
            quals.append(max(2, q_mid - 4))
        else:
            out.append(ch)
            quals.append(int(np.clip(rng.normal(q_mid, 3), 2, 50)))
    qual = "".join(chr(33 + q) for q in quals)
    return "".join(out), qual


@dataclasses.dataclass(frozen=True)
class OntErrorModel:
    """Systematic (non-iid) ONT error structure.

    The iid :func:`mutate` model is the regime where majority voting is
    already near-optimal — which made the round-2 polisher eval circular
    (VERDICT r2 weak #3). Real ONT errors are structured; medaka exists to
    fix exactly that structure (ref medaka_polish.py:113-134). This model
    reproduces the three dominant modes reported for R10.4 chemistry:

    - **homopolymer-length-dependent indels**: a base inside a homopolymer
      run of length r deletes with probability ``del_rate * min(1 +
      hp_slope*(r-1), hp_cap)`` — runs shrink systematically, the classic
      ONT failure voting cannot fix (every subread shrinks the same run);
      insertions inside a run duplicate the run base.
    - **context-biased substitutions**: the sub rate at a position is
      multiplied by a per-(prev base, base) context factor
      (``motif_sub_boost``); substitutions are transitions (A<->G, C<->T)
      with probability ``transition_frac`` instead of uniform.
    - **strand asymmetry**: callers apply the model to the *sequenced*
      strand (:func:`simulate_library` mutates after orientation), so a
      boosted context on one strand is a different context on the other —
      '+' and '-' reads of one molecule carry different systematic errors.
    """

    sub_rate: float = 0.006
    ins_rate: float = 0.002
    del_rate: float = 0.004
    hp_slope: float = 1.0
    hp_cap: float = 10.0
    # context multipliers: (prev_base, base) -> sub-rate factor. Defaults
    # boost pyrimidine-after-purine calls, a reported ONT bias family.
    motif_sub_boost: tuple = (("GA", 3.0), ("CT", 2.5), ("TC", 2.0))
    transition_frac: float = 0.6

    def context_matrix(self) -> np.ndarray:
        m = np.ones((4, 4), np.float64)
        code = {"A": 0, "C": 1, "G": 2, "T": 3}
        for pair, f in self.motif_sub_boost:
            m[code[pair[0]], code[pair[1]]] = f
        return m


_TRANSITION = np.array([2, 3, 0, 1], np.int8)  # A<->G, C<->T
_CODE_OF = np.full(128, -1, np.int8)
for _i, _b in enumerate("ACGT"):
    _CODE_OF[ord(_b)] = _i


def _run_lengths(codes: np.ndarray) -> np.ndarray:
    """Length of the homopolymer run containing each position (vectorized)."""
    n = len(codes)
    if n == 0:
        return np.zeros(0, np.int32)
    boundary = np.empty(n, bool)
    boundary[0] = True
    boundary[1:] = codes[1:] != codes[:-1]
    run_id = np.cumsum(boundary) - 1
    counts = np.bincount(run_id)
    return counts[run_id].astype(np.int32)


def mutate_ont(
    rng: np.random.Generator, seq: str, model: OntErrorModel
) -> tuple[str, str]:
    """Apply the systematic ONT error model; returns (read, phred33 quals).

    Vectorized (no per-character Python loop): position-wise deletion /
    substitution / insertion draws with homopolymer- and context-dependent
    rates, then one splice pass.
    """
    codes = _CODE_OF[np.frombuffer(seq.encode("ascii"), np.uint8)].astype(np.int8)
    known = codes >= 0
    n = len(codes)
    if n == 0:
        return "", ""
    runs = _run_lengths(codes)
    hp_mult = np.minimum(1.0 + model.hp_slope * (runs - 1), model.hp_cap)

    del_p = np.where(known, model.del_rate * hp_mult, 0.0)
    ctx = model.context_matrix()
    prev = np.concatenate([[0], np.clip(codes[:-1], 0, 3)])
    sub_p = np.where(
        known, model.sub_rate * ctx[prev, np.clip(codes, 0, 3)], 0.0
    )
    ins_p = np.where(known, model.ins_rate * hp_mult, model.ins_rate)

    u = rng.random((3, n))
    deleted = u[0] < del_p
    substituted = ~deleted & (u[1] < sub_p)
    inserted = u[2] < ins_p  # one extra base BEFORE this position

    new_base = codes.copy()
    is_trans = rng.random(n) < model.transition_frac
    trans = _TRANSITION[np.clip(codes, 0, 3)]
    shift = rng.integers(1, 4, n).astype(np.int8)
    transv = (np.clip(codes, 0, 3) + shift) % 4
    transv = np.where(transv == trans, (transv + 1) % 4, transv).astype(np.int8)
    new_base = np.where(substituted & is_trans, trans, new_base)
    new_base = np.where(substituted & ~is_trans, transv, new_base)

    # inserted base: duplicate the run base inside homopolymers, random else
    ins_base = np.where(
        (runs > 1) & known, np.clip(codes, 0, 3), rng.integers(0, 4, n)
    ).astype(np.int8)

    total = max(model.sub_rate + model.ins_rate + model.del_rate, 1e-6)
    q_mid = int(np.clip(-10.0 * np.log10(total), 5, 40))
    base_q = np.clip(rng.normal(q_mid, 3, n), 2, 50).astype(np.int32)
    base_q = np.where(substituted, np.maximum(2, q_mid - 4), base_q)
    # low-ish quality on homopolymer tails, where the signal truly is flat
    base_q = np.where(runs >= 4, np.maximum(2, base_q - 6), base_q)

    out_codes: list[np.ndarray] = []
    out_quals: list[np.ndarray] = []
    keep = ~deleted
    # interleave insertions: build (2, n) stacks [ins?, base?] then mask
    stack_codes = np.stack([ins_base, new_base], axis=1).reshape(-1)
    stack_keep = np.stack([inserted, keep], axis=1).reshape(-1)
    stack_quals = np.stack(
        [np.full(n, max(2, q_mid - 6), np.int32), base_q], axis=1
    ).reshape(-1)
    out_codes = stack_codes[stack_keep]
    out_quals = stack_quals[stack_keep]
    read = np.frombuffer(b"ACGT", np.uint8)[np.clip(out_codes, 0, 3)].tobytes().decode()
    qual = "".join(chr(33 + int(q)) for q in out_quals)
    return read, qual


@dataclasses.dataclass
class Molecule:
    """Ground truth for one unique molecule (one expected consensus)."""

    region: str
    umi_fwd: str   # concrete fwd UMI (as in + orientation)
    umi_rev: str   # concrete rev UMI (as in + orientation)
    num_reads: int

    @property
    def combined_umi(self) -> str:
        return self.umi_fwd + self.umi_rev


@dataclasses.dataclass
class SimulatedLibrary:
    reference: dict[str, str]        # region name -> sequence
    molecules: list[Molecule]
    reads: list[tuple[str, str, str]]  # (header, sequence, qual)

    @property
    def true_counts(self) -> dict[str, int]:
        counts: dict[str, int] = {}
        for m in self.molecules:
            counts[m.region] = counts.get(m.region, 0) + 1
        return counts


def make_reference(
    rng: np.random.Generator,
    num_regions: int = 8,
    region_len: tuple[int, int] = (1500, 2200),
    num_similar_pairs: int = 0,
    similar_divergence: float = 0.01,
    num_negative_controls: int = 0,
) -> dict[str, str]:
    """Toy TCR reference library.

    ``num_similar_pairs`` appends near-duplicate regions (>= 99% identical by
    default) to exercise the self-homology region clustering
    (region_split.py:61-216). Negative controls get the reference's reserved
    suffixes (region_split.py:302-309) and receive no molecules.
    """
    ref: dict[str, str] = {}
    for i in range(num_regions):
        n = int(rng.integers(region_len[0], region_len[1] + 1))
        ref[f"TCR{i:04d}"] = _rand_seq(rng, n)
    names = list(ref)
    for j in range(num_similar_pairs):
        src = names[j % len(names)]
        seq = list(ref[src])
        n_mut = max(1, int(len(seq) * similar_divergence))
        for pos in rng.choice(len(seq), size=n_mut, replace=False):
            choices = [b for b in "ACGT" if b != seq[pos]]
            seq[pos] = choices[rng.integers(3)]
        ref[f"{src}_sim{j}"] = "".join(seq)
    for k in range(num_negative_controls):
        n = int(rng.integers(region_len[0], region_len[1] + 1))
        ref[f"NC{k:03d}_full_n"] = _rand_seq(rng, n)
    return ref


def simulate_library(
    seed: int = 0,
    num_regions: int = 8,
    molecules_per_region: tuple[int, int] = (2, 6),
    reads_per_molecule: tuple[int, int] = (4, 12),
    sub_rate: float = 0.01,
    ins_rate: float = 0.005,
    del_rate: float = 0.005,
    umi_fwd_pattern: str = "TTTVVTTVVVVTTVVVVTTVVVVTTVVVVTTT",
    umi_rev_pattern: str = "AAABBBBAABBBBAABBBBAABBBBAABBAAA",
    reference: dict[str, str] | None = None,
    with_adapters: bool = False,
    error_model: OntErrorModel | None = None,
    **reference_kwargs,
) -> SimulatedLibrary:
    """Generate a full library with ground truth.

    Reads are shuffled and emitted in random +/- orientation; headers carry
    ``mol=<i>`` ground-truth tags (ignored by the pipeline, used by tests).

    ``with_adapters=True`` emits UNTRIMMED reads: the full UVP forward
    primer at the 5' end and revcomp of the reverse primer at the 3' end
    (what the basecaller hands to ``dorado trim``) — requires the pipeline's
    primer-trim stage. The default emits pre-trimmed reads with the short
    leftover flanks.

    ``error_model`` switches from iid errors (``sub/ins/del_rate``) to the
    systematic :class:`OntErrorModel`; errors are then applied to the
    SEQUENCED strand (after orientation), so strand asymmetry is real.
    """
    rng = np.random.default_rng(seed)
    ref = reference if reference is not None else make_reference(
        rng, num_regions=num_regions, **reference_kwargs
    )
    molecules: list[Molecule] = []
    reads: list[tuple[str, str, str]] = []
    countable = [n for n in ref if not n.endswith(("_v_n", "cdr3j_n", "full_n"))]
    for region in countable:
        n_mol = int(rng.integers(molecules_per_region[0], molecules_per_region[1] + 1))
        for _ in range(n_mol):
            mol = Molecule(
                region=region,
                umi_fwd=instantiate_iupac(rng, umi_fwd_pattern),
                umi_rev=instantiate_iupac(rng, umi_rev_pattern),
                num_reads=int(rng.integers(reads_per_molecule[0], reads_per_molecule[1] + 1)),
            )
            molecules.append(mol)
    left = PRIMER_FWD if with_adapters else LEFT_FLANK
    right = revcomp(PRIMER_REV) if with_adapters else RIGHT_FLANK
    for mi, mol in enumerate(molecules):
        template = (
            left + mol.umi_fwd + ref[mol.region] + mol.umi_rev + right
        )
        template_rc = revcomp(template)
        for ri in range(mol.num_reads):
            orient = "-" if rng.random() < 0.5 else "+"
            if error_model is not None:
                # mutate the sequenced strand: systematic contexts differ
                # between orientations, like a real flow cell
                seq, qual = mutate_ont(
                    rng, template_rc if orient == "-" else template, error_model
                )
            else:
                seq, qual = mutate(rng, template, sub_rate, ins_rate, del_rate)
                if orient == "-":
                    seq, qual = revcomp(seq), qual[::-1]
            reads.append((f"read_m{mi}_r{ri} mol={mi} orient={orient}", seq, qual))
    order = rng.permutation(len(reads))
    reads = [reads[i] for i in order]
    return SimulatedLibrary(reference=ref, molecules=molecules, reads=reads)
