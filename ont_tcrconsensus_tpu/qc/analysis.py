"""Post-hoc QC analysis and reporting.

Rebuild of the reference's analysis layer (/root/reference/
ont_tcr_consensus/analysis.py, 1232 LoC) and its driver notebook
(notebooks/analysis.ipynb): log parsers, count transforms, distribution
fits, sensitivity summaries and the plot set, writing per-library PDFs
under ``outs/``. Parsers target THIS framework's artifact formats (which
keep the reference's filenames); each function cites its reference
analogue.
"""

from __future__ import annotations

import os
from collections import defaultdict

import numpy as np

# matplotlib is imported lazily inside plot functions so headless/quick runs
# never pay for it


# ---------------------------------------------------------------------------
# parsers (analysis.py:76-114 analogues, reading our log formats)


def parse_merged_consensus_bam_filter_log(log_path: str) -> dict[str, float]:
    """Key/value parse of merged_consensus_bam_filter.log
    (analysis.py:84-105 parses the reference log by line index; ours parses
    by label so reordering cannot silently break it)."""
    out: dict[str, float] = {}
    labels = {
        "Total # primary alignments": "n_primary",
        "# primary alignments with allowed length": "n_correct_len",
        "# alignments too short": "n_short",
        "# alignments too long": "n_long",
        "# written alignments passing blast id filter": "n_written",
        "- minimal region overlap": "minimal_region_overlap",
        "- minimal blast identity with reference": "blast_id_threshold",
    }
    with open(log_path) as fh:
        for line in fh:
            for label, key in labels.items():
                if line.startswith(label):
                    out[key] = float(line.rstrip().rsplit(":", 1)[1])
    return out


def parse_quantile_95_blast_id_from_self_homology_log(log_path: str) -> float | None:
    """analysis.py:108-114 analogue."""
    with open(log_path) as fh:
        for line in fh:
            if line.startswith("0.950 quantile blast identity"):
                return float(line.rstrip().rsplit(":", 1)[1])
    return None


def parse_raw_nanopore_qual_from_fastq_stats(log_path: str) -> float | None:
    """Mean raw-read quality from the fastq-stats artifact
    (analysis.py:76-81 parses seqkit's AvgQual column; ours reads the
    pre-filter row of logs/<library>_fastq_stats.log)."""
    with open(log_path) as fh:
        header = fh.readline().rstrip("\n").split("\t")
        try:
            qcol = header.index("avg_qual")
        except ValueError:
            return None
        for line in fh:
            parts = line.rstrip("\n").split("\t")
            if parts and parts[0] == "post_trim_pre_filter":
                return float(parts[qcol])
    return None


def read_counts_csv(path: str) -> dict[str, int]:
    out: dict[str, int] = {}
    with open(path) as fh:
        next(fh, None)
        for line in fh:
            region, _, count = line.rstrip("\n").rpartition(",")
            if region:
                out[region] = int(count)
    return out


def read_two_column_csv(path: str) -> list[tuple[str, float]]:
    rows = []
    with open(path) as fh:
        next(fh, None)
        for line in fh:
            a, _, b = line.rstrip("\n").rpartition(",")
            if a:
                rows.append((a, float(b)))
    return rows


# ---------------------------------------------------------------------------
# count transforms (analysis.py:560-574)


def filter_counts_on_log_umi_count_threshold(
    counts: dict[str, int], log10_threshold: float
) -> dict[str, int]:
    """Keep regions with log10(count) >= threshold (analysis.py:573)."""
    return {
        region: c for region, c in counts.items()
        if c > 0 and np.log10(c) >= log10_threshold
    }


def filter_counts_on_umi_quantile_threshold(
    counts: dict[str, int], quantile_umi_threshold: float = 0.05
) -> dict[str, int]:
    """Keep regions whose count exceeds the q-quantile of all counts
    (analysis.py:565-570: strict >, quantile over the full Count column)."""
    if not counts:
        return {}
    bar = float(np.quantile(np.asarray(list(counts.values()), np.float64),
                            quantile_umi_threshold))
    return {region: c for region, c in counts.items() if c > bar}


def negative_control_counts(
    counts: dict[str, int],
    suffixes: tuple[str, ...] = ("_v_n", "cdr3j_n", "full_n"),
) -> dict[str, int]:
    """Spiked-negative-control subset (analysis.py:53-73)."""
    return {r: c for r, c in counts.items() if r.endswith(suffixes)}


def fit_count_distributions(counts: list[int]) -> dict[str, float]:
    """Negative-binomial + normal fits with KS tests (analysis.py:577-811).

    The NB is moment-fit (r from mean/variance); KS p-values quantify how
    well each family explains the per-region UMI count spread.
    """
    from scipy import stats as sps

    x = np.asarray([c for c in counts if c > 0], dtype=np.float64)
    out: dict[str, float] = {"n": float(x.size)}
    if x.size < 3:
        return out
    mean, var = float(x.mean()), float(x.var(ddof=1))
    out["mean"] = mean
    out["var"] = var
    # normal fit
    ks_norm = sps.kstest(x, "norm", args=(mean, max(np.sqrt(var), 1e-9)))
    out["ks_normal_p"] = float(ks_norm.pvalue)
    # negative binomial via moments (var > mean required)
    if var > mean:
        r = mean**2 / (var - mean)
        p = r / (r + mean)
        out["nb_r"] = float(r)
        out["nb_p"] = float(p)
        nb = sps.nbinom(r, p)
        ks_nb = sps.kstest(x, nb.cdf)
        out["ks_nbinom_p"] = float(ks_nb.pvalue)
    return out


def estimate_precision_at_num_subreads(
    subread_blast_rows: list[tuple[str, float]],
    perfect_id: float = 1.0,
) -> dict[int, dict[str, float]]:
    """Consensus precision as a function of UMI cluster depth
    (minimap2_align.py:362-435, offline tool).

    For each subread count: how many consensus sequences exist, and what
    fraction align to the reference with blast identity >= ``perfect_id``.
    """
    per_depth: dict[int, list[float]] = defaultdict(list)
    for n, blast_id in subread_blast_rows:
        if str(n).isdigit():
            per_depth[int(n)].append(blast_id)
    return {
        n: {
            "n_consensus": len(ids),
            "n_perfect": sum(1 for b in ids if b >= perfect_id),
            "precision": sum(1 for b in ids if b >= perfect_id) / len(ids),
        }
        for n, ids in sorted(per_depth.items())
    }


# ---------------------------------------------------------------------------
# summary / sensitivity (analysis.py:814-911)


def write_results_summary(
    counts: dict[str, int],
    reference_regions: set[str],
    out_path: str,
    log10_threshold: float | None = None,
    negative_suffixes: tuple[str, ...] = ("_v_n", "cdr3j_n", "full_n"),
) -> dict[str, float]:
    """Sensitivity vs reference + negative-control leakage report."""
    countable = {r for r in reference_regions if not r.endswith(negative_suffixes)}
    detected = {r for r, c in counts.items() if c > 0 and not r.endswith(negative_suffixes)}
    filtered = (
        filter_counts_on_log_umi_count_threshold(counts, log10_threshold)
        if log10_threshold is not None else counts
    )
    detected_filtered = {
        r for r in filtered if not r.endswith(negative_suffixes)
    }
    ncs = negative_control_counts(counts, negative_suffixes)
    summary = {
        "num_reference_regions": len(countable),
        "num_detected": len(countable & detected),
        "sensitivity": (len(countable & detected) / len(countable)) if countable else 0.0,
        "num_detected_after_threshold": len(countable & detected_filtered),
        "num_negative_controls_with_counts": sum(1 for c in ncs.values() if c > 0),
        "total_negative_control_counts": sum(ncs.values()),
        "total_umi_counts": sum(counts.values()),
    }
    missing = sorted(countable - detected)
    with open(out_path, "w") as fh:
        for k, v in summary.items():
            fh.write(f"{k}: {v}\n")
        fh.write(f"missing_regions ({len(missing)}): {missing}\n")
    return summary


# ---------------------------------------------------------------------------
# plots (analysis.py:117-557, 577-811, 914-1232) — matplotlib PDFs


def _savefig(fig, out_path):
    fig.tight_layout()
    fig.savefig(out_path)
    import matplotlib.pyplot as plt

    plt.close(fig)


def plot_blast_id_hist(region_blast_rows: list[tuple[str, float]], out_path: str,
                       threshold: float | None = None):
    """Consensus blast-id distribution (analysis.py:117-228)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    vals = [b for _, b in region_blast_rows]
    fig, ax = plt.subplots(figsize=(6, 4))
    ax.hist(vals, bins=60)
    if threshold is not None:
        ax.axvline(threshold, color="red", linestyle="--", label=f"threshold {threshold:.4f}")
        ax.legend()
    ax.set_xlabel("blast identity vs reference")
    ax.set_ylabel("# consensus sequences")
    _savefig(fig, out_path)


def plot_nt_length_deviation_hists(short_rows, long_rows, out_path: str):
    """Too-short / too-long alignment histograms (analysis.py:231-325)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, axes = plt.subplots(1, 2, figsize=(10, 4))
    axes[0].hist([v for _, v in short_rows], bins=40)
    axes[0].set_xlabel("nt short of minimal overlap")
    axes[1].hist([v for _, v in long_rows], bins=40)
    axes[1].set_xlabel("nt past maximal length")
    for ax in axes:
        ax.set_ylabel("# alignments")
    _savefig(fig, out_path)


def plot_subreads_per_umi_hist(subread_rows: list[tuple[str, float]], out_path: str):
    """Subreads-per-UMI histogram (analysis.py:393-434)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    ns = [int(n) for n, _ in subread_rows if str(n).isdigit()]
    fig, ax = plt.subplots(figsize=(6, 4))
    ax.hist(ns, bins=np.arange(0.5, (max(ns) if ns else 1) + 1.5))
    ax.set_xlabel("# subreads per UMI cluster")
    ax.set_ylabel("# clusters")
    _savefig(fig, out_path)


def plot_blast_id_vs_subreads_box(subread_rows: list[tuple[str, float]], out_path: str):
    """Blast-id-vs-subreads boxplots (analysis.py:437-557)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    groups: dict[int, list[float]] = defaultdict(list)
    for n, b in subread_rows:
        if str(n).isdigit():
            groups[int(n)].append(b)
    keys = sorted(groups)
    fig, ax = plt.subplots(figsize=(8, 4))
    if keys:
        ax.boxplot([groups[k] for k in keys], tick_labels=[str(k) for k in keys])
    ax.set_xlabel("# subreads")
    ax.set_ylabel("blast identity")
    _savefig(fig, out_path)


def plot_umi_count_hist(counts: dict[str, int], out_path: str,
                        log10_threshold: float | None = None,
                        negative_suffixes=("_v_n", "cdr3j_n", "full_n")):
    """UMI count histogram with negative-control overlay + fit annotations
    (analysis.py:577-811)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    pos = [c for r, c in counts.items() if c > 0 and not r.endswith(negative_suffixes)]
    neg = [c for r, c in counts.items() if c > 0 and r.endswith(negative_suffixes)]
    fig, ax = plt.subplots(figsize=(7, 4))
    bins = np.logspace(0, np.log10(max(pos + neg + [10])), 40)
    ax.hist(pos, bins=bins, alpha=0.7, label="TCR regions")
    if neg:
        ax.hist(neg, bins=bins, alpha=0.7, color="red", label="negative controls")
    if log10_threshold is not None:
        ax.axvline(10**log10_threshold, color="black", linestyle="--",
                   label=f"log10 threshold {log10_threshold}")
    ax.set_xscale("log")
    ax.set_xlabel("UMI count")
    ax.set_ylabel("# regions")
    fits = fit_count_distributions(pos)
    if "ks_nbinom_p" in fits:
        ax.set_title(
            f"NB fit r={fits['nb_r']:.2f} (KS p={fits['ks_nbinom_p']:.3f}); "
            f"normal KS p={fits['ks_normal_p']:.3f}", fontsize=9,
        )
    ax.legend()
    _savefig(fig, out_path)


def plot_percent_alignments_above_blast_id(
    region_blast_rows: list[tuple[str, float]],
    out_path: str,
    minimal_blast_id: float | None = None,
    quantile_95_blast_id: float | None = None,
    percent_correct_overlap_length: float | None = None,
):
    """Percent-of-alignments blast-id histogram in the precision band
    (analysis.py:328-390: 0.0001-wide bins over [0.995, 1.0], bar heights
    as % of all alignments, red/blue threshold lines for the all-TCR and
    95%-of-TCR precision bars)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    vals = np.asarray([b for _, b in region_blast_rows], np.float64)
    bins = np.arange(0.995, 1.0002, 0.0001)
    hist, edges = np.histogram(vals, bins=bins)
    pct = (hist / max(len(vals), 1)) * 100.0
    fig, ax = plt.subplots(figsize=(8, 4))
    ax.bar(edges[:-1], pct, width=np.diff(edges), color="black", alpha=0.25,
           edgecolor="none", align="edge")
    if minimal_blast_id is not None:
        ax.axvline(minimal_blast_id, color="red", linewidth=0.75,
                   label="Required minimal blast identity\nto distinguish all TCRs")
    if quantile_95_blast_id is not None:
        ax.axvline(quantile_95_blast_id, color="blue", linewidth=0.75,
                   label="Required minimal blast identity\nto distinguish 95% of all TCRs")
    ax.set_xlim(0.995, 1.001)
    ax.set_xlabel("Blast identity with reference", fontsize=8)
    ax.set_ylabel("% of all TCR alignments\nwith correct overlap length", fontsize=8)
    if percent_correct_overlap_length is not None:
        ax.set_title(
            f"{round(percent_correct_overlap_length, 2)}% of all TCR alignments"
            "\nhave correct overlap length", fontsize=8,
        )
    if minimal_blast_id is not None or quantile_95_blast_id is not None:
        ax.legend(fontsize=8, loc="center left", bbox_to_anchor=(1, 0.5))
    _savefig(fig, out_path)


def plot_log_transformed_umi_counts_hist(
    counts: dict[str, int],
    out_path: str,
    most_similar_regions: set[str] | None = None,
    log_umi_counts_filter_threshold: float | None = None,
    plot_normal_dist_fit: bool = True,
    plot_percentiles: bool = True,
    title: str | None = None,
) -> dict[str, float]:
    """Log-transformed UMI-count histogram with normal fit + percentile
    lines (analysis.py:660-811). ``most_similar_regions`` overlays the
    near-homolog subset (the reference filters its most-similar-region dict
    at blast id > 0.99925); the title carries the log10 95th/5th percentile
    spread like the reference. Returns the fit stats."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    from scipy import stats as sps

    pos = {r: c for r, c in counts.items() if c > 0}
    logs = np.log(np.asarray(list(pos.values()), np.float64))
    out: dict[str, float] = {"n": float(logs.size)}
    fig, ax = plt.subplots(figsize=(7, 4))
    if logs.size:
        xmax = float(logs.max()) * 1.1 + 0.5
        bins = np.arange(0, xmax, max(xmax / 40, 0.05))
        ax.hist(logs, bins=bins, density=True, alpha=0.25, color="black",
                edgecolor="none", zorder=4, label="All TCRs")
        if most_similar_regions:
            sim = np.log(np.asarray(
                [c for r, c in pos.items() if r in most_similar_regions],
                np.float64,
            ))
            if sim.size:
                ax.hist(sim, bins=bins, density=True, alpha=0.25, color="red",
                        edgecolor="none", zorder=4, label="Most similar TCRs")
        if log_umi_counts_filter_threshold is not None:
            ax.axvline(log_umi_counts_filter_threshold, color="orange",
                       zorder=6, label="Filter threshold")
        if plot_percentiles:
            ax.axvline(np.quantile(logs, 0.05), color="yellow", zorder=6,
                       label="5th percentile")
            ax.axvline(np.median(logs), color="blue", zorder=6, label="median")
            ax.axvline(np.quantile(logs, 0.95), color="black", zorder=6,
                       label="95th percentile")
        spread = float(
            np.log10(np.quantile(list(pos.values()), 0.95))
            - np.log10(np.quantile(list(pos.values()), 0.05))
        )
        out["log10_diff_95th_5th"] = round(spread, 2)
        ax.set_title(
            f"{title or ''}\nlog10 diff. 95th vs 5th percentile = "
            f"{round(spread, 2)}", fontsize=8,
        )
        if plot_normal_dist_fit and logs.size >= 3:
            mean, std = float(logs.mean()), float(logs.std())
            ks = sps.kstest(logs, "norm", args=(mean, max(std, 1e-9)))
            out["ks_normal_stat"] = float(ks.statistic)
            out["ks_normal_p"] = float(ks.pvalue)
            x = np.linspace(logs.min(), logs.max(), 100)
            ax.plot(x, sps.norm.pdf(x, mean, std), "r-",
                    label="Fitted\nNormal Distribution")
    ax.set_xlabel("log(TCR UMI counts)", fontsize=8)
    ax.set_ylabel("Density", fontsize=8)
    ax.legend(fontsize=7, loc="center left", bbox_to_anchor=(1, 0.5))
    _savefig(fig, out_path)
    return out


_PLATE_ROWS = "ABCDEFGHIJKLMNOP"  # 384-well plate: 16 rows x 24 columns


def parse_plate_well(region_name: str) -> tuple[int, int, int] | None:
    """Region name -> (plate, row, col) for 384-well layouts.

    The reference's TCR names embed plate + well as fields 1 and 2 of the
    underscore-split name, e.g. ``TCR_3_B07_...`` -> plate 3, well B07
    (analysis.py:921-926: ``ref.split("_")[1] + "_" + ref.split("_")[2]``).
    Returns None when the name doesn't carry a parseable plate/well.
    """
    parts = region_name.split("_")
    if len(parts) < 3:
        return None
    try:
        plate = int(parts[1])
    except ValueError:
        return None
    well = parts[2]
    if not well or well[0].upper() not in _PLATE_ROWS:
        return None
    try:
        col = int(well[1:])
    except ValueError:
        return None
    if not (1 <= col <= 24):
        return None
    return plate, _PLATE_ROWS.index(well[0].upper()), col - 1


def plot_plate_heatmap(counts: dict[str, int], out_path: str,
                       reference_regions: set[str] | None = None,
                       rows: int = 16, cols: int = 24):
    """384-well plate heatmaps (analysis.py:914-993).

    Region names carrying plate/well ids (:func:`parse_plate_well`) get one
    log-count heatmap per plate — wells absent from the reference are NaN,
    present-but-undetected wells are 0 (the reference's semantics). Names
    without well ids fall back to a single sorted-order grid.
    ``out_path`` is used as-is for the fallback, and with ``_plate<N>``
    inserted before the extension per real plate.
    """
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    ref_names = reference_regions if reference_regions is not None else set(counts)
    placed = {n: parse_plate_well(n) for n in ref_names}
    parseable = {n: p for n, p in placed.items() if p is not None}

    if not parseable:
        grid = np.full((rows, cols), np.nan)
        for i, region in enumerate(sorted(counts)):
            if i >= rows * cols:
                break
            grid[i // cols, i % cols] = counts[region]
        fig, ax = plt.subplots(figsize=(10, 6))
        im = ax.imshow(grid, aspect="auto", cmap="viridis")
        fig.colorbar(im, ax=ax, label="UMI count")
        ax.set_xlabel("plate column")
        ax.set_ylabel("plate row")
        _savefig(fig, out_path)
        return

    plates = sorted({p[0] for p in parseable.values()})
    root, ext = os.path.splitext(out_path)
    for plate in plates:
        grid = np.full((len(_PLATE_ROWS), 24), np.nan)
        for name, (pl, i, j) in parseable.items():
            if pl != plate:
                continue
            c = counts.get(name, 0)
            grid[i, j] = np.log10(c) if c > 0 else 0.0
        fig, ax = plt.subplots(figsize=(10, 7))
        im = ax.matshow(grid, cmap="viridis")
        ax.set_xticks(np.arange(24), labels=[str(c + 1) for c in range(24)], fontsize=7)
        ax.set_yticks(np.arange(len(_PLATE_ROWS)), labels=list(_PLATE_ROWS), fontsize=7)
        ax.set_title(f"Plate: {plate}", pad=20)
        fig.colorbar(im, ax=ax, fraction=0.02, pad=0.03,
                     label="Log transformed\nUMI count")
        _savefig(fig, f"{root}_plate{plate}{ext}")


# ---------------------------------------------------------------------------
# V-gene composition plots (analysis.py:996-1232)


def load_tcr_refs_csv(path: str,
                      name_col: str = "name",
                      trav_col: str = "TRAV_IMGT_allele_collapsed",
                      trbv_col: str = "TRBV_IMGT_allele_collapsed") -> dict[str, dict[str, str]]:
    """TCR metadata table: name -> {TRAV, TRBV} (the tcr_refs_df input of
    the reference's V-gene plots)."""
    import csv

    out: dict[str, dict[str, str]] = {}
    with open(path) as fh:
        for row in csv.DictReader(fh):
            name = row.get(name_col, "").strip()
            if name:
                out[name] = {
                    "TRAV": row.get(trav_col, "").strip(),
                    "TRBV": row.get(trbv_col, "").strip(),
                }
    return out


def v_gene_fold_change(counts: dict[str, int], tcr_refs: dict[str, dict[str, str]],
                       gene: str) -> dict[str, float]:
    """Per-V-allele fold change of output fraction over input composition
    (analysis.py:1010-1035): detected fraction of counts per allele divided
    by the allele's share of the reference library."""
    input_counts: dict[str, int] = defaultdict(int)
    for meta in tcr_refs.values():
        if meta.get(gene):
            input_counts[meta[gene]] += 1
    total_input = sum(input_counts.values())
    out_frac: dict[str, float] = defaultdict(float)
    total_counts = sum(counts.get(n, 0) for n in tcr_refs)
    for name, meta in tcr_refs.items():
        if meta.get(gene) and total_counts:
            out_frac[meta[gene]] += counts.get(name, 0) / total_counts
    return {
        allele: (out_frac.get(allele, 0.0) / (n / total_input)) if total_input else 0.0
        for allele, n in input_counts.items()
    }


def plot_v_gene_fold_change(counts: dict[str, int],
                            tcr_refs: dict[str, dict[str, str]],
                            out_dir: str, title: str | None = None):
    """TRAV/TRBV fold-change-over-input barplots, median-normalized
    (analysis.py:996-1117; same output filenames)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    for gene in ("TRAV", "TRBV"):
        fc = v_gene_fold_change(counts, tcr_refs, gene)
        if not fc:
            continue
        items = sorted(fc.items(), key=lambda kv: -kv[1])
        vals = np.array([v for _, v in items], dtype=float)
        med = np.median(vals[vals > 0]) if (vals > 0).any() else 1.0
        fig, ax = plt.subplots(figsize=(max(6, len(items) / 4), 4))
        ax.bar(np.arange(len(items)), vals / (med or 1.0),
               edgecolor="black", linewidth=0.5, color="lightblue")
        ax.axhline(1, color="red", linewidth=0.75)
        ax.set_xticks(np.arange(len(items)))
        ax.set_xticklabels([a for a, _ in items], rotation=90, fontsize=7)
        ax.set_ylabel("Fold change over input\n(normalized to median)", fontsize=8)
        if title:
            ax.set_title(title, fontsize=8)
        _savefig(fig, os.path.join(
            out_dir, f"{gene}_fold_change_over_input_barplot.pdf"
        ))


def plot_v_gene_missing_tcrs(counts: dict[str, int],
                             tcr_refs: dict[str, dict[str, str]],
                             reference_regions: set[str],
                             out_dir: str, title: str | None = None):
    """V-allele distribution of undetected TCRs (analysis.py:1120-1232;
    same output filenames). Returns the missing set."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    detected = {r for r, c in counts.items() if c > 0}
    missing = sorted(set(reference_regions) & set(tcr_refs) - detected)
    if not missing:
        return []
    for gene in ("TRAV", "TRBV"):
        counter: dict[str, int] = defaultdict(int)
        for name in missing:
            allele = tcr_refs[name].get(gene)
            if allele:
                counter[allele] += 1
        if not counter:
            continue
        items = sorted(counter.items(), key=lambda kv: -kv[1])
        total = sum(v for _, v in items)
        fig, ax = plt.subplots(figsize=(max(4, len(items) / 1.5), 4))
        ax.bar(np.arange(len(items)), [v / total for _, v in items],
               edgecolor="black", linewidth=0.5, color="lightblue")
        ax.set_ylim(0, 1)
        ax.set_xticks(np.arange(len(items)))
        ax.set_xticklabels([a for a, _ in items], rotation=90, fontsize=7)
        ax.set_ylabel("Fraction of missing TCRs", fontsize=8)
        ax.set_title(f"{title or ''}, # missing TCRs = {total}", fontsize=8)
        _savefig(fig, os.path.join(out_dir, f"{gene}_counter_missing_tcr_barplot.pdf"))
    return missing


# ---------------------------------------------------------------------------
# per-library driver (notebook cell 3 analogue)


def run_library_analysis(
    library_dir: str,
    reference_regions: set[str],
    out_dir: str | None = None,
    log10_threshold: float | None = None,
    tcr_refs: dict[str, dict[str, str]] | None = None,
) -> dict[str, float]:
    """Produce the per-library outs/ PDFs + results_summary.txt."""
    out_dir = out_dir or os.path.join(library_dir, "outs")
    os.makedirs(out_dir, exist_ok=True)
    logs = os.path.join(library_dir, "logs")
    counts = read_counts_csv(os.path.join(library_dir, "counts", "umi_consensus_counts.csv"))

    blast_csv = os.path.join(logs, "merged_consensus_region_blast_id.csv")
    if os.path.exists(blast_csv):
        rows = read_two_column_csv(blast_csv)
        plot_blast_id_hist(rows, os.path.join(out_dir, "blast_id_hist.pdf"))
        # precision-band percent hist (analysis.py:328-390): thresholds from
        # the filter log + the run-level self-homology log
        flog = os.path.join(logs, "merged_consensus_bam_filter.log")
        fstats = (
            parse_merged_consensus_bam_filter_log(flog)
            if os.path.exists(flog) else {}
        )
        pct = None
        if fstats.get("n_primary"):
            pct = 100.0 * fstats.get("n_correct_len", 0) / fstats["n_primary"]
        hlog = os.path.join(
            os.path.dirname(library_dir),
            "ref_homology_out_generate_region_split_dict.log",
        )
        q95 = (
            parse_quantile_95_blast_id_from_self_homology_log(hlog)
            if os.path.exists(hlog) else None
        )
        plot_percent_alignments_above_blast_id(
            rows, os.path.join(out_dir, "precision_blast_id_hist.pdf"),
            minimal_blast_id=fstats.get("blast_id_threshold"),
            quantile_95_blast_id=q95,
            percent_correct_overlap_length=pct,
        )
    short_csv = os.path.join(logs, "merged_consensus_region_nt_too_short.csv")
    long_csv = os.path.join(logs, "merged_consensus_region_nt_too_long.csv")
    if os.path.exists(short_csv) and os.path.exists(long_csv):
        plot_nt_length_deviation_hists(
            read_two_column_csv(short_csv), read_two_column_csv(long_csv),
            os.path.join(out_dir, "nt_length_deviation.pdf"),
        )
    sub_csv = os.path.join(logs, "merged_consensus_number_of_subreads_blast_id.csv")
    if os.path.exists(sub_csv):
        rows = read_two_column_csv(sub_csv)
        plot_subreads_per_umi_hist(rows, os.path.join(out_dir, "subreads_per_umi.pdf"))
        plot_blast_id_vs_subreads_box(rows, os.path.join(out_dir, "blast_id_vs_subreads.pdf"))
        # precision-vs-depth report (minimap2_align.py:362-435 analogue, fed
        # by the pipeline's own subreads/blast-id artifact)
        per_depth = estimate_precision_at_num_subreads(rows)
        with open(os.path.join(out_dir, "precision_at_num_subreads.tsv"), "w") as fh:
            fh.write("num_subreads\tn_consensus\tn_perfect\tprecision\n")
            for n, st in per_depth.items():
                fh.write(
                    f"{n}\t{st['n_consensus']:.0f}\t{st['n_perfect']:.0f}"
                    f"\t{st['precision']:.6f}\n"
                )
    plot_umi_count_hist(counts, os.path.join(out_dir, "umi_count_hist.pdf"),
                        log10_threshold=log10_threshold)
    # log-transformed hist with the most-similar overlay (analysis.py:660-811)
    most_similar_json = os.path.join(
        os.path.dirname(library_dir),
        "ref_homology_out_most_similar_region_dict.json",
    )
    most_similar: set[str] | None = None
    if os.path.exists(most_similar_json):
        import json as _json

        with open(most_similar_json) as fh:
            sim_map = _json.load(fh)
        most_similar = {
            region for region, bids in sim_map.items()
            if bids and max(bids) > 0.99925
        }
    plot_log_transformed_umi_counts_hist(
        counts, os.path.join(out_dir, "log_transformed_umi_counts_hist.pdf"),
        most_similar_regions=most_similar,
        log_umi_counts_filter_threshold=log10_threshold,
    )
    plot_plate_heatmap(counts, os.path.join(out_dir, "plate_heatmap.pdf"),
                       reference_regions=reference_regions)
    if tcr_refs:
        plot_v_gene_fold_change(counts, tcr_refs, out_dir)
        plot_v_gene_missing_tcrs(counts, tcr_refs, reference_regions, out_dir)
    return write_results_summary(
        counts, reference_regions,
        os.path.join(out_dir, "results_summary.txt"),
        log10_threshold=log10_threshold,
    )


def read_libraries_csv(path: str) -> dict[str, dict]:
    """libraries.csv (ref README.md:62-82): barcode -> {library_name,
    ref_library_name, log_umi_counts_filter_threshold}."""
    out: dict[str, dict] = {}
    with open(path) as fh:
        next(fh, None)
        for line in fh:
            parts = [p.strip() for p in line.split(",")]
            if len(parts) < 4 or not parts[0]:
                continue
            try:
                thr = float(parts[3])
            except ValueError:
                thr = None
            out[parts[0]] = {
                "library_name": parts[1],
                "ref_library_name": parts[2],
                "log_umi_counts_filter_threshold": thr,
            }
    return out


def run_all_libraries(nano_dir: str, reference_regions,
                      libraries_csv: str | None = None,
                      tcr_refs_csv: str | None = None) -> dict[str, dict]:
    """Loop all per-library dirs (notebook cells 1+3).

    ``reference_regions`` is either one region-name set applied everywhere
    or a dict keyed by ``ref_library_name`` — the per-library reference
    mapping of ``libraries.csv`` (ref README.md:62-82: barcode,
    library_name, ref_library_name, log_umi_counts_filter_threshold).
    Output summaries are keyed ``<barcode>_<library_name>`` like the
    notebook's outs/ directories. ``tcr_refs_csv`` enables the V-gene
    composition plots."""
    meta = read_libraries_csv(libraries_csv) if libraries_csv and os.path.exists(
        libraries_csv
    ) else {}
    tcr_refs = load_tcr_refs_csv(tcr_refs_csv) if tcr_refs_csv and os.path.exists(
        tcr_refs_csv
    ) else None
    out = {}
    for name in sorted(os.listdir(nano_dir)):
        lib_dir = os.path.join(nano_dir, name)
        if not os.path.isdir(os.path.join(lib_dir, "counts")):
            continue
        m = meta.get(name, {})
        regions = reference_regions
        if isinstance(reference_regions, dict):
            regions = reference_regions.get(
                m.get("ref_library_name", ""), set()
            ) or set().union(*reference_regions.values())
        key = f"{name}_{m['library_name']}" if m.get("library_name") else name
        out[key] = run_library_analysis(
            lib_dir, regions,
            out_dir=os.path.join(lib_dir, "outs") if not m.get("library_name")
            else os.path.join(lib_dir, "outs", key),
            log10_threshold=m.get("log_umi_counts_filter_threshold"),
            tcr_refs=tcr_refs,
        )
    return out
