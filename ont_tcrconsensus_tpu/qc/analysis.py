"""Post-hoc QC analysis and reporting.

Rebuild of the reference's analysis layer (/root/reference/
ont_tcr_consensus/analysis.py, 1232 LoC) and its driver notebook
(notebooks/analysis.ipynb): log parsers, count transforms, distribution
fits, sensitivity summaries and the plot set, writing per-library PDFs
under ``outs/``. Parsers target THIS framework's artifact formats (which
keep the reference's filenames); each function cites its reference
analogue.
"""

from __future__ import annotations

import os
from collections import defaultdict

import numpy as np

# matplotlib is imported lazily inside plot functions so headless/quick runs
# never pay for it


# ---------------------------------------------------------------------------
# parsers (analysis.py:76-114 analogues, reading our log formats)


def parse_merged_consensus_bam_filter_log(log_path: str) -> dict[str, float]:
    """Key/value parse of merged_consensus_bam_filter.log
    (analysis.py:84-105 parses the reference log by line index; ours parses
    by label so reordering cannot silently break it)."""
    out: dict[str, float] = {}
    labels = {
        "Total # primary alignments": "n_primary",
        "# primary alignments with allowed length": "n_correct_len",
        "# alignments too short": "n_short",
        "# alignments too long": "n_long",
        "# written alignments passing blast id filter": "n_written",
        "- minimal region overlap": "minimal_region_overlap",
        "- minimal blast identity with reference": "blast_id_threshold",
    }
    with open(log_path) as fh:
        for line in fh:
            for label, key in labels.items():
                if line.startswith(label):
                    out[key] = float(line.rstrip().rsplit(":", 1)[1])
    return out


def parse_quantile_95_blast_id_from_self_homology_log(log_path: str) -> float | None:
    """analysis.py:108-114 analogue."""
    with open(log_path) as fh:
        for line in fh:
            if line.startswith("0.950 quantile blast identity"):
                return float(line.rstrip().rsplit(":", 1)[1])
    return None


def read_counts_csv(path: str) -> dict[str, int]:
    out: dict[str, int] = {}
    with open(path) as fh:
        next(fh, None)
        for line in fh:
            region, _, count = line.rstrip("\n").rpartition(",")
            if region:
                out[region] = int(count)
    return out


def read_two_column_csv(path: str) -> list[tuple[str, float]]:
    rows = []
    with open(path) as fh:
        next(fh, None)
        for line in fh:
            a, _, b = line.rstrip("\n").rpartition(",")
            if a:
                rows.append((a, float(b)))
    return rows


# ---------------------------------------------------------------------------
# count transforms (analysis.py:560-574)


def filter_counts_on_log_umi_count_threshold(
    counts: dict[str, int], log10_threshold: float
) -> dict[str, int]:
    """Keep regions with log10(count) >= threshold (analysis.py:573)."""
    return {
        region: c for region, c in counts.items()
        if c > 0 and np.log10(c) >= log10_threshold
    }


def negative_control_counts(
    counts: dict[str, int],
    suffixes: tuple[str, ...] = ("_v_n", "cdr3j_n", "full_n"),
) -> dict[str, int]:
    """Spiked-negative-control subset (analysis.py:53-73)."""
    return {r: c for r, c in counts.items() if r.endswith(suffixes)}


def fit_count_distributions(counts: list[int]) -> dict[str, float]:
    """Negative-binomial + normal fits with KS tests (analysis.py:577-811).

    The NB is moment-fit (r from mean/variance); KS p-values quantify how
    well each family explains the per-region UMI count spread.
    """
    from scipy import stats as sps

    x = np.asarray([c for c in counts if c > 0], dtype=np.float64)
    out: dict[str, float] = {"n": float(x.size)}
    if x.size < 3:
        return out
    mean, var = float(x.mean()), float(x.var(ddof=1))
    out["mean"] = mean
    out["var"] = var
    # normal fit
    ks_norm = sps.kstest(x, "norm", args=(mean, max(np.sqrt(var), 1e-9)))
    out["ks_normal_p"] = float(ks_norm.pvalue)
    # negative binomial via moments (var > mean required)
    if var > mean:
        r = mean**2 / (var - mean)
        p = r / (r + mean)
        out["nb_r"] = float(r)
        out["nb_p"] = float(p)
        nb = sps.nbinom(r, p)
        ks_nb = sps.kstest(x, nb.cdf)
        out["ks_nbinom_p"] = float(ks_nb.pvalue)
    return out


def estimate_precision_at_num_subreads(
    subread_blast_rows: list[tuple[str, float]],
    perfect_id: float = 1.0,
) -> dict[int, dict[str, float]]:
    """Consensus precision as a function of UMI cluster depth
    (minimap2_align.py:362-435, offline tool).

    For each subread count: how many consensus sequences exist, and what
    fraction align to the reference with blast identity >= ``perfect_id``.
    """
    per_depth: dict[int, list[float]] = defaultdict(list)
    for n, blast_id in subread_blast_rows:
        if str(n).isdigit():
            per_depth[int(n)].append(blast_id)
    return {
        n: {
            "n_consensus": len(ids),
            "n_perfect": sum(1 for b in ids if b >= perfect_id),
            "precision": sum(1 for b in ids if b >= perfect_id) / len(ids),
        }
        for n, ids in sorted(per_depth.items())
    }


# ---------------------------------------------------------------------------
# summary / sensitivity (analysis.py:814-911)


def write_results_summary(
    counts: dict[str, int],
    reference_regions: set[str],
    out_path: str,
    log10_threshold: float | None = None,
    negative_suffixes: tuple[str, ...] = ("_v_n", "cdr3j_n", "full_n"),
) -> dict[str, float]:
    """Sensitivity vs reference + negative-control leakage report."""
    countable = {r for r in reference_regions if not r.endswith(negative_suffixes)}
    detected = {r for r, c in counts.items() if c > 0 and not r.endswith(negative_suffixes)}
    filtered = (
        filter_counts_on_log_umi_count_threshold(counts, log10_threshold)
        if log10_threshold is not None else counts
    )
    detected_filtered = {
        r for r in filtered if not r.endswith(negative_suffixes)
    }
    ncs = negative_control_counts(counts, negative_suffixes)
    summary = {
        "num_reference_regions": len(countable),
        "num_detected": len(countable & detected),
        "sensitivity": (len(countable & detected) / len(countable)) if countable else 0.0,
        "num_detected_after_threshold": len(countable & detected_filtered),
        "num_negative_controls_with_counts": sum(1 for c in ncs.values() if c > 0),
        "total_negative_control_counts": sum(ncs.values()),
        "total_umi_counts": sum(counts.values()),
    }
    missing = sorted(countable - detected)
    with open(out_path, "w") as fh:
        for k, v in summary.items():
            fh.write(f"{k}: {v}\n")
        fh.write(f"missing_regions ({len(missing)}): {missing}\n")
    return summary


# ---------------------------------------------------------------------------
# plots (analysis.py:117-557, 577-811, 914-1232) — matplotlib PDFs


def _savefig(fig, out_path):
    fig.tight_layout()
    fig.savefig(out_path)
    import matplotlib.pyplot as plt

    plt.close(fig)


def plot_blast_id_hist(region_blast_rows: list[tuple[str, float]], out_path: str,
                       threshold: float | None = None):
    """Consensus blast-id distribution (analysis.py:117-228)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    vals = [b for _, b in region_blast_rows]
    fig, ax = plt.subplots(figsize=(6, 4))
    ax.hist(vals, bins=60)
    if threshold is not None:
        ax.axvline(threshold, color="red", linestyle="--", label=f"threshold {threshold:.4f}")
        ax.legend()
    ax.set_xlabel("blast identity vs reference")
    ax.set_ylabel("# consensus sequences")
    _savefig(fig, out_path)


def plot_nt_length_deviation_hists(short_rows, long_rows, out_path: str):
    """Too-short / too-long alignment histograms (analysis.py:231-325)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, axes = plt.subplots(1, 2, figsize=(10, 4))
    axes[0].hist([v for _, v in short_rows], bins=40)
    axes[0].set_xlabel("nt short of minimal overlap")
    axes[1].hist([v for _, v in long_rows], bins=40)
    axes[1].set_xlabel("nt past maximal length")
    for ax in axes:
        ax.set_ylabel("# alignments")
    _savefig(fig, out_path)


def plot_subreads_per_umi_hist(subread_rows: list[tuple[str, float]], out_path: str):
    """Subreads-per-UMI histogram (analysis.py:393-434)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    ns = [int(n) for n, _ in subread_rows if str(n).isdigit()]
    fig, ax = plt.subplots(figsize=(6, 4))
    ax.hist(ns, bins=np.arange(0.5, (max(ns) if ns else 1) + 1.5))
    ax.set_xlabel("# subreads per UMI cluster")
    ax.set_ylabel("# clusters")
    _savefig(fig, out_path)


def plot_blast_id_vs_subreads_box(subread_rows: list[tuple[str, float]], out_path: str):
    """Blast-id-vs-subreads boxplots (analysis.py:437-557)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    groups: dict[int, list[float]] = defaultdict(list)
    for n, b in subread_rows:
        if str(n).isdigit():
            groups[int(n)].append(b)
    keys = sorted(groups)
    fig, ax = plt.subplots(figsize=(8, 4))
    if keys:
        ax.boxplot([groups[k] for k in keys], tick_labels=[str(k) for k in keys])
    ax.set_xlabel("# subreads")
    ax.set_ylabel("blast identity")
    _savefig(fig, out_path)


def plot_umi_count_hist(counts: dict[str, int], out_path: str,
                        log10_threshold: float | None = None,
                        negative_suffixes=("_v_n", "cdr3j_n", "full_n")):
    """UMI count histogram with negative-control overlay + fit annotations
    (analysis.py:577-811)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    pos = [c for r, c in counts.items() if c > 0 and not r.endswith(negative_suffixes)]
    neg = [c for r, c in counts.items() if c > 0 and r.endswith(negative_suffixes)]
    fig, ax = plt.subplots(figsize=(7, 4))
    bins = np.logspace(0, np.log10(max(pos + neg + [10])), 40)
    ax.hist(pos, bins=bins, alpha=0.7, label="TCR regions")
    if neg:
        ax.hist(neg, bins=bins, alpha=0.7, color="red", label="negative controls")
    if log10_threshold is not None:
        ax.axvline(10**log10_threshold, color="black", linestyle="--",
                   label=f"log10 threshold {log10_threshold}")
    ax.set_xscale("log")
    ax.set_xlabel("UMI count")
    ax.set_ylabel("# regions")
    fits = fit_count_distributions(pos)
    if "ks_nbinom_p" in fits:
        ax.set_title(
            f"NB fit r={fits['nb_r']:.2f} (KS p={fits['ks_nbinom_p']:.3f}); "
            f"normal KS p={fits['ks_normal_p']:.3f}", fontsize=9,
        )
    ax.legend()
    _savefig(fig, out_path)


def plot_plate_heatmap(counts: dict[str, int], out_path: str,
                       rows: int = 16, cols: int = 24):
    """384-well plate heatmap (analysis.py:914-993). Region names are mapped
    to wells in sorted order when they don't carry explicit well ids."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    grid = np.full((rows, cols), np.nan)
    for i, region in enumerate(sorted(counts)):
        if i >= rows * cols:
            break
        grid[i // cols, i % cols] = counts[region]
    fig, ax = plt.subplots(figsize=(10, 6))
    im = ax.imshow(grid, aspect="auto", cmap="viridis")
    fig.colorbar(im, ax=ax, label="UMI count")
    ax.set_xlabel("plate column")
    ax.set_ylabel("plate row")
    _savefig(fig, out_path)


# ---------------------------------------------------------------------------
# per-library driver (notebook cell 3 analogue)


def run_library_analysis(
    library_dir: str,
    reference_regions: set[str],
    out_dir: str | None = None,
    log10_threshold: float | None = None,
) -> dict[str, float]:
    """Produce the per-library outs/ PDFs + results_summary.txt."""
    out_dir = out_dir or os.path.join(library_dir, "outs")
    os.makedirs(out_dir, exist_ok=True)
    logs = os.path.join(library_dir, "logs")
    counts = read_counts_csv(os.path.join(library_dir, "counts", "umi_consensus_counts.csv"))

    blast_csv = os.path.join(logs, "merged_consensus_region_blast_id.csv")
    if os.path.exists(blast_csv):
        rows = read_two_column_csv(blast_csv)
        plot_blast_id_hist(rows, os.path.join(out_dir, "blast_id_hist.pdf"))
    short_csv = os.path.join(logs, "merged_consensus_region_nt_too_short.csv")
    long_csv = os.path.join(logs, "merged_consensus_region_nt_too_long.csv")
    if os.path.exists(short_csv) and os.path.exists(long_csv):
        plot_nt_length_deviation_hists(
            read_two_column_csv(short_csv), read_two_column_csv(long_csv),
            os.path.join(out_dir, "nt_length_deviation.pdf"),
        )
    sub_csv = os.path.join(logs, "merged_consensus_number_of_subreads_blast_id.csv")
    if os.path.exists(sub_csv):
        rows = read_two_column_csv(sub_csv)
        plot_subreads_per_umi_hist(rows, os.path.join(out_dir, "subreads_per_umi.pdf"))
        plot_blast_id_vs_subreads_box(rows, os.path.join(out_dir, "blast_id_vs_subreads.pdf"))
    plot_umi_count_hist(counts, os.path.join(out_dir, "umi_count_hist.pdf"),
                        log10_threshold=log10_threshold)
    plot_plate_heatmap(counts, os.path.join(out_dir, "plate_heatmap.pdf"))
    return write_results_summary(
        counts, reference_regions,
        os.path.join(out_dir, "results_summary.txt"),
        log10_threshold=log10_threshold,
    )


def run_all_libraries(nano_dir: str, reference_regions: set[str],
                      libraries_csv: str | None = None) -> dict[str, dict]:
    """Loop all per-library dirs (notebook cells 1+3).

    ``libraries.csv`` (README.md:62-82) columns: barcode, library_name,
    ref_library_name, log_umi_counts_filter_threshold. Absent -> every
    library dir under nano_dir with no threshold."""
    thresholds: dict[str, float | None] = {}
    if libraries_csv and os.path.exists(libraries_csv):
        with open(libraries_csv) as fh:
            next(fh, None)
            for line in fh:
                parts = [p.strip() for p in line.split(",")]
                if len(parts) >= 4 and parts[0]:
                    try:
                        thresholds[parts[0]] = float(parts[3])
                    except ValueError:
                        thresholds[parts[0]] = None
    out = {}
    for name in sorted(os.listdir(nano_dir)):
        lib_dir = os.path.join(nano_dir, name)
        if not os.path.isdir(os.path.join(lib_dir, "counts")):
            continue
        out[name] = run_library_analysis(
            lib_dir, reference_regions, log10_threshold=thresholds.get(name)
        )
    return out
