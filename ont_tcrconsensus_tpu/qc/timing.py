"""Per-stage wall-clock accounting.

The reference has no tracing at all (SURVEY §5: only stderr narration); this
gives every pipeline run a ``stage_timing.tsv`` artifact so perf work has a
breakdown to aim at, and ``bench.py`` can print where time goes.

Every ``stage()`` scope measures THROUGH an :mod:`obs.trace` span: the one
duration computed at span exit feeds this table, the run-level
``telemetry.json`` stage roll-up, and (at ``telemetry: full``) the
``trace.json`` timeline row — one clock read, three views that cannot
disagree.
"""

from __future__ import annotations

from collections import defaultdict
from contextlib import contextmanager

from ont_tcrconsensus_tpu.obs import trace


class StageTimer:
    """Accumulates wall seconds per named stage (re-entrant across batches)."""

    def __init__(self):
        self.seconds: dict[str, float] = defaultdict(float)
        self.calls: dict[str, int] = defaultdict(int)

    @contextmanager
    def stage(self, name: str):
        sp = trace.span(name)
        try:
            with sp:
                yield
        finally:
            # sp.dur_s was computed in the span's own exit (which already
            # ran, exception or not) — record the identical measurement
            self.seconds[name] += sp.dur_s
            self.calls[name] += 1

    def add(self, name: str, seconds: float) -> None:
        """Record externally-measured seconds (e.g. an overlapped worker's
        wall clock, pipeline/overlap.py) under ``name``."""
        self.seconds[name] += seconds
        self.calls[name] += 1

    def merge(self, other: "StageTimer") -> None:
        for k, v in other.seconds.items():
            self.seconds[k] += v
            self.calls[k] += other.calls[k]

    def summary(self) -> dict[str, float]:
        return {k: round(v, 3) for k, v in sorted(
            self.seconds.items(), key=lambda kv: -kv[1]
        )}

    def write_tsv(self, path: str) -> None:
        with open(path, "w") as fh:
            fh.write("stage\tseconds\tcalls\n")
            for name, sec in sorted(self.seconds.items(), key=lambda kv: -kv[1]):
                fh.write(f"{name}\t{sec:.3f}\t{self.calls[name]}\n")
