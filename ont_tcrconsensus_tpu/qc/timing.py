"""Per-stage wall-clock accounting.

The reference has no tracing at all (SURVEY §5: only stderr narration); this
gives every pipeline run a ``stage_timing.tsv`` artifact so perf work has a
breakdown to aim at, and ``bench.py`` can print where time goes.
"""

from __future__ import annotations

import time
from collections import defaultdict
from contextlib import contextmanager


class StageTimer:
    """Accumulates wall seconds per named stage (re-entrant across batches)."""

    def __init__(self):
        self.seconds: dict[str, float] = defaultdict(float)
        self.calls: dict[str, int] = defaultdict(int)

    @contextmanager
    def stage(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.seconds[name] += time.perf_counter() - t0
            self.calls[name] += 1

    def add(self, name: str, seconds: float) -> None:
        """Record externally-measured seconds (e.g. an overlapped worker's
        wall clock, pipeline/overlap.py) under ``name``."""
        self.seconds[name] += seconds
        self.calls[name] += 1

    def merge(self, other: "StageTimer") -> None:
        for k, v in other.seconds.items():
            self.seconds[k] += v
            self.calls[k] += other.calls[k]

    def summary(self) -> dict[str, float]:
        return {k: round(v, 3) for k, v in sorted(
            self.seconds.items(), key=lambda kv: -kv[1]
        )}

    def write_tsv(self, path: str) -> None:
        with open(path, "w") as fh:
            fh.write("stage\tseconds\tcalls\n")
            for name, sec in sorted(self.seconds.items(), key=lambda kv: -kv[1]):
                fh.write(f"{name}\t{sec:.3f}\t{self.calls[name]}\n")
