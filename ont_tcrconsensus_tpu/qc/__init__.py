"""qc subpackage."""
