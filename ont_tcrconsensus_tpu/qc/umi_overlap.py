"""Cross-region UMI collision audit.

Replicates ``count_overlapping_umis_between_all_regions``
(/root/reference/ont_tcr_consensus/extract_umis.py:270-369): for every pair
of regions, count round-2 cluster-consensus UMIs appearing in both. The
reference's shipped code compares UMIs by EXACT equality (its fuzzy edlib
variant is commented out, :282-289); we replicate the exact-match semantics
with a hash join — O(total UMIs) instead of O(regions^2 * UMIs^2) of Ray
tasks — and emit the same TSV/stderr artifacts.
"""

from __future__ import annotations

import itertools
import os
from collections import Counter


def count_overlapping_umis(
    region_umis: dict[str, list[str]],
    logs_dir: str,
    overlapping_umi_edit_threshold: int = 1,
) -> list[bool]:
    """region -> cluster UMIs; writes regions_w_overlapping_umis.tsv.

    Returns per-region-pair booleans in ``itertools.combinations`` order,
    matching the reference's return value.
    """
    tsv_path = os.path.join(logs_dir, "regions_w_overlapping_umis.tsv")
    err_path = os.path.join(logs_dir, "region_region_umi_comparison.stderr")

    counters = {region: Counter(umis) for region, umis in region_umis.items()}
    out: list[bool] = []
    tsv_rows: list[str] = []
    warn_rows: list[str] = []
    for r1, r2 in itertools.combinations(region_umis, 2):
        c1, c2 = counters[r1], counters[r2]
        if len(c1) > len(c2):
            c1, c2 = c2, c1
        # reference counts, per region-1 UMI, how many region-2 UMIs equal it
        overlap = sum(n1 * c2.get(umi, 0) for umi, n1 in c1.items())
        multi_warn = any(c2.get(umi, 0) > 1 for umi in c1)
        if multi_warn:
            warn_rows.append(
                f"WARNING: there are UMIs from {r1} that match more than 1 "
                f"UMI within {r2}\n"
            )
        if overlap:
            tsv_rows.append(f"region_{r1}\tregion_{r2}\t{overlap}\n")
        out.append(bool(overlap))

    # single atomic write per call: reruns do not accumulate duplicate
    # headers (unlike the reference's unguarded appends, extract_umis.py:325)
    with open(tsv_path, "w") as fh:
        fh.write("region_1\tregion_2\tumi_overlap_count\n")
        fh.writelines(tsv_rows)
    if warn_rows:
        with open(err_path, "w") as ferr:
            ferr.writelines(warn_rows)
    return out
