"""QC artifact emitters: the reference's in-pipeline empirical QC (SURVEY §4).

Replicates the artifact set of ``filter_consensus_alignments``
(/root/reference/ont_tcr_consensus/minimap2_align.py:167-357): seven CSVs +
a filter log, in the same filenames and column layouts, so the reference's
analysis notebook parsers keep working against this framework's output.
"""

from __future__ import annotations

import os

import numpy as np


def _write_csv(path: str, header: list[str], rows: list[tuple]) -> None:
    with open(path, "w") as fh:
        fh.write(",".join(header) + "\n")
        for row in rows:
            fh.write(",".join(str(x) for x in row) + "\n")


def write_consensus_filter_artifacts(
    qc_rows: list[dict],
    region_lengths: dict[str, int],
    logs_dir: str,
    prefix: str,
    blast_id_threshold: float,
    minimal_region_overlap: float,
) -> dict[str, str]:
    """Emit the 7 QC CSVs + the bam-filter log.

    ``qc_rows`` come from ``stages.assign_reads(collect_qc=...)`` on the
    merged-consensus pass. ``prefix`` mirrors the reference's
    ``<bam basename>`` (e.g. ``merged_consensus``).
    """
    paths = {
        "nt_too_short": os.path.join(logs_dir, f"{prefix}_nt_too_short.csv"),
        "region_nt_too_short": os.path.join(logs_dir, f"{prefix}_region_nt_too_short.csv"),
        "nt_too_long": os.path.join(logs_dir, f"{prefix}_nt_too_long.csv"),
        "region_nt_too_long": os.path.join(logs_dir, f"{prefix}_region_nt_too_long.csv"),
        "blast_id": os.path.join(logs_dir, f"{prefix}_blast_id.csv"),
        "region_blast_id": os.path.join(logs_dir, f"{prefix}_region_blast_id.csv"),
        "num_subreads_blast_id": os.path.join(logs_dir, f"{prefix}_number_of_subreads_blast_id.csv"),
        "log": os.path.join(logs_dir, f"{prefix}_bam_filter.log"),
    }

    short_rows, long_rows, blast_rows, subread_rows = [], [], [], []
    n_primary = n_short = n_long = n_correct_len = n_written = 0
    for row in qc_rows:
        n_primary += 1
        status = row["status"]
        if status == "short":
            n_short += 1
            short_rows.append((row["region"], row["nt_short"]))
            continue
        if status == "long":
            n_long += 1
            long_rows.append((row["region"], row["nt_long"]))
            continue
        n_correct_len += 1
        blast_rows.append((row["region"], row["blast_id"]))
        # consensus names end in _<n_subreads> (medaka_polish.py:146-180)
        num_subreads = row["name"].rsplit("_", 1)[-1]
        subread_rows.append((num_subreads, row["blast_id"]))
        if status == "pass":
            n_written += 1

    _write_csv(paths["region_nt_too_short"], ["region", "number_of_nt"], short_rows)
    _write_csv(paths["nt_too_short"], ["number_of_nt"], [(nt,) for _, nt in short_rows])
    _write_csv(paths["region_nt_too_long"], ["region", "number_of_nt"], long_rows)
    _write_csv(paths["nt_too_long"], ["number_of_nt"], [(nt,) for _, nt in long_rows])
    _write_csv(paths["region_blast_id"], ["region", "blast_id"], blast_rows)
    _write_csv(paths["blast_id"], ["blast_id"], [(b,) for _, b in blast_rows])
    _write_csv(paths["num_subreads_blast_id"], ["number_of_subreads", "blast_id"], subread_rows)

    region_lens = list(region_lengths.values())
    allowed_short = [rl - rl * minimal_region_overlap for rl in region_lens]
    allowed_long = [rl * (2 - minimal_region_overlap) - rl for rl in region_lens]
    allowed_diff = [rl - rl * blast_id_threshold for rl in region_lens]
    with open(paths["log"], "w") as log:
        log.write("Consensus alignment filtering performed with the following parameters:\n")
        log.write(f"- minimal region overlap: {minimal_region_overlap}\n")
        log.write(f"- minimal blast identity with reference: {blast_id_threshold}\n")
        log.write("From these parameters follows:\n")
        log.write(f"- Minimal Phred Q = {round(-10 * np.log10(max(1 - blast_id_threshold, 1e-12)), 2)}\n")
        log.write(f"- Median region nucleotide length: {np.median(region_lens)}\n")
        log.write(f"- Median allowed too few nucleotides/region: {round(np.median(allowed_short), 2)}\n")
        log.write(f"- Median allowed too many nucleotides/region: {round(np.median(allowed_long), 2)}\n")
        log.write(f"- Median allowed nucleotide difference/region: {round(np.median(allowed_diff), 2)}\n")
        log.write(f"Total # primary alignments: {n_primary}\n")
        log.write(f"# primary alignments with allowed length: {n_correct_len}\n")
        log.write(f"# alignments too short: {n_short}\n")
        log.write(f"# alignments too long: {n_long}\n")
        log.write(f"# written alignments passing blast id filter: {n_written}\n")
        if n_primary:
            log.write(f"% written of primary: {round(100 * n_written / n_primary, 2)}\n")
    return paths


def write_region_split_log(
    stats,
    groups: dict,
    store,
    panel_names: list[str],
    region_lengths: dict[str, int],
    negative_suffixes: tuple[str, ...],
    log_path: str,
) -> None:
    """Detection-fraction log of the round-1 split
    (region_split.py:285-331). ``groups`` maps key -> [(block, rows)] into
    the columnar ``store``."""
    per_group_counts = [
        sum(len(rows) for _, rows in parts) for parts in groups.values()
    ]
    detected = set()
    for parts in groups.values():
        for bi, rows in parts:
            detected.update(
                int(i) for i in np.unique(store.blocks[bi].region_idx[rows])
            )
    detected_names = {
        panel_names[i] for i in detected
        if not panel_names[i].endswith(negative_suffixes)
    }
    countable = {n for n in region_lengths if not n.endswith(negative_suffixes)}
    frac = len(countable & detected_names) / len(countable) if countable else 0.0
    missing = sorted(countable - detected_names)
    with open(log_path, "w") as fh:
        fh.write(f"Total # primary alignments in bam file: {stats.n_aligned}\n")
        med = np.median(per_group_counts) if per_group_counts else 0
        fh.write(
            "median # of primary alignments in region clusters that have "
            f"minimal region overlap and are not too long: {round(float(med), 3)}\n"
        )
        if stats.n_aligned:
            fh.write(
                "% of primary alignments that have shorter overlap than minimal region overlap: "
                f"{round(100 * stats.n_short / stats.n_aligned, 2)}\n"
            )
            fh.write(
                "% of primary alignments that have too long reads: "
                f"{round(100 * stats.n_long / stats.n_aligned, 2)}\n"
            )
        fh.write(
            "fraction detected regions of total regions in reference in initial "
            f"non-polished read alignments: {round(frac, 4)}\n"
        )
        fh.write(
            "# of missing regions from reference in initial non-polished read "
            f"alignments: {len(missing)}\n"
        )
        fh.write(
            "missing/non-detected regions from reference in initial non-polished "
            f"read alignments: {set(missing) if missing else 'set()'}\n"
        )


def write_fastq_stats_log(stats, log_path: str) -> None:
    """Before/after filter read stats — the seqkit-stat QC boundary artifact
    (ref preprocessing.py:126-157 runs ``seqkit stat -a`` on the trimmed and
    the filtered fastq; here both aggregates come from the fused pass)."""
    with open(log_path, "w") as fh:
        fh.write("stage\tnum_seqs\tsum_len\tmin_len\tavg_len\tmax_len\tavg_qual\n")
        for name, ls in (("post_trim_pre_filter", stats.pre_filter),
                         ("post_filter_pass", stats.post_filter)):
            fh.write(
                f"{name}\t{ls.n}\t{ls.sum_len}\t{ls.min_len}\t"
                f"{ls.avg_len:.1f}\t{ls.max_len}\t{ls.avg_qual:.2f}\n"
            )


def write_flagstat_log(stats, log_path: str) -> None:
    """Alignment summary — the ``samtools flagstat`` analogue
    (ref minimap2_align.py:152-153). No BAM exists in this framework, so the
    equivalent categories come from the fused pass counters."""
    with open(log_path, "w") as fh:
        fh.write(f"{stats.n_total} in total (reads entering alignment)\n")
        fh.write(f"{stats.n_aligned} primary mapped "
                 f"({_pct(stats.n_aligned, stats.n_total)} : score gate)\n")
        n_unmapped = stats.n_total - stats.n_ee_fail - stats.n_aligned
        fh.write(f"{stats.n_ee_fail} failed EE/length filter "
                 f"({_pct(stats.n_ee_fail, stats.n_total)})\n")
        fh.write(f"{max(n_unmapped, 0)} unmapped "
                 f"({_pct(max(n_unmapped, 0), stats.n_total)})\n")
        fh.write(f"{stats.n_short} mapped too short\n")
        fh.write(f"{stats.n_long} read too long\n")
        fh.write(f"{stats.n_low_blast} below blast-id threshold\n")
        fh.write(f"{stats.n_pass} passing all filters "
                 f"({_pct(stats.n_pass, stats.n_total)})\n")


def _pct(a: int, b: int) -> str:
    return f"{100.0 * a / b:.2f}%" if b else "N/A"


def write_self_homology_log(stats: dict, log_path: str) -> None:
    """Self-homology quantile log (region_split.py:138-165 format)."""
    with open(log_path, "w") as fh:
        fh.write(
            "Homology pairs after prefiltering: "
            f"{stats.get('num_pairs_prefilter', 0)}\n"
        )
        if "median_blast_id" in stats:
            fh.write(f"Median blast identity of most similar regions: {stats['median_blast_id']}\n")
            fh.write(f"0.925 quantile blast identity of most similar regions: {stats['q925_blast_id']}\n")
            fh.write(f"0.950 quantile blast identity of most similar regions: {stats['q950_blast_id']}\n")
            fh.write(f"0.975 quantile blast identity of most similar regions: {stats['q975_blast_id']}\n")
            fh.write(f"0.990 quantile blast identity of most similar regions: {stats['q990_blast_id']}\n")
            fh.write(f"Maximal blast identity of most similar regions: {stats['max_blast_id']}\n")
