"""cs-tag-style alignment difference profiling.

The reference dumps, per alignment pass, the 40 most common minimap2 ``cs``
difference strings with their region and blast-id breakdowns
(/root/reference/ont_tcr_consensus/minimap2_align.py:21-37,140-150) — the
pipeline's error-profile debugging artifact. This framework has no BAM/cs
tags, so the equivalent difference strings are reconstructed host-side with
a banded global alignment of each (sampled) read against the reference span
it aligned to, emitted in cs syntax:

    :N      run of N matches
    *<r><q> substitution (reference base, query base)
    +<seq>  insertion in the query
    -<seq>  deletion from the reference

Profiling is a QC path, not a hot path: it runs on a capped sample
(default 1000 reads/library) with unit-cost edit alignment — the motif
distribution, not base-perfect minimap2 score parity, is the artifact.
"""

from __future__ import annotations

from collections import Counter, defaultdict

import numpy as np

_BASE = "acgtn"  # cs syntax is lowercase


def banded_cs(query: np.ndarray, ref: np.ndarray, band: int = 96) -> str:
    """cs difference string of a banded global alignment (unit costs).

    Args:
      query/ref: dense uint8 code arrays (no padding).
    """
    q = np.asarray(query, dtype=np.int16)
    r = np.asarray(ref, dtype=np.int16)
    n, m = len(q), len(r)
    if n == 0:
        return f"-{''.join(_BASE[c] for c in r)}" if m else ""
    if m == 0:
        return f"+{''.join(_BASE[c] for c in q)}"
    # band around the length-interpolated diagonal
    half = max(band // 2, abs(n - m) + 8)
    BIG = 1 << 20
    # rows: query positions 0..n; per row keep [lo, lo+W) of ref positions
    W = 2 * half + 1
    ptr = np.zeros((n + 1, W), dtype=np.uint8)  # 0 diag, 1 up(q-gap? see below), 2 left
    prev = np.full(W, BIG, dtype=np.int64)
    lo_of = [0] * (n + 1)

    def row_lo(i: int) -> int:
        center = round(i * m / n)
        return max(0, min(center - half, m))

    lo = row_lo(0)
    lo_of[0] = lo
    js = np.arange(lo, min(lo + W, m + 1))
    prev[: len(js)] = js  # D[0][j] = j deletions
    ptr[0, : len(js)] = 2

    for i in range(1, n + 1):
        nlo = row_lo(i)
        lo_of[i] = nlo
        cur = np.full(W, BIG, dtype=np.int64)
        js = np.arange(nlo, min(nlo + W, m + 1))
        k = len(js)
        # shift the previous row into this row's band frame:
        # aligned_prev[t] = prev value at ref position (nlo + t - 1)
        shift = nlo - lo
        aligned_prev = np.full(W + 1, BIG, dtype=np.int64)
        t = np.arange(W + 1)
        src = t + shift - 1
        okm = (src >= 0) & (src < W)
        aligned_prev[okm] = prev[src[okm]]
        diag = aligned_prev[:W]                       # prev row, j-1
        up = aligned_prev[1 : W + 1]                  # prev row, j
        qi = q[i - 1]
        jmask = js >= 1
        rj = r[np.clip(js - 1, 0, m - 1)]
        sub = np.where((rj == qi) & (qi < 4) & (rj < 4), 0, 1)
        d = np.where(jmask[:k], diag[:k] + sub[:k], BIG)
        u = up[:k] + 1
        best = np.minimum(d, u)
        p = np.where(u < d, 1, 0).astype(np.uint8)    # ties prefer diag
        # left (ref-base deletion) chains collapse under unit cost:
        # left[j] = min_{l<j}(best[l] + (j-l)) via a prefix-min cascade
        idx = np.arange(k)
        run_min = np.minimum.accumulate(best - idx)
        left = run_min[np.maximum(idx - 1, 0)] + idx
        left[0] = BIG
        take_left = left < best
        best = np.where(take_left, left, best)
        p = np.where(take_left, 2, p).astype(np.uint8)
        cur[:k] = best
        ptr[i, :k] = p
        prev = cur
        lo = nlo

    # traceback
    i, jpos = n, m
    ops: list[tuple[str, str]] = []  # (op, payload)
    while i > 0 or jpos > 0:
        lo = lo_of[i]
        t = jpos - lo
        if t < 0 or t >= W:
            # fell off the band — bail with a conservative tail
            break
        p = ptr[i, t]
        if i > 0 and jpos > 0 and p == 0:
            qc, rc = q[i - 1], r[jpos - 1]
            if qc == rc and qc < 4:
                ops.append((":", ""))
            else:
                ops.append(("*", _BASE[rc] + _BASE[qc]))
            i -= 1
            jpos -= 1
        elif i > 0 and p == 1:
            ops.append(("+", _BASE[q[i - 1]]))
            i -= 1
        elif jpos > 0:
            ops.append(("-", _BASE[r[jpos - 1]]))
            jpos -= 1
        else:
            ops.append(("+", _BASE[q[i - 1]]))
            i -= 1
    ops.reverse()

    # compress to cs syntax
    out: list[str] = []
    match_run = 0
    k = 0
    while k < len(ops):
        op, payload = ops[k]
        if op == ":":
            match_run += 1
            k += 1
            continue
        if match_run:
            out.append(f":{match_run}")
            match_run = 0
        if op == "*":
            out.append(f"*{payload}")
            k += 1
        else:  # run-collect insertions/deletions
            run = [payload]
            k += 1
            while k < len(ops) and ops[k][0] == op:
                run.append(ops[k][1])
                k += 1
            out.append(op + "".join(run))
    if match_run:
        out.append(f":{match_run}")
    return "".join(out)


def profile_store(store, panel, sample_size: int = 1000, seed: int = 0):
    """cs-tag counters over a read-store sample.

    Returns (tag_counter, tag->region counter, tag->blast_id counter) — the
    same triple the reference builds from the BAM (minimap2_align.py:21-37).
    Reads are profiled in their aligned orientation against the reference
    span recorded by the fused pass.
    """
    from ont_tcrconsensus_tpu.ops import encode

    handles = [
        (bi, r) for bi, blk in enumerate(store.blocks) for r in range(blk.num_reads)
    ]
    rng = np.random.default_rng(seed)
    if len(handles) > sample_size:
        pick = rng.choice(len(handles), size=sample_size, replace=False)
        handles = [handles[int(i)] for i in np.sort(pick)]

    tag_counter: Counter = Counter()
    tag_region: dict[str, Counter] = defaultdict(Counter)
    tag_blast: dict[str, Counter] = defaultdict(Counter)
    for bi, r in handles:
        blk = store.blocks[bi]
        ln = int(blk.lens[r])
        qcodes = blk.codes[r, :ln]
        if blk.is_rev[r]:
            qcodes = encode.revcomp_codes(qcodes)
        ridx = int(blk.region_idx[r])
        rs, re = int(blk.ref_start[r]), int(blk.ref_end[r])
        ref_codes = panel.codes[ridx, rs:re]
        tag = banded_cs(qcodes, ref_codes)
        tag_counter[tag] += 1
        tag_region[tag][panel.names[ridx]] += 1
        tag_blast[tag][round(float(blk.blast_id[r]), 6)] += 1
    return tag_counter, tag_region, tag_blast


def write_error_profile_log(
    tag_counter: Counter, tag_region: dict, tag_blast: dict, log_path: str,
    top_n: int = 40,
) -> None:
    """Reference log format (minimap2_align.py:140-150 sections)."""
    top = tag_counter.most_common(top_n)
    with open(log_path, "w") as fh:
        fh.write(f"\nTop {top_n} most common cs tags:\n")
        for tup in top:
            fh.write(str(tup) + "\n")
        fh.write(
            f"\nTop 4 most common regions counted for each of the top {top_n} "
            "most common cs tags:\n"
        )
        for tag, _ in top:
            fh.write(f"{tag} {tag_region[tag].most_common(4)}\n")
        fh.write(
            f"\nTop 4 most common blast identities counted for each of the top {top_n} "
            "most common cs tags:\n"
        )
        for tag, _ in top:
            fh.write(f"{tag} {tag_blast[tag].most_common(4)}\n")
