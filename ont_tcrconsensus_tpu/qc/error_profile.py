"""cs-tag-style alignment difference profiling.

The reference dumps, per alignment pass, the 40 most common minimap2 ``cs``
difference strings with their region and blast-id breakdowns
(/root/reference/ont_tcr_consensus/minimap2_align.py:21-37,140-150) — the
pipeline's error-profile debugging artifact. This framework has no BAM/cs
tags, so the equivalent difference strings are reconstructed host-side with
a banded global alignment of each (sampled) read against the reference span
it aligned to, emitted in cs syntax:

    :N      run of N matches
    *<r><q> substitution (reference base, query base)
    +<seq>  insertion in the query
    -<seq>  deletion from the reference

Profiling is a QC path, not a hot path: it runs on a capped sample
(default 1000 reads/library) with unit-cost edit alignment — the motif
distribution, not base-perfect minimap2 score parity, is the artifact.
"""

from __future__ import annotations

import functools
from collections import Counter, defaultdict

import jax
import jax.numpy as jnp
import numpy as np

from ont_tcrconsensus_tpu.robustness import watchdog

_BASE = "acgtn"  # cs syntax is lowercase


def banded_cs(query: np.ndarray, ref: np.ndarray, band: int = 96) -> str:
    """cs difference string of a banded global alignment (unit costs).

    Args:
      query/ref: dense uint8 code arrays (no padding).
    """
    q = np.asarray(query, dtype=np.int16)
    r = np.asarray(ref, dtype=np.int16)
    n, m = len(q), len(r)
    if n == 0:
        return f"-{''.join(_BASE[c] for c in r)}" if m else ""
    if m == 0:
        return f"+{''.join(_BASE[c] for c in q)}"
    # band around the length-interpolated diagonal
    half = max(band // 2, abs(n - m) + 8)
    BIG = 1 << 20
    # rows: query positions 0..n; per row keep [lo, lo+W) of ref positions
    W = 2 * half + 1
    ptr = np.zeros((n + 1, W), dtype=np.uint8)  # 0 diag, 1 up(q-gap? see below), 2 left
    prev = np.full(W, BIG, dtype=np.int64)
    lo_of = [0] * (n + 1)

    def row_lo(i: int) -> int:
        center = round(i * m / n)
        return max(0, min(center - half, m))

    lo = row_lo(0)
    lo_of[0] = lo
    js = np.arange(lo, min(lo + W, m + 1))
    prev[: len(js)] = js  # D[0][j] = j deletions
    ptr[0, : len(js)] = 2

    for i in range(1, n + 1):
        nlo = row_lo(i)
        lo_of[i] = nlo
        cur = np.full(W, BIG, dtype=np.int64)
        js = np.arange(nlo, min(nlo + W, m + 1))
        k = len(js)
        # shift the previous row into this row's band frame:
        # aligned_prev[t] = prev value at ref position (nlo + t - 1)
        shift = nlo - lo
        aligned_prev = np.full(W + 1, BIG, dtype=np.int64)
        t = np.arange(W + 1)
        src = t + shift - 1
        okm = (src >= 0) & (src < W)
        aligned_prev[okm] = prev[src[okm]]
        diag = aligned_prev[:W]                       # prev row, j-1
        up = aligned_prev[1 : W + 1]                  # prev row, j
        qi = q[i - 1]
        jmask = js >= 1
        rj = r[np.clip(js - 1, 0, m - 1)]
        sub = np.where((rj == qi) & (qi < 4) & (rj < 4), 0, 1)
        d = np.where(jmask[:k], diag[:k] + sub[:k], BIG)
        u = up[:k] + 1
        best = np.minimum(d, u)
        p = np.where(u < d, 1, 0).astype(np.uint8)    # ties prefer diag
        # left (ref-base deletion) chains collapse under unit cost:
        # left[j] = min_{l<j}(best[l] + (j-l)) via a prefix-min cascade
        idx = np.arange(k)
        run_min = np.minimum.accumulate(best - idx)
        left = run_min[np.maximum(idx - 1, 0)] + idx
        left[0] = BIG
        take_left = left < best
        best = np.where(take_left, left, best)
        p = np.where(take_left, 2, p).astype(np.uint8)
        cur[:k] = best
        ptr[i, :k] = p
        prev = cur
        lo = nlo

    return _traceback_cs(q, r, ptr, lo_of, W)


def _traceback_cs(q, r, ptr, lo_of, W) -> str:
    """Emit the cs string from a filled pointer matrix (shared by the
    single-read and batched fills)."""
    n, m = len(q), len(r)
    i, jpos = n, m
    ops: list[tuple[str, str]] = []  # (op, payload)
    while i > 0 or jpos > 0:
        lo = lo_of[i]
        t = jpos - lo
        if t < 0 or t >= W:
            # fell off the band — bail with a conservative tail
            break
        p = ptr[i, t]
        if i > 0 and jpos > 0 and p == 0:
            qc, rc = q[i - 1], r[jpos - 1]
            if qc == rc and qc < 4:
                ops.append((":", ""))
            else:
                ops.append(("*", _BASE[rc] + _BASE[qc]))
            i -= 1
            jpos -= 1
        elif i > 0 and p == 1:
            ops.append(("+", _BASE[q[i - 1]]))
            i -= 1
        elif jpos > 0:
            ops.append(("-", _BASE[r[jpos - 1]]))
            jpos -= 1
        else:
            ops.append(("+", _BASE[q[i - 1]]))
            i -= 1
    ops.reverse()

    # compress to cs syntax
    out: list[str] = []
    match_run = 0
    k = 0
    while k < len(ops):
        op, payload = ops[k]
        if op == ":":
            match_run += 1
            k += 1
            continue
        if match_run:
            out.append(f":{match_run}")
            match_run = 0
        if op == "*":
            out.append(f"*{payload}")
            k += 1
        else:  # run-collect insertions/deletions
            run = [payload]
            k += 1
            while k < len(ops) and ops[k][0] == op:
                run.append(ops[k][1])
                k += 1
            out.append(op + "".join(run))
    if match_run:
        out.append(f":{match_run}")
    return "".join(out)


def banded_cs_batch(queries: list[np.ndarray], refs: list[np.ndarray],
                    band: int = 96) -> list[str]:
    """Batched :func:`banded_cs`: one vectorized DP fill across reads.

    Bit-identical to the single-read version (per-read band geometry is
    preserved by masking each read's out-of-band lanes), but the row loop
    runs once for the whole batch — the QC profiling pass drops from
    ~0.2 s/read of small-array numpy calls to a few seconds per thousand
    reads. Band-width outliers (clipped alignments with |n-m| far above the
    band, whose wide lanes would inflate the shared pointer tensor for the
    whole batch) fall back to the single-read path.
    """
    B = len(queries)
    if B == 0:
        return []
    qs = [np.asarray(q, dtype=np.int16) for q in queries]
    rs = [np.asarray(r, dtype=np.int16) for r in refs]
    ns = np.array([len(q) for q in qs], np.int32)
    ms = np.array([len(r) for r in rs], np.int32)
    # degenerate rows handled scalar (identical to banded_cs early-outs)
    out: list[str | None] = [None] * B
    halves_all = np.maximum(band // 2, np.abs(ns - ms) + 8)
    w_cap = 2 * max(band // 2, 128) + 1
    live = []
    for b in range(B):
        if ns[b] == 0:
            out[b] = f"-{''.join(_BASE[c] for c in rs[b])}" if ms[b] else ""
        elif ms[b] == 0:
            out[b] = f"+{''.join(_BASE[c] for c in qs[b])}"
        elif 2 * halves_all[b] + 1 > w_cap:
            out[b] = banded_cs(qs[b], rs[b], band=band)  # band outlier
        else:
            live.append(b)
    if not live:
        return [s if s is not None else "" for s in out]

    idx = np.array(live)
    n_arr, m_arr = ns[idx], ms[idx]
    L = len(idx)
    n_max = int(n_arr.max())
    m_max = int(m_arr.max())
    halves = halves_all[idx]
    Ws = 2 * halves + 1
    W = int(Ws.max())
    BIG = 1 << 20

    qpad = np.zeros((L, n_max), np.int16)
    rpad = np.zeros((L, m_max), np.int16)
    for k, b in enumerate(live):
        qpad[k, : ns[b]] = qs[b]
        rpad[k, : ms[b]] = rs[b]

    # per-read, per-row band starts: row_lo(i) = clip(round(i*m/n) - half, 0, m)
    # (multiply-then-divide like banded_cs's round(i*m/n): exact int product
    # before the fp divide, so half-way cases round identically)
    rows = np.arange(n_max + 1, dtype=np.int32)[None, :]
    centers = np.rint(rows * m_arr[:, None] / n_arr[:, None]).astype(np.int32)
    lo_all = np.clip(centers - halves[:, None], 0, None)
    lo_all = np.minimum(lo_all, m_arr[:, None])          # (L, n_max+1)

    ptr = np.zeros((L, n_max + 1, W), dtype=np.uint8)
    lanes = np.arange(W, dtype=np.int32)[None, :]        # (1, W)
    lane_ok = lanes < Ws[:, None]                        # per-read band width

    # row 0: D[0][j] = j deletions for j in [lo, lo+W) ∩ [0, m]
    js0 = lo_all[:, 0:1] + lanes
    valid0 = lane_ok & (js0 <= m_arr[:, None])
    prev = np.where(valid0, js0, BIG).astype(np.int32)
    ptr[:, 0, :] = np.where(valid0, 2, 0)

    for i in range(1, n_max + 1):
        alive = i <= n_arr                               # (L,)
        nlo = lo_all[:, i]
        shift = nlo - lo_all[:, i - 1]                   # (L,)
        # aligned_prev[t] = prev at lane (t + shift - 1); [:W] = diag, [1:] = up
        src = lanes + shift[:, None] - 1                 # (L, W) for diag
        okm = (src >= 0) & (src < W)
        diag = np.where(okm, np.take_along_axis(prev, np.clip(src, 0, W - 1), 1), BIG)
        src_up = src + 1
        oku = (src_up >= 0) & (src_up < W)
        up = np.where(oku, np.take_along_axis(prev, np.clip(src_up, 0, W - 1), 1), BIG)

        js = nlo[:, None] + lanes                        # (L, W) ref positions
        valid = lane_ok & (js <= m_arr[:, None]) & alive[:, None]
        qi = qpad[np.arange(L), np.minimum(i, n_arr) - 1][:, None]  # (L, 1)
        rj = np.take_along_axis(rpad, np.clip(js - 1, 0, m_max - 1), 1)
        sub = np.where((rj == qi) & (qi < 4) & (rj < 4), 0, 1)
        d = np.where(js >= 1, diag + sub, BIG)
        u = up + 1
        best = np.minimum(d, u)
        p = np.where(u < d, 1, 0).astype(np.uint8)       # ties prefer diag
        best = np.where(valid, best, BIG)
        # left (ref-gap) chains collapse under unit cost: prefix-min cascade
        run_min = np.minimum.accumulate(best - lanes, axis=1)
        left = np.take_along_axis(run_min, np.maximum(lanes - 1, 0), 1) + lanes
        left[:, 0] = BIG
        take_left = (left < best) & valid
        best = np.where(take_left, left, best)
        p = np.where(take_left, 2, p).astype(np.uint8)
        cur = np.where(valid, best, BIG).astype(np.int32)
        ptr[:, i, :] = np.where(valid, p, 0)
        prev = np.where(alive[:, None], cur, prev)

    for k, b in enumerate(live):
        out[b] = _traceback_cs(
            qs[b], rs[b], ptr[k], lo_all[k, : ns[b] + 1], int(Ws[k])
        )
    return [s if s is not None else "" for s in out]


# ---------------------------------------------------------------------------
# device cs path (the on-chip bench made the QC stage the largest block:
# 26.5s of a 59.4s timed run at 512+667 profiled sequences — the host numpy
# fill walks ~2.3k sequential rows per chunk and the python traceback ~2.6k
# steps per read; BENCH_TPU_CAPTURE_FULL.json.stderr.log).  The fill and the
# traceback both run as lax.scan on the accelerator; only a compact per-step
# op log (kind + the two base codes) returns to host, where the cs string is
# assembled per contiguous segment instead of per base.  Output is
# bit-identical to banded_cs_batch (asserted by
# tests/test_qc.py::test_error_profile_device_matches_batch over
# ragged/degenerate/band-outlier cases).

_K_MATCH, _K_SUB, _K_INS, _K_DEL, _K_STOP = 0, 1, 2, 3, 4


@functools.partial(jax.jit, static_argnames=("w_pad",))
def _device_cs_core(qpad, rpad, n_arr, m_arr, lo_all, ws, *, w_pad):
    """Banded unit-cost DP fill + traceback on device.

    Args: qpad (L,N) int16, rpad (L,M) int16, n_arr/m_arr (L,) int32,
    lo_all (L, N+1) int32 per-row band starts, ws (L,) int32 per-read band
    widths; w_pad static >= ws.max().  Returns (kind, qb, rb): (S, L)
    uint8 step logs in TRACEBACK (reverse) order, kind==_K_STOP past the
    walk's end.  Semantics mirror banded_cs_batch row by row: ties prefer
    diagonal over up, a strict `<` lets the left chain win, and a
    fallen-off-band walk stops with the conservative tail.
    """
    L, N = qpad.shape
    M = rpad.shape[1]
    BIG = jnp.int32(1 << 20)
    lanes = jnp.arange(w_pad, dtype=jnp.int32)[None, :]
    lane_ok = lanes < ws[:, None]

    js0 = lo_all[:, 0:1] + lanes
    valid0 = lane_ok & (js0 <= m_arr[:, None])
    prev0 = jnp.where(valid0, js0, BIG).astype(jnp.int32)
    ptr0 = jnp.where(valid0, jnp.uint8(2), jnp.uint8(0))

    def fill_row(prev, i):
        nlo = jax.lax.dynamic_slice_in_dim(lo_all, i, 1, axis=1)[:, 0]
        plo = jax.lax.dynamic_slice_in_dim(lo_all, i - 1, 1, axis=1)[:, 0]
        alive = i <= n_arr
        shift = nlo - plo
        src = lanes + shift[:, None] - 1
        okm = (src >= 0) & (src < w_pad)
        diag = jnp.where(
            okm, jnp.take_along_axis(prev, jnp.clip(src, 0, w_pad - 1), 1), BIG
        )
        src_up = src + 1
        oku = (src_up >= 0) & (src_up < w_pad)
        up = jnp.where(
            oku, jnp.take_along_axis(prev, jnp.clip(src_up, 0, w_pad - 1), 1),
            BIG,
        )
        js = nlo[:, None] + lanes
        valid = lane_ok & (js <= m_arr[:, None]) & alive[:, None]
        qi = jnp.take_along_axis(
            qpad, jnp.clip(jnp.minimum(i, n_arr) - 1, 0, N - 1)[:, None], 1
        ).astype(jnp.int32)
        rj = jnp.take_along_axis(
            rpad, jnp.clip(js - 1, 0, M - 1), 1
        ).astype(jnp.int32)
        sub = jnp.where((rj == qi) & (qi < 4) & (rj < 4), 0, 1)
        d = jnp.where(js >= 1, diag + sub, BIG)
        u = up + 1
        best = jnp.minimum(d, u)
        p = jnp.where(u < d, jnp.uint8(1), jnp.uint8(0))
        best = jnp.where(valid, best, BIG)
        run_min = jax.lax.cummin(best - lanes, axis=1)
        left = jnp.take_along_axis(run_min, jnp.maximum(lanes - 1, 0), 1) + lanes
        left = left.at[:, 0].set(BIG)
        take_left = (left < best) & valid
        best = jnp.where(take_left, left, best)
        p = jnp.where(take_left, jnp.uint8(2), p)
        cur = jnp.where(valid, best, BIG).astype(jnp.int32)
        prow = jnp.where(valid, p, jnp.uint8(0))
        return jnp.where(alive[:, None], cur, prev), prow

    _, ptr_rows = jax.lax.scan(
        fill_row, prev0, jnp.arange(1, N + 1, dtype=jnp.int32)
    )
    ptr = jnp.concatenate([ptr0[None], ptr_rows], axis=0)  # (N+1, L, W)
    ptr_flat = ptr.reshape(-1)
    row_stride = jnp.int32(L * w_pad)
    read_off = jnp.arange(L, dtype=jnp.int32) * w_pad

    def tb_step(carry, _):
        i, j, done = carry
        lo_i = jnp.take_along_axis(lo_all, jnp.clip(i, 0, N)[:, None], 1)[:, 0]
        t = j - lo_i
        in_band = (t >= 0) & (t < ws)
        walking = ((i > 0) | (j > 0)) & ~done
        stop_now = walking & ~in_band  # fell off the band -> bail
        act = walking & in_band
        tc = jnp.clip(t, 0, w_pad - 1)
        p = jnp.take(ptr_flat, i * row_stride + read_off + tc)
        qc = jnp.take_along_axis(
            qpad, jnp.clip(i - 1, 0, N - 1)[:, None], 1
        )[:, 0].astype(jnp.uint8)
        rc = jnp.take_along_axis(
            rpad, jnp.clip(j - 1, 0, M - 1)[:, None], 1
        )[:, 0].astype(jnp.uint8)
        is_diag = (i > 0) & (j > 0) & (p == 0)
        is_up = ~is_diag & (i > 0) & (p == 1)
        is_left = ~is_diag & ~is_up & (j > 0)
        # residual: i > 0, j == 0, p != 1 -> query insertion (the python
        # walk's final else branch)
        is_tail_ins = ~is_diag & ~is_up & ~is_left
        kind = jnp.where(
            is_diag,
            jnp.where((qc == rc) & (qc < 4), jnp.uint8(_K_MATCH),
                      jnp.uint8(_K_SUB)),
            jnp.where(is_up | is_tail_ins, jnp.uint8(_K_INS),
                      jnp.uint8(_K_DEL)),
        )
        kind = jnp.where(act, kind, jnp.uint8(_K_STOP))
        di = jnp.where(is_diag | is_up | is_tail_ins, 1, 0)
        dj = jnp.where(is_diag | is_left, 1, 0)
        i = jnp.where(act, i - di, i)
        j = jnp.where(act, j - dj, j)
        done = done | stop_now | ((i == 0) & (j == 0))
        return (i, j, done), (kind, qc, rc)

    (_, _, _), (kind, qb, rb) = jax.lax.scan(
        tb_step, (n_arr, m_arr, jnp.zeros((L,), bool)), None, length=N + M
    )
    return kind, qb, rb


def _cs_from_oplog(kind: np.ndarray, qb: np.ndarray, rb: np.ndarray) -> str:
    """cs string from ONE read's reverse-order op log (1-D arrays)."""
    stop = np.flatnonzero(kind == _K_STOP)
    end = int(stop[0]) if stop.size else kind.size
    k = kind[:end][::-1]
    q = qb[:end][::-1]
    r = rb[:end][::-1]
    if end == 0:
        return ""
    bounds = np.flatnonzero(np.diff(k)) + 1
    out: list[str] = []
    start = 0
    for stop_ in list(bounds) + [end]:
        seg_kind = int(k[start])
        ln = stop_ - start
        if seg_kind == _K_MATCH:
            out.append(f":{ln}")
        elif seg_kind == _K_SUB:
            out.append("".join(
                f"*{_BASE[r[s]]}{_BASE[q[s]]}" for s in range(start, stop_)
            ))
        elif seg_kind == _K_INS:
            out.append("+" + "".join(_BASE[c] for c in q[start:stop_]))
        else:
            out.append("-" + "".join(_BASE[c] for c in r[start:stop_]))
        start = stop_
    return "".join(out)


def banded_cs_batch_device(queries: list[np.ndarray], refs: list[np.ndarray],
                           band: int = 96, tile: int = 512) -> list[str]:
    """Device twin of :func:`banded_cs_batch` (bit-identical output).

    The degenerate-row and band-outlier fallbacks reuse the host paths
    verbatim; live reads run the jitted fill+traceback in fixed-shape
    tiles (lengths bucketed to 256, band lanes to 64) so the persistent
    compile cache holds a handful of variants across chunk geometries.
    """
    B = len(queries)
    if B == 0:
        return []
    qs = [np.asarray(q, dtype=np.int16) for q in queries]
    rs = [np.asarray(r, dtype=np.int16) for r in refs]
    ns = np.array([len(q) for q in qs], np.int32)
    ms = np.array([len(r) for r in rs], np.int32)
    out: list[str | None] = [None] * B
    halves_all = np.maximum(band // 2, np.abs(ns - ms) + 8)
    w_cap = 2 * max(band // 2, 128) + 1
    live = []
    for b in range(B):
        if ns[b] == 0:
            out[b] = f"-{''.join(_BASE[c] for c in rs[b])}" if ms[b] else ""
        elif ms[b] == 0:
            out[b] = f"+{''.join(_BASE[c] for c in qs[b])}"
        elif 2 * halves_all[b] + 1 > w_cap:
            out[b] = banded_cs(qs[b], rs[b], band=band)  # band outlier
        else:
            live.append(b)

    def bucket(x: int, q: int) -> int:
        return -(-x // q) * q

    for s in range(0, len(live), tile):
        part = live[s : s + tile]
        L = len(part)
        n_arr = ns[part]
        m_arr = ms[part]
        halves = halves_all[part]
        ws = 2 * halves + 1
        N = bucket(int(n_arr.max()), 256)
        M = bucket(int(m_arr.max()), 256)
        w_pad = bucket(int(ws.max()), 64)
        L_pad = bucket(L, 64)
        qpad = np.zeros((L_pad, N), np.int16)
        rpad = np.zeros((L_pad, M), np.int16)
        for k, b in enumerate(part):
            qpad[k, : ns[b]] = qs[b]
            rpad[k, : ms[b]] = rs[b]
        n_full = np.ones(L_pad, np.int32)  # pad rows: 1-base walks, discarded
        m_full = np.ones(L_pad, np.int32)
        n_full[:L] = n_arr
        m_full[:L] = m_arr
        ws_full = np.full(L_pad, ws.max() if L else 1, np.int32)
        ws_full[:L] = ws
        rows = np.arange(N + 1, dtype=np.int32)[None, :]
        centers = np.rint(rows * m_full[:, None] / n_full[:, None]).astype(np.int32)
        halves_full = np.ones(L_pad, np.int32)
        halves_full[:L] = halves
        lo_all = np.clip(centers - halves_full[:, None], 0, None)
        lo_all = np.minimum(lo_all, m_full[:, None])
        kind, qb, rb = jax.device_get(_device_cs_core(
            jnp.asarray(qpad), jnp.asarray(rpad), jnp.asarray(n_full),
            jnp.asarray(m_full), jnp.asarray(lo_all), jnp.asarray(ws_full),
            w_pad=w_pad,
        ))
        for k, b in enumerate(part):
            out[b] = _cs_from_oplog(kind[:, k], qb[:, k], rb[:, k])
    return [s_ if s_ is not None else "" for s_ in out]


def profile_store(store, panel, sample_size: int = 1000, seed: int = 0,
                  chunk: int = 1024):
    """cs-tag counters over a read-store sample.

    Returns (tag_counter, tag->region counter, tag->blast_id counter) — the
    same triple the reference builds from the BAM (minimap2_align.py:21-37).
    Reads are profiled in their aligned orientation against the reference
    span recorded by the fused pass. The sample is processed in
    length-sorted chunks: the vectorized DP row loop runs to each chunk's
    longest read, so homogeneous chunks waste no rows.
    """
    from ont_tcrconsensus_tpu.ops import encode

    # Uniform sample over ALL survivors — restricting to SW-verified rows
    # would bias the profile toward the need-ranked hard quarter
    # (code-review r5 finding #2). Fast-path rows carry synthesized ref
    # spans (exact up to net indel drift, <2% of the region — assign.py
    # DIVERGENCES #12); the cs tags come from THIS function's own
    # re-alignment, so the span only slices the reference and the drift
    # adds edge noise far below the selection bias it replaces. Their
    # blast-id is NaN and is excluded from the blast histogram below.
    handles = [
        (bi, r) for bi, blk in enumerate(store.blocks) for r in range(blk.num_reads)
    ]
    rng = np.random.default_rng(seed)
    if len(handles) > sample_size:
        pick = rng.choice(len(handles), size=sample_size, replace=False)
        handles = [handles[int(i)] for i in np.sort(pick)]
    handles.sort(key=lambda h: int(store.blocks[h[0]].lens[h[1]]))

    tag_counter: Counter = Counter()
    tag_region: dict[str, Counter] = defaultdict(Counter)
    tag_blast: dict[str, Counter] = defaultdict(Counter)
    for s in range(0, len(handles), chunk):
        # liveness: one heartbeat per profiled chunk — this runs on an
        # overlapped worker under its own watchdog guard (overlap.py), so
        # a long sample must report progress or a wedged dispatch would be
        # indistinguishable from legitimate bulk work
        watchdog.heartbeat("qc.error_profile_chunk")
        part = handles[s : s + chunk]
        queries, ref_spans = [], []
        for bi, r in part:
            blk = store.blocks[bi]
            ln = int(blk.lens[r])
            qcodes = blk.codes[r, :ln]
            if blk.is_rev[r]:
                qcodes = encode.revcomp_codes(qcodes)
            queries.append(qcodes)
            ridx = int(blk.region_idx[r])
            rs, re = int(blk.ref_start[r]), int(blk.ref_end[r])
            ref_spans.append(panel.codes[ridx, rs:re])
        # accelerator backends run the jitted fill+traceback (bit-identical;
        # the QC pass was the largest stage of the first on-chip bench);
        # host CPU keeps the numpy fill, which wins there at test shapes
        if jax.default_backend() != "cpu":
            tags = banded_cs_batch_device(queries, ref_spans)
        else:
            tags = banded_cs_batch(queries, ref_spans)
        for (bi, r), tag in zip(part, tags):
            blk = store.blocks[bi]
            ridx = int(blk.region_idx[r])
            tag_counter[tag] += 1
            tag_region[tag][panel.names[ridx]] += 1
            b = float(blk.blast_id[r])
            if not np.isnan(b):
                tag_blast[tag][round(b, 6)] += 1
    return tag_counter, tag_region, tag_blast


def write_error_profile_log(
    tag_counter: Counter, tag_region: dict, tag_blast: dict, log_path: str,
    top_n: int = 40,
) -> None:
    """Reference log format (minimap2_align.py:140-150 sections)."""
    top = tag_counter.most_common(top_n)
    with open(log_path, "w") as fh:
        fh.write(f"\nTop {top_n} most common cs tags:\n")
        for tup in top:
            fh.write(str(tup) + "\n")
        fh.write(
            f"\nTop 4 most common regions counted for each of the top {top_n} "
            "most common cs tags:\n"
        )
        for tag, _ in top:
            fh.write(f"{tag} {tag_region[tag].most_common(4)}\n")
        fh.write(
            f"\nTop 4 most common blast identities counted for each of the top {top_n} "
            "most common cs tags:\n"
        )
        for tag, _ in top:
            fh.write(f"{tag} {tag_blast[tag].most_common(4)}\n")
