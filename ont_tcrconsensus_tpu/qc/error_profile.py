"""cs-tag-style alignment difference profiling.

The reference dumps, per alignment pass, the 40 most common minimap2 ``cs``
difference strings with their region and blast-id breakdowns
(/root/reference/ont_tcr_consensus/minimap2_align.py:21-37,140-150) — the
pipeline's error-profile debugging artifact. This framework has no BAM/cs
tags, so the equivalent difference strings are reconstructed host-side with
a banded global alignment of each (sampled) read against the reference span
it aligned to, emitted in cs syntax:

    :N      run of N matches
    *<r><q> substitution (reference base, query base)
    +<seq>  insertion in the query
    -<seq>  deletion from the reference

Profiling is a QC path, not a hot path: it runs on a capped sample
(default 1000 reads/library) with unit-cost edit alignment — the motif
distribution, not base-perfect minimap2 score parity, is the artifact.
"""

from __future__ import annotations

from collections import Counter, defaultdict

import numpy as np

_BASE = "acgtn"  # cs syntax is lowercase


def banded_cs(query: np.ndarray, ref: np.ndarray, band: int = 96) -> str:
    """cs difference string of a banded global alignment (unit costs).

    Args:
      query/ref: dense uint8 code arrays (no padding).
    """
    q = np.asarray(query, dtype=np.int16)
    r = np.asarray(ref, dtype=np.int16)
    n, m = len(q), len(r)
    if n == 0:
        return f"-{''.join(_BASE[c] for c in r)}" if m else ""
    if m == 0:
        return f"+{''.join(_BASE[c] for c in q)}"
    # band around the length-interpolated diagonal
    half = max(band // 2, abs(n - m) + 8)
    BIG = 1 << 20
    # rows: query positions 0..n; per row keep [lo, lo+W) of ref positions
    W = 2 * half + 1
    ptr = np.zeros((n + 1, W), dtype=np.uint8)  # 0 diag, 1 up(q-gap? see below), 2 left
    prev = np.full(W, BIG, dtype=np.int64)
    lo_of = [0] * (n + 1)

    def row_lo(i: int) -> int:
        center = round(i * m / n)
        return max(0, min(center - half, m))

    lo = row_lo(0)
    lo_of[0] = lo
    js = np.arange(lo, min(lo + W, m + 1))
    prev[: len(js)] = js  # D[0][j] = j deletions
    ptr[0, : len(js)] = 2

    for i in range(1, n + 1):
        nlo = row_lo(i)
        lo_of[i] = nlo
        cur = np.full(W, BIG, dtype=np.int64)
        js = np.arange(nlo, min(nlo + W, m + 1))
        k = len(js)
        # shift the previous row into this row's band frame:
        # aligned_prev[t] = prev value at ref position (nlo + t - 1)
        shift = nlo - lo
        aligned_prev = np.full(W + 1, BIG, dtype=np.int64)
        t = np.arange(W + 1)
        src = t + shift - 1
        okm = (src >= 0) & (src < W)
        aligned_prev[okm] = prev[src[okm]]
        diag = aligned_prev[:W]                       # prev row, j-1
        up = aligned_prev[1 : W + 1]                  # prev row, j
        qi = q[i - 1]
        jmask = js >= 1
        rj = r[np.clip(js - 1, 0, m - 1)]
        sub = np.where((rj == qi) & (qi < 4) & (rj < 4), 0, 1)
        d = np.where(jmask[:k], diag[:k] + sub[:k], BIG)
        u = up[:k] + 1
        best = np.minimum(d, u)
        p = np.where(u < d, 1, 0).astype(np.uint8)    # ties prefer diag
        # left (ref-base deletion) chains collapse under unit cost:
        # left[j] = min_{l<j}(best[l] + (j-l)) via a prefix-min cascade
        idx = np.arange(k)
        run_min = np.minimum.accumulate(best - idx)
        left = run_min[np.maximum(idx - 1, 0)] + idx
        left[0] = BIG
        take_left = left < best
        best = np.where(take_left, left, best)
        p = np.where(take_left, 2, p).astype(np.uint8)
        cur[:k] = best
        ptr[i, :k] = p
        prev = cur
        lo = nlo

    return _traceback_cs(q, r, ptr, lo_of, W)


def _traceback_cs(q, r, ptr, lo_of, W) -> str:
    """Emit the cs string from a filled pointer matrix (shared by the
    single-read and batched fills)."""
    n, m = len(q), len(r)
    i, jpos = n, m
    ops: list[tuple[str, str]] = []  # (op, payload)
    while i > 0 or jpos > 0:
        lo = lo_of[i]
        t = jpos - lo
        if t < 0 or t >= W:
            # fell off the band — bail with a conservative tail
            break
        p = ptr[i, t]
        if i > 0 and jpos > 0 and p == 0:
            qc, rc = q[i - 1], r[jpos - 1]
            if qc == rc and qc < 4:
                ops.append((":", ""))
            else:
                ops.append(("*", _BASE[rc] + _BASE[qc]))
            i -= 1
            jpos -= 1
        elif i > 0 and p == 1:
            ops.append(("+", _BASE[q[i - 1]]))
            i -= 1
        elif jpos > 0:
            ops.append(("-", _BASE[r[jpos - 1]]))
            jpos -= 1
        else:
            ops.append(("+", _BASE[q[i - 1]]))
            i -= 1
    ops.reverse()

    # compress to cs syntax
    out: list[str] = []
    match_run = 0
    k = 0
    while k < len(ops):
        op, payload = ops[k]
        if op == ":":
            match_run += 1
            k += 1
            continue
        if match_run:
            out.append(f":{match_run}")
            match_run = 0
        if op == "*":
            out.append(f"*{payload}")
            k += 1
        else:  # run-collect insertions/deletions
            run = [payload]
            k += 1
            while k < len(ops) and ops[k][0] == op:
                run.append(ops[k][1])
                k += 1
            out.append(op + "".join(run))
    if match_run:
        out.append(f":{match_run}")
    return "".join(out)


def banded_cs_batch(queries: list[np.ndarray], refs: list[np.ndarray],
                    band: int = 96) -> list[str]:
    """Batched :func:`banded_cs`: one vectorized DP fill across reads.

    Bit-identical to the single-read version (per-read band geometry is
    preserved by masking each read's out-of-band lanes), but the row loop
    runs once for the whole batch — the QC profiling pass drops from
    ~0.2 s/read of small-array numpy calls to a few seconds per thousand
    reads. Band-width outliers (clipped alignments with |n-m| far above the
    band, whose wide lanes would inflate the shared pointer tensor for the
    whole batch) fall back to the single-read path.
    """
    B = len(queries)
    if B == 0:
        return []
    qs = [np.asarray(q, dtype=np.int16) for q in queries]
    rs = [np.asarray(r, dtype=np.int16) for r in refs]
    ns = np.array([len(q) for q in qs], np.int32)
    ms = np.array([len(r) for r in rs], np.int32)
    # degenerate rows handled scalar (identical to banded_cs early-outs)
    out: list[str | None] = [None] * B
    halves_all = np.maximum(band // 2, np.abs(ns - ms) + 8)
    w_cap = 2 * max(band // 2, 128) + 1
    live = []
    for b in range(B):
        if ns[b] == 0:
            out[b] = f"-{''.join(_BASE[c] for c in rs[b])}" if ms[b] else ""
        elif ms[b] == 0:
            out[b] = f"+{''.join(_BASE[c] for c in qs[b])}"
        elif 2 * halves_all[b] + 1 > w_cap:
            out[b] = banded_cs(qs[b], rs[b], band=band)  # band outlier
        else:
            live.append(b)
    if not live:
        return [s if s is not None else "" for s in out]

    idx = np.array(live)
    n_arr, m_arr = ns[idx], ms[idx]
    L = len(idx)
    n_max = int(n_arr.max())
    m_max = int(m_arr.max())
    halves = halves_all[idx]
    Ws = 2 * halves + 1
    W = int(Ws.max())
    BIG = 1 << 20

    qpad = np.zeros((L, n_max), np.int16)
    rpad = np.zeros((L, m_max), np.int16)
    for k, b in enumerate(live):
        qpad[k, : ns[b]] = qs[b]
        rpad[k, : ms[b]] = rs[b]

    # per-read, per-row band starts: row_lo(i) = clip(round(i*m/n) - half, 0, m)
    # (multiply-then-divide like banded_cs's round(i*m/n): exact int product
    # before the fp divide, so half-way cases round identically)
    rows = np.arange(n_max + 1, dtype=np.int32)[None, :]
    centers = np.rint(rows * m_arr[:, None] / n_arr[:, None]).astype(np.int32)
    lo_all = np.clip(centers - halves[:, None], 0, None)
    lo_all = np.minimum(lo_all, m_arr[:, None])          # (L, n_max+1)

    ptr = np.zeros((L, n_max + 1, W), dtype=np.uint8)
    lanes = np.arange(W, dtype=np.int32)[None, :]        # (1, W)
    lane_ok = lanes < Ws[:, None]                        # per-read band width

    # row 0: D[0][j] = j deletions for j in [lo, lo+W) ∩ [0, m]
    js0 = lo_all[:, 0:1] + lanes
    valid0 = lane_ok & (js0 <= m_arr[:, None])
    prev = np.where(valid0, js0, BIG).astype(np.int32)
    ptr[:, 0, :] = np.where(valid0, 2, 0)

    for i in range(1, n_max + 1):
        alive = i <= n_arr                               # (L,)
        nlo = lo_all[:, i]
        shift = nlo - lo_all[:, i - 1]                   # (L,)
        # aligned_prev[t] = prev at lane (t + shift - 1); [:W] = diag, [1:] = up
        src = lanes + shift[:, None] - 1                 # (L, W) for diag
        okm = (src >= 0) & (src < W)
        diag = np.where(okm, np.take_along_axis(prev, np.clip(src, 0, W - 1), 1), BIG)
        src_up = src + 1
        oku = (src_up >= 0) & (src_up < W)
        up = np.where(oku, np.take_along_axis(prev, np.clip(src_up, 0, W - 1), 1), BIG)

        js = nlo[:, None] + lanes                        # (L, W) ref positions
        valid = lane_ok & (js <= m_arr[:, None]) & alive[:, None]
        qi = qpad[np.arange(L), np.minimum(i, n_arr) - 1][:, None]  # (L, 1)
        rj = np.take_along_axis(rpad, np.clip(js - 1, 0, m_max - 1), 1)
        sub = np.where((rj == qi) & (qi < 4) & (rj < 4), 0, 1)
        d = np.where(js >= 1, diag + sub, BIG)
        u = up + 1
        best = np.minimum(d, u)
        p = np.where(u < d, 1, 0).astype(np.uint8)       # ties prefer diag
        best = np.where(valid, best, BIG)
        # left (ref-gap) chains collapse under unit cost: prefix-min cascade
        run_min = np.minimum.accumulate(best - lanes, axis=1)
        left = np.take_along_axis(run_min, np.maximum(lanes - 1, 0), 1) + lanes
        left[:, 0] = BIG
        take_left = (left < best) & valid
        best = np.where(take_left, left, best)
        p = np.where(take_left, 2, p).astype(np.uint8)
        cur = np.where(valid, best, BIG).astype(np.int32)
        ptr[:, i, :] = np.where(valid, p, 0)
        prev = np.where(alive[:, None], cur, prev)

    for k, b in enumerate(live):
        out[b] = _traceback_cs(
            qs[b], rs[b], ptr[k], lo_all[k, : ns[b] + 1], int(Ws[k])
        )
    return [s if s is not None else "" for s in out]


def profile_store(store, panel, sample_size: int = 1000, seed: int = 0,
                  chunk: int = 1024):
    """cs-tag counters over a read-store sample.

    Returns (tag_counter, tag->region counter, tag->blast_id counter) — the
    same triple the reference builds from the BAM (minimap2_align.py:21-37).
    Reads are profiled in their aligned orientation against the reference
    span recorded by the fused pass. The sample is processed in
    length-sorted chunks: the vectorized DP row loop runs to each chunk's
    longest read, so homogeneous chunks waste no rows.
    """
    from ont_tcrconsensus_tpu.ops import encode

    # Uniform sample over ALL survivors — restricting to SW-verified rows
    # would bias the profile toward the need-ranked hard quarter
    # (code-review r5 finding #2). Fast-path rows carry synthesized ref
    # spans (exact up to net indel drift, <2% of the region — assign.py
    # DIVERGENCES #12); the cs tags come from THIS function's own
    # re-alignment, so the span only slices the reference and the drift
    # adds edge noise far below the selection bias it replaces. Their
    # blast-id is NaN and is excluded from the blast histogram below.
    handles = [
        (bi, r) for bi, blk in enumerate(store.blocks) for r in range(blk.num_reads)
    ]
    rng = np.random.default_rng(seed)
    if len(handles) > sample_size:
        pick = rng.choice(len(handles), size=sample_size, replace=False)
        handles = [handles[int(i)] for i in np.sort(pick)]
    handles.sort(key=lambda h: int(store.blocks[h[0]].lens[h[1]]))

    tag_counter: Counter = Counter()
    tag_region: dict[str, Counter] = defaultdict(Counter)
    tag_blast: dict[str, Counter] = defaultdict(Counter)
    for s in range(0, len(handles), chunk):
        part = handles[s : s + chunk]
        queries, ref_spans = [], []
        for bi, r in part:
            blk = store.blocks[bi]
            ln = int(blk.lens[r])
            qcodes = blk.codes[r, :ln]
            if blk.is_rev[r]:
                qcodes = encode.revcomp_codes(qcodes)
            queries.append(qcodes)
            ridx = int(blk.region_idx[r])
            rs, re = int(blk.ref_start[r]), int(blk.ref_end[r])
            ref_spans.append(panel.codes[ridx, rs:re])
        tags = banded_cs_batch(queries, ref_spans)
        for (bi, r), tag in zip(part, tags):
            blk = store.blocks[bi]
            ridx = int(blk.region_idx[r])
            tag_counter[tag] += 1
            tag_region[tag][panel.names[ridx]] += 1
            b = float(blk.blast_id[r])
            if not np.isnan(b):
                tag_blast[tag][round(b, 6)] += 1
    return tag_counter, tag_region, tag_blast


def write_error_profile_log(
    tag_counter: Counter, tag_region: dict, tag_blast: dict, log_path: str,
    top_n: int = 40,
) -> None:
    """Reference log format (minimap2_align.py:140-150 sections)."""
    top = tag_counter.most_common(top_n)
    with open(log_path, "w") as fh:
        fh.write(f"\nTop {top_n} most common cs tags:\n")
        for tup in top:
            fh.write(str(tup) + "\n")
        fh.write(
            f"\nTop 4 most common regions counted for each of the top {top_n} "
            "most common cs tags:\n"
        )
        for tag, _ in top:
            fh.write(f"{tag} {tag_region[tag].most_common(4)}\n")
        fh.write(
            f"\nTop 4 most common blast identities counted for each of the top {top_n} "
            "most common cs tags:\n"
        )
        for tag, _ in top:
            fh.write(f"{tag} {tag_blast[tag].most_common(4)}\n")
