"""CLI entry point: ``tcr-consensus-tpu-analysis <nano_dir> <reference.fa>``.

The reference drives its post-hoc QC from a notebook
(/root/reference/notebooks/analysis.ipynb: read libraries.csv, loop
libraries, call the analysis.py plot/summary functions into per-library
``outs/`` dirs). Here the same loop is a console script over the pipeline's
output tree, so analysis runs headless on the TPU VM right after the
pipeline.

``--reference`` may be repeated as ``name=path`` to register multiple
reference libraries; ``libraries.csv``'s ``ref_library_name`` column then
selects the region set per library (ref README.md:62-82).
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        description="Post-hoc QC/analysis over a completed pipeline output tree."
    )
    parser.add_argument("nano_dir", help="The nano_tcr output dir of a pipeline run")
    parser.add_argument(
        "reference", nargs="+",
        help="Reference fasta path, or repeated name=path pairs for "
             "libraries.csv ref_library_name mapping",
    )
    parser.add_argument("--libraries-csv", default=None,
                        help="barcode,library_name,ref_library_name,threshold CSV")
    parser.add_argument("--tcr-refs-csv", default=None,
                        help="TCR composition CSV enabling the V-gene plots")
    args = parser.parse_args(argv)

    from ont_tcrconsensus_tpu.io import fastx
    from ont_tcrconsensus_tpu.qc import analysis

    if len(args.reference) == 1 and "=" not in args.reference[0]:
        regions = set(fastx.read_fasta_dict(args.reference[0]))
    else:
        regions = {}
        for pair in args.reference:
            name, _, path = pair.partition("=")
            if not path:
                parser.error(f"expected name=path, got {pair!r}")
            regions[name] = set(fastx.read_fasta_dict(path))

    summaries = analysis.run_all_libraries(
        args.nano_dir, regions,
        libraries_csv=args.libraries_csv,
        tcr_refs_csv=args.tcr_refs_csv,
    )
    json.dump(summaries, sys.stdout, indent=2, default=float)
    print()
    return 0


if __name__ == "__main__":
    sys.exit(main())
