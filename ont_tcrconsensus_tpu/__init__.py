"""ont_tcrconsensus_tpu — a TPU-native framework for ONT TCR UMI consensus calling.

A ground-up JAX/XLA/Pallas re-design of the capabilities of
schumacherlab/ONT-TCRconsensus (a CPU-cluster pipeline orchestrating
minimap2/vsearch/edlib/spoa/medaka via Ray + subprocess; see
/root/reference/ont_tcr_consensus/tcr_consensus.py:33-478 for the reference
entry point). Instead of "Ray task -> subprocess -> files on disk", this
framework streams padded, length-bucketed device batches through a library of
JAX kernels:

- ``ops``       device kernels: expected-error filtering, IUPAC fuzzy match,
                batched edit distance, k-mer sketch + banded affine alignment,
                pileup/consensus.
- ``models``    Flax consensus-polisher RNN (medaka-class bi-GRU).
- ``cluster``   greedy centroid UMI clustering and reference self-homology
                region clustering driven by device distance batches.
- ``parallel``  device-mesh management (data-sharded pipeline batches via
                shard_map, tensor-parallel polisher training), the HBM
                batch budgeter, and multi-host distribution
                (``jax.distributed`` + shard-by-barcode over DCN).
- ``io``        host data plane: FASTQ/FASTA streaming, encoding, batching,
                a C++ fast parser, and a read simulator.
- ``pipeline``  the end-to-end two-round UMI consensus pipeline: the fused
                per-batch device pass (trim/filter/align/UMI), columnar read
                store, config and stage-level resume.
- ``qc``        QC artifacts, stats and analysis plots.
"""

__version__ = "0.1.0"
