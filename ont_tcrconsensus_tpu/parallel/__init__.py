"""parallel subpackage."""
