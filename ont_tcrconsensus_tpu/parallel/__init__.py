"""Parallel execution layer: device meshes, HBM budgeting, multi-host.

- :mod:`.mesh` — mesh construction + shardings (data axis for pipeline
  batches, model axis for polisher tensor parallelism).
- :mod:`.budget` — the HBM batch budgeter (the reference's medaka memory
  model, TPU edition).
- :mod:`.distributed` — ``jax.distributed`` bring-up, shard-by-barcode
  across hosts, end-of-run count gathering.
"""
