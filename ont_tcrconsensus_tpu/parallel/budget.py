"""HBM-budgeted batch sizing — the medaka memory model, TPU edition.

The reference schedules its dominant stage with a hand-fit linear memory
model: ``mem_GB/cluster = 0.0143 * max_subreads + 0.0286`` plus a task
overhead, split into <=20 GB batches and quantized into 75 bins so Ray can
bucket the requests (/root/reference/ont_tcr_consensus/medaka_polish.py:
11-92). The TPU equivalent sizes DEVICE BATCHES from array-shape arithmetic
against the chip's real HBM capacity: one knob (``hbm_budget_gb``), batch
sizes derived, OOM-free by construction.

Footprint models (bytes, from the shapes the kernels actually allocate):

- fused read pass (:mod:`..pipeline.assign`): per read of padded width W —
  ~10 u8 planes of W (codes/quals/oriented/revcomp/shifted/masks), two
  k-mer-profile scatters of (dim+1) f32, top_k banded-SW output clusters
  of 6 int32 bands, and the (R,) candidate score rows.
- polish cluster tile (:mod:`..ops.pileup`): per cluster of S subreads x
  width W — the dominant term is the traceback planes (tdir+fjump), two u8
  planes of (W rows x band) per subread, plus the base/ins pileup columns.

Powers of two keep XLA compile caches small (one program per size).
"""

from __future__ import annotations

import dataclasses

DEFAULT_HBM_GB = 12.0  # conservative v5e chip budget when detection fails


def detect_hbm_gb() -> float:
    """Per-chip HBM capacity; falls back to a conservative default."""
    try:
        import jax

        dev = jax.local_devices()[0]
        stats = dev.memory_stats()
        if stats and "bytes_limit" in stats:
            return stats["bytes_limit"] / 1e9
    except Exception:
        pass
    return DEFAULT_HBM_GB


def _pow2_floor(n: int, lo: int, hi: int) -> int:
    p = lo
    while p * 2 <= min(n, hi):
        p *= 2
    return max(p, lo)


@dataclasses.dataclass
class BudgetModel:
    """Derives device batch sizes from one HBM budget.

    ``working_fraction`` reserves headroom for XLA scratch, fusion
    temporaries and double-buffered transfers.
    """

    hbm_gb: float
    working_fraction: float = 0.25

    @property
    def budget_bytes(self) -> int:
        return int(self.hbm_gb * 1e9 * self.working_fraction)

    def read_bytes(self, width: int, profile_dim: int = 4096,
                   top_k: int = 2, band_width: int = 256,
                   num_refs: int = 1024) -> int:
        planes = 10 * width                      # u8 code/qual/mask planes
        profiles = 2 * 4 * (profile_dim + 1)     # fwd+rev scatter targets
        scores = 2 * 4 * num_refs                # both-strand candidate rows
        sw_out = top_k * 6 * 4 * band_width      # per-pair band outputs
        return planes + profiles + scores + sw_out

    def read_batch(self, width: int, profile_dim: int = 4096,
                   top_k: int = 2, band_width: int = 256,
                   num_refs: int = 1024) -> int:
        per = self.read_bytes(width, profile_dim, top_k, band_width, num_refs)
        return _pow2_floor(self.budget_bytes // per, 128, 16384)

    def cluster_bytes(self, s_bucket: int, width: int,
                      band_width: int = 128,
                      keep_final_pileup: bool = True,
                      keep_pos: bool = False) -> int:
        traceback = 2 * s_bucket * width * band_width  # tdir+fjump u8 planes
        # base_at/ins_cnt/ins_base (+ pos_at int32 only when the served
        # polisher's v4 quality channels consume it, keep_pos);
        # keep_final_pileup (the rnn polish path, the default with bundled
        # weights) transiently holds BOTH the accumulated per-part pileups
        # and the full scatter buffers at compaction-scatter time
        # (ADVICE r3), hence the extra copy
        per_cell = (1 + 4 + 1) + (4 if keep_pos else 0)
        pileup = (2 if keep_final_pileup else 1) * s_bucket * width * per_cell
        votes = 2 * width * 4 * 8                      # vote stacks (int32)
        return traceback + pileup + votes

    # Flat alignment lanes (clusters x subreads) per polish dispatch. Above
    # this the pileup working set (direction planes + traceback log) crowds
    # HBM without improving utilization — 4096 lanes already saturate the
    # sequential DP scans.
    MAX_POLISH_LANES = 4096

    def cluster_batch(self, s_bucket: int, width: int,
                      band_width: int = 128,
                      keep_final_pileup: bool = True,
                      keep_pos: bool = False) -> int:
        per = self.cluster_bytes(s_bucket, width, band_width,
                                 keep_final_pileup, keep_pos)
        hi = min(256, max(1, self.MAX_POLISH_LANES // max(s_bucket, 1)))
        return _pow2_floor(self.budget_bytes // per, 1, hi)


def degraded_budget(budget: BudgetModel, n_surviving: int,
                    n_total: int) -> BudgetModel:
    """The budget for a mesh that lost slices mid-run.

    The model's batch sizes are GLOBAL (each slice sees batch/n_data
    rows), so a budget sized for ``n_total`` slices over-commits the
    survivors by exactly the lost fraction: scale ``hbm_gb`` by
    ``n_surviving / n_total`` and every derived batch shrinks
    proportionally, keeping the per-slice HBM load constant through the
    degradation. Idempotent under repeated losses (each call scales the
    CURRENT budget by the CURRENT survival fraction).
    """
    if n_surviving >= n_total:
        return budget
    frac = max(n_surviving, 1) / max(n_total, 1)
    return dataclasses.replace(budget, hbm_gb=budget.hbm_gb * frac)
