"""Multi-host distribution: ``jax.distributed`` + shard-by-barcode over DCN.

The reference markets "any cluster" but in practice runs Ray on one node
(/root/reference/ont_tcr_consensus/tcr_consensus.py:73 ``ray.init()``
local-only; SURVEY §2.3). This module supplies the real multi-host story for
the TPU build:

- **library-level data parallelism across hosts**: barcode libraries are
  fully independent (the reference fans them out as Ray tasks,
  tcr_consensus.py:141-167), so each host process owns a deterministic
  shard of the library list and runs the complete per-library pipeline on
  its local chips. No cross-host traffic during a library.
- **within a host**: the device mesh shards read/cluster batches over ICI
  (:mod:`.mesh`); the two axes compose (DCN outer, ICI inner) exactly like
  the scaling-book dp-over-pod recipe.
- **end-of-run gather**: per-library counts are all-gathered to every
  process (one variable-length byte collective) so each host can write the
  complete results CSV; the heavy intermediates never cross DCN.

Initialization: on TPU pods ``jax.distributed.initialize()`` discovers the
coordinator from the TPU metadata; elsewhere (tests, CPU fleets) pass
explicit ``coordinator_address``/``num_processes``/``process_id`` or set the
standard JAX env vars.
"""

from __future__ import annotations

import json

import numpy as np


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None,
               required: bool = False) -> None:
    """Bring up the JAX distributed runtime (idempotent).

    Must run before the first JAX computation of the process — the CLI
    does this (env-gated, pipeline/cli.py) before importing the pipeline.
    No-op when already initialized. ``required=True`` (what
    ``RunConfig.distributed`` requests) re-raises any bring-up failure:
    silently degrading an intended multi-host run to N independent
    single-process runs would race every host over the same output tree.
    Without ``required``, an auto-detection miss (plain single-host run)
    is demoted to a stderr note; an explicit ``num_processes`` > 1 always
    re-raises.
    """
    import sys

    import jax

    if jax.distributed.is_initialized():
        return
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except (ValueError, RuntimeError) as exc:
        if required or num_processes not in (None, 1):
            raise
        print(
            f"jax.distributed not started ({exc}); continuing single-process",
            file=sys.stderr,
        )


def process_index() -> int:
    import jax

    return jax.process_index()


def process_count() -> int:
    import jax

    return jax.process_count()


def shard_libraries(paths: list[str], index: int | None = None,
                    count: int | None = None) -> list[str]:
    """The library shard owned by this process: deterministic round-robin
    over the *sorted* list, so every process derives the same partition
    without communicating (the DCN analogue of the reference's per-library
    Ray fan-out, tcr_consensus.py:141-167)."""
    index = process_index() if index is None else index
    count = process_count() if count is None else count
    if count <= 1:
        return list(paths)
    return [p for i, p in enumerate(sorted(paths)) if i % count == index]


def barrier(name: str = "barrier") -> None:
    """Block until every process arrives (no-op single-process)."""
    from jax.experimental import multihost_utils

    if process_count() > 1:
        multihost_utils.sync_global_devices(name)


def allgather_object(obj) -> list:
    """All-gather one JSON-serializable object per process.

    Two fixed-shape collectives (max length, then padded uint8 payload) via
    ``multihost_utils.process_allgather`` — counts dicts are tiny, so this
    is one DCN round, not a data-plane path.
    """
    from jax.experimental import multihost_utils

    if process_count() <= 1:
        return [obj]
    payload = np.frombuffer(
        json.dumps(obj, sort_keys=True).encode(), dtype=np.uint8
    )
    n = np.asarray(payload.size, dtype=np.int32)
    sizes = np.asarray(multihost_utils.process_allgather(n))
    width = int(sizes.max())
    padded = np.zeros((width,), np.uint8)
    padded[: payload.size] = payload
    gathered = np.asarray(multihost_utils.process_allgather(padded))
    out = []
    for i in range(gathered.shape[0]):
        out.append(json.loads(bytes(gathered[i, : int(sizes[i])]).decode()))
    return out


def merge_results(local: dict[str, dict[str, int]]) -> dict[str, dict[str, int]]:
    """Union of every process's {library: {region: count}} results."""
    merged: dict[str, dict[str, int]] = {}
    for part in allgather_object(local):
        merged.update(part)
    return merged
