"""Device-mesh management and sharded pipeline execution.

The reference scales by Ray CPU tasks on one node (SURVEY §2.3: task/data
parallelism over libraries and region clusters; no model/tensor parallelism
exists). The TPU-native equivalents:

- **data axis** ("data"): read/cluster batches sharded across chips; the
  alignment, pileup, and clustering kernels are embarrassingly parallel over
  their batch dimension, so sharding the inputs lets XLA run them with zero
  collectives (the all-reduce appears only in summaries/losses).
- **model axis** ("model"): tensor parallelism for the polisher's dense/GRU
  feature dimensions — overkill for this model's size, but it exercises the
  tp path the dryrun validates.
- multi-host: the same meshes span hosts via ``jax.distributed`` — the data
  axis then shards by barcode library, mirroring the reference's
  per-library Ray fan-out (tcr_consensus.py:141-167), with collectives
  riding ICI within a host and DCN across hosts.

Nothing here requires N physical chips: tests and the driver's dryrun use
``XLA_FLAGS=--xla_force_host_platform_device_count=N`` CPU devices.
"""

from __future__ import annotations

import threading

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ont_tcrconsensus_tpu.obs import metrics as obs_metrics
from ont_tcrconsensus_tpu.obs import transfers as obs_transfers
from ont_tcrconsensus_tpu.robustness import faults, jobscope

# --- per-job slice install (serve-plane slice packing) ----------------------
# The slice-packed runner pool (serve/slices.py + serve/daemon.py) gives
# each resident tenant job a DISJOINT subset of the local devices. The
# job's run builds its meshes through the unchanged make_mesh default
# path, so the restriction rides the job's scope: the runner installs the
# slice before dispatch, and every make_mesh inside that job — including
# on overlap stage workers, which adopt the scope — sees only the slice's
# devices. A thread-local fallback serves unscoped callers (unit tests);
# plain threads — every one-shot CLI run — see jax.local_devices()
# exactly as before.
_TLS = threading.local()


def install_slice_devices(devices) -> None:
    """Restrict ``make_mesh``'s default device set for the calling job
    scope (or thread, unscoped); ``None`` clears. Owned by the
    serve-plane runner pool."""
    devs = list(devices) if devices is not None else None
    if jobscope.active():
        jobscope.set("slice_devices", devs)
        return
    _TLS.devices = devs


def slice_devices():
    """The calling job's installed slice devices (None = whole host)."""
    devs = jobscope.get("slice_devices")
    if devs is not None:
        return devs
    return getattr(_TLS, "devices", None)


def install_degrade_hook(hook) -> None:
    """Install a callable(lost_devices) fired when :func:`degrade_mesh`
    drops a data slice inside the calling job scope (or thread, unscoped);
    ``None`` clears. The runner pool uses it to quarantine the lost
    devices out of the allocator's free pool — the fault stays the losing
    tenant's fault."""
    if jobscope.active():
        jobscope.set("degrade_hook", hook)
        return
    _TLS.degrade_hook = hook


def make_mesh(shape: dict[str, int] | None = None, devices=None) -> Mesh:
    """Build a mesh; default puts every device on the data axis.

    ``shape`` e.g. {"data": 4, "model": 2}; axis sizes must multiply to the
    device count used. Defaults to LOCAL devices — or, under a serve-plane
    slice install (:func:`install_slice_devices`), the calling thread's
    slice of them: the pipeline's meshes are intra-host (chips of one TPU
    VM), while the cross-host axis is the library shard over gloo/DCN
    (parallel/distributed.py) — a global-device mesh here would hand every
    process the same (process-0) chips.
    """
    if devices is None:
        devices = slice_devices()
    devices = list(devices if devices is not None else jax.local_devices())
    if not shape:
        shape = {"data": len(devices)}
    names = tuple(shape)
    sizes = tuple(shape[n] for n in names)
    total = int(np.prod(sizes))
    if total > len(devices):
        raise ValueError(f"mesh {shape} needs {total} devices, have {len(devices)}")
    arr = np.array(devices[:total]).reshape(sizes)
    return Mesh(arr, names)


def shard_map_compat(f, mesh: Mesh, in_specs, out_specs, check_vma=False):
    """``jax.shard_map`` across jax generations.

    Newer jax exports ``shard_map`` at top level with a ``check_vma`` knob;
    older releases (e.g. the 0.4.x line some containers pin) only have
    ``jax.experimental.shard_map`` where the same knob is ``check_rep``.
    Every shard_map call site routes through here so the multichip paths
    run (and are tested) on both.
    """
    try:
        from jax import shard_map
    except ImportError:  # jax < 0.5: experimental home, check_rep spelling
        from jax.experimental.shard_map import shard_map as _sm

        return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=check_vma)
    return shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                     check_vma=check_vma)


def mesh_data_size(mesh: Mesh) -> int:
    """Size of the mesh's ``data`` axis (the one shared helper for every
    divisibility check before a shard_map dispatch)."""
    return dict(zip(mesh.axis_names, mesh.devices.shape))["data"]


def data_sharding(mesh: Mesh, ndim: int) -> NamedSharding:
    """Shard the leading (batch) axis over the data axis; rest replicated."""
    return NamedSharding(mesh, P("data", *([None] * (ndim - 1))))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def materialized_shard_bytes(placed) -> int:
    """Bytes the device(s) actually hold for one placed array: the sum
    over its addressable shards. For a data-sharded array this equals the
    logical nbytes (each row lives on exactly one slice); for a
    REPLICATED placement it is N copies — the honest h2d charge either
    way. Falls back to the logical size when the shard API is absent
    (plain numpy input, old jax)."""
    try:
        shards = placed.addressable_shards
        total = 0
        for s in shards:
            total += int(s.data.nbytes)
        return total
    except Exception:
        return obs_transfers.nbytes_of(placed)


def mark_mesh_slices(mesh: Mesh, busy: float = 1.0) -> None:
    """Per-slice busy gauge (``tcr_mesh_slice_busy``): every device of the
    active mesh marked ``busy``; :func:`degrade_mesh` re-marks survivors 1
    and the lost slice 0, so a /metrics scrape shows exactly which slices
    still carry work. Free no-op when telemetry is off."""
    if not obs_metrics.armed():
        return
    for d in mesh.devices.flat:
        obs_metrics.mesh_slice_set(f"{d.platform}:{d.id}", busy)
    obs_metrics.gauge_set("mesh.slice_busy", float(mesh.devices.size) * busy)


def shard_batch(mesh: Mesh, *arrays):
    """device_put each array with its leading axis on the data axis.

    Leading dimensions must divide the data-axis size; callers pad batches
    (the pipeline's static-shape batching already guarantees this for
    power-of-two batch sizes).

    The transfer ledger is charged PER MATERIALIZED SHARD (summed
    ``addressable_shards`` bytes), not once per logical array: under
    ``data=N`` the device-side bytes are what ``--report --memory``
    reconciles against, and a replicated placement really does move N
    copies over the interconnect.
    """
    faults.inject("mesh.dispatch")
    out = []
    nbytes = 0
    for a in arrays:
        placed = jax.device_put(a, data_sharding(mesh, np.ndim(a)))
        nbytes += materialized_shard_bytes(placed)
        out.append(placed)
    obs_transfers.h2d("transfer.h2d", None, nbytes=nbytes)
    mark_mesh_slices(mesh)
    return tuple(out) if len(out) > 1 else out[0]


def degrade_mesh(mesh: Mesh) -> Mesh | None:
    """The surviving mesh after one data slice is lost, or ``None`` when
    the data axis cannot shrink (already 1 — nothing left to degrade to;
    the caller re-raises and the run dies honestly).

    The new data axis is the largest power of two <= (n_data - 1), over
    the FIRST surviving devices of the old mesh: power-of-two keeps the
    pipeline's batch-divisibility discipline (pad-to-multiple batching,
    pow2 compile-shape buckets) intact through the degradation, so the
    re-dispatched node runs the exact single-chip program per slice —
    just fewer slices. Non-data axes are preserved.
    """
    axes = dict(zip(mesh.axis_names, mesh.devices.shape))
    n_data = axes.get("data", 1)
    if n_data <= 1:
        return None
    new_n = 1
    while new_n * 2 <= n_data - 1:
        new_n *= 2
    axes["data"] = new_n
    names = tuple(axes)
    sizes = tuple(axes[n] for n in names)
    survivors = list(mesh.devices.flat)[: int(np.prod(sizes))]
    lost = [d for d in mesh.devices.flat if d not in survivors]
    new_mesh = Mesh(np.array(survivors).reshape(sizes), names)
    if obs_metrics.armed():
        for d in lost:
            obs_metrics.mesh_slice_set(f"{d.platform}:{d.id}", 0.0)
    mark_mesh_slices(new_mesh)
    hook = jobscope.get("degrade_hook")
    if hook is None:
        hook = getattr(_TLS, "degrade_hook", None)
    if hook is not None:
        try:
            hook(lost)
        except Exception:
            pass  # quarantine bookkeeping must never fail the degrade path
    return new_mesh


def node_sharding_plan(spec, mesh: Mesh) -> dict[str, dict]:
    """Per-node paired in/out shardings from the graph's declared
    :attr:`Edge.sharding` specs — the pjit discipline made executable.

    For every node, each hbm edge with a declared sharding maps to a
    :class:`NamedSharding` whose leading axis is the declared mesh axis
    (the batch axis; trailing dims replicated — ndim is resolved at
    placement time via :func:`data_sharding`, the plan stores the leading
    axis name). Producer out specs equal consumer in specs BY
    CONSTRUCTION of the graph (graftcheck's reshard-site lint is a hard
    violation), so stage boundaries never reshard. Returns
    ``{node: {"in": {edge: axis}, "out": {edge: axis}}}`` for nodes
    touching at least one declared edge.
    """
    plan: dict[str, dict] = {}
    for node in spec.schedule:
        ins = {
            e: spec.edges[e].sharding for e in node.inputs
            if e in spec.edges and spec.edges[e].placement == "hbm"
            and spec.edges[e].sharding is not None
        }
        outs = {
            e: spec.edges[e].sharding for e in node.outputs
            if e in spec.edges and spec.edges[e].placement == "hbm"
            and spec.edges[e].sharding is not None
        }
        if ins or outs:
            plan[node.name] = {"in": ins, "out": outs}
    return plan


def axis_sharding(mesh: Mesh, axis: str, ndim: int) -> NamedSharding:
    """NamedSharding splitting the leading dim over ``axis`` (the runtime
    face of one :func:`node_sharding_plan` entry)."""
    return NamedSharding(mesh, P(axis, *([None] * (ndim - 1))))


def polisher_param_sharding(mesh: Mesh, params) -> dict:
    """Tensor-parallel layout for the polisher: Dense kernels split on the
    output-feature axis over "model"; biases and GRU cells replicated.

    (The reference has no model parallelism at all — SURVEY §2.3; this is
    the TP story for the one neural component in the pipeline.)
    """
    has_model = "model" in mesh.axis_names

    def spec_for(path, leaf):
        name = "/".join(str(p.key) for p in path if hasattr(p, "key"))
        if has_model and leaf.ndim == 2 and name.endswith("kernel"):
            if "embed" in name:
                # column-parallel: split the hidden (output) features
                return NamedSharding(mesh, P(None, "model"))
            if "head" in name:
                # row-parallel: the class dim (5) is indivisible, split inputs
                return NamedSharding(mesh, P("model", None))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map_with_path(spec_for, params)


def sharded_train_step(mesh: Mesh, optimizer):
    """The polisher train step jitted over the mesh: dp on the batch,
    tp on the dense kernels. Returns (step_fn, place_params, place_batch)."""
    from ont_tcrconsensus_tpu.models import polisher as polisher_mod

    base_step = polisher_mod.make_train_step(optimizer)

    def place_params(params):
        # replicated params materialize one copy PER device: the shard sum
        # is the honest h2d charge, not the logical tree size
        placed = jax.device_put(params, polisher_param_sharding(mesh, params))
        obs_transfers.h2d("transfer.h2d", None, nbytes=sum(
            materialized_shard_bytes(leaf)
            for leaf in jax.tree_util.tree_leaves(placed)
        ))
        return placed

    def place_batch(feats, labels, ins_labels, mask):
        placed = (
            jax.device_put(feats, data_sharding(mesh, 3)),
            jax.device_put(labels, data_sharding(mesh, 2)),
            jax.device_put(ins_labels, data_sharding(mesh, 2)),
            jax.device_put(mask, data_sharding(mesh, 2)),
        )
        obs_transfers.h2d("transfer.h2d", None, nbytes=sum(
            materialized_shard_bytes(p) for p in placed
        ))
        return placed

    return jax.jit(base_step), place_params, place_batch
