"""Kernel-level device microbench: certify TPU performance in <60 s of uptime.

VERDICT r3 #1: two rounds ended with no device-verified number because the
only perf harness was the full pipeline bench (minutes of dataset build +
pipeline run).  This bench measures the four hot device kernels on ONE
synthetic batch each, writes partial JSON after every kernel (a mid-run
tunnel death keeps what was captured), and uses a persistent compilation
cache so a retry after an outage skips every compile.

Kernels and their units:
  sw      banded affine SW forward (ops.sw_pallas.align_banded_pallas)
          vs the XLA-scan kernel (ops.sw_align.align_banded) on the SAME
          shapes — certifies the claimed HBM-traffic win on-chip.
          unit: Gcell/s (cells = pairs * rows * band).
  pileup  pileup forward planes (ops.pileup_pallas.forward_planes_pallas).
          unit: Gcell/s.
  rnn     polisher inference (models.polisher.apply_logits), the medaka-RNN
          analog. unit: clusters/s (batch rows per second).
  rnn_bf16  the same network served in bfloat16 (the exactness-A/B-gated
          polish fast path) — certifies the MXU-rate win on-chip.
  fused   the production fused assign pass (pipeline.assign.AssignEngine)
          on one encoded read batch. unit: reads/s.

Usage:
  python kernel_bench.py                   # all kernels -> KERNEL_BENCH.json
  python kernel_bench.py --kernel sw       # one kernel
  python kernel_bench.py --force-cpu       # dev run on host CPU

Reference baselines: the XLA-scan SW kernel's ~0.2 Gcell/s HBM-bound rate
(ops/sw_pallas.py module docstring) and the CPU pipeline's ~884 reads/s
node rate (BASELINE.md).
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))

# --- roofline reporting (VERDICT r4 #5; recalibrated r6) -------------------
# The banded-DP kernels are VPU work (int32 adds/max/selects on (8,128)
# vector registers; the MXU never sees them). The r5 analytic model —
# "40 VPU ops/cell vs an 8x128x4-ALU x 1.67 GHz = 6.84e12 ops/s peak" —
# produced mfu_est = 1.1114 for the SW kernel, i.e. the model is WRONG
# (VERDICT r5 weak #4): an honest recount of sw_pallas._row_step puts the
# F shift-doubling cascade alone at ~5 ops x log2(128) = 35 ops/cell
# (it is NOT amortizable — every pass touches every lane), ~57 total, so
# the measured 190 Gcell/s implies >= 10.8e12 lane-ops/s — above the
# public-number ALU estimate. Either the VPU sustains more ops/cycle than
# the 4-ALU figure or Mosaic fuses cmp+select chains; both are invisible
# from here. An uncalibratable analytic peak is not a roofline, so the
# report now states utilization against the best MEASURED on-chip rate
# (provenance below) and keeps the op count only as descriptive context.
MEASURED_PEAK_GCELLS = {
    # best observed on-chip rates at these exact shapes: KERNEL_BENCH.json
    # captured 2026-08-02 on TPU v5 lite (round 5)
    "sw": 190.066,
    "pileup": 65.941,
}
PEAK_PROVENANCE = "best on-chip capture 2026-08-02, TPU v5 lite (r5)"
# The lane-packed pileup layout claims ~2x the pre-packing rate; the
# committed KERNEL_BENCH.json must say whether the claim held on-chip, so
# bench_pileup carries an explicit certification verdict against this
# target instead of leaving the 65.9 Gcell/s capture to speak for itself.
LANE_PACKED_TARGET_GCELLS = 100.0
# MXU peak for the RNN serving matmuls (v5e bf16; fp32 serving runs lower,
# so this mfu_est is a lower bound on achievable headroom).
PEAK_MXU_FLOPS_V5E = 197e12


def _vs_measured_peak(gcells: float, kernel: str) -> float:
    return round(gcells / MEASURED_PEAK_GCELLS[kernel], 4)


SW_PAIRS = 256
SW_LEN = 2048
SW_BAND = 128          # production band (pipeline/assign.py band_width=128)
PILEUP_LANES = 128
PILEUP_LEN = 2048
PILEUP_BAND = 64       # production band (ops/consensus.py pileup path)
RNN_BATCH = 64
RNN_LEN = 2048
FUSED_READS = 1024


def _timed(fn, *args, iters: int, **kwargs):
    """(compile_s, steady_s_per_iter). Blocks on every output leaf."""
    import jax

    t0 = time.perf_counter()
    out = fn(*args, **kwargs)
    jax.block_until_ready(out)
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args, **kwargs)
    jax.block_until_ready(out)
    return compile_s, (time.perf_counter() - t0) / iters


def _rng_pairs(rng, n, length, divergence=0.1):
    """Synthetic read/ref pairs with realistic ~90% identity so alignment
    paths wander within the band (all-match inputs would undersell the
    selects)."""
    import numpy as np

    refs = rng.integers(0, 4, size=(n, length), dtype=np.uint8)
    reads = refs.copy()
    flip = rng.random((n, length)) < divergence
    reads[flip] = (reads[flip] + rng.integers(1, 4, size=int(flip.sum()))) % 4
    lens = np.full((n,), length, np.int32)
    return reads, lens, refs, lens.copy()


def bench_sw(iters: int) -> dict:
    import jax
    import numpy as np

    from ont_tcrconsensus_tpu.ops import sw_align, sw_pallas

    if jax.default_backend() == "cpu":
        # compiled Pallas needs an accelerator; interpret mode would measure
        # the interpreter, not the kernel (and the XLA baseline is only
        # interesting as the on-chip ratio)
        return {
            "metric": "sw_pallas_gcells_per_sec", "value": None,
            "unit": "Gcell/s", "note": "pallas skipped on cpu backend",
        }
    rng = np.random.default_rng(7)
    reads, rlens, refs, tlens = _rng_pairs(rng, SW_PAIRS, SW_LEN)
    offs = np.zeros((SW_PAIRS,), np.int32)
    cells = SW_PAIRS * SW_LEN * SW_BAND

    comp_p, dt_p = _timed(
        sw_pallas.align_banded_pallas, reads, rlens, refs, tlens, offs,
        band_width=SW_BAND, iters=iters,
    )
    # XLA-scan baseline on identical shapes (the ~0.2 Gcell/s HBM-bound
    # kernel the Pallas one claims to beat); fewer iters, it is slower
    comp_x, dt_x = _timed(
        sw_align.align_banded, reads, rlens, refs, tlens, offs,
        band_width=SW_BAND, iters=max(1, iters // 4),
    )
    gc = cells / dt_p / 1e9
    return {
        "metric": "sw_pallas_gcells_per_sec",
        "value": round(gc, 3),
        "unit": "Gcell/s",
        "xla_scan_gcells_per_sec": round(cells / dt_x / 1e9, 3),
        "speedup_vs_xla_scan": round(dt_x / dt_p, 2),
        "vs_measured_peak": _vs_measured_peak(gc, "sw"),
        "peak_model": f"{MEASURED_PEAK_GCELLS['sw']} Gcell/s, "
                      f"{PEAK_PROVENANCE}; ~57 VPU ops/cell "
                      "(descriptive — the r5 analytic ALU peak measured "
                      ">1.0 'MFU' and is retired as uncalibratable)",
        "shapes": {"pairs": SW_PAIRS, "len": SW_LEN, "band": SW_BAND},
        "compile_s": round(comp_p, 1),
        "iter_ms": round(dt_p * 1e3, 2),
    }


def bench_pileup(iters: int) -> dict:
    import jax
    import numpy as np

    from ont_tcrconsensus_tpu.ops import pileup_pallas

    if jax.default_backend() == "cpu":
        return {
            "metric": "pileup_pallas_gcells_per_sec", "value": None,
            "unit": "Gcell/s", "note": "pallas skipped on cpu backend",
        }
    rng = np.random.default_rng(11)
    reads, rlens, refs, tlens = _rng_pairs(rng, PILEUP_LANES, PILEUP_LEN)
    cells = PILEUP_LANES * PILEUP_LEN * PILEUP_BAND

    comp, dt = _timed(
        pileup_pallas.forward_planes_pallas, reads, rlens, refs, tlens,
        band_width=PILEUP_BAND, iters=iters,
    )
    gc = cells / dt / 1e9
    return {
        "metric": "pileup_pallas_gcells_per_sec",
        "value": round(gc, 3),
        "unit": "Gcell/s",
        "vs_measured_peak": _vs_measured_peak(gc, "pileup"),
        "peak_model": f"{MEASURED_PEAK_GCELLS['pileup']} Gcell/s, "
                      f"{PEAK_PROVENANCE} (pre-lane-packing layout; the "
                      "packed kernel targets ~2x of it)",
        "lane_packed_target_gcells": LANE_PACKED_TARGET_GCELLS,
        "lane_packed_certified": bool(gc >= LANE_PACKED_TARGET_GCELLS),
        "shapes": {"lanes": PILEUP_LANES, "len": PILEUP_LEN, "band": PILEUP_BAND},
        "compile_s": round(comp, 1),
        "iter_ms": round(dt * 1e3, 2),
    }


def bench_rnn(iters: int) -> dict:
    return _bench_rnn(iters, bf16=False)


def bench_rnn_bf16(iters: int) -> dict:
    """The bf16 polish fast path (exactness-A/B-gated in serving,
    models/polisher.py): certifies the MXU-rate win on-chip. The A/B gate
    itself is separate evidence (scripts/bf16_ab.py) — this measures only
    the speed side."""
    return _bench_rnn(iters, bf16=True)


def _bench_rnn(iters: int, bf16: bool) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ont_tcrconsensus_tpu.models import polisher

    params = polisher.load_default_params()
    if params is None:
        params = polisher.init_params()
    fdim = polisher.params_feature_dim(params)  # served weights decide (v4: 25)
    rng = np.random.default_rng(13)
    feats = jnp.asarray(
        rng.random((RNN_BATCH, RNN_LEN, fdim), np.float32)
    )
    fn = jax.jit(functools.partial(polisher.apply_logits, bf16=bf16))
    comp, dt = _timed(fn, params, feats, iters=iters)
    # matmul flops per position = 2 * (sum of all 2-D kernel elements);
    # GRU gate matmuls dominate, so this is the roofline numerator
    kernels = [
        np.asarray(x) for x in jax.tree_util.tree_leaves(params)
        if getattr(x, "ndim", 0) == 2
    ]
    flops_per_pos = 2 * int(sum(k.size for k in kernels))
    pos_per_sec = RNN_BATCH * RNN_LEN / dt
    return {
        "metric": ("rnn_polish_bf16_clusters_per_sec" if bf16
                   else "rnn_polish_clusters_per_sec"),
        "value": round(RNN_BATCH / dt, 1),
        "unit": "clusters/s",
        "positions_per_sec": round(pos_per_sec, 0),
        "model_flops_per_pos": flops_per_pos,
        "mfu_est": round(pos_per_sec * flops_per_pos / PEAK_MXU_FLOPS_V5E, 5),
        "mfu_model": f"2*params matmul flops/pos vs {PEAK_MXU_FLOPS_V5E:.0e} "
                     "bf16 v5e MXU peak"
                     + ("" if bf16 else " (fp32 serving: lower-bound est)"),
        "shapes": {"batch": RNN_BATCH, "len": RNN_LEN, "features": fdim},
        "compile_s": round(comp, 1),
        "iter_ms": round(dt * 1e3, 2),
    }


def bench_fused(iters: int) -> dict:
    return _bench_fused(iters, fast=False)


def bench_fused_fast(iters: int) -> dict:
    """The round-1 production configuration: SW only on the needy quarter
    (assign._fused_pass sw_subset_denom, DIVERGENCES #12). Certifies the
    fast path's on-chip win over the exact full-batch SW above."""
    return _bench_fused(iters, fast=True)


def _bench_fused(iters: int, fast: bool) -> dict:
    """The production fused pass (trim+EE+sketch+SW+UMI) on one batch."""
    import numpy as np

    from ont_tcrconsensus_tpu.io import bucketing, fastx, simulator
    from ont_tcrconsensus_tpu.pipeline import assign
    from ont_tcrconsensus_tpu.pipeline.config import RunConfig

    lib = simulator.simulate_library(
        seed=5,
        num_regions=24,
        molecules_per_region=(3, 5),
        reads_per_molecule=(8, 12),
        error_model=simulator.OntErrorModel(),
        with_adapters=True,
        num_similar_pairs=2,
        num_negative_controls=1,
    )
    cfg = RunConfig(reference_file="", fastq_pass_dir="")
    region_cluster = {name: i for i, name in enumerate(lib.reference)}
    panel = assign.ReferencePanel.build(lib.reference, region_cluster)
    engine = assign.AssignEngine(
        panel,
        umi_fwd=cfg.umi_fwd,
        umi_rev=cfg.umi_rev,
        primers=cfg.primer_sequences(),
    )
    recs = (
        fastx.FastxRecord(name=n_.split()[0], comment="", sequence=s, quality=q)
        for n_, s, q in lib.reads[:FUSED_READS]
    )
    batch = max(
        bucketing.batch_reads(recs, batch_size=FUSED_READS),
        key=lambda b: int(np.sum(b.lengths > 0)),
    )
    n = int(np.sum(batch.lengths > 0))

    def run():
        return engine.run_batch_async(
            batch, max_ee_rate=0.03, min_len=500,
            overlap_frac=0.95 if fast else None,
        )

    comp, dt = _timed(run, iters=iters)
    sys.path.insert(0, REPO)
    from bench import NORTH_STAR_READS_PER_SEC_PER_CHIP

    return {
        "metric": ("fused_assign_fast_reads_per_sec" if fast
                   else "fused_assign_reads_per_sec"),
        "value": round(n / dt, 1),
        "unit": "reads/s",
        # round-1 assign alone must beat the WHOLE-pipeline north star
        # by a comfortable margin for the end-to-end number to reach it
        "vs_north_star": round(n / dt / NORTH_STAR_READS_PER_SEC_PER_CHIP, 4),
        "shapes": {"reads": n, "padded_len": int(batch.codes.shape[1]),
                   "regions": len(lib.reference)},
        "compile_s": round(comp, 1),
        "iter_ms": round(dt * 1e3, 2),
    }


BENCHES = {
    "sw": bench_sw,
    "pileup": bench_pileup,
    "rnn": bench_rnn,
    "rnn_bf16": bench_rnn_bf16,
    "fused": bench_fused,
    "fused_fast": bench_fused_fast,
}


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--kernel", default="all", choices=["all", *BENCHES])
    ap.add_argument("--out", default=os.path.join(REPO, "KERNEL_BENCH.json"))
    ap.add_argument("--iters", type=int, default=8)
    ap.add_argument("--force-cpu", action="store_true")
    args = ap.parse_args()

    if not args.force_cpu:
        # jax.devices() hangs INDEFINITELY in-process when the axon tunnel
        # is wedged; gate backend init behind the killable subprocess probe
        # (the tunnel can still die in the window between probe and init —
        # callers like the capture loop keep an outer timeout for that).
        sys.path.insert(0, REPO)
        from bench import probe_once

        plat, detail = probe_once(timeout=90)
        if plat is None:
            print(f"kernel_bench: backend unreachable ({detail})",
                  file=sys.stderr)
            return 2

    import jax

    if args.force_cpu:
        # the axon plugin overrides JAX_PLATFORMS; config API is the only
        # reliable CPU override (tests/conftest.py has the full story)
        jax.config.update("jax_platforms", "cpu")
    jax.config.update(
        "jax_compilation_cache_dir", os.path.join(REPO, ".jax_kernel_cache")
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    dev = jax.devices()[0]
    prior = {}
    if os.path.exists(args.out):
        try:
            with open(args.out) as fh:
                prior = json.load(fh)
            if not isinstance(prior, dict):
                prior = {}
        except (json.JSONDecodeError, OSError):
            prior = {}
    if prior.get("platform") == "tpu" and dev.platform != "tpu":
        # NEVER overwrite scarce device evidence with a CPU dev run (e.g.
        # --force-cpu without --out, or a tunnel death downgrading the
        # backend mid-session): redirect the report, resuming from any
        # prior redirected report instead.
        args.out = args.out + ".cpu.json"
        print(
            f"kernel_bench: prior TPU results preserved; cpu report goes to "
            f"{args.out}", file=sys.stderr,
        )
        prior = {}
        if os.path.exists(args.out):
            try:
                with open(args.out) as fh:
                    prior = json.load(fh)
                if not isinstance(prior, dict):
                    prior = {}
            except (json.JSONDecodeError, OSError):
                prior = {}

    report = {
        "platform": dev.platform,
        "device_kind": dev.device_kind,
        "num_devices": jax.device_count(),
        "captured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "kernels": {},
    }
    if prior.get("platform") == dev.platform:
        report["kernels"] = prior.get("kernels", {})

    if args.kernel == "all":
        # incremental resume: a retry after a mid-list tunnel death only
        # runs the kernels still missing a result. "Missing" = no entry or
        # an error entry; a deliberate cpu-skip (value None + note) counts
        # as captured so CPU dev runs do not re-measure forever.
        def needs_run(entry: dict) -> bool:
            if not entry or "error" in entry:
                return True
            return entry.get("value") is None and "note" not in entry

        names = [
            n for n in BENCHES if needs_run(report["kernels"].get(n, {}))
        ]
        if not names:
            print("kernel_bench: all kernels already captured", file=sys.stderr)
            print(json.dumps({**report, "kernels": report["kernels"]}))
            return 0
    else:
        names = [args.kernel]
    rc = 0
    for name in names:
        t0 = time.perf_counter()
        try:
            res = BENCHES[name](args.iters)
        except Exception as exc:  # keep partials: a dead tunnel mid-list
            import traceback

            traceback.print_exc()
            res = {"error": f"{type(exc).__name__}: {str(exc)[:300]}"}
            rc = 1
        res["wall_s"] = round(time.perf_counter() - t0, 1)
        report["kernels"][name] = res
        # atomic partial write after EVERY kernel
        tmp = args.out + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(report, fh, indent=1)
        os.replace(tmp, args.out)
        print(f"kernel_bench: {name}: {res}", file=sys.stderr)

    print(json.dumps(report))
    return rc


if __name__ == "__main__":
    sys.exit(main())
