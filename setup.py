"""Install-time build of the native fastx parser (VERDICT r4 weak #5).

The C++ streaming parser (ont_tcrconsensus_tpu/io/native/fastx_parser.cpp)
used to be a committed binary; now it compiles at install into the build
tree (and so into wheels), best-effort: a host without g++/zlib still
installs fine and the runtime loader's build-on-first-use + pure-Python
fallback (io/native/__init__.py) take over.

The build is warning-clean under ``-Wall -Wextra`` (enforced: the flags
are always on). ``GRAFT_SANITIZE=address,undefined`` switches the build
to an ASan/UBSan instrumented library (``-O1 -g -fsanitize=...
-fno-omit-frame-pointer``) for the sanitized fuzz replay
(``scripts/fuzz_ingest.py --sanitized``); see README "Static analysis &
sanitized builds".
"""

from __future__ import annotations

import os
import subprocess

from setuptools import setup
from setuptools.command.build_py import build_py

SANITIZE_ENV = "GRAFT_SANITIZE"
WARN_FLAGS = ("-Wall", "-Wextra")


def native_build_command(src: str, out: str, sanitize: str | None) -> list[str]:
    """Mirror of io/native/__init__.py's build_command — setup.py cannot
    import the package it is about to build, so the flags live here too
    (tests/test_native.py pins the two in sync)."""
    if sanitize:
        opt = ["-O1", "-g", f"-fsanitize={sanitize}", "-fno-omit-frame-pointer"]
    else:
        opt = ["-O3"]
    return ["g++", *opt, *WARN_FLAGS, "-shared", "-fPIC", src, "-lz", "-o", out]


class BuildPyWithNativeParser(build_py):
    def run(self):
        super().run()
        native = os.path.join(
            self.build_lib, "ont_tcrconsensus_tpu", "io", "native"
        )
        src = os.path.join(native, "fastx_parser.cpp")
        out = os.path.join(native, "libfastx.so")
        if not os.path.exists(src):
            return
        sanitize = os.environ.get(SANITIZE_ENV) or None
        cmd = native_build_command(src, out, sanitize)
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=300)
            print(f"built native fastx parser: {out}"
                  + (f" (sanitize={sanitize})" if sanitize else ""))
        except Exception as exc:  # noqa: BLE001 — any failure means fallback
            print(
                "native fastx parser not built "
                f"({type(exc).__name__}); the pure-Python parser will be "
                "used (or build-on-first-use retries at runtime)"
            )


setup(cmdclass={"build_py": BuildPyWithNativeParser})
