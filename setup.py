"""Install-time build of the native fastx parser (VERDICT r4 weak #5).

The C++ streaming parser (ont_tcrconsensus_tpu/io/native/fastx_parser.cpp)
used to be a committed binary; now it compiles at install into the build
tree (and so into wheels), best-effort: a host without g++/zlib still
installs fine and the runtime loader's build-on-first-use + pure-Python
fallback (io/native/__init__.py) take over.
"""

from __future__ import annotations

import os
import subprocess

from setuptools import setup
from setuptools.command.build_py import build_py


class BuildPyWithNativeParser(build_py):
    def run(self):
        super().run()
        native = os.path.join(
            self.build_lib, "ont_tcrconsensus_tpu", "io", "native"
        )
        src = os.path.join(native, "fastx_parser.cpp")
        out = os.path.join(native, "libfastx.so")
        if not os.path.exists(src):
            return
        cmd = ["g++", "-O3", "-shared", "-fPIC", src, "-lz", "-o", out]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=300)
            print(f"built native fastx parser: {out}")
        except Exception as exc:  # noqa: BLE001 — any failure means fallback
            print(
                "native fastx parser not built "
                f"({type(exc).__name__}); the pure-Python parser will be "
                "used (or build-on-first-use retries at runtime)"
            )


setup(cmdclass={"build_py": BuildPyWithNativeParser})
