"""Benchmark: end-to-end pipeline throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric: reads/sec through the complete two-round consensus pipeline
(EE filter -> align/assign -> UMI extract -> cluster -> subread select ->
vote consensus (+RNN polish if bundled) -> consensus align/filter -> round-2
dedup -> counts) on a simulated library, measured on the second run so
compile time is excluded (caches are warm in-process).

Baseline: the reference CPU pipeline processes ~70M reads in 20-24h on a
110-CPU Xeon Silver node (BASELINE.md) => ~884 reads/s for the whole node.
vs_baseline = our single-chip reads/s divided by that node rate.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import time

REFERENCE_NODE_READS_PER_SEC = 70e6 / (22 * 3600)  # ~884, BASELINE.md midpoint


def build_dataset(root: str, seed: int = 33):
    from ont_tcrconsensus_tpu.io import fastx, simulator

    lib = simulator.simulate_library(
        seed=seed,
        num_regions=8,
        molecules_per_region=(6, 10),
        reads_per_molecule=(6, 12),
        sub_rate=0.01,
        ins_rate=0.004,
        del_rate=0.004,
    )
    os.makedirs(os.path.join(root, "fastq_pass", "barcode01"), exist_ok=True)
    fastx.write_fasta(os.path.join(root, "reference.fa"), lib.reference.items())
    fastx.write_fastq(
        os.path.join(root, "fastq_pass", "barcode01", "barcode01.fastq.gz"), lib.reads
    )
    return lib


def run_once(root: str):
    from ont_tcrconsensus_tpu.pipeline.config import RunConfig
    from ont_tcrconsensus_tpu.pipeline.run import run_with_config

    shutil.rmtree(os.path.join(root, "fastq_pass", "nano_tcr"), ignore_errors=True)
    cfg = RunConfig.from_dict({
        "reference_file": os.path.join(root, "reference.fa"),
        "fastq_pass_dir": os.path.join(root, "fastq_pass"),
        "minimal_length": 1000,
        "min_reads_per_cluster": 4,
        "read_batch_size": 256,
        "delete_tmp_files": True,
    })
    t0 = time.time()
    results = run_with_config(cfg)
    dt = time.time() - t0
    return results, dt


def main():
    root = "/tmp/ont_tcr_bench"
    shutil.rmtree(root, ignore_errors=True)
    lib = build_dataset(root)
    n_reads = len(lib.reads)

    # warm-up run compiles every kernel; timed run measures steady state
    _, warm_dt = run_once(root)
    results, dt = run_once(root)

    counts_ok = results.get("barcode01") == lib.true_counts
    reads_per_sec = n_reads / dt
    print(
        f"bench: {n_reads} reads, warm {warm_dt:.1f}s, timed {dt:.1f}s, "
        f"counts_exact={counts_ok}",
        file=sys.stderr,
    )
    print(json.dumps({
        "metric": "pipeline_reads_per_sec_per_chip",
        "value": round(reads_per_sec, 2),
        "unit": "reads/s",
        "vs_baseline": round(reads_per_sec / REFERENCE_NODE_READS_PER_SEC, 4),
    }))


if __name__ == "__main__":
    main()
