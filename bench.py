"""Benchmark: end-to-end pipeline throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric: reads/sec through the complete two-round consensus pipeline
(primer trim -> EE filter -> align/assign -> UMI extract -> cluster ->
subread select -> vote consensus (+RNN polish if bundled) -> consensus
align/filter -> round-2 dedup -> counts) on a representative simulated
library, measured on the second run so compile time is excluded.

Representative means (VERDICT r1 #5): >=10k untrimmed reads with ragged
1.4-2.3 kb lengths, a homologous reference panel (near-duplicate region
pairs at ~1% divergence, like real TCR libraries sharing V segments) plus
negative-control regions, full adapter+primer ends so the trim stage is
exercised, and — since round 3 — the SYSTEMATIC ONT error model
(homopolymer-length-dependent indels, context-biased substitutions, strand
asymmetry; io/simulator.OntErrorModel) instead of iid errors. Stderr
reports the per-stage timing breakdown, read->region assignment accuracy
vs ground truth, and counts_exact vs the simulator.

Baseline: the reference CPU pipeline processes ~70M reads in 20-24h on a
110-CPU Xeon Silver node (BASELINE.md) => ~884 reads/s for the whole node.
vs_baseline = our single-chip reads/s divided by that node rate.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import time

REFERENCE_NODE_READS_PER_SEC = 70e6 / (22 * 3600)  # ~884, BASELINE.md midpoint

# North star (BASELINE.md): the whole 70M-read library in <1 h on a v5e-8 —
# ~2,430 reads/s/chip. vs_north_star in the JSON line makes every capture
# self-interpreting against that bar (VERDICT r4 #5).
NORTH_STAR_READS_PER_SEC_PER_CHIP = 70e6 / 3600 / 8

NUM_READS_TARGET = 10_000


def probe_once(timeout: float = 75.0) -> tuple[str | None, str]:
    """One timeout-wrapped subprocess backend probe.

    Returns (platform | None, detail).  Shared by probe_backend and
    scripts/device_capture_loop.py — jax.devices() hangs indefinitely when
    the axon tunnel is wedged, so the probe must run in a killable child.
    """
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; d = jax.devices(); print(d[0].platform)"],
            capture_output=True, text=True, timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return None, "probe timed out"
    if proc.returncode != 0:
        tail = (proc.stderr or "").strip().splitlines()[-1:] or ["?"]
        return None, tail[0]
    return proc.stdout.strip() or None, "ok"


def probe_backend(deadline_sec: float = 900.0, attempt_timeout: float = 300.0) -> bool:
    """Wait for a usable jax backend BEFORE building the dataset.

    Round-2's capture died with rc=1 because a transient tunnel outage made
    ``jax.devices()`` raise AFTER minutes of dataset building (VERDICT r2
    missing #4).  jax caches backend-discovery failures in-process, so each
    attempt runs in a fresh subprocess; we retry with backoff until the
    deadline.  Returns True when a backend answered, False when the deadline
    passed without one.
    """
    t0 = time.time()
    attempt = 0
    while True:
        attempt += 1
        remaining = deadline_sec - (time.time() - t0)
        if remaining <= 0:
            return False
        plat, detail = probe_once(min(attempt_timeout, max(remaining, 30.0)))
        if plat is not None:
            print(
                f"bench: backend up ({plat}) after "
                f"{time.time() - t0:.0f}s, attempt {attempt}",
                file=sys.stderr,
            )
            return True
        print(f"bench: backend probe {attempt} failed: {detail}", file=sys.stderr)
        time.sleep(min(30.0, max(5.0, remaining * 0.05)))


def build_dataset(root: str, seed: int = 33):
    from ont_tcrconsensus_tpu.io import fastx, simulator

    # BENCH_READS scales the dataset down for CPU-side diagnostics (the
    # driver's TPU runs keep the full default); regions scale with reads so
    # the workload stays shape-representative.
    target = int(os.environ.get("BENCH_READS", NUM_READS_TARGET))
    frac = max(min(target / NUM_READS_TARGET, 1.0), 0.02)
    lib = simulator.simulate_library(
        seed=seed,
        num_regions=max(int(56 * frac), 6),
        molecules_per_region=(8, 14),
        reads_per_molecule=(12, 22),
        error_model=simulator.OntErrorModel(),
        with_adapters=True,
        num_similar_pairs=max(int(6 * frac), 1),
        similar_divergence=0.01,
        num_negative_controls=max(int(2 * frac), 1),
    )
    os.makedirs(os.path.join(root, "fastq_pass", "barcode01"), exist_ok=True)
    fastx.write_fasta(os.path.join(root, "reference.fa"), lib.reference.items())
    fastx.write_fastq(
        os.path.join(root, "fastq_pass", "barcode01", "barcode01.fastq.gz"), lib.reads
    )
    return lib


def parse_mesh_spec(spec: str) -> dict[str, int]:
    """``"data=8"`` / ``"data=4,model=2"`` -> {"data": 8, "model": 2}.

    The axis order is preserved (it is the mesh's device-grid order);
    values must be positive ints and a ``data`` axis is required — the
    bench's sharded arm is the data-parallel scaling story.
    """
    shape: dict[str, int] = {}
    for part in spec.split(","):
        if "=" not in part:
            raise ValueError(f"--mesh axis {part!r} is not name=N")
        name, _, val = part.partition("=")
        n = int(val)
        if n < 1:
            raise ValueError(f"--mesh axis {name!r} size {n} must be >= 1")
        shape[name.strip()] = n
    if "data" not in shape:
        raise ValueError(f"--mesh {spec!r} needs a 'data' axis")
    return shape


def run_once(root: str, live_port: int | None = None, mesh_shape=None):
    from ont_tcrconsensus_tpu.pipeline.config import RunConfig
    from ont_tcrconsensus_tpu.pipeline.run import run_with_config

    shutil.rmtree(os.path.join(root, "fastq_pass", "nano_tcr"), ignore_errors=True)
    raw = {
        "reference_file": os.path.join(root, "reference.fa"),
        "fastq_pass_dir": os.path.join(root, "fastq_pass"),
        "minimal_length": 1000,
        "min_reads_per_cluster": 4,
        "read_batch_size": 1024,
        "delete_tmp_files": False,
    }
    if live_port is not None:
        raw["live_port"] = live_port
    if mesh_shape:
        raw["mesh_shape"] = dict(mesh_shape)
    cfg = RunConfig.from_dict(raw)
    t0 = time.time()
    results = run_with_config(cfg)
    dt = time.time() - t0
    return results, dt, cfg


def run_daemon_bench(root: str, args,
                     mesh_shape=None) -> tuple[float, float, dict, object]:
    """The --daemon arm: cold-start vs steady-state through the warm-serving
    daemon (serve/daemon.py) instead of two bare run_with_config calls.

    Cold-start = daemon construction -> first job done (template validation,
    compile-cache arming, AOT bucket prewarm, and the first job's residual
    compiles all included — the number the ≤10s goal is judged against once
    the persistent cache is primed). Steady-state = the second job's
    dispatch-to-done seconds through the already-warm process; its
    telemetry.json compile count ~0 is the ROADMAP-3 success signal.
    Returns (cold_s, steady_s, steady job snapshot, daemon).
    """
    import threading

    from ont_tcrconsensus_tpu.serve.daemon import Daemon

    shutil.rmtree(os.path.join(root, "fastq_pass", "nano_tcr"),
                  ignore_errors=True)
    template = {
        "reference_file": os.path.join(root, "reference.fa"),
        "fastq_pass_dir": os.path.join(root, "fastq_pass"),
        "minimal_length": 1000,
        "min_reads_per_cluster": 4,
        "read_batch_size": 1024,
        "delete_tmp_files": False,
    }
    workers = 1
    if mesh_shape:
        # --mesh + --daemon: the shape pins every job's slice through the
        # serve-plane allocator (serve/slices.py sizes the lease by the
        # axis product), so the bench jobs really run sharded — this used
        # to be silently ignored
        template["mesh_shape"] = dict(mesh_shape)
        workers = 2
    t0 = time.time()
    daemon = Daemon(template, port=args.live_port or 0,
                    state_dir=os.path.join(root, "serve_state"),
                    workers=workers)
    loop = threading.Thread(target=daemon.serve_forever,
                            name="bench-daemon", daemon=True)
    loop.start()

    def run_job() -> dict:
        status, snap = daemon.submit({})
        if status != 202:
            raise RuntimeError(f"daemon rejected the bench job "
                               f"({status}): {snap}")
        deadline = time.time() + 3600.0
        while time.time() < deadline:
            cur = daemon.job_snapshot(snap["id"])
            if cur is not None and cur["state"] in ("done", "failed"):
                if cur["state"] == "failed":
                    raise RuntimeError(
                        f"{snap['id']} failed: {cur['error']}")
                return cur
            time.sleep(0.2)
        raise RuntimeError(f"{snap['id']} did not finish within an hour")

    try:
        run_job()
        cold_s = time.time() - t0
        # fresh output tree: the steady-state job is a new tenant, not a
        # resume of the first one
        shutil.rmtree(os.path.join(root, "fastq_pass", "nano_tcr"))
        job2 = run_job()
        steady_s = job2["finished_t"] - job2["started_t"]
    finally:
        daemon.request_stop()
        loop.join(timeout=60.0)
    return cold_s, steady_s, job2, daemon


def assignment_accuracy(root: str, lib) -> float:
    """Fraction of round-1 surviving reads binned into the region cluster
    that contains their true region (ground truth from simulator headers)."""
    import glob

    region_of_mol = {i: m.region for i, m in enumerate(lib.molecules)}
    nano = os.path.join(root, "fastq_pass", "nano_tcr")
    with open(os.path.join(nano, "region_cluster_dict.json")) as fh:
        region_cluster = json.load(fh)
    ok = n = 0
    lib_dirs = glob.glob(os.path.join(nano, "*", "region_cluster_fasta"))
    for d in lib_dirs:
        for fa in glob.glob(os.path.join(d, "region_cluster*.fasta")):
            cluster_id = int(
                os.path.basename(fa)[len("region_cluster"):-len(".fasta")]
            )
            with open(fa) as fh:
                for line in fh:
                    if not line.startswith(">"):
                        continue
                    mol = int(line.split("_m", 1)[1].split("_", 1)[0])
                    n += 1
                    if region_cluster[region_of_mol[mol]] == cluster_id:
                        ok += 1
    return ok / n if n else 0.0


def read_raw_telemetry(root: str) -> dict | None:
    """The timed run's telemetry.json payload (None when absent/garbage)."""
    path = os.path.join(root, "fastq_pass", "nano_tcr", "telemetry.json")
    try:
        with open(path) as fh:
            tele = json.load(fh)
    except (OSError, ValueError):
        return None
    return tele if isinstance(tele, dict) else None


def read_telemetry_summary(root: str) -> dict | None:
    """Compact telemetry roll-up for the bench JSON line: per-site dispatch
    counts + host-gap/block totals, compile count/seconds, HBM high-water
    and peak host RSS — the numbers ROADMAP items 1 and 3 are blocked on,
    committed with every capture (nano_tcr/telemetry.json, obs/report.py)."""
    tele = read_raw_telemetry(root)
    if tele is None:
        return None
    gauges = tele.get("gauges", {})
    return {
        "dispatch": tele.get("dispatch", {}),
        "compile": {
            "count": tele.get("compile", {}).get("count", 0),
            "seconds": tele.get("compile", {}).get("seconds", 0.0),
        },
        "hbm_high_water_bytes": gauges.get("device.hbm_bytes_in_use"),
        "peak_host_rss_bytes": gauges.get("host.rss_bytes"),
    }


def read_stage_timing(root: str) -> dict[str, float]:
    import glob

    out: dict[str, float] = {}
    for tsv in glob.glob(os.path.join(
        root, "fastq_pass", "nano_tcr", "*", "logs", "stage_timing.tsv"
    )):
        with open(tsv) as fh:
            next(fh)
            for line in fh:
                stage, sec, _ = line.split("\t")
                out[stage] = out.get(stage, 0.0) + float(sec)
    return out


def emit(value: float, extra: dict | None = None) -> None:
    line = {
        "metric": "pipeline_reads_per_sec_per_chip",
        "value": round(value, 2),
        "unit": "reads/s",
        "vs_baseline": round(value / REFERENCE_NODE_READS_PER_SEC, 4),
        "vs_north_star": round(value / NORTH_STAR_READS_PER_SEC_PER_CHIP, 4),
    }
    if extra:
        line.update(extra)
    print(json.dumps(line))


def parse_args(argv=None):
    import argparse

    ap = argparse.ArgumentParser(
        description="End-to-end pipeline throughput bench (one JSON line)."
    )
    ap.add_argument(
        "--ledger", default=os.environ.get("BENCH_HISTORY"),
        help="cross-run history ledger (.jsonl) to append this capture to "
        "(obs/history.py schema — the same entry run.py writes to "
        "nano_tcr/history.jsonl); defaults to the BENCH_HISTORY env var",
    )
    ap.add_argument(
        "--gate", action="store_true",
        help="gate this capture against the ledger baseline "
        "(scripts/perf_gate.py math: median + MAD over matching "
        "fingerprint/backend/n_reads entries) and exit 1 on regression; "
        "the capture is appended to the ledger either way",
    )
    ap.add_argument(
        "--daemon", action="store_true",
        help="run the jobs through the warm-serving daemon (serve/) "
        "instead of two bare pipeline calls: cold-start (daemon start + "
        "AOT prewarm + first job) and steady-state (second job through "
        "the warm process) land as warmup_s/steady_s in the JSON line "
        "and the ledger entry",
    )
    ap.add_argument(
        "--live-port", type=int, default=None, metavar="PORT",
        help="arm the live observability plane (obs/live.py) for the bench "
        "runs: /healthz, /metrics, /progress on 127.0.0.1:PORT (0 = "
        "ephemeral) — lets an operator watch a long TPU capture mid-flight",
    )
    ap.add_argument(
        "--mesh", default=None, metavar="SPEC",
        help="run the pipeline sharded over a device mesh, e.g. "
        "'data=8' or 'data=4,model=2' (parallel/mesh.py): batches split "
        "over the data axis, counts stay identical to the single-device "
        "run. Without enough physical devices the needed count is forced "
        "via XLA_FLAGS --xla_force_host_platform_device_count (virtual "
        "CPU devices — relative scaling only). The mesh config lands as "
        "'mesh_config' in the JSON line and the ledger entry, so per-"
        "mesh scaling history gates only against its own shape. With "
        "--daemon the shape is threaded into the serve template and pins "
        "each bench job's slice through the serve-plane slice allocator.",
    )
    ap.add_argument("--gate-threshold", type=float, default=0.15)
    ap.add_argument("--gate-mad-k", type=float, default=4.0)
    ap.add_argument("--gate-min-samples", type=int, default=3)
    ap.add_argument(
        "--rt-budget", type=float,
        default=float(os.environ.get("BENCH_RT_BUDGET", "0")),
        help="absolute host_round_trip_bytes budget for --gate (bytes; "
        "default 0 or the BENCH_RT_BUDGET env var): the data plane is "
        "device-resident, so ANY measured round-trip fails the gate even "
        "on a thin ledger; pass a negative value to fall back to the "
        "relative median+MAD gate over the ledger baseline",
    )
    return ap.parse_args(argv)


def main(argv=None) -> int:
    args = parse_args(argv)
    if args.gate and not args.ledger:
        print("bench: --gate needs a ledger (--ledger or BENCH_HISTORY)",
              file=sys.stderr)
        return 2
    mesh_shape = None
    if args.mesh:
        mesh_shape = parse_mesh_spec(args.mesh)
        # the device-count force must land in the environment BEFORE
        # any jax import in this process (the flag is read at backend
        # init); harmless on a real multi-chip backend, and exactly
        # how tests/conftest.py builds its virtual 8-device mesh
        total = 1
        for n in mesh_shape.values():
            total *= n
        flags = os.environ.get("XLA_FLAGS", "")
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={total}"
        ).strip()
        arm = "daemon (slice-allocator)" if args.daemon else "sharded"
        print(f"bench: {arm} arm, mesh {mesh_shape}", file=sys.stderr)
    # Probe FIRST so a dead backend yields a diagnosable artifact (rc=0,
    # "tpu_unavailable") instead of a stack trace after minutes of setup.
    # BENCH_FORCE_CPU=1 is a dev-only escape hatch for relative timing when
    # the TPU tunnel is down (the axon plugin overrides JAX_PLATFORMS, so
    # the config API is the only reliable CPU override — see tests/conftest).
    if os.environ.get("BENCH_FORCE_CPU"):
        import jax

        jax.config.update("jax_platforms", "cpu")
        print("bench: BENCH_FORCE_CPU set; running on host CPU", file=sys.stderr)
    elif not probe_backend():
        # The tunnel is down RIGHT NOW — but scripts/device_capture_loop.py
        # may have captured a real-chip run earlier. ADVICE r4: never put
        # the stale number in `value` (dashboards read just that field and
        # would treat an old measurement as current) — the run's primary
        # result stays 0.0/tpu_unavailable and the prior capture rides
        # along under `last_known_good`, with its source file and mtime.
        # BENCH_NO_FALLBACK drops even that (the capture loop parses our
        # stdout into the capture files, so any echo here would launder an
        # old small capture into BENCH_TPU_CAPTURE_FULL).
        extra = {"error": "tpu_unavailable"}
        if not os.environ.get("BENCH_NO_FALLBACK"):
            for path in ("BENCH_TPU_CAPTURE_FULL.json", "BENCH_TPU_CAPTURE.json"):
                full = os.path.join(os.path.dirname(os.path.abspath(__file__)), path)
                try:
                    with open(full) as fh:
                        line = json.load(fh)
                    if (isinstance(line, dict)
                            and float(line.get("value", 0.0)) > 0.0):
                        extra["last_known_good"] = {
                            **line,
                            "source": path,
                            "captured_mtime": time.strftime(
                                "%Y-%m-%dT%H:%M:%SZ",
                                time.gmtime(os.path.getmtime(full)),
                            ),
                        }
                        break
                except (OSError, ValueError):
                    continue
        emit(0.0, extra)
        return 0

    root = "/tmp/ont_tcr_bench"
    shutil.rmtree(root, ignore_errors=True)
    lib = build_dataset(root)
    n_reads = len(lib.reads)

    # warm-up run compiles every kernel; timed run measures steady state.
    # --daemon measures the same split through the serve daemon instead.
    daemon_extra: dict | None = None
    try:
        if args.daemon:
            from ont_tcrconsensus_tpu.pipeline.config import RunConfig
            from ont_tcrconsensus_tpu.pipeline.run import _read_counts_csv

            warm_dt, dt, job2, daemon = run_daemon_bench(
                root, args, mesh_shape=mesh_shape)
            results = {"barcode01": _read_counts_csv(os.path.join(
                root, "fastq_pass", "nano_tcr", "barcode01", "counts",
                "umi_consensus_counts.csv"))}
            cfg = RunConfig.from_dict({
                "reference_file": os.path.join(root, "reference.fa"),
                "fastq_pass_dir": os.path.join(root, "fastq_pass"),
                "minimal_length": 1000,
                "min_reads_per_cluster": 4,
                "read_batch_size": 1024,
                "delete_tmp_files": False,
                **({"mesh_shape": dict(mesh_shape)} if mesh_shape else {}),
            })
            pre = daemon.prewarm_report or {}
            daemon_extra = {
                # cold start = daemon launch to "accepting jobs" (arm +
                # journal resume + AOT prewarm): the ROADMAP-3 <=10s claim
                "cold_start_s": (round(daemon.warmup_s, 3)
                                 if daemon.warmup_s is not None else None),
                "dispatch_first_stage_s": job2.get("first_stage_s"),
                "prewarm_compiled": pre.get("compiled", 0),
                "prewarm_failed": pre.get("failed", 0),
                "prewarm_seconds": pre.get("seconds", 0.0),
            }
        else:
            _, warm_dt, _ = run_once(root, live_port=args.live_port,
                                     mesh_shape=mesh_shape)
            results, dt, cfg = run_once(root, live_port=args.live_port,
                                        mesh_shape=mesh_shape)
    except Exception as exc:  # backend died mid-run: still record a JSON line
        import traceback

        traceback.print_exc()
        emit(0.0, {"error": f"{type(exc).__name__}: {str(exc)[:200]}"})
        return 0

    counts_ok = results.get("barcode01") == lib.true_counts
    acc = assignment_accuracy(root, lib)
    timing = read_stage_timing(root)
    reads_per_sec = n_reads / dt
    print(
        f"bench: {n_reads} reads ({len(lib.molecules)} molecules, "
        f"{len(lib.reference)} regions), warm {warm_dt:.1f}s, timed {dt:.1f}s, "
        f"counts_exact={counts_ok}, assignment_accuracy={acc:.4f}",
        file=sys.stderr,
    )
    if not counts_ok:
        got = results.get("barcode01", {})
        diff = {
            k: (got.get(k, 0), lib.true_counts.get(k, 0))
            for k in set(got) | set(lib.true_counts)
            if got.get(k, 0) != lib.true_counts.get(k, 0)
        }
        print(f"bench: count diffs (got, want): {diff}", file=sys.stderr)
    print(f"bench: stage timing {timing}", file=sys.stderr)
    # warm/steady split (cross-run schema shared with the serve ledger
    # entries): warmup_s is compile-dominated, steady_s is the number the
    # throughput claims rest on
    emit_extra = {"n_reads": n_reads, "counts_exact": counts_ok,
                  "warmup_s": round(warm_dt, 3), "steady_s": round(dt, 3)}
    if daemon_extra is not None:
        emit_extra["daemon"] = daemon_extra
    if mesh_shape:
        from ont_tcrconsensus_tpu.obs import history as _h

        emit_extra["mesh_config"] = _h.mesh_config_str(mesh_shape)
    # cross-run keys (obs/history.py): the committed BENCH_*.json line and
    # the history ledger share one schema, so a capture file IS a valid
    # baseline entry and trend scripts need no translation layer
    import jax

    from ont_tcrconsensus_tpu.obs import history as obs_history

    backend = jax.default_backend()
    fingerprint = obs_history.config_fingerprint(cfg)
    sha = obs_history.git_sha()
    emit_extra.update({
        "backend": backend, "config_fingerprint": fingerprint,
        "git_sha": sha,
    })
    telemetry = read_telemetry_summary(root)
    if telemetry is not None:
        # dispatch-tax + recompile + memory HWM summary of the TIMED run
        # (warm process: compile count ~0 is the ROADMAP-3 success signal)
        emit_extra["telemetry"] = telemetry
    breakdown_path = os.environ.get("BENCH_BREAKDOWN")
    if breakdown_path:
        import jax

        # stages suffixed _bg ran OVERLAPPED off the critical path
        # (pipeline/overlap.py): they are listed for visibility but
        # excluded from the critical-path sum the percentages and the
        # unstaged line are computed against
        total = sum(
            v for k, v in timing.items() if not k.endswith("_bg")
        ) or 1.0
        with open(breakdown_path, "w") as fh:
            fh.write("# Bench stage breakdown\n\n")
            fh.write(
                f"{n_reads} reads, backend={jax.default_backend()}, "
                f"timed {dt:.1f}s ({reads_per_sec:.1f} reads/s), "
                f"warm {warm_dt:.1f}s, counts_exact={counts_ok}, "
                f"assignment_accuracy={acc:.4f}\n\n"
            )
            fh.write("| stage | seconds | % of staged time |\n|---|---|---|\n")
            for stage, sec in sorted(timing.items(), key=lambda kv: -kv[1]):
                fh.write(f"| {stage} | {sec:.1f} | {100 * sec / total:.1f} |\n")
            fh.write(
                f"\nUnstaged (dataset IO, artifact writes, orchestration): "
                f"{dt - total:.1f}s of the timed run. Stages suffixed _bg "
                "ran overlapped off the critical path and are excluded "
                "from the staged total.\n"
            )
    rc = 0
    entry = obs_history.build_entry(
        "bench", read_raw_telemetry(root), fingerprint=fingerprint,
        sha=sha, backend=backend, n_reads=n_reads,
        reads_per_sec=round(reads_per_sec, 2),
        warmup_s=warm_dt, steady_s=dt,
        extra={"counts_exact": counts_ok, "duration_s": round(dt, 3),
               # per-mesh-config scaling entry: matching_entries pools a
               # sharded capture only with its own mesh shape
               **({"mesh_config": obs_history.mesh_config_str(mesh_shape)}
                  if mesh_shape else {})},
    )
    if args.gate:
        # gate BEFORE appending: the baseline is the ledger as it stood,
        # never polluted by the entry under judgment
        baseline, problems = obs_history.read_entries(args.ledger)
        for p in problems:
            print(f"bench: ledger {p}", file=sys.stderr)
        result = obs_history.evaluate_gate(
            baseline, entry, rel_threshold=args.gate_threshold,
            mad_k=args.gate_mad_k, min_samples=args.gate_min_samples,
        )
        print(f"bench: perf gate {result.status.upper()} — {result.reason}",
              file=sys.stderr)
        if result.status == "fail":
            rc = 1
        # data-plane gate: host_round_trip_bytes, lower-better — a
        # reintroduced device->host->device flow fails with measured vs
        # allowed bytes even when the timing gate stays green. The hard
        # default is an absolute near-zero budget (no ledger history
        # needed); --rt-budget <0 reverts to the relative baseline gate.
        transfer = obs_history.evaluate_bytes_gate(
            baseline, entry, rel_threshold=args.gate_threshold,
            mad_k=args.gate_mad_k, min_samples=args.gate_min_samples,
            abs_budget=args.rt_budget if args.rt_budget >= 0 else None,
        )
        print(f"bench: transfer gate {transfer.status.upper()} — "
              f"{transfer.reason}", file=sys.stderr)
        if transfer.status == "fail":
            rc = 1
        # serving-SLO gate: the ledger's newest serve_load entry (the
        # scripts/serve_load.py report) vs its own baseline pool; a
        # ledger without load history WARNs — the bench entry under
        # judgment is never a load report, so current=None here
        load = obs_history.evaluate_load_gate(
            baseline, None, rel_threshold=args.gate_threshold,
            mad_k=args.gate_mad_k, min_samples=args.gate_min_samples,
        )
        print(f"bench: load gate {load.status.upper()} — {load.reason}",
              file=sys.stderr)
        if load.status == "fail":
            rc = 1
    if args.ledger:
        try:
            obs_history.append_entry(args.ledger, entry)
        except OSError as exc:
            print(f"bench: could not append to ledger {args.ledger}: "
                  f"{exc!r}", file=sys.stderr)
    emit(reads_per_sec, emit_extra)
    return rc


if __name__ == "__main__":
    sys.exit(main())
