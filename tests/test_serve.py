"""Warm-serving daemon (serve/): queue admission + drain journal units,
/jobs control-plane routes, and the two e2e contracts the subsystem
exists for — ZERO steady-state compiles (a second job through one warm
daemon shows XLA compile count 0 in its own telemetry.json, with counts
CSV + consensus FASTA byte-identical to the one-shot CLI path) and
SIGTERM-equivalent drain (in-flight job completes at its next stage
boundary, the rest journal, a restarted daemon resumes them through
verified resume).

The warm e2e pair is also the tier-1 daemon smoke (scripts/tier1.sh).
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import time
import urllib.error
import urllib.request

import pytest

from ont_tcrconsensus_tpu.obs import history as obs_history
from ont_tcrconsensus_tpu.obs import live as obs_live
from ont_tcrconsensus_tpu.obs import metrics as obs_metrics
from ont_tcrconsensus_tpu.parallel.budget import BudgetModel
from ont_tcrconsensus_tpu.pipeline.config import RunConfig
from ont_tcrconsensus_tpu.robustness import shutdown
from ont_tcrconsensus_tpu.serve import prewarm as serve_prewarm
from ont_tcrconsensus_tpu.serve import queue as serve_queue
from ont_tcrconsensus_tpu.serve.daemon import Daemon

# the suite-wide persistent compile cache (tests/conftest.py): pointing
# the daemon's knob at it keeps e2e reruns warm across CI invocations
_TEST_CACHE = os.environ.get(
    "JAX_TEST_CACHE_DIR",
    os.path.join(os.path.dirname(__file__), ".jax_cache"),
)

_BASE = {"reference_file": "r.fa", "fastq_pass_dir": "fq"}


def _mini_cfg(**over) -> RunConfig:
    return RunConfig.from_dict({**_BASE, **over})


def _get(url: str) -> tuple[int, dict]:
    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            return resp.status, json.loads(resp.read().decode() or "null")
    except urllib.error.HTTPError as err:
        body = err.read().decode()
        return err.code, (json.loads(body) if body.startswith("{") else {})


def _post(url: str, obj=None, data: bytes | None = None) -> tuple[int, dict]:
    payload = json.dumps(obj).encode() if data is None else data
    req = urllib.request.Request(
        url, data=payload, method="POST",
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, json.loads(resp.read().decode() or "null")
    except urllib.error.HTTPError as err:
        body = err.read().decode()
        return err.code, (json.loads(body) if body.startswith("{") else {})


# ---------------------------------------------------------------------------
# config knobs + ledger schema


def test_config_serve_knob_validation():
    cfg = _mini_cfg()
    assert cfg.compile_cache_dir is None
    assert cfg.serve_queue_max == 8 and cfg.serve_prewarm is True
    assert _mini_cfg(compile_cache_dir="off").compile_cache_dir == "off"
    assert _mini_cfg(compile_cache_dir="/tmp/x").compile_cache_dir == "/tmp/x"
    for bad in ("", 5, True):
        with pytest.raises(ValueError, match="compile_cache_dir"):
            _mini_cfg(compile_cache_dir=bad)
    assert _mini_cfg(serve_queue_max=1).serve_queue_max == 1
    for bad in (0, -3, True, "4"):
        with pytest.raises(ValueError, match="serve_queue_max"):
            _mini_cfg(serve_queue_max=bad)


def test_fingerprint_excludes_serve_and_cache_knobs():
    fp = obs_history.config_fingerprint(_mini_cfg())
    varied = _mini_cfg(compile_cache_dir="/tmp/cache", serve_queue_max=2,
                       serve_prewarm=False, live_port=0)
    assert obs_history.config_fingerprint(varied) == fp
    assert obs_history.config_fingerprint(
        _mini_cfg(read_batch_size=256)) != fp


def test_build_entry_warm_steady_split():
    entry = obs_history.build_entry("serve", warmup_s=12.34567, steady_s=1.5)
    assert entry["source"] == "serve"
    assert entry["warmup_s"] == 12.346 and entry["steady_s"] == 1.5
    bare = obs_history.build_entry("bench")
    assert "warmup_s" not in bare and "steady_s" not in bare


# ---------------------------------------------------------------------------
# queue: admission, FIFO lifecycle, drain journal


def test_queue_admission_queue_full_and_over_budget():
    q = serve_queue.JobQueue(2, BudgetModel(12.0))
    j1 = q.submit({"a": 1}, _mini_cfg())
    assert j1.id == "job-0001" and j1.state == "queued"
    q.submit({}, _mini_cfg())
    with pytest.raises(serve_queue.AdmissionError) as ei:
        q.submit({}, _mini_cfg())
    assert ei.value.reason == "queue_full"
    assert q.depth() == 2
    # a job whose explicit read batch cannot fit the working budget is
    # rejected at submit time, never accepted and OOM-killed mid-run
    tight = serve_queue.JobQueue(8, BudgetModel(1.0))
    with pytest.raises(serve_queue.AdmissionError) as ei:
        tight.submit({}, _mini_cfg(read_batch_size=1 << 22))
    assert ei.value.reason == "over_budget"
    assert "budget" in ei.value.detail


def test_queue_pop_mark_requeue_lifecycle():
    q = serve_queue.JobQueue(8, BudgetModel(12.0))
    job = q.submit({}, _mini_cfg())
    popped = q.pop(timeout=0.01)
    assert popped is job and job.state == "running"
    assert job.wait_s is not None and job.wait_s >= 0.0
    q.requeue_front(job)
    assert job.state == "requeued" and q.depth() == 1
    assert q.pop(timeout=0.01) is job
    q.mark(job, "done", result={"libraries": {"barcode01": 5}})
    snap = q.job(job.id).snapshot()
    assert snap["state"] == "done" and snap["result"]["libraries"] == \
        {"barcode01": 5}
    assert q.pop(timeout=0.01) is None and q.depth() == 0


def test_queue_metrics_planted_on_submit_and_reject():
    reg = obs_metrics.arm()
    try:
        q = serve_queue.JobQueue(1, BudgetModel(12.0))
        q.submit({}, _mini_cfg())
        with pytest.raises(serve_queue.AdmissionError):
            q.submit({}, _mini_cfg())
        summary = reg.summary()
        assert summary["counters"]["serve.submitted"] == 1
        assert summary["counters"]["serve.rejected"] == 1
        assert summary["gauges"]["serve.queue_depth"] == 1
    finally:
        obs_metrics.disarm()


def test_journal_roundtrip_consume_and_garbage(tmp_path):
    sd = str(tmp_path)
    jobs = [serve_queue.Job(id="job-0001", raw={"k": 1}, state="requeued",
                            submitted_t=1.0),
            serve_queue.Job(id="job-0002", raw={"k": 2}, submitted_t=2.0)]
    path = serve_queue.write_journal(sd, jobs)
    assert path and os.path.exists(path)
    recs = serve_queue.load_journal(sd)
    assert [r["id"] for r in recs] == ["job-0001", "job-0002"]
    assert recs[0]["raw"] == {"k": 1}
    assert not os.path.exists(path), "journal must be consumed on load"
    assert serve_queue.load_journal(sd) == []
    # an empty drain removes any stale journal instead of resurrecting it
    serve_queue.write_journal(sd, jobs)
    assert serve_queue.write_journal(sd, []) is None
    assert not os.path.exists(serve_queue.journal_path(sd))
    # torn/garbage journals degrade to [] — a restart must never wedge
    with open(serve_queue.journal_path(sd), "w") as fh:
        fh.write("{torn")
    assert serve_queue.load_journal(sd) == []
    with open(serve_queue.journal_path(sd), "w") as fh:
        json.dump({"schema": 1, "jobs": [{"id": "x", "raw": "not a dict"},
                                         "garbage"]}, fh)
    assert serve_queue.load_journal(sd) == []


# ---------------------------------------------------------------------------
# shutdown coordinator stack (daemon outer / job inner nesting)


def test_shutdown_coordinator_stack_nesting():
    outer = shutdown.ShutdownCoordinator()
    inner = shutdown.ShutdownCoordinator()
    shutdown.activate(outer)
    try:
        shutdown.activate(inner)
        shutdown.request("inner stop")
        assert inner.requested() and not outer.requested()
        shutdown.deactivate(inner)
        # the daemon's coordinator is active again, not None
        shutdown.request("outer stop")
        assert outer.requested()
    finally:
        shutdown.deactivate(outer)
    assert shutdown._ACTIVE is None and shutdown._STACK == []


# ---------------------------------------------------------------------------
# prewarm bucket enumeration


def test_declared_width_buckets():
    assert serve_prewarm.declared_width_buckets(
        _mini_cfg(max_read_length=200)) == [256]
    assert serve_prewarm.declared_width_buckets(
        _mini_cfg(max_read_length=1000)) == [256, 512, 1024]
    # past the largest declared width, every declared bucket is in play
    assert serve_prewarm.declared_width_buckets(
        _mini_cfg(max_read_length=9000)) == [256, 512, 1024, 2048, 3072,
                                             4096]


# ---------------------------------------------------------------------------
# /jobs routes (controller-less plane stays read-only; duck-typed controller)


def test_post_jobs_without_controller_is_503():
    srv = obs_live.arm(0)
    try:
        base = f"http://127.0.0.1:{srv.port}"
        assert _post(base + "/jobs", {"x": 1})[0] == 503
        assert _get(base + "/jobs")[0] == 503
        assert _get(base + "/healthz")[0] == 200  # read plane unaffected
    finally:
        obs_live.disarm()
    assert obs_live._JOBS is None


class _EchoController:
    def submit(self, obj):
        return 202, {"id": "job-0001", "echo": obj}

    def jobs_snapshot(self):
        return {"jobs": [], "queue_depth": 0}

    def job_snapshot(self, job_id):
        return {"id": job_id} if job_id == "job-0001" else None


def test_jobs_routes_with_controller(monkeypatch):
    srv = obs_live.arm(0)
    obs_live.set_jobs_controller(_EchoController())
    try:
        base = f"http://127.0.0.1:{srv.port}"
        status, body = _post(base + "/jobs", {"read_batch_size": 96})
        assert status == 202 and body["echo"] == {"read_batch_size": 96}
        assert _post(base + "/nope", {})[0] == 404
        assert _post(base + "/jobs", data=b"{torn")[0] == 400
        assert _post(base + "/jobs", data=b"[1, 2]")[0] == 400
        assert _post(base + "/jobs", data=b"")[0] == 400
        monkeypatch.setattr(obs_live, "MAX_JOB_BODY_BYTES", 8)
        assert _post(base + "/jobs", {"k": "0123456789"})[0] == 413
        status, body = _get(base + "/jobs")
        assert status == 200 and body["jobs"] == []
        assert _get(base + "/jobs/job-0001") == (200, {"id": "job-0001"})
        assert _get(base + "/jobs/zzz")[0] == 404
    finally:
        obs_live.set_jobs_controller(None)
        obs_live.disarm()


def test_node_start_hook_fires_and_never_fails_the_stage():
    seen: list[str] = []
    obs_live.set_node_start_hook(seen.append)
    try:
        obs_live.progress_node_start("round1_polish")
    finally:
        obs_live.set_node_start_hook(None)
    assert seen == ["round1_polish"]

    def boom(name):
        raise RuntimeError("observer bug")

    obs_live.set_node_start_hook(boom)
    try:
        obs_live.progress_node_start("round1_polish")  # must not raise
    finally:
        obs_live.set_node_start_hook(None)


# ---------------------------------------------------------------------------
# daemon submit-side validation (no serve loop needed)


def test_daemon_submit_validation_and_draining(tmp_path):
    daemon = Daemon(dict(_BASE), port=0, state_dir=str(tmp_path))
    status, payload = daemon.submit({"no_such_knob": 1})
    assert status == 400 and payload["error"] == "invalid_config"
    status, payload = daemon.submit({"read_batch_size": 1 << 24})
    assert status == 409 and payload["error"] == "over_budget"
    status, payload = daemon.submit({"live_port": 0})
    assert status == 202
    # the daemon owns the live plane: a tenant cannot re-point it
    job = daemon.queue.job(payload["id"])
    assert job.raw["live_port"] is None
    daemon._draining.set()
    status, payload = daemon.submit({})
    assert status == 503 and payload["error"] == "draining"


def test_daemon_queue_max_from_template_and_override(tmp_path):
    daemon = Daemon({**_BASE, "serve_queue_max": 3}, port=0,
                    state_dir=str(tmp_path))
    assert daemon.queue.max_depth == 3
    daemon = Daemon({**_BASE, "serve_queue_max": 3}, port=0,
                    state_dir=str(tmp_path), queue_max=1)
    assert daemon.queue.max_depth == 1
    daemon.submit({})
    status, payload = daemon.submit({})
    assert status == 429 and payload["error"] == "queue_full"


# ---------------------------------------------------------------------------
# e2e: one warm daemon, two tenants, zero steady-state compiles,
# byte-identity vs the one-shot CLI path
#
# slow-marked: the warm_daemon_runs fixture costs ~45s (a full one-shot
# baseline run plus a two-job daemon serve), so these run in tier1.sh's
# dedicated daemon smoke arm (-k "serve_e2e or ..." -m 'slow or not slow')
# rather than in the generic non-slow sweep.


@pytest.fixture(scope="module")
def serve_library(tmp_path_factory):
    from ont_tcrconsensus_tpu.io import fastx, simulator

    tmp = tmp_path_factory.mktemp("serve_lib")
    lib = simulator.simulate_library(
        seed=29,
        num_regions=3,
        molecules_per_region=(2, 3),
        reads_per_molecule=(5, 7),
        sub_rate=0.006,
        ins_rate=0.003,
        del_rate=0.003,
        region_len=(700, 850),
    )
    fastx.write_fasta(tmp / "reference.fa", lib.reference.items())
    fq_dir = tmp / "fastq_pass" / "barcode01"
    fq_dir.mkdir(parents=True)
    fastx.write_fastq(fq_dir / "barcode01.fastq.gz", lib.reads)
    return tmp, lib


def _stage(src, root):
    root.mkdir(parents=True, exist_ok=True)
    shutil.copy(src / "reference.fa", root / "reference.fa")
    shutil.copytree(src / "fastq_pass", root / "fastq_pass")
    return root


def _raw_cfg(root, **over) -> dict:
    raw = {
        "reference_file": str(root / "reference.fa"),
        "fastq_pass_dir": str(root / "fastq_pass"),
        "minimal_length": 600,
        "min_reads_per_cluster": 4,
        "read_batch_size": 96,
        "polish_method": "poa",
        "delete_tmp_files": False,
        "compile_cache_dir": _TEST_CACHE,
    }
    raw.update(over)
    return raw


def _wait_for_server(timeout: float = 120.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        srv = obs_live.server()
        if srv is not None:
            return srv
        time.sleep(0.05)
    raise AssertionError("daemon never armed its live plane")


def _submit_and_wait(jobs_url: str, raw: dict,
                     timeout: float = 600.0) -> dict:
    status, snap = _post(jobs_url, raw)
    assert status == 202, snap
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        st, cur = _get(f"{jobs_url}/{snap['id']}")
        if st == 200 and cur["state"] in ("done", "failed"):
            return cur
        time.sleep(0.2)
    raise AssertionError(f"{snap['id']} did not finish in {timeout}s")


@pytest.fixture(scope="module")
def warm_daemon_runs(serve_library, tmp_path_factory):
    """One one-shot baseline run, then one warm daemon serving two tenant
    jobs (identical input content, separate workdirs) over real HTTP."""
    from ont_tcrconsensus_tpu.pipeline.run import run_with_config

    src, lib = serve_library
    base = tmp_path_factory.mktemp("serve_e2e")
    oneshot = _stage(src, base / "oneshot")
    res_one = run_with_config(RunConfig.from_dict(_raw_cfg(oneshot)))
    nano_one = oneshot / "fastq_pass" / "nano_tcr"

    w1 = _stage(src, base / "w1")
    w2 = _stage(src, base / "w2")
    ledger = str(base / "serve_ledger.jsonl")
    daemon = Daemon(_raw_cfg(w1, history_ledger=ledger), port=0,
                    state_dir=str(base / "state"), prewarm_widths=[1024])
    loop = threading.Thread(target=daemon.serve_forever,
                            name="serve-e2e", daemon=True)
    loop.start()
    try:
        srv = _wait_for_server()
        jobs_url = f"http://127.0.0.1:{srv.port}/jobs"
        snaps = [
            _submit_and_wait(jobs_url, _raw_cfg(w, history_ledger=ledger))
            for w in (w1, w2)
        ]
        _, listing = _get(jobs_url)
    finally:
        daemon.request_stop()
        loop.join(timeout=120.0)
    assert not loop.is_alive(), "daemon did not stop"
    return lib, res_one, nano_one, w1, w2, snaps, listing, daemon, ledger


@pytest.mark.slow
def test_serve_e2e_jobs_complete_with_latency_tap(warm_daemon_runs):
    _, _, _, _, _, snaps, listing, daemon, _ = warm_daemon_runs
    for snap in snaps:
        assert snap["state"] == "done", snap
        assert snap["wait_s"] is not None and snap["wait_s"] >= 0.0
        # dispatch-to-first-stage latency measured through the live
        # plane's node-start hook (the ≤10s goal's measurement channel)
        assert snap["first_stage_s"] is not None
        assert snap["first_stage_s"] > 0.0
    assert listing["jobs_done"] == 2 and listing["queue_depth"] == 0
    assert daemon.warmup_s is not None and daemon.warmup_s > 0.0


@pytest.mark.slow
def test_serve_e2e_zero_steady_state_compiles(warm_daemon_runs):
    """The tentpole contract: the SECOND job through the warm daemon
    dispatches with zero XLA backend compiles — proven by its own
    telemetry.json via the jax.monitoring compile listener. (Job 1 may
    legitimately show 0 too: the persistent cache and earlier tests in
    this process can pre-warm it, so only job 2's count is asserted.)"""
    _, _, _, _, w2, _, _, _, _ = warm_daemon_runs
    tele = json.loads(
        (w2 / "fastq_pass" / "nano_tcr" / "telemetry.json").read_text())
    assert tele["compile"]["count"] == 0, tele["compile"]
    # the run recorded which persistent cache it armed
    cache = tele["analysis"]["compile_cache"]
    assert cache["armed"] is True and cache["dir"] == _TEST_CACHE


@pytest.mark.slow
def test_serve_e2e_outputs_byte_identical_to_oneshot(warm_daemon_runs):
    lib, res_one, nano_one, w1, w2, snaps, _, _, _ = warm_daemon_runs
    assert res_one == {"barcode01": lib.true_counts}
    total = sum(lib.true_counts.values())
    for snap in snaps:
        assert snap["result"]["libraries"] == {"barcode01": total}
    for rel in (
        ("barcode01", "counts", "umi_consensus_counts.csv"),
        ("barcode01", "fasta", "merged_consensus.fasta"),
    ):
        want = nano_one.joinpath(*rel).read_bytes()
        for w in (w1, w2):
            got = (w / "fastq_pass" / "nano_tcr").joinpath(*rel).read_bytes()
            assert got == want, \
                f"daemon path must not change {'/'.join(rel)}"


@pytest.mark.slow
def test_serve_e2e_prewarm_compiled_declared_buckets(warm_daemon_runs):
    _, _, _, _, _, _, _, daemon, _ = warm_daemon_runs
    report = daemon.prewarm_report
    assert report is not None and report.get("compiled", 0) >= 1, report
    fused = [e for e in report["entries"] if e["kind"] == "fused_assign"]
    assert fused and all(e["ok"] for e in fused), fused
    assert all(e["width"] == 1024 and e["batch"] == 96 for e in fused)
    # poa polish: the RNN polisher prewarm degrades to a report line
    pol = [e for e in report["entries"] if e["kind"] == "polisher"]
    assert pol and not pol[0]["ok"]


@pytest.mark.slow
def test_serve_e2e_ledger_records_warm_steady_split(warm_daemon_runs):
    _, _, _, _, _, _, _, daemon, ledger = warm_daemon_runs
    entries, problems = obs_history.read_entries(ledger)
    assert problems == []
    serve_entries = [e for e in entries if e["source"] == "serve"]
    run_entries = [e for e in entries if e["source"] == "run"]
    assert len(serve_entries) == 2 and len(run_entries) == 2
    first, second = serve_entries
    # warm-up cost rides the FIRST job's entry only; steady_s every job
    assert first["warmup_s"] == daemon.warmup_s
    assert "warmup_s" not in second
    for e in serve_entries:
        assert e["steady_s"] > 0.0
        assert e["job_id"].startswith("job-")
        assert e["dispatch_first_stage_s"] is not None
        assert e["wait_s"] >= 0.0


@pytest.mark.slow
def test_serve_e2e_plane_disarmed_after_daemon(warm_daemon_runs):
    assert obs_live.server() is None
    assert obs_live._JOBS is None and obs_live._NODE_START_HOOK is None
    assert obs_metrics.registry() is None
    assert shutdown._ACTIVE is None


# ---------------------------------------------------------------------------
# e2e: drain mid-queue -> journal -> restarted daemon resumes


@pytest.mark.slow
def test_serve_drain_journals_and_restart_resumes(serve_library,
                                                  tmp_path_factory):
    """SIGTERM-equivalent drain: a cooperative stop request lands on the
    in-flight job's coordinator (exactly what the signal handler does),
    the job drains at its next stage boundary and is requeued with
    resume=true, the untouched second job journals behind it, and a
    restarted daemon runs both to byte-correct completion."""
    from ont_tcrconsensus_tpu.graph import nodes as graph_nodes
    from ont_tcrconsensus_tpu.pipeline.run import _read_counts_csv

    src, lib = serve_library
    base = tmp_path_factory.mktemp("serve_drain")
    w1 = _stage(src, base / "w1")
    w2 = _stage(src, base / "w2")
    state = str(base / "state")

    fired = threading.Event()
    orig = graph_nodes.round1_polish

    def draining_round1_polish(ctx, inputs):
        if not fired.is_set():
            fired.set()
            # same path as the first SIGTERM: request() on the active
            # (= the in-flight run's) coordinator; Preempted at the next
            # stage boundary
            shutdown.request("test drain")
        return orig(ctx, inputs)

    daemon = Daemon(_raw_cfg(w1), port=0, state_dir=state, do_prewarm=False)
    loop = threading.Thread(target=daemon.serve_forever,
                            name="serve-drain", daemon=True)
    graph_nodes.round1_polish = draining_round1_polish
    try:
        loop.start()
        srv = _wait_for_server()
        jobs_url = f"http://127.0.0.1:{srv.port}/jobs"
        assert _post(jobs_url, _raw_cfg(w1))[0] == 202
        assert _post(jobs_url, _raw_cfg(w2))[0] == 202
        # the daemon drains ITSELF after the Preempted job
        loop.join(timeout=600.0)
        assert not loop.is_alive(), "daemon did not drain"
    finally:
        graph_nodes.round1_polish = orig
    assert fired.is_set(), "gated node never ran"

    journal_file = serve_queue.journal_path(state)
    with open(journal_file) as fh:
        journal = json.load(fh)
    assert len(journal["jobs"]) == 2
    drained, untouched = journal["jobs"]
    assert drained["state"] == "requeued"
    # committed stages of the drained job resume, not refuse
    assert drained["raw"]["resume"] is True
    assert untouched["state"] == "queued"

    daemon2 = Daemon(_raw_cfg(w1), port=0, state_dir=state, do_prewarm=False)
    loop2 = threading.Thread(target=daemon2.serve_forever,
                             name="serve-resume", daemon=True)
    loop2.start()
    try:
        srv2 = _wait_for_server()
        jobs_url2 = f"http://127.0.0.1:{srv2.port}/jobs"
        listing: dict = {}
        deadline = time.monotonic() + 600.0
        while time.monotonic() < deadline:
            st, listing = _get(jobs_url2)
            if st == 200 and listing.get("jobs_done", 0) >= 2:
                break
            time.sleep(0.25)
        assert listing.get("jobs_done") == 2, listing
        assert all(j["state"] == "done" for j in listing["jobs"]), listing
    finally:
        daemon2.request_stop()
        loop2.join(timeout=120.0)
    assert not os.path.exists(journal_file), "journal must be consumed"
    for w in (w1, w2):
        counts = _read_counts_csv(str(
            w / "fastq_pass" / "nano_tcr" / "barcode01" / "counts" /
            "umi_consensus_counts.csv"))
        assert counts == lib.true_counts
