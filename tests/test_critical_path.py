"""Unit tests for obs/critical_path.py on hand-built synthetic graphs.

The analyzer must reconstruct the executed DAG from a telemetry.json
payload alone: known slack/what-if answers on a diamond graph, the
never-crash degradation ladder for garbage artifacts, the dispatch-tax
join, pool efficiency, and the ``--report --critical-path`` /
``--report --json`` surfaces over a synthetic artifact directory.
"""

from __future__ import annotations

import json

from ont_tcrconsensus_tpu.obs import critical_path, report as obs_report


def diamond_telemetry() -> dict:
    """A -> (B, C) -> D with durations 2/3/1/1: the critical path is
    A-B-D = 6s, C has 2s slack, and only A and B are worth attacking."""
    return {
        "telemetry": "on",
        "duration_s": 6.5,
        "graph": {
            "nodes": {
                "A": {"critical_s": 2.0, "overlapped_s": 0.0, "runs": 1,
                      "skips": 0, "units": 10, "inputs": [],
                      "outputs": ["a"]},
                "B": {"critical_s": 3.0, "overlapped_s": 0.0, "runs": 1,
                      "skips": 0, "units": 5, "inputs": ["a"],
                      "outputs": ["b"]},
                "C": {"critical_s": 1.0, "overlapped_s": 1.5, "runs": 1,
                      "skips": 0, "units": 2, "inputs": ["a"],
                      "outputs": ["c"]},
                "D": {"critical_s": 1.0, "overlapped_s": 0.0, "runs": 1,
                      "skips": 0, "units": 1, "inputs": ["b", "c"],
                      "outputs": ["d"]},
            },
            "edges": {"a": "hbm", "b": "hbm", "c": "host", "d": "disk"},
            "pool": {"busy_s": 3.0, "idle_s": 1.0, "window_s": 2.0,
                     "slots": 2},
        },
        "dispatch_by_stage": {
            "B": {"dispatches": 4, "gets": 2, "host_s": 0.5, "block_s": 1.2},
            "C_bg": {"dispatches": 1, "gets": 1, "host_s": 0.1,
                     "block_s": 0.2},
        },
    }


def test_diamond_known_answers():
    a = critical_path.analyze(diamond_telemetry())
    assert a["problems"] == []
    assert a["critical_path"] == ["A", "B", "D"]
    assert a["critical_path_s"] == 6.0
    assert a["nodes_total_s"] == 7.0
    nodes = a["nodes"]
    assert nodes["C"]["slack_s"] == 2.0
    assert nodes["A"]["slack_s"] == 0.0 and nodes["B"]["slack_s"] == 0.0
    assert nodes["C"]["on_critical_path"] is False
    assert nodes["B"]["on_critical_path"] is True
    # what-if: freeing B shortens to A-C-D = 4s (saves 2); freeing C
    # saves nothing — it was never on the path
    assert nodes["B"]["what_if_saved_s"] == 2.0
    assert nodes["C"]["what_if_saved_s"] == 0.0
    assert nodes["A"]["what_if_saved_s"] == 2.0  # B(3)+D(1)=4 remains
    assert nodes["B"]["units"] == 5


def test_dispatch_tax_join_folds_bg_spans():
    a = critical_path.analyze(diamond_telemetry())
    assert a["nodes"]["B"]["dispatch"] == {
        "dispatches": 4, "gets": 2, "host_s": 0.5, "block_s": 1.2}
    # the worker's C_bg span rolls into node C
    assert a["nodes"]["C"]["dispatch"]["block_s"] == 0.2
    assert a["nodes"]["A"]["dispatch"] is None


def test_pool_efficiency():
    a = critical_path.analyze(diamond_telemetry())
    assert a["pool"]["busy_s"] == 3.0
    assert a["pool"]["efficiency"] == 0.75
    # imperative-path artifact: pool rides top-level, no graph section
    b = critical_path.analyze({"overlap_pool": {"busy_s": 1.0,
                                                "idle_s": 3.0}})
    assert b["problems"]  # no graph -> named problem, but never a crash


def test_trace_join_computes_makespan():
    trace = {"traceEvents": [
        {"ph": "X", "name": "A", "ts": 0.0, "dur": 2e6},
        {"ph": "X", "name": "B", "ts": 2e6, "dur": 3e6},
        {"ph": "X", "name": "C_bg", "ts": 2e6, "dur": 1e6},
        {"ph": "X", "name": "D", "ts": 5e6, "dur": 1e6},
        {"ph": "i", "name": "chaos.inject", "ts": 1.0},
        {"ph": "X", "name": "unrelated", "ts": 0.0, "dur": 9e9},
    ]}
    a = critical_path.analyze(diamond_telemetry(), trace)
    assert a["trace"]["makespan_s"] == 6.0
    assert a["trace"]["node_windows_s"]["C"] == [2.0, 3.0]


def test_degrades_to_named_problems():
    # no graph section at all (imperative / pre-graph artifact)
    a = critical_path.analyze({"duration_s": 1.0})
    assert any("no executed-graph section" in p for p in a["problems"])
    assert "critical_path" not in a
    # graph present but nodes is garbage
    a = critical_path.analyze({"graph": {"nodes": "what"}})
    assert any("no nodes object" in p for p in a["problems"])
    # one garbage node entry is dropped by name; the rest still analyze
    tele = diamond_telemetry()
    tele["graph"]["nodes"]["Z"] = ["not", "an", "object"]
    tele["graph"]["nodes"]["B"]["critical_s"] = "fast"
    a = critical_path.analyze(tele)
    assert any("'Z'" in p for p in a["problems"])
    assert any("bad critical_s" in p for p in a["problems"])
    assert a["critical_path"]  # still computed (B treated as 0s)
    # dependency metadata absent -> named problem, totals still reported
    bare = {"graph": {"nodes": {"A": {"critical_s": 2.0}}}}
    a = critical_path.analyze(bare)
    assert any("no inputs/outputs metadata" in p for p in a["problems"])
    assert a["nodes_total_s"] == 2.0 and "critical_path" not in a
    # a dependency cycle cannot crash the walk
    cyc = {"graph": {"nodes": {
        "A": {"critical_s": 1.0, "inputs": ["b"], "outputs": ["a"]},
        "B": {"critical_s": 1.0, "inputs": ["a"], "outputs": ["b"]},
    }}}
    a = critical_path.analyze(cyc)
    assert any("cycle" in p for p in a["problems"])
    # not even a dict
    a = critical_path.analyze([])
    assert a["problems"]


def test_render_smoke():
    lines: list[str] = []
    critical_path.render(critical_path.analyze(diamond_telemetry()), lines)
    text = "\n".join(lines)
    assert "critical path: 6.000s over 3 node(s)" in text
    assert "what-if" in text and "overlap pool" in text
    # problem-only analyses render their problems and stop
    lines = []
    critical_path.render(critical_path.analyze({}), lines)
    assert lines and "critical-path:" in lines[0]


# --- the --report surfaces over a synthetic artifact dir ---------------------


def _write_artifact(tmp_path, payload) -> str:
    wd = tmp_path / "nano_tcr"
    wd.mkdir(exist_ok=True)
    (wd / "telemetry.json").write_text(json.dumps(payload))
    return str(wd)


def test_report_critical_path_text(tmp_path, capsys):
    wd = _write_artifact(tmp_path, diamond_telemetry())
    assert obs_report.report_main(wd, critical_path=True) == 0
    out = capsys.readouterr().out
    assert "critical path: 6.000s" in out
    assert "overlap pool: busy 3.000s" in out


def test_report_json_machine_dump(tmp_path, capsys):
    wd = _write_artifact(tmp_path, diamond_telemetry())
    assert obs_report.report_main(wd, as_json=True, critical_path=True) == 0
    data = json.loads(capsys.readouterr().out)
    assert data["problems"] == []
    assert data["telemetry"]["telemetry.json"]["duration_s"] == 6.5
    cp = data["critical_path"]["telemetry.json"]
    assert cp["critical_path"] == ["A", "B", "D"]
    assert data["history"] == {} and data["stage_timing_tsvs"] == 0


def test_report_json_never_crash_matches_text_exit_codes(tmp_path, capsys):
    """--json holds the same never-crash contract and exit codes as the
    text renderer on valid-JSON-but-garbage artifacts."""
    wd = tmp_path / "nano_tcr"
    wd.mkdir()
    (wd / "telemetry.json").write_text('{"stages": [], "dispatch": 7}')
    (wd / "telemetry_p1.json").write_text('["not", "an", "object"]')
    assert obs_report.report_main(str(wd), as_json=True,
                                  critical_path=True) == 1
    data = json.loads(capsys.readouterr().out)
    probs = "\n".join(data["problems"])
    assert "malformed telemetry artifact telemetry.json" in probs
    assert "telemetry_p1.json: not a JSON object" in probs
    # empty dir -> same "no telemetry" exit 1; nonsense target -> exit 2
    empty = tmp_path / "empty" / "nano_tcr"
    empty.mkdir(parents=True)
    assert obs_report.report_main(str(empty), as_json=True) == 1
    capsys.readouterr()
    assert obs_report.report_main(str(tmp_path / "nope"), as_json=True) == 2
