"""graftrace (tools/graftrace): every finding class fires on a seeded
fixture, root discovery sees every spawn mechanism, the CLI honours the
graftcheck --expect contract, and the SHIPPED tree is clean modulo the
justified expected list.

Two fixtures reproduce shipped bug shapes: the PR 5 watchdog
cancel-vs-scope-exit race (an unlocked ``pop`` on a registry table the
monitor thread mutates under its lock) and a two-lock AB/BA order
inversion. The dynamic twin (robustness/lockcheck) is unit-tested here
too; its whole-pipeline proof rides the chaos e2e in test_chaos.py.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from ont_tcrconsensus_tpu.robustness import lockcheck  # noqa: E402
from tools.graftrace.cli import DEFAULT_EXPECT, analyze_paths  # noqa: E402
from tools.graftrace.cli import main as graftrace_main  # noqa: E402


def trace(tmp_path, files: dict[str, str]):
    """Write a fixture tree, analyze it, return (findings, roots)."""
    for rel, source in files.items():
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(source)
    return analyze_paths([str(tmp_path)])


def rules_of(findings) -> set[str]:
    return {f.rule for f in findings}


# ---------------------------------------------------------------------------
# race-unlocked-write — the PR 5 cancel-vs-scope-exit shape


_WATCHDOG_RACE = (
    "import threading\n"
    "\n"
    'LOCK_OWNERSHIP = {"Watchdog._entries": "_lock"}\n'
    "\n"
    "\n"
    "class Watchdog:\n"
    "    def __init__(self):\n"
    "        self._lock = threading.Lock()\n"
    "        self._entries = {}\n"
    "\n"
    "    def start(self):\n"
    "        threading.Thread(target=self._monitor, daemon=True).start()\n"
    "\n"
    "    def _monitor(self):\n"
    "        with self._lock:\n"
    '            self._entries["beat"] = 1\n'
    "\n"
    "    def cancel(self, name):\n"
    "        self._entries.pop(name, None)  # seeded: forgot the lock\n"
    "\n"
    "\n"
    "def _run_with_config():\n"
    "    wd = Watchdog()\n"
    "    wd.start()\n"
    '    wd.cancel("x")\n'
)


def test_race_unlocked_write_fires_on_pr5_cancel_shape(tmp_path):
    findings, roots = trace(tmp_path, {"pipeline/run.py": _WATCHDOG_RACE})
    assert rules_of(findings) == {"race-unlocked-write"}
    (f,) = findings
    assert "Watchdog._entries" in f.message
    assert "main:pipeline-loop" in f.message
    assert "thread:Watchdog._monitor" in f.message
    # anchored at the unlocked write, not the guarded one
    assert "pop" in (tmp_path / "pipeline/run.py").read_text().splitlines()[
        f.line - 1]


def test_race_needs_two_roots(tmp_path):
    """The same unlocked write is NOT a race when only one root reaches
    the location (no spawn site -> single-threaded by construction)."""
    single = _WATCHDOG_RACE.replace(
        "    wd.start()\n", "").replace(
        "    def start(self):\n"
        "        threading.Thread(target=self._monitor, daemon=True)"
        ".start()\n\n", "")
    findings, _ = trace(tmp_path, {"pipeline/run.py": single})
    assert findings == []


def test_race_cleared_by_taking_the_lock(tmp_path):
    fixed = _WATCHDOG_RACE.replace(
        "        self._entries.pop(name, None)  # seeded: forgot the lock",
        "        with self._lock:\n"
        "            self._entries.pop(name, None)")
    findings, _ = trace(tmp_path, {"pipeline/run.py": fixed})
    assert findings == []


def test_unlocked_reads_tolerated_by_doctrine(tmp_path):
    """Registries tolerate torn reads for display: a lock-free *read*
    from a second root must not flag when every write is guarded."""
    readers = _WATCHDOG_RACE.replace(
        "        self._entries.pop(name, None)  # seeded: forgot the lock",
        "        return len(self._entries)")
    findings, _ = trace(tmp_path, {"pipeline/run.py": readers})
    assert findings == []


def test_race_on_module_level_table(tmp_path):
    """Module-global container mutations race too; plain rebinds are the
    exempt atomic-reference hand-off and must not count as writes."""
    findings, _ = trace(tmp_path, {"pipeline/run.py": (
        "import threading\n"
        "_JOBS = {}\n"
        "_ACTIVE = None\n"
        "def worker():\n"
        "    _JOBS['k'] = 1\n"
        "def _run_with_config():\n"
        "    global _ACTIVE\n"
        "    threading.Thread(target=worker, daemon=True).start()\n"
        "    _JOBS['m'] = 2\n"
        "    _ACTIVE = object()  # rebind: exempt\n"
    )})
    assert rules_of(findings) == {"race-unlocked-write"}
    (f,) = findings
    assert "_JOBS" in f.message and "_ACTIVE" not in f.message


# ---------------------------------------------------------------------------
# deadlock-order-inversion — seeded two-lock AB/BA cycle


_TWO_LOCK = (
    "import threading\n"
    "LOCK_A = threading.Lock()\n"
    "LOCK_B = threading.Lock()\n"
    "def forward():\n"
    "    with LOCK_A:\n"
    "        with LOCK_B:\n"
    "            pass\n"
    "def backward():\n"
    "    with LOCK_B:\n"
    "        with LOCK_A:\n"
    "            pass\n"
    "def worker():\n"
    "    backward()\n"
    "def _run_with_config():\n"
    "    threading.Thread(target=worker, daemon=True).start()\n"
    "    forward()\n"
)


def test_deadlock_order_inversion_fires(tmp_path):
    findings, _ = trace(tmp_path, {"pipeline/run.py": _TWO_LOCK})
    assert rules_of(findings) == {"deadlock-order-inversion"}
    (f,) = findings
    assert "LOCK_A" in f.message and "LOCK_B" in f.message
    assert "->" in f.message  # witness edges with sites


def test_consistent_lock_order_is_clean(tmp_path):
    consistent = _TWO_LOCK.replace(
        "    with LOCK_B:\n"
        "        with LOCK_A:\n",
        "    with LOCK_A:\n"
        "        with LOCK_B:\n", 1).replace(
        "def backward():\n"
        "    with LOCK_A:\n", "def backward():\n    with LOCK_A:\n")
    findings, _ = trace(tmp_path, {"pipeline/run.py": consistent})
    assert findings == []


def test_order_edges_cross_object_boundaries(tmp_path):
    """A method that calls into another object while holding its own lock
    contributes an interprocedural edge (the JobQueue->Metrics shape);
    the worker reaches the queue through a typed module global, the way
    armed singletons are published in the real tree."""
    findings, _ = trace(tmp_path, {"pipeline/run.py": (
        "import threading\n"
        "class Registry:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.q = Queue()\n"
        "    def add(self):\n"
        "        with self._lock:\n"
        "            self.q.ping()\n"
        "class Queue:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def ping(self):\n"
        "        with self._lock:\n"
        "            pass\n"
        "    def submit_side(self):\n"
        "        with self._lock:\n"
        "            _REG.add()\n"
        '_REG: "Registry | None" = None\n'
        '_Q: "Queue | None" = None\n'
        "def worker():\n"
        "    _Q.submit_side()\n"
        "def _run_with_config():\n"
        "    threading.Thread(target=worker, daemon=True).start()\n"
        "    _REG.add()\n"
    )})
    assert rules_of(findings) == {"deadlock-order-inversion"}
    (f,) = findings
    assert "Queue._lock" in f.message and "Registry._lock" in f.message


# ---------------------------------------------------------------------------
# blocking-under-lock / signal-unsafe-call


def test_blocking_under_lock_fires(tmp_path):
    findings, _ = trace(tmp_path, {"pipeline/run.py": (
        "import threading, time\n"
        "LOCK = threading.Lock()\n"
        "def _run_with_config():\n"
        "    with LOCK:\n"
        "        time.sleep(1)\n"
        "        open('x').read()\n"
    )})
    assert rules_of(findings) == {"blocking-under-lock"}
    assert len(findings) == 2
    assert all("LOCK" in f.message for f in findings)


def test_condition_wait_on_held_lock_exempt(tmp_path):
    """Condition.wait RELEASES the held lock while waiting — the JobQueue
    pop pattern must not flag."""
    findings, _ = trace(tmp_path, {"pipeline/run.py": (
        "import threading\n"
        "class Q:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._cv = threading.Condition(self._lock)\n"
        "    def pop(self):\n"
        "        with self._cv:\n"
        "            self._cv.wait(0.1)\n"
        "def _run_with_config():\n"
        "    Q().pop()\n"
    )})
    assert findings == []


def test_signal_unsafe_call_fires(tmp_path):
    findings, _ = trace(tmp_path, {"pipeline/run.py": (
        "import signal, threading\n"
        "LOCK = threading.Lock()\n"
        "def handler(sig, frame):\n"
        "    with LOCK:\n"
        "        pass\n"
        "def _run_with_config():\n"
        "    signal.signal(signal.SIGUSR1, handler)\n"
    )})
    assert rules_of(findings) == {"signal-unsafe-call"}
    (f,) = findings
    assert "signal:run.handler" in f.message


# ---------------------------------------------------------------------------
# root discovery & traversal mechanics


def test_root_inventory_sees_every_spawn_mechanism(tmp_path):
    _, roots = trace(tmp_path, {
        "pipeline/run.py": (
            "import signal, threading\n"
            "from concurrent.futures import ThreadPoolExecutor\n"
            "def worker():\n"
            "    pass\n"
            "def handler(sig, frame):\n"
            "    pass\n"
            "def _run_with_config():\n"
            "    threading.Thread(target=worker).start()\n"
            "    ThreadPoolExecutor(2).submit(worker)\n"
            "    signal.signal(signal.SIGUSR1, handler)\n"
        ),
        "serve/http.py": (
            "from http.server import BaseHTTPRequestHandler\n"
            "class H(BaseHTTPRequestHandler):\n"
            "    def do_GET(self):\n"
            "        pass\n"
        ),
    })
    kinds = {(r.kind, r.name) for r in roots}
    assert ("main", "main:pipeline-loop") in kinds
    assert ("thread", "thread:run.worker") in kinds
    assert ("pool", "pool:run.worker") in kinds
    assert ("signal", "signal:run.handler") in kinds
    assert ("http", "http:H.do_GET") in kinds


def test_unresolvable_thread_target_still_inventoried(tmp_path):
    _, roots = trace(tmp_path, {"pipeline/run.py": (
        "import threading\n"
        "class S:\n"
        "    def go(self, srv):\n"
        "        threading.Thread(target=srv.serve_forever).start()\n"
    )})
    ext = [r for r in roots
           if r.kind == "thread"]  # graftlint: disable=chaos-unknown-kind
    assert len(ext) == 1
    assert ext[0].func is None and "external" in ext[0].name


def test_data_arg_submit_is_traversed_not_spawned(tmp_path):
    """JobQueue.submit takes DATA args — graftrace must walk into it (the
    unlocked write inside is reachable from two roots), not treat it as a
    pool spawn site."""
    findings, roots = trace(tmp_path, {"pipeline/run.py": (
        "import threading\n"
        'LOCK_OWNERSHIP = {"Q.jobs": "_lock"}\n'
        "class Q:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.jobs = {}\n"
        "    def submit(self, raw):\n"
        "        self.jobs[raw] = 1  # unlocked, reached via .submit()\n"
        '_Q: "Q | None" = None\n'
        "def worker():\n"
        "    _Q.submit('w')\n"
        "def _run_with_config():\n"
        "    q = Q()\n"
        "    threading.Thread(target=worker, daemon=True).start()\n"
        "    q.submit('m')\n"
    )})
    assert rules_of(findings) == {"race-unlocked-write"}
    assert not any(
        r.kind == "pool" for r in roots)  # graftlint: disable=chaos-unknown-kind


def test_workers_start_with_empty_lockset(tmp_path):
    """A spawner holding a lock at the spawn site must not leak that lock
    into the worker's lockset (else every write looks guarded)."""
    findings, _ = trace(tmp_path, {"pipeline/run.py": (
        "import threading\n"
        'LOCK_OWNERSHIP = {"W.table": "_lock"}\n'
        "class W:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.table = {}\n"
        "    def spawn(self):\n"
        "        with self._lock:\n"
        "            threading.Thread(target=self._bg, daemon=True).start()\n"
        "    def _bg(self):\n"
        "        self.table['k'] = 1  # unlocked: spawner's lock not ours\n"
        "    def poke(self):\n"
        "        with self._lock:\n"
        "            self.table['m'] = 2\n"
        "def _run_with_config():\n"
        "    w = W()\n"
        "    w.spawn()\n"
        "    w.poke()\n"
    )})
    assert rules_of(findings) == {"race-unlocked-write"}


# ---------------------------------------------------------------------------
# CLI contract (graftcheck discipline)


def test_cli_shipped_tree_matches_expected_list(monkeypatch, capsys):
    monkeypatch.chdir(REPO)
    assert graftrace_main(["--expect"]) == 0
    out = capsys.readouterr().out
    assert "[expected]" in out


def test_expected_list_entries_all_justified():
    body = json.load(open(DEFAULT_EXPECT))
    assert body["findings"], "expected list exists but is empty?"
    for entry in body["findings"]:
        assert entry.get("justification", "").strip(), (
            f"unjustified expected finding: {entry['rule']} at "
            f"{entry['path']}:{entry['line']}")


def test_cli_json_carries_exit_code_and_roots(monkeypatch, capsys):
    monkeypatch.chdir(REPO)
    assert graftrace_main(["--expect", "--json"]) == 0
    body = json.loads(capsys.readouterr().out)
    assert body["exit_code"] == 0
    assert body["count"] == 0
    assert len(body["baselined"]) == len(
        json.load(open(DEFAULT_EXPECT))["findings"])
    names = {r["name"] for r in body["roots"]}
    assert "main:pipeline-loop" in names
    assert "main:daemon-loop" in names
    assert "thread:Watchdog._monitor" in names


def test_cli_new_finding_fails_expect(tmp_path, capsys):
    (tmp_path / "pipeline").mkdir(parents=True)
    (tmp_path / "pipeline" / "run.py").write_text(_WATCHDOG_RACE)
    expect = tmp_path / "empty.json"
    expect.write_text('{"findings": []}')
    rc = graftrace_main([str(tmp_path), "--expect", str(expect)])
    assert rc == 1
    assert "race-unlocked-write" in capsys.readouterr().out


def test_cli_stale_expected_entry_fails(tmp_path, capsys):
    (tmp_path / "clean.py").write_text("x = 1\n")
    expect = tmp_path / "stale.json"
    expect.write_text(json.dumps({"findings": [{
        "path": "gone.py", "rule": "race-unlocked-write",
        "message": "fixed long ago"}]}))
    rc = graftrace_main([str(tmp_path), "--expect", str(expect)])
    assert rc == 1
    assert "no longer reported" in capsys.readouterr().err


def test_cli_roots_json(monkeypatch, capsys):
    monkeypatch.chdir(REPO)
    assert graftrace_main(["--roots", "--json"]) == 0
    body = json.loads(capsys.readouterr().out)
    assert all({"name", "kind", "func", "path", "line"} <= set(r)
               for r in body["roots"])


def test_cli_bad_path_is_usage_error(capsys):
    assert graftrace_main(["definitely/not/a/path"]) == 2
    assert "no such path" in capsys.readouterr().err


def test_cli_never_crashes_on_unreadable_expect(tmp_path, capsys):
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    (tmp_path / "ok.py").write_text("x = 1\n")
    assert graftrace_main([str(tmp_path), "--expect", str(bad)]) == 2
    assert "cannot read" in capsys.readouterr().err


def test_graftrace_is_jax_free_under_poisoned_import():
    """The whole CLI path must run with jax IMPOSSIBLE to import."""
    code = (
        "import sys\n"
        "class _Poison:\n"
        "    def find_spec(self, name, *a, **k):\n"
        "        if name == 'jax' or name.startswith('jax.'):\n"
        "            raise ImportError('jax import poisoned by test')\n"
        "sys.meta_path.insert(0, _Poison())\n"
        "from tools.graftrace.cli import main\n"
        "sys.exit(main(['--expect']))\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code], cwd=REPO,
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "internal error" not in proc.stderr


# ---------------------------------------------------------------------------
# dynamic twin: robustness/lockcheck


@pytest.fixture()
def armed_lockcheck():
    lockcheck.arm()
    lockcheck.reset()
    yield
    lockcheck.disarm()
    lockcheck.reset()


def test_lockcheck_disarmed_is_inert():
    lockcheck.disarm()
    lockcheck.reset()
    lock = lockcheck.make_lock()
    assert type(lock) is type(threading.Lock())
    lockcheck.assert_held(lock, "anything")  # no violation machinery runs
    assert lockcheck.violations() == []


def test_lockcheck_armed_records_unheld_entry(armed_lockcheck):
    lock = lockcheck.make_lock()
    lockcheck.assert_held(lock, "Fixture._locked")
    (v,) = lockcheck.violations()
    assert "Fixture._locked" in v and "without owning" in v


def test_lockcheck_armed_passes_held_entry(armed_lockcheck):
    lock = lockcheck.make_lock()
    with lock:
        lockcheck.assert_held(lock, "Fixture._locked")
    assert lockcheck.violations() == []


def test_lockcheck_armed_lock_is_condition_compatible(armed_lockcheck):
    lock = lockcheck.make_lock()
    cv = threading.Condition(lock)
    with cv:
        assert not cv.wait(0.01)  # times out, no crash: RLock works


def test_lockcheck_skips_pre_arming_plain_locks(armed_lockcheck):
    plain = threading.Lock()  # constructed before arming (no _is_owned)
    lockcheck.assert_held(plain, "Legacy._locked")
    assert lockcheck.violations() == []


def test_lockcheck_violations_bounded(armed_lockcheck):
    lock = lockcheck.make_lock()
    for _ in range(lockcheck.MAX_VIOLATIONS + 20):
        lockcheck.assert_held(lock, "Hot._locked")
    assert len(lockcheck.violations()) == lockcheck.MAX_VIOLATIONS


def test_lockcheck_arm_from_env(monkeypatch):
    lockcheck.disarm()
    monkeypatch.delenv(lockcheck.ENV_VAR, raising=False)
    assert lockcheck.arm_from_env() is None
    assert not lockcheck.armed()
    monkeypatch.setenv(lockcheck.ENV_VAR, "1")
    assert lockcheck.arm_from_env() is True
    assert lockcheck.armed()
    lockcheck.disarm()


def test_lockcheck_guarded_method_clean_when_called_properly(
        armed_lockcheck):
    """The shipped assert_held plants pass when the caller honours the
    *_locked contract — FlightRecorder.add_instant under its own lock."""
    from ont_tcrconsensus_tpu.obs.live import FlightRecorder
    rec = FlightRecorder(max_events=8)
    rec.add_instant("x", {})
    assert lockcheck.violations() == []


def test_lockcheck_catches_contract_breach(armed_lockcheck):
    from ont_tcrconsensus_tpu.obs.live import FlightRecorder
    rec = FlightRecorder(max_events=8)
    rec._add_locked({"k": "breach"})  # deliberately without the lock
    assert any("FlightRecorder._add_locked" in v
               for v in lockcheck.violations())
