"""Unit tests for the liveness watchdog (robustness/watchdog.py).

Fast, pipeline-free coverage of the pieces the chaos e2e scenarios in
test_chaos.py compose: deadline auto-scaling math at tiny/huge workload
sizes, soft-deadline stall reporting (event + stack dump), hard-deadline
cancellation delivering :class:`StageTimeout` into the stalled thread,
heartbeat-driven deadline resets, and the disarmed fast path. Every
deadline here is sub-second so the whole file stays well inside the
tier-1 budget.
"""

import threading
import time

import pytest

from ont_tcrconsensus_tpu.robustness import retry, watchdog

#: safety cap on the tests' own simulated wedges: reached only when the
#: watchdog fails to cancel, so the suite can't hang on a regression
_WEDGE_CAP_S = 30.0


@pytest.fixture(autouse=True)
def _clean_watchdog_state():
    retry.recorder().reset()
    yield
    watchdog.deactivate()
    retry.recorder().reset()


def _events(site: str) -> list[dict]:
    return [e for e in retry.recorder().events if e["site"] == site]


# --- deadline auto-scaling math ---------------------------------------------


def test_scaled_timeout_tiny_workloads_keep_full_base():
    """Up to units_per_base units the base is the deadline: fixed overhead
    (compiles, warmup) dominates tiny workloads, so they must not get a
    proportionally tiny — spuriously firing — deadline."""
    assert watchdog.scaled_timeout(60.0, 0) == 60.0
    assert watchdog.scaled_timeout(60.0, 1) == 60.0
    assert watchdog.scaled_timeout(60.0, watchdog.UNITS_PER_BASE) == 60.0


def test_scaled_timeout_huge_workloads_scale_linearly():
    base = 60.0
    upb = watchdog.UNITS_PER_BASE
    assert watchdog.scaled_timeout(base, 10 * upb) == pytest.approx(600.0)
    assert watchdog.scaled_timeout(base, 1000 * upb) == pytest.approx(60000.0)
    # just past the knee: scaling is continuous, not a step
    assert watchdog.scaled_timeout(base, upb + 1) == pytest.approx(
        base * (upb + 1) / upb
    )


def test_scaled_timeout_monotone_and_never_below_base():
    prev = 0.0
    for units in (0, 1, 10, 999, 1000, 1001, 5000, 10**7):
        t = watchdog.scaled_timeout(5.0, units)
        assert t >= 5.0
        assert t >= prev
        prev = t


def test_scaled_timeout_custom_units_per_base():
    assert watchdog.scaled_timeout(10.0, 8, units_per_base=4) == 20.0
    assert watchdog.scaled_timeout(10.0, 3, units_per_base=4) == 10.0


# --- StageTimeout / classifier contract -------------------------------------


def test_stage_timeout_classified_transient():
    """The watchdog's cancel exception re-enters the retry path: both the
    isinstance and the DEADLINE_EXCEEDED message marker say transient —
    and the argument-less construction (all PyThreadState_SetAsyncExc can
    deliver) still carries the marker."""
    exc = watchdog.StageTimeout()
    assert "DEADLINE_EXCEEDED" in str(exc)
    assert retry.classify(exc) == "transient"
    assert retry.classify(watchdog.StageTimeout("custom message")) == "transient"


# --- disarmed fast path ------------------------------------------------------


def test_disarmed_heartbeat_and_guard_are_noops():
    assert not watchdog.active()
    watchdog.heartbeat("anywhere")  # must not raise
    with watchdog.guard("stage", units=10**9):
        watchdog.heartbeat("inside")
    assert watchdog.active_deadline_s() is None
    assert retry.recorder().events == []


# --- armed behavior ----------------------------------------------------------


def test_soft_deadline_emits_stall_event_and_stack_dump(tmp_path):
    log = tmp_path / "watchdog.log"
    wd = watchdog.activate(watchdog.Watchdog(
        base_timeout_s=10.0, soft_fraction=0.02, tick_s=0.02,
        log_path=str(log),
    ))
    wd.start()
    try:
        with watchdog.guard("polish", units=0):
            watchdog.heartbeat("polish.chunk")
            time.sleep(0.5)  # soft deadline (0.2s) expires; hard (10s) not
    finally:
        wd.stop()
    stalls = _events("watchdog.stall")
    assert len(stalls) == 1  # soft fires ONCE per stall, not per tick
    ev = stalls[0]
    assert ev["outcome"] == "stall_detected"
    assert ev["classification"] == "stall"
    assert ev["detail"]["stage"] == "polish"
    assert ev["detail"]["last_heartbeat_site"] == "polish.chunk"
    assert ev["detail"]["stalled_s"] >= ev["detail"]["soft_deadline_s"]
    # the all-thread faulthandler dump landed in the library log
    dump = log.read_text()
    assert "dumping all thread stacks" in dump
    assert "Thread" in dump or "Current thread" in dump


def test_hard_deadline_cancels_stalled_thread_with_stage_timeout():
    wd = watchdog.activate(watchdog.Watchdog(base_timeout_s=0.3, tick_s=0.02))
    wd.start()
    try:
        with pytest.raises(watchdog.StageTimeout):
            with watchdog.guard("wedged"):
                deadline = time.monotonic() + _WEDGE_CAP_S
                while time.monotonic() < deadline:  # interruptible wedge
                    time.sleep(0.01)
                raise AssertionError("watchdog never cancelled the stall")
    finally:
        wd.stop()
    outcomes = [e["outcome"] for e in _events("watchdog.stall")]
    assert "hard_cancel" in outcomes
    assert "stall_detected" in outcomes  # soft fired on the way to hard


def test_soft_report_rearms_after_recovery():
    """A stall that RECOVERS via heartbeats (never reaching the hard
    deadline) must be diagnosed again if the stage stalls a second time —
    the soft report re-arms on every heartbeat, not only at hard cancel."""
    wd = watchdog.activate(watchdog.Watchdog(
        base_timeout_s=10.0, soft_fraction=0.02, tick_s=0.02,
    ))
    wd.start()
    try:
        with watchdog.guard("flappy"):
            time.sleep(0.4)                    # stall 1: past soft (0.2s)
            watchdog.heartbeat("flappy.tick")  # recovery re-arms the report
            time.sleep(0.4)                    # stall 2: must report again
    finally:
        wd.stop()
    outcomes = [e["outcome"] for e in _events("watchdog.stall")]
    assert outcomes.count("stall_detected") == 2
    assert "hard_cancel" not in outcomes


def test_heartbeats_reset_the_deadline():
    """Steady progress never fires, regardless of total stage length: 0.6s
    of work under a 0.25s hard deadline, heartbeating every 0.05s."""
    wd = watchdog.activate(watchdog.Watchdog(base_timeout_s=0.25, tick_s=0.02))
    wd.start()
    try:
        with watchdog.guard("steady"):
            for _ in range(12):
                watchdog.heartbeat("steady.tick")
                time.sleep(0.05)
    finally:
        wd.stop()
    assert _events("watchdog.stall") == []


def test_cancelled_stage_retry_gets_fresh_deadline():
    """After a hard cancel the stall clock resets: a retry attempt inside
    the SAME guard scope that then makes steady progress is not cancelled
    again, and a SECOND stall is detected again (soft re-arms)."""
    wd = watchdog.activate(watchdog.Watchdog(base_timeout_s=0.3, tick_s=0.02))
    wd.start()
    cancels = 0
    try:
        with watchdog.guard("retryable"):
            for _attempt in range(3):
                try:
                    deadline = time.monotonic() + _WEDGE_CAP_S
                    while time.monotonic() < deadline:
                        time.sleep(0.01)
                except watchdog.StageTimeout:
                    cancels += 1
                    continue
    finally:
        wd.stop()
    assert cancels == 3
    outcomes = [e["outcome"] for e in _events("watchdog.stall")]
    assert outcomes.count("hard_cancel") == 3
    assert outcomes.count("stall_detected") == 3  # soft re-armed each time


def test_guard_exit_is_race_free_with_cancel():
    """A guard that exits right as the deadline expires must never leak a
    StageTimeout into code OUTSIDE the scope: the cancel is sent under the
    registry lock only while the scope is still registered, and a queued
    undelivered exception is cleared at guard exit."""
    wd = watchdog.activate(watchdog.Watchdog(base_timeout_s=0.05, tick_s=0.01))
    wd.start()
    try:
        for _ in range(20):
            try:
                with watchdog.guard("short"):
                    time.sleep(0.06)  # straddles the deadline
            except watchdog.StageTimeout:
                pass  # delivered inside the scope: fine
            # 10ms of post-scope work: a leaked async exc would land here
            t0 = time.monotonic()
            while time.monotonic() - t0 < 0.01:
                pass
    finally:
        wd.stop()


def test_worker_thread_guard_is_independent_of_main_thread():
    """Guards are per-thread (overlap.py workers register their own): a
    stalled worker is cancelled while the main thread's scope is
    untouched."""
    wd = watchdog.activate(watchdog.Watchdog(base_timeout_s=0.3, tick_s=0.02))
    wd.start()
    seen: dict = {}

    def worker():
        try:
            with watchdog.guard("overlap.qc"):
                deadline = time.monotonic() + _WEDGE_CAP_S
                while time.monotonic() < deadline:
                    time.sleep(0.01)
        except watchdog.StageTimeout as exc:
            seen["exc"] = exc

    try:
        with watchdog.guard("main"):
            t = threading.Thread(target=worker)
            t.start()
            while t.is_alive():
                watchdog.heartbeat("main.loop")  # main makes progress
                time.sleep(0.02)
            t.join()
    finally:
        wd.stop()
    assert isinstance(seen.get("exc"), watchdog.StageTimeout)
    cancelled = [e for e in _events("watchdog.stall")
                 if e["outcome"] == "hard_cancel"]
    assert [e["detail"]["stage"] for e in cancelled] == ["overlap.qc"]


def test_cli_installs_sigquit_stack_dump():
    """The CLI registers SIGQUIT -> all-thread faulthandler dump at startup
    (ISSUE 5 satellite): a wedged production run is diagnosable with
    ``kill -QUIT`` even when the watchdog is disarmed."""
    import faulthandler
    import signal

    from ont_tcrconsensus_tpu.pipeline import cli

    if not hasattr(signal, "SIGQUIT"):
        pytest.skip("platform has no SIGQUIT")
    faulthandler.unregister(signal.SIGQUIT)  # a clean slate
    cli._install_stack_dump_signal()
    try:
        assert faulthandler.unregister(signal.SIGQUIT)  # it WAS registered
    finally:
        # never leave a half-registered handler behind for other tests
        faulthandler.unregister(signal.SIGQUIT)


def test_active_deadline_reflects_scaled_units():
    wd = watchdog.activate(watchdog.Watchdog(base_timeout_s=2.0))
    # no monitor needed: deadline introspection is registry-only
    with watchdog.guard("big", units=watchdog.UNITS_PER_BASE * 5):
        assert watchdog.active_deadline_s() == pytest.approx(10.0)
    assert watchdog.active_deadline_s() is None


def test_early_return_disarms_watchdog(tmp_path):
    """Every exit path of run_with_config must tear down the process-global
    watchdog — the only_run_reference_self_homology early return used to
    leak an armed monitor into the embedder's next (even unarmed) run."""
    from ont_tcrconsensus_tpu.io import fastx, simulator
    from ont_tcrconsensus_tpu.pipeline.config import RunConfig
    from ont_tcrconsensus_tpu.pipeline.run import run_with_config

    lib = simulator.simulate_library(seed=3, num_regions=2,
                                     molecules_per_region=(1, 1),
                                     reads_per_molecule=(1, 1))
    fastx.write_fasta(tmp_path / "reference.fa", lib.reference.items())
    (tmp_path / "fastq_pass").mkdir()
    cfg = RunConfig.from_dict({
        "reference_file": str(tmp_path / "reference.fa"),
        "fastq_pass_dir": str(tmp_path / "fastq_pass"),
        "stage_timeout_s": 60,
        "only_run_reference_self_homology": True,
    })
    assert run_with_config(cfg) == {}
    assert not watchdog.active(), "early return leaked an armed watchdog"


def test_stall_drill_refuses_deadline_past_safety_cap():
    """A stall/hang drill under a hard deadline beyond STALL_CAP_S would
    end BEFORE the watchdog fires and wrongly diagnose it as disarmed —
    the injection must refuse loudly up front instead (and instantly:
    no sleep happens on this path)."""
    from ont_tcrconsensus_tpu.robustness import faults

    watchdog.activate(watchdog.Watchdog(base_timeout_s=faults.STALL_CAP_S * 2))
    with watchdog.guard("polish"):
        with pytest.raises(RuntimeError, match="safety cap"):
            faults._stall_until_cancelled("hang", "polish.dispatch")
        with pytest.raises(RuntimeError, match="safety cap"):
            faults._stall_until_cancelled("stall", "polish.dispatch")
