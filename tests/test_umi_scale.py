"""UMI clustering at lane-scale cardinality (VERDICT r2 weak #6).

North-star config #2 produces region clusters with 10^4-10^5 unique UMIs;
the shortlist + budgeted-dovetail + merge-repair path (cluster/umi.py) only
departs from the exact full-matrix path above _FULL_MATRIX_MAX=256 uniques,
so default-suite group sizes never exercise the regime where shortlist
misses and the O(U*K) pair stream matter. This test clusters ~37k uniques
(20k molecules x 1-3 errored copies, 0-2 edits each — the same edit regime
as round-1 UMI reads) and asserts molecule-level correctness:

- no molecule's copies are split across clusters (recall),
- over-merged clusters stay at the UMI-collision floor (two 64-nt UMIs
  landing within the identity threshold by chance; seed-fixed, 4 pairs),
- cluster count lands on molecules minus those collisions exactly.

Runs in ~6 min on a 1-core CPU host: ``pytest -m slow tests/test_umi_scale.py``.
"""

import numpy as np
import pytest

from ont_tcrconsensus_tpu.cluster.umi import cluster_umis
from ont_tcrconsensus_tpu.io import simulator


@pytest.mark.slow
def test_umi_clustering_20k_molecules():
    rng = np.random.default_rng(9)
    n_mol = 20_000
    umis: list[str] = []
    truth: list[int] = []
    for m in range(n_mol):
        u = simulator.instantiate_iupac(
            rng, "TTTVVTTVVVVTTVVVVTTVVVVTTVVVVTTT"
        ) + simulator.instantiate_iupac(
            rng, "AAABBBBAABBBBAABBBBAABBBBAABBAAA"
        )
        for _ in range(int(rng.integers(1, 4))):
            s = list(u)
            for _ in range(int(rng.integers(0, 3))):
                p = int(rng.integers(len(s)))
                op = int(rng.integers(3))
                if op == 0:
                    s[p] = "ACGT"[rng.integers(4)]
                elif op == 1:
                    s.insert(p, "ACGT"[rng.integers(4)])
                elif len(s) > 1:
                    del s[p]
            umis.append("".join(s))
            truth.append(m)

    assert len(set(umis)) > 20_000  # well inside the shortlist regime

    res = cluster_umis(umis, 0.9)
    labels = np.asarray(res.labels)

    by_mol: dict[int, set[int]] = {}
    lab_mols: dict[int, set[int]] = {}
    for lab, m in zip(labels, truth):
        by_mol.setdefault(m, set()).add(int(lab))
        lab_mols.setdefault(int(lab), set()).add(m)

    split = sum(1 for s in by_mol.values() if len(s) > 1)
    overmerged = sum(1 for s in lab_mols.values() if len(s) > 1)
    assert split == 0, f"{split} molecules split across clusters"
    assert overmerged <= 10, f"{overmerged} clusters span multiple molecules"
    # every merge removes at least one cluster from the molecule count
    assert n_mol - res.num_clusters <= overmerged * 2
    assert res.num_clusters >= n_mol - 10
