"""Overlapped stage executor (pipeline/overlap.py)."""

import threading
import time

import pytest

from ont_tcrconsensus_tpu.pipeline.overlap import StageExecutor
from ont_tcrconsensus_tpu.qc.timing import StageTimer


def test_commit_returns_result_and_records_split_timing():
    ex = StageExecutor()
    timer = StageTimer()
    gate = threading.Event()

    def work():
        gate.wait(5.0)
        time.sleep(0.05)
        return {"answer": 42}

    stage = ex.submit("qc_stage", work)
    gate.set()
    result = ex.commit(stage, timer)
    assert result == {"answer": 42}
    # critical-path entry = blocking wait; _bg entry = worker wall clock
    assert "qc_stage" in timer.seconds
    assert timer.seconds["qc_stage_bg"] >= 0.05
    assert not ex.wait_all()  # committed stages are no longer pending


def test_commit_reraises_worker_failure_on_main_thread():
    ex = StageExecutor()

    def boom():
        raise ValueError("qc exploded")

    stage = ex.submit("bad_stage", boom)
    with pytest.raises(ValueError, match="qc exploded"):
        ex.commit(stage)


def test_commit_records_bg_timer_even_when_stage_failed():
    """The worker's wall clock must land in <name>_bg on the FAILURE path
    too — the timing table would otherwise under-report exactly the runs
    someone is diagnosing (ISSUE 2 satellite)."""
    ex = StageExecutor()
    timer = StageTimer()

    def boom():
        time.sleep(0.05)
        raise ValueError("qc exploded")

    stage = ex.submit("bad_stage", boom)
    with pytest.raises(ValueError, match="qc exploded"):
        ex.commit(stage, timer)
    assert timer.seconds["bad_stage_bg"] >= 0.05
    assert "bad_stage" in timer.seconds  # critical-path wait still recorded


def test_rerun_sync_reexecutes_the_stage_callable():
    """rerun_sync is the transient-recovery path: the same callable runs
    again on the calling thread and returns a fresh result."""
    ex = StageExecutor()
    calls = []

    def work(x):
        calls.append(x)
        return x * 2

    stage = ex.submit("s", work, 21)
    assert ex.commit(stage) == 42
    assert stage.rerun_sync() == 42
    assert calls == [21, 21]


def test_wait_all_collects_failures_without_raising():
    ex = StageExecutor()
    ex.submit("ok", lambda: 1)
    ex.submit("bad", lambda: (_ for _ in ()).throw(RuntimeError("x")))
    failures = ex.wait_all()
    assert [name for name, _ in failures] == ["bad"]
    assert isinstance(failures[0][1], RuntimeError)
    assert not ex.wait_all()


def test_permits_bound_in_flight_stages():
    """The permit semaphore caps live background stages: a third submit
    blocks until one of the first two finishes (the memory bound —
    deferred stages pin their input buffers)."""
    ex = StageExecutor(max_in_flight=2)
    release = threading.Event()
    started = []

    def work(i):
        started.append(i)
        release.wait(5.0)
        return i

    s1 = ex.submit("a", work, 1)
    s2 = ex.submit("b", work, 2)
    t0 = time.perf_counter()
    blocker: list = []

    def third():
        blocker.append(ex.submit("c", work, 3))

    t = threading.Thread(target=third)
    t.start()
    time.sleep(0.15)
    assert not blocker  # still blocked on the permit
    release.set()
    t.join(5.0)
    assert blocker and time.perf_counter() - t0 >= 0.1
    assert ex.commit(s1) == 1 and ex.commit(s2) == 2
    assert ex.commit(blocker[0]) == 3
