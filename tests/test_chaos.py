"""Chaos e2e: every registered injection point, faulted + resumed, must
reproduce the uninterrupted run byte-for-byte.

The contract under test (ISSUE 2 / README "Failure semantics"): for each
fault the robustness layer either *recovers in-run* (transient retry, OOM
batch shrink, QC recompute) or *degrades to a resumable state* (fallback,
torn-manifest tolerance, preemption, process kill) — and in both cases the
final counts CSV and consensus FASTA are byte-identical to a run where the
fault never fired, with the retry recorded in robustness_report.json.

Everything here runs on the simulator library; runs inside one pytest
process share the in-memory jit cache, so each scenario costs seconds.
"""

import json
import os
import shutil
import signal
import subprocess
import sys

import pytest

from ont_tcrconsensus_tpu.io import fastx, simulator
from ont_tcrconsensus_tpu.pipeline.config import RunConfig
from ont_tcrconsensus_tpu.pipeline.run import run_with_config
from ont_tcrconsensus_tpu.robustness import faults, shutdown

pytestmark = pytest.mark.chaos

COUNTS_CSV = os.path.join("nano_tcr", "barcode01", "counts",
                          "umi_consensus_counts.csv")
MERGED_FASTA = os.path.join("nano_tcr", "barcode01", "fasta",
                            "merged_consensus.fasta")
MANIFEST = os.path.join("nano_tcr", "barcode01", "stage_manifest.json")
REPORT = os.path.join("nano_tcr", "robustness_report.json")


@pytest.fixture(autouse=True)
def _disarm_chaos():
    yield
    faults.disarm()
    shutdown.deactivate()


@pytest.fixture(scope="module")
def chaos_lib(tmp_path_factory):
    """Simulated library + ONE uninterrupted baseline run (the byte-identity
    reference for every scenario)."""
    tmp = tmp_path_factory.mktemp("chaos")
    lib = simulator.simulate_library(
        seed=23,
        num_regions=3,
        molecules_per_region=(2, 3),
        reads_per_molecule=(5, 8),
        sub_rate=0.006,
        ins_rate=0.003,
        del_rate=0.003,
        region_len=(700, 850),  # stays in the 1024-width bucket
    )
    inputs = tmp / "inputs"
    (inputs / "fastq_pass" / "barcode01").mkdir(parents=True)
    fastx.write_fasta(inputs / "reference.fa", lib.reference.items())
    fastx.write_fastq(
        inputs / "fastq_pass" / "barcode01" / "barcode01.fastq.gz", lib.reads
    )
    baseline = tmp / "baseline"
    _stage_inputs(inputs, baseline)
    results = run_with_config(_cfg(baseline))
    assert results["barcode01"] == lib.true_counts
    return {
        "tmp": tmp,
        "inputs": inputs,
        "lib": lib,
        "baseline_artifacts": _artifact_bytes(baseline),
        "baseline_counts": results["barcode01"],
    }


def _stage_inputs(inputs, root):
    root.mkdir(parents=True, exist_ok=True)
    shutil.copy(inputs / "reference.fa", root / "reference.fa")
    shutil.copytree(inputs / "fastq_pass", root / "fastq_pass")


def _cfg(root, **overrides) -> RunConfig:
    d = {
        "reference_file": str(root / "reference.fa"),
        "fastq_pass_dir": str(root / "fastq_pass"),
        "minimal_length": 600,
        "min_reads_per_cluster": 4,
        "read_batch_size": 64,
        "polish_method": "poa",
        "delete_tmp_files": False,
        "hbm_budget_gb": 12.0,       # deterministic budget-derived batches
        "retry_base_delay_s": 0.0,   # no wall-clock tax on test retries
    }
    d.update(overrides)
    return RunConfig.from_dict(d)


def _artifact_bytes(root) -> dict[str, bytes]:
    out = {}
    for rel in (COUNTS_CSV, MERGED_FASTA):
        path = root / "fastq_pass" / rel
        assert path.exists(), f"missing artifact {rel}"
        out[rel] = path.read_bytes()
    return out


def _report(root) -> dict:
    return json.load(open(root / "fastq_pass" / REPORT))


def _manifest_stages(root) -> dict:
    """Stage map of the library manifest (v2 ``{"version", "stages"}`` or
    legacy v1 flat) — what ``"counts" in ...`` should be asked of."""
    data = json.loads((root / "fastq_pass" / MANIFEST).read_text())
    return data.get("stages", data)


def _assert_byte_identical(chaos_lib, root):
    got = _artifact_bytes(root)
    for rel, want in chaos_lib["baseline_artifacts"].items():
        assert got[rel] == want, f"{rel} diverged from the uninterrupted run"


# --- in-run recovery scenarios ---------------------------------------------


def test_chaos_transient_assign_dispatch_recovers(chaos_lib, tmp_path):
    """A transient device fault on the fused-pass dispatch retries the
    (idempotent) pass and completes with byte-identical outputs."""
    root = tmp_path / "transient"
    _stage_inputs(chaos_lib["inputs"], root)
    results = run_with_config(_cfg(root, chaos=[
        {"site": "assign.dispatch", "kind": "transient"},
    ]))
    assert results["barcode01"] == chaos_lib["baseline_counts"]
    assert faults.fired("assign.dispatch") == 1
    _assert_byte_identical(chaos_lib, root)
    site = _report(root)["sites"]["assign.round1"]
    assert site["by_outcome"]["retried"] == 1
    assert site["by_outcome"]["recovered"] == 1
    assert site["by_classification"]["transient"] >= 1
    # resume after an in-run recovery is a no-op with identical results
    resumed = run_with_config(_cfg(root, resume=True))
    assert resumed["barcode01"] == chaos_lib["baseline_counts"]
    _assert_byte_identical(chaos_lib, root)


def test_chaos_oom_polish_shrinks_batch_and_completes(chaos_lib, tmp_path):
    """RESOURCE_EXHAUSTED on the polish dispatch DEGRADES instead of
    skipping: the chunk requeues at a budget-shrunken cluster batch and the
    group completes — the library never enters the failed/skip path."""
    root = tmp_path / "oom"
    _stage_inputs(chaos_lib["inputs"], root)
    results = run_with_config(_cfg(root, chaos=[
        {"site": "polish.dispatch", "kind": "oom"},
    ]))
    assert results["barcode01"] == chaos_lib["baseline_counts"]
    assert faults.fired("polish.dispatch") == 1
    _assert_byte_identical(chaos_lib, root)
    report = _report(root)
    outcomes = report["sites"]["polish.dispatch"]["by_outcome"]
    assert outcomes["oom_shrink"] == 1
    assert outcomes["recovered"] >= 1
    shrink = next(e for e in report["events"] if e["outcome"] == "oom_shrink")
    assert shrink["classification"] == "oom"
    assert (shrink["detail"]["cluster_batch_to"]
            < shrink["detail"]["cluster_batch_from"])
    # no group was skipped: the degradation log must not exist
    assert not (root / "fastq_pass" / "nano_tcr" / "barcode01" / "logs"
                / "incomplete_region_clusters.log").exists()


def test_chaos_transient_polish_dispatch_retries_same_shape(chaos_lib, tmp_path):
    """A transient fault on the polish dispatch retries the SAME chunk
    shape (no batch shrink) and completes byte-identically."""
    root = tmp_path / "polish_transient"
    _stage_inputs(chaos_lib["inputs"], root)
    results = run_with_config(_cfg(root, chaos=[
        {"site": "polish.dispatch", "kind": "transient"},
    ]))
    assert results["barcode01"] == chaos_lib["baseline_counts"]
    _assert_byte_identical(chaos_lib, root)
    outcomes = _report(root)["sites"]["polish.dispatch"]["by_outcome"]
    assert outcomes["retried"] == 1 and outcomes["recovered"] == 1
    assert "oom_shrink" not in outcomes


def test_chaos_overlap_worker_death_recomputed(chaos_lib, tmp_path):
    """A QC worker thread dying of a transient fault is recomputed on the
    main thread at commit; the run completes with identical outputs and
    the error-profile artifact still exists."""
    root = tmp_path / "worker"
    _stage_inputs(chaos_lib["inputs"], root)
    results = run_with_config(_cfg(root, chaos=[
        {"site": "overlap.worker", "kind": "transient"},
    ]))
    assert results["barcode01"] == chaos_lib["baseline_counts"]
    assert faults.fired("overlap.worker") == 1
    _assert_byte_identical(chaos_lib, root)
    logs = root / "fastq_pass" / "nano_tcr" / "barcode01" / "logs"
    assert (logs / "barcode01_align_error_profile.log").exists()
    outcomes = _report(root)["sites"]["overlap.worker"]["by_outcome"]
    assert outcomes["retried"] == 1 and outcomes["recovered"] == 1


@pytest.mark.parametrize("round_site,expect_fasta_identical", [
    ("cluster.batched_round1", True),
    ("cluster.batched_round2", True),
])
def test_chaos_poisoned_batched_pass_falls_back_per_region(
        chaos_lib, tmp_path, round_site, expect_fasta_identical):
    """A deterministic failure of the library-wide batched UMI clustering
    pass degrades to the per-region retry loop with identical counts
    (the run.py fallback that previously had zero test coverage)."""
    root = tmp_path / round_site.replace(".", "_")
    _stage_inputs(chaos_lib["inputs"], root)
    results = run_with_config(_cfg(root, chaos=[
        {"site": round_site, "kind": "error"},
    ]))
    assert results["barcode01"] == chaos_lib["baseline_counts"]
    assert faults.fired(round_site) == 1
    got = _artifact_bytes(root)
    assert got[COUNTS_CSV] == chaos_lib["baseline_artifacts"][COUNTS_CSV]
    if expect_fasta_identical:
        assert got[MERGED_FASTA] == chaos_lib["baseline_artifacts"][MERGED_FASTA]
    site = _report(root)["sites"][round_site]
    assert site["by_outcome"]["degraded"] == 1
    assert site["by_classification"]["fatal"] >= 1  # never burned retries
    # the degraded run is COMPLETE: manifest marked, resume skips it
    assert "counts" in _manifest_stages(root)


def test_chaos_mesh_device_lost_degrades_and_completes(chaos_lib, tmp_path):
    """A mesh slice dying mid-polish (DEVICE_LOST on the sharded chunk
    dispatch) escalates to the graph executor, which shrinks the data
    axis to the survivors (2 -> 1), rescales the HBM budget, re-runs the
    node on the degraded mesh, and completes byte-identically — with the
    degradation recorded as a mesh.degraded event and counted in
    telemetry."""
    root = tmp_path / "mesh_lost"
    _stage_inputs(chaos_lib["inputs"], root)
    results = run_with_config(_cfg(root, mesh_shape={"data": 2}, chaos=[
        {"site": "mesh.device_lost", "kind": "device-lost"},
    ]))
    assert results["barcode01"] == chaos_lib["baseline_counts"]
    assert faults.fired("mesh.device_lost") == 1
    _assert_byte_identical(chaos_lib, root)
    report = _report(root)
    # the polish loop escalated instead of retrying the broken mesh...
    escalated = report["sites"]["polish.dispatch"]["by_outcome"]
    assert escalated["escalated"] == 1
    assert "retried" not in escalated and "oom_shrink" not in escalated
    # ...and the executor's degraded-mesh loop re-ran the node
    degraded = report["sites"]["mesh.degraded"]["by_outcome"]
    assert degraded["degraded"] == 1
    ev = next(e for e in report["events"] if e["site"] == "mesh.degraded")
    assert ev["classification"] == "device_lost"
    assert ev["detail"]["node"] == "round1_polish"
    assert ev["detail"]["data_from"] == 2 and ev["detail"]["data_to"] == 1
    # telemetry: the re-execution is counted under the fault site, and the
    # lost slice's busy gauge reads 0 with the survivor at 1
    tele = json.loads(
        (root / "fastq_pass" / "nano_tcr" / "telemetry.json").read_text())
    assert tele["counters"]["mesh.degraded"] == 1
    assert tele["mesh_degraded_by_site"] == {"mesh.device_lost": 1}
    busy = tele["mesh_slice_busy"]
    assert sorted(busy.values()) == [0.0, 1.0]
    # no group was skipped: the degradation was a re-run, not a give-up
    assert not (root / "fastq_pass" / "nano_tcr" / "barcode01" / "logs"
                / "incomplete_region_clusters.log").exists()
    assert "counts" in _manifest_stages(root)


@pytest.mark.slow
def test_chaos_mesh_slice_oom_shrinks_under_mesh(chaos_lib, tmp_path):
    """HBM exhaustion on one slice of a sharded polish dispatch rides the
    existing oom-shrink path (the batch requeues smaller, quantized to
    the mesh), NOT the degraded-mesh escalation — the mesh keeps all its
    slices and the run completes byte-identically."""
    root = tmp_path / "mesh_oom"
    _stage_inputs(chaos_lib["inputs"], root)
    results = run_with_config(_cfg(root, mesh_shape={"data": 2}, chaos=[
        {"site": "mesh.slice_oom", "kind": "oom"},
    ]))
    assert results["barcode01"] == chaos_lib["baseline_counts"]
    assert faults.fired("mesh.slice_oom") == 1
    _assert_byte_identical(chaos_lib, root)
    report = _report(root)
    outcomes = report["sites"]["polish.dispatch"]["by_outcome"]
    assert outcomes["oom_shrink"] == 1
    assert "mesh.degraded" not in report["sites"]


# --- crash/resume scenarios -------------------------------------------------


def test_chaos_torn_manifest_resume_regenerates(chaos_lib, tmp_path):
    """A manifest torn mid-write (skip=1 tears the final 'counts' mark)
    must not brick resume: the corrupt manifest reads as 'no stages done',
    the library reruns, and the regenerated artifacts are byte-identical."""
    root = tmp_path / "torn"
    _stage_inputs(chaos_lib["inputs"], root)
    results = run_with_config(_cfg(root, chaos=[
        {"site": "layout.manifest_write", "kind": "torn", "skip": 1},
    ]))
    assert results["barcode01"] == chaos_lib["baseline_counts"]
    assert faults.fired("layout.manifest_write") == 1
    manifest_path = root / "fastq_pass" / MANIFEST
    with pytest.raises(ValueError):
        json.loads(manifest_path.read_text())  # really torn
    # resume on the torn manifest: warns, reruns, byte-identical
    resumed = run_with_config(_cfg(root, resume=True))
    assert resumed["barcode01"] == chaos_lib["baseline_counts"]
    _assert_byte_identical(chaos_lib, root)
    assert "counts" in _manifest_stages(root)  # rewritten healthy


def test_chaos_preemption_drains_and_resumes(chaos_lib, tmp_path):
    """A preemption request landing at the round-1 checkpoint stops the
    run with the round-1 stage committed; resume completes round 2 only,
    byte-identically."""
    root = tmp_path / "preempt"
    _stage_inputs(chaos_lib["inputs"], root)
    with pytest.raises(shutdown.Preempted):
        run_with_config(_cfg(root, chaos=[
            {"site": "run.round1_checkpoint", "kind": "preempt"},
        ]))
    stages_done = _manifest_stages(root)
    assert "round1_consensus" in stages_done  # committed checkpoint survives
    assert "counts" not in stages_done        # in-flight stage was NOT marked
    # the report is written even on the preemption path
    assert (root / "fastq_pass" / REPORT).exists()
    # round-1 QC committed BEFORE the checkpoint: artifact present
    logs = root / "fastq_pass" / "nano_tcr" / "barcode01" / "logs"
    assert (logs / "barcode01_align_error_profile.log").exists()
    resumed = run_with_config(_cfg(root, resume=True))
    assert resumed["barcode01"] == chaos_lib["baseline_counts"]
    _assert_byte_identical(chaos_lib, root)


@pytest.mark.slow
def test_chaos_process_kill_midstage_resume_byte_identical(chaos_lib, tmp_path):
    """SIGKILL-grade process death (os._exit, no flushes) right after the
    round-1 checkpoint: the manifest survives atomically, and a resume=true
    rerun completes round 2 with byte-identical artifacts. Runs the
    faulted half in a subprocess; slow-marked for the interpreter+compile
    startup (`pytest -m chaos` includes it)."""
    root = tmp_path / "kill"
    _stage_inputs(chaos_lib["inputs"], root)
    cfg = _cfg(root, chaos=[{"site": "run.round1_checkpoint", "kind": "kill"}])
    cfg_path = tmp_path / "kill_config.json"
    cfg_path.write_text(json.dumps(cfg.to_dict()))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop(faults.ENV_VAR, None)
    proc = subprocess.run(
        [sys.executable, "-c",
         "import sys; from ont_tcrconsensus_tpu.pipeline.cli import main; "
         "sys.exit(main(sys.argv[1:]))", str(cfg_path), "--cpu"],
        env=env, capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == faults.KILL_EXIT_CODE, proc.stderr[-2000:]
    assert "CHAOS: killing process" in proc.stderr
    stages_done = _manifest_stages(root)
    assert "round1_consensus" in stages_done and "counts" not in stages_done
    resumed = run_with_config(_cfg(root, resume=True))
    assert resumed["barcode01"] == chaos_lib["baseline_counts"]
    _assert_byte_identical(chaos_lib, root)


def test_chaos_corrupt_input_quarantines_and_stays_byte_identical(chaos_lib, tmp_path):
    """File-level data fault (ISSUE 3): malformed records spliced into the
    lane mid-file. With on_bad_record=quarantine the run completes, the
    damage lands in quarantine.fastq.gz + robustness_report.json, and the
    clean-read subset's counts CSV and consensus FASTA are byte-identical
    to an uncorrupted run — under contracts=strict."""
    root = tmp_path / "corrupt"
    _stage_inputs(chaos_lib["inputs"], root)
    results = run_with_config(_cfg(root, on_bad_record="quarantine",
                                   contracts="strict", chaos=[
        {"site": "ingest.library_fastq", "kind": "corrupt-input"},
    ]))
    assert results["barcode01"] == chaos_lib["baseline_counts"]
    assert faults.fired("ingest.library_fastq") == 1
    _assert_byte_identical(chaos_lib, root)
    # quarantine artifact holds the spliced damage
    lib_dir = root / "fastq_pass" / "nano_tcr" / "barcode01"
    q = lib_dir / "quarantine.fastq.gz"
    assert q.exists()
    import gzip as gzip_mod

    quarantined = gzip_mod.open(q, "rb").read()
    assert b"chaos" in quarantined
    # machine-readable reasons in the robustness report
    report = _report(root)
    site = report["sites"]["ingest.quarantine"]
    assert site["by_outcome"]["quarantined"] >= 3
    summary = next(e for e in report["events"]
                   if e["site"] == "ingest.quarantine"
                   and e["outcome"] == "summary")
    assert summary["detail"]["n_bad"] >= 3
    # strict contracts all held (summary recorded, zero violations)
    csum = report["contracts"]
    assert csum["mode"] == "strict"
    assert csum["violated"] == {}
    assert csum["checked"]["ingest"] >= 1
    # the original input was never touched (only a .chaos sibling was read)
    assert (root / "fastq_pass" / "barcode01" / "barcode01.fastq.gz").read_bytes() \
        == (chaos_lib["inputs"] / "fastq_pass" / "barcode01"
            / "barcode01.fastq.gz").read_bytes()


@pytest.mark.slow
def test_chaos_truncate_file_quarantines_gzip_tail(chaos_lib, tmp_path):
    """truncate-file cuts the .gz mid-stream: the run must complete on the
    decodable prefix with the gzip truncation recorded as a quarantine
    event — reads in the lost tail are gone, so artifacts may differ, but
    nothing crashes and the loss is auditable."""
    root = tmp_path / "trunc"
    _stage_inputs(chaos_lib["inputs"], root)
    results = run_with_config(_cfg(root, on_bad_record="quarantine", chaos=[
        {"site": "ingest.library_fastq", "kind": "truncate-file"},
    ]))
    assert faults.fired("ingest.library_fastq") == 1
    assert "barcode01" in results  # the library completed
    report = _report(root)
    reasons = [e["detail"].get("reason", "") for e in report["events"]
               if e["site"] == "ingest.quarantine" and "detail" in e]
    assert any("gzip" in r for r in reasons)
    counts = root / "fastq_pass" / "nano_tcr" / "barcode01" / "counts" / \
        "umi_consensus_counts.csv"
    assert counts.exists()


# --- liveness (watchdog) scenarios ------------------------------------------


def test_chaos_stall_polish_dispatch_detected_retried_byte_identical(
        chaos_lib, tmp_path):
    """ISSUE 5 acceptance: an injected stall at polish.dispatch (progress
    stops in an interruptible loop; nothing raises) is DETECTED within the
    configured hard deadline, the stage is cancelled into the transient
    retry path, and the run completes with counts CSV + consensus FASTA
    byte-identical to a clean run — plus the stall is auditable (report
    event + all-thread stack dump in the library log)."""
    root = tmp_path / "stall"
    _stage_inputs(chaos_lib["inputs"], root)
    # base sized per the config contract: above the slowest LEGITIMATE
    # single dispatch on this workload (the warm round-2 fused assign is
    # one ~2.5s device call with no heartbeat inside), below the test's
    # patience — deadlines are a property of the workload, not a constant
    results = run_with_config(_cfg(root, stage_timeout_s=6.0, chaos=[
        {"site": "polish.dispatch", "kind": "stall"},
    ]))
    assert results["barcode01"] == chaos_lib["baseline_counts"]
    assert faults.fired("polish.dispatch") == 1
    _assert_byte_identical(chaos_lib, root)
    report = _report(root)
    wd = report["sites"]["watchdog.stall"]["by_outcome"]
    assert wd["stall_detected"] >= 1 and wd["hard_cancel"] >= 1
    cancels = [e for e in report["events"]
               if e["site"] == "watchdog.stall" and e["outcome"] == "hard_cancel"]
    assert any(e["detail"]["stage"] == "round1_polish" for e in cancels)
    # detection latency: within the configured hard deadline plus monitor
    # tick slack — never "eventually"
    for e in cancels:
        assert e["detail"]["stalled_s"] <= e["detail"]["hard_deadline_s"] + 1.0
    # the cancel re-entered the existing transient retry path and recovered
    pol = report["sites"]["polish.dispatch"]["by_outcome"]
    assert pol["retried"] >= 1 and pol["recovered"] >= 1
    assert any(e["site"] == "polish.dispatch" and e["outcome"] == "retried"
               and "DEADLINE_EXCEEDED" in e.get("error", "")
               for e in report["events"])
    # post-hoc diagnosis artifact: soft-deadline stack dump + hard-cancel
    # notice in the per-library watchdog log
    wlog = root / "fastq_pass" / "nano_tcr" / "barcode01" / "logs" / \
        "watchdog.log"
    dump = wlog.read_text()
    assert "dumping all thread stacks" in dump
    assert "exceeded its hard deadline" in dump


@pytest.mark.slow
def test_chaos_hang_c_level_wedge_detected_and_recovered(chaos_lib, tmp_path):
    """The honest-limitation case: a hang inside ONE long C call (a wedged
    XLA dispatch). The watchdog detects and stack-dumps ON TIME (soft
    deadline), queues the cancel at the hard deadline, and the StageTimeout
    lands when the call returns — the stage then retries and completes
    byte-identically. Slow-marked: the wedge must outlive its deadline."""
    root = tmp_path / "hang"
    _stage_inputs(chaos_lib["inputs"], root)
    results = run_with_config(_cfg(root, stage_timeout_s=6.0, chaos=[
        {"site": "polish.dispatch", "kind": "hang"},
    ]))
    assert results["barcode01"] == chaos_lib["baseline_counts"]
    assert faults.fired("polish.dispatch") == 1
    _assert_byte_identical(chaos_lib, root)
    report = _report(root)
    wd = report["sites"]["watchdog.stall"]["by_outcome"]
    assert wd["stall_detected"] >= 1 and wd["hard_cancel"] >= 1
    pol = report["sites"]["polish.dispatch"]["by_outcome"]
    assert pol["retried"] >= 1 and pol["recovered"] >= 1


# --- resume-integrity (verified resume) scenarios ---------------------------


def test_chaos_corrupt_artifact_full_verify_recomputes_byte_identical(
        chaos_lib, tmp_path):
    """ISSUE 5 acceptance: disk corruption landing on a completed stage's
    artifact between the run and its resume (size-preserving byte flip) is
    caught by verify_resume=full, recorded as a resume.verify event, and
    the stage recomputes to byte-identical output instead of resuming from
    garbage."""
    root = tmp_path / "rot_full"
    shutil.copytree(chaos_lib["tmp"] / "baseline", root)
    resumed = run_with_config(_cfg(root, resume=True, verify_resume="full",
                                   chaos=[
        {"site": "resume.verify", "kind": "corrupt-artifact"},
    ]))
    assert faults.fired("resume.verify") == 1
    assert resumed["barcode01"] == chaos_lib["baseline_counts"]
    _assert_byte_identical(chaos_lib, root)  # recomputed over the rot
    report = _report(root)
    (ev,) = [e for e in report["events"] if e["site"] == "resume.verify"]
    assert ev["outcome"] == "rerun" and ev["classification"] == "integrity"
    assert "sha256" in ev["error"]
    assert ev["detail"] == {"library": "barcode01", "stage": "counts",
                            "mode": "full"}
    # the regenerated artifact was re-checksummed into a healthy manifest
    stages_done = _manifest_stages(root)
    assert stages_done["counts"]["artifacts"]


def test_chaos_corrupt_artifact_off_and_fast_blind_trust(chaos_lib, tmp_path):
    """The control arms: verify_resume=off reproduces the legacy blind
    trust (the corrupted artifact is skipped over and NEVER repaired), and
    fast's size check — by design — cannot see a size-preserving flip.
    Only full's sha256 (previous test) catches this fault."""
    for mode in ("off", "fast"):
        root = tmp_path / f"rot_{mode}"
        shutil.copytree(chaos_lib["tmp"] / "baseline", root)
        run_with_config(_cfg(root, resume=True, verify_resume=mode, chaos=[
            {"site": "resume.verify", "kind": "corrupt-artifact"},
        ]))
        assert faults.fired("resume.verify") == 1
        got = (root / "fastq_pass" / COUNTS_CSV).read_bytes()
        want = chaos_lib["baseline_artifacts"][COUNTS_CSV]
        assert len(got) == len(want)  # the rot was size-preserving...
        assert got != want, mode      # ...and flowed through unnoticed
        assert all(e["site"] != "resume.verify"
                   for e in _report(root)["events"]), mode


@pytest.mark.slow
def test_chaos_v1_manifest_resume_migration(chaos_lib, tmp_path):
    """Manifest v1 -> v2 migration e2e (ISSUE 5 satellite): a mixed-version
    manifest resumes on its verified v2 stage; a pure-v1 (pre-checksum)
    manifest is unverifiable under the default fast mode — warn, re-run,
    byte-identical, and the rewritten manifest is v2 with checksums; and
    verify_resume=off keeps trusting v1 marks (legacy behavior).

    Slow-marked (one full library re-run): the v1 read-path and
    verify_stage semantics this composes are tier-1 units in test_io."""
    root = tmp_path / "v1"
    shutil.copytree(chaos_lib["tmp"] / "baseline", root)
    mpath = root / "fastq_pass" / MANIFEST
    v2 = json.loads(mpath.read_text())
    v1_flat = {stage: info["t"] for stage, info in v2["stages"].items()}

    # mixed-version workdir: counts carries v2 checksums, round1 is a
    # v1-era null entry — resume verifies counts and skips instantly
    mixed = {"version": 2, "stages": dict(v2["stages"])}
    mixed["stages"]["round1_consensus"] = {
        "t": v1_flat["round1_consensus"], "artifacts": None,
    }
    mpath.write_text(json.dumps(mixed))
    resumed = run_with_config(_cfg(root, resume=True))  # fast (default)
    assert resumed["barcode01"] == chaos_lib["baseline_counts"]
    _assert_byte_identical(chaos_lib, root)
    assert all(e["site"] != "resume.verify" for e in _report(root)["events"])

    # pure v1: every stage unverifiable under fast -> warn + full re-run
    mpath.write_text(json.dumps(v1_flat))
    resumed = run_with_config(_cfg(root, resume=True))
    assert resumed["barcode01"] == chaos_lib["baseline_counts"]
    _assert_byte_identical(chaos_lib, root)
    evs = [e for e in _report(root)["events"] if e["site"] == "resume.verify"]
    assert evs and all("unverifiable" in e["error"] for e in evs)
    migrated = json.loads(mpath.read_text())
    assert migrated["version"] == 2
    assert migrated["stages"]["counts"]["artifacts"]  # checksummed now

    # v1 + verify_resume=off: the legacy blind trust still skips
    mpath.write_text(json.dumps(v1_flat))
    resumed = run_with_config(_cfg(root, resume=True, verify_resume="off"))
    assert resumed["barcode01"] == chaos_lib["baseline_counts"]
    assert json.loads(mpath.read_text()) == v1_flat  # a pure skip: untouched


def test_chaos_disarmed_run_writes_clean_report(chaos_lib):
    """The A/B guard: with nothing armed the baseline run's report exists
    and records zero events — the robustness layer is pure bookkeeping on
    the no-fault path."""
    report = _report(chaos_lib["tmp"] / "baseline")
    assert report["sites"] == {}
    assert report["events"] == []
    assert report["policy"]["max_attempts"] >= 1
    # conservation contracts ran (warn mode default) and all held — the
    # summary is a top-level field, never an event, on the clean path
    assert report["contracts"]["mode"] == "warn"
    assert report["contracts"]["violated"] == {}
    assert report["contracts"]["checked"]["counts"] >= 1
    # SIGTERM disposition was restored: the run's coordinator is gone
    handler = signal.getsignal(signal.SIGTERM)
    owner = getattr(handler, "__self__", None)
    assert not isinstance(owner, shutdown.ShutdownCoordinator)


def test_chaos_lockcheck_armed_run_byte_identical(chaos_lib, tmp_path,
                                                  monkeypatch):
    """TCR_LOCKCHECK=1 — the dynamic half of the graftrace proof
    (tools/graftrace): every LOCK_OWNERSHIP lock becomes an RLock with
    runtime owner-assertions at the *_locked contract boundaries. A full
    armed run must report ZERO violations and reproduce the
    uninterrupted baseline byte-for-byte (arming may not change
    behavior, only observe it)."""
    from ont_tcrconsensus_tpu.robustness import lockcheck

    root = tmp_path / "lockcheck"
    _stage_inputs(chaos_lib["inputs"], root)
    monkeypatch.setenv(lockcheck.ENV_VAR, "1")
    lockcheck.reset()
    try:
        results = run_with_config(_cfg(root))  # arms itself from the env
        assert lockcheck.armed()
        assert results["barcode01"] == chaos_lib["baseline_counts"]
        _assert_byte_identical(chaos_lib, root)
        assert lockcheck.violations() == []
        # negative control: the instrumentation bites when the *_locked
        # contract is actually breached (this is not a silent no-op pass)
        from ont_tcrconsensus_tpu.obs.live import FlightRecorder
        rec = FlightRecorder(max_events=4)
        rec._add_locked({"name": "breach"})
        assert any("FlightRecorder._add_locked" in v
                   for v in lockcheck.violations())
    finally:
        lockcheck.disarm()
        lockcheck.reset()
