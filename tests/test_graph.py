"""Stage dataflow graph (ISSUE 8): builder validation, executor semantics,
A/B byte-identity vs the imperative path, and chaos recovery under
``executor: graph``.

Layout:

- builder/spec unit tests — pure IR, no jax, milliseconds;
- synthetic executor tests — real StageExecutor worker pool + real
  watchdog/chaos/metrics layers over toy node fns, still no device work.
  These prove the overlap GENERALIZATION: a stage runs off the critical
  path because of its edge declaration alone, with zero executor or
  run.py special-casing;
- production-graph shape tests — ``build_library_graph`` under the config
  knobs, jax-free by construction (the ``--validate`` story);
- e2e on the simulator library — one graph-executor baseline shared by
  the imperative A/B, a stall chaos run, and a corrupt-artifact resume.

Synthetic graphs pass node names through VARIABLES, not literals: the
graftlint graph/obs rules police string literals against the production
registries (GRAPH_NODES / OBS_SITES), and fixture names are deliberately
outside that vocabulary.
"""

import json
import os
import shutil
import threading
import time
from types import SimpleNamespace

import pytest

from ont_tcrconsensus_tpu.graph import GRAPH_NODES
from ont_tcrconsensus_tpu.graph.executor import GraphExecutor
from ont_tcrconsensus_tpu.graph.ir import GraphBuilder, GraphValidationError
from ont_tcrconsensus_tpu.obs import metrics as obs_metrics
from ont_tcrconsensus_tpu.qc.timing import StageTimer
from ont_tcrconsensus_tpu.robustness import faults, retry

COUNTS_CSV = os.path.join("nano_tcr", "barcode01", "counts",
                          "umi_consensus_counts.csv")
MERGED_FASTA = os.path.join("nano_tcr", "barcode01", "fasta",
                            "merged_consensus.fasta")

# fixture node/edge names, held in variables so the literal-scoped lint
# rules (graph-unknown-node / obs-unknown-site) stay out of test graphs
N_LOAD, N_COMPUTE, N_QC, N_EXTRA, N_FINISH = (
    "t-load", "t-compute", "t-qc", "t-extra", "t-finish")
N_RESUME, N_TAIL = "t-resume", "t-tail"


@pytest.fixture(autouse=True)
def _disarm():
    yield
    faults.disarm()
    obs_metrics.disarm()


def _ctx(**over):
    d = dict(cfg=SimpleNamespace(resume=False), timer=StageTimer(), lay=None)
    d.update(over)
    return SimpleNamespace(**d)


def _problems(excinfo) -> str:
    return "\n".join(excinfo.value.problems)


# ---------------------------------------------------------------------------
# builder / spec units


def _diamond(extra_sink: bool = False) -> GraphBuilder:
    """src -> load -> compute -> finish, with qc (and optionally extra)
    hanging off compute's output as pure side sinks."""
    b = GraphBuilder("t")
    b.input("src", "disk")
    b.edge("x", "hbm")
    b.edge("y", "host")
    b.edge("q", "host")
    b.edge("out", "host")
    b.add_node(N_LOAD, lambda ctx, i: {"x": i["src"] * 2},
               inputs=("src",), outputs=("x",))
    b.add_node(N_COMPUTE, lambda ctx, i: {"y": i["x"] + 1},
               inputs=("x",), outputs=("y",))
    b.add_node(N_QC, lambda ctx, i: {"q": ("qc", i["y"])},
               inputs=("y",), outputs=("q",))
    if extra_sink:
        b.edge("q2", "host")
        b.add_node(N_EXTRA, lambda ctx, i: {"q2": ("extra", i["y"])},
                   inputs=("y",), outputs=("q2",))
    b.add_node(N_FINISH, lambda ctx, i: {"out": i["y"] * 10},
               inputs=("y",), outputs=("out",))
    b.result("out")
    return b


def test_builder_valid_graph_schedule_and_side_sinks():
    spec = _diamond().build()
    assert [n.name for n in spec.schedule] == [N_LOAD, N_COMPUTE, N_QC,
                                              N_FINISH]
    assert spec.side_sinks() == [N_QC]
    assert spec.edges["x"].placement == "hbm"
    d = spec.describe()
    assert d["side_sinks"] == [N_QC] and d["results"] == ["out"]
    assert d["edges"]["src"] == "disk"


def test_builder_collects_every_problem_at_once():
    b = GraphBuilder("bad")
    b.input("src", "disk")
    b.edge("w", "vram")                      # unknown placement
    b.edge("lonely", "host")                 # dangling
    b.add_node(N_LOAD, None, inputs=("src", "ghost"), outputs=("w", "w2"))
    b.result("nope")
    with pytest.raises(GraphValidationError) as exc:
        b.build()
    text = _problems(exc)
    assert "unknown placement 'vram'" in text
    assert "undeclared input edge 'ghost'" in text
    assert "undeclared output edge 'w2'" in text
    assert "'lonely' is dangling" in text
    assert "result edge 'nope' is not declared" in text
    assert len(exc.value.problems) >= 5
    assert str(exc.value).startswith("invalid stage graph:")


def test_builder_cycle_reported_with_member_names():
    b = GraphBuilder("cyc")
    b.edge("e1", "host")
    b.edge("e2", "host")
    b.add_node(N_LOAD, None, inputs=("e2",), outputs=("e1",))
    b.add_node(N_COMPUTE, None, inputs=("e1",), outputs=("e2",))
    b.result("e1")
    with pytest.raises(GraphValidationError) as exc:
        b.build()
    (line,) = [p for p in exc.value.problems if "cycle" in p]
    assert N_LOAD in line and N_COMPUTE in line


def test_builder_duplicate_declarations_and_producer():
    b = GraphBuilder("dup")
    b.input("src", "disk")
    b.edge("y", "host")
    b.edge("y", "host")
    b.add_node(N_LOAD, None, inputs=("src",), outputs=("y",))
    b.add_node(N_LOAD, None, inputs=("src",), outputs=("y",))
    b.add_node(N_COMPUTE, None, inputs=("src",), outputs=("y",))
    b.result("y")
    with pytest.raises(GraphValidationError) as exc:
        b.build()
    text = _problems(exc)
    assert "edge 'y' declared twice" in text
    assert f"node {N_LOAD!r} declared twice" in text
    assert "produced by both" in text


def test_builder_rejects_edge_name_colliding_with_node():
    b = GraphBuilder("clash")
    b.input("src", "disk")
    b.edge(N_LOAD, "host")  # same name as the node below
    b.add_node(N_LOAD, None, inputs=("src",), outputs=(N_LOAD,))
    b.result(N_LOAD)
    with pytest.raises(GraphValidationError) as exc:
        b.build()
    assert any("collides with a node of the same name" in p
               for p in exc.value.problems)


def test_builder_sharding_only_on_hbm_and_described():
    b = GraphBuilder("sh")
    b.input("src", "disk")
    b.edge("x", "hbm", sharding="data")
    b.edge("y", "host", sharding="data")   # host edges have no layout
    b.edge("z", "hbm", sharding="")        # empty spec is a typo
    b.add_node(N_LOAD, None, inputs=("src",), outputs=("x", "y", "z"))
    b.result("x", "y", "z")
    with pytest.raises(GraphValidationError) as exc:
        b.build()
    text = _problems(exc)
    assert "declared on a 'host' edge" in text
    assert "sharding spec must be a non-empty string" in text
    # the valid declaration survives and shows up in describe()
    ok = GraphBuilder("sh-ok")
    ok.input("src", "disk")
    ok.edge("x", "hbm", sharding="data")
    ok.edge("out", "host")
    ok.add_node(N_LOAD, None, inputs=("src", "x"), outputs=("out",))
    ok.add_node(N_COMPUTE, None, inputs=("src",), outputs=("x",))
    ok.result("out")
    spec = ok.build()
    assert spec.edges["x"].sharding == "data"
    assert spec.describe()["shardings"] == {"x": "data"}


def _resume_chain(h_placement: str, provides=("e2",), reload_fn="default"):
    """src -> load -> resume(disk artifact + crossing edge) -> tail."""
    b = GraphBuilder("res")
    b.input("src", "disk")
    b.edge("e1", "host")
    b.edge("d", "disk")
    b.edge("e2", h_placement)
    b.edge("out", "host")
    b.edge("sq", "host")
    b.add_node(N_LOAD, lambda ctx, i: {"e1": 1}, inputs=("src",),
               outputs=("e1",))
    b.add_node(N_QC, lambda ctx, i: {"sq": 2}, inputs=("e1",),
               outputs=("sq",))
    rl = (lambda ctx: {"e2": 42}) if reload_fn == "default" else reload_fn
    b.add_node(N_RESUME, lambda ctx, i: {"d": "p", "e2": 42},
               inputs=("e1",), outputs=("d", "e2"),
               resume_key="rk", resume_reload=rl, resume_provides=provides)
    b.add_node(N_TAIL, lambda ctx, i: {"out": i["e2"]}, inputs=("e2",),
               outputs=("out",))
    b.result("out")
    return b


def test_builder_hbm_resume_crossing_needs_reload_coverage():
    # relaxed: an hbm edge MAY cross the resume boundary when the reload
    # re-provides it (re-encoded + re-uploaded from the disk artifact) —
    # the production round1->round2 device hand-off depends on this
    spec = _resume_chain("hbm").build()
    assert spec.crossing_edges(N_RESUME) == ["e2"]
    # ...but an hbm crossing the reload does NOT provide stays fatal
    with pytest.raises(GraphValidationError) as exc:
        _resume_chain("hbm", provides=()).build()
    assert any("device memory cannot survive a restart" in p
               for p in exc.value.problems)


def test_builder_rejects_unprovided_crossing_and_missing_reload():
    with pytest.raises(GraphValidationError) as exc:
        _resume_chain("host", provides=()).build()
    assert any("reload does not provide it" in p for p in exc.value.problems)
    with pytest.raises(GraphValidationError) as exc:
        _resume_chain("host", reload_fn=None).build()
    assert any("no resume_reload" in p for p in exc.value.problems)


def test_builder_rejects_resume_node_without_disk_output():
    b = GraphBuilder("nodisk")
    b.input("src", "disk")
    b.edge("e1", "host")
    b.add_node(N_RESUME, None, inputs=("src",), outputs=("e1",),
               resume_key="rk")
    b.result("e1")
    with pytest.raises(GraphValidationError) as exc:
        b.build()
    assert any("no disk-placed edge" in p for p in exc.value.problems)


def test_spec_skip_closure_absorbs_only_side_sinks():
    spec = _resume_chain("host").build()
    # qc hangs off load (inside the closure) -> absorbed; tail consumes the
    # resume node's provided edge from OUTSIDE the closure -> never absorbed
    assert spec.skip_closure(N_RESUME) == {N_LOAD, N_QC, N_RESUME}
    assert spec.crossing_edges(N_RESUME) == ["e2"]
    assert spec.nodes[N_RESUME].checkpoint  # resume implies a barrier


# ---------------------------------------------------------------------------
# synthetic executor (real overlap pool / watchdog / chaos / metrics; no jax)


def test_executor_runs_serially_without_side_pool():
    spec = _diamond().build()
    out = GraphExecutor(spec, _ctx()).run({"src": 3})
    assert out == {"out": 70}


def test_executor_rejects_missing_graph_input():
    spec = _diamond().build()
    with pytest.raises(ValueError, match="missing inputs"):
        GraphExecutor(spec, _ctx()).run({})


def test_executor_output_contract_enforced():
    b = GraphBuilder("t")
    b.input("src", "disk")
    b.edge("y", "host")
    b.add_node(N_LOAD, lambda ctx, i: {"wrong": 1}, inputs=("src",),
               outputs=("y",))
    b.result("y")
    spec = b.build()
    with pytest.raises(RuntimeError, match="returned edges"):
        GraphExecutor(spec, _ctx()).run({"src": 0})


def test_executor_overlaps_side_sinks_by_declaration_alone():
    """The overlap generalization (ISSUE 8 acceptance): BOTH side sinks —
    including one added purely by declaring an unconsumed output edge —
    run on worker threads and commit on the main thread, with zero
    overlap-specific code anywhere near the node bodies."""
    from ont_tcrconsensus_tpu.pipeline.overlap import StageExecutor

    b = _diamond(extra_sink=True)
    spec = b.build()
    assert spec.side_sinks() == [N_QC, N_EXTRA]

    threads: dict[str, int] = {}
    orig_qc, orig_extra = spec.nodes[N_QC].fn, spec.nodes[N_EXTRA].fn

    def spy(name, fn):
        def wrapped(ctx, i):
            threads[name] = threading.get_ident()
            time.sleep(0.02)  # a visible worker wall clock
            return fn(ctx, i)
        return wrapped

    spec.nodes[N_QC].fn = spy(N_QC, orig_qc)
    spec.nodes[N_EXTRA].fn = spy(N_EXTRA, orig_extra)
    committed: list[int] = []
    spec.nodes[N_EXTRA].commit = (
        lambda ctx, outputs: committed.append(threading.get_ident()))

    reg = obs_metrics.arm()
    out = GraphExecutor(spec, _ctx(), side_exec=StageExecutor(2)).run(
        {"src": 3})
    assert out == {"out": 70}
    main = threading.get_ident()
    assert threads[N_QC] != main and threads[N_EXTRA] != main
    assert committed == [main]  # commit hooks stay on the main thread
    g = reg.summary()["graph"]
    for name in (N_QC, N_EXTRA):
        assert g["nodes"][name]["runs"] == 1
        assert g["nodes"][name]["overlapped_s"] > 0
    assert g["nodes"][N_COMPUTE]["overlapped_s"] == 0
    assert g["edges"]["x"] == "hbm" and g["edges"]["src"] == "disk"


def test_executor_recovers_dead_worker_on_main_thread():
    """An overlapped worker dying mid-stage (chaos at overlap.worker)
    surfaces at the commit barrier and is recomputed synchronously — the
    artifact survives, only the overlap is lost."""
    from ont_tcrconsensus_tpu.pipeline.overlap import StageExecutor

    spec = _diamond().build()
    faults.arm([{"site": "overlap.worker", "kind": "transient"}])
    out = GraphExecutor(spec, _ctx(), side_exec=StageExecutor(2)).run(
        {"src": 3})
    assert out == {"out": 70}
    assert faults.fired("overlap.worker") == 1


def test_executor_chaos_site_fires_on_critical_node_bodies():
    """Every critical node body shares the graph.node injection site — the
    per-node generalization of the imperative hand-placed sites."""
    spec = _diamond().build()
    faults.arm([{"site": "graph.node", "kind": "transient"}])
    with pytest.raises(faults.TransientChaosError):
        GraphExecutor(spec, _ctx()).run({"src": 3})
    assert faults.fired("graph.node") == 1


def test_executor_mesh_refuses_resharding_graph():
    """Under a mesh, a graph whose declared shardings disagree across a
    node would make XLA reshard at a stage boundary: the executor refuses
    it outright. Without a mesh the same graph runs — the gate (like the
    whole sharding plan) is mesh-armed only."""
    b = GraphBuilder("t")
    b.input("src", "disk")
    b.edge("ina", "hbm", sharding="data")
    b.edge("outa", "hbm", sharding="model")
    b.edge("res", "host")
    b.add_node(N_LOAD, lambda ctx, i: {"ina": i["src"]},
               inputs=("src",), outputs=("ina",))
    b.add_node(N_COMPUTE, lambda ctx, i: {"outa": i["ina"]},
               inputs=("ina",), outputs=("outa",))
    b.add_node(N_FINISH, lambda ctx, i: {"res": i["outa"]},
               inputs=("outa",), outputs=("res",))
    b.result("res")
    spec = b.build()
    ctx = _ctx(engine=SimpleNamespace(mesh=object()))
    with pytest.raises(RuntimeError, match="cannot run sharded"):
        GraphExecutor(spec, ctx).run({"src": 1})
    assert GraphExecutor(spec, _ctx()).run({"src": 5}) == {"res": 5}


def test_executor_degraded_mesh_rerun_records_and_completes():
    """A device_lost escaping a node body triggers the remesh hook, a
    mesh.degraded record + telemetry counters, a republished sharding
    plan, and a re-run of the WHOLE node — the run completes."""
    b = GraphBuilder("t")
    b.input("src", "disk")
    b.edge("x", "hbm", sharding="data")
    b.edge("out", "host")
    calls = []

    def body(ctx, i):
        calls.append(ctx.node_shardings)
        if len(calls) == 1:
            raise faults.DeviceLostChaosError("DEVICE_LOST: slice 1 halted")
        return {"x": i["src"] * 2}

    b.add_node(N_LOAD, body, inputs=("src",), outputs=("x",))
    b.add_node(N_COMPUTE, lambda ctx, i: {"out": i["x"] + 1},
               inputs=("x",), outputs=("out",))
    b.result("out")
    spec = b.build()
    remeshes = []

    def remesh(node, exc):
        remeshes.append(node)
        return {"data_from": 2, "data_to": 1}

    ctx = _ctx(engine=SimpleNamespace(mesh=object()), remesh=remesh)
    rec = retry.recorder()
    before = len(rec.events)
    reg = obs_metrics.arm()
    out = GraphExecutor(spec, ctx).run({"src": 3})
    assert out == {"out": 7}
    assert remeshes == [N_LOAD]
    # both attempts saw the node's published plan (re-set after the remesh)
    assert calls == [{"in": {}, "out": {"x": "data"}}] * 2
    (ev,) = [e for e in rec.events[before:] if e["site"] == "mesh.degraded"]
    assert ev["classification"] == "device_lost"
    assert ev["outcome"] == "degraded"
    assert ev["detail"] == {"node": N_LOAD, "data_from": 2, "data_to": 1}
    s = reg.summary()
    assert s["counters"]["mesh.degraded"] == 1
    assert s["mesh_degraded_by_site"] == {"mesh.device_lost": 1}


def test_executor_device_lost_without_remesh_propagates():
    """No remesh hook (unsharded run) or a hook that cannot shrink any
    further (returns None): the fault propagates and the run dies
    honestly instead of looping."""
    spec = _diamond().build()
    calls = []

    def dying(ctx, i):
        calls.append(1)
        raise faults.DeviceLostChaosError("DEVICE_LOST: no survivors")

    spec.nodes[N_LOAD].fn = dying
    with pytest.raises(faults.DeviceLostChaosError):
        GraphExecutor(spec, _ctx()).run({"src": 3})
    assert len(calls) == 1
    ctx = _ctx(remesh=lambda node, exc: None)
    with pytest.raises(faults.DeviceLostChaosError):
        GraphExecutor(spec, ctx).run({"src": 3})
    assert len(calls) == 2


def test_executor_resume_skips_closure_and_reloads_crossing_edges():
    """With the resume node's manifest stage done+verified, its whole skip
    closure is skipped (side sink included), crossing edges come from the
    reload, and downstream still runs."""
    ran: list[str] = []
    spec = _resume_chain("host").build()
    for name in (N_LOAD, N_QC, N_RESUME, N_TAIL):
        orig = spec.nodes[name].fn

        def wrapped(ctx, i, name=name, orig=orig):
            ran.append(name)
            return orig(ctx, i)

        spec.nodes[name].fn = wrapped

    class FakeLay:
        library = "t"

        def stage_done(self, key):
            return key == "rk"

        def verify_stage(self, key, mode):
            return True, None

    ctx = _ctx(cfg=SimpleNamespace(resume=True, verify_resume="fast"),
               lay=FakeLay())
    reg = obs_metrics.arm()
    out = GraphExecutor(spec, ctx).run({"src": 0})
    assert out == {"out": 42}  # 42 came from resume_reload, not the node fn
    assert ran == [N_TAIL]
    nodes = reg.summary()["graph"]["nodes"]
    for skipped in (N_LOAD, N_QC, N_RESUME):
        entry = nodes[skipped]
        assert entry["critical_s"] == 0.0 and entry["overlapped_s"] == 0.0
        assert entry["runs"] == 0 and entry["skips"] == 1
        # declared structure is recorded even for skipped nodes, so the
        # critical-path analyzer sees the full DAG on resume artifacts
        assert "inputs" in entry and "outputs" in entry
    assert nodes[N_TAIL]["runs"] == 1


# ---------------------------------------------------------------------------
# production graph shape (jax-free — the --validate story)


def _shape_cfg(**over):
    from ont_tcrconsensus_tpu.pipeline.config import RunConfig

    d = {"reference_file": "ref.fa", "fastq_pass_dir": "fastq_pass"}
    d.update(over)
    return RunConfig.from_dict(d)


def test_production_graph_matches_registry_and_derivations():
    from ont_tcrconsensus_tpu.graph import pipeline as graph_pipeline

    spec = graph_pipeline.build_library_graph(_shape_cfg())
    assert {n.name for n in spec.schedule} == set(GRAPH_NODES)
    assert spec.side_sinks() == [
        "round1_error_profile", "write_region_fastas", "round2_error_profile"
    ]
    closure = spec.skip_closure("round1_consensus")
    assert len(closure) == 8
    assert "round1_error_profile" in closure and \
        "write_region_fastas" in closure
    assert not any(n.startswith("round2") for n in closure)
    # the resume boundary now hands off the ENCODED consensus (hbm edge,
    # re-provided by the reload); merged_consensus is artifact-only
    assert spec.crossing_edges("round1_consensus") == ["cons_codes"]
    for hbm_edge in ("read_store", "cons_store", "cons_codes",
                     "r1_polished"):
        assert spec.edges[hbm_edge].placement == "hbm"
    for disk_edge in ("library_fastq", "merged_fasta", "counts_csv"):
        assert spec.edges[disk_edge].placement == "disk"
    assert spec.results == ("region_counts",)


def test_production_graph_under_every_knob_combination():
    from ont_tcrconsensus_tpu.graph import pipeline as graph_pipeline

    sizes = {}
    for sample in (512, 0):
        for fastas in (True, False):
            spec = graph_pipeline.build_library_graph(_shape_cfg(
                error_profile_sample=sample,
                write_intermediate_fastas=fastas,
            ))
            sizes[(bool(sample), fastas)] = len(spec.schedule)
    assert sizes == {(True, True): 13, (True, False): 12,
                     (False, True): 11, (False, False): 10}


def test_graph_package_importable_without_jax():
    """--validate must be able to build and reject graphs on a machine
    with no accelerator stack: the graph package (and a full production
    build) never imports jax at module scope."""
    import subprocess
    import sys

    code = (
        "import sys\n"
        "from ont_tcrconsensus_tpu.graph import pipeline as gp\n"
        "from ont_tcrconsensus_tpu.pipeline.config import RunConfig\n"
        "cfg = RunConfig.from_dict({'reference_file': 'r.fa',"
        " 'fastq_pass_dir': 'fq'})\n"
        "spec = gp.build_library_graph(cfg)\n"
        "assert len(spec.schedule) == 13\n"
        "assert 'jax' not in sys.modules, 'graph build dragged in jax'\n"
    )
    subprocess.run([sys.executable, "-c", code], check=True,
                   cwd=os.path.dirname(os.path.dirname(__file__)))


# ---------------------------------------------------------------------------
# --validate and --report wiring (still jax-free)


def _write_validate_inputs(root):
    from ont_tcrconsensus_tpu.io import fastx

    root.mkdir(parents=True, exist_ok=True)
    fastx.write_fasta(root / "reference.fa", [("regA", "ACGT" * 200)])
    fq = root / "fastq_pass" / "barcode01"
    fq.mkdir(parents=True)
    fastx.write_fastq(fq / "barcode01.fastq.gz",
                      [("read1", "ACGT" * 200, "I" * 800)])
    cfg_path = root / "config.json"
    cfg_path.write_text(json.dumps({
        "reference_file": str(root / "reference.fa"),
        "fastq_pass_dir": str(root / "fastq_pass"),
    }))
    return cfg_path


def test_validate_reports_graph_summary(tmp_path, capsys):
    from ont_tcrconsensus_tpu.io.validate import validate_inputs

    cfg_path = _write_validate_inputs(tmp_path)
    assert validate_inputs(str(cfg_path)) == 0
    out = capsys.readouterr().out
    assert "validate: stage graph: 13 nodes" in out
    assert "3 off-critical-path" in out


def test_validate_skips_graph_for_imperative_executor(tmp_path, capsys):
    from ont_tcrconsensus_tpu.io.validate import validate_inputs

    cfg_path = _write_validate_inputs(tmp_path)
    cfg = json.loads(cfg_path.read_text())
    cfg["executor"] = "imperative"
    cfg_path.write_text(json.dumps(cfg))
    assert validate_inputs(str(cfg_path)) == 0
    assert "stage graph" not in capsys.readouterr().out


def test_validate_rejects_invalid_graph_with_named_problems(
        tmp_path, capsys, monkeypatch):
    from ont_tcrconsensus_tpu.graph import pipeline as graph_pipeline
    from ont_tcrconsensus_tpu.io.validate import validate_inputs

    cfg_path = _write_validate_inputs(tmp_path)

    def broken(cfg):
        raise GraphValidationError([
            f"dependency cycle among nodes: {N_LOAD} -> {N_COMPUTE}",
            "edge 'lonely' is dangling (declared but never produced "
            "or consumed)",
        ])

    monkeypatch.setattr(graph_pipeline, "build_library_graph", broken)
    assert validate_inputs(str(cfg_path)) == 1
    out = capsys.readouterr().out
    assert "stage graph: dependency cycle among nodes" in out
    assert "stage graph: edge 'lonely' is dangling" in out
    assert "FAIL" in out


def test_report_renders_graph_section_without_jax():
    from ont_tcrconsensus_tpu.obs import report as obs_report

    lines: list[str] = []
    obs_report._render_telemetry({
        "telemetry": "full",
        "duration_s": 1.0,
        "graph": {
            "nodes": {
                "round1_polish": {"critical_s": 2.5, "overlapped_s": 0.0,
                                  "runs": 1, "skips": 0},
                "round1_error_profile": {"critical_s": 0.01,
                                         "overlapped_s": 1.25,
                                         "runs": 1, "skips": 0},
                "round1_fused_assign": {"critical_s": 0.0,
                                        "overlapped_s": 0.0,
                                        "runs": 0, "skips": 1},
            },
            "edges": {"read_store": "hbm", "counts_csv": "disk"},
        },
    }, lines)
    text = "\n".join(lines)
    assert "stage graph (per-node critical vs overlapped seconds):" in text
    assert "round1_error_profile" in text and "1.250s" in text
    assert "resume-skipped" in text
    assert "graph edges (placement): " in text
    assert "counts_csv[disk]" in text and "read_store[hbm]" in text


# ---------------------------------------------------------------------------
# e2e on the simulator (shared baseline; ~seconds per run on the warm cache)


@pytest.fixture(scope="module")
def graph_lib(tmp_path_factory):
    """Small simulated library + ONE graph-executor baseline run — the
    byte-identity reference for the A/B and chaos scenarios."""
    from ont_tcrconsensus_tpu.io import fastx, simulator

    tmp = tmp_path_factory.mktemp("graph_e2e")
    lib = simulator.simulate_library(
        seed=11,
        num_regions=2,
        molecules_per_region=(2, 2),
        reads_per_molecule=(5, 6),
        sub_rate=0.006,
        ins_rate=0.003,
        del_rate=0.003,
        region_len=(700, 850),
    )
    inputs = tmp / "inputs"
    (inputs / "fastq_pass" / "barcode01").mkdir(parents=True)
    fastx.write_fasta(inputs / "reference.fa", lib.reference.items())
    fastx.write_fastq(
        inputs / "fastq_pass" / "barcode01" / "barcode01.fastq.gz", lib.reads)
    baseline = tmp / "baseline"
    results, nano = _run_lib(inputs, baseline, executor="graph")
    assert results["barcode01"] == lib.true_counts
    return {
        "tmp": tmp,
        "inputs": inputs,
        "lib": lib,
        "baseline": baseline,
        "baseline_nano": nano,
        "baseline_counts": results["barcode01"],
        "baseline_artifacts": _artifact_bytes(baseline),
    }


def _run_lib(inputs, root, **overrides):
    from ont_tcrconsensus_tpu.pipeline.config import RunConfig
    from ont_tcrconsensus_tpu.pipeline.run import run_with_config

    if not (root / "reference.fa").exists():
        root.mkdir(parents=True, exist_ok=True)
        shutil.copy(inputs / "reference.fa", root / "reference.fa")
        shutil.copytree(inputs / "fastq_pass", root / "fastq_pass")
    d = {
        "reference_file": str(root / "reference.fa"),
        "fastq_pass_dir": str(root / "fastq_pass"),
        "minimal_length": 600,
        "min_reads_per_cluster": 4,
        "read_batch_size": 64,
        "polish_method": "poa",
        "delete_tmp_files": False,
        "hbm_budget_gb": 12.0,
        "retry_base_delay_s": 0.0,
        # "on" writes telemetry.json (all the graph assertions need) without
        # full-mode trace collection — keeps this module's wall time down
        "telemetry": "on",
    }
    d.update(overrides)
    return run_with_config(RunConfig.from_dict(d)), \
        root / "fastq_pass" / "nano_tcr"


def _artifact_bytes(root) -> dict[str, bytes]:
    out = {}
    for rel in (COUNTS_CSV, MERGED_FASTA):
        path = root / "fastq_pass" / rel
        assert path.exists(), f"missing artifact {rel}"
        out[rel] = path.read_bytes()
    return out


def _assert_byte_identical(graph_lib, root):
    got = _artifact_bytes(root)
    for rel, want in graph_lib["baseline_artifacts"].items():
        assert got[rel] == want, f"{rel} diverged from the graph baseline"


def _telemetry(nano) -> dict:
    return json.loads((nano / "telemetry.json").read_text())


def test_graph_run_attributes_telemetry_per_node(graph_lib):
    """ISSUE 8 acceptance: telemetry.json attributes spans/metrics per
    node, and the QC profiles + region fastas ran overlapped without any
    overlap-specific code in run.py (it is an edge-placement consequence)."""
    tele = _telemetry(graph_lib["baseline_nano"])
    g = tele["graph"]
    assert set(g["nodes"]) == set(GRAPH_NODES)
    for name, row in g["nodes"].items():
        assert row["runs"] == 1 and row["skips"] == 0, name
    # proof an overlapped node ran off the critical path is its `<name>_bg`
    # worker-thread span reaching the TSV (only the DeferredStage worker
    # emits one), not its overlapped_s magnitude: that is wall time rounded
    # to 1ms, and write_region_fastas can legitimately finish under that on
    # a fast box. Only the slower QC profiles must show nonzero worker
    # seconds. (critical_s for these nodes is the commit-barrier wait —
    # small but not necessarily zero.)
    tsv = (graph_lib["baseline_nano"] / "barcode01" / "logs" /
           "stage_timing.tsv").read_text()
    for overlapped in ("round1_error_profile", "write_region_fastas",
                      "round2_error_profile"):
        assert g["nodes"][overlapped]["overlapped_s"] >= 0, overlapped
        assert f"{overlapped}_bg\t" in tsv, overlapped
    for profiled in ("round1_error_profile", "round2_error_profile"):
        assert g["nodes"][profiled]["overlapped_s"] > 0, profiled
    assert g["nodes"]["round1_polish"]["overlapped_s"] == 0
    assert g["edges"]["read_store"] == "hbm"
    assert g["edges"]["counts_csv"] == "disk"
    # the per-node spans feed the same stage table + TSV as before
    assert "round1_polish\t" in tsv


def test_graph_vs_imperative_byte_identity(graph_lib, tmp_path):
    """The serial A/B: executor=imperative produces byte-identical counts
    CSV and consensus FASTA, and its telemetry keeps the pre-graph shape
    (no "graph" section)."""
    res, nano = _run_lib(graph_lib["inputs"], tmp_path / "imperative",
                         executor="imperative")
    assert res["barcode01"] == graph_lib["baseline_counts"]
    _assert_byte_identical(graph_lib, tmp_path / "imperative")
    assert "graph" not in _telemetry(nano)


@pytest.mark.chaos
def test_graph_chaos_stall_detected_and_recovered(graph_lib, tmp_path):
    """A stall injected under the polish dispatch is cancelled by the
    node-scoped watchdog guard (deadline scaled by the node's declared
    units), retried, and the run stays byte-identical — under
    executor: graph."""
    root = tmp_path / "stall"
    results, nano = _run_lib(graph_lib["inputs"], root, executor="graph",
                             stage_timeout_s=6.0, chaos=[
        {"site": "polish.dispatch", "kind": "stall"},
    ])
    assert results["barcode01"] == graph_lib["baseline_counts"]
    assert faults.fired("polish.dispatch") == 1
    _assert_byte_identical(graph_lib, root)
    report = json.load(open(nano / "robustness_report.json"))
    cancels = [e for e in report["events"]
               if e["site"] == "watchdog.stall"
               and e["outcome"] == "hard_cancel"]
    assert any(e["detail"]["stage"] == "round1_polish" for e in cancels)
    pol = report["sites"]["polish.dispatch"]["by_outcome"]
    assert pol["retried"] >= 1 and pol["recovered"] >= 1
    # the stall's wall time is attributed to the node that owned it
    g = _telemetry(nano)["graph"]["nodes"]
    assert g["round1_polish"]["critical_s"] >= 6.0


@pytest.mark.chaos
def test_graph_chaos_corrupt_counts_resumes_from_round1_node(
        graph_lib, tmp_path):
    """Corruption on the completed counts artifact fails full verification,
    and the graph resume scan falls back to the round1_consensus resume
    node: the whole round-1 closure (side sinks included) is skipped, the
    crossing edge reloads from disk, round 2 recomputes byte-identical."""
    root = tmp_path / "rot"
    shutil.copytree(graph_lib["baseline"], root)
    results, nano = _run_lib(graph_lib["inputs"], root, executor="graph",
                             resume=True, verify_resume="full", chaos=[
        {"site": "resume.verify", "kind": "corrupt-artifact"},
    ])
    assert faults.fired("resume.verify") == 1
    assert results["barcode01"] == graph_lib["baseline_counts"]
    _assert_byte_identical(graph_lib, root)
    report = json.load(open(nano / "robustness_report.json"))
    (ev,) = [e for e in report["events"] if e["site"] == "resume.verify"]
    assert ev["outcome"] == "rerun" and ev["detail"]["stage"] == "counts"
    g = _telemetry(nano)["graph"]["nodes"]
    for skipped in ("round1_fused_assign", "round1_polish",
                    "round1_error_profile", "write_region_fastas",
                    "round1_consensus"):
        entry = g[skipped]
        assert entry["critical_s"] == 0.0 and entry["overlapped_s"] == 0.0
        assert entry["runs"] == 0 and entry["skips"] == 1, skipped
        # declared edges survive the skip (the critical-path analyzer
        # rebuilds the DAG from resume artifacts too); units stay 0 —
        # nothing was evaluated
        assert "inputs" in entry and entry["units"] == 0, skipped
    for ran in ("round2_fused_assign", "round2_counts"):
        assert g[ran]["runs"] == 1 and g[ran]["skips"] == 0, ran
