"""Native C++ fastx parser vs the pure-Python reference parser."""

import numpy as np
import pytest

from ont_tcrconsensus_tpu.io import fastx
from ont_tcrconsensus_tpu.io import native
from ont_tcrconsensus_tpu.ops import encode

pytestmark = pytest.mark.skipif(
    native.load() is None, reason="no C++ toolchain for the native parser"
)


def _compare(path):
    parsed = native.parse_file(path)
    assert parsed is not None
    py_records = list(fastx.read_fastx(path))
    assert parsed.num_records == len(py_records)
    for i, rec in enumerate(py_records):
        name, codes, quals = parsed.record(i)
        assert name == rec.header
        np.testing.assert_array_equal(codes, encode.encode_seq(rec.sequence))
        if rec.quality is not None:
            want = np.frombuffer(rec.quality.encode(), np.uint8) - 33
            np.testing.assert_array_equal(quals, want)
        else:
            assert quals is None


def test_fastq_gz_matches_python(tmp_path):
    path = tmp_path / "x.fastq.gz"
    fastx.write_fastq(path, [
        ("r1 extra=1", "ACGTN", "IIIII"),
        ("r2", "GGTTAACC", "!!!!!!!!"),
    ])
    _compare(str(path))


def test_fasta_multiline_matches_python(tmp_path):
    path = tmp_path / "x.fasta"
    fastx.write_fasta(path, [("a desc", "ACGT" * 40), ("b", "TTTTA")], width=13)
    _compare(str(path))


def test_blank_lines_tolerated(tmp_path):
    path = tmp_path / "x.fastq"
    path.write_text("@r1\nACGT\n+\nIIII\n\n\n@r2\nGG\n+\nII\n")
    parsed = native.parse_file(str(path))
    assert parsed.num_records == 2
    assert parsed.names == ["r1", "r2"]


def test_malformed_raises(tmp_path):
    path = tmp_path / "bad.fastq"
    path.write_text("@r1\nACGT\n+\nII\n")  # qual length mismatch
    with pytest.raises(ValueError, match="qual length"):
        native.parse_file(str(path))


def _clean_parity_cases():
    """Clean-input edge cases previously only exercised implicitly through
    e2e: header edge cases, FASTA multiline, gz vs plain (ISSUE 3)."""
    return [
        ("header_comment", "x.fastq",
         b"@r1 runid=abc ch=1\nACGT\n+\nIIII\n@r2 c=2\nGG\n+comment\nII\n"),
        ("empty_seq_record", "x.fastq", b"@r1\n\n+\n\n@r2\nAC\n+\nII\n"),
        ("lowercase_and_n", "x.fastq", b"@r1\nacgtnN\n+\nIIIIII\n"),
        ("fasta_multiline", "x.fasta",
         b">a first desc\nACGT\nTTTT\nGG\n>b\nCCCC\n\n>c trailing\nAA"),
        ("crlf_fastq", "x.fastq", b"@r1\r\nACGT\r\n+\r\nIIII\r\n"),
        ("blank_separated", "x.fastq", b"@r1\nACGT\n+\nIIII\n\n\n@r2\nGG\n+\nII\n"),
    ]


@pytest.mark.parametrize("label,name,data",
                         _clean_parity_cases(),
                         ids=[c[0] for c in _clean_parity_cases()])
@pytest.mark.parametrize("gz", [False, True], ids=["plain", "gz"])
def test_native_matches_python_on_clean_edge_cases(tmp_path, label, name, data, gz):
    """Native vs pure-Python parity on CLEAN inputs, .gz and plain; the
    tolerant parse must agree with the strict one (records identical, zero
    bad regions) so the quarantine path costs nothing on healthy data."""
    import gzip

    from ont_tcrconsensus_tpu.io import validate as validate_mod

    path = tmp_path / (name + (".gz" if gz else ""))
    path.write_bytes(gzip.compress(data) if gz else data)
    _compare(str(path))
    strict = native.parse_file(str(path))
    tol = native.parse_file(str(path), tolerant=True)
    assert tol.bad == []
    assert tol.num_records == strict.num_records
    np.testing.assert_array_equal(tol.codes, strict.codes)
    assert tol.names == strict.names
    py_recs, py_bads = validate_mod.parse_path_tolerant(str(path))
    assert not py_bads
    assert [r.header.decode() for r in py_recs] == tol.names


def test_truncated_gzip_rejected_strict_kept_tolerant(tmp_path):
    """gzread reports truncation only via gzerror (not its return value):
    the strict parser must reject a truncated .gz instead of silently
    accepting the prefix — the fuzzer caught the original silent accept."""
    import gzip

    payload = gzip.compress(b"".join(
        b"@r%d\nACGTACGT\n+\nIIIIIIII\n" % i for i in range(100)))
    path = tmp_path / "t.fastq.gz"
    path.write_bytes(payload[: len(payload) // 2])
    with pytest.raises(ValueError, match="gzip"):
        native.parse_file(str(path))
    tol = native.parse_file(str(path), tolerant=True)
    assert tol.num_records > 0
    assert any("gzip" in reason for _, reason, _ in tol.bad)


def test_large_roundtrip_speed(tmp_path):
    import time

    from ont_tcrconsensus_tpu.io import simulator

    lib = simulator.simulate_library(seed=3, num_regions=4)
    path = tmp_path / "big.fastq.gz"
    fastx.write_fastq(path, lib.reads)
    t0 = time.time()
    parsed = native.parse_file(str(path))
    native_dt = time.time() - t0
    assert parsed.num_records == len(lib.reads)
    t0 = time.time()
    n_py = sum(1 for _ in fastx.read_fastx(path))
    py_dt = time.time() - t0
    assert n_py == parsed.num_records
    # informational; tiny inputs may not show a gap
    print(f"native {native_dt * 1e3:.1f}ms vs python {py_dt * 1e3:.1f}ms")


def test_batch_parsed_matches_batch_reads(tmp_path):
    """The native columnar ingest path must produce byte-identical batches
    to the pure-Python record path (same bucketing, order, padding)."""
    import numpy as np

    from ont_tcrconsensus_tpu.io import bucketing, fastx, native, simulator

    lib = simulator.simulate_library(
        seed=3, num_regions=2, molecules_per_region=(2, 3),
        reads_per_molecule=(3, 5), region_len=(300, 900),
    )
    path = tmp_path / "reads.fastq.gz"
    fastx.write_fastq(path, lib.reads)
    parsed = native.parse_file(path)
    if parsed is None:
        import pytest

        pytest.skip("native parser unavailable")
    widths = (512, 1024, 2048)
    a = list(bucketing.batch_parsed_reads(parsed, batch_size=8, widths=widths))
    b = list(bucketing.batch_reads(fastx.read_fastx(path), batch_size=8, widths=widths))
    assert len(a) == len(b)
    for x, y in zip(a, b):
        assert x.width == y.width
        assert x.ids == y.ids
        np.testing.assert_array_equal(x.codes, y.codes)
        np.testing.assert_array_equal(x.quals, y.quals)
        np.testing.assert_array_equal(x.lengths, y.lengths)
        np.testing.assert_array_equal(x.valid, y.valid)


def _write_big_fastq(path, n=3000, seed=5):
    rng = np.random.default_rng(seed)
    reads = []
    for i in range(n):
        ln = int(rng.integers(40, 400))
        seq = "".join(rng.choice(list("ACGT"), size=ln))
        qual = "".join(chr(33 + int(q)) for q in rng.integers(2, 40, size=ln))
        reads.append((f"r{i} mol={i}", seq, qual))
    fastx.write_fastq(path, reads)
    return reads


def test_parse_chunks_concat_equals_parse_file(tmp_path):
    """Streamed chunks, concatenated, must be byte-identical to the
    whole-file parse — small chunk_bases forces many chunk boundaries,
    exercising the carry/split logic on both record kinds."""
    path = str(tmp_path / "big.fastq.gz")
    _write_big_fastq(path)
    whole = native.parse_file(path)
    chunks = list(native.parse_chunks(path, chunk_bases=16_384))
    assert len(chunks) > 5, "chunking did not actually chunk"
    assert sum(c.num_records for c in chunks) == whole.num_records
    np.testing.assert_array_equal(
        np.concatenate([c.codes for c in chunks]), whole.codes
    )
    np.testing.assert_array_equal(
        np.concatenate([c.quals for c in chunks]), whole.quals
    )
    np.testing.assert_array_equal(
        np.concatenate([c.lengths for c in chunks]), whole.lengths
    )
    assert [n for c in chunks for n in c.names] == whole.names

    # FASTA too (multi-line records split across chunk boundaries; the
    # stream reads 64 KB blocks, so the fixture must span several blocks)
    fpath = str(tmp_path / "big.fasta")
    fastx.write_fasta(
        fpath, [(f"s{i}", "ACGTTGCA" * (10 + i % 37)) for i in range(1500)],
        width=60,
    )
    whole = native.parse_file(fpath)
    chunks = list(native.parse_chunks(fpath, chunk_bases=4096))
    assert len(chunks) > 3
    assert sum(c.num_records for c in chunks) == whole.num_records
    np.testing.assert_array_equal(
        np.concatenate([c.codes for c in chunks]), whole.codes
    )
    assert [n for c in chunks for n in c.names] == whole.names


def test_batch_parsed_chunks_matches_whole_file(tmp_path):
    """Cross-chunk batching must produce the SAME batches (shapes, order,
    content) as batching the whole-file parse."""
    from ont_tcrconsensus_tpu.io import bucketing

    path = str(tmp_path / "big2.fastq.gz")
    _write_big_fastq(path, n=2000, seed=9)
    whole = native.parse_file(path)
    want = list(bucketing.batch_parsed_reads(
        whole, batch_size=256, widths=(128, 512), min_len=50
    ))
    got = list(bucketing.batch_parsed_chunks(
        native.parse_chunks(path, chunk_bases=16_384),
        batch_size=256, widths=(128, 512), min_len=50,
    ))
    assert len(got) == len(want)
    for g, w in zip(got, want):
        assert g.width == w.width and g.ids == w.ids
        np.testing.assert_array_equal(g.codes, w.codes)
        np.testing.assert_array_equal(g.quals, w.quals)
        np.testing.assert_array_equal(g.lengths, w.lengths)
        np.testing.assert_array_equal(g.valid, w.valid)


def test_batch_parsed_chunks_subsample(tmp_path):
    from ont_tcrconsensus_tpu.io import bucketing

    path = str(tmp_path / "big3.fastq.gz")
    _write_big_fastq(path, n=500, seed=13)
    got = list(bucketing.batch_parsed_chunks(
        native.parse_chunks(path, chunk_bases=8192),
        batch_size=64, widths=(512,), min_len=1, subsample=100,
    ))
    assert sum(int(b.valid.sum()) for b in got) == 100


def test_parse_chunks_blank_lines_and_crlf(tmp_path):
    """Blank separator lines and CRLF endings across chunk boundaries."""
    path = str(tmp_path / "w.fastq")
    recs = []
    for i in range(200):
        recs.append(f"@r{i}\r\nACGTACGT\r\n+\r\nIIIIIIII\r\n\r\n")
    (tmp_path / "w.fastq").write_text("".join(recs))
    whole = native.parse_file(path)
    assert whole.num_records == 200
    chunks = list(native.parse_chunks(path, chunk_bases=512))
    assert sum(c.num_records for c in chunks) == 200
    np.testing.assert_array_equal(
        np.concatenate([c.codes for c in chunks]), whole.codes
    )


# --- build hygiene (ISSUE 4: sanitized native builds) ----------------------


def test_native_build_is_warning_clean(tmp_path):
    """-Wall -Wextra are always on and the shipped parser compiles with
    ZERO warnings (the native complement of graftlint's zero-finding
    gate on the Python tree)."""
    ok, out = native.build_library(str(tmp_path / "libfastx_check.so"))
    assert ok, out
    assert "warning" not in out.lower(), out


def test_setup_py_build_command_matches_loader():
    """setup.py cannot import the package it builds, so it mirrors
    build_command; this pins the two flag sets byte-identical (plain and
    sanitized) so they cannot drift apart."""
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # executing setup.py would invoke setup(); pull just the helper out by
    # exec'ing the source above the setup() call into a bare namespace
    source = open(os.path.join(repo, "setup.py")).read()
    ns = {}
    exec(compile(source.split("setup(cmdclass")[0], "setup.py", "exec"), ns)
    for sanitize in (None, "address,undefined"):
        assert (ns["native_build_command"]("SRC", "OUT", sanitize)
                == native.build_command("SRC", "OUT", sanitize))


def test_lib_override_env_is_authoritative(tmp_path, monkeypatch):
    """GRAFT_FASTX_LIB must load exactly that artifact or fail loudly —
    EVEN when an earlier in-process load() already cached the default
    build (a silent fallback to the cached unsanitized lib would turn the
    sanitized fuzz gate into a no-op)."""
    assert native.load() is not None  # default build cached in-process
    monkeypatch.setenv(native.LIB_OVERRIDE_ENV, str(tmp_path / "missing.so"))
    with pytest.raises(OSError):
        native.load()
    monkeypatch.delenv(native.LIB_OVERRIDE_ENV)
    assert native.load() is not None  # cached default still served after
