"""Native C++ fastx parser vs the pure-Python reference parser."""

import numpy as np
import pytest

from ont_tcrconsensus_tpu.io import fastx
from ont_tcrconsensus_tpu.io import native
from ont_tcrconsensus_tpu.ops import encode

pytestmark = pytest.mark.skipif(
    native.load() is None, reason="no C++ toolchain for the native parser"
)


def _compare(path):
    parsed = native.parse_file(path)
    assert parsed is not None
    py_records = list(fastx.read_fastx(path))
    assert parsed.num_records == len(py_records)
    for i, rec in enumerate(py_records):
        name, codes, quals = parsed.record(i)
        assert name == rec.header
        np.testing.assert_array_equal(codes, encode.encode_seq(rec.sequence))
        if rec.quality is not None:
            want = np.frombuffer(rec.quality.encode(), np.uint8) - 33
            np.testing.assert_array_equal(quals, want)
        else:
            assert quals is None


def test_fastq_gz_matches_python(tmp_path):
    path = tmp_path / "x.fastq.gz"
    fastx.write_fastq(path, [
        ("r1 extra=1", "ACGTN", "IIIII"),
        ("r2", "GGTTAACC", "!!!!!!!!"),
    ])
    _compare(str(path))


def test_fasta_multiline_matches_python(tmp_path):
    path = tmp_path / "x.fasta"
    fastx.write_fasta(path, [("a desc", "ACGT" * 40), ("b", "TTTTA")], width=13)
    _compare(str(path))


def test_blank_lines_tolerated(tmp_path):
    path = tmp_path / "x.fastq"
    path.write_text("@r1\nACGT\n+\nIIII\n\n\n@r2\nGG\n+\nII\n")
    parsed = native.parse_file(str(path))
    assert parsed.num_records == 2
    assert parsed.names == ["r1", "r2"]


def test_malformed_raises(tmp_path):
    path = tmp_path / "bad.fastq"
    path.write_text("@r1\nACGT\n+\nII\n")  # qual length mismatch
    with pytest.raises(ValueError, match="qual length"):
        native.parse_file(str(path))


def test_large_roundtrip_speed(tmp_path):
    import time

    from ont_tcrconsensus_tpu.io import simulator

    lib = simulator.simulate_library(seed=3, num_regions=4)
    path = tmp_path / "big.fastq.gz"
    fastx.write_fastq(path, lib.reads)
    t0 = time.time()
    parsed = native.parse_file(str(path))
    native_dt = time.time() - t0
    assert parsed.num_records == len(lib.reads)
    t0 = time.time()
    n_py = sum(1 for _ in fastx.read_fastx(path))
    py_dt = time.time() - t0
    assert n_py == parsed.num_records
    # informational; tiny inputs may not show a gap
    print(f"native {native_dt * 1e3:.1f}ms vs python {py_dt * 1e3:.1f}ms")


def test_batch_parsed_matches_batch_reads(tmp_path):
    """The native columnar ingest path must produce byte-identical batches
    to the pure-Python record path (same bucketing, order, padding)."""
    import numpy as np

    from ont_tcrconsensus_tpu.io import bucketing, fastx, native, simulator

    lib = simulator.simulate_library(
        seed=3, num_regions=2, molecules_per_region=(2, 3),
        reads_per_molecule=(3, 5), region_len=(300, 900),
    )
    path = tmp_path / "reads.fastq.gz"
    fastx.write_fastq(path, lib.reads)
    parsed = native.parse_file(path)
    if parsed is None:
        import pytest

        pytest.skip("native parser unavailable")
    widths = (512, 1024, 2048)
    a = list(bucketing.batch_parsed_reads(parsed, batch_size=8, widths=widths))
    b = list(bucketing.batch_reads(fastx.read_fastx(path), batch_size=8, widths=widths))
    assert len(a) == len(b)
    for x, y in zip(a, b):
        assert x.width == y.width
        assert x.ids == y.ids
        np.testing.assert_array_equal(x.codes, y.codes)
        np.testing.assert_array_equal(x.quals, y.quals)
        np.testing.assert_array_equal(x.lengths, y.lengths)
        np.testing.assert_array_equal(x.valid, y.valid)
