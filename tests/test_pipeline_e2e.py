"""End-to-end pipeline: simulated library -> bit-exact UMI counts.

The north-star acceptance (SURVEY §6): UMI counts concordant with ground
truth on a library with known molecules. Every molecule gets >=
min_reads_per_cluster reads at moderate error rates, so the expected count
per region is exactly its molecule count.
"""

import json

import pytest

from ont_tcrconsensus_tpu.io import fastx, simulator
from ont_tcrconsensus_tpu.pipeline.config import RunConfig
from ont_tcrconsensus_tpu.pipeline.run import run_with_config


@pytest.fixture(scope="module")
def sim_library(tmp_path_factory):
    # region_len (700, 850) keeps every read in the 1024-width bucket (vs
    # 2048 at the 1500-2200 default): the CPU SW scan and the polish pileup
    # both halve, cutting each e2e run ~2x (VERDICT r2 weak #5 — suite
    # runtime). Full-scale read shapes stay covered by bench.py and -m tpu.
    tmp = tmp_path_factory.mktemp("e2e")
    lib = simulator.simulate_library(
        seed=11,
        num_regions=4,
        molecules_per_region=(2, 3),
        reads_per_molecule=(5, 8),
        sub_rate=0.006,
        ins_rate=0.003,
        del_rate=0.003,
        region_len=(700, 850),
    )
    ref_path = tmp / "reference.fa"
    fastx.write_fasta(ref_path, lib.reference.items())
    fq_dir = tmp / "fastq_pass" / "barcode01"
    fq_dir.mkdir(parents=True)
    fastx.write_fastq(fq_dir / "barcode01.fastq.gz", lib.reads)
    return tmp, lib


def _base_config(tmp):
    return RunConfig.from_dict({
        "reference_file": str(tmp / "reference.fa"),
        "fastq_pass_dir": str(tmp / "fastq_pass"),
        "minimal_length": 600,
        "min_reads_per_cluster": 4,
        "read_batch_size": 64,
        "polish_method": "poa",
        "delete_tmp_files": False,
        # strict conservation contracts on the clean e2e path: any
        # accounting drift across ingest/assign/umi/consensus/counts
        # fails these tests instead of warning (ISSUE 3 acceptance)
        "contracts": "strict",
    })


def test_pipeline_counts_match_ground_truth(sim_library):
    tmp, lib = sim_library
    cfg = _base_config(tmp)
    results = run_with_config(cfg)
    assert "barcode01" in results
    got = results["barcode01"]
    want = lib.true_counts
    assert got == want, f"counts mismatch: got {got} want {want}"

    # artifact layout parity
    lib_dir = tmp / "fastq_pass" / "nano_tcr" / "barcode01"
    assert (lib_dir / "counts" / "umi_consensus_counts.csv").exists()
    assert (tmp / "fastq_pass" / "nano_tcr" / "region_cluster_dict.json").exists()
    csv = (lib_dir / "counts" / "umi_consensus_counts.csv").read_text().splitlines()
    assert csv[0] == "TCR,Count"
    csv_counts = dict(line.rsplit(",", 1) for line in csv[1:])
    assert {k: int(v) for k, v in csv_counts.items()} == want


def test_pipeline_consensus_sequences_exact(sim_library):
    """Round-1 consensus must reproduce each molecule's true template."""
    tmp, lib = sim_library
    lib_dir = tmp / "fastq_pass" / "nano_tcr" / "barcode01"
    merged = lib_dir / "fasta" / "merged_consensus.fasta"
    assert merged.exists()
    consensus = {rec.name: rec.sequence for rec in fastx.read_fastx(merged)}
    templates = {
        simulator.LEFT_FLANK + m.umi_fwd + lib.reference[m.region] + m.umi_rev
        + simulator.RIGHT_FLANK
        for m in lib.molecules
    }
    exact = sum(1 for seq in consensus.values() if seq in templates)
    assert len(consensus) == len(lib.molecules)
    assert exact == len(consensus), (
        f"only {exact}/{len(consensus)} consensus sequences are bit-exact"
    )


@pytest.mark.parametrize("polish_method", ["poa", "rnn"])
def test_pipeline_mesh_rnn_counts_exact(sim_library, tmp_path, polish_method):
    """8-device data-sharded runs with BOTH polish methods: the mesh path
    (SURVEY §2.3, virtual CPU mesh) must produce counts identical to ground
    truth, with the confidence-gated RNN never corrupting a correct
    consensus AND the poa variant covering keep_final_pileup=False under a
    mesh (ADVICE r3: the folded single-method test silently dropped
    whichever path the bundled-weights check deselected)."""
    from ont_tcrconsensus_tpu.models import polisher as polisher_mod

    if polish_method == "rnn" and polisher_mod.load_default_params() is None:
        pytest.skip("no bundled polisher weights")
    tmp, lib = sim_library
    import shutil

    root = tmp_path / "mesh_rnn"
    shutil.copytree(tmp / "fastq_pass" / "barcode01", root / "fastq_pass" / "barcode01")
    shutil.copy(tmp / "reference.fa", root / "reference.fa")
    cfg = RunConfig.from_dict({
        "reference_file": str(root / "reference.fa"),
        "fastq_pass_dir": str(root / "fastq_pass"),
        "minimal_length": 600,
        "min_reads_per_cluster": 4,
        "read_batch_size": 64,
        "polish_method": polish_method,
        "delete_tmp_files": False,
        "mesh_shape": {"data": 8},
    })
    results = run_with_config(cfg)
    assert results["barcode01"] == lib.true_counts


@pytest.mark.slow  # ~35s: a full e2e run whose only NEW assertion is the
# profiler artifact glob — result correctness is already pinned by the
# non-slow e2e tests in this file; reruns in the slow suite.
def test_pipeline_profiler_trace_written(sim_library, tmp_path):
    """profile_trace_dir wraps the run in a jax.profiler trace (device-level
    observability; SURVEY §5 tracing row) without touching the results."""
    import glob
    import shutil

    tmp, lib = sim_library
    root = tmp_path / "prof"
    shutil.copytree(tmp / "fastq_pass" / "barcode01", root / "fastq_pass" / "barcode01")
    shutil.copy(tmp / "reference.fa", root / "reference.fa")
    cfg = _base_config(root)
    cfg.profile_trace_dir = str(tmp_path / "trace")
    results = run_with_config(cfg)
    assert results["barcode01"] == lib.true_counts
    assert glob.glob(str(tmp_path / "trace" / "**" / "*.xplane.pb"),
                     recursive=True), "no profiler trace written"


def test_pipeline_resume_skips_completed(sim_library):
    tmp, lib = sim_library
    cfg = _base_config(tmp)
    cfg.resume = True
    results = run_with_config(cfg)
    assert results["barcode01"] == lib.true_counts


def test_pipeline_refuses_existing_dir_without_resume(sim_library):
    tmp, _ = sim_library
    cfg = _base_config(tmp)
    with pytest.raises(FileExistsError):
        run_with_config(cfg)


def test_pipeline_untrimmed_reads_with_primer_trim(tmp_path):
    """Untrimmed reads (full adapter+primer ends) through the trim stage
    (dorado trim analogue, ref preprocessing.py:7-59) -> exact counts and
    consensus starting exactly at the UMI."""
    lib = simulator.simulate_library(
        seed=19,
        num_regions=3,
        molecules_per_region=(2, 3),
        reads_per_molecule=(5, 8),
        sub_rate=0.01,
        ins_rate=0.004,
        del_rate=0.004,
        region_len=(650, 800),  # + adapters stays in the 1024-width bucket
        with_adapters=True,
    )
    fastx.write_fasta(tmp_path / "reference.fa", lib.reference.items())
    fq_dir = tmp_path / "fastq_pass" / "barcode01"
    fq_dir.mkdir(parents=True)
    fastx.write_fastq(fq_dir / "barcode01.fastq.gz", lib.reads)
    cfg = RunConfig.from_dict({
        "reference_file": str(tmp_path / "reference.fa"),
        "fastq_pass_dir": str(tmp_path / "fastq_pass"),
        "minimal_length": 500,
        "min_reads_per_cluster": 4,
        "read_batch_size": 64,
        "polish_method": "poa",
        "delete_tmp_files": False,
    })
    results = run_with_config(cfg)
    assert results["barcode01"] == lib.true_counts

    # trimmed consensus: primers gone, full region recovered exactly; the
    # cut position itself may fuzz by a base when read errors fall inside a
    # primer (dorado trim has the same boundary ambiguity), so the UMI-edge
    # bases are not required to be byte-exact on every molecule
    merged = tmp_path / "fastq_pass" / "nano_tcr" / "barcode01" / "fasta" / "merged_consensus.fasta"
    templates = {
        m.umi_fwd + lib.reference[m.region] + m.umi_rev for m in lib.molecules
    }
    consensus = [rec.sequence for rec in fastx.read_fastx(merged)]
    assert len(consensus) == len(lib.molecules)
    region_seqs = set(lib.reference.values())
    for seq in consensus:
        assert any(r in seq for r in region_seqs), "region not exactly recovered"
        assert len(seq) < max(len(t) for t in templates) + 10, "primers not trimmed"
    exact = sum(1 for seq in consensus if seq in templates)
    assert exact >= len(consensus) - 1

    # the trim actually fired (logged)
    ee_log = (tmp_path / "fastq_pass" / "nano_tcr" / "barcode01" / "logs"
              / "ee_filter.log").read_text()
    n_trimmed = int(ee_log.split("reads with primer trim: ")[1].split()[0])
    assert n_trimmed == len(lib.reads)


@pytest.mark.slow
def test_pipeline_degrades_gracefully_on_poisoned_group(sim_library, tmp_path, monkeypatch):
    """One failing region cluster must not abort the library: the rest
    completes and the failure is reported (ref tcr_consensus.py:329-346)."""
    import shutil

    from ont_tcrconsensus_tpu.pipeline import stages

    tmp, lib = sim_library
    root = tmp_path / "poison"
    shutil.copytree(tmp / "fastq_pass" / "barcode01", root / "fastq_pass" / "barcode01")
    shutil.copy(tmp / "reference.fa", root / "reference.fa")

    real_polish = stages.polish_clusters_all
    poisoned = "region_cluster0"

    def flaky_polish(selected_by_group, store, **kw):
        # poison the device chunks that contain the target group: the
        # library-wide batcher must fail ONLY the chunk's groups and
        # complete every other chunk (its per-chunk try/except)
        def poison_polisher(sub, lens, drafts, dlens, **_kw):
            raise RuntimeError("injected failure")

        ok_groups = [(g, s) for g, s in selected_by_group if g != poisoned]
        bad_groups = [(g, s) for g, s in selected_by_group if g == poisoned]
        by_group, failed = real_polish(ok_groups, store, **kw)
        kw_bad = dict(kw, polisher=poison_polisher)
        bad_by_group, bad_failed = real_polish(bad_groups, store, **kw_bad)
        assert poisoned in bad_failed, "chunk failure did not mark the group"
        by_group.update(bad_by_group)
        failed.update(bad_failed)
        return by_group, failed

    monkeypatch.setattr(stages, "polish_clusters_all", flaky_polish)
    cfg = RunConfig.from_dict({
        "reference_file": str(root / "reference.fa"),
        "fastq_pass_dir": str(root / "fastq_pass"),
        "minimal_length": 600,  # sim_library regions are 700-850 nt
        "min_reads_per_cluster": 4,
        "read_batch_size": 64,
        "polish_method": "poa",
        "delete_tmp_files": False,
    })
    results = run_with_config(cfg)

    nano = root / "fastq_pass" / "nano_tcr" / "barcode01"
    report = (nano / "logs" / "incomplete_region_clusters.log").read_text()
    assert poisoned in report and "injected failure" in report
    # an incomplete library is NOT checkpointed: resume must retry it
    mpath = nano / "stage_manifest.json"
    manifest = json.loads(mpath.read_text()) if mpath.exists() else {}
    stages_done = manifest.get("stages", manifest)  # v2 or legacy v1 shape
    assert "round1_consensus" not in stages_done
    assert "counts" not in stages_done
    # every region outside the poisoned cluster still has exact counts
    cluster_map = json.loads(
        (root / "fastq_pass" / "nano_tcr" / "region_cluster_dict.json").read_text()
    )
    unaffected = {r for r, c in cluster_map.items() if c != 0}
    assert unaffected, "poisoned cluster swallowed every region"
    got = results["barcode01"]
    for region in unaffected:
        assert got.get(region) == lib.true_counts.get(region)
    for region, c in cluster_map.items():
        if c == 0:
            assert region not in got


def test_pipeline_empty_and_zero_survivor_libraries(tmp_path):
    """Empty-input edge cases (ISSUE 3 satellite): an empty FASTQ and a
    library whose reads all fail the length gate must both complete with
    empty-but-valid artifacts — and pass strict contracts + quarantine
    policy. Regions with zero clusters simply emit no counts rows."""
    fastx.write_fasta(tmp_path / "reference.fa",
                      [("regionA", "ACGT" * 200), ("regionB", "GGCATT" * 150)])
    fq1 = tmp_path / "fastq_pass" / "barcode01"
    fq2 = tmp_path / "fastq_pass" / "barcode02"
    fq1.mkdir(parents=True)
    fq2.mkdir(parents=True)
    (fq1 / "barcode01.fastq").write_bytes(b"")  # empty input file
    # all reads far below minimal_length: 0 survivors after the gate
    fastx.write_fastq(fq2 / "barcode02.fastq.gz",
                      [(f"r{i}", "ACGT" * 10, "I" * 40) for i in range(8)])
    cfg = RunConfig.from_dict({
        "reference_file": str(tmp_path / "reference.fa"),
        "fastq_pass_dir": str(tmp_path / "fastq_pass"),
        "minimal_length": 600,
        "min_reads_per_cluster": 4,
        "read_batch_size": 64,
        "polish_method": "poa",
        "delete_tmp_files": False,
        "contracts": "strict",
        "on_bad_record": "quarantine",
    })
    results = run_with_config(cfg)
    assert results == {"barcode01": {}, "barcode02": {}}
    for lib in ("barcode01", "barcode02"):
        lib_dir = tmp_path / "fastq_pass" / "nano_tcr" / lib
        csv = lib_dir / "counts" / "umi_consensus_counts.csv"
        assert csv.read_text() == "TCR,Count\n"  # empty-but-valid artifact
        merged = lib_dir / "fasta" / "merged_consensus.fasta"
        assert merged.exists() and merged.read_text() == ""
        manifest = json.loads((lib_dir / "stage_manifest.json").read_text())
        assert "counts" in manifest.get("stages", manifest)  # complete
        # nothing was quarantined: the inputs were clean, just empty/short
        assert not (lib_dir / "quarantine.fastq.gz").exists()


def _mesh_artifacts(tmp, tmp_path, name, mesh_shape):
    """Run the library under ``mesh_shape`` in a fresh root; return the
    bytes of the counts CSV and merged consensus FASTA."""
    import shutil

    root = tmp_path / name
    shutil.copytree(tmp / "fastq_pass" / "barcode01",
                    root / "fastq_pass" / "barcode01")
    shutil.copy(tmp / "reference.fa", root / "reference.fa")
    cfg = _base_config(root)
    cfg.mesh_shape = mesh_shape
    run_with_config(cfg)
    lib_dir = root / "fastq_pass" / "nano_tcr" / "barcode01"
    return {
        "counts": (lib_dir / "counts" / "umi_consensus_counts.csv").read_bytes(),
        "fasta": (lib_dir / "fasta" / "merged_consensus.fasta").read_bytes(),
    }


def _baseline_artifacts(tmp):
    """The unsharded module-baseline artifacts (written by
    test_pipeline_counts_match_ground_truth, which runs first in file
    order — the same reuse test_pipeline_consensus_sequences_exact
    relies on)."""
    lib_dir = tmp / "fastq_pass" / "nano_tcr" / "barcode01"
    return {
        "counts": (lib_dir / "counts" / "umi_consensus_counts.csv").read_bytes(),
        "fasta": (lib_dir / "fasta" / "merged_consensus.fasta").read_bytes(),
    }


@pytest.mark.slow
def test_pipeline_mesh_data2_byte_identical_to_unsharded(sim_library, tmp_path):
    """Sharded execution is an implementation detail: a data=2 mesh run
    must reproduce the unsharded run's counts CSV and consensus FASTA
    byte-for-byte (the sharded kernels are bitwise-equal per chip, and
    stage boundaries never reshard)."""
    tmp, _ = sim_library
    want = _baseline_artifacts(tmp)
    got = _mesh_artifacts(tmp, tmp_path, "mesh_d2", {"data": 2})
    assert got == want, "data=2 artifacts diverged from the unsharded run"


@pytest.mark.slow
def test_pipeline_mesh_scaling_sweep_byte_identical(sim_library, tmp_path):
    """The full ISSUE-18 equivalence sweep: data=1, 4 and 8 all produce
    artifacts byte-identical to the unsharded baseline (data=2 is the
    non-slow arm above)."""
    tmp, _ = sim_library
    want = _baseline_artifacts(tmp)
    for n in (1, 4, 8):
        got = _mesh_artifacts(tmp, tmp_path, f"mesh_d{n}", {"data": n})
        assert got == want, f"data={n} artifacts diverged"


def test_mesh_batch_divisibility_validated(sim_library):
    tmp, _ = sim_library
    cfg = _base_config(tmp)
    cfg.mesh_shape = {"data": 8}
    cfg.read_batch_size = 100  # not divisible by 8
    from ont_tcrconsensus_tpu.pipeline.run import make_mesh_from_config

    with pytest.raises(ValueError, match="read_batch_size"):
        make_mesh_from_config(cfg)
