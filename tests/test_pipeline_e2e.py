"""End-to-end pipeline: simulated library -> bit-exact UMI counts.

The north-star acceptance (SURVEY §6): UMI counts concordant with ground
truth on a library with known molecules. Every molecule gets >=
min_reads_per_cluster reads at moderate error rates, so the expected count
per region is exactly its molecule count.
"""

import json
import os

import numpy as np
import pytest

from ont_tcrconsensus_tpu.io import fastx, simulator
from ont_tcrconsensus_tpu.pipeline.config import RunConfig
from ont_tcrconsensus_tpu.pipeline.run import run_with_config


@pytest.fixture(scope="module")
def sim_library(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("e2e")
    lib = simulator.simulate_library(
        seed=11,
        num_regions=4,
        molecules_per_region=(2, 4),
        reads_per_molecule=(6, 10),
        sub_rate=0.01,
        ins_rate=0.004,
        del_rate=0.004,
    )
    ref_path = tmp / "reference.fa"
    fastx.write_fasta(ref_path, lib.reference.items())
    fq_dir = tmp / "fastq_pass" / "barcode01"
    fq_dir.mkdir(parents=True)
    fastx.write_fastq(fq_dir / "barcode01.fastq.gz", lib.reads)
    return tmp, lib


def _base_config(tmp):
    return RunConfig.from_dict({
        "reference_file": str(tmp / "reference.fa"),
        "fastq_pass_dir": str(tmp / "fastq_pass"),
        "minimal_length": 1000,
        "min_reads_per_cluster": 4,
        "read_batch_size": 128,
        "polish_method": "poa",
        "delete_tmp_files": False,
    })


def test_pipeline_counts_match_ground_truth(sim_library):
    tmp, lib = sim_library
    cfg = _base_config(tmp)
    results = run_with_config(cfg)
    assert "barcode01" in results
    got = results["barcode01"]
    want = lib.true_counts
    assert got == want, f"counts mismatch: got {got} want {want}"

    # artifact layout parity
    lib_dir = tmp / "fastq_pass" / "nano_tcr" / "barcode01"
    assert (lib_dir / "counts" / "umi_consensus_counts.csv").exists()
    assert (tmp / "fastq_pass" / "nano_tcr" / "region_cluster_dict.json").exists()
    csv = (lib_dir / "counts" / "umi_consensus_counts.csv").read_text().splitlines()
    assert csv[0] == "TCR,Count"
    csv_counts = dict(line.rsplit(",", 1) for line in csv[1:])
    assert {k: int(v) for k, v in csv_counts.items()} == want


def test_pipeline_consensus_sequences_exact(sim_library):
    """Round-1 consensus must reproduce each molecule's true template."""
    tmp, lib = sim_library
    lib_dir = tmp / "fastq_pass" / "nano_tcr" / "barcode01"
    merged = lib_dir / "fasta" / "merged_consensus.fasta"
    assert merged.exists()
    consensus = {rec.name: rec.sequence for rec in fastx.read_fastx(merged)}
    templates = {
        simulator.LEFT_FLANK + m.umi_fwd + lib.reference[m.region] + m.umi_rev
        + simulator.RIGHT_FLANK
        for m in lib.molecules
    }
    exact = sum(1 for seq in consensus.values() if seq in templates)
    assert len(consensus) == len(lib.molecules)
    assert exact == len(consensus), (
        f"only {exact}/{len(consensus)} consensus sequences are bit-exact"
    )


def test_pipeline_rnn_polish_keeps_counts_exact(sim_library, tmp_path):
    """The confidence-gated RNN pass must never corrupt a correct consensus."""
    from ont_tcrconsensus_tpu.models import polisher as polisher_mod

    if polisher_mod.load_default_params() is None:
        pytest.skip("no bundled polisher weights")
    tmp, lib = sim_library
    import shutil

    root = tmp_path / "rnn"
    shutil.copytree(tmp / "fastq_pass" / "barcode01", root / "fastq_pass" / "barcode01")
    shutil.copy(tmp / "reference.fa", root / "reference.fa")
    cfg = RunConfig.from_dict({
        "reference_file": str(root / "reference.fa"),
        "fastq_pass_dir": str(root / "fastq_pass"),
        "minimal_length": 1000,
        "min_reads_per_cluster": 4,
        "read_batch_size": 128,
        "polish_method": "rnn",
        "delete_tmp_files": False,
    })
    results = run_with_config(cfg)
    assert results["barcode01"] == lib.true_counts


def test_pipeline_resume_skips_completed(sim_library):
    tmp, lib = sim_library
    cfg = _base_config(tmp)
    cfg.resume = True
    results = run_with_config(cfg)
    assert results["barcode01"] == lib.true_counts


def test_pipeline_refuses_existing_dir_without_resume(sim_library):
    tmp, _ = sim_library
    cfg = _base_config(tmp)
    with pytest.raises(FileExistsError):
        run_with_config(cfg)
