"""Slice-packed multi-tenant serving (serve/slices.py + the daemon
runner pool): allocator units (buddy alignment, fragmentation, sizing,
quarantine), packed-daemon contracts with a stubbed runner (two tenants
resident concurrently on disjoint slices, device-lost isolating one
tenant, drain journaling every resident), and the slow real-pipeline
packed e2es (byte identity vs the serial daemon; tenant A degraded by a
mesh device loss while tenant B's outputs stay byte-identical).

The stubbed tests are the tier-1 slice-pack smoke (scripts/tier1.sh
selects them by the ``slice_pack`` substring); the real-pipeline e2es
are slow-marked.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import time

import pytest

from ont_tcrconsensus_tpu.obs import live as obs_live
from ont_tcrconsensus_tpu.obs import metrics as obs_metrics
from ont_tcrconsensus_tpu.parallel.budget import BudgetModel
from ont_tcrconsensus_tpu.pipeline.config import RunConfig
from ont_tcrconsensus_tpu.robustness import faults, shutdown
from ont_tcrconsensus_tpu.serve import queue as serve_queue
from ont_tcrconsensus_tpu.serve import slices as serve_slices
from ont_tcrconsensus_tpu.serve.daemon import Daemon

_BASE = {"reference_file": "r.fa", "fastq_pass_dir": "fq"}


def _mini_cfg(**over) -> RunConfig:
    return RunConfig.from_dict({**_BASE, **over})


class _Dev:
    """Stand-in device: anything with .platform/.id labels like jax's."""

    def __init__(self, i: int):
        self.platform = "fake"
        self.id = i

    def __repr__(self):
        return f"fake:{self.id}"


def _alloc(n: int, hbm_gb: float = 12.0) -> serve_slices.SliceAllocator:
    return serve_slices.SliceAllocator(
        [_Dev(i) for i in range(n)], BudgetModel(hbm_gb))


# ---------------------------------------------------------------------------
# allocator units


def test_config_serve_workers_validation():
    assert _mini_cfg().serve_workers == 1
    assert _mini_cfg(serve_workers=4).serve_workers == 4
    with pytest.raises(ValueError, match="serve_workers"):
        _mini_cfg(serve_workers=0)
    with pytest.raises(ValueError, match="serve_workers"):
        _mini_cfg(serve_workers=True)


def test_allocator_allowance_is_degraded_budget_arithmetic():
    alloc = _alloc(8, hbm_gb=16.0)
    assert alloc.max_size == 8
    # a slice of n of N devices gets exactly the degraded-mesh fraction
    assert alloc.allowance(8).hbm_gb == pytest.approx(16.0)
    assert alloc.allowance(2).hbm_gb == pytest.approx(4.0)
    assert alloc.allowance(1).hbm_gb == pytest.approx(2.0)


def test_allocator_size_for_smallest_fit_and_mesh_pin():
    alloc = _alloc(8)
    # a small job fits the smallest slice
    size, detail = alloc.size_for(_mini_cfg(read_batch_size=96))
    assert size == 1, detail
    # an explicit mesh_shape pins the pow2 ceiling of its axis product
    size, detail = alloc.size_for(
        _mini_cfg(read_batch_size=96, mesh_shape={"data": 2}))
    assert size == 2, detail
    size, detail = alloc.size_for(_mini_cfg(mesh_shape={"data": 3}))
    assert size == 4, detail
    # a shape wider than the pool is a loud (None, why), not a wait
    size, detail = alloc.size_for(_mini_cfg(mesh_shape={"data": 16}))
    assert size is None and "largest grantable" in detail


def test_allocator_alignment_makes_fragmentation_real():
    alloc = _alloc(4)
    for j in ("a", "b", "c", "d"):
        assert alloc.try_assign(j, 1) is not None
    assert alloc.try_assign("e", 1) is None  # full residency
    # free the MIDDLE run 1..2: two free devices, but neither aligned
    # pair (0..1, 2..3) is fully free — a 2-slice must wait, not carve
    # a misaligned run
    alloc.release("b")
    alloc.release("c")
    assert alloc.try_assign("e", 2) is None
    assert alloc.can_ever_fit(2)  # ...but waiting is not hopeless
    alloc.release("d")
    lease = alloc.try_assign("e", 2)
    assert lease is not None and (lease.start, lease.size) == (2, 2)


def test_allocator_quarantine_survives_release_and_shrinks_admission():
    alloc = _alloc(8)
    obs_metrics.arm()
    try:
        a = alloc.try_assign("tenant-a", 4)
        b = alloc.try_assign("tenant-b", 2)
        assert (a.start, a.size) == (0, 4)
        assert (b.start, b.size) == (4, 2)
        labels = alloc.quarantine("tenant-a")
        assert labels == [f"fake:{i}" for i in range(4)]
        # the loss outlives the job: release returns nothing to the pool
        alloc.release("tenant-a")
        snap = alloc.snapshot()
        assert snap["quarantined"] == 4
        assert all(snap["devices"][f"fake:{i}"] == "quarantined"
                   for i in range(4))
        # B's disjoint lease never noticed
        assert snap["leases"] == {
            "tenant-b": {"slice": "4+2", "devices": ["fake:4", "fake:5"]}}
        # the whole mesh is gone for good, but the aligned 4..7 run
        # survives (busy counts: B frees later) — admission shrinks to
        # the largest grantable slice (4 of 8)
        assert not alloc.can_ever_fit(8)
        assert alloc.can_ever_fit(4)
        assert alloc.admission_budget().hbm_gb == pytest.approx(12.0 / 2)
        # metered: quarantine counter up, busy gauge down, tenant cleared
        reg = obs_metrics.registry()
        assert reg.slice_quarantined == {f"fake:{i}": 1.0 for i in range(4)}
        text = "\n".join(reg.prometheus_lines())
        assert 'tcr_slice_quarantined_total{slice="fake:0"} 1' in text
        assert 'tcr_mesh_slice_busy{slice="fake:4",tenant="tenant-b"} 1' \
            in text
    finally:
        obs_metrics.disarm()


def test_allocator_assign_chaos_fires_before_pool_mutation():
    alloc = _alloc(2)
    faults.arm([{"site": "serve.slice_assign", "kind": "error"}], seed=0)
    try:
        with pytest.raises(RuntimeError, match="serve.slice_assign"):
            alloc.try_assign("a", 1)
    finally:
        faults.disarm()
    # nothing leaked: the fault fired before the carve
    assert alloc.snapshot()["leases"] == {}
    assert alloc.try_assign("a", 1) is not None


def test_allocator_pack_chaos_fires_after_pool_consistent():
    alloc = _alloc(2)
    assert alloc.try_assign("a", 2) is not None
    faults.arm([{"site": "serve.pack", "kind": "error"}], seed=0)
    try:
        with pytest.raises(RuntimeError, match="serve.pack"):
            alloc.release("a")
    finally:
        faults.disarm()
    # the fault hit AFTER the devices went back: pool fully consistent
    snap = alloc.snapshot()
    assert snap["leases"] == {} and snap["quarantined"] == 0
    assert alloc.try_assign("b", 2) is not None


# ---------------------------------------------------------------------------
# packed daemon with a stubbed runner (the tier-1 slice-pack smoke)


class _StubRunner:
    """Replaces run_with_config: records the slice it ran on, optionally
    raises per-tenant, then parks on a gate polling the shutdown
    checkpoint (so a daemon drain preempts it like a real run)."""

    def __init__(self):
        self.gate = threading.Event()
        self.lock = threading.Lock()
        self.calls: list[tuple[str, tuple]] = []  # (tag, devices)
        self.raises: dict[str, list[BaseException]] = {}

    def tag_calls(self, tag: str) -> list[tuple]:
        with self.lock:
            return [d for t, d in self.calls if t == tag]

    def __call__(self, cfg):
        from ont_tcrconsensus_tpu.parallel import mesh as mesh_mod

        tag = os.path.basename(cfg.fastq_pass_dir)
        with self.lock:
            self.calls.append((tag, tuple(mesh_mod.slice_devices() or ())))
            planned = self.raises.get(tag)
            exc = planned.pop(0) if planned else None
        if exc is not None:
            raise exc
        while not self.gate.wait(0.02):
            shutdown.checkpoint("stub.run")
        return {"barcode01": {"r1": 1}}


@pytest.fixture
def packed(tmp_path, monkeypatch):
    """A 2-worker packed daemon over the suite's 8 CPU devices, its
    runner stubbed; yields (daemon, runner, submit, exit_codes)."""
    from ont_tcrconsensus_tpu.pipeline import run as run_mod

    runner = _StubRunner()
    monkeypatch.setattr(run_mod, "run_with_config", runner)
    template = {**_BASE, "compile_cache_dir": "off"}
    daemon = Daemon(template, port=0, state_dir=str(tmp_path / "state"),
                    do_prewarm=False, workers=2)
    codes: list[int] = []
    loop = threading.Thread(
        target=lambda: codes.append(daemon.serve_forever()),
        name="serve-packed", daemon=True)
    loop.start()
    deadline = time.monotonic() + 60.0
    while obs_live.server() is None and time.monotonic() < deadline:
        time.sleep(0.02)
    assert obs_live.server() is not None, "daemon never armed"

    def submit(tag: str, **over) -> str:
        # absolute per-test dir: a completed job appends history under
        # <fastq_pass_dir>/nano_tcr/, which must not land in the repo cwd
        status, snap = daemon.submit(
            {"fastq_pass_dir": str(tmp_path / tag), **over})
        assert status == 202, snap
        return snap["id"]

    try:
        yield daemon, runner, submit, codes
    finally:
        runner.gate.set()
        daemon.request_stop()
        loop.join(timeout=60.0)
        assert not loop.is_alive(), "packed daemon did not stop"


def _wait(predicate, timeout: float = 30.0, what: str = "condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


def test_slice_pack_two_tenants_resident_on_disjoint_slices(packed):
    daemon, runner, submit, _ = packed
    a, b = submit("fqA"), submit("fqB")
    _wait(lambda: daemon.allocator.resident() == 2, what="2 residents")
    snap = daemon.jobs_snapshot()
    assert snap["resident_jobs"] == 2
    leases = snap["slices"]["leases"]
    assert set(leases) == {a, b}
    # disjoint: no device appears in both tenants' slices
    devs_a = set(leases[a]["devices"])
    devs_b = set(leases[b]["devices"])
    assert devs_a and devs_b and not (devs_a & devs_b)
    # each run's mesh really came up over ITS slice's devices
    _wait(lambda: runner.tag_calls("fqA") and runner.tag_calls("fqB"),
          what="both stubs started")
    got_a = {f"{d.platform}:{d.id}" for d in runner.tag_calls("fqA")[0]}
    got_b = {f"{d.platform}:{d.id}" for d in runner.tag_calls("fqB")[0]}
    assert got_a == devs_a and got_b == devs_b
    # daemon-plane metrics: residency gauge + per-slice tenant labels
    reg = obs_metrics.registry()
    text = "\n".join(reg.prometheus_lines())
    assert "tcr_serve_resident_jobs 2" in text
    for dev in devs_a:
        assert f'tcr_mesh_slice_busy{{slice="{dev}",tenant="{a}"}} 1' in text
    runner.gate.set()
    _wait(lambda: daemon.jobs_snapshot()["jobs_done"] == 2,
          what="both jobs done")
    final = daemon.jobs_snapshot()
    assert final["resident_jobs"] == 0
    assert all(j["state"] == "done" for j in final["jobs"])
    assert final["slices"]["leases"] == {}
    text = "\n".join(obs_metrics.registry().prometheus_lines())
    assert "tcr_serve_resident_jobs 0" in text


def test_slice_pack_device_lost_isolates_one_tenant(packed):
    daemon, runner, submit, _ = packed
    # tenant A's first run dies with DEVICE_LOST ESCAPING the mesh (no
    # in-slice survivor); tenant B just runs
    runner.raises["fqA"] = [
        faults.DeviceLostChaosError("DEVICE_LOST: slice drill")]
    b = submit("fqB")
    _wait(lambda: daemon.allocator.resident() >= 1, what="B resident")
    a = submit("fqA")
    # A's slice is quarantined, A requeues for a fresh slice and — with
    # the gate open for its retry — completes; B never noticed
    _wait(lambda: daemon.allocator.snapshot()["quarantined"] >= 1,
          what="quarantine after A's device loss")
    assert daemon.jobs_snapshot()["jobs"], "jobs listing went away"
    _wait(lambda: len(runner.tag_calls("fqA")) >= 2,
          what="A's retry on a fresh slice")
    runner.gate.set()
    _wait(lambda: daemon.jobs_snapshot()["jobs_done"] == 2,
          what="both tenants done")
    snap = daemon.jobs_snapshot()
    states = {j["id"]: j for j in snap["jobs"]}
    assert states[a]["state"] == "done" and states[b]["state"] == "done"
    # the retry resumed (committed stages carry over) on DIFFERENT devices
    job_a = daemon.queue.job(a)
    assert job_a.raw["resume"] is True and job_a.attempts == 1
    first, second = runner.tag_calls("fqA")[:2]
    assert not (set(first) & set(second)), "retry landed on the dead slice"
    # B ran exactly once, uninterrupted
    assert len(runner.tag_calls("fqB")) == 1
    # the dead capacity is out of circulation and admission shrank
    pool = snap["slices"]
    assert pool["quarantined"] == 1
    assert daemon.queue.budget.hbm_gb < daemon.budget.hbm_gb
    # the isolation event is on /metrics
    text = "\n".join(obs_metrics.registry().prometheus_lines())
    assert "tcr_slice_quarantined_total" in text


def test_slice_pack_pinned_whole_mesh_job_queues_until_repack(packed):
    daemon, runner, submit, _ = packed
    small = submit("fqSmall")
    _wait(lambda: daemon.allocator.resident() == 1, what="small resident")
    # the whole-mesh job cannot co-reside: free slices exist, but no
    # aligned 8-run is free — it must STAY QUEUED, not be rejected
    big = submit("fqBig", mesh_shape={"data": 8})
    time.sleep(0.6)
    states = {j["id"]: j["state"] for j in daemon.jobs_snapshot()["jobs"]}
    assert states[big] in ("queued", "requeued"), states
    assert states[small] == "running"
    runner.gate.set()
    _wait(lambda: daemon.jobs_snapshot()["jobs_done"] == 2,
          what="repack ran the big job")
    states = {j["id"]: j["state"] for j in daemon.jobs_snapshot()["jobs"]}
    assert states == {small: "done", big: "done"}
    # the big job really got the whole mesh
    assert len(runner.tag_calls("fqBig")[0]) == 8


def test_slice_pack_drain_journals_every_resident(packed):
    daemon, runner, submit, codes = packed
    a, b = submit("fqA"), submit("fqB")
    c = submit("fqQueued")  # third tenant: queued behind the pool
    _wait(lambda: daemon.allocator.resident() == 2, what="2 residents")
    _wait(lambda: len(runner.tag_calls("fqA")) == 1
          and len(runner.tag_calls("fqB")) == 1, what="both runs started")
    # SIGTERM-equivalent: the daemon coordinator preempts BOTH resident
    # runs at their next checkpoint; all three jobs must journal
    daemon._coord.request("drill")
    _wait(lambda: bool(codes), timeout=60.0, what="daemon drain")
    assert codes == [143]
    journal_file = serve_queue.journal_path(daemon.state_dir)
    with open(journal_file) as fh:
        journal = json.load(fh)
    by_id = {j["id"]: j for j in journal["jobs"]}
    assert set(by_id) == {a, b, c}
    for jid in (a, b):
        assert by_id[jid]["state"] == "requeued"
        assert by_id[jid]["raw"]["resume"] is True
    assert by_id[c]["state"] == "queued"


# ---------------------------------------------------------------------------
# slow: real-pipeline packed e2es (byte identity + tenant isolation)


_TEST_CACHE = os.environ.get(
    "JAX_TEST_CACHE_DIR",
    os.path.join(os.path.dirname(__file__), ".jax_cache"),
)


@pytest.fixture(scope="module")
def packed_library(tmp_path_factory):
    from ont_tcrconsensus_tpu.io import fastx, simulator

    tmp = tmp_path_factory.mktemp("packed_lib")
    lib = simulator.simulate_library(
        seed=31,
        num_regions=2,
        molecules_per_region=(2, 3),
        reads_per_molecule=(5, 7),
        sub_rate=0.006,
        ins_rate=0.003,
        del_rate=0.003,
        region_len=(700, 850),
    )
    fastx.write_fasta(tmp / "reference.fa", lib.reference.items())
    fq_dir = tmp / "fastq_pass" / "barcode01"
    fq_dir.mkdir(parents=True)
    fastx.write_fastq(fq_dir / "barcode01.fastq.gz", lib.reads)
    return tmp, lib


def _stage(src, root):
    root.mkdir(parents=True, exist_ok=True)
    shutil.copy(src / "reference.fa", root / "reference.fa")
    shutil.copytree(src / "fastq_pass", root / "fastq_pass")
    return root


def _raw_cfg(root, **over) -> dict:
    raw = {
        "reference_file": str(root / "reference.fa"),
        "fastq_pass_dir": str(root / "fastq_pass"),
        "minimal_length": 600,
        "min_reads_per_cluster": 4,
        "read_batch_size": 96,
        "polish_method": "poa",
        "delete_tmp_files": False,
        "compile_cache_dir": _TEST_CACHE,
    }
    raw.update(over)
    return raw


_ARTIFACTS = (
    ("barcode01", "counts", "umi_consensus_counts.csv"),
    ("barcode01", "fasta", "merged_consensus.fasta"),
)


def _run_packed(daemon, raws, resident_probe=None, timeout=900.0):
    """Drive a packed daemon through ``raws``; returns the final jobs
    listing. ``resident_probe`` is polled while waiting (concurrency
    high-water tracking)."""
    codes: list[int] = []
    loop = threading.Thread(
        target=lambda: codes.append(daemon.serve_forever()),
        name="serve-packed-e2e", daemon=True)
    loop.start()
    try:
        _wait(lambda: obs_live.server() is not None, timeout=120.0,
              what="live plane")
        ids = []
        for raw in raws:
            status, snap = daemon.submit(raw)
            assert status == 202, snap
            ids.append(snap["id"])
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if resident_probe is not None:
                resident_probe()
            listing = daemon.jobs_snapshot()
            if listing["jobs_done"] >= len(raws):
                break
            time.sleep(0.1)
        listing = daemon.jobs_snapshot()
        assert listing["jobs_done"] == len(raws), listing
        metrics_text = "\n".join(obs_metrics.registry().prometheus_lines())
        pool = daemon.allocator.snapshot()
    finally:
        daemon.request_stop()
        loop.join(timeout=120.0)
    assert not loop.is_alive(), "packed daemon did not stop"
    assert codes == [0]
    return ids, listing, metrics_text, pool


@pytest.mark.slow
def test_packed_e2e_two_tenants_byte_identical_to_serial(
        packed_library, tmp_path_factory):
    """Two tenant jobs resident at once on disjoint slices produce counts
    CSV + consensus FASTA byte-identical to the one-shot serial run."""
    from ont_tcrconsensus_tpu.pipeline.run import run_with_config

    src, lib = packed_library
    base = tmp_path_factory.mktemp("packed_e2e")
    oneshot = _stage(src, base / "oneshot")
    res_one = run_with_config(RunConfig.from_dict(_raw_cfg(oneshot)))
    assert res_one == {"barcode01": lib.true_counts}
    nano_one = oneshot / "fastq_pass" / "nano_tcr"

    w1 = _stage(src, base / "w1")
    w2 = _stage(src, base / "w2")
    daemon = Daemon(_raw_cfg(w1), port=0, state_dir=str(base / "state"),
                    do_prewarm=False, workers=2)
    high_water = [0]

    def probe():
        high_water[0] = max(high_water[0], daemon.allocator.resident())

    _, listing, metrics_text, _ = _run_packed(
        daemon, [_raw_cfg(w) for w in (w1, w2)], resident_probe=probe)
    assert all(j["state"] == "done" for j in listing["jobs"]), listing
    # the point of packing: both tenants were resident AT ONCE
    assert high_water[0] >= 2, "tenants never overlapped"
    assert "tcr_serve_resident_jobs" in metrics_text
    for rel in _ARTIFACTS:
        want = nano_one.joinpath(*rel).read_bytes()
        for w in (w1, w2):
            got = (w / "fastq_pass" / "nano_tcr").joinpath(*rel).read_bytes()
            assert got == want, \
                f"packed serving must not change {'/'.join(rel)}"


@pytest.mark.slow
def test_packed_e2e_device_lost_on_tenant_a_never_perturbs_tenant_b(
        packed_library, tmp_path_factory):
    """The isolation acceptance drill: mesh.device_lost fires inside
    tenant A's 2-device slice. A's run degrades WITHIN its slice (2 -> 1)
    and completes; the dead device is quarantined out of the pool; B —
    resident on a disjoint slice the whole time — finishes byte-identical
    and uninterrupted (its robustness report records nothing)."""
    src, lib = packed_library
    base = tmp_path_factory.mktemp("packed_chaos")
    oneshot = _stage(src, base / "oneshot")
    from ont_tcrconsensus_tpu.pipeline.run import run_with_config

    run_with_config(RunConfig.from_dict(_raw_cfg(oneshot)))
    nano_one = oneshot / "fastq_pass" / "nano_tcr"

    wa = _stage(src, base / "wa")
    wb = _stage(src, base / "wb")
    daemon = Daemon(_raw_cfg(wa), port=0, state_dir=str(base / "state"),
                    do_prewarm=False, workers=2)
    raws = [
        _raw_cfg(wa, mesh_shape={"data": 2}, chaos=[
            {"site": "mesh.device_lost", "kind": "device-lost"},
        ]),
        _raw_cfg(wb),
    ]
    ids, listing, metrics_text, pool = _run_packed(daemon, raws)
    states = {j["id"]: j["state"] for j in listing["jobs"]}
    assert states == {ids[0]: "done", ids[1]: "done"}, listing
    # A survived by degrading; the lost device left the pool for good
    assert pool["quarantined"] == 1, pool
    assert "tcr_slice_quarantined_total" in metrics_text
    report_a = json.loads(
        (wa / "fastq_pass" / "nano_tcr" / "robustness_report.json")
        .read_text())
    ev = next(e for e in report_a["events"] if e["site"] == "mesh.degraded")
    assert ev["classification"] == "device_lost"
    assert ev["detail"]["data_from"] == 2 and ev["detail"]["data_to"] == 1
    # B's own report shows an untouched run: no degradation, no retries
    report_b = json.loads(
        (wb / "fastq_pass" / "nano_tcr" / "robustness_report.json")
        .read_text())
    assert report_b["events"] == [], report_b["events"]
    # both tenants' artifacts — including degraded A's — byte-identical
    for rel in _ARTIFACTS:
        want = nano_one.joinpath(*rel).read_bytes()
        for w in (wa, wb):
            got = (w / "fastq_pass" / "nano_tcr").joinpath(*rel).read_bytes()
            assert got == want, \
                f"isolation drill changed {'/'.join(rel)}"
