"""graftlint (tools/graftlint): every rule family fires on a known-bad
fixture snippet, suppressions work, and the shipped tree is clean.

The fixture trees are written to tmp_path and linted through the same
``run_paths`` entry point the tier-1 gate uses, so the cross-file rules
(chaos sites, config fields) locate their anchors exactly as they do on
the real tree. Two seeded regression fixtures reproduce shipped bugs:
the PR 2 ``except Exception``-swallows-``Preempted`` shape (fixed by
making ``Preempted`` a ``BaseException`` — the ``preempted-base`` rule
pins that) and a misspelled chaos-site literal (the silent-dead-injection
-point class the ``chaos-unknown-site`` rule exists for).
"""

from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.graftlint import run_paths  # noqa: E402
from tools.graftlint.core import main as graftlint_main  # noqa: E402


def lint(tmp_path, files: dict[str, str]) -> list:
    for rel, source in files.items():
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(source)
    return run_paths([str(tmp_path)])


def rules_of(findings) -> set[str]:
    return {f.rule for f in findings}


# ---------------------------------------------------------------------------
# rule family 1: jit-hygiene


def test_jit_host_sync_fires(tmp_path):
    findings = lint(tmp_path, {"bad.py": (
        "import numpy as np\n"
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    y = np.asarray(x)\n"
        "    z = float(x)\n"
        "    return y, z, x.item()\n"
    )})
    assert rules_of(findings) == {"jit-host-sync"}
    assert len(findings) == 3


def test_jit_impure_and_tracer_branch_fire(tmp_path):
    findings = lint(tmp_path, {"bad.py": (
        "import time, random\n"
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    t = time.time()\n"
        "    r = random.random()\n"
        "    if x > 0:\n"
        "        x = x + 1\n"
        "    while x < 9:\n"
        "        x = x + t + r\n"
        "    return x\n"
    )})
    assert rules_of(findings) == {"jit-impure-call", "jit-tracer-branch"}
    assert sum(f.rule == "jit-tracer-branch" for f in findings) == 2


def test_jit_call_site_wrapping_detected(tmp_path):
    """jax.jit(fn) / jit(shard_map(fn, ...)) mark fn as jitted too."""
    findings = lint(tmp_path, {"bad.py": (
        "import jax\n"
        "def inner(a):\n"
        "    return a.item()\n"
        "wrapped = jax.jit(jax.vmap(inner))\n"
    )})
    assert rules_of(findings) == {"jit-host-sync"}


def test_jit_static_and_shape_branches_are_clean(tmp_path):
    """static_argnames params and .shape/len()-derived values are not
    tracers; `is None` tests and directly-called nested helpers (the
    sw_pallas pad_to shape) must not flag."""
    findings = lint(tmp_path, {"ok.py": (
        "import functools\n"
        "import jax\n"
        "import jax.numpy as jnp\n"
        "@functools.partial(jax.jit, static_argnames=('n',))\n"
        "def f(x, n, opt=None):\n"
        "    if n > 4:\n"
        "        x = x[:n]\n"
        "    if opt is None:\n"
        "        opt = 0\n"
        "    def pad_to(y, m):\n"
        "        if y.shape[0] == m:\n"
        "            return y\n"
        "        return jnp.zeros(m, y.dtype)\n"
        "    for _ in range(len(x)):\n"
        "        x = x + opt\n"
        "    return pad_to(x, x.shape[0] + n)\n"
    )})
    assert findings == []


# ---------------------------------------------------------------------------
# rule family 2: exception-guard


def test_pr2_regression_except_exception_swallows_preempted(tmp_path):
    """Seeded regression: the PR 2 bug shape. Preempted subclassing
    Exception makes every `except Exception` skip guard swallow a
    preemption into 'library failed, skipped' — the rule pins the fix
    (BaseException) at the class definition."""
    findings = lint(tmp_path, {"bad.py": (
        "class Preempted(Exception):\n"
        "    pass\n"
        "def guard(run_library, fastqs):\n"
        "    for fq in fastqs:\n"
        "        try:\n"
        "            run_library(fq)\n"
        "        except Exception as exc:\n"  # swallows the Preempted above
        "            print('skipped', fq, exc)\n"
    )})
    assert rules_of(findings) == {"preempted-base"}


def test_bare_except_and_broad_swallow_fire(tmp_path):
    findings = lint(tmp_path, {"bad.py": (
        "def f(work):\n"
        "    try:\n"
        "        work()\n"
        "    except:\n"
        "        pass\n"
        "    try:\n"
        "        work()\n"
        "    except BaseException:\n"
        "        return None\n"
        "    try:\n"
        "        work()\n"
        "    except Preempted:\n"
        "        pass\n"
    )})
    assert rules_of(findings) == {
        "bare-except", "broad-except-swallow", "preempted-swallow",
    }


def test_storing_or_reraising_the_exception_is_clean(tmp_path):
    """The overlap-executor shapes: store for later re-raise, queue to the
    consumer, bare re-raise — none may flag (and Preempted deriving from
    BaseException is the fixed, correct form)."""
    findings = lint(tmp_path, {"ok.py": (
        "class Preempted(BaseException):\n"
        "    pass\n"
        "def f(work, q):\n"
        "    exc_holder = []\n"
        "    try:\n"
        "        work()\n"
        "    except BaseException as exc:\n"
        "        exc_holder.append(exc)\n"
        "    try:\n"
        "        work()\n"
        "    except BaseException as exc:\n"
        "        q.put(exc)\n"
        "    try:\n"
        "        work()\n"
        "    except BaseException:\n"
        "        raise\n"
        "    try:\n"
        "        work()\n"
        "    except Preempted as p:\n"
        "        stored = p\n"
        "        raise stored\n"
    )})
    assert findings == []


# ---------------------------------------------------------------------------
# rule family 3: chaos-site cross-check

_MINI_FAULTS = (
    "KNOWN_SITES = frozenset({'assign.dispatch', 'polish.dispatch'})\n"
    "def inject(site):\n"
    "    pass\n"
)


def test_misspelled_chaos_site_fires(tmp_path):
    """Seeded regression: a typo'd plant literal is a silently dead
    injection point — arming the real site never fires."""
    findings = lint(tmp_path, {
        "faults.py": _MINI_FAULTS,
        "plant.py": (
            "import faults\n"
            "def go():\n"
            "    faults.inject('assign.dispatch')\n"
            "    faults.inject('polish.dipsatch')\n"  # misspelled
        ),
    })
    assert rules_of(findings) == {"chaos-unknown-site", "chaos-unplanted-site"}
    unknown = [f for f in findings if f.rule == "chaos-unknown-site"]
    assert len(unknown) == 1 and "polish.dipsatch" in unknown[0].message
    # the typo also leaves the REAL site unplanted: both directions report
    unplanted = [f for f in findings if f.rule == "chaos-unplanted-site"]
    assert len(unplanted) == 1 and "polish.dispatch" in unplanted[0].message


def test_chaos_parity_is_clean(tmp_path):
    findings = lint(tmp_path, {
        "faults.py": _MINI_FAULTS,
        "plant.py": (
            "import faults\n"
            "def go():\n"
            "    faults.inject('assign.dispatch')\n"
            "    faults.mutate_input('polish.dispatch', 'x')\n"
        ),
    })
    assert findings == []


# ---------------------------------------------------------------------------
# rule family 3b: obs-site cross-check (telemetry mirror of the chaos rule)

_MINI_OBS = (
    "OBS_SITES = frozenset({'assign.batches', 'polish.dispatch'})\n"
    "KNOWN_SITES = OBS_SITES\n"
)


def test_misspelled_obs_site_fires_both_directions(tmp_path):
    findings = lint(tmp_path, {
        "obs.py": _MINI_OBS,
        "plant.py": (
            "import metrics, device\n"
            "def go():\n"
            "    metrics.counter_add('asign.batches')\n"  # misspelled
            "    with device.dispatch('polish.dispatch'):\n"
            "        pass\n"
        ),
    })
    assert rules_of(findings) == {"obs-unknown-site", "obs-unplanted-site"}
    unknown = [f for f in findings if f.rule == "obs-unknown-site"]
    assert len(unknown) == 1 and "asign.batches" in unknown[0].message
    unplanted = [f for f in findings if f.rule == "obs-unplanted-site"]
    assert len(unplanted) == 1 and "'assign.batches'" in unplanted[0].message
    assert unplanted[0].path.endswith("obs.py")  # anchored at the registry


def test_obs_parity_is_clean_and_dynamic_names_skip(tmp_path):
    findings = lint(tmp_path, {
        "obs.py": _MINI_OBS,
        "plant.py": (
            "import metrics, trace, timer\n"
            "def go(name):\n"
            "    metrics.counter_add('assign.batches')\n"
            "    with timer.stage('polish.dispatch'):\n"
            "        pass\n"
            "    with trace.span(f'{name}_bg'):\n"  # dynamic: out of scope
            "        pass\n"
        ),
    })
    assert findings == []


def test_obs_registry_does_not_pollute_chaos_known_sites(tmp_path):
    """The obs registry aliases KNOWN_SITES from a separate OBS_SITES
    literal on purpose: the chaos rule collects string constants from
    every ``KNOWN_SITES = ...`` assignment, and an alias assignment
    carries none — the two vocabularies must not merge (obs entries would
    all report chaos-unplanted-site)."""
    findings = lint(tmp_path, {
        "faults.py": _MINI_FAULTS,
        "obs.py": _MINI_OBS,
        "plant.py": (
            "import faults, metrics, device\n"
            "def go():\n"
            "    faults.inject('assign.dispatch')\n"
            "    faults.inject('polish.dispatch')\n"
            "    metrics.counter_add('assign.batches')\n"
            "    with device.dispatch('polish.dispatch'):\n"
            "        pass\n"
        ),
    })
    assert findings == []


_MINI_FAULTS_WITH_KINDS = (
    "KNOWN_SITES = frozenset({'assign.dispatch', 'polish.dispatch'})\n"
    "KINDS = ('transient', 'stall')\n"
    "def inject(site):\n"
    "    pass\n"
)


def test_typod_chaos_kind_fires_both_directions(tmp_path):
    """A typo'd kind in a test's spec dict arms a plan that tests nothing
    (chaos-unknown-kind) AND leaves the real kind with no arming spec
    anywhere (chaos-unused-kind) — both directions must report."""
    findings = lint(tmp_path, {
        "faults.py": _MINI_FAULTS_WITH_KINDS,
        "plant.py": (
            "import faults\n"
            "def go():\n"
            "    faults.inject('assign.dispatch')\n"
            "    faults.inject('polish.dispatch')\n"
        ),
        "test_plan.py": (
            "SPECS = [\n"
            "    {'site': 'assign.dispatch', 'kind': 'transient'},\n"
            "    {'site': 'polish.dispatch', 'kind': 'stal'},\n"  # misspelled
            "]\n"
        ),
    })
    assert rules_of(findings) == {"chaos-unknown-kind", "chaos-unused-kind"}
    unknown = [f for f in findings if f.rule == "chaos-unknown-kind"]
    assert len(unknown) == 1 and "'stal'" in unknown[0].message
    unused = [f for f in findings if f.rule == "chaos-unused-kind"]
    assert len(unused) == 1 and "'stall'" in unused[0].message
    assert unused[0].path.endswith("faults.py")  # anchored at KINDS itself


def test_chaos_kind_handler_comparisons_checked_but_not_arming(tmp_path):
    """``spec.kind == X`` handler comparisons are validated against KINDS
    (a typo'd handler branch is dead code) but do NOT count as arming the
    kind — only spec literals / FaultSpec(kind=...) calls keep a kind
    'used'."""
    findings = lint(tmp_path, {
        "faults.py": _MINI_FAULTS_WITH_KINDS,
        "plant.py": (
            "import faults\n"
            "def go(spec):\n"
            "    faults.inject('assign.dispatch')\n"
            "    faults.inject('polish.dispatch')\n"
            "    if spec.kind == 'transinet':\n"  # dead handler branch
            "        pass\n"
            "    if spec.kind in ('transient', 'stall'):\n"
            "        pass\n"
        ),
        "test_plan.py": (
            "import faults\n"
            "SPECS = [{'site': 'assign.dispatch', 'kind': 'transient'}]\n"
            "ALSO = faults.FaultSpec(site='polish.dispatch', kind='stall')\n"
        ),
    })
    # the comparisons alone did not mark kinds used — the spec dict and
    # the FaultSpec call did; only the typo'd handler comparison reports
    assert rules_of(findings) == {"chaos-unknown-kind"}
    (bad,) = findings
    assert "'transinet'" in bad.message


def test_chaos_kind_parity_is_clean(tmp_path):
    findings = lint(tmp_path, {
        "faults.py": _MINI_FAULTS_WITH_KINDS,
        "plant.py": (
            "import faults\n"
            "def go():\n"
            "    faults.inject('assign.dispatch')\n"
            "    faults.inject('polish.dispatch')\n"
        ),
        "test_plan.py": (
            "SPECS = [\n"
            "    {'site': 'assign.dispatch', 'kind': 'transient'},\n"
            "    {'site': 'polish.dispatch', 'kind': 'stall'},\n"
            "]\n"
        ),
    })
    assert findings == []


# ---------------------------------------------------------------------------
# rule family 4: config-field cross-check

_MINI_CONFIG = (
    "import dataclasses\n"
    "@dataclasses.dataclass\n"
    "class RunConfig:\n"
    "    resume: bool = False\n"
    "    read_batch_size = None\n"
    "    @property\n"
    "    def cluster_identity(self):\n"
    "        return 0.93\n"
    "    def validate(self):\n"
    "        pass\n"
)


def test_config_field_typo_fires(tmp_path):
    findings = lint(tmp_path, {
        "config.py": _MINI_CONFIG,
        "use.py": (
            "from config import RunConfig\n"
            "def run(cfg: RunConfig):\n"
            "    return cfg.reusme\n"  # typo'd field
            "def load(d):\n"
            "    cfg = RunConfig.from_dict(d)\n"
            "    return cfg.read_batchsize\n"  # typo'd field
        ),
    })
    assert rules_of(findings) == {"config-unknown-field"}
    assert len(findings) == 2


def test_config_fields_properties_methods_are_clean(tmp_path):
    findings = lint(tmp_path, {
        "config.py": _MINI_CONFIG,
        "use.py": (
            "import dataclasses\n"
            "from config import RunConfig\n"
            "def run(cfg: RunConfig, untyped):\n"
            "    cfg2 = dataclasses.replace(cfg, resume=True)\n"
            "    ok = (cfg.resume, cfg.read_batch_size, cfg.cluster_identity,\n"
            "          cfg2.validate())\n"
            "    return ok, untyped.whatever\n"  # untyped: out of scope
        ),
    })
    assert findings == []


# ---------------------------------------------------------------------------
# unused-import + suppressions + output plumbing


def test_unused_import_fires_and_noqa_exempts(tmp_path):
    findings = lint(tmp_path, {"mod.py": (
        "import os\n"
        "import json  # noqa: F401  (re-exported)\n"
        "import sys\n"
        "print(sys.argv)\n"
    )})
    assert [f.rule for f in findings] == ["unused-import"]
    assert "`os`" in findings[0].message


def test_init_py_exempt_from_unused_import(tmp_path):
    findings = lint(tmp_path, {"pkg/__init__.py": "import os\n"})
    assert findings == []


def test_inline_and_file_suppressions(tmp_path):
    findings = lint(tmp_path, {
        "inline.py": (
            "import os  # graftlint: disable=unused-import\n"
        ),
        "whole_file.py": (
            "# graftlint: disable-file=bare-except\n"
            "def f(work):\n"
            "    try:\n"
            "        work()\n"
            "    except:\n"
            "        pass\n"
        ),
    })
    assert findings == []


def test_parse_error_reported(tmp_path):
    findings = lint(tmp_path, {"broken.py": "def f(:\n"})
    assert [f.rule for f in findings] == ["parse-error"]


def test_nul_byte_reported_as_parse_error(tmp_path):
    """ast.parse raises bare ValueError (not SyntaxError) on NUL bytes;
    a corrupted file must become a finding, not a linter traceback."""
    (tmp_path / "nul.py").write_bytes(b"x = 1\n\x00\n")
    findings = run_paths([str(tmp_path)])
    assert [f.rule for f in findings] == ["parse-error"]


def test_noqa_with_unrelated_code_does_not_exempt(tmp_path):
    """`# noqa: E501` on an unused import must still flag; only a bare
    noqa or an F401 code list is a re-export marker."""
    findings = lint(tmp_path, {"mod.py": (
        "import os  # noqa: E501\n"
        "import json  # noqa\n"
        "import abc  # noqa: E501, F401\n"
    )})
    assert [f.rule for f in findings] == ["unused-import"]
    assert "`os`" in findings[0].message


def test_sort_key_lambda_does_not_leak_taint(tmp_path):
    """A lambda's params are only traced INSIDE the lambda: a sort-key
    lambda reusing a static name must not poison later branches on it."""
    findings = lint(tmp_path, {"ok.py": (
        "import functools\n"
        "import jax\n"
        "import jax.numpy as jnp\n"
        "@functools.partial(jax.jit, static_argnames=('n',))\n"
        "def f(x, n):\n"
        "    order = sorted(range(3), key=lambda n: -n)\n"
        "    if n > 4:\n"
        "        return jnp.sum(x[:n]) + order[0]\n"
        "    return jnp.sum(x)\n"
    )})
    assert findings == []


def test_cli_exit_codes_and_json(tmp_path, capsys):
    (tmp_path / "bad.py").write_text("import os\n")
    assert graftlint_main([str(tmp_path)]) == 1
    assert graftlint_main(["--json", str(tmp_path)]) == 1
    out = capsys.readouterr().out
    assert '"unused-import"' in out and '"count": 1' in out
    (tmp_path / "bad.py").write_text("import os\nprint(os.sep)\n")
    assert graftlint_main([str(tmp_path)]) == 0
    assert graftlint_main([str(tmp_path / "nope")]) == 2


# ---------------------------------------------------------------------------
# rule family: donation-use-after-donate


def test_donation_use_after_donate_fires(tmp_path):
    findings = lint(tmp_path, {"bad.py": (
        "import jax\n"
        "step = jax.jit(lambda x: x + 1, donate_argnums=(0,))\n"
        "def go(buf):\n"
        "    out = step(buf)\n"
        "    print(buf.sum())\n"
        "    return out\n"
    )})
    assert rules_of(findings) == {"donation-use-after-donate"}
    (f,) = findings
    assert "`buf` was donated to `step` on line 4" in f.message


def test_donation_decorated_and_inline_forms_fire(tmp_path):
    findings = lint(tmp_path, {"bad.py": (
        "from functools import partial\n"
        "import jax\n"
        "@partial(jax.jit, donate_argnums=(1,))\n"
        "def step(carry, buf):\n"
        "    return carry + buf\n"
        "def go(c, buf):\n"
        "    out = step(c, buf)\n"
        "    inline = jax.jit(step, donate_argnums=(0,))(c, buf)\n"
        "    return out + inline + buf\n"
    )})
    # line 7 donates buf (decorated step, position 1); the line-8 inline
    # call loads it while poisoned AND line 9 loads it again — 2 findings
    assert sum(f.rule == "donation-use-after-donate" for f in findings) == 2
    assert {f.line for f in findings} == {8, 9}
    assert all("`buf` was donated" in f.message for f in findings)


def test_donation_rebind_and_reorder_are_clean(tmp_path):
    findings = lint(tmp_path, {"ok.py": (
        "import jax\n"
        "step = jax.jit(lambda x: x + 1, donate_argnums=(0,))\n"
        "def rebind(buf):\n"
        "    buf = step(buf)\n"
        "    return buf.sum()\n"
        "def reorder(buf):\n"
        "    total = buf.sum()\n"
        "    return step(buf), total\n"
        "def no_donation(buf):\n"
        "    g = jax.jit(lambda x: x)\n"
        "    out = g(buf)\n"
        "    return out, buf.sum()\n"
    )})
    assert findings == []


# ---------------------------------------------------------------------------
# rule family: recompile-hazard


def test_recompile_hazard_pad_to_and_jnp_shape_fire(tmp_path):
    findings = lint(tmp_path, {"bad.py": (
        "import jax.numpy as jnp\n"
        "def pad(xs, pad_batch):\n"
        "    n = max(len(x) for x in xs)\n"
        "    m = n + 7\n"
        "    z = jnp.zeros((m, 4))\n"
        "    return pad_batch(xs, pad_to=n), z\n"
    )})
    assert sum(f.rule == "recompile-hazard" for f in findings) == 2
    assert {f.line for f in findings} == {5, 6}


def test_recompile_hazard_quantizers_sanitize(tmp_path):
    findings = lint(tmp_path, {"ok.py": (
        "import jax.numpy as jnp\n"
        "DEFAULT_WIDTHS = (64, 128, 256)\n"
        "def pad(xs, pad_batch, pow2_ceil):\n"
        "    n = pow2_ceil(max(len(x) for x in xs))\n"
        "    w = next(w for w in DEFAULT_WIDTHS if w >= len(xs))\n"
        "    z = jnp.zeros((n, w))\n"
        "    return pad_batch(xs, pad_to=w)\n"
        "def host_ok(xs, np):\n"
        "    return np.zeros((len(xs), 4))\n"
    )})
    assert [f for f in findings if f.rule == "recompile-hazard"] == []


def test_recompile_hazard_taint_flows_into_branches(tmp_path):
    """Assignments inside compound statements poison sinks after them."""
    findings = lint(tmp_path, {"bad.py": (
        "import jax.numpy as jnp\n"
        "def f(xs, flag):\n"
        "    if flag:\n"
        "        n = len(xs)\n"
        "    else:\n"
        "        n = 8\n"
        "    return jnp.zeros(n)\n"
    )})
    assert sum(f.rule == "recompile-hazard" for f in findings) == 1


# ---------------------------------------------------------------------------
# rule family: lock-discipline


_LOCK_FIXTURE_HEADER = (
    "import threading\n"
    'LOCK_OWNERSHIP = {"Reg.counters": "_lock"}\n'
    "class Reg:\n"
    "    def __init__(self):\n"
    "        self._lock = threading.Lock()\n"
    "        self.counters = {}\n"
)


def test_lock_discipline_unlocked_mutations_fire(tmp_path):
    findings = lint(tmp_path, {"bad.py": _LOCK_FIXTURE_HEADER + (
        "    def bad(self, k):\n"
        "        self.counters[k] = 1\n"
        "        self.counters.update(a=2)\n"
        "        del self.counters[k]\n"
    )})
    assert sum(f.rule == "lock-discipline" for f in findings) == 3


def test_lock_discipline_locked_reads_and_conventions_clean(tmp_path):
    findings = lint(tmp_path, {"ok.py": _LOCK_FIXTURE_HEADER + (
        "    def good(self, k):\n"
        "        with self._lock:\n"
        "            self.counters[k] = 1\n"
        "            self.counters.update(a=2)\n"
        "    def read(self):\n"
        "        return len(self.counters)\n"
        "    def _bump_locked(self, k):\n"
        "        self.counters[k] = 1\n"
        "    def unowned(self):\n"
        "        self.other = {}\n"
    )})
    assert findings == []


def test_lock_discipline_wrong_lock_and_nested_def_fire(tmp_path):
    findings = lint(tmp_path, {"bad.py": _LOCK_FIXTURE_HEADER + (
        "    def wrong(self, k):\n"
        "        with self._other_lock:\n"
        "            self.counters[k] = 1\n"
        "    def deferred(self, k):\n"
        "        with self._lock:\n"
        "            def cb():\n"
        "                self.counters[k] = 1\n"
        "            return cb\n"
    )})
    # holding the WRONG lock doesn't count, and a nested def runs later
    # (possibly on another thread) so the held set must not flow in
    assert sum(f.rule == "lock-discipline" for f in findings) == 2


def test_lock_discipline_noop_without_ownership_table(tmp_path):
    findings = lint(tmp_path, {"free.py": (
        "class Reg:\n"
        "    def bad(self, k):\n"
        "        self.counters = {}\n"
    )})
    assert findings == []


# ---------------------------------------------------------------------------
# --baseline: known findings don't fail, new ones do


def _baseline_fixture(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import jax.numpy as jnp\n"
        "def f(xs):\n"
        "    n = len(xs)\n"
        "    return jnp.zeros(n)\n"
    )
    return bad


def test_baseline_suppresses_known_and_fails_new(tmp_path, capsys):
    _baseline_fixture(tmp_path)
    base = tmp_path / "baseline.json"
    assert graftlint_main([str(tmp_path), "--write-baseline", str(base)]) == 0
    # the recorded finding no longer fails the run...
    assert graftlint_main([str(tmp_path), "--baseline", str(base)]) == 0
    out = capsys.readouterr().out
    assert "[baselined]" in out
    # ...but a NEW finding still does, reported alongside the baselined one
    (tmp_path / "new.py").write_text("import os\n")
    assert graftlint_main([str(tmp_path), "--baseline", str(base)]) == 1
    out = capsys.readouterr().out
    assert "unused-import" in out and "[baselined]" in out


def test_baseline_stale_entry_reported_not_fatal(tmp_path, capsys):
    bad = _baseline_fixture(tmp_path)
    base = tmp_path / "baseline.json"
    assert graftlint_main([str(tmp_path), "--write-baseline", str(base)]) == 0
    bad.write_text("import jax.numpy as jnp\nprint(jnp)\n")  # fix the finding
    assert graftlint_main([str(tmp_path), "--baseline", str(base)]) == 0
    assert "stale baseline entry" in capsys.readouterr().err


def test_baseline_survives_line_drift(tmp_path):
    bad = _baseline_fixture(tmp_path)
    base = tmp_path / "baseline.json"
    assert graftlint_main([str(tmp_path), "--write-baseline", str(base)]) == 0
    bad.write_text("# a comment shifting every line\n" + bad.read_text())
    assert graftlint_main([str(tmp_path), "--baseline", str(base)]) == 0


def test_baseline_unreadable_is_usage_error(tmp_path, capsys):
    _baseline_fixture(tmp_path)
    assert graftlint_main(
        [str(tmp_path), "--baseline", str(tmp_path / "nope.json")]) == 2
    assert "cannot read baseline" in capsys.readouterr().err


def test_baseline_json_output_splits_new_and_known(tmp_path, capsys):
    import json as _json

    _baseline_fixture(tmp_path)
    base = tmp_path / "baseline.json"
    assert graftlint_main([str(tmp_path), "--write-baseline", str(base)]) == 0
    (tmp_path / "new.py").write_text("import os\n")
    capsys.readouterr()
    assert graftlint_main(
        ["--json", str(tmp_path), "--baseline", str(base)]) == 1
    body = _json.loads(capsys.readouterr().out)
    assert body["count"] == 1
    assert body["findings"][0]["rule"] == "unused-import"
    assert [f["rule"] for f in body["baselined"]] == ["recompile-hazard"]
    assert body["stale_baseline"] == []


# ---------------------------------------------------------------------------
# the shipped tree is clean (acceptance; known findings are baselined
# with justifications in tools/graftlint/baseline.json)


def test_shipped_tree_is_clean_modulo_baseline(monkeypatch):
    from tools.graftlint.core import apply_baseline, load_baseline

    # repo-relative paths: the baseline records findings exactly as the
    # tier-1 gate produces them (run from the repo root)
    monkeypatch.chdir(REPO)
    findings = run_paths(["ont_tcrconsensus_tpu", "tests", "scripts",
                          "tools"])
    known = load_baseline(
        os.path.join(REPO, "tools", "graftlint", "baseline.json"))
    new, baselined, stale = apply_baseline(findings, known)
    assert new == [], "\n".join(f.format() for f in new)
    # the baseline file is exact: no stale entries, and every entry
    # carries a human justification
    assert stale == set(), stale
    with open(os.path.join(REPO, "tools", "graftlint", "baseline.json"),
              encoding="utf-8") as fh:
        import json as _json

        body = _json.load(fh)
    assert all(e.get("justification") for e in body["findings"])
    assert len(baselined) == len(body["findings"])
