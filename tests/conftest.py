"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip sharding logic is exercised on host CPU devices
(XLA_FLAGS=--xla_force_host_platform_device_count=8) so tests run anywhere;
the driver separately dry-runs the multi-chip path via __graft_entry__.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The environment pre-sets JAX_PLATFORMS=axon (the TPU tunnel) and the axon
# plugin re-prepends itself over the env var, so the config API is the only
# reliable override: tests must run on the 8-device virtual CPU mesh, not
# hog the real chip.
import jax  # noqa: E402  (import after XLA_FLAGS is set)

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: reruns skip every jit/pallas compile (the
# suite is single-core CPU-bound; compiles are a large slice of a cold run).
_cache_dir = os.environ.get(
    "JAX_TEST_CACHE_DIR", os.path.join(os.path.dirname(__file__), ".jax_cache")
)
jax.config.update("jax_compilation_cache_dir", _cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
