"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip sharding logic is exercised on host CPU devices
(XLA_FLAGS=--xla_force_host_platform_device_count=8) so tests run anywhere;
the driver separately dry-runs the multi-chip path via __graft_entry__.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
