"""Round-1 SW fast path (assign._fused_pass sw_subset_denom; VERDICT r4 #4).

Round 1 skips base-level SW for sketch-confident reads and synthesizes the
three filter inputs (junk gate, ref span, region pick) from the sketch +
amplicon geometry; only the needy quarter of each batch is SW'd. These
tests pin:

  1. the calibration the fast path rests on — uniform-random junk and real
     simulated ONT reads separate by a wide cosine gap around
     SW_COS_CONFIDENT (the aligned-gate floor for non-SW'd rows);
  2. A/B end-to-end: run_assign with the fast path ON vs OFF admits the
     same survivors with the same region/strand/UMI outputs, and rejects
     injected junk in both modes;
  3. the sw_done contract: fast blocks mark synthesized rows False and
     the error profiler samples only SW-verified rows.

Reference semantics pinned: the round-1 filters are region_split.py:261-269
(ref-overlap + read-length window) and the minimap2 primary-alignment gate;
round 2 (minimap2_align.py:209-245 blast-id filter) never takes this path.
"""

import numpy as np
import pytest

from ont_tcrconsensus_tpu.cluster import regions
from ont_tcrconsensus_tpu.io import fastx, simulator
from ont_tcrconsensus_tpu.ops import encode, sketch
from ont_tcrconsensus_tpu.pipeline import assign as A

UMI_FWD = "TTTVVTTVVVVTTVVVVTTVVVVTTVVVVTTT"
UMI_REV = "AAABBBBAABBBBAABBBBAABBBBAABBAAA"


def _library(seed=73, num_regions=6):
    return simulator.simulate_library(
        seed=seed, num_regions=num_regions, molecules_per_region=(3, 4),
        reads_per_molecule=(2, 4), error_model=simulator.OntErrorModel(),
        with_adapters=True, region_len=(1100, 1400),
    )


def _panel(lib):
    res = regions.self_homology_map(lib.reference, cluster_threshold=0.93)
    return A.ReferencePanel.build(dict(lib.reference), res.region_cluster)


def _junk_records(rng, n, lens=(1200, 2200)):
    recs = []
    for i in range(n):
        seq = "".join(
            "ACGT"[b] for b in rng.integers(0, 4, int(rng.integers(*lens)))
        )
        recs.append(fastx.FastxRecord(f"junk{i}", "", seq, "I" * len(seq)))
    return recs


def test_cosine_separation_backs_the_confident_floor():
    """Junk tops out well under SW_COS_CONFIDENT; real reads stay well over.

    This is the measured basis for synthesizing the aligned gate without
    SW (see the calibration constants in pipeline/assign.py)."""
    lib = _library()
    panel = _panel(lib)
    rng = np.random.default_rng(11)

    real = [s for _, s, _ in lib.reads]
    junk = [r.sequence for r in _junk_records(rng, 60)]
    codes = [encode.encode_seq(s) for s in real + junk]
    c, lens = encode.pad_batch(codes, pad_value=encode.PAD_CODE, multiple=256)
    _, sc, _ = sketch.candidates_both_strands(
        np.asarray(c), np.asarray(lens), panel.d_profiles, top_k=2
    )
    cos1 = np.asarray(sc)[:, 0]
    real_min = cos1[: len(real)].min()
    junk_max = cos1[len(real):].max()
    # wide two-sided margin around the floor: the gate is robust to
    # simulator noise, not balanced on a knife edge
    assert junk_max < A.SW_COS_CONFIDENT - 0.05, junk_max
    assert real_min > A.SW_COS_CONFIDENT + 0.05, real_min


def _run(reads, panel, fast_denom):
    eng = A.AssignEngine(panel, UMI_FWD, UMI_REV, primers=[],
                         fast_denom=fast_denom)
    return A.run_assign(
        reads, eng, max_ee_rate=0.07, min_len=900,
        minimal_region_overlap=0.95, max_softclip_5_end=81,
        max_softclip_3_end=76, batch_size=128, max_read_length=4096,
    )


@pytest.mark.slow  # ~40s: the heaviest fast-vs-exact equivalence sweep;
# the non-slow tier keeps the cheaper done-mask/error-profile and cosine
# separation checks over the same engine
def test_fast_vs_exact_same_survivors_and_outputs():
    lib = _library(seed=91)
    panel = _panel(lib)
    rng = np.random.default_rng(5)
    reads = [
        fastx.FastxRecord(h.split()[0], "", s, q) for h, s, q in lib.reads
    ] + _junk_records(rng, 12)
    order = rng.permutation(len(reads))
    reads = [reads[i] for i in order]

    store_fast, stats_fast = _run(reads, panel, fast_denom=4)
    store_exact, stats_exact = _run(reads, panel, fast_denom=0)

    assert stats_fast.n_pass == stats_exact.n_pass
    # junk is rejected in BOTH modes (fast: cosine floor, exact: MIN_SCORE)
    for store in (store_fast, store_exact):
        for blk in store.blocks:
            assert not any(n.startswith("junk") for n in blk.names)

    def flat(store):
        out = {}
        for blk in store.blocks:
            for i, nm in enumerate(blk.names):
                out[nm] = (
                    int(blk.region_idx[i]), bool(blk.is_rev[i]),
                    int(blk.lens[i]),
                    tuple(int(blk.umi[k][i]) for k in sorted(blk.umi)),
                )
        return out

    assert flat(store_fast) == flat(store_exact)


@pytest.mark.slow  # ~36s: two full AssignEngine compiles over 256 reads.
# Tier-1 keeps single-device fast-vs-exact equivalence (this file) and
# sharded-vs-single parity for kernels/consensus/pileup (test_parallel);
# the mesh-layout filter-decision agreement reruns in the slow suite.
def test_sharded_fast_path_matches_single_device():
    """shard_map fast path over the 8-device mesh produces the same filter
    DECISIONS as the single-device fast path. The SW subset is selected
    per shard (top-k over each shard's rows), so sw_done/spans/raw scores
    legitimately differ between mesh layouts — what must agree is
    everything the host filters on: region pick, trim frame, gates, UMI
    locations."""
    import jax
    from jax.sharding import Mesh

    from ont_tcrconsensus_tpu.io import bucketing

    lib = _library(seed=29)
    panel = _panel(lib)
    recs = [
        fastx.FastxRecord(h.split()[0], "", s, q) for h, s, q in lib.reads
    ]
    recs = (recs * 4)[:256]
    batch = next(bucketing.batch_reads(recs, batch_size=256, widths=(2048,)))

    kw = dict(primers=[], fast_denom=4)
    eng1 = A.AssignEngine(panel, UMI_FWD, UMI_REV, **kw)
    mesh = Mesh(np.array(jax.devices()[:8]), ("data",))
    eng8 = A.AssignEngine(panel, UMI_FWD, UMI_REV, mesh=mesh, **kw)

    out1 = eng1.run_batch(batch, 0.07, 900, overlap_frac=0.95)
    out8 = eng8.run_batch(batch, 0.07, 900, overlap_frac=0.95)
    assert set(out1) == set(out8)
    # both layouts ran the subset fast path, not a degenerate full-SW
    assert 0 < int(out1["sw_done"].sum()) < len(out1["sw_done"])
    assert 0 < int(out8["sw_done"].sum()) < len(out8["sw_done"])
    for k in ("ridx", "lens", "t_start", "ee_ok", "is_rev",
              "d5", "s5", "e5", "d3", "s3", "e3", "start3"):
        np.testing.assert_array_equal(
            np.asarray(out1[k]), np.asarray(out8[k]), err_msg=k
        )
    np.testing.assert_array_equal(
        np.asarray(out1["score"]) >= A.MIN_SCORE,
        np.asarray(out8["score"]) >= A.MIN_SCORE,
    )


def test_sw_done_mask_and_error_profile_sampling():
    lib = _library(seed=17)
    panel = _panel(lib)
    reads = [
        fastx.FastxRecord(h.split()[0], "", s, q) for h, s, q in lib.reads
    ]
    store_fast, _ = _run(reads, panel, fast_denom=4)
    store_exact, _ = _run(reads, panel, fast_denom=0)

    fast_done = np.concatenate([b.sw_done for b in store_fast.blocks])
    exact_done = np.concatenate([b.sw_done for b in store_exact.blocks])
    assert exact_done.all()
    assert not fast_done.all(), "fast path SW'd every read — no win"
    # synthesized rows carry NaN blast-id; SW'd rows a real one
    for blk in store_fast.blocks:
        synth = ~blk.sw_done
        assert np.isnan(blk.blast_id[synth]).all()
        assert not np.isnan(blk.blast_id[blk.sw_done]).any()

    # the error profiler samples UNIFORMLY over all survivors (restricting
    # to SW'd rows would bias it toward the need-ranked hard quarter) but
    # keeps NaN synthesized blast-ids out of the blast histogram
    from ont_tcrconsensus_tpu.qc import error_profile

    n_total = sum(blk.num_reads for blk in store_fast.blocks)
    tags, _, tag_blast = error_profile.profile_store(
        store_fast, panel, sample_size=64
    )
    assert sum(tags.values()) == min(64, n_total)
    for counter in tag_blast.values():
        assert not any(np.isnan(b) for b in counter)
