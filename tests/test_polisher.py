"""Polisher model: shapes, training signal, pipeline adapter, serialization."""

import numpy as np
import pytest

from ont_tcrconsensus_tpu.models import polisher, train
from ont_tcrconsensus_tpu.ops import encode


def test_forward_shapes():
    params = polisher.init_params(0)
    feats = np.zeros((2, 64, polisher.FEATURE_DIM), np.float32)
    logits = np.asarray(polisher.apply_logits(params, feats))
    assert logits.shape == (2, 64, polisher.TOTAL_LOGITS)
    assert np.isfinite(logits).all()


def test_examples_are_consistent():
    ex = train.make_examples(seed=0, n_examples=4, template_len=128, width=256)
    assert ex.feats.shape[0] == 4
    assert ex.feats.shape[2] == polisher.FEATURE_DIM
    assert set(np.unique(ex.labels)).issubset(set(range(5)))
    assert set(np.unique(ex.ins_labels)).issubset(set(range(5)))
    # supervised positions exist and sit within the draft
    assert ex.mask.sum() > 100


@pytest.fixture(scope="module")
def trained():
    """ONE shared 60-step training run (suite-runtime budget: training
    dominated this module's cost, VERDICT r2 weak #5). 60 steps over a
    12-example pool still reaches loss ratio ~0.02 and held-out accuracy
    1.0 on CPU — enough signal for both assertions below."""
    return train.train(
        steps=60, batch_size=8, pool_examples=12, template_len=128, log_every=0
    )


def test_training_reduces_loss(trained):
    _, losses = trained
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])


def test_polish_draft_identity_when_confident(trained):
    # hand-build features where the pileup unanimously supports the draft
    params, _ = trained
    ex = train.make_examples(seed=7, n_examples=8, template_len=128, width=256)
    logits = np.asarray(polisher.apply_logits(params, ex.feats))
    pred = logits[..., : polisher.NUM_CLASSES].argmax(-1)
    m = ex.mask > 0
    acc = (pred[m] == ex.labels[m]).mean()
    assert acc > 0.97, acc


def test_save_load_roundtrip(tmp_path):
    params = polisher.init_params(3)
    path = tmp_path / "w.msgpack"
    polisher.save_params(params, path)
    back = polisher.load_params(str(path))
    flat_a = np.concatenate([np.ravel(x) for x in _leaves(params)])
    flat_b = np.concatenate([np.ravel(x) for x in _leaves(back)])
    np.testing.assert_array_equal(flat_a, flat_b)


def _leaves(tree):
    import jax

    return [np.asarray(x) for x in jax.tree_util.tree_leaves(tree)]


def test_pipeline_adapter_preserves_good_consensus():
    """The batched adapter: (C, S, W) cluster tile in, (C, W) drafts out."""
    params = polisher.init_params(0)
    rng = np.random.default_rng(0)
    from ont_tcrconsensus_tpu.io import simulator

    C, S, W = 3, 4, 256
    sub = np.full((C, S, W), encode.PAD_CODE, np.uint8)
    lens = np.zeros((C, S), np.int32)
    drafts = np.full((C, W), encode.PAD_CODE, np.uint8)
    dlens = np.zeros((C,), np.int32)
    for c in range(C):
        template = simulator._rand_seq(rng, 200)
        for i in range(S):
            s, _ = simulator.mutate(rng, template, 0.01, 0.005, 0.005)
            enc = encode.encode_seq(s)
            sub[c, i, : len(enc)] = enc
            lens[c, i] = len(enc)
        t = encode.encode_seq(template)
        drafts[c, : len(t)] = t
        dlens[c] = len(t)
    fn = polisher.make_pipeline_polisher(params)
    out, out_lens = fn(sub, lens, drafts, dlens)
    # untrained model may mutate covered positions, but shape/contract holds
    assert out.shape == (C, W)
    for c in range(C):
        assert 0 < out_lens[c] <= W
        assert (out[c, out_lens[c]:] == encode.PAD_CODE).all()
    # padding clusters stay empty
    sub0 = np.full((1, S, W), encode.PAD_CODE, np.uint8)
    out0, l0 = fn(sub0, np.zeros((1, S), np.int32),
                  np.full((1, W), encode.PAD_CODE, np.uint8), np.zeros((1,), np.int32))
    assert l0[0] == 0


def test_pileup_reuse_path_matches_recompute():
    """polish(pileup=<final converged pileup>) must produce output identical
    to the from-scratch recompute — the fast path the pipeline takes when
    consensus_clusters_batch exits via convergence."""
    from ont_tcrconsensus_tpu.io import simulator
    from ont_tcrconsensus_tpu.ops import consensus

    params = polisher.init_params(0)
    rng = np.random.default_rng(7)
    C, S, W = 2, 6, 256
    sub = np.full((C, S, W), encode.PAD_CODE, np.uint8)
    lens = np.zeros((C, S), np.int32)
    for c in range(C):
        template = simulator._rand_seq(rng, 180)
        for i in range(S):
            s, _ = simulator.mutate(rng, template, 0.01, 0.005, 0.005)
            enc = encode.encode_seq(s)
            sub[c, i, : len(enc)] = enc
            lens[c, i] = len(enc)

    drafts, dlens, final_pileup = consensus.consensus_clusters_batch(
        sub, lens, rounds=6, band_width=consensus.POLISH_BAND_WIDTH,
        keep_final_pileup=True,
    )
    assert final_pileup is not None, "deep-depth clusters must converge"

    fn = polisher.make_pipeline_polisher(params)
    out_fast, lens_fast = fn(sub, lens, drafts, dlens, pileup=final_pileup)
    out_slow, lens_slow = fn(sub, lens, drafts, dlens)
    np.testing.assert_array_equal(lens_fast, lens_slow)
    np.testing.assert_array_equal(out_fast, out_slow)


def test_two_pass_polish_contract():
    """iterations=2 re-piles against the first pass's output: the contract
    (shapes, PAD tail, empty clusters stay empty) must hold, and with the
    trained bundled weights a clean cluster must survive both passes
    unchanged (never-worse under iteration)."""
    params = polisher.load_default_params() or polisher.init_params(0)
    rng = np.random.default_rng(4)
    from ont_tcrconsensus_tpu.io import simulator

    C, S, W = 2, 5, 256
    sub = np.full((C, S, W), encode.PAD_CODE, np.uint8)
    lens = np.zeros((C, S), np.int32)
    drafts = np.full((C, W), encode.PAD_CODE, np.uint8)
    dlens = np.zeros((C,), np.int32)
    templates = []
    for c in range(C):
        template = simulator._rand_seq(rng, 200)
        templates.append(template)
        for i in range(S):
            enc = encode.encode_seq(template)  # clean subreads
            sub[c, i, : len(enc)] = enc
            lens[c, i] = len(enc)
        t = encode.encode_seq(template)
        drafts[c, : len(t)] = t
        dlens[c] = len(t)
    one = polisher.make_pipeline_polisher(params, iterations=1)
    two = polisher.make_pipeline_polisher(params, iterations=2)
    o1, l1 = one(sub, lens, drafts, dlens)
    o2, l2 = two(sub, lens, drafts, dlens)
    for c in range(C):
        t = encode.encode_seq(templates[c])
        assert l1[c] == len(t) and (o1[c, : l1[c]] == t).all()
        assert l2[c] == len(t) and (o2[c, : l2[c]] == t).all()
        assert (o2[c, l2[c]:] == encode.PAD_CODE).all()
    # empty cluster stays empty through both passes
    o0, l0 = two(
        np.full((1, S, W), encode.PAD_CODE, np.uint8),
        np.zeros((1, S), np.int32),
        np.full((1, W), encode.PAD_CODE, np.uint8),
        np.zeros((1,), np.int32),
    )
    assert l0[0] == 0


# ---------------------------------------------------------------------------
# v4: strand + quality features (VERDICT r4 #6)


def test_pileup_features_v4_channels():
    """The strand split and quality weighting must reflect the inputs:
    fwd/rev counts partition the plain counts, and a high-qual base vote
    carries more quality-weighted mass than a low-qual one."""
    import jax.numpy as jnp

    from ont_tcrconsensus_tpu.ops import consensus, pileup

    S, W = 4, 32
    draft = np.zeros(W, np.uint8)  # all A
    base_at = np.full((S, W), pileup.UNCOVERED, np.uint8)
    pos_at = np.full((S, W), -1, np.int32)
    base_at[:, :8] = 0          # four A votes on columns 0-7
    base_at[3, 4] = 2           # one dissenting G at column 4
    pos_at[:, :8] = np.arange(8)[None, :]
    quals = np.full((S, W), 10, np.uint8)
    quals[3, :] = 40            # the dissenter is high-quality
    is_rev = np.array([False, False, True, True])
    feats = np.asarray(consensus.pileup_features_v4(
        jnp.asarray(base_at), jnp.zeros((S, W), jnp.int32),
        jnp.zeros((S, W), jnp.uint8), jnp.asarray(draft),
        jnp.asarray(pos_at), jnp.asarray(quals), jnp.asarray(is_rev),
    ))
    assert feats.shape == (W, consensus.FEATURE_DIM_V4)
    assert np.isfinite(feats).all()
    # column 0: 2 fwd A + 2 rev A -> strand channels split the count
    assert np.isclose(feats[0, 0], np.log1p(2.0))   # fwd A
    assert np.isclose(feats[0, 5], np.log1p(2.0))   # rev A
    # column 4: A channel lost one vote to G on the rev strand
    assert np.isclose(feats[4, 5], np.log1p(1.0))   # rev A
    assert np.isclose(feats[4, 7], np.log1p(1.0))   # rev G
    # quality-weighted: G's single Q40 vote (4.0) outweighs each A's Q10
    qw_a, qw_g = feats[4, 10], feats[4, 12]
    assert np.expm1(qw_g) > np.expm1(qw_a) / 3  # 4.0 vs 3.0 total A mass
    # beyond the pileup: zero counts, finite
    assert (feats[8:, :10] == 0).all()


def test_make_examples_v4_shapes_and_signal():
    ex = train.make_examples(
        seed=3, n_examples=4, template_len=128, width=256, features="v4"
    )
    assert ex.feats.shape[2] == polisher.FEATURE_DIM_V4
    assert np.isfinite(ex.feats).all()
    # strand channels must both be populated across the pool (random
    # orientation) — all-zero rev counts would mean orientation never fired
    assert ex.feats[..., 5:10].sum() > 0
    assert ex.feats[..., 0:5].sum() > 0
    # quality-weighted channels carry mass wherever base counts do
    assert ex.feats[..., 10:14].sum() > 0


def test_v4_adapter_serves_and_gates(tmp_path):
    """A 25-dim params tree routes the v4 feature path end-to-end (tile ->
    pileup -> features -> logits -> splice), with and without quals."""
    from ont_tcrconsensus_tpu.io import simulator
    from ont_tcrconsensus_tpu.ops import consensus

    params = polisher.init_params(0, feature_dim=polisher.FEATURE_DIM_V4)
    assert polisher.params_feature_dim(params) == polisher.FEATURE_DIM_V4
    rng = np.random.default_rng(11)
    C, S, W = 2, 6, 256
    sub = np.full((C, S, W), encode.PAD_CODE, np.uint8)
    lens = np.zeros((C, S), np.int32)
    quals = np.zeros((C, S, W), np.uint8)
    strands = np.zeros((C, S), bool)
    for c in range(C):
        template = simulator._rand_seq(rng, 180)
        template_rc = simulator.revcomp(template)
        for i in range(S):
            r, q, is_rev = train._simulate_oriented_read(
                rng, template, template_rc, (0.01, 0.005, 0.005), None
            )
            sub[c, i, : len(r)] = r
            quals[c, i, : len(q)] = q
            lens[c, i] = len(r)
            strands[c, i] = is_rev
    drafts, dlens, final_pileup = consensus.consensus_clusters_batch(
        sub, lens, rounds=6, band_width=consensus.POLISH_BAND_WIDTH,
        keep_final_pileup=True,
    )
    assert final_pileup is not None and len(final_pileup) == 4
    fn = polisher.make_pipeline_polisher(params)
    # reuse path (pileup handed over) == recompute path, like the v1 test
    out_fast, lens_fast = fn(sub, lens, drafts, dlens, pileup=final_pileup,
                             quals=quals, strands=strands)
    out_slow, lens_slow = fn(sub, lens, drafts, dlens,
                             quals=quals, strands=strands)
    np.testing.assert_array_equal(lens_fast, lens_slow)
    np.testing.assert_array_equal(out_fast, out_slow)
    # no quals at all (FASTA serving): QUAL_FILL stands in, still runs
    out_nq, lens_nq = fn(sub, lens, drafts, dlens)
    assert (np.asarray(lens_nq) > 0).all()


def test_v4_weight_preference(tmp_path, monkeypatch):
    """serving_weights_path prefers v4 > v3 > v2 among existing files."""
    import os

    monkeypatch.setattr(polisher, "_WEIGHTS_DIR", str(tmp_path))
    monkeypatch.setattr(
        polisher, "DEFAULT_WEIGHTS", str(tmp_path / "polisher_v2.msgpack")
    )
    monkeypatch.setattr(polisher, "_WEIGHT_PREFERENCE", (
        str(tmp_path / "polisher_v4.msgpack"),
        str(tmp_path / "polisher_v3.msgpack"),
        str(tmp_path / "polisher_v2.msgpack"),
    ))
    polisher.save_params(polisher.init_params(0), tmp_path / "polisher_v2.msgpack")
    assert os.path.basename(polisher.serving_weights_path()) == "polisher_v2.msgpack"
    polisher.save_params(
        polisher.init_params(0, feature_dim=polisher.FEATURE_DIM_V4),
        tmp_path / "polisher_v4.msgpack",
    )
    # evidence gate: unevaluated v4 weights (no sibling _eval.json, e.g.
    # written mid-training) must NOT flip the served generation
    assert os.path.basename(polisher.serving_weights_path()) == "polisher_v2.msgpack"
    (tmp_path / "polisher_v4_eval.json").write_text("{}")
    assert os.path.basename(polisher.serving_weights_path()) == "polisher_v4.msgpack"
    back = polisher.load_params(polisher.serving_weights_path())
    assert polisher.params_feature_dim(back) == polisher.FEATURE_DIM_V4


def test_sample_depth_lowdepth_distribution():
    """lowdepth mode: ~70% of draws in 2-4 (the counts-contract regime),
    the rest 5..max; bounds always respected, incl. a caller-narrowed
    range (code-review r5)."""
    rng = np.random.default_rng(0)
    draws = [train.sample_depth(rng, (2, 8), "lowdepth") for _ in range(2000)]
    assert min(draws) >= 2 and max(draws) <= 8
    low = sum(d <= 4 for d in draws) / len(draws)
    assert 0.6 < low < 0.8, low
    # narrowed range excludes the low band entirely -> plain uniform
    draws5 = [train.sample_depth(rng, (5, 8), "lowdepth") for _ in range(200)]
    assert min(draws5) >= 5
    # uniform mode ignores the band
    draws_u = [train.sample_depth(rng, (2, 8), "uniform") for _ in range(200)]
    assert min(draws_u) >= 2 and max(draws_u) <= 8


def test_low_depth_specialist_pass_scope():
    """The depth-2 specialist must touch ONLY exactly-low_depth clusters:
    depth-3 (below the main gate) keeps the vote consensus verbatim, and
    deep clusters keep the main model's behavior with or without the
    specialist wired."""
    from ont_tcrconsensus_tpu.io import simulator
    from ont_tcrconsensus_tpu.ops import consensus

    main = polisher.init_params(0)  # 15-dim v1 main model
    low = polisher.init_params(1, feature_dim=polisher.FEATURE_DIM_V4)
    rng = np.random.default_rng(21)
    C, S, W = 3, 6, 256
    sub = np.full((C, S, W), encode.PAD_CODE, np.uint8)
    lens = np.zeros((C, S), np.int32)
    depths = [2, 3, 6]  # below-low, between, above-gate
    for c in range(C):
        template = simulator._rand_seq(rng, 180)
        for i in range(depths[c]):
            s, _ = simulator.mutate(rng, template, 0.02, 0.01, 0.01)
            e = encode.encode_seq(s)
            sub[c, i, : len(e)] = e
            lens[c, i] = len(e)
    drafts, dlens = consensus.consensus_clusters_batch(sub, lens)
    drafts, dlens = np.asarray(drafts), np.asarray(dlens)

    plain = polisher.make_pipeline_polisher(main, min_polish_depth=4)
    with_low = polisher.make_pipeline_polisher(
        main, min_polish_depth=4, low_depth_params=low
    )
    assert with_low.wants_v4  # specialist needs pos_at retained
    o_p, l_p = plain(sub, lens, drafts, dlens)
    o_l, l_l = with_low(sub, lens, drafts, dlens)
    # depth-3: below both the gate and the specialist -> identical vote
    np.testing.assert_array_equal(o_p[1], o_l[1])
    np.testing.assert_array_equal(o_p[2], o_l[2])  # deep: main model both
    # depth-2 with the plain adapter: untouched vote consensus
    assert l_p[0] == dlens[0] and (o_p[0, : l_p[0]] == drafts[0, : dlens[0]]).all()
    # POSITIVE proof the pass can fire (a regression that silently kills
    # low_mask would otherwise go unnoticed — code-review r5): with the
    # confidence gate dropped, an untrained specialist's argmax output
    # must actually change the depth-2 cluster, and only that cluster
    eager = polisher.make_pipeline_polisher(
        main, min_polish_depth=4, low_depth_params=low, min_confidence=0.0
    )
    o_e, l_e = eager(sub, lens, drafts, dlens)
    changed = not (
        l_e[0] == dlens[0] and (o_e[0, : l_e[0]] == drafts[0, : dlens[0]]).all()
    )
    assert changed, "depth-2 specialist never fired"
    np.testing.assert_array_equal(o_e[1], o_p[1])  # depth-3 still vote


def test_bf16_logits_shape_and_dtype():
    """The bf16 serving path produces fp32 logits of the same shape as the
    fp32 path (values certified separately by the exactness A/B)."""
    import jax.numpy as jnp

    params = polisher.init_params(length=32)
    feats = np.random.default_rng(0).random((2, 32, polisher.FEATURE_DIM))
    lo32 = polisher.apply_logits(params, jnp.asarray(feats, jnp.float32))
    lo16 = polisher.apply_logits(
        params, jnp.asarray(feats, jnp.float32), bf16=True
    )
    assert lo16.shape == lo32.shape
    assert lo16.dtype == jnp.float32
    # bf16 is an approximation of the fp32 logits, not garbage
    assert float(jnp.max(jnp.abs(lo16 - lo32))) < 0.5


def test_bf16_serving_gate(tmp_path, monkeypatch):
    """bf16_serving_certified: artifact-gated, per-backend, weights- and
    specialist-pinned, device-kind-pinned, and never on for CPU."""
    import json
    import os

    monkeypatch.setattr(polisher, "_WEIGHTS_DIR", str(tmp_path))
    served = os.path.basename(polisher.serving_weights_path())
    low = polisher._current_low_depth_basename()

    # no artifact -> off
    assert not polisher.bf16_serving_certified("tpu")
    # certifying artifact -> on for that backend (+ matching device kind)
    rec = {"backend": "tpu", "identical": True, "weights": served,
           "low_depth_weights": low, "device_kind": "TPU v5 lite",
           "min_polish_depth": 4}
    with open(tmp_path / "polisher_bf16_ab_tpu.json", "w") as fh:
        json.dump(rec, fh)
    assert polisher.bf16_serving_certified("tpu", "TPU v5 lite")
    assert not polisher.bf16_serving_certified("axon", "TPU v5 lite")
    # a DIFFERENT accelerator generation was never A/B'd -> off
    assert not polisher.bf16_serving_certified("tpu", "TPU v6e")
    # a different serving gate config (min_polish_depth) was never A/B'd
    assert not polisher.bf16_serving_certified(
        "tpu", "TPU v5 lite", min_polish_depth=2
    )
    # cpu is categorically off (bf16 emulation is slower there)
    with open(tmp_path / "polisher_bf16_ab_cpu.json", "w") as fh:
        json.dump({**rec, "backend": "cpu", "device_kind": "cpu"}, fh)
    assert not polisher.bf16_serving_certified("cpu", "cpu")
    # a failed A/B, a weights-generation change, or a low-depth specialist
    # change all invalidate the cert
    for bad in ({"identical": False},
                {"weights": "stale_generation.msgpack"},
                {"low_depth_weights": "other_specialist.msgpack"}):
        with open(tmp_path / "polisher_bf16_ab_tpu.json", "w") as fh:
            json.dump({**rec, **bad}, fh)
        assert not polisher.bf16_serving_certified("tpu", "TPU v5 lite"), bad
