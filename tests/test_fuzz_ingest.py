"""Differential ingest fuzzing (scripts/fuzz_ingest.py).

The native C++ parser and the pure-Python tolerant twin must agree
record-for-record and rejection-for-rejection on seeded byte-level corpus
mutations — no crash, no hang, no divergence. The 5-seed smoke runs in
tier-1; the >=1000-corpus campaign is slow-marked (acceptance: ISSUE 3).
"""

import importlib.util
import os

import pytest

from ont_tcrconsensus_tpu.io import native

_SCRIPT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                       "scripts", "fuzz_ingest.py")

pytestmark = pytest.mark.skipif(
    native.load() is None, reason="no C++ toolchain for the native parser"
)


def _load_fuzz():
    spec = importlib.util.spec_from_file_location("fuzz_ingest", _SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_fuzz_smoke_5_seeds(tmp_path):
    """Seeded 5-seed smoke (tier-1 budget: a few seconds)."""
    fuzz = _load_fuzz()
    failures = fuzz.run_campaign(list(range(5)), cases=12, tmp_dir=str(tmp_path))
    assert not failures, "\n".join(failures[:20])


def test_fuzz_targeted_gzip_truncation(tmp_path):
    """Every gzip truncation fraction of one corpus agrees across parsers
    (the mid-stream gzip mutation gets dedicated, deterministic coverage
    beyond its random draw in the campaign)."""
    fuzz = _load_fuzz()
    data = b"".join(b"@r%d\nACGTACGTACGTACGT\n+\nIIIIIIIIIIIIIIII\n" % i
                    for i in range(100))
    for pct in range(5, 100, 10):
        problems = fuzz.differential_check(
            data, str(tmp_path), gz=True, gz_truncate_frac=pct / 100.0)
        assert not problems, f"truncation at {pct}%: {problems}"


@pytest.mark.slow
def test_fuzz_full_campaign(tmp_path):
    """>=1000 seeded mutated corpora through both parsers (acceptance)."""
    fuzz = _load_fuzz()
    failures = fuzz.run_campaign(list(range(5)), cases=200, tmp_dir=str(tmp_path))
    assert not failures, "\n".join(failures[:50])


# --- sanitized replay (ISSUE 4): same differential corpus, ASan/UBSan build


def _run_sanitized(*args: str) -> "subprocess.CompletedProcess":
    import subprocess
    import sys

    return subprocess.run(
        [sys.executable, _SCRIPT, "--sanitized", *args],
        capture_output=True, text=True, timeout=240,
    )


def test_sanitized_fuzz_smoke():
    """A seeded corpus replays through the ASan/UBSan parser with zero
    sanitizer reports (any report aborts the child: nonzero exit). Skips
    itself (exit 0 + notice) when libasan is unavailable."""
    proc = _run_sanitized("--seeds", "1", "--cases", "10")
    assert proc.returncode == 0, proc.stderr[-2000:]
    # prove the replay actually ran sanitized (not the silent-skip path)
    if "skipping" not in proc.stderr:
        assert "sanitized replay" in proc.stderr, proc.stderr[-2000:]


@pytest.mark.slow
def test_sanitized_fuzz_full_campaign():
    """Full differential corpus through the instrumented parser
    (acceptance: zero sanitizer reports over the >=1000-corpus replay)."""
    proc = _run_sanitized("--seeds", "5", "--cases", "250")
    assert proc.returncode == 0, proc.stderr[-4000:]
