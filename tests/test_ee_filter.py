import numpy as np

from ont_tcrconsensus_tpu.ops import ee_filter, encode


def _np_expected_errors(qual_str):
    q = np.frombuffer(qual_str.encode(), dtype=np.uint8).astype(np.float64) - 33
    return float(np.sum(10.0 ** (-q / 10.0)))


def test_expected_errors_matches_numpy():
    quals = ["IIII", "!!!!", "5555555555", "I5I5I5"]
    batch, lengths = encode.phred_batch(quals)
    ee = np.asarray(ee_filter.expected_errors(batch, lengths))
    for i, q in enumerate(quals):
        np.testing.assert_allclose(ee[i], _np_expected_errors(q), rtol=1e-5)


def test_padding_does_not_leak():
    batch, lengths = encode.phred_batch(["!!", "!!!!"])
    ee = np.asarray(ee_filter.expected_errors(batch, lengths))
    # '!' is Q0 => perr 1.0 each
    np.testing.assert_allclose(ee, [2.0, 4.0], rtol=1e-5)


def test_ee_rate_mask_vsearch_semantics():
    # max_ee_rate 0.07, min_len 4 (scaled-down reference config values,
    # configs/run_config.json:6-7)
    quals = ["IIII", "!!!!", "III"]  # Q40 passes, Q0 fails, too short fails
    batch, lengths = encode.phred_batch(quals)
    mask = np.asarray(
        ee_filter.ee_rate_mask(batch, lengths, max_ee_rate=0.07, min_len=4)
    )
    assert mask.tolist() == [True, False, False]
