import numpy as np

from ont_tcrconsensus_tpu.ops import edit_distance, encode, sketch


def _lev(a, b):
    m, n = len(a), len(b)
    D = np.zeros((m + 1, n + 1), dtype=np.int64)
    D[:, 0] = np.arange(m + 1)
    D[0, :] = np.arange(n + 1)
    for i in range(1, m + 1):
        for j in range(1, n + 1):
            D[i, j] = min(
                D[i - 1, j - 1] + (a[i - 1] != b[j - 1]),
                D[i - 1, j] + 1,
                D[i, j - 1] + 1,
            )
    return int(D[m, n])


def _rand_seqs(rng, n, lo, hi):
    return [
        "".join(rng.choice(list("ACGT")) for _ in range(rng.integers(lo, hi)))
        for _ in range(n)
    ]


def test_pairwise_matches_numpy():
    rng = np.random.default_rng(0)
    a = _rand_seqs(rng, 16, 50, 70)
    b = _rand_seqs(rng, 16, 50, 70)
    ab, al = encode.encode_batch(a)
    bb, bl = encode.encode_batch(b)
    d = np.asarray(edit_distance.pairwise(ab, al, bb, bl))
    for i in range(16):
        assert d[i] == _lev(a[i], b[i]), i


def test_many_vs_many_matches_numpy():
    rng = np.random.default_rng(1)
    q = _rand_seqs(rng, 6, 56, 68)
    t = _rand_seqs(rng, 5, 56, 68)
    qb, ql = encode.encode_batch(q)
    tb, tl = encode.encode_batch(t)
    D = np.asarray(edit_distance.many_vs_many(qb, ql, tb, tl))
    for i in range(6):
        for j in range(5):
            assert D[i, j] == _lev(q[i], t[j]), (i, j)


def test_identity_of_mutated_umis():
    # a UMI with 2 substitutions over 60nt: identity = 1 - 2/60 ~ 0.967
    rng = np.random.default_rng(2)
    u = "".join(rng.choice(list("ACGT")) for _ in range(60))
    v = u[:10] + ("A" if u[10] != "A" else "C") + u[11:30] + (
        "G" if u[30] != "G" else "T"
    ) + u[31:]
    ub, ul = encode.encode_batch([u])
    vb, vl = encode.encode_batch([v])
    ident = np.asarray(edit_distance.identity_matrix(ub, ul, vb, vl))[0, 0]
    np.testing.assert_allclose(ident, 1 - 2 / 60, rtol=1e-6)
    # pipeline thresholds: joins at 0.93, separate at 0.97
    assert ident > 0.93 and ident < 0.97


def test_kmer_prefilter_ranks_true_match_first():
    rng = np.random.default_rng(3)
    targets = _rand_seqs(rng, 32, 56, 68)
    # queries are lightly mutated copies of targets
    q_idx = [3, 17, 30]
    queries = []
    for i in q_idx:
        t = list(targets[i])
        for pos in rng.integers(0, len(t), 2):
            t[pos] = rng.choice(list("ACGT"))
        queries.append("".join(t))
    qb, ql = encode.encode_batch(queries)
    tb, tl = encode.encode_batch(targets)
    qp = sketch.kmer_profile(qb, ql, k=4, dim=None)
    tp = sketch.kmer_profile(tb, tl, k=4, dim=None)
    cand = np.asarray(sketch.top_candidates(qp, tp, top_k=4))
    for row, i in enumerate(q_idx):
        assert i in cand[row], (row, i, cand[row])


def test_empty_vs_nonempty():
    ab, al = encode.encode_batch(["ACGT"])
    bb, bl = encode.encode_batch(["ACGT"])
    bl0 = np.array([0], dtype=np.int32)
    d = np.asarray(edit_distance.pairwise(ab, al, bb, bl0))
    assert d[0] == 4
