import numpy as np

from ont_tcrconsensus_tpu.ops import edit_distance, encode, sketch


def _lev(a, b):
    m, n = len(a), len(b)
    D = np.zeros((m + 1, n + 1), dtype=np.int64)
    D[:, 0] = np.arange(m + 1)
    D[0, :] = np.arange(n + 1)
    for i in range(1, m + 1):
        for j in range(1, n + 1):
            D[i, j] = min(
                D[i - 1, j - 1] + (a[i - 1] != b[j - 1]),
                D[i - 1, j] + 1,
                D[i, j - 1] + 1,
            )
    return int(D[m, n])


def _rand_seqs(rng, n, lo, hi):
    return [
        "".join(rng.choice(list("ACGT")) for _ in range(rng.integers(lo, hi)))
        for _ in range(n)
    ]


def test_pairwise_matches_numpy():
    rng = np.random.default_rng(0)
    a = _rand_seqs(rng, 16, 50, 70)
    b = _rand_seqs(rng, 16, 50, 70)
    ab, al = encode.encode_batch(a)
    bb, bl = encode.encode_batch(b)
    d = np.asarray(edit_distance.pairwise(ab, al, bb, bl))
    for i in range(16):
        assert d[i] == _lev(a[i], b[i]), i


def test_many_vs_many_matches_numpy():
    rng = np.random.default_rng(1)
    q = _rand_seqs(rng, 6, 56, 68)
    t = _rand_seqs(rng, 5, 56, 68)
    qb, ql = encode.encode_batch(q)
    tb, tl = encode.encode_batch(t)
    D = np.asarray(edit_distance.many_vs_many(qb, ql, tb, tl))
    for i in range(6):
        for j in range(5):
            assert D[i, j] == _lev(q[i], t[j]), (i, j)


def test_identity_of_mutated_umis():
    # a UMI with 2 substitutions over 60nt: identity = 1 - 2/60 ~ 0.967
    rng = np.random.default_rng(2)
    u = "".join(rng.choice(list("ACGT")) for _ in range(60))
    v = u[:10] + ("A" if u[10] != "A" else "C") + u[11:30] + (
        "G" if u[30] != "G" else "T"
    ) + u[31:]
    ub, ul = encode.encode_batch([u])
    vb, vl = encode.encode_batch([v])
    ident = np.asarray(edit_distance.identity_matrix(ub, ul, vb, vl))[0, 0]
    np.testing.assert_allclose(ident, 1 - 2 / 60, rtol=1e-6)
    # pipeline thresholds: joins at 0.93, separate at 0.97
    assert ident > 0.93 and ident < 0.97


def test_kmer_prefilter_ranks_true_match_first():
    rng = np.random.default_rng(3)
    targets = _rand_seqs(rng, 32, 56, 68)
    # queries are lightly mutated copies of targets
    q_idx = [3, 17, 30]
    queries = []
    for i in q_idx:
        t = list(targets[i])
        for pos in rng.integers(0, len(t), 2):
            t[pos] = rng.choice(list("ACGT"))
        queries.append("".join(t))
    qb, ql = encode.encode_batch(queries)
    tb, tl = encode.encode_batch(targets)
    qp = sketch.kmer_profile(qb, ql, k=4, dim=None)
    tp = sketch.kmer_profile(tb, tl, k=4, dim=None)
    cand = np.asarray(sketch.top_candidates(qp, tp, top_k=4))
    for row, i in enumerate(q_idx):
        assert i in cand[row], (row, i, cand[row])


def test_empty_vs_nonempty():
    ab, al = encode.encode_batch(["ACGT"])
    bb, bl = encode.encode_batch(["ACGT"])
    bl0 = np.array([0], dtype=np.int32)
    d = np.asarray(edit_distance.pairwise(ab, al, bb, bl0))
    assert d[0] == 4


def _dovetail_oracle(a: str, b: str, k: int = 8) -> int:
    """O(nm) oracle: min over all cells of D[i][j] + relu overhangs."""
    m, n = len(a), len(b)
    D = np.zeros((m + 1, n + 1), dtype=np.int64)
    D[:, 0] = np.maximum(np.arange(m + 1) - k, 0)
    D[0, :] = np.maximum(np.arange(n + 1) - k, 0)
    for i in range(1, m + 1):
        for j in range(1, n + 1):
            D[i, j] = min(
                D[i - 1, j - 1] + (a[i - 1] != b[j - 1]),
                D[i - 1, j] + 1,
                D[i, j - 1] + 1,
            )
    tail_a = np.maximum(m - np.arange(m + 1) - k, 0)[:, None]
    tail_b = np.maximum(n - np.arange(n + 1) - k, 0)[None, :]
    return int((D + tail_a + tail_b).min())


def test_pairwise_dovetail_matches_oracle():
    rng = np.random.default_rng(7)
    a = _rand_seqs(rng, 24, 40, 80)
    b = _rand_seqs(rng, 24, 40, 80)
    # include boundary-fuzz pairs: same core, ragged ends
    core = _rand_seqs(rng, 8, 56, 64)
    for c in core:
        a.append("GG" + c)
        b.append(c + "TTA")
    ca, la = encode.encode_batch(a, pad_to=96)
    cb, lb = encode.encode_batch(b, pad_to=96)
    got = np.asarray(edit_distance.pairwise_dovetail(ca, la, cb, lb))
    want = [_dovetail_oracle(x, y) for x, y in zip(a, b)]
    assert got.tolist() == want


def test_dovetail_frees_boundary_fuzz_but_counts_internal_errors():
    core = "ACGTTGCA" * 8  # 64 nt
    mutated = core[:30] + "T" + core[31:]  # one internal substitution
    ca, la = encode.encode_batch(["AGT" + core], pad_to=96)
    cb, lb = encode.encode_batch([mutated + "CC"], pad_to=96)
    d = int(np.asarray(edit_distance.pairwise_dovetail(ca, la, cb, lb))[0])
    assert d == 1  # terminal fuzz free, internal sub counted
    # degenerate empty overlap is NOT free for long sequences
    ca, la = encode.encode_batch(["A" * 64], pad_to=96)
    cb, lb = encode.encode_batch(["C" * 64], pad_to=96)
    d = int(np.asarray(edit_distance.pairwise_dovetail(ca, la, cb, lb))[0])
    assert d >= 64 - 2 * 8 - 8
