"""Serve-plane load harness + chaos drills (scripts/serve_load.py).

Unit half (sub-second): seeded-mix determinism, the exact rejection-
accounting invariants, report schema validation, the torn-journal chaos
degradation, the retry/poison ladder, retry-backoff pop order, the new
live-gauge / per-reason metrics families, and the serving-SLO load gate
(obs/history.evaluate_load_gate + perf_gate's additive ``load`` key).

Smoke half (a few seconds, in-process stub daemon): the full smoke
scenario — seeded mix accounting, exact saturation 429s, one mid-drain
503, journal -> restart -> every accepted job completes — plus an
in-process induced-crash drill (flight recorder + journal) and the
slice-packed scenario (>= 2 tenants resident at once on disjoint
slices). The tier-1 load-smoke stage (scripts/tier1.sh) runs the same
scenarios as scripts.

E2e half (slow-marked): the subprocess crash/drain drills with the real
pipeline and artifact byte-identity against an uninterrupted run.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO_ROOT, "scripts"))

import serve_load  # noqa: E402

from ont_tcrconsensus_tpu.obs import history  # noqa: E402
from ont_tcrconsensus_tpu.obs import metrics as obs_metrics  # noqa: E402
from ont_tcrconsensus_tpu.parallel.budget import BudgetModel  # noqa: E402
from ont_tcrconsensus_tpu.robustness import faults  # noqa: E402
from ont_tcrconsensus_tpu.serve import queue as queue_mod  # noqa: E402

PERF_GATE = os.path.join(REPO_ROOT, "scripts", "perf_gate.py")

# a syntactically valid template; config validation never stats the
# filesystem, so the stub-runner control-plane tests need no dataset
_BASE = {"reference_file": "r.fa", "fastq_pass_dir": "fq"}


@pytest.fixture(autouse=True)
def _no_chaos_bleed():
    yield
    faults.disarm()


# --- deterministic schedule ---------------------------------------------------


def test_schedule_is_a_pure_function_of_seed_and_mix():
    mix = serve_load.parse_mix("ok=4,over_budget=2,oversized_body=1")
    a = serve_load.build_schedule(3, mix, 2.0)
    b = serve_load.build_schedule(3, mix, 2.0)
    assert a == b
    assert serve_load.build_schedule(4, mix, 2.0) != a


def test_schedule_carries_the_exact_mix_multiset_in_window():
    mix = {"ok": 3, "invalid_config": 2}
    sched = serve_load.build_schedule(0, mix, 1.5)
    kinds = sorted(s["kind"] for s in sched)
    assert kinds == ["invalid_config", "invalid_config", "ok", "ok", "ok"]
    offsets = [s["t"] for s in sched]
    assert offsets == sorted(offsets)
    assert all(0.0 <= t < 1.5 for t in offsets)


def test_parse_mix_rejects_unknown_kind_and_empty():
    with pytest.raises(ValueError, match="unknown mix kind"):
        serve_load.parse_mix("ok=1,no_such_kind=2")
    with pytest.raises(ValueError, match="no submissions"):
        serve_load.parse_mix("ok=0")


def test_payloads_provoke_their_refusals():
    obj, _ = serve_load.payload_for("over_budget", _BASE)
    assert obj["read_batch_size"] == 1 << 24
    obj, _ = serve_load.payload_for("invalid_config", _BASE)
    assert any(k not in _BASE for k in obj)
    _, raw = serve_load.payload_for("oversized_body", _BASE)
    assert len(raw) > (1 << 20)


def test_percentile_nearest_rank():
    vals = [5.0, 1.0, 3.0, 2.0, 4.0]
    assert serve_load.percentile(vals, 50) == 3.0
    assert serve_load.percentile(vals, 99) == 5.0
    assert serve_load.percentile([7.0], 50) == 7.0
    assert serve_load.percentile([], 50) is None


# --- exact accounting ---------------------------------------------------------


def _sound_report(**over):
    report = {
        "schema": 1, "source": "serve_load", "scenario": "smoke", "seed": 0,
        "submitted": 10, "accepted": 6, "completed": 4, "poisoned": 1,
        "failed": 0, "journaled_remaining": 1,
        "rejected_by_reason": {"queue_full": 3, "invalid_config": 1},
        "wait_s": {"p50": 0.1, "p99": 0.2},
        "first_stage_s": {"p50": None, "p99": None},
        "invariants": [],
    }
    report.update(over)
    return report


def test_invariants_hold_on_a_sound_ledger():
    assert serve_load.check_invariants(_sound_report()) == []


def test_invariants_catch_unaccounted_submissions():
    bad = serve_load.check_invariants(_sound_report(submitted=11))
    assert len(bad) == 1 and "submitted (11)" in bad[0]


def test_invariants_catch_lost_accepted_jobs():
    bad = serve_load.check_invariants(_sound_report(completed=3))
    assert len(bad) == 1 and "accepted (6)" in bad[0]


def test_report_schema_validates_and_names_holes():
    assert serve_load.validate_report(_sound_report()) == []
    missing = _sound_report()
    del missing["rejected_by_reason"]
    missing["wait_s"] = {"p50": 0.1}
    problems = serve_load.validate_report(missing)
    assert any("rejected_by_reason" in p for p in problems)
    assert any("wait_s missing 'p99'" in p for p in problems)


def test_ledger_reason_prefers_body_then_status_map():
    led = serve_load.Ledger()
    led.record("ok", 202, {"id": "job-1"})
    led.record("ok", 429, {"error": "queue_full"})
    led.record("ok", 413, {})          # no body reason -> status map
    led.record("ok", 500, {})          # unknown status -> http_500
    assert led.submitted == 4 and led.accepted == 1
    assert led.accepted_ids == ["job-1"]
    assert led.rejected_by_reason == {
        "queue_full": 1, "body_too_large": 1, "http_500": 1}


# --- torn-journal chaos (satellite a) ----------------------------------------


def _job(jid="job-0001", raw=None):
    return queue_mod.Job(id=jid, raw=dict(raw or _BASE),
                         submitted_t=time.time())


def test_torn_journal_degrades_to_named_warning_and_empty_queue(
        tmp_path, capsys):
    state = str(tmp_path / "state")
    faults.arm([{"site": "serve.journal_write", "kind": "torn"}])
    path = queue_mod.write_journal(state, [_job()])
    faults.disarm()
    # the tear hit the FINAL path with half the payload — not valid JSON
    with open(path) as fh:
        torn = fh.read()
    with pytest.raises(ValueError):
        json.loads(torn)
    assert queue_mod.load_journal(state) == []
    err = capsys.readouterr().err
    assert "torn/unreadable drain journal" in err
    assert os.path.exists(path + ".bad")       # evidence quarantined
    assert not os.path.exists(path)            # restart path is clean
    # a second restart does not re-trip (the journal is simply absent)
    assert queue_mod.load_journal(state) == []


def test_journal_write_is_atomic_and_fsynced(tmp_path):
    state = str(tmp_path / "state")
    path = queue_mod.write_journal(state, [_job(), _job("job-0002")])
    with open(path) as fh:
        payload = json.load(fh)
    assert [j["id"] for j in payload["jobs"]] == ["job-0001", "job-0002"]
    assert not os.path.exists(path + ".tmp")
    # garbage that is valid JSON but the wrong shape also degrades
    with open(path, "w") as fh:
        json.dump({"schema": 1, "jobs": "not-a-list"}, fh)
    assert queue_mod.load_journal(state) == []
    assert os.path.exists(path + ".bad")


# --- retry/poison ladder (tentpole hardening) --------------------------------


def _daemon(tmp_path, **kw):
    from ont_tcrconsensus_tpu.serve.daemon import Daemon

    return Daemon(dict(_BASE), port=0, state_dir=str(tmp_path / "state"),
                  queue_max=4, do_prewarm=False, **kw)


def test_transient_failures_requeue_with_backoff_then_poison(tmp_path):
    d = _daemon(tmp_path)
    job = _job()
    exc = faults.TransientChaosError("UNAVAILABLE: injected")
    out1 = d._failure_outcome(job, exc)
    assert out1.state == "retry" and job.attempts == 1
    assert d.queue.pending == [job]
    assert job.not_before > time.monotonic()   # backoff gate armed
    d.queue.pending.clear()
    out2 = d._failure_outcome(job, exc)
    assert out2.state == "retry" and job.attempts == 2
    d.queue.pending.clear()
    # third strike: retry budget (retry_max_attempts=3) exhausted
    out3 = d._failure_outcome(job, exc)
    assert out3.state == "poisoned"
    assert "retry_exhausted" in out3.error
    entries = queue_mod.load_poison(str(tmp_path / "state"))
    assert len(entries) == 1
    assert entries[0]["classification"] == "retry_exhausted"
    assert entries[0]["attempts"] == 3
    assert entries[0]["raw"] == _BASE


def test_fatal_and_oom_poison_immediately(tmp_path):
    d = _daemon(tmp_path)
    out = d._failure_outcome(_job("job-0001"), ValueError("deterministic"))
    assert out.state == "poisoned" and "fatal" in out.error
    out = d._failure_outcome(_job("job-0002"),
                             faults.OomChaosError("RESOURCE_EXHAUSTED"))
    assert out.state == "poisoned" and "oom" in out.error
    classifications = [e["classification"] for e in
                      queue_mod.load_poison(str(tmp_path / "state"))]
    assert classifications == ["fatal", "oom"]
    assert d.queue.pending == []               # nothing re-enters the queue


def test_backing_off_job_never_stalls_later_arrivals():
    q = queue_mod.JobQueue(4, BudgetModel(8.0))
    slow, quick = _job("job-slow"), _job("job-quick")
    q.requeue_back(slow, delay_s=30.0)
    q.requeue_back(quick, delay_s=0.0)
    assert q.pop(timeout=0.2) is quick         # FIFO among ELIGIBLE only
    assert q.pop(timeout=0.05) is None         # slow still gated
    slow.not_before = 0.0
    assert q.pop(timeout=0.2) is slow


# --- metrics families (satellite b) ------------------------------------------


def test_live_gauge_and_reject_reason_families():
    reg = obs_metrics.MetricsRegistry()
    reg.gauge_set("serve.queue_depth", 5)
    reg.gauge_set("serve.queue_depth", 2)
    reg.reject_add("queue_full")
    reg.reject_add("queue_full")
    reg.reject_add("draining")
    summary = reg.summary()
    assert summary["gauges_live"]["serve.queue_depth"] == 2.0   # last value
    assert summary["gauges"]["serve.queue_depth"] == 5.0        # high water
    assert summary["serve_rejected_by_reason"] == {
        "draining": 1, "queue_full": 2}
    lines = reg.prometheus_lines()
    assert 'tcr_gauge_current{site="serve.queue_depth"} 2' in lines
    assert 'tcr_serve_rejected_total{reason="queue_full"} 2' in lines
    assert 'tcr_serve_rejected_total{reason="draining"} 1' in lines


# --- serving-SLO load gate (permanence) --------------------------------------


def _load_entry(p99=2.0, rps=50.0, fp="f0", n_reads=100):
    return history.build_entry(
        "serve_load", fingerprint=fp, sha=None, backend="cpu",
        n_reads=n_reads, reads_per_sec=rps, warmup_s=1.0,
        extra={"p99_wait_s": p99})


def test_load_gate_warns_without_history():
    res = history.evaluate_load_gate([_load_entry()][:0])
    assert res.status == "warn" and "not armed" in res.reason
    res = history.evaluate_load_gate(
        [_load_entry()], {"source": "bench", "reads_per_sec": 1.0})
    assert res.status == "warn" and "not load-gated" in res.reason


def test_load_gate_passes_within_noise_and_fails_regressions():
    baseline = [_load_entry(p99=2.0 + 0.01 * i) for i in range(3)]
    ok = history.evaluate_load_gate(baseline + [_load_entry(p99=2.05)])
    assert ok.status == "pass"
    slow = history.evaluate_load_gate(baseline + [_load_entry(p99=9.0)])
    assert slow.status == "fail" and slow.metric == "p99_wait_s"
    starved = history.evaluate_load_gate(baseline + [_load_entry(rps=5.0)])
    assert starved.status == "fail" and starved.metric == "reads_per_sec"
    # a different workload shape is a different baseline pool -> thin/warn
    other = history.evaluate_load_gate(baseline + [_load_entry(n_reads=999)])
    assert other.status == "warn"


def test_perf_gate_json_carries_one_object_with_load_key(tmp_path):
    ledger = tmp_path / "ledger.jsonl"
    with open(ledger, "w") as fh:
        for e in ([_load_entry(p99=2.0 + 0.01 * i) for i in range(3)]
                  + [_load_entry(p99=2.02)]):
            fh.write(json.dumps(e) + "\n")
    proc = subprocess.run(
        [sys.executable, PERF_GATE, str(ledger), "--json"],
        capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr
    body = json.loads(proc.stdout)             # ONE object, additive keys
    assert body["load"]["status"] == "pass"
    assert "transfer" in body and "status" in body


# --- in-process smoke + crash drills -----------------------------------------


def test_smoke_scenario_exact_accounting_and_resume(tmp_path):
    out = tmp_path / "load_report.json"
    rc = serve_load.main([
        "--scenario", "smoke", "--seed", "7",
        "--mix", "ok=2,over_budget=1,invalid_config=1,oversized_body=1",
        "--period-s", "0.3", "--stub-job-s", "0.02",
        "--queue-max", "2", "--burst", "4",
        "--workdir", str(tmp_path / "w"), "--out", str(out),
    ])
    assert rc == 0
    report = json.loads(out.read_text())
    assert report["invariants"] == []
    assert serve_load.validate_report(report) == []
    assert report["rejected_by_reason"]["queue_full"] == 2   # burst 4 - max 2
    assert report["rejected_by_reason"]["over_budget"] == 1
    assert report["rejected_by_reason"]["invalid_config"] == 1
    assert report["rejected_by_reason"]["body_too_large"] == 1
    assert report["rejected_by_reason"]["draining"] == 1
    assert report["drills"]["mid_drain_503"] == 1
    assert report["drills"]["saturation"]["queue_full_429"] == 2
    assert report["drills"]["resume"]["journal_consumed"]
    assert (report["drills"]["resume"]["completed_after_restart"]
            == report["drills"]["drain"]["journaled"] == 2)
    assert report["drills"]["metrics"]["live_queue_depth_gauge"]
    assert report["drills"]["metrics"]["serve_rejected_total"] >= 1


def test_packed_scenario_concurrent_residency_and_exact_accounting(tmp_path):
    """The slice-pack load arm: >= 2 tenants provably resident AT ONCE
    on disjoint slices, tenant labels live on /metrics while packed, and
    the same exact ledger as every other scenario."""
    out = tmp_path / "load_report.json"
    ledger_path = tmp_path / "ledger.jsonl"
    rc = serve_load.main([
        "--scenario", "packed", "--seed", "11",
        "--mix", "ok=3,over_budget=1",
        "--period-s", "0.2", "--stub-job-s", "0.02",
        "--queue-max", "4", "--workers", "2",
        "--workdir", str(tmp_path / "w"), "--out", str(out),
        "--ledger", str(ledger_path),
    ])
    assert rc == 0
    report = json.loads(out.read_text())
    assert report["invariants"] == []
    assert serve_load.validate_report(report) == []
    packed = report["drills"]["packed"]
    assert packed["resident_high_water"] >= 2
    assert packed["disjoint_slices"] is True
    assert len(packed["overlap_observed"]) >= 2
    rej = sum(report["rejected_by_reason"].values())
    assert report["submitted"] == report["accepted"] + rej
    assert report["rejected_by_reason"]["over_budget"] == 1
    assert report["completed"] == report["accepted"] == 3
    assert report["drills"]["metrics"]["resident_jobs_gauge"]
    assert report["drills"]["metrics"]["slice_busy_tenant_labels"] >= 2
    assert packed["exit_code"] == 0
    # the appended ledger entry is ACCEPTED by the load gate: a packed
    # entry gates p99 wait like any serve_load entry (reads_per_sec is
    # None under the stub runner, so that metric is simply not gated)
    entries = [json.loads(line)
               for line in ledger_path.read_text().splitlines()]
    assert entries and entries[-1]["source"] == "serve_load"
    assert entries[-1]["scenario"] == "packed"
    assert entries[-1]["resident_high_water"] >= 2
    pool = [dict(entries[-1]) for _ in range(3)] + entries
    res = history.evaluate_load_gate(pool)
    assert res.status in ("pass", "warn"), res.reason


def test_inprocess_crash_flushes_flight_recorder_and_journals(
        tmp_path, monkeypatch):
    from ont_tcrconsensus_tpu.pipeline import run as run_mod

    monkeypatch.setattr(run_mod, "run_with_config",
                        lambda cfg: {"barcode01": {}})
    state = str(tmp_path / "state")
    d = _daemon(tmp_path)
    assert d.submit({})[0] == 202
    assert d.submit({})[0] == 202
    faults.arm([{"site": "serve.daemon_loop", "kind": "error",
                 "message": "induced loop crash"}])
    box = {}

    def _run():
        try:
            d.serve_forever()
        except RuntimeError as exc:
            box["error"] = str(exc)

    th = threading.Thread(target=_run, daemon=True)
    th.start()
    th.join(timeout=60.0)
    assert not th.is_alive()
    assert box["error"] == "induced loop crash"
    # the popped job was requeued before the raise: BOTH jobs journaled
    with open(queue_mod.journal_path(state)) as fh:
        journal = json.load(fh)
    assert len(journal["jobs"]) == 2
    with open(os.path.join(state, "logs", "flight_recorder.json")) as fh:
        flight = json.load(fh)
    assert flight["reason"] == "serve_crash:RuntimeError"
    assert flight["events"]


# --- slow e2e: subprocess crash/drain with byte-identity ---------------------


def _run_scenario(tmp_path, scenario):
    out = tmp_path / "load_report.json"
    rc = serve_load.main([
        "--scenario", scenario, "--seed", "3", "--tenants", "2",
        "--drain-after-s", "1", "--timeout-s", "500",
        "--workdir", str(tmp_path / "w"), "--out", str(out),
    ])
    report = json.loads(out.read_text())
    assert rc == 0, report["invariants"]
    assert report["invariants"] == []
    assert report["drills"]["byte_identity"] is True
    assert report["drills"]["resume"]["journal_consumed"]
    assert report["completed"] == report["accepted"]
    return report


@pytest.mark.slow
def test_crash_e2e_flight_recorder_journal_and_byte_identity(tmp_path):
    report = _run_scenario(tmp_path, "crash")
    assert report["drills"]["disruption"]["exit_code"] != 0
    assert report["drills"]["flight_recorder"]["reason"] == \
        "serve_crash:RuntimeError"
    # the induced crash fired before any pop completed a job: everything
    # accepted rode the journal into generation 2
    assert (report["drills"]["journal"]["journaled"]
            == report["drills"]["resume"]["completed_after_restart"])


@pytest.mark.slow
def test_drain_e2e_sigterm_under_load_byte_identity(tmp_path):
    report = _run_scenario(tmp_path, "drain")
    assert report["drills"]["disruption"]["exit_code"] == 143
    assert report["drills"]["flight_recorder"]["reason"] == "serve_drain"
    # the 503 window in a subprocess drain is however long the in-flight
    # job takes to reach its next stage boundary — honest outcomes are
    # the observed 503 or the daemon finishing its drain first
    assert report["drills"]["mid_drain_503"] in (1, "daemon_already_down")
