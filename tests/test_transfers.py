"""Device data-plane auditor (obs/transfers.py): byte-accounting units,
donation-verdict logic, the executor's ledger tap on synthetic graphs,
the --report --memory reconciler with its never-crash garbage ladder,
and the host_round_trip_bytes gate (library + perf_gate CLI on a mixed
legacy/upgraded ledger).
"""

from __future__ import annotations

import dataclasses
import json
import subprocess
import sys
from pathlib import Path
from types import SimpleNamespace

import numpy as np
import pytest

from ont_tcrconsensus_tpu.graph import check as graph_check
from ont_tcrconsensus_tpu.graph.executor import GraphExecutor
from ont_tcrconsensus_tpu.graph.ir import GraphBuilder
from ont_tcrconsensus_tpu.obs import history
from ont_tcrconsensus_tpu.obs import metrics as obs_metrics
from ont_tcrconsensus_tpu.obs import report as obs_report
from ont_tcrconsensus_tpu.obs import transfers
from ont_tcrconsensus_tpu.qc.timing import StageTimer

PERF_GATE = Path(__file__).resolve().parents[1] / "scripts" / "perf_gate.py"

# fixture node/edge names in variables, keeping the literal-scoped lint
# rules (graph-unknown-node / obs-unknown-site) out of test graphs
N_DEV1, N_DEV2 = "t-dev1", "t-dev2"
S_SITE = "t-site"


@pytest.fixture(autouse=True)
def _disarm():
    yield
    obs_metrics.disarm()


def _ctx():
    return SimpleNamespace(cfg=SimpleNamespace(resume=False),
                           timer=StageTimer(), lay=None)


# ---------------------------------------------------------------------------
# byte accounting


@dataclasses.dataclass
class _Block:
    codes: np.ndarray
    names: list


def test_nbytes_of_arrays_containers_and_dataclasses():
    a = np.zeros((4, 8), np.int8)
    assert transfers.nbytes_of(a) == 32
    assert transfers.nbytes_of({"x": a, "y": [a, a]}) == 96
    assert transfers.nbytes_of((b"abcd", "ef")) == 6
    blk = _Block(codes=np.zeros(16, np.int8), names=["aa", "bb"])
    assert transfers.nbytes_of(blk) == 16 + 4
    assert transfers.nbytes_of(None) == 0
    assert transfers.nbytes_of(object()) == 0  # unknown leaf: count 0


def test_nbytes_of_never_consumes_iterators():
    """A generator edge value must survive being measured — consuming it
    here would corrupt the pipeline the ledger audits."""
    gen = (i for i in range(5))
    assert transfers.nbytes_of(gen) == 0
    assert list(gen) == [0, 1, 2, 3, 4]


# ---------------------------------------------------------------------------
# donation verdict logic (pure)


def test_donation_verdict_ladder():
    dev = ({10, 11}, True)
    cpu = ({10}, False)
    assert transfers.donation_verdict(None, dev) == "unknown"
    assert transfers.donation_verdict(cpu, ({10}, False)) == "unknown"
    assert transfers.donation_verdict(dev, ({11, 99}, True)) == "donated"
    assert transfers.donation_verdict(dev, ({98, 99}, True)) == "copied"
    assert transfers.donation_verdict(dev, None) == "copied"


# ---------------------------------------------------------------------------
# ledger plants + registry roll-up


def test_ledger_sites_rollup_and_prometheus():
    reg = obs_metrics.arm()
    transfers.h2d(S_SITE, np.zeros(100, np.int8))
    transfers.h2d(S_SITE, None, nbytes=50)
    transfers.d2h(S_SITE, np.zeros(25, np.int8))
    tr = reg.summary()["transfers"]
    assert tr["sites"][S_SITE] == {
        "h2d_bytes": 150, "h2d": 2, "d2h_bytes": 25, "d2h": 1}
    assert tr["host_round_trip_bytes"] == 0
    text = "\n".join(reg.prometheus_lines())
    assert ('tcr_transfer_site_bytes_total{site="t-site",direction="h2d"} '
            "150") in text
    # an armed-but-idle registry emits no transfer families at all (the
    # exposition stays valid, families only appear once fed)
    assert "tcr_transfer" not in "\n".join(obs_metrics.arm()
                                           .prometheus_lines())


def test_plants_are_noops_when_disarmed():
    obs_metrics.disarm()
    transfers.h2d(S_SITE, np.zeros(8))
    transfers.d2h(S_SITE, np.zeros(8))
    transfers.edge_materialized("e", "hbm", np.zeros(8))
    transfers.audit_donation("e", "n", None, None)
    transfers.node_hbm_boundary("n")
    transfers.static_hbm("n", 100)
    assert obs_metrics.registry() is None


# ---------------------------------------------------------------------------
# the executor tap: per-edge attribution, round-trip charge, donation audit


def _round_trip_graph() -> GraphBuilder:
    """dev1 -> h(host) -> dev2: the host edge sits between two device
    nodes, so graftcheck flags it as a round-trip and the executor must
    charge its bytes to host_round_trip_bytes."""
    b = GraphBuilder("t")
    b.input("src", "disk")
    b.edge("x", "hbm")
    b.edge("h", "host")
    b.edge("out", "host")
    b.add_node(N_DEV1, lambda ctx, i: {"x": i["src"] * 2, "h": i["src"] + 1},
               inputs=("src",), outputs=("x", "h"))
    b.add_node(N_DEV2, lambda ctx, i: {"out": i["x"] + i["h"]},
               inputs=("x", "h"), outputs=("out",))
    b.result("out")
    return b


def test_round_trip_edges_matches_static_findings():
    spec = _round_trip_graph().build()
    assert graph_check.round_trip_edges(spec) == {"h"}


def test_executor_tap_attributes_edges_and_charges_round_trip():
    spec = _round_trip_graph().build()
    reg = obs_metrics.arm()
    src = np.ones(100, np.int8)
    out = GraphExecutor(spec, _ctx()).run({"src": src})
    assert out["out"].shape == (100,)
    tr = reg.summary()["transfers"]
    assert tr["edges"]["x"] == {"bytes": 100, "count": 1,
                                "direction": "h2d", "placement": "hbm"}
    assert tr["edges"]["h"]["direction"] == "d2h"
    # only the round-trip edge h is charged to the run-level budget
    assert tr["host_round_trip_bytes"] == 100
    # x is donation-eligible (hbm, dropped at dev2); numpy buffers carry
    # no unsafe_buffer_pointer, so the verdict degrades to unknown
    assert tr["donation"]["x"] == {"verdict": "unknown", "node": N_DEV2}


def test_executor_tap_is_inert_when_disarmed():
    spec = _round_trip_graph().build()
    out = GraphExecutor(spec, _ctx()).run({"src": np.ones(10, np.int8)})
    assert out["out"].shape == (10,)
    assert obs_metrics.registry() is None


# ---------------------------------------------------------------------------
# the reconciler (jax-free) + its garbage ladder


def _artifact(**transfers_over) -> dict:
    tr = {
        "sites": {}, "edges": {}, "host_round_trip_bytes": 0,
        "static_hbm_by_node": {"round1_polish": 4000},
        "node_hbm": {"round1_polish": {"delta_bytes": 64, "end_bytes": 4100,
                                       "samples": 2}},
    }
    tr.update(transfers_over)
    return {"telemetry": "on", "duration_s": 1.0, "transfers": tr}


def test_analyze_memory_reconciles_and_flags_divergence():
    a = transfers.analyze_memory(_artifact())
    row = a["nodes"]["round1_polish"]
    assert row["static_bytes"] == 4000 and row["measured_end_bytes"] == 4100
    assert abs(row["divergence"] - 0.025) < 1e-9
    assert a["problems"] == []
    # beyond threshold -> named problem with both numbers
    a = transfers.analyze_memory(_artifact(
        node_hbm={"round1_polish": {"end_bytes": 9000, "delta_bytes": 0,
                                    "samples": 1}}))
    assert any("hbm divergence at node round1_polish" in p
               and "4000" in p and "9000" in p for p in a["problems"])


def test_analyze_memory_names_copied_donations():
    a = transfers.analyze_memory(_artifact(
        donation={"read_store": {"verdict": "copied",
                                 "node": "round1_polish"}}))
    assert a["donation"] == {"copied": 1}
    assert any("donation regression" in p and "read_store" in p
               for p in a["problems"])


def test_analyze_memory_garbage_ladder():
    # pre-upgrade artifact / telemetry off
    a = transfers.analyze_memory({"duration_s": 1.0})
    assert any("no transfers section" in p for p in a["problems"])
    # transfers is valid JSON but not an object
    a = transfers.analyze_memory({"transfers": 7})
    assert any("not an object" in p for p in a["problems"])
    # garbage per-node entries dropped by name, the rest reconcile
    art = _artifact()
    art["transfers"]["node_hbm"]["zz"] = ["garbage"]
    art["transfers"]["static_hbm_by_node"]["yy"] = "much"
    a = transfers.analyze_memory(art)
    assert any("'zz'" in p for p in a["problems"])
    assert any("'yy'" in p for p in a["problems"])
    assert "divergence" in a["nodes"]["round1_polish"]
    # garbage host_round_trip_bytes named, not crashed on
    a = transfers.analyze_memory(_artifact(host_round_trip_bytes="lots"))
    assert any("host_round_trip_bytes" in p for p in a["problems"])
    # static only (CPU backend: no memory stats) -> named degradation
    a = transfers.analyze_memory(_artifact(node_hbm={}))
    assert any("no measured per-node HBM samples" in p for p in a["problems"])
    # not even a dict
    assert transfers.analyze_memory([])["problems"]


def test_render_memory_smoke():
    lines: list[str] = []
    transfers.render_memory(transfers.analyze_memory(_artifact()), lines)
    text = "\n".join(lines)
    assert "static graftcheck estimate vs measured" in text
    assert "round1_polish" in text
    lines = []
    transfers.render_memory(transfers.analyze_memory({}), lines)
    assert any("memory problem:" in ln for ln in lines)


# --- the --report --memory surface (same ladder as --critical-path) ----------


def _write_artifact(tmp_path, payload) -> str:
    wd = tmp_path / "nano_tcr"
    wd.mkdir(exist_ok=True)
    (wd / "telemetry.json").write_text(
        payload if isinstance(payload, str) else json.dumps(payload))
    return str(wd)


def test_report_memory_text(tmp_path, capsys):
    wd = _write_artifact(tmp_path, _artifact())
    assert obs_report.report_main(wd, memory=True) == 0
    out = capsys.readouterr().out
    assert "-- memory reconciliation --" in out
    assert "round1_polish" in out and "data plane:" in out


def test_report_memory_json_machine_dump(tmp_path, capsys):
    wd = _write_artifact(tmp_path, _artifact())
    assert obs_report.report_main(wd, as_json=True, memory=True) == 0
    data = json.loads(capsys.readouterr().out)
    mem = data["memory"]["telemetry.json"]
    assert mem["nodes"]["round1_polish"]["static_bytes"] == 4000
    assert mem["problems"] == []


def test_report_memory_json_never_crash_matches_text_exit_codes(tmp_path,
                                                                capsys):
    """Exit-code parity on the degradation ladder: garbage transfers
    section -> 1 both modes; a pre-upgrade artifact without the section
    -> 0 with a named memory problem; nonsense target -> 2."""
    wd = _write_artifact(tmp_path, '{"transfers": 7, "duration_s": 1.0}')
    assert obs_report.report_main(wd, memory=True) == 1
    text = capsys.readouterr().out
    assert "malformed telemetry artifact telemetry.json" in text
    assert obs_report.report_main(wd, as_json=True, memory=True) == 1
    data = json.loads(capsys.readouterr().out)
    assert any("malformed telemetry artifact" in p for p in data["problems"])
    # pre-upgrade artifact: degradation is informational, not a failure
    pre = tmp_path / "pre"
    pre.mkdir()
    wd2 = _write_artifact(pre, {"telemetry": "on", "duration_s": 1.0})
    assert obs_report.report_main(wd2, memory=True) == 0
    assert "no transfers section" in capsys.readouterr().out
    assert obs_report.report_main(wd2, as_json=True, memory=True) == 0
    data = json.loads(capsys.readouterr().out)
    assert any("no transfers section" in p
               for p in data["memory"]["telemetry.json"]["problems"])
    assert obs_report.report_main(str(tmp_path / "nope"), memory=True,
                                  as_json=True) == 2


# ---------------------------------------------------------------------------
# history ledger fields + the bytes gate


def _tele_with_transfers() -> dict:
    return {
        "duration_s": 5.0, "stages": {}, "dispatch": {},
        "compile": {"count": 1, "seconds": 0.5}, "gauges": {},
        "transfers": {
            "sites": {S_SITE: {"h2d_bytes": 1000, "h2d": 2,
                               "d2h_bytes": 300, "d2h": 1}},
            "edges": {}, "host_round_trip_bytes": 128,
            "donation": {"read_store": {"verdict": "donated",
                                        "node": "round1_polish"}},
        },
    }


def test_build_entry_carries_transfer_fields():
    e = history.build_entry("run", _tele_with_transfers(), fingerprint="f",
                            backend="cpu", n_reads=100)
    assert e["transfer_bytes"] == {"h2d": 1000, "d2h": 300}
    assert e["host_round_trip_bytes"] == 128
    assert e["donation"] == {"read_store": "donated"}
    # pre-upgrade telemetry: the keys are simply absent
    e = history.build_entry("run", {"duration_s": 1.0}, fingerprint="f",
                            backend="cpu", n_reads=100)
    assert "transfer_bytes" not in e and "host_round_trip_bytes" not in e


def _bentry(rt=None, **over) -> dict:
    e = {"fingerprint": "f", "backend": "cpu", "n_reads": 100,
         "duration_s": 10.0}
    if rt is not None:
        e["host_round_trip_bytes"] = rt
    e.update(over)
    return e


def test_bytes_gate_pass_fail_and_zero_baseline():
    base = [_bentry(rt=1000) for _ in range(4)]
    assert history.evaluate_bytes_gate(base, _bentry(rt=1050)).status == "pass"
    res = history.evaluate_bytes_gate(base, _bentry(rt=5000))
    assert res.status == "fail"
    assert "5000 B" in res.reason and "allowed" in res.reason
    # a 0-byte baseline is the ideal: ANY reintroduced round-trip fails,
    # with the measured bytes in the verdict (zero is a usable value
    # here, unlike the timing gate's metrics)
    zero = [_bentry(rt=0) for _ in range(4)]
    res = history.evaluate_bytes_gate(zero, _bentry(rt=4096))
    assert res.status == "fail" and "4096 B" in res.reason


def test_bytes_gate_absolute_budget():
    # abs_budget is the no-history mode: the device-resident data plane
    # budgets ~0 bytes, so any measured round-trip fails deterministically
    # even on an empty ledger
    res = history.evaluate_bytes_gate([], _bentry(rt=4096), abs_budget=0.0)
    assert res.status == "fail"
    assert "4096 B" in res.reason and "absolute" in res.reason
    ok = history.evaluate_bytes_gate([], _bentry(rt=0), abs_budget=0.0)
    assert ok.status == "pass"
    # pre-upgrade current entry still degrades to warn, never a crash
    res = history.evaluate_bytes_gate([], _bentry(), abs_budget=0.0)
    assert res.status == "warn"


def test_perf_gate_cli_rt_budget_seeded_regression(tmp_path):
    """The tier1.sh seeded regression arm: under --rt-budget 0 a seeded
    host round-trip exits nonzero with measured-vs-allowed bytes in the
    reason, and the honest zero passes with no baseline history at all."""
    ledger = tmp_path / "ledger.jsonl"
    with open(ledger, "w") as fh:
        fh.write(json.dumps(_bentry(rt=0)) + "\n")
        fh.write(json.dumps(_bentry(rt=4096)) + "\n")  # seeded round-trip
    proc = subprocess.run(
        [sys.executable, str(PERF_GATE), str(ledger), "--rt-budget", "0"],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1
    assert "4096 B" in proc.stdout and "allowed 0 B" in proc.stdout
    clean = tmp_path / "clean.jsonl"
    with open(clean, "w") as fh:
        fh.write(json.dumps(_bentry(rt=0)) + "\n")
    proc = subprocess.run(
        [sys.executable, str(PERF_GATE), str(clean), "--rt-budget", "0"],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0 and "transfer PASS" in proc.stdout


def test_bytes_gate_tolerates_legacy_ledgers():
    # all-legacy baseline: WARN (recorded, not gated), names the skips
    legacy = [_bentry() for _ in range(4)]
    res = history.evaluate_bytes_gate(legacy, _bentry(rt=4096))
    assert res.status == "warn" and "legacy" in res.reason
    # mixed ledger: legacy entries are skipped, upgraded ones still gate
    mixed = legacy + [_bentry(rt=100) for _ in range(3)]
    res = history.evaluate_bytes_gate(mixed, _bentry(rt=9000))
    assert res.status == "fail" and "legacy skipped" in res.reason
    # current entry itself pre-upgrade: WARN, never a crash
    res = history.evaluate_bytes_gate(mixed, _bentry())
    assert res.status == "warn"


def test_perf_gate_cli_mixed_ledger_transfer_verdict(tmp_path):
    """The CLI surface: a mixed legacy/upgraded ledger gates the byte
    metric on the upgraded entries only, fails with measured-vs-allowed
    bytes, and keeps --json one parseable object."""
    ledger = tmp_path / "ledger.jsonl"
    with open(ledger, "w") as fh:
        for _ in range(3):
            fh.write(json.dumps(_bentry()) + "\n")
        for _ in range(3):
            fh.write(json.dumps(_bentry(rt=100)) + "\n")
        fh.write(json.dumps(_bentry(rt=50000)) + "\n")
    proc = subprocess.run(
        [sys.executable, str(PERF_GATE), str(ledger)],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1
    assert "transfer FAIL" in proc.stdout and "allowed" in proc.stdout
    proc = subprocess.run(
        [sys.executable, str(PERF_GATE), str(ledger), "--json"],
        capture_output=True, text=True, timeout=120)
    verdict = json.loads(proc.stdout)
    assert verdict["status"] == "pass"  # timing unchanged
    assert verdict["transfer"]["status"] == "fail"
    assert verdict["transfer"]["current"] == 50000.0
    # an all-legacy ledger stays a valid baseline: transfer WARNs, rc 0
    thin = tmp_path / "legacy.jsonl"
    with open(thin, "w") as fh:
        for _ in range(4):
            fh.write(json.dumps(_bentry()) + "\n")
    proc = subprocess.run(
        [sys.executable, str(PERF_GATE), str(thin)],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0
    assert "transfer WARN" in proc.stdout


# ---------------------------------------------------------------------------
# donation-audit e2e (slow: full tiny pipeline under the graph executor)


@pytest.mark.slow
def test_donation_audit_e2e_tiny_pipeline(tmp_path):
    """A default telemetry run commits the transfers section end to end:
    per-edge bytes, donation verdicts in the closed vocabulary, static
    per-node HBM from graftcheck, and a ledger entry carrying the
    transfer fields — then bench-style gating catches a seeded round-trip
    regression against that run's own baseline."""
    from ont_tcrconsensus_tpu.io import fastx, simulator
    from ont_tcrconsensus_tpu.pipeline.config import RunConfig
    from ont_tcrconsensus_tpu.pipeline.run import run_with_config

    lib = simulator.simulate_library(
        seed=7, num_regions=2, molecules_per_region=(2, 2),
        reads_per_molecule=(5, 6), sub_rate=0.006, ins_rate=0.003,
        del_rate=0.003, region_len=(700, 800),
    )
    fastx.write_fasta(tmp_path / "reference.fa", lib.reference.items())
    fq = tmp_path / "fastq_pass" / "barcode01"
    fq.mkdir(parents=True)
    fastx.write_fastq(fq / "barcode01.fastq.gz", lib.reads)
    cfg = RunConfig.from_dict({
        "reference_file": str(tmp_path / "reference.fa"),
        "fastq_pass_dir": str(tmp_path / "fastq_pass"),
        "minimal_length": 600, "min_reads_per_cluster": 4,
        "read_batch_size": 64, "polish_method": "poa",
        "delete_tmp_files": False, "telemetry": "on",
    })
    run_with_config(cfg)
    nano = tmp_path / "fastq_pass" / "nano_tcr"
    tele = json.loads((nano / "telemetry.json").read_text())
    tr = tele["transfers"]
    assert tr["sites"] and tr["edges"]
    # the production graph is device-resident end to end: graftcheck finds
    # zero round-trip edges, so the runtime ledger charges exactly 0 bytes
    # (the control arm for falsifiability is
    # test_executor_tap_attributes_edges_and_charges_round_trip, where a
    # deliberately host-materialized edge IS charged)
    assert tr["host_round_trip_bytes"] == 0
    assert tr["donation"]
    verdicts = set(d["verdict"] for d in tr["donation"].values())
    assert verdicts <= {"donated", "unknown"}, (
        f"copied donation verdict on the donated path: {tr['donation']}")
    # the honest run passes the near-zero absolute budget with no history
    assert history.evaluate_bytes_gate(
        [], history.build_entry("run", tele, fingerprint="f", backend="cpu",
                                n_reads=100), abs_budget=0.0,
    ).status == "pass"
    assert tr["static_hbm_by_node"]  # graftcheck liveness, recorded armed
    entries, problems = history.read_entries(str(nano / "history.jsonl"))
    assert problems == [] and entries
    assert "transfer_bytes" in entries[-1]
    assert "host_round_trip_bytes" in entries[-1]
    # seeded host round-trip vs this run's own baseline: the bytes gate
    # names the regression in measured-vs-allowed bytes
    base = entries * 3
    seeded = dict(entries[-1])
    seeded["host_round_trip_bytes"] = (
        entries[-1]["host_round_trip_bytes"] * 10 + 100_000)
    res = history.evaluate_bytes_gate(base, seeded)
    assert res.status == "fail" and "host round-trip" in res.reason
