"""Banded SW kernel vs full-DP numpy oracle, plus amplicon-geometry cases."""

import numpy as np

from ont_tcrconsensus_tpu.io import simulator
from ont_tcrconsensus_tpu.ops import encode, sw_align


def _pad(seqs, width):
    out = np.full((len(seqs), width), encode.PAD_CODE, dtype=np.uint8)
    lens = np.zeros(len(seqs), dtype=np.int32)
    for i, s in enumerate(seqs):
        out[i, : len(s)] = s
        lens[i] = len(s)
    return out, lens


def _run_one(read, ref, offset=0, band=256):
    reads, rlens = _pad([read], 256)
    refs, tlens = _pad([ref], 256)
    res = sw_align.align_banded(
        reads, rlens, refs, tlens, np.array([offset], np.int32), band_width=band
    )
    return {k: int(getattr(res, k)[0]) for k in
            ("score", "read_start", "read_end", "ref_start", "ref_end", "n_match", "n_cols")}


def test_exact_substring():
    rng = np.random.default_rng(0)
    ref = rng.integers(0, 4, 80).astype(np.uint8)
    read = np.concatenate([rng.integers(0, 4, 10), ref, rng.integers(0, 4, 7)]).astype(np.uint8)
    got = _run_one(read, ref, offset=-10)
    assert got["score"] == 80 * sw_align.MATCH
    assert got["n_match"] == 80 and got["n_cols"] == 80
    assert (got["read_start"], got["read_end"]) == (10, 90)
    assert (got["ref_start"], got["ref_end"]) == (0, 80)


def test_matches_numpy_oracle_random():
    rng = np.random.default_rng(1)
    for trial in range(12):
        n = int(rng.integers(40, 120))
        m = int(rng.integers(40, 120))
        # correlated pair: mutate a shared core so a clear local optimum exists
        core = rng.integers(0, 4, min(n, m)).astype(np.uint8)
        read = core[:n].copy()
        ref = core[:m].copy()
        nmut = int(rng.integers(0, 8))
        for p in rng.choice(min(n, m), size=nmut, replace=False):
            ref[p] = (ref[p] + 1 + rng.integers(3)) % 4
        want = sw_align.align_np(read, ref)
        got = _run_one(read, ref)
        assert got["score"] == int(want.score), trial
        for f in ("read_start", "read_end", "ref_start", "ref_end", "n_match", "n_cols"):
            assert got[f] == int(getattr(want, f)), (trial, f)


def test_matches_numpy_oracle_with_indels():
    rng = np.random.default_rng(2)
    for trial in range(8):
        ref = rng.integers(0, 4, 100).astype(np.uint8)
        read = list(ref)
        # random indels + subs
        for _ in range(5):
            p = int(rng.integers(len(read)))
            op = rng.integers(3)
            if op == 0:
                read.insert(p, int(rng.integers(4)))
            elif op == 1 and len(read) > 10:
                del read[p]
            else:
                read[p] = (read[p] + 1) % 4
        read = np.array(read, dtype=np.uint8)
        want = sw_align.align_np(read, ref)
        got = _run_one(read, ref)
        assert got["score"] == int(want.score), trial
        assert got["n_cols"] == int(want.n_cols), trial
        assert got["n_match"] == int(want.n_match), trial


def test_amplicon_geometry():
    """Full amplicon read vs its region: band must absorb flank+UMI overhangs."""
    rng = np.random.default_rng(3)
    region = simulator._rand_seq(rng, 1500)
    umi_f = simulator.instantiate_iupac(rng, "TTTVVTTVVVVTTVVVVTTVVVVTTVVVVTTT")
    umi_r = simulator.instantiate_iupac(rng, "AAABBBBAABBBBAABBBBAABBBBAABBAAA")
    full = simulator.LEFT_FLANK + umi_f + region + umi_r + simulator.RIGHT_FLANK
    read_str, _ = simulator.mutate(rng, full, 0.01, 0.005, 0.005)
    read = encode.encode_seq(read_str)
    ref = encode.encode_seq(region)
    reads, rlens = _pad([read], 2048)
    refs, tlens = _pad([ref], 2048)
    overhang = len(simulator.LEFT_FLANK) + len(umi_f)
    res = sw_align.align_banded(
        reads, rlens, refs, tlens, np.array([-overhang], np.int32), band_width=256
    )
    ref_cov = (int(res.ref_end[0]) - int(res.ref_start[0])) / len(region)
    assert ref_cov > 0.99
    assert float(res.blast_id[0]) > 0.96
    # softclips bounded by flank+UMI sizes (plus indel slack)
    assert int(res.read_start[0]) <= overhang + 10
    assert len(read) - int(res.read_end[0]) <= overhang + 10


def test_pallas_kernel_matches_jnp_kernel():
    """Interpreter-mode Pallas vs the scan kernel: identical results."""
    from ont_tcrconsensus_tpu.ops import sw_pallas

    rng = np.random.default_rng(7)
    reads_l, refs_l, offs = [], [], []
    for t in range(6):
        ref = rng.integers(0, 4, int(rng.integers(60, 120))).astype(np.uint8)
        read = list(ref)
        for _ in range(6):
            p = int(rng.integers(len(read)))
            op = rng.integers(3)
            if op == 0:
                read.insert(p, int(rng.integers(4)))
            elif op == 1 and len(read) > 10:
                del read[p]
            else:
                read[p] = (read[p] + 1) % 4
        reads_l.append(np.array(read, np.uint8))
        refs_l.append(ref)
        offs.append(0)
    reads, rlens = _pad(reads_l, 128)
    refs, tlens = _pad(refs_l, 128)
    offs = np.array(offs, np.int32)

    want = sw_align.align_banded(reads, rlens, refs, tlens, offs, band_width=128)
    got = sw_pallas.align_banded_pallas(
        reads, rlens, refs, tlens, offs, band_width=128, interpret=True
    )
    for f in ("score", "read_start", "read_end", "ref_start", "ref_end",
              "n_match", "n_cols"):
        np.testing.assert_array_equal(
            np.asarray(getattr(got, f)), np.asarray(getattr(want, f)), err_msg=f
        )


def test_batch_is_elementwise():
    rng = np.random.default_rng(4)
    seqs = [rng.integers(0, 4, int(rng.integers(50, 120))).astype(np.uint8) for _ in range(6)]
    refs_l = [rng.integers(0, 4, int(rng.integers(50, 120))).astype(np.uint8) for _ in range(6)]
    reads, rlens = _pad(seqs, 128)
    refs, tlens = _pad(refs_l, 128)
    res = sw_align.align_banded(reads, rlens, refs, tlens, np.zeros(6, np.int32))
    for i in range(6):
        got = _run_one(seqs[i], refs_l[i])
        assert int(res.score[i]) == got["score"]
