"""Unified telemetry layer (obs/): trace schema, recompile audit, disarmed
overhead, artifact e2e, and the --report renderer.

The e2e pair (telemetry=full vs =off on the same tiny library) is also the
tier-1 telemetry smoke (scripts/tier1.sh): artifacts must exist and
validate, and the PIPELINE outputs must be byte-identical — telemetry
observes the run, it must never change it.
"""

from __future__ import annotations

import json
import shutil
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from ont_tcrconsensus_tpu.obs import KNOWN_SITES, OBS_SITES
from ont_tcrconsensus_tpu.obs import device as obs_device
from ont_tcrconsensus_tpu.obs import metrics as obs_metrics
from ont_tcrconsensus_tpu.obs import report as obs_report
from ont_tcrconsensus_tpu.obs import trace as obs_trace

REQUIRED_PHASES = {"X", "i", "M", "C"}


def validate_trace(payload: dict) -> None:
    """Chrome trace-event schema + per-thread monotonic consistency."""
    assert isinstance(payload.get("traceEvents"), list)
    spans_by_tid: dict[int, list[tuple[float, float]]] = {}
    for ev in payload["traceEvents"]:
        assert ev["ph"] in REQUIRED_PHASES, ev
        assert isinstance(ev["pid"], int) and isinstance(ev["tid"], int)
        assert isinstance(ev["name"], str) and ev["name"]
        if ev["ph"] == "M":
            assert ev["name"] == "thread_name" and ev["args"]["name"]
            continue
        assert ev["ts"] >= 0.0, ev
        if ev["ph"] == "X":
            assert ev["dur"] >= 0.0, ev
            spans_by_tid.setdefault(ev["tid"], []).append(
                (ev["ts"], ev["dur"])
            )
        elif ev["ph"] == "i":
            assert ev.get("s") == "t"
    # spans on one thread must be monotonically consistent: sorted by start
    # they either nest (scope discipline) or are disjoint — a span can
    # never PARTIALLY overlap a sibling, which is what a broken clock or a
    # cross-thread mixup would produce
    for tid, spans in spans_by_tid.items():
        open_ends: list[float] = []
        for ts, dur in sorted(spans):
            end = ts + dur
            while open_ends and ts >= open_ends[-1] - 0.5:
                open_ends.pop()
            if open_ends:
                assert end <= open_ends[-1] + 0.5, (
                    f"tid {tid}: span [{ts}, {end}] partially overlaps "
                    f"enclosing span ending at {open_ends[-1]}"
                )
            open_ends.append(end)


@pytest.fixture
def armed_metrics():
    reg = obs_metrics.arm()
    yield reg
    obs_metrics.disarm()


@pytest.fixture
def armed_trace():
    col = obs_trace.arm()
    yield col
    obs_trace.disarm()


# ---------------------------------------------------------------------------
# trace collector + span plumbing


def test_trace_json_schema_and_thread_rows(tmp_path, armed_metrics, armed_trace):
    with obs_trace.span("round1_polish"):
        with obs_trace.span("round1_umi_cluster"):
            time.sleep(0.01)
        obs_trace.instant("chaos.inject", args={"kind": "transient"})
    t = threading.Thread(
        target=lambda: obs_trace.span("round2_umi_cluster").__enter__().__exit__(
            None, None, None
        ),
        name="worker-thread",
    )
    t.start()
    t.join()
    armed_trace.add_counter("memory", {"host_rss_bytes": 123})
    path = tmp_path / "trace.json"
    armed_trace.write(str(path))
    payload = json.loads(path.read_text())
    validate_trace(payload)
    names = {e["name"] for e in payload["traceEvents"]}
    assert {"round1_polish", "round1_umi_cluster", "round2_umi_cluster",
            "chaos.inject", "memory", "thread_name"} <= names
    thread_names = {e["args"]["name"] for e in payload["traceEvents"]
                    if e["ph"] == "M"}
    assert "worker-thread" in thread_names


def test_trace_buffer_cap_drops_and_reports(tmp_path):
    """A multi-hour full-telemetry run must not grow RSS without bound:
    past max_events the collector drops (never silently — the count lands
    in otherData.dropped_events)."""
    col = obs_trace.TraceCollector(max_events=3)
    obs_trace._ARMED = col
    try:
        for _ in range(6):
            obs_trace.instant("chaos.inject")
    finally:
        obs_trace.disarm()
    path = tmp_path / "trace.json"
    col.write(str(path))
    payload = json.loads(path.read_text())
    assert len(payload["traceEvents"]) == 3  # thread meta + 2 instants
    assert payload["otherData"]["dropped_events"] == 4
    validate_trace(payload)


def test_stage_timer_and_trace_are_one_measurement(armed_metrics, armed_trace):
    """StageTimer seconds, the registry stage roll-up and the trace span
    duration all come from the SAME clock-read pair — bit-identical."""
    from ont_tcrconsensus_tpu.qc.timing import StageTimer

    timer = StageTimer()
    with timer.stage("round1_polish"):
        time.sleep(0.01)
    reg_seconds = armed_metrics.stages["round1_polish"][0]
    (span_ev,) = [e for e in armed_trace.events if e.get("ph") == "X"]
    assert timer.seconds["round1_polish"] == reg_seconds
    assert span_ev["dur"] == reg_seconds * 1e6
    assert timer.calls["round1_polish"] == 1


def test_robustness_events_carry_both_clocks():
    """Satellite: every robustness_report.json event places on the trace
    timeline — RobustnessRecorder.record (the single funnel for retry,
    watchdog, contract, quarantine and resume-verify events) stamps wall
    AND monotonic time on every event."""
    from ont_tcrconsensus_tpu.robustness.retry import RobustnessRecorder

    rec = RobustnessRecorder()
    t_wall0, t_mono0 = time.time(), time.monotonic()
    rec.record("polish.dispatch", classification="transient", outcome="retried")
    (ev,) = rec.events
    assert abs(ev["t_wall"] - t_wall0) < 5.0
    assert t_mono0 <= ev["t_mono"] <= time.monotonic()


# ---------------------------------------------------------------------------
# recompile audit


def test_recompile_counter_new_shape_yes_repeat_no(armed_metrics):
    import jax
    import jax.numpy as jnp

    obs_device.install_compile_listener()
    jitted = jax.jit(lambda x: x * 3 + 1)
    with obs_trace.span("round1_polish"):
        jitted(jnp.ones((1733,))).block_until_ready()
    n_fresh = armed_metrics.summary()["compile"]["count"]
    assert n_fresh >= 1, "a fresh shape must record >=1 XLA compile"
    assert any(k.startswith("round1_polish")
               for k in armed_metrics.compiles), armed_metrics.compiles
    jitted(jnp.ones((1733,))).block_until_ready()
    assert armed_metrics.summary()["compile"]["count"] == n_fresh, (
        "a repeated shape must record 0 new compiles"
    )
    jitted(jnp.ones((1741,))).block_until_ready()
    assert armed_metrics.summary()["compile"]["count"] > n_fresh, (
        "a new shape bucket must record a new compile"
    )


# ---------------------------------------------------------------------------
# disarmed overhead


def test_disarmed_hot_paths_touch_no_registry():
    """telemetry=off leaves the planted sites as ONE module-attr check: a
    method-less sentinel in the slot must blow up the moment any call path
    touches it — and with the slot at None every call is a silent no-op."""
    assert obs_metrics._ARMED is None and obs_trace._ARMED is None
    obs_metrics.counter_add("assign.batches")
    obs_metrics.gauge_max("host.rss_bytes", 1.0)
    obs_metrics.observe("polish.chunk_clusters", 4)
    obs_trace.instant("chaos.inject")
    with obs_device.dispatch("polish.dispatch", bucket="8x1024"):
        pass
    out = obs_device.timed_get("umi.distance", np.arange(4))
    np.testing.assert_array_equal(out, np.arange(4))
    sentinel = object()  # no registry methods at all
    obs_metrics._ARMED = sentinel
    try:
        with pytest.raises(AttributeError):
            obs_metrics.counter_add("assign.batches")
    finally:
        obs_metrics._ARMED = None
    obs_trace._ARMED = sentinel
    try:
        with pytest.raises(AttributeError):
            obs_trace.instant("chaos.inject")
    finally:
        obs_trace._ARMED = None


def test_dispatch_split_attributes_nested_gets(armed_metrics):
    """A timed_get inside a dispatch frame credits its blocked seconds to
    the frame's site; the frame's host_s is what remains."""
    with obs_device.dispatch("polish.dispatch", bucket="8x1024"):
        obs_device.timed_get("consensus.get", np.arange(8))
        time.sleep(0.02)
    d = armed_metrics.dispatch["polish.dispatch"]
    assert d[0] == 1 and d[2] >= 0.015  # one dispatch, host_s owns the sleep
    assert armed_metrics.dispatch["consensus.get"][1] == 1  # the get counted
    assert armed_metrics.dispatch["consensus.get"][3] == 0.0  # seconds -> frame
    # frameless get records under its own site
    obs_device.timed_get("umi.distance", np.arange(8))
    assert armed_metrics.dispatch["umi.distance"][1] == 1


def test_known_sites_registry_is_exported():
    assert KNOWN_SITES is OBS_SITES
    assert "polish.dispatch" in KNOWN_SITES and "xla.compile" in KNOWN_SITES


def test_config_rejects_bad_telemetry_level():
    from ont_tcrconsensus_tpu.pipeline.config import RunConfig

    with pytest.raises(ValueError, match="telemetry"):
        RunConfig.from_dict({
            "reference_file": "r.fa", "fastq_pass_dir": "fq",
            "telemetry": "loud",
        })


# ---------------------------------------------------------------------------
# e2e: artifacts at telemetry=full, byte-identity vs telemetry=off


@pytest.fixture(scope="module")
def obs_library(tmp_path_factory):
    from ont_tcrconsensus_tpu.io import fastx, simulator

    tmp = tmp_path_factory.mktemp("obs_e2e")
    lib = simulator.simulate_library(
        seed=23,
        num_regions=3,
        molecules_per_region=(2, 3),
        reads_per_molecule=(5, 7),
        sub_rate=0.006,
        ins_rate=0.003,
        del_rate=0.003,
        region_len=(700, 850),
    )
    fastx.write_fasta(tmp / "reference.fa", lib.reference.items())
    fq_dir = tmp / "fastq_pass" / "barcode01"
    fq_dir.mkdir(parents=True)
    fastx.write_fastq(fq_dir / "barcode01.fastq.gz", lib.reads)
    return tmp, lib


def _run(src, root, telemetry: str):
    from ont_tcrconsensus_tpu.pipeline.config import RunConfig
    from ont_tcrconsensus_tpu.pipeline.run import run_with_config

    root.mkdir(parents=True, exist_ok=True)
    shutil.copy(src / "reference.fa", root / "reference.fa")
    shutil.copytree(src / "fastq_pass", root / "fastq_pass")
    cfg = RunConfig.from_dict({
        "reference_file": str(root / "reference.fa"),
        "fastq_pass_dir": str(root / "fastq_pass"),
        "minimal_length": 600,
        "min_reads_per_cluster": 4,
        "read_batch_size": 64,
        "polish_method": "poa",
        "delete_tmp_files": False,
        "telemetry": telemetry,
    })
    return run_with_config(cfg), root / "fastq_pass" / "nano_tcr"


@pytest.fixture(scope="module")
def telemetry_runs(obs_library, tmp_path_factory):
    src, lib = obs_library
    res_full, nano_full = _run(src, tmp_path_factory.mktemp("t_full"), "full")
    res_off, nano_off = _run(src, tmp_path_factory.mktemp("t_off"), "off")
    return lib, res_full, nano_full, res_off, nano_off


def test_telemetry_full_e2e_artifacts(telemetry_runs):
    lib, res_full, nano, _, _ = telemetry_runs
    assert res_full["barcode01"] == lib.true_counts
    tele = json.loads((nano / "telemetry.json").read_text())
    assert tele["telemetry"] == "full"
    assert tele["stages"], "stage roll-up must be populated"
    disp = tele["dispatch"]
    assert disp["assign.dispatch"]["dispatches"] >= 1
    assert disp["assign.dispatch"]["host_s"] >= 0.0
    assert "polish.dispatch" in disp and "cluster.batched_dispatch" in disp
    assert "count" in tele["compile"] and "seconds" in tele["compile"]
    # peak host RSS is always reported; HBM high-water only on backends
    # whose devices expose memory_stats (absent on CPU — still a key case)
    assert tele["gauges"]["host.rss_bytes"] > 0
    assert isinstance(tele["robustness_events"], dict)
    trace_payload = json.loads((nano / "logs" / "trace.json").read_text())
    validate_trace(trace_payload)
    names = {e["name"] for e in trace_payload["traceEvents"]}
    assert "round1_polish" in names
    # the overlap worker's _bg span lands on the worker's own named row
    assert any(n.endswith("_bg") for n in names)
    # per-library stage_timing.tsv keeps its exact format (byte-compat
    # columns + rounding; now derived from the same spans as the trace)
    tsv = (nano / "barcode01" / "logs" / "stage_timing.tsv").read_text()
    lines = tsv.splitlines()
    assert lines[0] == "stage\tseconds\tcalls"
    for line in lines[1:]:
        stage, sec, calls = line.split("\t")
        assert sec == f"{float(sec):.3f}" and int(calls) >= 1
    # cross-run observability (obs/history.py): every telemetry-armed run
    # appends one entry to nano_tcr/history.jsonl
    from ont_tcrconsensus_tpu.obs import history as obs_history

    entries, problems = obs_history.read_entries(str(nano / "history.jsonl"))
    assert problems == [] and len(entries) == 1
    assert entries[0]["source"] == "run" and entries[0]["backend"] == "cpu"
    # graph nodes carry declared edges + units; the worker pool's
    # busy/idle split lands under graph.pool (graph executor default)
    gnodes = tele["graph"]["nodes"]
    assert any(g.get("inputs") or g.get("outputs") for g in gnodes.values())
    assert any(g.get("units") for g in gnodes.values())
    pool = tele["graph"]["pool"]
    assert pool["slots"] >= 1 and pool["busy_s"] >= 0.0
    assert pool["idle_s"] >= 0.0 and pool["window_s"] >= 0.0
    # device data-plane ledger (obs/transfers.py): every telemetry-armed
    # run commits a transfers section — per-site and per-edge bytes, the
    # run-level round-trip budget, donation verdicts from the executor's
    # drop-point audit, and graftcheck's static per-node HBM estimates
    tr = tele["transfers"]
    assert tr["sites"], "instrumented device_put/get sites must record"
    assert all(s["d2h_bytes"] >= 0 and s["h2d_bytes"] >= 0
               for s in tr["sites"].values())
    assert tr["edges"], "executor edge materialization must be attributed"
    assert all(e["direction"] in ("h2d", "d2h") for e in tr["edges"].values())
    # the data plane is device-resident: zero round-trip edges statically
    # (graftcheck) means zero bytes charged at runtime, and no donated
    # edge may degrade to a host copy
    assert tr["host_round_trip_bytes"] == 0
    verdicts = {d["verdict"] for d in tr.get("donation", {}).values()}
    assert verdicts <= {"donated", "unknown"}
    assert tr["static_hbm_by_node"], "graftcheck liveness must be recorded"
    # and the history entry carries the roll-up for bench.py --gate
    assert entries[0]["transfer_bytes"]["d2h"] >= 0
    assert entries[0]["host_round_trip_bytes"] == tr["host_round_trip_bytes"]


def test_telemetry_off_is_byte_identical_and_artifact_free(telemetry_runs):
    lib, res_full, nano_full, res_off, nano_off = telemetry_runs
    assert res_off == res_full == {"barcode01": lib.true_counts}
    assert not (nano_off / "telemetry.json").exists()
    assert not (nano_off / "logs" / "trace.json").exists()
    assert not (nano_off / "history.jsonl").exists()
    for rel in (
        ("barcode01", "counts", "umi_consensus_counts.csv"),
        ("barcode01", "fasta", "merged_consensus.fasta"),
    ):
        a = nano_full.joinpath(*rel).read_bytes()
        b = nano_off.joinpath(*rel).read_bytes()
        assert a == b, f"telemetry must not change {'/'.join(rel)}"


def test_report_renders_without_jax(telemetry_runs):
    """--report works from the committed artifacts alone, in a process
    where importing jax is poisoned (the wedged-tunnel scenario)."""
    _, _, nano, _, _ = telemetry_runs
    code = (
        "import sys\n"
        "sys.modules['jax'] = None\n"  # any `import jax` now raises
        "from ont_tcrconsensus_tpu.pipeline.cli import main\n"
        f"sys.exit(main(['--report', {str(nano)!r}]))\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    assert "dispatch sites" in proc.stdout
    assert "XLA compiles" in proc.stdout
    assert "trace:" in proc.stdout


def test_report_degrades_on_valid_json_garbage(tmp_path, capsys):
    """Never-crash contract (cf. the PR 5 manifest readers): a telemetry
    artifact that parses but has the wrong shape names the problem and
    exits 1 instead of raising on the wedged-host diagnosis path."""
    wd = tmp_path / "nano_tcr"
    wd.mkdir()
    (wd / "telemetry.json").write_text('{"stages": [], "dispatch": 7}')
    (wd / "telemetry_p1.json").write_text('["not", "an", "object"]')
    (wd / "robustness_report.json").write_text('["garbage"]')
    assert obs_report.report_main(str(wd)) == 1
    out = capsys.readouterr().out
    assert "malformed telemetry artifact" in out
    assert "unreadable robustness_report.json" in out


def test_report_resolves_fastq_pass_dir_and_flags_missing(telemetry_runs, tmp_path, capsys):
    _, _, nano, _, nano_off = telemetry_runs
    # parent fastq_pass dir resolves to its nano_tcr child
    assert obs_report.report_main(str(nano.parent)) == 0
    # a telemetry-off workdir has no telemetry.json -> exit 1, explained
    assert obs_report.report_main(str(nano_off)) == 1
    out = capsys.readouterr().out
    assert "no telemetry*.json" in out
    # nonsense target -> exit 2
    assert obs_report.report_main(str(tmp_path / "nope")) == 2
