"""Robustness layer units (robustness/): classification, retry policy,
fault registry, recorder, preemption coordinator."""

import json
import os
import signal
import time

import pytest

from ont_tcrconsensus_tpu.robustness import faults, retry, shutdown


@pytest.fixture(autouse=True)
def _disarm_chaos():
    yield
    faults.disarm()
    shutdown.deactivate()


# --- classification ---------------------------------------------------------


def test_classify_families():
    assert retry.classify(faults.TransientChaosError("x")) == "transient"
    assert retry.classify(faults.OomChaosError("x")) == "oom"
    assert retry.classify(RuntimeError("UNAVAILABLE: socket closed")) == "transient"
    assert retry.classify(RuntimeError("DEADLINE_EXCEEDED waiting")) == "transient"
    assert retry.classify(ConnectionResetError("peer")) == "transient"
    assert retry.classify(RuntimeError("RESOURCE_EXHAUSTED: alloc")) == "oom"
    assert retry.classify(RuntimeError("Allocator ran out of memory")) == "oom"
    assert retry.classify(MemoryError()) == "oom"
    # a deterministic bug must never be retried
    assert retry.classify(ValueError("shape mismatch")) == "fatal"
    assert retry.classify(KeyError("region_cluster0")) == "fatal"


def test_oom_markers_win_over_transient_markers():
    # real XLA OOM messages often also mention the transfer machinery
    exc = RuntimeError("RESOURCE_EXHAUSTED during transfer to device")
    assert retry.classify(exc) == "oom"


def test_classify_device_lost_outranks_everything():
    assert retry.classify(faults.DeviceLostChaosError("x")) == "device_lost"
    assert retry.classify(RuntimeError("DEVICE_LOST: slice 3")) == "device_lost"
    # a dead device's message may also carry transport/allocator markers;
    # the device being gone is the binding fact
    assert retry.classify(
        RuntimeError("UNAVAILABLE: device halted")) == "device_lost"
    assert retry.classify(
        RuntimeError("Device lost during RESOURCE_EXHAUSTED cleanup")
    ) == "device_lost"


def test_call_with_retry_escalates_device_lost():
    """A dead slice can be neither retried nor shrunk around: the fault
    escalates immediately (one attempt, outcome "escalated") to the graph
    executor's degraded-mesh loop."""
    rec = retry.RobustnessRecorder()
    calls = []

    def dead():
        calls.append(1)
        raise faults.DeviceLostChaosError("DEVICE_LOST: slice gone")

    with pytest.raises(faults.DeviceLostChaosError):
        retry.call_with_retry("site", dead, recorder=rec, sleep=lambda s: None)
    assert len(calls) == 1  # never retried on the broken mesh
    assert rec.events[-1]["classification"] == "device_lost"
    assert rec.events[-1]["outcome"] == "escalated"


# --- retry policy -----------------------------------------------------------


def test_retry_policy_deterministic_bounded():
    p = retry.RetryPolicy(base_delay_s=0.1, max_delay_s=1.0, jitter=0.25, seed=7)
    delays = [p.delay(a) for a in range(1, 9)]
    assert delays == [p.delay(a) for a in range(1, 9)]  # pure in (seed, attempt)
    assert all(d <= 1.0 * 1.25 for d in delays)  # capped (plus jitter band)
    assert delays[0] < delays[4]  # grows before the cap


def test_call_with_retry_recovers_from_transient():
    rec = retry.RobustnessRecorder()
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 2:
            raise faults.TransientChaosError("flaky dispatch")
        return "ok"

    out = retry.call_with_retry(
        "site", flaky, policy=retry.RetryPolicy(max_attempts=3, base_delay_s=0),
        recorder=rec, sleep=lambda s: None,
    )
    assert out == "ok" and len(calls) == 2
    assert [e["outcome"] for e in rec.events] == ["retried", "recovered"]
    assert rec.events[0]["classification"] == "transient"


def test_call_with_retry_fatal_raises_immediately():
    rec = retry.RobustnessRecorder()
    calls = []

    def bug():
        calls.append(1)
        raise ValueError("deterministic bug")

    with pytest.raises(ValueError):
        retry.call_with_retry("site", bug, recorder=rec, sleep=lambda s: None)
    assert len(calls) == 1  # never retried
    assert rec.events[-1]["outcome"] == "fatal"


def test_call_with_retry_oom_never_retries_same_shape():
    """These call sites have no shrinkable batch: re-dispatching the same
    shape into an exhausted HBM is doomed, so oom raises immediately to
    the caller's degradation path instead of burning the retry budget."""
    rec = retry.RobustnessRecorder()
    calls = []

    def ooms():
        calls.append(1)
        raise faults.OomChaosError("RESOURCE_EXHAUSTED: hbm full")

    with pytest.raises(faults.OomChaosError):
        retry.call_with_retry("site", ooms, recorder=rec, sleep=lambda s: None)
    assert len(calls) == 1
    assert rec.events[-1]["classification"] == "oom"
    assert rec.events[-1]["outcome"] == "not_retryable"


def test_call_with_retry_exhausts_and_reraises():
    rec = retry.RobustnessRecorder()
    calls = []

    def always_flaky():
        calls.append(1)
        raise faults.TransientChaosError("still down")

    with pytest.raises(faults.TransientChaosError):
        retry.call_with_retry(
            "site", always_flaky,
            policy=retry.RetryPolicy(max_attempts=3, base_delay_s=0),
            recorder=rec, sleep=lambda s: None,
        )
    assert len(calls) == 3
    assert [e["outcome"] for e in rec.events] == ["retried", "retried", "exhausted"]


def test_call_with_retry_reset_hook_clears_partial_side_effects():
    rows = []
    calls = []

    def fn():
        rows.append("partial")
        calls.append(1)
        if len(calls) < 2:
            raise faults.TransientChaosError("mid-stream")
        return list(rows)

    out = retry.call_with_retry(
        "site", fn, policy=retry.RetryPolicy(max_attempts=2, base_delay_s=0),
        recorder=retry.RobustnessRecorder(), sleep=lambda s: None,
        reset=rows.clear,
    )
    assert out == ["partial"]  # no duplicated partial rows


# --- fault registry ---------------------------------------------------------


def test_faults_skip_times_counters():
    faults.arm([{"site": "polish.dispatch", "kind": "transient",
                 "skip": 1, "times": 2}])
    faults.inject("polish.dispatch")  # skip hit: passes through
    with pytest.raises(faults.TransientChaosError):
        faults.inject("polish.dispatch")
    with pytest.raises(faults.TransientChaosError):
        faults.inject("polish.dispatch")
    faults.inject("polish.dispatch")  # times exhausted: disarmed
    assert faults.fired("polish.dispatch") == 2
    desc = faults.describe()
    assert desc["hits"]["polish.dispatch"] == 4


def test_faults_disarmed_is_noop():
    faults.disarm()
    faults.inject("polish.dispatch")
    assert not faults.active()
    assert faults.fired("polish.dispatch") == 0


def test_faults_unknown_site_or_kind_rejected():
    with pytest.raises(ValueError, match="unknown chaos site"):
        faults.arm([{"site": "nope.nope"}])
    with pytest.raises(ValueError, match="unknown chaos kind"):
        # the bad kind IS the test
        faults.arm([{"site": "polish.dispatch", "kind": "wat"}])  # graftlint: disable=chaos-unknown-kind


def test_faults_oom_and_error_kinds():
    faults.arm([{"site": "polish.dispatch", "kind": "oom"},
                {"site": "assign.dispatch", "kind": "error"}])
    with pytest.raises(faults.OomChaosError, match="RESOURCE_EXHAUSTED"):
        faults.inject("polish.dispatch")
    with pytest.raises(RuntimeError, match="injected error fault"):
        faults.inject("assign.dispatch")


def test_faults_env_arming(monkeypatch):
    faults.disarm()
    monkeypatch.setenv(faults.ENV_VAR, json.dumps(
        {"seed": 5, "faults": [{"site": "overlap.worker"}]}
    ))
    plan = faults.arm_from_env()
    assert plan is not None and plan.seed == 5
    with pytest.raises(faults.TransientChaosError):
        faults.inject("overlap.worker")
    # every run re-declares its chaos state: env arming is FRESH each time
    # (counters reset), and an unset env leaves the current plan untouched
    plan2 = faults.arm_from_env()
    assert plan2 is not plan and faults.fired("overlap.worker") == 0
    monkeypatch.delenv(faults.ENV_VAR)
    assert faults.arm_from_env() is None
    assert faults.active()  # unset env did not disarm plan2


def test_faults_probabilistic_mode_is_seeded():
    def pattern(seed):
        faults.arm([{"site": "polish.dispatch", "p": 0.5, "times": 0}],
                   seed=seed)
        pat = []
        for _ in range(32):
            try:
                faults.inject("polish.dispatch")
                pat.append(0)
            except faults.TransientChaosError:
                pat.append(1)
        return pat

    assert pattern(3) == pattern(3)  # deterministic replay
    assert 0 < sum(pattern(3)) < 32  # actually probabilistic
    assert pattern(3) != pattern(4)  # seed-sensitive


def test_tear_write_truncates_and_disarms(tmp_path):
    path = str(tmp_path / "manifest.json")
    payload = json.dumps({"round1_consensus": 123.0, "counts": 456.0})
    faults.arm([{"site": "layout.manifest_write", "kind": "torn"}])
    assert faults.tear_write("layout.manifest_write", path, payload) is True
    torn = open(path).read()
    assert torn and payload.startswith(torn) and len(torn) < len(payload)
    with pytest.raises(ValueError):
        json.loads(torn)
    # spec exhausted: the next write goes through normally
    assert faults.tear_write("layout.manifest_write", path, payload) is False


# --- recorder ---------------------------------------------------------------


def test_recorder_summary_and_report_write(tmp_path):
    rec = retry.RobustnessRecorder()
    rec.record("a", classification="transient", outcome="retried", attempt=1)
    rec.record("a", classification="transient", outcome="recovered", attempt=2)
    rec.record("b", classification="oom", outcome="oom_shrink",
               detail={"cluster_batch_from": 8, "cluster_batch_to": 4})
    s = rec.summary()
    assert s["a"]["events"] == 2
    assert s["a"]["by_outcome"] == {"retried": 1, "recovered": 1}
    assert s["b"]["by_classification"] == {"oom": 1}
    path = str(tmp_path / "robustness_report.json")
    rec.write(path, policy=retry.RetryPolicy(max_attempts=5))
    report = json.load(open(path))
    assert report["policy"]["max_attempts"] == 5
    assert report["sites"]["b"]["by_outcome"]["oom_shrink"] == 1
    assert len(report["events"]) == 3


# --- preemption coordinator -------------------------------------------------


def test_shutdown_checkpoint_raises_after_request():
    coord = shutdown.ShutdownCoordinator()
    with coord:
        shutdown.checkpoint("run.library_start")  # no-op before request
        shutdown.request("test stop")
        with pytest.raises(shutdown.Preempted) as ei:
            shutdown.checkpoint("run.library_start")
        assert ei.value.site == "run.library_start"
    shutdown.checkpoint("run.library_start")  # deactivated: no-op again


def test_shutdown_preempted_is_not_an_exception():
    # the per-library `except Exception` degradation guard must never
    # swallow a preemption into "library failed, skipped"
    assert not issubclass(shutdown.Preempted, Exception)
    assert issubclass(shutdown.Preempted, BaseException)


def test_shutdown_real_signal_sets_flag_and_restores_handler():
    coord = shutdown.ShutdownCoordinator()
    prev = signal.getsignal(signal.SIGTERM)
    with coord:
        os.kill(os.getpid(), signal.SIGTERM)
        deadline = time.time() + 5.0
        while not coord.requested() and time.time() < deadline:
            time.sleep(0.01)  # delivery lands between bytecodes
        assert coord.requested()
        with pytest.raises(shutdown.Preempted):
            shutdown.checkpoint("after_signal")
    assert signal.getsignal(signal.SIGTERM) is prev


def test_first_real_signal_after_cooperative_request_still_drains():
    """A chaos preempt / request() must not make the NEXT real SIGTERM
    look like a 'second signal': the first actual signal always takes the
    drain path, keeping the handler installed."""
    coord = shutdown.ShutdownCoordinator()
    saved = {sig: signal.getsignal(sig) for sig in coord.SIGNALS}
    try:
        with coord:
            # pre-neuter the saved dispositions so a regression to the old
            # behavior (uninstall + re-kill) cannot take down the process
            coord._previous = {sig: signal.SIG_IGN for sig in coord.SIGNALS}
            shutdown.request("chaos preempt")
            coord._on_signal(signal.SIGTERM, None)  # FIRST real signal
            assert coord._installed  # drain path: no escalation
            assert coord.requested()
            coord._on_signal(signal.SIGTERM, None)  # second real signal
            assert not coord._installed  # now the operator means NOW
    finally:
        for sig, handler in saved.items():  # undo the neutered restore
            signal.signal(sig, handler)
        shutdown.deactivate()


def test_preempt_chaos_kind_triggers_active_coordinator():
    coord = shutdown.ShutdownCoordinator()
    with coord:
        faults.arm([{"site": "run.round1_checkpoint", "kind": "preempt"}])
        faults.inject("run.round1_checkpoint")  # requests, does not raise
        with pytest.raises(shutdown.Preempted):
            shutdown.checkpoint("run.round1_checkpoint")


def test_mutate_input_corrupt_copy_preserves_clean_records(tmp_path):
    """corrupt-input writes a seeded mutated SIBLING (original untouched)
    whose clean records are byte-identical, and is deterministic per plan
    seed; disarmed, mutate_input is a pass-through."""
    from ont_tcrconsensus_tpu.io import fastx

    src = tmp_path / "lib.fastq.gz"
    reads = [(f"r{i}", "ACGT" * 30, "I" * 120) for i in range(10)]
    fastx.write_fastq(src, reads)
    original = src.read_bytes()

    assert faults.mutate_input("ingest.library_fastq", str(src)) == str(src)

    faults.arm([{"site": "ingest.library_fastq", "kind": "corrupt-input"}], seed=7)
    out = faults.mutate_input("ingest.library_fastq", str(src))
    assert out != str(src) and out.endswith(".gz")
    assert src.read_bytes() == original  # never modified in place
    clean = [(r.header, r.sequence, r.quality)
             for r in fastx.read_fastx(src)]
    from ont_tcrconsensus_tpu.io import validate as validate_mod

    recs, bads = validate_mod.parse_path_tolerant(out)
    kept = [(r.header.decode(), r.seq.decode(), r.qual.decode()) for r in recs
            if not r.header.startswith(b"chaos_")]
    assert kept == clean
    assert len(bads) == 3  # the three spliced blocks, all quarantined
    mutated_once = open(out, "rb").read()
    faults.arm([{"site": "ingest.library_fastq", "kind": "corrupt-input"}], seed=7)
    assert open(faults.mutate_input("ingest.library_fastq", str(src)),
                "rb").read() == mutated_once  # seeded determinism
    faults.disarm()


def test_mutate_input_truncate_file(tmp_path):
    from ont_tcrconsensus_tpu.io import fastx

    src = tmp_path / "lib.fastq.gz"
    fastx.write_fastq(src, [(f"r{i}", "ACGT" * 50, "I" * 200) for i in range(50)])
    faults.arm([{"site": "ingest.library_fastq", "kind": "truncate-file"}])
    out = faults.mutate_input("ingest.library_fastq", str(src))
    assert out.endswith(".gz") and os.path.getsize(out) < os.path.getsize(src)
    from ont_tcrconsensus_tpu.io import validate as validate_mod

    recs, bads = validate_mod.parse_path_tolerant(out)
    assert recs, "decodable prefix lost"
    assert any(b.reason == validate_mod.R_GZIP for b in bads)
    faults.disarm()


def test_chaos_sibling_path_never_contains_fastq(tmp_path):
    """ONT's standard naming puts 'fastq' in the stem (fastq_runid_*); the
    chaos copy's name must still evade the '*fastq*' input-discovery glob
    or a leftover copy becomes an extra library on resume."""
    from ont_tcrconsensus_tpu.io import fastx

    src = tmp_path / "fastq_runid_abc_0.fastq.gz"
    fastx.write_fastq(src, [("r1", "ACGT" * 30, "I" * 120)])
    faults.arm([{"site": "ingest.library_fastq", "kind": "corrupt-input"}])
    out = faults.mutate_input("ingest.library_fastq", str(src))
    assert "fastq" not in os.path.basename(out)
    assert out.endswith(".gz")
    faults.disarm()
