"""Band centering under one-sided primer trims (code-review r3 finding).

When only one primer is located, the missed side keeps its adapter junk
inside the virtual-trim span and a symmetric margin split mis-centers the
SW band by ~junk/2 — at band 128 (+/-64) that clipped the true path. The
fused pass anchors the trusted side instead (assign._fused_pass); this
test corrupts the 5' adapter+primer of every read so the 5' match fails,
then requires every read to still pass filters with the correct region at
the default band width.
"""

import numpy as np
import pytest

from ont_tcrconsensus_tpu.cluster import regions
from ont_tcrconsensus_tpu.io import fastx, simulator
from ont_tcrconsensus_tpu.pipeline import assign as A

UMI_FWD = "TTTVVTTVVVVTTVVVVTTVVVVTTVVVVTTT"
UMI_REV = "AAABBBBAABBBBAABBBBAABBBBAABBAAA"


def test_one_sided_trim_reads_stay_in_band():
    from ont_tcrconsensus_tpu.pipeline.config import RunConfig

    lib = simulator.simulate_library(
        seed=51, num_regions=3, molecules_per_region=(2, 3),
        reads_per_molecule=(3, 4), error_model=simulator.OntErrorModel(),
        with_adapters=True, region_len=(1100, 1300),
    )
    res = regions.self_homology_map(lib.reference, cluster_threshold=0.93)
    panel = A.ReferencePanel.build(dict(lib.reference), res.region_cluster)
    primers = RunConfig.from_dict(
        {"reference_file": "x", "fastq_pass_dir": "y"}
    ).primer_sequences()

    rng = np.random.default_rng(0)
    reads = []
    for h, s, q in lib.reads:
        # scramble the first 60 nt: the 5' primer match fails, the read is
        # trimmed only at its 3' end and keeps ~60 nt of junk in the span
        # in-place substitution keeps the simulator's quality string aligned
        s = "".join("ACGT"[rng.integers(4)] for _ in range(60)) + s[60:]
        reads.append(fastx.FastxRecord(h.split()[0], "", s, q))

    eng = A.AssignEngine(panel, UMI_FWD, UMI_REV, primers=primers)
    store, stats = A.run_assign(
        reads, eng, max_ee_rate=0.07, min_len=1000,
        minimal_region_overlap=0.95, max_softclip_5_end=81,
        max_softclip_3_end=76, batch_size=64, max_read_length=4096,
    )
    assert stats.n_pass == stats.n_total == len(reads)

    region_of_mol = {i: m.region for i, m in enumerate(lib.molecules)}
    for blk in store.blocks:
        for i, nm in enumerate(blk.names):
            mol = int(nm.split("_m", 1)[1].split("_", 1)[0])
            assert panel.names[int(blk.region_idx[i])] == region_of_mol[mol]


def test_asymmetric_softclip_budgets_fixed_physical_windows():
    """UMI windows are FIXED in the physical read frame, strand-independent
    (ADVICE r4): the reference hands extract_umis the sequencer-orientation
    read (region_split.py:493-500 get_forward_sequence) and always slices
    seq[:a5] / seq[-a3:] (extract_umis.py:120-121) — it never swaps budgets
    per strand. An earlier revision swapped them (molecule-frame
    reasoning); this pins the parity behavior with budgets asymmetric
    enough (a5=160 >> a3=60, left flank 100 nt) to tell the two apart:

    - plus reads find both UMIs (each inside its window);
    - minus reads find the physical-5' UMI (revcomp of the molecule 3'
      structure, well inside the 160 window) but MISS the physical-3' one
      (the molecule 5' flank ends 100 nt from the read end, outside the
      60 window) — exactly as the reference would. The budget swap would
      have found it (132 < 160), so a regression flips the assertion.
    """
    from ont_tcrconsensus_tpu.io import bucketing
    from ont_tcrconsensus_tpu.ops import encode as enc

    rng = np.random.default_rng(7)
    bases = np.array(list("ACGT"))
    mk = lambda n: "".join(rng.choice(bases, size=n))
    reference = {"R0": mk(1200), "R1": mk(1200)}
    res = regions.self_homology_map(reference, cluster_threshold=0.93)
    panel = A.ReferencePanel.build(reference, res.region_cluster)

    left, right = mk(100), mk(10)           # asymmetric flanks
    iupac = {"V": "ACG", "B": "CGT", "T": "T", "A": "A"}

    def inst(pattern):
        return "".join(iupac[c][rng.integers(len(iupac[c]))] for c in pattern)
    recs = []
    for i, region in enumerate(["R0", "R1", "R0", "R1"]):
        u5, u3 = inst(UMI_FWD), inst(UMI_REV)
        template = left + u5 + reference[region] + u3 + right
        seq = template if i % 2 == 0 else enc.revcomp_str(template)
        recs.append(fastx.FastxRecord(f"r{i}", "", seq, None))

    eng = A.AssignEngine(panel, UMI_FWD, UMI_REV, primers=[], a5=160, a3=60)
    batch = next(bucketing.batch_reads(recs, batch_size=8, with_quals=False))
    out = eng.run_batch(batch, max_ee_rate=0.07, min_len=500)
    valid = batch.lengths > 0
    assert valid.sum() == 4
    assert out["is_rev"][valid].tolist() == [False, True, False, True]
    plus = valid & ~out["is_rev"]
    minus = valid & out["is_rev"]
    assert (out["d5"][plus] == 0).all(), out["d5"][plus]
    assert (out["d3"][plus] == 0).all(), out["d3"][plus]
    assert (out["d5"][minus] == 0).all(), out["d5"][minus]
    # molecule-5' UMI sits 100-132 nt from the minus read's physical 3'
    # end: outside the fixed 60 nt window, so it must NOT be located
    assert (out["d3"][minus] > 3).all(), out["d3"][minus]

    # with both budgets covering both flanks the windows are sufficient on
    # both strands — every UMI found, strand-independent
    eng_wide = A.AssignEngine(panel, UMI_FWD, UMI_REV, primers=[],
                              a5=160, a3=160)
    out_w = eng_wide.run_batch(batch, max_ee_rate=0.07, min_len=500)
    assert (out_w["d5"][valid] == 0).all(), out_w["d5"][valid]
    assert (out_w["d3"][valid] == 0).all(), out_w["d3"][valid]


@pytest.mark.slow  # ~25s: full targeted-vs-fused agreement sweep; the
# non-slow band tests cover the same window math on smaller inputs
def test_targeted_pass_agrees_with_fused_pass():
    """Given the fused pass's own chosen ref as the single candidate, the
    round-2 targeted pass must reproduce its assignment exactly (ridx,
    score, blast-id, spans) — the unit-level counterpart of the e2e A/B
    counts equality."""
    from ont_tcrconsensus_tpu.io import bucketing
    from ont_tcrconsensus_tpu.pipeline.config import RunConfig

    lib = simulator.simulate_library(
        seed=9, num_regions=4, molecules_per_region=(1, 2),
        reads_per_molecule=(1, 2), sub_rate=0.005, ins_rate=0.002,
        del_rate=0.002, region_len=(1200, 1400),
    )
    res = regions.self_homology_map(lib.reference, cluster_threshold=0.93)
    panel = A.ReferencePanel.build(dict(lib.reference), res.region_cluster)
    cfg = RunConfig.from_dict({"reference_file": "x", "fastq_pass_dir": "y"})
    eng = A.AssignEngine(panel, cfg.umi_fwd, cfg.umi_rev, primers=[])

    # molecule-(+)-oriented records, like round-2 consensus input
    recs = [
        fastx.FastxRecord(f"c{i}", "",
                          simulator.LEFT_FLANK + lib.reference[r]
                          + simulator.RIGHT_FLANK, None)
        for i, r in enumerate(lib.reference)
    ]
    import numpy as np

    batch = next(bucketing.batch_reads(recs, batch_size=64, with_quals=False))
    full = eng.run_batch(batch, max_ee_rate=1.0, min_len=1)
    cand = np.full((len(batch.ids), 1), -1, np.int32)
    cand[batch.valid, 0] = full["ridx"][batch.valid]
    import jax

    tgt = jax.device_get(eng.run_batch_targeted_async(batch, cand, min_len=1))
    v = batch.valid
    assert (tgt["ridx"][v] == full["ridx"][v]).all()
    assert (tgt["score"][v] == full["score"][v]).all()
    assert (np.abs(tgt["blast_id"][v] - full["blast_id"][v]) < 1e-6).all()
    assert (tgt["ref_start"][v] == full["ref_start"][v]).all()
    assert (tgt["ref_end"][v] == full["ref_end"][v]).all()
    assert (tgt["d5"][v] == full["d5"][v]).all()
    assert (tgt["d3"][v] == full["d3"][v]).all()
