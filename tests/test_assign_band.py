"""Band centering under one-sided primer trims (code-review r3 finding).

When only one primer is located, the missed side keeps its adapter junk
inside the virtual-trim span and a symmetric margin split mis-centers the
SW band by ~junk/2 — at band 128 (+/-64) that clipped the true path. The
fused pass anchors the trusted side instead (assign._fused_pass); this
test corrupts the 5' adapter+primer of every read so the 5' match fails,
then requires every read to still pass filters with the correct region at
the default band width.
"""

import numpy as np

from ont_tcrconsensus_tpu.cluster import regions
from ont_tcrconsensus_tpu.io import fastx, simulator
from ont_tcrconsensus_tpu.pipeline import assign as A

UMI_FWD = "TTTVVTTVVVVTTVVVVTTVVVVTTVVVVTTT"
UMI_REV = "AAABBBBAABBBBAABBBBAABBBBAABBAAA"


def test_one_sided_trim_reads_stay_in_band():
    from ont_tcrconsensus_tpu.pipeline.config import RunConfig

    lib = simulator.simulate_library(
        seed=51, num_regions=3, molecules_per_region=(2, 3),
        reads_per_molecule=(3, 4), error_model=simulator.OntErrorModel(),
        with_adapters=True, region_len=(1100, 1300),
    )
    res = regions.self_homology_map(lib.reference, cluster_threshold=0.93)
    panel = A.ReferencePanel.build(dict(lib.reference), res.region_cluster)
    primers = RunConfig.from_dict(
        {"reference_file": "x", "fastq_pass_dir": "y"}
    ).primer_sequences()

    rng = np.random.default_rng(0)
    reads = []
    for h, s, q in lib.reads:
        # scramble the first 60 nt: the 5' primer match fails, the read is
        # trimmed only at its 3' end and keeps ~60 nt of junk in the span
        # in-place substitution keeps the simulator's quality string aligned
        s = "".join("ACGT"[rng.integers(4)] for _ in range(60)) + s[60:]
        reads.append(fastx.FastxRecord(h.split()[0], "", s, q))

    eng = A.AssignEngine(panel, UMI_FWD, UMI_REV, primers=primers)
    store, stats = A.run_assign(
        reads, eng, max_ee_rate=0.07, min_len=1000,
        minimal_region_overlap=0.95, max_softclip_5_end=81,
        max_softclip_3_end=76, batch_size=64, max_read_length=4096,
    )
    assert stats.n_pass == stats.n_total == len(reads)

    region_of_mol = {i: m.region for i, m in enumerate(lib.molecules)}
    for blk in store.blocks:
        for i, nm in enumerate(blk.names):
            mol = int(nm.split("_m", 1)[1].split("_", 1)[0])
            assert panel.names[int(blk.region_idx[i])] == region_of_mol[mol]
