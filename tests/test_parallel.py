"""Mesh management + sharded execution on the 8-device virtual CPU mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ont_tcrconsensus_tpu.parallel import mesh as mesh_mod


def test_make_mesh_default_all_data():
    m = mesh_mod.make_mesh()
    assert m.axis_names == ("data",)
    assert m.devices.size == len(jax.devices())


def test_make_mesh_2d_and_overflow():
    m = mesh_mod.make_mesh({"data": 4, "model": 2})
    assert m.devices.shape == (4, 2)
    with pytest.raises(ValueError, match="devices"):
        mesh_mod.make_mesh({"data": 64})


def test_shard_batch_places_leading_axis():
    m = mesh_mod.make_mesh({"data": 8})
    x = np.arange(8 * 16, dtype=np.float32).reshape(8, 16)
    sx = mesh_mod.shard_batch(m, x)
    assert sx.sharding.spec == jax.sharding.PartitionSpec("data", None)
    np.testing.assert_array_equal(np.asarray(sx), x)


def test_sharded_kernel_matches_single_device():
    """The alignment kernel gives identical results under data sharding."""
    from ont_tcrconsensus_tpu.ops import sw_align

    rng = np.random.default_rng(0)
    B, L = 8, 128
    reads = rng.integers(0, 4, (B, L)).astype(np.uint8)
    refs = reads.copy()
    lens = np.full(B, L, np.int32)
    offs = np.zeros(B, np.int32)
    plain = np.asarray(sw_align.align_banded(reads, lens, refs, lens, offs).score)

    m = mesh_mod.make_mesh({"data": 8})
    sreads, srefs, slens, soffs = mesh_mod.shard_batch(m, reads, refs, lens, offs)
    sharded = np.asarray(sw_align.align_banded(sreads, slens, srefs, slens, soffs).score)
    np.testing.assert_array_equal(plain, sharded)


def test_sharded_pileup_matches_single_device():
    """The polish pileup path gives identical columns under lane sharding
    (VERDICT r2 #3: the polish stage must run on every chip)."""
    from ont_tcrconsensus_tpu.ops import pileup

    rng = np.random.default_rng(1)
    C, S, W = 8, 4, 256
    sub = rng.integers(0, 4, (C, S, W)).astype(np.uint8)
    lens = rng.integers(W // 2, W, (C, S)).astype(np.int32)
    drafts = sub[:, 0, :].copy()
    dlens = lens[:, 0].copy()
    plain = pileup.pileup_columns_batch_auto(
        sub, lens, jnp.asarray(drafts), jnp.asarray(dlens),
        band_width=64, out_len=W,
    )
    m = mesh_mod.make_mesh({"data": 8})
    sharded = pileup.pileup_columns_batch_auto(
        sub, lens, jnp.asarray(drafts), jnp.asarray(dlens),
        band_width=64, out_len=W, mesh=m,
    )
    for a, b in zip(plain, sharded):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def _noisy_copy(rng, template):
    """Template codes with a few iid sub/ins/del errors."""
    out = []
    for b in template:
        r = rng.random()
        if r < 0.01:
            continue
        if r < 0.02:
            out.append(rng.integers(0, 4))
        out.append(int(b) if rng.random() > 0.02 else int(rng.integers(0, 4)))
    return np.array(out, np.uint8)


def test_sharded_consensus_matches_single_device():
    from ont_tcrconsensus_tpu.ops import consensus as consensus_mod

    rng = np.random.default_rng(2)
    C, S, W = 8, 6, 256
    sub = np.zeros((C, S, W), np.uint8)
    lens = np.zeros((C, S), np.int32)
    for c in range(C):
        template = rng.integers(0, 4, 180).astype(np.uint8)
        for s in range(S):
            mut = _noisy_copy(rng, template)
            sub[c, s, : len(mut)] = mut
            lens[c, s] = len(mut)
    d0, l0 = consensus_mod.consensus_clusters_batch(sub, lens)
    m = mesh_mod.make_mesh({"data": 8})
    d1, l1 = consensus_mod.consensus_clusters_batch(sub, lens, mesh=m)
    np.testing.assert_array_equal(l0, l1)
    np.testing.assert_array_equal(d0, d1)


def test_sharded_consensus_non_pow2_mesh_axis():
    """A non-pow2 data axis disables converged-cluster compaction (pow2
    sub-batches could not divide it) but must still produce identical
    drafts; C=6 divides the axis so the mesh survives the entry guard."""
    from ont_tcrconsensus_tpu.ops import consensus as consensus_mod

    rng = np.random.default_rng(5)
    C, S, W = 6, 4, 256
    sub = np.zeros((C, S, W), np.uint8)
    lens = np.zeros((C, S), np.int32)
    for c in range(C):
        template = rng.integers(0, 4, 150).astype(np.uint8)
        for s in range(S):
            mut = _noisy_copy(rng, template)
            sub[c, s, : len(mut)] = mut
            lens[c, s] = len(mut)
    d0, l0 = consensus_mod.consensus_clusters_batch(sub, lens)
    m = mesh_mod.make_mesh({"data": 6}, devices=jax.devices()[:6])
    d1, l1, pile = consensus_mod.consensus_clusters_batch(
        sub, lens, mesh=m, keep_final_pileup=True
    )
    np.testing.assert_array_equal(l0, l1)
    np.testing.assert_array_equal(d0, d1)
    assert pile is not None  # converged, with the full-C rounds


def test_graft_entry_single_chip():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    assert np.isfinite(np.asarray(out)).all()


def test_graft_entry_multichip():
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)
