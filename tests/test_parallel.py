"""Mesh management + sharded execution on the 8-device virtual CPU mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ont_tcrconsensus_tpu.parallel import mesh as mesh_mod


def test_make_mesh_default_all_data():
    m = mesh_mod.make_mesh()
    assert m.axis_names == ("data",)
    assert m.devices.size == len(jax.devices())


def test_make_mesh_2d_and_overflow():
    m = mesh_mod.make_mesh({"data": 4, "model": 2})
    assert m.devices.shape == (4, 2)
    with pytest.raises(ValueError, match="devices"):
        mesh_mod.make_mesh({"data": 64})


def test_shard_batch_places_leading_axis():
    m = mesh_mod.make_mesh({"data": 8})
    x = np.arange(8 * 16, dtype=np.float32).reshape(8, 16)
    sx = mesh_mod.shard_batch(m, x)
    assert sx.sharding.spec == jax.sharding.PartitionSpec("data", None)
    np.testing.assert_array_equal(np.asarray(sx), x)


def test_sharded_kernel_matches_single_device():
    """The alignment kernel gives identical results under data sharding."""
    from ont_tcrconsensus_tpu.ops import sw_align

    rng = np.random.default_rng(0)
    B, L = 8, 128
    reads = rng.integers(0, 4, (B, L)).astype(np.uint8)
    refs = reads.copy()
    lens = np.full(B, L, np.int32)
    offs = np.zeros(B, np.int32)
    plain = np.asarray(sw_align.align_banded(reads, lens, refs, lens, offs).score)

    m = mesh_mod.make_mesh({"data": 8})
    sreads, srefs, slens, soffs = mesh_mod.shard_batch(m, reads, refs, lens, offs)
    sharded = np.asarray(sw_align.align_banded(sreads, slens, srefs, slens, soffs).score)
    np.testing.assert_array_equal(plain, sharded)


def test_graft_entry_single_chip():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    assert np.isfinite(np.asarray(out)).all()


def test_graft_entry_multichip():
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)
