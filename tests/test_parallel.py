"""Mesh management + sharded execution on the 8-device virtual CPU mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ont_tcrconsensus_tpu.parallel import mesh as mesh_mod


def test_make_mesh_default_all_data():
    m = mesh_mod.make_mesh()
    assert m.axis_names == ("data",)
    assert m.devices.size == len(jax.devices())


def test_make_mesh_2d_and_overflow():
    m = mesh_mod.make_mesh({"data": 4, "model": 2})
    assert m.devices.shape == (4, 2)
    with pytest.raises(ValueError, match="devices"):
        mesh_mod.make_mesh({"data": 64})


def test_shard_batch_places_leading_axis():
    m = mesh_mod.make_mesh({"data": 8})
    x = np.arange(8 * 16, dtype=np.float32).reshape(8, 16)
    sx = mesh_mod.shard_batch(m, x)
    assert sx.sharding.spec == jax.sharding.PartitionSpec("data", None)
    np.testing.assert_array_equal(np.asarray(sx), x)


def test_sharded_kernel_matches_single_device():
    """The alignment kernel gives identical results under data sharding."""
    from ont_tcrconsensus_tpu.ops import sw_align

    rng = np.random.default_rng(0)
    B, L = 8, 128
    reads = rng.integers(0, 4, (B, L)).astype(np.uint8)
    refs = reads.copy()
    lens = np.full(B, L, np.int32)
    offs = np.zeros(B, np.int32)
    plain = np.asarray(sw_align.align_banded(reads, lens, refs, lens, offs).score)

    m = mesh_mod.make_mesh({"data": 8})
    sreads, srefs, slens, soffs = mesh_mod.shard_batch(m, reads, refs, lens, offs)
    sharded = np.asarray(sw_align.align_banded(sreads, slens, srefs, slens, soffs).score)
    np.testing.assert_array_equal(plain, sharded)


def test_sharded_pileup_matches_single_device():
    """The polish pileup path gives identical columns under lane sharding
    (VERDICT r2 #3: the polish stage must run on every chip)."""
    from ont_tcrconsensus_tpu.ops import pileup

    rng = np.random.default_rng(1)
    C, S, W = 8, 4, 256
    sub = rng.integers(0, 4, (C, S, W)).astype(np.uint8)
    lens = rng.integers(W // 2, W, (C, S)).astype(np.int32)
    drafts = sub[:, 0, :].copy()
    dlens = lens[:, 0].copy()
    plain = pileup.pileup_columns_batch_auto(
        sub, lens, jnp.asarray(drafts), jnp.asarray(dlens),
        band_width=64, out_len=W,
    )
    m = mesh_mod.make_mesh({"data": 8})
    sharded = pileup.pileup_columns_batch_auto(
        sub, lens, jnp.asarray(drafts), jnp.asarray(dlens),
        band_width=64, out_len=W, mesh=m,
    )
    for a, b in zip(plain, sharded):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def _noisy_copy(rng, template):
    """Template codes with a few iid sub/ins/del errors."""
    out = []
    for b in template:
        r = rng.random()
        if r < 0.01:
            continue
        if r < 0.02:
            out.append(rng.integers(0, 4))
        out.append(int(b) if rng.random() > 0.02 else int(rng.integers(0, 4)))
    return np.array(out, np.uint8)


def test_sharded_consensus_matches_single_device():
    from ont_tcrconsensus_tpu.ops import consensus as consensus_mod

    rng = np.random.default_rng(2)
    C, S, W = 8, 6, 256
    sub = np.zeros((C, S, W), np.uint8)
    lens = np.zeros((C, S), np.int32)
    for c in range(C):
        template = rng.integers(0, 4, 180).astype(np.uint8)
        for s in range(S):
            mut = _noisy_copy(rng, template)
            sub[c, s, : len(mut)] = mut
            lens[c, s] = len(mut)
    d0, l0 = consensus_mod.consensus_clusters_batch(sub, lens)
    m = mesh_mod.make_mesh({"data": 8})
    d1, l1 = consensus_mod.consensus_clusters_batch(sub, lens, mesh=m)
    np.testing.assert_array_equal(l0, l1)
    np.testing.assert_array_equal(d0, d1)


def test_sharded_consensus_non_pow2_mesh_axis():
    """A non-pow2 data axis disables converged-cluster compaction (pow2
    sub-batches could not divide it) but must still produce identical
    drafts; C=6 divides the axis so the mesh survives the entry guard."""
    from ont_tcrconsensus_tpu.ops import consensus as consensus_mod

    rng = np.random.default_rng(5)
    C, S, W = 6, 4, 256
    sub = np.zeros((C, S, W), np.uint8)
    lens = np.zeros((C, S), np.int32)
    for c in range(C):
        template = rng.integers(0, 4, 150).astype(np.uint8)
        for s in range(S):
            mut = _noisy_copy(rng, template)
            sub[c, s, : len(mut)] = mut
            lens[c, s] = len(mut)
    d0, l0 = consensus_mod.consensus_clusters_batch(sub, lens)
    m = mesh_mod.make_mesh({"data": 6}, devices=jax.devices()[:6])
    d1, l1, pile = consensus_mod.consensus_clusters_batch(
        sub, lens, mesh=m, keep_final_pileup=True
    )
    np.testing.assert_array_equal(l0, l1)
    np.testing.assert_array_equal(d0, d1)
    assert pile is not None  # converged, with the full-C rounds


def test_graft_entry_single_chip():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    assert np.isfinite(np.asarray(out)).all()


def test_graft_entry_multichip():
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)


# ---------------------------------------------------------------------------
# per-materialized-shard transfer ledger + degraded-mesh machinery (ISSUE 18)


@pytest.fixture
def metrics_reg():
    from ont_tcrconsensus_tpu.obs import metrics as obs_metrics

    reg = obs_metrics.arm()
    yield reg
    obs_metrics.disarm()


def test_shard_batch_ledger_charges_per_shard_sum(metrics_reg):
    """A data-sharded placement holds each row on exactly one slice: the
    summed shard bytes — what the h2d ledger is charged — equal the
    logical array size, for any mix of dtypes in one dispatch."""
    m = mesh_mod.make_mesh({"data": 8})
    x = np.zeros((16, 32), np.float32)
    y = np.zeros((16,), np.int32)
    sx, sy = mesh_mod.shard_batch(m, x, y)
    assert mesh_mod.materialized_shard_bytes(sx) == x.nbytes
    s = metrics_reg.summary()
    tr = s["transfers"]["sites"]["transfer.h2d"]
    assert tr["h2d_bytes"] == x.nbytes + y.nbytes
    # every slice of the dispatching mesh is marked busy
    assert s["mesh_slice_busy"] == {
        f"{d.platform}:{d.id}": 1.0 for d in m.devices.flat
    }
    assert s["gauges"]["mesh.slice_busy"] == 8.0


def test_replicated_placement_charges_n_copies(metrics_reg):
    """A replicated placement really moves one copy per device; the
    shard-sum charge is N x logical — the honest interconnect bill the
    single-logical-size ledger used to hide."""
    m = mesh_mod.make_mesh({"data": 8})
    a = np.zeros((4, 4), np.float32)
    placed = jax.device_put(a, mesh_mod.replicated(m))
    assert (mesh_mod.materialized_shard_bytes(placed)
            == m.devices.size * a.nbytes)
    # plain numpy (no shard API): falls back to the logical size
    assert mesh_mod.materialized_shard_bytes(a) == a.nbytes


def test_degrade_mesh_pow2_ladder(metrics_reg):
    """Losing a slice shrinks the data axis to the largest pow2 <= n-1
    (8 -> 4 -> 2 -> 1 -> dead), keeping batch divisibility intact; the
    lost slices' busy gauges drop to 0 and survivors re-mark 1."""
    m = mesh_mod.make_mesh({"data": 8})
    mesh_mod.mark_mesh_slices(m)
    sizes = []
    while m is not None:
        m2 = mesh_mod.degrade_mesh(m)
        if m2 is not None:
            sizes.append(mesh_mod.mesh_data_size(m2))
        m = m2
    assert sizes == [4, 2, 1]
    slices = metrics_reg.summary()["mesh_slice_busy"]
    assert sum(v == 1.0 for v in slices.values()) == 1  # last survivor
    assert sum(v == 0.0 for v in slices.values()) == 7


def test_degrade_mesh_preserves_model_axis():
    m = mesh_mod.make_mesh({"data": 4, "model": 2})
    d = mesh_mod.degrade_mesh(m)
    assert dict(zip(d.axis_names, d.devices.shape)) == {"data": 2, "model": 2}
    # survivors are the FIRST devices of the old mesh, in order
    assert list(d.devices.flat) == list(m.devices.flat)[:4]


def test_degraded_budget_scales_hbm_proportionally():
    from ont_tcrconsensus_tpu.parallel import budget as budget_mod

    b = budget_mod.BudgetModel(hbm_gb=16.0)
    d = budget_mod.degraded_budget(b, 1, 2)
    assert d.hbm_gb == pytest.approx(8.0)
    # every derived batch shrinks (or holds at the pow2 floor), never grows
    assert d.read_batch(1024) <= b.read_batch(1024)
    assert d.cluster_batch(8, 1024) <= b.cluster_batch(8, 1024)
    # no actual loss (or nonsense "growth"): the budget is untouched
    assert budget_mod.degraded_budget(b, 2, 2) is b
    assert budget_mod.degraded_budget(b, 4, 2) is b
    # a second loss compounds against the CURRENT budget
    dd = budget_mod.degraded_budget(d, 1, 2)
    assert dd.hbm_gb == pytest.approx(4.0)


def test_node_sharding_plan_pairs_producer_and_consumer():
    """The production graph's declared Edge.sharding specs resolve to a
    per-node plan where every declared hbm edge carries the SAME axis on
    its producer's out map and each consumer's in map — the pjit
    discipline the executor publishes as ctx.node_shardings."""
    from ont_tcrconsensus_tpu.graph import pipeline as graph_pipeline
    from ont_tcrconsensus_tpu.pipeline.config import RunConfig

    cfg = RunConfig.from_dict({"reference_file": "r.fa",
                               "fastq_pass_dir": "fq"})
    spec = graph_pipeline.build_library_graph(cfg)
    m = mesh_mod.make_mesh({"data": 2})
    plan = mesh_mod.node_sharding_plan(spec, m)
    assert plan, "production graph declares no sharded edges"
    for name, maps in plan.items():
        for e, axis in list(maps["out"].items()) + list(maps["in"].items()):
            assert spec.edges[e].sharding == axis
            sh = mesh_mod.axis_sharding(m, axis, ndim=2)
            assert sh.spec == jax.sharding.PartitionSpec(axis, None)
        for e, axis in maps["out"].items():
            for omaps in plan.values():
                if e in omaps["in"]:
                    assert omaps["in"][e] == axis
