"""Live observability plane (obs/live.py): /healthz /metrics /progress,
the crash flight recorder, ETA priors, and the disarmed-overhead contract.

The e2e pair (live_port=0 vs live off on the same tiny library) is also
the tier-1 live smoke (scripts/tier1.sh): all three endpoints must serve
valid payloads MID-RUN — probed from inside a gated graph node — the
SIGUSR1 flush must land a schema-valid flight_recorder.json, and the
pipeline outputs must stay byte-identical: the live plane observes the
run, it must never change it.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import signal
import threading
import time
import urllib.error
import urllib.request

import pytest

from ont_tcrconsensus_tpu.obs import history as obs_history
from ont_tcrconsensus_tpu.obs import live as obs_live
from ont_tcrconsensus_tpu.obs import metrics as obs_metrics
from ont_tcrconsensus_tpu.obs import report as obs_report
from ont_tcrconsensus_tpu.obs import trace as obs_trace
from ont_tcrconsensus_tpu.robustness import watchdog

# Prometheus text exposition 0.0.4: every sample line is
# name{labels} value — families are announced by # HELP / # TYPE
_PROM_SAMPLE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"'
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*")*\})?'
    r" [0-9eE+.\-]+$"
)


def validate_prometheus(text: str) -> dict[str, int]:
    """Parse an exposition; returns {family sample prefix: sample count}."""
    families: dict[str, int] = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        assert _PROM_SAMPLE.match(line), f"bad exposition line: {line!r}"
        name = line.split("{", 1)[0].split(" ", 1)[0]
        families[name] = families.get(name, 0) + 1
    return families


# ---------------------------------------------------------------------------
# flight recorder ring


def test_flight_ring_bounds_drops_and_atomic_flush(tmp_path):
    ring = obs_live.FlightRecorder(max_events=4)
    assert ring.flush("early") is None  # no path yet: nowhere to write
    for i in range(6):
        ring.add_instant(f"ev{i}")
    stats = ring.stats()
    assert stats["buffered"] == 4 and stats["total"] == 6
    assert stats["dropped"] == 2 and stats["last_flush"] is None
    path = tmp_path / "logs" / "flight_recorder.json"
    ring.set_flush_path(str(path))
    assert ring.flush("test_reason") == str(path)
    assert not path.with_suffix(".json.tmp").exists()
    rec = json.loads(path.read_text())
    assert rec["schema"] == obs_live.FLIGHT_SCHEMA
    assert rec["reason"] == "test_reason" and rec["pid"] == os.getpid()
    assert rec["dropped"] == 2
    assert [e["name"] for e in rec["events"]] == ["ev2", "ev3", "ev4", "ev5"]
    assert all(e["kind"] == "instant" and e["t_s"] >= 0.0 and e["thread"]
               for e in rec["events"])
    assert ring.stats()["last_flush"]["reason"] == "test_reason"


def test_flight_ring_event_kinds():
    ring = obs_live.FlightRecorder()
    with obs_trace.span("round1_polish") as sp:
        pass
    ring.add_span(sp)
    ring.add_instant("chaos.inject", args={"kind": "transient"})
    ring.add_beat("polish.chunk")
    kinds = [(e["kind"], e["name"]) for e in ring.events]
    assert kinds == [("span", "round1_polish"), ("instant", "chaos.inject"),
                     ("heartbeat", "polish.chunk")]
    (span_ev, inst_ev, _) = list(ring.events)
    assert span_ev["dur_s"] >= 0.0 and inst_ev["args"] == {"kind": "transient"}


# ---------------------------------------------------------------------------
# progress tracker + ETA


def test_progress_eta_from_priors_and_measured_override():
    tr = obs_live.ProgressTracker()
    snap = tr.snapshot()
    assert snap["eta_s"] is None and snap["eta_basis"] is None
    tr.set_totals(2)
    tr.set_priors({"a": {"s": 10.0, "units": 0},
                   "b": {"s": 20.0, "units": 0}})
    tr.start_library("barcode01")
    tr.set_plan(["a", "b"])
    snap = tr.snapshot()
    # this library (10+20) + 1 more full library (libs_left excludes the
    # in-flight one): 30 + 30
    assert snap["eta_basis"] == "history_priors"
    assert snap["eta_s"] == pytest.approx(60.0, abs=1.0)
    assert snap["library"] == "barcode01" and snap["nodes_total"] == 2
    # measured pace overrides the prior for later estimates
    tr.node_start("a")
    tr.node_finish("a", 5.0)
    snap = tr.snapshot()
    # remaining b=20, next library a(measured 5)+b(20)=25
    assert snap["eta_s"] == pytest.approx(45.0, abs=1.0)
    assert snap["nodes_done"] == 1
    tr.node_finish("b", 21.0)
    tr.finish_library()
    assert tr.snapshot()["libraries_done"] == 1


def test_progress_eta_measured_pace_and_units_rescale():
    tr = obs_live.ProgressTracker()
    tr.set_totals(1)
    tr.start_library("l")
    tr.set_plan(["a", "b"])
    tr.node_start("a")
    tr.node_finish("a", 8.0)
    snap = tr.snapshot()
    # no priors: basis falls back to this run's own pace; b is unmeasured
    # so it gets the mean of known estimates (8.0)
    assert snap["eta_basis"] == "measured_pace"
    assert snap["eta_s"] == pytest.approx(8.0, abs=1.0)
    # units rescale applies to the IN-FLIGHT node only: a prior measured
    # at 100 units predicts 2x the seconds at 200 units
    tr2 = obs_live.ProgressTracker()
    tr2.set_totals(1)
    tr2.set_priors({"a": {"s": 10.0, "units": 100}})
    tr2.start_library("l")
    tr2.set_plan(["a"])
    tr2.node_start("a", units=200)
    snap = tr2.snapshot()
    assert snap["node"] == "a" and snap["node_units"] == 200
    assert snap["eta_s"] == pytest.approx(20.0, abs=1.0)


def test_progress_in_flight_node_elapsed_is_subtracted_and_clamped():
    tr = obs_live.ProgressTracker()
    tr.set_totals(1)
    tr.set_priors({"a": {"s": 0.05, "units": 0}})
    tr.start_library("l")
    tr.set_plan(["a"])
    tr.node_start("a")
    time.sleep(0.12)  # elapsed > prior: the node estimate clamps at 0
    snap = tr.snapshot()
    assert snap["eta_s"] == pytest.approx(0.0, abs=0.02)
    assert snap["node_elapsed_s"] >= 0.1


def test_load_node_priors_fingerprint_filter_runs_division_median(tmp_path):
    ledger = tmp_path / "history.jsonl"
    entries = [
        # 3 runs summed: per-execution sample is 30/3=10s, 9/3=3 units
        {"schema": 1, "fingerprint": "fp1",
         "nodes": {"n": {"s": 30.0, "runs": 3, "units": 9}}},
        {"schema": 1, "fingerprint": "fp1",
         "nodes": {"n": {"s": 14.0, "runs": 1, "units": 5}}},
        # wrong fingerprint: a differently-sized workload never pollutes
        {"schema": 1, "fingerprint": "fp2",
         "nodes": {"n": {"s": 9000.0, "runs": 1, "units": 1}}},
        # garbage shapes are skipped, never raise
        {"schema": 1, "fingerprint": "fp1", "nodes": "nope"},
        {"schema": 1, "fingerprint": "fp1",
         "nodes": {"n": {"s": True, "runs": 1}, "m": "x"}},
    ]
    with open(ledger, "w") as fh:
        for e in entries:
            fh.write(json.dumps(e) + "\n")
        fh.write("not json\n")
    priors = obs_live.load_node_priors(
        [str(ledger), str(tmp_path / "missing.jsonl")], "fp1")
    assert priors["n"]["s"] == pytest.approx(12.0)   # median(10, 14)
    assert priors["n"]["units"] == pytest.approx(4.0)  # median(3, 5)
    assert obs_live.load_node_priors([str(ledger)], "fp-none") == {}


# ---------------------------------------------------------------------------
# /metrics rendering


def test_metrics_text_is_valid_exposition_and_covers_registry():
    reg = obs_metrics.arm()
    try:
        obs_metrics.counter_add("assign.batches", 3)
        obs_metrics.gauge_max("host.rss_bytes", 12345.0)
        obs_metrics.observe("polish.chunk_clusters", 7)
        reg.stage_add("round1_polish", 0.25)
        obs_metrics.pool_add("overlap.pool", busy_s=1.0, idle_s=0.5,
                             window_s=1.5, slots=2)
        obs_metrics.graph_node_add("round1_polish", critical_s=0.25)
        obs_metrics.mesh_slice_set("cpu:0", 1.0)
        obs_metrics.mesh_slice_set("cpu:1", 0.0)
        obs_metrics.mesh_degraded_add("mesh.device_lost")
        text = obs_live._metrics_text()
    finally:
        obs_metrics.disarm()
    fams = validate_prometheus(text)
    assert fams["tcr_up"] == 1
    assert fams["tcr_counter_total"] >= 1
    assert fams["tcr_gauge"] >= 1
    assert fams["tcr_observations_count"] >= 1
    assert fams["tcr_stage_seconds_total"] >= 1
    assert fams["tcr_pool_busy_seconds_total"] >= 1
    assert fams["tcr_graph_node_critical_seconds_total"] >= 1
    assert fams["tcr_mesh_slice_busy"] == 2
    assert fams["tcr_mesh_degraded_total"] == 1
    assert 'tcr_counter_total{site="assign.batches"} 3' in text
    assert 'tcr_mesh_slice_busy{slice="cpu:1"} 0' in text
    assert 'tcr_mesh_degraded_total{site="mesh.device_lost"} 1' in text
    # disarmed registry: still a valid, non-empty exposition
    fams_off = validate_prometheus(obs_live._metrics_text())
    assert fams_off == {"tcr_up": 1}


def test_prom_label_escaping():
    assert obs_metrics.prom_label('a"b\\c\nd') == 'a\\"b\\\\c\\nd'
    text = (
        f'tcr_counter_total{{site="{obs_metrics.prom_label(chr(10))}"}} 1'
    )
    assert _PROM_SAMPLE.match(text)


# ---------------------------------------------------------------------------
# /healthz verdict


def test_healthz_stalled_verdict_from_watchdog_heartbeat_age():
    payload = obs_live._healthz_payload()
    assert payload["status"] == "ok" and not payload["watchdog"]["armed"]
    wd = watchdog.Watchdog(base_timeout_s=0.2)  # monitor NOT started:
    watchdog.activate(wd)                        # verdict math only
    try:
        with wd.guard("round1_polish"):
            watchdog.heartbeat("polish.chunk")
            fresh = obs_live._healthz_payload()
            assert fresh["status"] == "ok"
            (entry,) = fresh["watchdog"]["stages"]
            assert entry["stage"] == "round1_polish"
            assert entry["last_heartbeat_site"] == "polish.chunk"
            time.sleep(0.15)  # past the soft deadline without a beat
            stale = obs_live._healthz_payload()
            assert stale["status"] == "stalled"
            assert stale["watchdog"]["stalled_stages"] == ["round1_polish"]
    finally:
        watchdog.deactivate(wd)
    assert obs_live._healthz_payload()["status"] == "ok"


# ---------------------------------------------------------------------------
# disarmed overhead: the one-module-attr-check contract


def test_disarmed_live_sites_touch_nothing():
    """Disarmed (the default), every planted live site must reduce to one
    module-attr check: a method-less sentinel in the slot blows up the
    moment any call path touches it, and with the slot at None every call
    is a silent no-op (the test_obs sentinel pattern)."""
    assert obs_live._RING is None and obs_live._PROGRESS is None
    assert obs_trace._RING is None
    obs_live.ring_event("flight.flush", {"reason": "x"})
    obs_live.set_flush_path("/nowhere")
    assert obs_live.flush_armed("crash:Nope") is None
    obs_live.progress_totals(3)
    obs_live.progress_library("barcode01")
    obs_live.progress_plan(["round1_polish"])
    obs_live.progress_node_start("round1_polish", units=4)
    obs_live.progress_node_finish("round1_polish", 1.0)
    obs_live.progress_node_skip("round1_polish")
    obs_live.progress_library_done()
    obs_live.configure_eta_priors(["/nowhere.jsonl"], "fp")  # and no I/O
    sentinel = object()
    obs_live._RING = sentinel
    try:
        with pytest.raises(AttributeError):
            obs_live.ring_event("flight.flush")
    finally:
        obs_live._RING = None
    obs_live._PROGRESS = sentinel
    try:
        with pytest.raises(AttributeError):
            obs_live.progress_node_start("round1_polish")
    finally:
        obs_live._PROGRESS = None
    obs_trace._RING = sentinel
    try:
        with pytest.raises(AttributeError):
            with obs_trace.span("round1_polish"):
                pass
    finally:
        obs_trace._RING = None


def test_watchdog_sinks_disarmed_are_one_attr_check():
    assert watchdog._BEAT_SINK is None and watchdog._EXPIRY_SINK is None
    watchdog.heartbeat("polish.chunk")  # no guard, no sink: silent no-op
    seen: list[str] = []
    watchdog.set_beat_sink(seen.append)
    try:
        # the sink sees every beat even with the watchdog itself disarmed
        watchdog.heartbeat("assign.batch")
    finally:
        watchdog.set_beat_sink(None)
    assert seen == ["assign.batch"]


def test_config_live_port_validation():
    from ont_tcrconsensus_tpu.pipeline.config import RunConfig

    base = {"reference_file": "r.fa", "fastq_pass_dir": "fq"}
    assert RunConfig.from_dict(base).live_port is None
    assert RunConfig.from_dict({**base, "live_port": 0}).live_port == 0
    for bad in (-1, 65536, True, "8080"):
        with pytest.raises(ValueError, match="live_port"):
            RunConfig.from_dict({**base, "live_port": bad})


def test_sigusr1_hook_flushes_and_restores(tmp_path):
    ring = obs_live.FlightRecorder()
    ring.add_instant("chaos.inject")
    path = tmp_path / "flight_recorder.json"
    ring.set_flush_path(str(path))
    obs_live._RING = ring
    hook = obs_live.Sigusr1Hook()
    prev = signal.getsignal(signal.SIGUSR1)
    hook.install()
    try:
        os.kill(os.getpid(), signal.SIGUSR1)
        deadline = time.monotonic() + 5.0
        while not path.exists() and time.monotonic() < deadline:
            time.sleep(0.01)
    finally:
        hook.restore()
        obs_live._RING = None
    assert json.loads(path.read_text())["reason"] == "sigusr1"
    assert signal.getsignal(signal.SIGUSR1) == prev


# ---------------------------------------------------------------------------
# --report flight-recorder tail (satellite: obs/report.py)


def _write_minimal_run(wd):
    wd.mkdir(parents=True, exist_ok=True)
    (wd / "telemetry.json").write_text(json.dumps({"telemetry": "on"}))


def test_report_renders_flight_recorder_tail(tmp_path, capsys):
    wd = tmp_path / "nano_tcr"
    _write_minimal_run(wd)
    (wd / "logs").mkdir()
    rec = {
        "schema": 1, "reason": "sigusr1", "t_wall": 1.0, "t0_wall": 0.0,
        "t0_mono": 0.0, "pid": 7, "dropped": 3,
        "events": [{"kind": "span", "name": "round1_polish", "t_s": 1.25,
                    "dur_s": 0.5, "thread": "MainThread"},
                   {"kind": "heartbeat", "name": "polish.chunk",
                    "t_s": 1.5, "thread": "MainThread"}],
    }
    (wd / "logs" / "flight_recorder.json").write_text(json.dumps(rec))
    assert obs_report.report_main(str(wd)) == 0
    out = capsys.readouterr().out
    assert "flight recorder flight_recorder.json: flushed on 'sigusr1'" in out
    assert "2 buffered event(s), 3 older dropped" in out
    assert "round1_polish" in out and "polish.chunk" in out
    data, rc = obs_report.collect_report(str(wd))
    assert rc == 0
    assert data["flight_recorders"]["flight_recorder.json"]["reason"] == \
        "sigusr1"


def test_report_degrades_on_flight_recorder_garbage(tmp_path, capsys):
    """Never-crash contract: valid-JSON-garbage flight recorders become
    named problems + exit 1, on both the text and --json paths."""
    wd = tmp_path / "nano_tcr"
    _write_minimal_run(wd)
    (wd / "logs").mkdir()
    (wd / "logs" / "flight_recorder.json").write_text(
        '{"schema": 1, "reason": "crash"}')  # events missing
    (wd / "logs" / "flight_recorder_p1.json").write_text('["not", "object"]')
    (wd / "logs" / "flight_recorder_p2.json").write_text("{torn")
    assert obs_report.report_main(str(wd)) == 1
    out = capsys.readouterr().out
    assert "malformed flight recorder flight_recorder.json" in out
    assert "unreadable flight recorder flight_recorder_p1.json" in out
    assert "unreadable flight recorder flight_recorder_p2.json" in out
    data, rc = obs_report.collect_report(str(wd))
    assert rc == 1 and data["flight_recorders"] == {}
    assert len([p for p in data["problems"] if "flight recorder" in p]) == 3


# ---------------------------------------------------------------------------
# e2e: endpoints probed mid-run, SIGUSR1 flush, byte-identity vs live-off


@pytest.fixture(scope="module")
def live_library(tmp_path_factory):
    from ont_tcrconsensus_tpu.io import fastx, simulator

    tmp = tmp_path_factory.mktemp("live_e2e")
    lib = simulator.simulate_library(
        seed=23,
        num_regions=3,
        molecules_per_region=(2, 3),
        reads_per_molecule=(5, 7),
        sub_rate=0.006,
        ins_rate=0.003,
        del_rate=0.003,
        region_len=(700, 850),
    )
    fastx.write_fasta(tmp / "reference.fa", lib.reference.items())
    fq_dir = tmp / "fastq_pass" / "barcode01"
    fq_dir.mkdir(parents=True)
    fastx.write_fastq(fq_dir / "barcode01.fastq.gz", lib.reads)
    return tmp, lib


def _run(src, root, ledger: str, live_port: int | None):
    from ont_tcrconsensus_tpu.pipeline.config import RunConfig
    from ont_tcrconsensus_tpu.pipeline.run import run_with_config

    root.mkdir(parents=True, exist_ok=True)
    shutil.copy(src / "reference.fa", root / "reference.fa")
    shutil.copytree(src / "fastq_pass", root / "fastq_pass")
    raw = {
        "reference_file": str(root / "reference.fa"),
        "fastq_pass_dir": str(root / "fastq_pass"),
        "minimal_length": 600,
        "min_reads_per_cluster": 4,
        "read_batch_size": 64,
        "polish_method": "poa",
        "delete_tmp_files": False,
        "history_ledger": ledger,
    }
    if live_port is not None:
        raw["live_port"] = live_port
    cfg = RunConfig.from_dict(raw)
    return run_with_config(cfg), root / "fastq_pass" / "nano_tcr"


def _fetch(url: str) -> tuple[int, str, str]:
    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            return (resp.status, resp.headers.get("Content-Type", ""),
                    resp.read().decode())
    except urllib.error.HTTPError as err:
        return err.code, err.headers.get("Content-Type", ""), ""


@pytest.fixture(scope="module")
def live_runs(live_library, tmp_path_factory):
    """Run A (live off) seeds the shared ledger with per-node priors and
    is the byte-identity baseline; run B (live_port=0) gates
    round1_polish open while a probe thread scrapes all three endpoints
    mid-run and SIGUSR1-flushes the flight recorder."""
    from ont_tcrconsensus_tpu.graph import nodes as graph_nodes

    src, lib = live_library
    ledger = str(tmp_path_factory.mktemp("live_ledger") / "ledger.jsonl")
    res_a, nano_a = _run(src, tmp_path_factory.mktemp("live_off"), ledger,
                         live_port=None)

    in_node = threading.Event()
    release = threading.Event()
    probed: dict[str, object] = {}

    orig = graph_nodes.round1_polish

    def gated_round1_polish(ctx, inputs):
        in_node.set()
        release.wait(timeout=60.0)
        return orig(ctx, inputs)

    def probe():
        try:
            if not in_node.wait(timeout=300.0):
                probed["error"] = "round1_polish never entered"
                return
            srv = obs_live.server()
            if srv is None:
                probed["error"] = "live server not armed"
                return
            base = f"http://127.0.0.1:{srv.port}"
            for route in ("/healthz", "/metrics", "/progress", "/nope"):
                probed[route] = _fetch(base + route)
            os.kill(os.getpid(), signal.SIGUSR1)
            # the handler runs on the main thread (blocked in an
            # interruptible Event.wait inside the gated node): give the
            # flush a moment to land before releasing the node
            time.sleep(1.0)
        except Exception as exc:  # surfaced by the consuming tests
            probed["error"] = repr(exc)
        finally:
            release.set()

    t = threading.Thread(target=probe, name="live-probe", daemon=True)
    graph_nodes.round1_polish = gated_round1_polish
    try:
        t.start()
        res_b, nano_b = _run(src, tmp_path_factory.mktemp("live_on"),
                             ledger, live_port=0)
    finally:
        graph_nodes.round1_polish = orig
        release.set()
        t.join(timeout=30.0)
    return lib, res_a, nano_a, res_b, nano_b, probed, ledger


def test_live_e2e_endpoints_serve_mid_run(live_runs):
    _, _, _, _, _, probed, _ = live_runs
    assert "error" not in probed, probed.get("error")
    status, ctype, body = probed["/healthz"]
    health = json.loads(body)
    assert status == 200 and ctype.startswith("application/json")
    assert health["status"] in ("ok", "stalled")
    assert health["pid"] == os.getpid()
    assert health["flight_recorder"]["capacity"] == obs_live.MAX_RING_EVENTS
    status, ctype, body = probed["/metrics"]
    assert status == 200 and ctype.startswith("text/plain")
    fams = validate_prometheus(body)
    assert fams["tcr_up"] == 1
    # the probe's own /healthz hit was counted before /metrics rendered
    assert 'tcr_counter_total{site="live.requests"}' in body
    # stages upstream of the gated round1_polish have completed spans
    assert fams.get("tcr_stage_seconds_total", 0) >= 1
    assert probed["/nope"][0] == 404


def test_live_e2e_progress_eta_from_history_priors(live_runs):
    _, _, _, _, _, probed, _ = live_runs
    assert "error" not in probed, probed.get("error")
    status, _, body = probed["/progress"]
    assert status == 200
    prog = json.loads(body)
    assert prog["library"] == "barcode01"
    assert prog["libraries_total"] == 1 and prog["libraries_done"] == 0
    assert prog["node"] == "round1_polish"
    assert 0 <= prog["nodes_done"] < prog["nodes_total"]
    # run A's ledger entry supplies per-node priors for THIS fingerprint
    assert prog["eta_basis"] == "history_priors"
    assert prog["eta_s"] is not None and prog["eta_s"] > 0.0


def test_live_e2e_sigusr1_flushes_schema_valid_flight_recorder(live_runs):
    _, _, _, _, nano_b, probed, _ = live_runs
    assert "error" not in probed, probed.get("error")
    rec = json.loads((nano_b / "logs" / "flight_recorder.json").read_text())
    assert rec["schema"] == obs_live.FLIGHT_SCHEMA
    assert rec["reason"] == "sigusr1"
    assert rec["pid"] == os.getpid()
    assert isinstance(rec["dropped"], int) and rec["dropped"] >= 0
    kinds = {e["kind"] for e in rec["events"]}
    # spans from completed stages, instants from arming/robustness, and
    # heartbeats from the assign/cluster batch loops all reach the ring
    assert {"span", "instant", "heartbeat"} <= kinds
    names = {e["name"] for e in rec["events"]}
    assert "flight.flush" in names
    for ev in rec["events"]:
        assert isinstance(ev["t_s"], float) and ev["thread"]
    # and --report renders the tail from the committed artifact
    text, rc = obs_report.render_report(str(nano_b))
    assert rc == 0
    assert "flight recorder flight_recorder.json: flushed on 'sigusr1'" \
        in text


def test_live_e2e_outputs_byte_identical_to_live_off(live_runs):
    lib, res_a, nano_a, res_b, nano_b, _, _ = live_runs
    assert res_a == res_b == {"barcode01": lib.true_counts}
    for rel in (
        ("barcode01", "counts", "umi_consensus_counts.csv"),
        ("barcode01", "fasta", "merged_consensus.fasta"),
    ):
        a = nano_a.joinpath(*rel).read_bytes()
        b = nano_b.joinpath(*rel).read_bytes()
        assert a == b, f"the live plane must not change {'/'.join(rel)}"


def test_live_e2e_ledger_entries_carry_node_seconds(live_runs):
    """Satellite: obs/history.py records per-node seconds, so the ETA
    priors and the critical-path analyzer share one source of truth."""
    _, _, _, _, _, _, ledger = live_runs
    entries, problems = obs_history.read_entries(ledger)
    assert problems == [] and len(entries) == 2  # run A + run B
    for entry in entries:
        nodes = entry["nodes"]
        assert "round1_polish" in nodes
        for g in nodes.values():
            assert g["s"] >= 0.0 and g["runs"] >= 1
    # the priors run B served its ETA from are reconstructible
    fp = entries[0]["fingerprint"]
    assert entries[1]["fingerprint"] == fp  # live_port is excluded
    priors = obs_live.load_node_priors([ledger], fp)
    assert priors["round1_polish"]["s"] >= 0.0


def test_live_e2e_plane_is_disarmed_after_run(live_runs):
    """run.py's finally must fully disarm: slots cleared, taps unwired,
    port released (the module sentinel contract holds again)."""
    assert obs_live._RING is None and obs_live._PROGRESS is None
    assert obs_live.server() is None
    assert obs_trace._RING is None
    assert watchdog._BEAT_SINK is None and watchdog._EXPIRY_SINK is None
