"""QC artifacts, UMI overlap audit, and the analysis layer."""

import os

import numpy as np
import pytest

from ont_tcrconsensus_tpu.qc import analysis, artifacts, umi_overlap


def _qc_rows():
    return [
        {"name": "rc0_cluster0_8", "region": "TCR1", "ref_span": 1500,
         "read_len": 1600, "region_len": 1500, "blast_id": 0.999, "status": "pass"},
        {"name": "rc0_cluster1_5", "region": "TCR1", "ref_span": 1200,
         "read_len": 1600, "region_len": 1500, "blast_id": 0.99,
         "status": "short", "nt_short": 225.0},
        {"name": "rc1_cluster0_4", "region": "TCR2", "ref_span": 1500,
         "read_len": 3400, "region_len": 1500, "blast_id": 0.99,
         "status": "long", "nt_long": 1743.0},
        {"name": "rc1_cluster2_6", "region": "TCR2", "ref_span": 1510,
         "read_len": 1610, "region_len": 1500, "blast_id": 0.97,
         "status": "low_blast_id"},
    ]


def test_consensus_filter_artifacts(tmp_path):
    paths = artifacts.write_consensus_filter_artifacts(
        _qc_rows(), {"TCR1": 1500, "TCR2": 1500}, str(tmp_path),
        "merged_consensus", blast_id_threshold=0.995, minimal_region_overlap=0.95,
    )
    for key in ("nt_too_short", "region_nt_too_short", "nt_too_long",
                "region_nt_too_long", "blast_id", "region_blast_id",
                "num_subreads_blast_id", "log"):
        assert os.path.exists(paths[key]), key
    blast = (tmp_path / "merged_consensus_region_blast_id.csv").read_text().splitlines()
    assert blast[0] == "region,blast_id"
    assert len(blast) == 3  # pass + low_blast rows reach the blast CSV
    sub = (tmp_path / "merged_consensus_number_of_subreads_blast_id.csv").read_text().splitlines()
    assert sub[1].startswith("8,")
    log = (tmp_path / "merged_consensus_bam_filter.log").read_text()
    assert "Total # primary alignments: 4" in log
    assert "# written alignments passing blast id filter: 1" in log


def test_bam_filter_log_roundtrip(tmp_path):
    artifacts.write_consensus_filter_artifacts(
        _qc_rows(), {"TCR1": 1500, "TCR2": 1500}, str(tmp_path),
        "merged_consensus", blast_id_threshold=0.995, minimal_region_overlap=0.95,
    )
    parsed = analysis.parse_merged_consensus_bam_filter_log(
        str(tmp_path / "merged_consensus_bam_filter.log")
    )
    assert parsed["n_primary"] == 4
    assert parsed["n_short"] == 1
    assert parsed["n_long"] == 1
    assert parsed["n_written"] == 1
    assert parsed["blast_id_threshold"] == pytest.approx(0.995)


def test_umi_overlap_audit(tmp_path):
    region_umis = {
        "TCR1": ["AAAA", "CCCC"],
        "TCR2": ["AAAA", "GGGG"],
        "TCR3": ["TTTT"],
    }
    flags = umi_overlap.count_overlapping_umis(region_umis, str(tmp_path))
    # pairs in combinations order: (1,2)=True, (1,3)=False, (2,3)=False
    assert flags == [True, False, False]
    tsv = (tmp_path / "regions_w_overlapping_umis.tsv").read_text().splitlines()
    assert tsv[1] == "region_TCR1\tregion_TCR2\t1"


def test_count_transforms_and_fits():
    counts = {"a": 100, "b": 120, "c": 3, "nc_full_n": 1}
    kept = analysis.filter_counts_on_log_umi_count_threshold(counts, 1.0)
    assert set(kept) == {"a", "b"}
    assert analysis.negative_control_counts(counts) == {"nc_full_n": 1}
    rng = np.random.default_rng(0)
    x = rng.negative_binomial(20, 0.2, size=200).tolist()
    fits = analysis.fit_count_distributions(x)
    assert fits["ks_nbinom_p"] > 0.01


def test_quantile_threshold_filter():
    counts = {f"r{i}": i + 1 for i in range(20)}  # 1..20
    kept = analysis.filter_counts_on_umi_quantile_threshold(counts, 0.25)
    # quantile(1..20, .25) = 5.75 -> strictly greater keeps 6..20
    assert set(kept) == {f"r{i}" for i in range(5, 20)}
    assert analysis.filter_counts_on_umi_quantile_threshold({}, 0.5) == {}


def test_precision_and_log_hist_plots(tmp_path):
    rows = [("TCR1", 0.9995), ("TCR1", 1.0), ("TCR2", 0.9991)] * 5
    analysis.plot_percent_alignments_above_blast_id(
        rows, str(tmp_path / "p.pdf"),
        minimal_blast_id=0.9992, quantile_95_blast_id=0.999,
        percent_correct_overlap_length=98.4,
    )
    assert (tmp_path / "p.pdf").exists()
    rng = np.random.default_rng(1)
    counts = {f"r{i}": int(c) for i, c in enumerate(
        np.exp(rng.normal(3.0, 0.5, 300)).astype(int) + 1
    )}
    stats = analysis.plot_log_transformed_umi_counts_hist(
        counts, str(tmp_path / "lg.pdf"),
        most_similar_regions={"r0", "r1"},
        log_umi_counts_filter_threshold=1.5,
    )
    assert (tmp_path / "lg.pdf").exists()
    assert stats["ks_normal_p"] > 0.001  # lognormal counts fit a normal in log
    assert "log10_diff_95th_5th" in stats


def test_precision_at_num_subreads():
    rows = [("4", 1.0), ("4", 0.999), ("8", 1.0), ("8", 1.0), ("x", 1.0)]
    est = analysis.estimate_precision_at_num_subreads(rows)
    assert est[4]["n_consensus"] == 2 and est[4]["n_perfect"] == 1
    assert est[4]["precision"] == pytest.approx(0.5)
    assert est[8]["precision"] == 1.0
    assert "x" not in est and 0 not in est


def test_results_summary(tmp_path):
    counts = {"TCR1": 50, "TCR2": 0, "NC_full_n": 2}
    summary = analysis.write_results_summary(
        counts, {"TCR1", "TCR2", "NC_full_n"}, str(tmp_path / "summary.txt"),
    )
    assert summary["num_reference_regions"] == 2
    assert summary["num_detected"] == 1
    assert summary["sensitivity"] == pytest.approx(0.5)
    assert summary["num_negative_controls_with_counts"] == 1
    text = (tmp_path / "summary.txt").read_text()
    assert "missing_regions (1): ['TCR2']" in text


def test_library_analysis_pdfs(tmp_path):
    lib = tmp_path / "barcode01"
    (lib / "logs").mkdir(parents=True)
    (lib / "counts").mkdir()
    artifacts.write_consensus_filter_artifacts(
        _qc_rows(), {"TCR1": 1500, "TCR2": 1500}, str(lib / "logs"),
        "merged_consensus", blast_id_threshold=0.995, minimal_region_overlap=0.95,
    )
    (lib / "counts" / "umi_consensus_counts.csv").write_text(
        "TCR,Count\nTCR1,40\nTCR2,25\n"
    )
    summary = analysis.run_library_analysis(str(lib), {"TCR1", "TCR2"})
    outs = os.listdir(lib / "outs")
    for pdf in ("blast_id_hist.pdf", "umi_count_hist.pdf", "plate_heatmap.pdf",
                "subreads_per_umi.pdf", "blast_id_vs_subreads.pdf",
                "nt_length_deviation.pdf", "results_summary.txt",
                "precision_blast_id_hist.pdf",
                "log_transformed_umi_counts_hist.pdf"):
        assert pdf in outs, pdf
    assert summary["sensitivity"] == 1.0


def test_analysis_cli(tmp_path, capsys):
    """Console-script analysis driver (notebook analogue) over an output tree."""
    from ont_tcrconsensus_tpu.qc.analysis_cli import main

    nano = tmp_path / "nano_tcr"
    lib = nano / "barcode01"
    (lib / "logs").mkdir(parents=True)
    (lib / "counts").mkdir()
    (lib / "counts" / "umi_consensus_counts.csv").write_text(
        "TCR,Count\nTCR1,40\nTCR2,25\n"
    )
    ref = tmp_path / "reference.fa"
    ref.write_text(">TCR1\nACGT\n>TCR2\nTTTT\n")
    assert main([str(nano), str(ref)]) == 0
    out = capsys.readouterr().out
    assert '"sensitivity": 1.0' in out
    assert (lib / "outs" / "results_summary.txt").exists()
    assert (lib / "outs" / "umi_count_hist.pdf").exists()

    # precision-at-depth report appears when the subreads artifact exists
    (lib / "logs" / "merged_consensus_number_of_subreads_blast_id.csv").write_text(
        "number_of_subreads,blast_id\n4,1.0\n4,0.99\n6,1.0\n"
    )
    assert main([str(nano), str(ref)]) == 0
    tsv = (lib / "outs" / "precision_at_num_subreads.tsv").read_text().splitlines()
    assert tsv[0] == "num_subreads\tn_consensus\tn_perfect\tprecision"
    assert tsv[1].startswith("4\t2\t1\t0.5")
    assert tsv[2].startswith("6\t1\t1\t1")


def test_error_profile_cs_strings():
    """banded_cs emits reference-syntax cs strings with exact edit cost."""
    import numpy as np

    from ont_tcrconsensus_tpu.ops import encode
    from ont_tcrconsensus_tpu.qc.error_profile import banded_cs

    r = encode.encode_seq("ACGTACGTACGTACGTACGT")
    assert banded_cs(r, r) == ":20"
    # one substitution in the middle
    q = r.copy()
    q[10] = (q[10] + 1) % 4
    cs = banded_cs(q, r)
    assert cs.startswith(":10*")
    assert cs.endswith(":9")
    # deletion of two bases
    q = np.concatenate([r[:5], r[7:]])
    cs = banded_cs(q, r)
    assert "-" in cs and cs.count("-") == 1
    # insertion
    q = np.concatenate([r[:5], np.array([0], np.uint8), r[5:]])
    cs = banded_cs(q, r)
    assert "+a" in cs


def test_error_profile_batch_matches_single():
    """banded_cs_batch is bit-identical to per-read banded_cs across ragged
    lengths, strand-flipped reads, and degenerate empty inputs."""
    import numpy as np

    from ont_tcrconsensus_tpu.qc.error_profile import banded_cs, banded_cs_batch

    rng = np.random.default_rng(5)
    queries, refs = [], []
    for _ in range(40):
        m = int(rng.integers(1, 400))
        r = rng.integers(0, 4, size=m).astype(np.uint8)
        q = list(r)
        # mutate: subs, indels at ~5%
        i = 0
        out = []
        while i < len(q):
            roll = rng.random()
            if roll < 0.02:
                out.append(int(rng.integers(0, 4)))  # sub
            elif roll < 0.04:
                pass  # deletion
            elif roll < 0.06:
                out.extend([q[i], int(rng.integers(0, 4))])  # insertion
            else:
                out.append(q[i])
            i += 1
        queries.append(np.array(out, np.uint8))
        refs.append(r)
    # degenerate rows
    queries += [np.zeros(0, np.uint8), np.array([1, 2], np.uint8)]
    refs += [np.array([1, 2, 3], np.uint8), np.zeros(0, np.uint8)]
    batch = banded_cs_batch(queries, refs)
    single = [banded_cs(q, r) for q, r in zip(queries, refs)]
    assert batch == single


def test_error_profile_device_matches_batch():
    """banded_cs_batch_device (the accelerator cs path profile_store routes
    every non-CPU backend through) is bit-identical to banded_cs_batch over
    ragged lengths, degenerate empty inputs, and band-width outliers that
    must fall back to the single-read path — the regression guard the
    module comment at qc/error_profile.py promises (mirrors
    test_error_profile_batch_matches_single)."""
    import numpy as np

    from ont_tcrconsensus_tpu.qc.error_profile import (
        banded_cs_batch,
        banded_cs_batch_device,
    )

    rng = np.random.default_rng(7)
    queries, refs = [], []
    for _ in range(40):
        m = int(rng.integers(1, 400))
        r = rng.integers(0, 4, size=m).astype(np.uint8)
        q = list(r)
        i = 0
        out = []
        while i < len(q):
            roll = rng.random()
            if roll < 0.02:
                out.append(int(rng.integers(0, 4)))  # sub
            elif roll < 0.04:
                pass  # deletion
            elif roll < 0.06:
                out.extend([q[i], int(rng.integers(0, 4))])  # insertion
            else:
                out.append(q[i])
            i += 1
        queries.append(np.array(out, np.uint8))
        refs.append(r)
    # degenerate rows: empty query / empty ref
    queries += [np.zeros(0, np.uint8), np.array([1, 2], np.uint8)]
    refs += [np.array([1, 2, 3], np.uint8), np.zeros(0, np.uint8)]
    # band outliers (|n - m| far above the band): the device path must
    # route them through the scalar fallback, like the host batch does
    queries += [np.array([2], np.uint8), rng.integers(0, 4, 300).astype(np.uint8)]
    refs += [rng.integers(0, 4, 260).astype(np.uint8), np.array([3], np.uint8)]
    # tile=16 forces multiple fixed-shape device tiles over the live rows
    device = banded_cs_batch_device(queries, refs, tile=16)
    host = banded_cs_batch(queries, refs)
    assert device == host


def test_stats_artifacts(tmp_path):
    from ont_tcrconsensus_tpu.pipeline.assign import AlignStats
    from ont_tcrconsensus_tpu.qc import artifacts
    import numpy as np

    stats = AlignStats(n_total=100, n_ee_fail=5, n_trimmed=90, n_aligned=92,
                       n_short=2, n_long=1, n_low_blast=0, n_pass=89)
    stats.pre_filter.update(np.array([100, 200, 300]), np.array([10.0, 12.0, 14.0]))
    stats.post_filter.update(np.array([200, 300]), np.array([12.0, 14.0]))
    p1 = tmp_path / "fq.log"
    artifacts.write_fastq_stats_log(stats, str(p1))
    text = p1.read_text()
    assert "post_trim_pre_filter\t3\t600\t100\t200.0\t300\t12.00" in text
    assert "post_filter_pass\t2\t500\t200\t250.0\t300\t13.00" in text
    p2 = tmp_path / "flag.log"
    artifacts.write_flagstat_log(stats, str(p2))
    text = p2.read_text()
    assert "100 in total" in text
    assert "92 primary mapped" in text
    assert "89 passing all filters" in text
