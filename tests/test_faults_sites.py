"""Chaos-site registry integrity (robustness/faults.py).

Two directions, mirroring graftlint's chaos-site cross-check at runtime:
arming an unknown site must fail fast (the registry's own error path),
and the planted-literal set in the shipped source must equal
``faults.KNOWN_SITES`` exactly — a typo'd plant or a stale registry entry
is a chaos plan that silently tests nothing.
"""

from __future__ import annotations

import os
import sys

import pytest

from ont_tcrconsensus_tpu.robustness import faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def test_unknown_site_rejected_at_spec_construction():
    with pytest.raises(ValueError, match="unknown chaos site"):
        faults.FaultSpec(site="assign.dipsatch")


def test_unknown_site_rejected_at_arm():
    try:
        with pytest.raises(ValueError, match="unknown chaos site"):
            faults.arm([{"site": "no.such.site", "kind": "transient"}])
    finally:
        faults.disarm()


def test_unknown_kind_and_bad_p_rejected():
    with pytest.raises(ValueError, match="unknown chaos kind"):
        # the bad kind IS the test
        faults.FaultSpec(site="assign.dispatch", kind="meteor")  # graftlint: disable=chaos-unknown-kind
    with pytest.raises(ValueError, match="outside"):
        faults.FaultSpec(site="assign.dispatch", p=1.5)


def test_known_sites_match_planted_sites_exactly():
    """Runtime twin of graftlint's chaos-unknown-site / chaos-unplanted-site
    pair: collect every inject/mutate_input/tear_write literal in the
    shipped package and require set equality with KNOWN_SITES."""
    from tools.graftlint.core import Project
    from tools.graftlint.rules.chaos_sites import planted_sites

    project = Project([os.path.join(REPO, "ont_tcrconsensus_tpu")])
    planted = planted_sites(project)
    assert set(planted) == set(faults.KNOWN_SITES), (
        f"planted-but-unknown: {sorted(set(planted) - faults.KNOWN_SITES)}; "
        f"known-but-unplanted: {sorted(faults.KNOWN_SITES - set(planted))}"
    )
