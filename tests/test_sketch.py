"""k-mer sketching: candidate selection, strand detection, revcomp."""

import numpy as np

from ont_tcrconsensus_tpu.io import simulator
from ont_tcrconsensus_tpu.ops import encode, sketch


def _encode_batch(seqs, pad_to):
    return encode.encode_batch(seqs, pad_to=pad_to)


def test_revcomp_batch_matches_host():
    rng = np.random.default_rng(0)
    seqs = ["".join(rng.choice(list("ACGT"), size=int(rng.integers(20, 100)))) for _ in range(8)]
    codes, lens = _encode_batch(seqs, 128)
    rc = np.asarray(sketch.revcomp_batch(codes, lens))
    for i, s in enumerate(seqs):
        want = encode.encode_seq(simulator.revcomp(s))
        np.testing.assert_array_equal(rc[i, : len(s)], want)


def test_candidates_find_true_region_and_strand():
    lib = simulator.simulate_library(seed=5, num_regions=6)
    ref_names = list(lib.reference)
    ref_codes, ref_lens = _encode_batch([lib.reference[n] for n in ref_names], 4096)
    profiles = sketch.kmer_profile(ref_codes, ref_lens)

    reads = [r for r in lib.reads[:64]]
    codes, lens = _encode_batch([seq for _, seq, _ in reads], 4096)
    idx, score, is_rev = sketch.candidates_both_strands(codes, lens, profiles)
    idx, is_rev = np.asarray(idx), np.asarray(is_rev)

    by_mol = {i: m for i, m in enumerate(lib.molecules)}
    correct = strand_ok = 0
    for r, (header, _, _) in enumerate(reads):
        mol = by_mol[int(header.split("mol=")[1].split()[0])]
        orient = header.split("orient=")[1].split()[0]
        if ref_names[idx[r, 0]] == mol.region:
            correct += 1
        if (orient == "-") == bool(is_rev[r]):
            strand_ok += 1
    assert correct == len(reads), "top-1 candidate must be the true region"
    assert strand_ok == len(reads), "strand detection must be exact"


def test_similar_regions_rank_together():
    rng = np.random.default_rng(1)
    ref = simulator.make_reference(rng, num_regions=5, num_similar_pairs=1)
    names = list(ref)
    codes, lens = _encode_batch([ref[n] for n in names], 4096)
    profiles = sketch.kmer_profile(codes, lens)
    sim = np.asarray(sketch.similarity_matrix(profiles, profiles))
    sim_name = [n for n in names if "_sim" in n][0]
    src = sim_name.split("_sim")[0]
    i, j = names.index(src), names.index(sim_name)
    off = sim[i, j]
    others = [sim[i, k] for k in range(len(names)) if k not in (i, j)]
    assert off > 0.5
    assert off > max(others) + 0.3


def test_diag_offset_symmetric():
    off = sketch.diag_offset(np.array([2100, 2000]), np.array([2000, 2100]))
    assert list(off) == [-50, 50]
