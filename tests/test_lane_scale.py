"""Lane-scale regression (VERDICT r2 next #5): >=100k reads end-to-end with
a >=20k-unique-UMI region cluster, so UMI clustering runs its shortlist +
merge-repair path (cluster/umi.py) in the regime where it actually matters.

Run with ``pytest -m slow tests/test_lane_scale.py`` (takes tens of minutes
on a CPU host; minutes on chip).
"""

import sys

import pytest


@pytest.mark.slow
def test_lane_scale_100k_exact_counts(tmp_path):
    sys.path.insert(0, "scripts")
    import lane_scale_proof

    lib, heavy_region, heavy_molecules = lane_scale_proof.build_dataset(
        str(tmp_path), target_reads=100_000
    )
    assert heavy_molecules >= 20_000
    assert len(lib.reads) >= 100_000

    from ont_tcrconsensus_tpu.pipeline.config import RunConfig
    from ont_tcrconsensus_tpu.pipeline.run import run_with_config

    cfg = RunConfig.from_dict({
        "reference_file": str(tmp_path / "reference.fa"),
        "fastq_pass_dir": str(tmp_path / "fastq_pass"),
        "minimal_length": 1000,
        "min_reads_per_cluster": 2,
        "delete_tmp_files": False,
        "write_intermediate_fastas": False,
        "error_profile_sample": 0,
    })
    results = run_with_config(cfg)
    got = results["barcode01"]
    want = lib.true_counts
    # the heavy region is the point: 20k+ molecules through the shortlist path
    assert got.get(heavy_region) == want[heavy_region], (
        got.get(heavy_region), want[heavy_region]
    )
    assert got == want, {
        k: (got.get(k, 0), want.get(k, 0))
        for k in set(got) | set(want) if got.get(k, 0) != want.get(k, 0)
    }
