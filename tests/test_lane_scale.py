"""Lane-scale regression tiers (VERDICT r2 #5, r3 #7).

Two tiers so scale correctness is guarded by a COMMAND, not a one-off
manual artifact:

- medium (``pytest -m slow tests/test_lane_scale.py -k medium``,
  ~10-15 min on the 1-core CPU host): ~3k reads with a >=600-unique-UMI
  heavy region — past the shortlist threshold (cluster/umi.py
  _FULL_MATRIX_MAX=256), so the shortlist + merge-repair path runs in the
  regime where it matters, with exact counts asserted.
- full (``pytest -m slow tests/test_lane_scale.py -k 100k``, hours on CPU,
  minutes on chip): the 100k-read / 20k-unique proof; kept for chip lanes
  and explicitly deselected by ``-k medium`` on CPU hosts. The committed
  artifact for this tier is LANE_SCALE.md (scripts/lane_scale_proof.py).
"""

import sys

import pytest


def _run(tmp_path, target_reads: int, min_heavy: int,
         heavy_floor: float = 0.96):
    sys.path.insert(0, "scripts")
    import lane_scale_proof

    lib, heavy_region, heavy_molecules = lane_scale_proof.build_dataset(
        str(tmp_path), target_reads=target_reads, min_heavy=min_heavy
    )

    from ont_tcrconsensus_tpu.pipeline.config import RunConfig
    from ont_tcrconsensus_tpu.pipeline.run import run_with_config

    cfg = RunConfig.from_dict({
        "reference_file": str(tmp_path / "reference.fa"),
        "fastq_pass_dir": str(tmp_path / "fastq_pass"),
        "minimal_length": 1000,
        "min_reads_per_cluster": 2,
        "delete_tmp_files": False,
        "write_intermediate_fastas": False,
        "error_profile_sample": 0,
    })
    results = run_with_config(cfg)
    got = results["barcode01"]
    want = lib.true_counts
    # The heavy region runs at depth 3, the regime where residual
    # vote+polish errors cost molecules at the blast-id gate — the
    # committed 60k artifact measures 97.5% recovery there (LANE_SCALE.md;
    # VERDICT r3 weak #3). The tier pins a floor so regressions are caught
    # while polisher improvements can only raise it; every depth-4 region
    # must stay EXACT.
    heavy_got = got.get(heavy_region, 0)
    assert heavy_got >= heavy_floor * want[heavy_region], (
        heavy_got, want[heavy_region]
    )
    assert heavy_got <= want[heavy_region], "overcount: molecules invented"
    rest_diffs = {
        k: (got.get(k, 0), want.get(k, 0))
        for k in set(got) | set(want)
        if k != heavy_region and got.get(k, 0) != want.get(k, 0)
    }
    assert not rest_diffs, rest_diffs
    return lib, heavy_molecules


@pytest.mark.slow
def test_lane_scale_medium_counts(tmp_path):
    lib, heavy_molecules = _run(tmp_path, target_reads=3_000, min_heavy=600)
    assert heavy_molecules >= 600          # shortlist regime (>256 uniques)
    assert len(lib.reads) >= 2_500


@pytest.mark.slow
def test_lane_scale_100k_counts(tmp_path):
    lib, heavy_molecules = _run(tmp_path, target_reads=100_000,
                                min_heavy=20_000)
    assert heavy_molecules >= 20_000
    assert len(lib.reads) >= 100_000
