"""Cross-run history ledger + noise-aware perf gate (obs/history.py,
scripts/perf_gate.py).

Unit half: config fingerprint stability, ledger append/rotation,
garbage-line degradation, and the median+MAD gate math on synthetic
ledgers with known answers (identical replay stays quiet, a seeded +30%
regression fails, a thin ledger warns, a noisy baseline self-widens).

E2e half (also the tier-1 perf-gate smoke via scripts/tier1.sh): two tiny
pipeline runs share a cross-run ledger, the gate passes on replay and
fails on a seeded +30% regression, ``--report --critical-path`` explains
the executed graph consistently with the measured wall time, and the
ledger knob leaves the pipeline outputs byte-identical.
"""

from __future__ import annotations

import json
import shutil
import subprocess
import sys
from pathlib import Path

import pytest

from ont_tcrconsensus_tpu.obs import history

REPO_ROOT = Path(__file__).resolve().parents[1]
PERF_GATE = REPO_ROOT / "scripts" / "perf_gate.py"


def _entry(fp="abc", backend="cpu", n_reads=100, **kw) -> dict:
    e = {"schema": 1, "fingerprint": fp, "backend": backend,
         "n_reads": n_reads}
    e.update(kw)
    return e


# ---------------------------------------------------------------------------
# config fingerprint


def test_fingerprint_ignores_paths_but_sees_knobs():
    from ont_tcrconsensus_tpu.pipeline.config import RunConfig

    a = RunConfig.from_dict({"reference_file": "r.fa",
                             "fastq_pass_dir": "fq"})
    b = RunConfig.from_dict({
        "reference_file": "/elsewhere/other.fa",
        "fastq_pass_dir": "/mnt/run42/fastq_pass",
        "history_ledger": "/tmp/BENCH_HISTORY.jsonl",
    })
    # same workload from another directory/machine -> same baseline pool
    assert history.config_fingerprint(a) == history.config_fingerprint(b)
    c = RunConfig.from_dict({"reference_file": "r.fa",
                             "fastq_pass_dir": "fq",
                             "read_batch_size": 32})
    assert history.config_fingerprint(c) != history.config_fingerprint(a)
    assert len(history.config_fingerprint(a)) == 16


def test_fingerprint_is_key_order_insensitive_on_dicts():
    assert (history.config_fingerprint({"a": 2, "b": 1})
            == history.config_fingerprint({"b": 1, "a": 2}))
    assert (history.config_fingerprint({"a": 2, "reference_file": "x"})
            == history.config_fingerprint({"a": 2, "reference_file": "y"}))


def test_git_sha_and_backend_detection_never_raise(tmp_path):
    sha = history.git_sha()  # the package lives in a repo here
    assert sha is None or (len(sha) == 40 and sha == sha.strip())
    assert history.git_sha(cwd=str(tmp_path)) is None  # not a repo
    assert history.detect_backend() in (None, "cpu", "tpu", "gpu")


# ---------------------------------------------------------------------------
# ledger file discipline


def test_append_rotates_to_newest_entries(tmp_path):
    path = str(tmp_path / "h.jsonl")
    for i in range(7):
        history.append_entry(path, _entry(i=i), max_entries=3)
    entries, problems = history.read_entries(path)
    assert problems == []
    assert [e["i"] for e in entries] == [4, 5, 6]


def test_read_entries_degrades_garbage_lines(tmp_path):
    path = tmp_path / "h.jsonl"
    path.write_text(
        json.dumps(_entry(i=0)) + "\n"
        + "{torn half of an entr\n"
        + "[1, 2, 3]\n"
        + "\n"
        + json.dumps(_entry(i=1)) + "\n"
    )
    entries, problems = history.read_entries(str(path))
    assert [e["i"] for e in entries] == [0, 1]
    assert any(p.startswith("line 2: not valid JSON") for p in problems)
    assert any(p.startswith("line 3: not a JSON object") for p in problems)
    entries, problems = history.read_entries(str(tmp_path / "missing.jsonl"))
    assert entries == [] and "unreadable ledger" in problems[0]


def test_build_entry_rolls_up_telemetry_summary():
    tele = {
        "duration_s": 5.0,
        "stages": {"round1_polish": {"seconds": 1.5, "calls": 2},
                   "junk": "not a dict"},
        "dispatch": {"polish.dispatch": {"host_s": 0.1, "block_s": 0.2},
                     "assign.dispatch": {"host_s": 0.3, "block_s": 0.4}},
        "compile": {"count": 3, "seconds": 2.0},
        "gauges": {"device.hbm_bytes_in_use": 100, "host.rss_bytes": 200},
    }
    e = history.build_entry("run", tele, fingerprint="f", sha="s",
                            backend="cpu", extra={"note": 1})
    assert e["schema"] == history.SCHEMA_VERSION
    assert e["source"] == "run" and e["fingerprint"] == "f"
    assert e["duration_s"] == 5.0
    assert e["stages"] == {"round1_polish": 1.5}
    assert e["dispatch_host_s"] == 0.4 and e["dispatch_block_s"] == 0.6
    assert e["compile_count"] == 3 and e["compile_s"] == 2.0
    assert e["hbm_high_water_bytes"] == 100 and e["note"] == 1
    bare = history.build_entry("bench", None, reads_per_sec=12.5)
    assert bare["reads_per_sec"] == 12.5 and "duration_s" not in bare


def test_build_entry_lifts_graftcheck_analysis():
    """The graftcheck verdict summary rides telemetry['analysis'] into the
    ledger entry — additive schema, absent when the analyzer didn't run."""
    tele = {"duration_s": 1.0,
            "analysis": {"graftcheck": {"verdict": "advisories",
                                        "violations": 0, "advisories": 7}}}
    e = history.build_entry("run", tele)
    assert e["graftcheck"]["verdict"] == "advisories"
    assert history.build_entry("run", {"duration_s": 1.0}).get(
        "graftcheck") is None
    # a garbage analysis section degrades to absence, never a crash
    weird = history.build_entry("run", {"duration_s": 1.0,
                                        "analysis": "torn-string"})
    assert weird.get("graftcheck") is None


def test_gate_tolerates_graftcheck_field_and_garbage_values():
    """Entries carrying the analyzer field — even with garbage in it —
    must neither crash the gate nor change its verdict."""
    entries = [dict(_entry(duration_s=10.0),
                    graftcheck={"verdict": "advisories"}) for _ in range(3)]
    entries += [dict(_entry(duration_s=10.0), graftcheck="garbage"),
                dict(_entry(duration_s=10.0), graftcheck=[1, 2])]
    current = dict(_entry(duration_s=10.0),
                   graftcheck={"verdict": "violations"})
    res = history.evaluate_gate(entries, current)
    assert res.status == "pass" and res.n_baseline == 5


# ---------------------------------------------------------------------------
# gate math on synthetic ledgers


def test_gate_quiet_on_identical_replay():
    entries = [_entry(duration_s=10.0) for _ in range(5)]
    res = history.evaluate_gate(entries, _entry(duration_s=10.0))
    assert res.status == "pass" and res.n_baseline == 5
    assert res.baseline_median == 10.0 and res.baseline_mad == 0.0


def test_gate_fails_seeded_30pct_regression_on_quiet_baseline():
    entries = [_entry(duration_s=10.0) for _ in range(5)]
    res = history.evaluate_gate(entries, _entry(duration_s=13.0))
    assert res.status == "fail" and "regression" in res.reason
    assert res.allowance == pytest.approx(1.5)  # 15% of the median
    # throughput metric gates in the opposite direction
    entries = [_entry(reads_per_sec=100.0) for _ in range(5)]
    assert history.evaluate_gate(
        entries, _entry(reads_per_sec=70.0)).status == "fail"
    assert history.evaluate_gate(
        entries, _entry(reads_per_sec=90.0)).status == "pass"
    # improvements never fail
    assert history.evaluate_gate(
        entries, _entry(reads_per_sec=500.0)).status == "pass"


def test_gate_noisy_baseline_widens_its_own_allowance():
    durs = [10.0, 12.0, 8.0, 14.0, 6.0]  # median 10, MAD 2
    entries = [_entry(duration_s=d) for d in durs]
    res = history.evaluate_gate(entries, _entry(duration_s=13.0))
    assert res.status == "pass"  # 4 * 1.4826 * 2 = 11.86s allowance
    assert res.allowance == pytest.approx(4 * history.MAD_SCALE * 2.0)
    # the same +30% WOULD fail were the baseline quiet (previous test);
    # with mad_k=0 the noisy baseline gates at the bare threshold again
    res = history.evaluate_gate(entries, _entry(duration_s=13.0), mad_k=0.0)
    assert res.status == "fail"


def test_gate_warns_on_thin_ledger_and_missing_metric():
    entries = [_entry(duration_s=10.0) for _ in range(2)]
    res = history.evaluate_gate(entries, _entry(duration_s=99.0))
    assert res.status == "warn" and "thin ledger" in res.reason
    res = history.evaluate_gate([], _entry())  # no metric at all
    assert res.status == "warn" and "no usable metric" in res.reason
    # bools are not metrics
    assert history.evaluate_gate(
        [], _entry(duration_s=True)).status == "warn"


def test_gate_baseline_pool_filters_on_fingerprint_backend_n_reads():
    entries = (
        [_entry(fp="other", duration_s=1.0)] * 5
        + [_entry(backend="tpu", duration_s=1.0)] * 5
        + [_entry(n_reads=7, duration_s=1.0)] * 5
        + [_entry(duration_s=10.0)] * 3
    )
    res = history.evaluate_gate(entries, _entry(duration_s=10.0))
    assert res.status == "pass" and res.n_baseline == 3
    assert res.baseline_median == 10.0  # the 1.0s foreigners never entered
    # gating the ledger's own latest entry: identity exclusion, so an
    # identical twin read from disk still counts as baseline
    tail = _entry(duration_s=10.0)
    pool = history.matching_entries(entries + [tail], tail)
    assert len(pool) == 3 and all(e is not tail for e in pool)


def test_gate_pools_per_mesh_config_with_legacy_tolerance():
    """A --mesh data=N arm gates only against entries of the SAME mesh
    shape; legacy entries (written before sharded execution, no
    mesh_config key) pool with single-device runs — never with a mesh
    arm, whose throughput is allowed to beat or trail single-device."""
    assert history.mesh_config_str(None) is None
    assert history.mesh_config_str({}) is None
    assert history.mesh_config_str({"data": 8}) == "data=8"
    assert history.mesh_config_str({"data": 4, "model": 2}) == "data=4,model=2"

    legacy = [_entry(duration_s=1.0) for _ in range(3)]  # no mesh_config key
    meshed = [_entry(duration_s=9.0, mesh_config="data=8") for _ in range(3)]
    entries = legacy + meshed
    # a mesh arm pools only with its own shape (9x slower than legacy: fine)
    res = history.evaluate_gate(
        entries, _entry(duration_s=9.0, mesh_config="data=8"))
    assert res.status == "pass" and res.n_baseline == 3
    assert res.baseline_median == 9.0
    # a single-device run pools with the legacy entries, not the mesh arm
    pool = history.matching_entries(entries, _entry(duration_s=1.0))
    assert pool == legacy
    # a different mesh shape is its own (empty) pool
    assert history.matching_entries(
        entries, _entry(mesh_config="data=4")) == []


def test_gate_prefers_reads_per_sec_over_duration():
    entries = [_entry(reads_per_sec=100.0, duration_s=10.0)
               for _ in range(5)]
    # duration regressed but throughput held: bench entries gate on rps
    res = history.evaluate_gate(
        entries, _entry(reads_per_sec=100.0, duration_s=50.0))
    assert res.status == "pass" and res.metric == "reads_per_sec"


# ---------------------------------------------------------------------------
# perf_gate CLI (subprocess — the exact surface tier1.sh calls)


def _gate(*args) -> subprocess.CompletedProcess:
    return subprocess.run(
        [sys.executable, str(PERF_GATE), *map(str, args)],
        capture_output=True, text=True, timeout=120,
    )


@pytest.fixture
def quiet_ledger(tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    for _ in range(4):
        history.append_entry(path, _entry(duration_s=10.0))
    return path


def test_perf_gate_cli_pass_fail_and_json(tmp_path, quiet_ledger):
    proc = _gate(quiet_ledger)  # latest vs the other three: identical
    assert proc.returncode == 0 and "PASS" in proc.stdout, proc.stderr
    # seeded +30% regression appended as the newest entry
    history.append_entry(quiet_ledger, _entry(duration_s=13.0))
    proc = _gate(quiet_ledger)
    assert proc.returncode == 1
    assert "FAIL" in proc.stdout and "regression" in proc.stdout
    proc = _gate(quiet_ledger, "--json")
    verdict = json.loads(proc.stdout)
    assert verdict["status"] == "fail" and verdict["n_baseline"] == 4
    # --current as an explicit entry file beats 'latest'
    cur = tmp_path / "current.json"
    cur.write_text(json.dumps(_entry(duration_s=10.1)))
    proc = _gate(quiet_ledger, "--current", str(cur))
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_perf_gate_cli_warn_and_usage_paths(tmp_path, quiet_ledger):
    proc = _gate(quiet_ledger, "--min-samples", "99")
    assert proc.returncode == 0 and "WARN" in proc.stdout
    proc = _gate(tmp_path / "missing.jsonl")
    assert proc.returncode == 2
    proc = _gate(quiet_ledger, "--current", tmp_path / "nope.json")
    assert proc.returncode == 2
    # garbage ledger lines: named stderr warning, verdict still rendered
    with open(quiet_ledger, "a") as fh:
        fh.write("{torn half of an entr\n")
    proc = _gate(quiet_ledger)
    assert proc.returncode == 0 and "PASS" in proc.stdout
    assert "line 5: not valid JSON" in proc.stderr


def test_perf_gate_runs_with_jax_poisoned(quiet_ledger):
    """The gate (like --report) must work on a wedged-tunnel host where
    any ``import jax`` hangs or raises."""
    code = (
        "import sys, runpy\n"
        "sys.modules['jax'] = None\n"
        f"sys.argv = ['perf_gate.py', {quiet_ledger!r}]\n"
        f"runpy.run_path({str(PERF_GATE)!r}, run_name='__main__')\n"
    )
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    assert "PASS" in proc.stdout


# ---------------------------------------------------------------------------
# e2e: two tiny runs -> shared ledger -> gate; --report --critical-path


@pytest.fixture(scope="module")
def history_library(tmp_path_factory):
    from ont_tcrconsensus_tpu.io import fastx, simulator

    tmp = tmp_path_factory.mktemp("history_e2e")
    lib = simulator.simulate_library(
        seed=29,
        num_regions=2,
        molecules_per_region=(2, 2),
        reads_per_molecule=(5, 6),
        sub_rate=0.006,
        ins_rate=0.003,
        del_rate=0.003,
        region_len=(650, 750),
    )
    fastx.write_fasta(tmp / "reference.fa", lib.reference.items())
    fq_dir = tmp / "fastq_pass" / "barcode01"
    fq_dir.mkdir(parents=True)
    fastx.write_fastq(fq_dir / "barcode01.fastq.gz", lib.reads)
    return tmp, lib


def _run(src, root, ledger: str | None):
    from ont_tcrconsensus_tpu.pipeline.config import RunConfig
    from ont_tcrconsensus_tpu.pipeline.run import run_with_config

    root.mkdir(parents=True, exist_ok=True)
    shutil.copy(src / "reference.fa", root / "reference.fa")
    shutil.copytree(src / "fastq_pass", root / "fastq_pass")
    cfg = RunConfig.from_dict({
        "reference_file": str(root / "reference.fa"),
        "fastq_pass_dir": str(root / "fastq_pass"),
        "minimal_length": 600,
        "min_reads_per_cluster": 4,
        "read_batch_size": 64,
        "polish_method": "poa",
        "delete_tmp_files": False,
        "telemetry": "on",
        **({"history_ledger": ledger} if ledger else {}),
    })
    return run_with_config(cfg), root / "fastq_pass" / "nano_tcr"


@pytest.fixture(scope="module")
def ledger_runs(history_library, tmp_path_factory):
    src, lib = history_library
    ledger = str(tmp_path_factory.mktemp("ledger") / "BENCH_HISTORY.jsonl")
    res1, nano1 = _run(src, tmp_path_factory.mktemp("h_run1"), ledger)
    # the second run takes NO ledger knob (pins both the byte-identity
    # acceptance and history_ledger's exclusion from the fingerprint);
    # its per-run entry is appended by hand, as an operator would
    res2, nano2 = _run(src, tmp_path_factory.mktemp("h_run2"), None)
    entries2, problems2 = history.read_entries(str(nano2 / "history.jsonl"))
    assert problems2 == [] and len(entries2) == 1
    history.append_entry(ledger, entries2[0])
    return lib, res1, nano1, res2, nano2, ledger


def test_run_writes_history_entry(ledger_runs):
    lib, res1, nano1, _, _, ledger = ledger_runs
    assert res1["barcode01"] == lib.true_counts
    entries, problems = history.read_entries(str(nano1 / "history.jsonl"))
    assert problems == [] and len(entries) == 1
    e = entries[0]
    assert e["source"] == "run" and e["schema"] == history.SCHEMA_VERSION
    assert e["backend"] == "cpu"
    assert e["duration_s"] > 0 and e["stages"]
    assert isinstance(e["fingerprint"], str) and len(e["fingerprint"]) == 16
    # recorded entries survive the renderer: --report names the ledger
    from ont_tcrconsensus_tpu.obs import report as obs_report

    text, rc = obs_report.render_report(str(nano1))
    assert rc == 0 and "run history: 1 entrie(s) in history.jsonl" in text


def test_shared_ledger_pools_runs_by_fingerprint(ledger_runs):
    *_, ledger = ledger_runs
    entries, problems = history.read_entries(ledger)
    assert problems == [] and len(entries) == 2
    # different directories, one with the ledger knob set: same pool
    assert entries[0]["fingerprint"] == entries[1]["fingerprint"]
    assert entries[0]["backend"] == entries[1]["backend"] == "cpu"


def test_ledger_knob_keeps_outputs_byte_identical(ledger_runs):
    lib, res1, nano1, res2, nano2, _ = ledger_runs
    assert res1 == res2 == {"barcode01": lib.true_counts}
    for rel in (
        ("barcode01", "counts", "umi_consensus_counts.csv"),
        ("barcode01", "fasta", "merged_consensus.fasta"),
    ):
        assert (nano1.joinpath(*rel).read_bytes()
                == nano2.joinpath(*rel).read_bytes()), rel


def test_perf_gate_passes_replay_and_fails_seeded_regression(
        ledger_runs, tmp_path):
    """The tier-1 smoke contract: a real two-run ledger gates quiet on an
    identical replay and loud on a +30% synthetic regression (mad_k=0
    keeps the two-sample allowance at the bare 15% threshold)."""
    *_, ledger = ledger_runs
    entries, _ = history.read_entries(ledger)
    replay = dict(entries[-1])  # byte-for-byte rerun of the newest run
    good = str(tmp_path / "replay.jsonl")
    shutil.copy(ledger, good)
    history.append_entry(good, replay)
    proc = _gate(good, "--min-samples", "2", "--mad-k", "0")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "PASS" in proc.stdout
    seeded = dict(entries[-1])
    durs = sorted(e["duration_s"] for e in entries)
    med = 0.5 * (durs[0] + durs[1])
    seeded["duration_s"] = round(1.3 * med, 3)  # the seeded +30% regression
    bad = str(tmp_path / "regressed.jsonl")
    shutil.copy(ledger, bad)
    history.append_entry(bad, seeded)
    proc = _gate(bad, "--min-samples", "2", "--mad-k", "0")
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "FAIL" in proc.stdout and "regression" in proc.stdout


def test_report_critical_path_matches_wall_time(ledger_runs, capsys):
    from ont_tcrconsensus_tpu.obs import report as obs_report

    _, _, nano1, *_ = ledger_runs
    assert obs_report.report_main(str(nano1), as_json=True,
                                  critical_path=True) == 0
    data = json.loads(capsys.readouterr().out)
    assert data["problems"] == []
    tele = data["telemetry"]["telemetry.json"]
    cp = data["critical_path"]["telemetry.json"]
    assert cp["problems"] == []
    assert cp["critical_path"], "executed graph must yield a critical path"
    # the critical path is bounded by (and explains most of) the per-
    # library wall time: above the node sum's floor, never above duration
    assert 0 < cp["critical_path_s"] <= cp["nodes_total_s"]
    assert cp["critical_path_s"] <= tele["duration_s"] * 1.05 + 0.5
    nodes = cp["nodes"]
    assert any(n["on_critical_path"] for n in nodes.values())
    for info in nodes.values():
        assert info["slack_s"] >= 0.0 and info["what_if_saved_s"] >= 0.0
    # units flowed from the executor's declarations into the artifact
    assert any(isinstance(n.get("units"), int) and n["units"] > 0
               for n in nodes.values())
    # pool accounting (busy/idle split) landed under graph.pool
    pool = tele["graph"].get("pool")
    assert pool and pool["slots"] >= 1 and pool["busy_s"] >= 0.0
    # human mode renders the same analysis, exit 0
    assert obs_report.report_main(str(nano1), critical_path=True) == 0
    out = capsys.readouterr().out
    assert "critical path:" in out and "what-if" in out


def test_run_history_never_fails_the_run(tmp_path, capsys):
    """record_run's never-crash contract: no armed registry -> silent
    no-op; an unwritable target degrades to a stderr warning, never an
    exception on the run's roll-up path."""
    from ont_tcrconsensus_tpu.obs import metrics as obs_metrics

    assert history.record_run(str(tmp_path), {}) is None  # disarmed
    obs_metrics.arm()
    try:
        blocker = tmp_path / "not_a_dir"
        blocker.write_text("x")  # nano_dir is a file: every write fails
        assert history.record_run(str(blocker), {}) is None
        # armed + writable: the entry lands and is returned
        entry = history.record_run(str(tmp_path), {})
        assert entry is not None and entry["source"] == "run"
        on_disk, problems = history.read_entries(
            str(tmp_path / "history.jsonl"))
        assert problems == [] and len(on_disk) == 1
    finally:
        obs_metrics.disarm()
    assert "could not append run-history entry" in capsys.readouterr().err
