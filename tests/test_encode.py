import numpy as np

from ont_tcrconsensus_tpu.ops import encode


def test_encode_decode_roundtrip():
    s = "ACGTACGTN"
    codes = encode.encode_seq(s)
    assert encode.decode_seq(codes) == s


def test_encode_lowercase():
    assert np.array_equal(encode.encode_seq("acgt"), encode.encode_seq("ACGT"))


def test_revcomp_matches_reference_semantics():
    # reference: str.maketrans("ACTG", "TGAC") then reverse
    # (/root/reference/ont_tcr_consensus/extract_umis.py:10-12)
    def ref_revcomp(seq):
        return seq.translate(str.maketrans("ACTG", "TGAC"))[::-1]

    for s in ["ACGT", "AAATTTCCCGGG", "TTTGGTTGGGGTTGGGGTTT"]:
        assert encode.revcomp_str(s) == ref_revcomp(s)


def test_iupac_masks_match_edlib_equality_table():
    # The 60-pair table at extract_umis.py:26-87 reduces to: degenerate base
    # matches exactly the ACGT expansions of its IUPAC definition.
    expansions = {
        "V": "ACG", "B": "CGT", "D": "AGT", "H": "ACT", "N": "ACGT",
        "R": "AG", "Y": "CT", "S": "CG", "W": "AT", "K": "GT", "M": "AC",
    }
    for deg, bases in expansions.items():
        dm = encode.encode_mask(deg)[0]
        for b in "ACGT":
            bm = encode.encode_mask(b)[0]
            assert bool(dm & bm) == (b in bases), (deg, b)


def test_pad_batch_shapes_and_lengths():
    seqs = [encode.encode_seq(s) for s in ["ACGT", "AC", "ACGTACGT"]]
    batch, lengths = encode.pad_batch(seqs, multiple=128)
    assert batch.shape == (3, 128)
    assert lengths.tolist() == [4, 2, 8]
    assert (batch[1, 2:] == encode.PAD_CODE).all()


def test_code_mask_consistency():
    # codes -> masks must agree with direct mask encoding for ACGTN
    s = "ACGTN"
    via_codes = encode.CODE_TO_MASK[encode.encode_seq(s)]
    direct = encode.encode_mask(s)
    assert np.array_equal(via_codes, direct)
