"""Pileup traceback kernel and iterative vote consensus."""

import numpy as np

from ont_tcrconsensus_tpu.io import simulator
from ont_tcrconsensus_tpu.ops import consensus, encode, pileup


def _pad(seq_codes, width):
    out = np.full((width,), encode.PAD_CODE, np.uint8)
    out[: len(seq_codes)] = seq_codes
    return out


def _pile_one(read_str, draft_str, width=128, band=64):
    read = encode.encode_seq(read_str)
    draft = encode.encode_seq(draft_str)
    base_at, ins_cnt, ins_base, _pos, spans = pileup.pileup_columns(
        _pad(read, width)[None, :],
        np.array([len(read)], np.int32),
        _pad(draft, width),
        np.int32(len(draft)),
        np.zeros(1, np.int32),
        band_width=band,
        out_len=width,
    )
    return np.asarray(base_at)[0], np.asarray(ins_cnt)[0], np.asarray(ins_base)[0]


def test_pileup_exact_read():
    draft = "ACGTACGTAGGTTCACACGGTT"
    base_at, ins_cnt, _ = _pile_one(draft, draft)
    want = encode.encode_seq(draft)
    np.testing.assert_array_equal(base_at[: len(draft)], want)
    assert (base_at[len(draft) :] == pileup.UNCOVERED).all()
    assert (ins_cnt == 0).all()


def test_pileup_substitution():
    draft = "ACGTACGTAGGTTCACACGGTT"
    read = draft[:5] + "T" + draft[6:]  # A->T at position 5 (draft has C at 5)
    assert draft[5] != "T"
    base_at, _, _ = _pile_one(read, draft)
    want = encode.encode_seq(draft)
    got = base_at[: len(draft)]
    diffs = np.where(got != want)[0]
    np.testing.assert_array_equal(diffs, [5])
    assert got[5] == encode.encode_seq("T")[0]


def test_pileup_deletion():
    draft = "ACGTACGTAGGTTCACACGGTT"
    read = draft[:8] + draft[9:]  # draft position 8 deleted
    base_at, _, _ = _pile_one(read, draft)
    assert base_at[8] == pileup.DELETION
    want = encode.encode_seq(draft)
    got = base_at[: len(draft)]
    assert (got[np.arange(len(draft)) != 8] == want[np.arange(len(draft)) != 8]).all()


def test_pileup_insertion():
    draft = "ACGTACGTAGGTTCACACGGTT"
    # inserted base differs from both neighbours (draft[8]='A', draft[9]='G')
    # so the optimal alignment is unambiguous
    read = draft[:9] + "C" + draft[9:]  # insertion after draft position 8
    base_at, ins_cnt, ins_base = _pile_one(read, draft)
    np.testing.assert_array_equal(base_at[: len(draft)], encode.encode_seq(draft))
    hits = np.where(ins_cnt > 0)[0]
    np.testing.assert_array_equal(hits, [8])
    assert ins_base[8] == encode.encode_seq("C")[0]
    assert ins_cnt[8] == 1


def test_pileup_partial_coverage():
    draft = "ACGTACGTAGGTTCACACGGTT"
    read = draft[6:17]  # interior slice only
    base_at, _, _ = _pile_one(read, draft)
    got = base_at[: len(draft)]
    assert (got[:6] == pileup.UNCOVERED).all()
    assert (got[17:] == pileup.UNCOVERED).all()
    np.testing.assert_array_equal(got[6:17], encode.encode_seq(draft)[6:17])


def _noisy_reads(rng, template, n, sub, ins, dele):
    reads = []
    for _ in range(n):
        s, _ = simulator.mutate(rng, template, sub, ins, dele)
        reads.append(encode.encode_seq(s))
    return reads


def test_consensus_recovers_template():
    rng = np.random.default_rng(0)
    template = simulator._rand_seq(rng, 300)
    reads = _noisy_reads(rng, template, 12, 0.02, 0.01, 0.01)
    width = 512
    sub = np.stack([_pad(r, width) for r in reads])
    lens = np.array([len(r) for r in reads], np.int32)
    cons, clen = consensus.consensus_cluster(sub, lens, rounds=3, band_width=128, pad_to=width)
    got = encode.decode_seq(cons, clen)
    assert got == template


def test_consensus_full_amplicon():
    rng = np.random.default_rng(1)
    region = simulator._rand_seq(rng, 1500)
    umi_f = simulator.instantiate_iupac(rng, "TTTVVTTVVVVTTVVVVTTVVVVTTVVVVTTT")
    umi_r = simulator.instantiate_iupac(rng, "AAABBBBAABBBBAABBBBAABBBBAABBAAA")
    template = simulator.LEFT_FLANK + umi_f + region + umi_r + simulator.RIGHT_FLANK
    reads = _noisy_reads(rng, template, 8, 0.02, 0.01, 0.01)
    width = 2048
    sub = np.stack([_pad(r, width) for r in reads])
    lens = np.array([len(r) for r in reads], np.int32)
    cons, clen = consensus.consensus_cluster(sub, lens, rounds=3, band_width=128, pad_to=width)
    got = encode.decode_seq(cons, clen)
    assert got == template, f"consensus differs: len {len(got)} vs {len(template)}"


def test_consensus_low_depth_still_close():
    rng = np.random.default_rng(2)
    template = simulator._rand_seq(rng, 300)
    reads = _noisy_reads(rng, template, 4, 0.02, 0.01, 0.01)
    width = 512
    sub = np.stack([_pad(r, width) for r in reads])
    lens = np.array([len(r) for r in reads], np.int32)
    cons, clen = consensus.consensus_cluster(sub, lens, rounds=3, band_width=128, pad_to=width)
    got = encode.decode_seq(cons, clen)
    # at depth 4 a few residual errors are expected; identity must be high
    from ont_tcrconsensus_tpu.ops import sw_align

    res = sw_align.align_np(encode.encode_seq(got), encode.encode_seq(template))
    assert res.n_match / max(len(template), 1) > 0.99


def test_pileup_features_shape():
    draft = "ACGTACGTAGGTTCACACGGTT"
    base_at, ins_cnt, ins_base = _pile_one(draft, draft, width=128)
    feats = consensus.pileup_features(
        np.asarray(base_at)[None, :], np.asarray(ins_cnt)[None, :],
        np.asarray(ins_base)[None, :],
        _pad(encode.encode_seq(draft), 128),
    )
    assert feats.shape == (128, 15)
    assert bool(np.isfinite(np.asarray(feats)).all())


def test_pileup_pallas_forward_matches_xla():
    """Pallas pileup forward (interpreter) must emit planes/columns identical
    to the XLA scan path on realistic small clusters."""
    from ont_tcrconsensus_tpu.io import simulator
    from ont_tcrconsensus_tpu.ops import pileup

    rng = np.random.default_rng(3)
    C, S, W = 2, 3, 256
    sub = np.full((C, S, W), encode.PAD_CODE, np.uint8)
    lens = np.zeros((C, S), np.int32)
    drafts = np.full((C, W), encode.PAD_CODE, np.uint8)
    dlens = np.zeros((C,), np.int32)
    for c in range(C):
        template = simulator._rand_seq(rng, 180)
        for i in range(S):
            s, _ = simulator.mutate(rng, template, 0.02, 0.01, 0.01)
            e = encode.encode_seq(s)
            sub[c, i, : len(e)] = e
            lens[c, i] = len(e)
        t = encode.encode_seq(template)
        drafts[c, : len(t)] = t
        dlens[c] = len(t)
    # one padded (empty) cluster exercises the no-alignment path
    sub[1] = encode.PAD_CODE
    lens[1] = 0
    dlens[1] = 0

    ref = pileup.pileup_columns_batch(
        sub, lens, drafts, dlens, band_width=64, out_len=W
    )
    got = pileup.pileup_columns_batch_auto(
        sub, lens, drafts, dlens, band_width=64, out_len=W, force_pallas=True
    )
    for a, b, name in zip(ref, got, ("base_at", "ins_cnt", "ins_base", "pos_at", "spans")):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=name)


def test_scan_traceback_matches_while_loop():
    """The scan-log traceback (production path) must be bit-identical to
    the fused while_loop version on the same forward planes."""
    from ont_tcrconsensus_tpu.io import simulator
    from ont_tcrconsensus_tpu.ops import pileup

    rng = np.random.default_rng(9)
    C, S, W = 2, 5, 256
    sub = np.full((C, S, W), encode.PAD_CODE, np.uint8)
    lens = np.zeros((C, S), np.int32)
    drafts = np.full((C, W), encode.PAD_CODE, np.uint8)
    dlens = np.zeros((C,), np.int32)
    for c in range(C):
        template = simulator._rand_seq(rng, 190)
        for i in range(S):
            s, _ = simulator.mutate(rng, template, 0.03, 0.015, 0.015)
            e = encode.encode_seq(s)
            sub[c, i, : len(e)] = e
            lens[c, i] = len(e)
        t = encode.encode_seq(template)
        drafts[c, : len(t)] = t
        dlens[c] = len(t)

    ref = pileup.pileup_columns_batch(
        sub, lens, drafts, dlens, band_width=64, out_len=W
    )
    lanes = C * S
    reads = sub.reshape(lanes, W)
    best, planes = pileup._forward_batch(
        reads, lens.reshape(lanes),
        np.repeat(drafts, S, axis=0), np.repeat(dlens, S),
        band_width=64,
    )
    got = pileup._traceback_batch(best, planes, reads, 64, W)
    shapes = [(C, S, W), (C, S, W), (C, S, W), (C, S, W), (C, S, 4)]
    for a, b, shp, name in zip(
        ref, got, shapes, ("base_at", "ins_cnt", "ins_base", "pos_at", "spans")
    ):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b).reshape(shp), err_msg=name
        )


def test_pileup_pallas_full_width_draft():
    """Regression: drafts extending into the last band_width columns of the
    padded width must still produce exact planes (the pre-shifted ref chunk
    loads previously ran out of the block for ragged L + W)."""
    from ont_tcrconsensus_tpu.io import simulator
    from ont_tcrconsensus_tpu.ops import pileup

    rng = np.random.default_rng(21)
    C, S, W = 1, 3, 256
    sub = np.full((C, S, W), encode.PAD_CODE, np.uint8)
    lens = np.zeros((C, S), np.int32)
    drafts = np.full((C, W), encode.PAD_CODE, np.uint8)
    dlens = np.zeros((C,), np.int32)
    for c in range(C):
        template = simulator._rand_seq(rng, 250)  # within band/2 of W
        for i in range(S):
            s, _ = simulator.mutate(rng, template, 0.02, 0.005, 0.005)
            e = encode.encode_seq(s)[:W]
            sub[c, i, : len(e)] = e
            lens[c, i] = len(e)
        t = encode.encode_seq(template)
        drafts[c, : len(t)] = t
        dlens[c] = len(t)

    ref = pileup.pileup_columns_batch(
        sub, lens, drafts, dlens, band_width=64, out_len=W
    )
    got = pileup.pileup_columns_batch_auto(
        sub, lens, drafts, dlens, band_width=64, out_len=W, force_pallas=True
    )
    for a, b, name in zip(ref, got, ("base_at", "ins_cnt", "ins_base", "pos_at", "spans")):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=name)
