"""Pileup traceback kernel and iterative vote consensus."""

import numpy as np

from ont_tcrconsensus_tpu.io import simulator
from ont_tcrconsensus_tpu.ops import consensus, encode, pileup


def _pad(seq_codes, width):
    out = np.full((width,), encode.PAD_CODE, np.uint8)
    out[: len(seq_codes)] = seq_codes
    return out


def _pile_one(read_str, draft_str, width=128, band=64):
    read = encode.encode_seq(read_str)
    draft = encode.encode_seq(draft_str)
    base_at, ins_cnt, ins_base, _pos, spans = pileup.pileup_columns(
        _pad(read, width)[None, :],
        np.array([len(read)], np.int32),
        _pad(draft, width),
        np.int32(len(draft)),
        np.zeros(1, np.int32),
        band_width=band,
        out_len=width,
    )
    return np.asarray(base_at)[0], np.asarray(ins_cnt)[0], np.asarray(ins_base)[0]


def test_pileup_exact_read():
    draft = "ACGTACGTAGGTTCACACGGTT"
    base_at, ins_cnt, _ = _pile_one(draft, draft)
    want = encode.encode_seq(draft)
    np.testing.assert_array_equal(base_at[: len(draft)], want)
    assert (base_at[len(draft) :] == pileup.UNCOVERED).all()
    assert (ins_cnt == 0).all()


def test_pileup_substitution():
    draft = "ACGTACGTAGGTTCACACGGTT"
    read = draft[:5] + "T" + draft[6:]  # A->T at position 5 (draft has C at 5)
    assert draft[5] != "T"
    base_at, _, _ = _pile_one(read, draft)
    want = encode.encode_seq(draft)
    got = base_at[: len(draft)]
    diffs = np.where(got != want)[0]
    np.testing.assert_array_equal(diffs, [5])
    assert got[5] == encode.encode_seq("T")[0]


def test_pileup_deletion():
    draft = "ACGTACGTAGGTTCACACGGTT"
    read = draft[:8] + draft[9:]  # draft position 8 deleted
    base_at, _, _ = _pile_one(read, draft)
    assert base_at[8] == pileup.DELETION
    want = encode.encode_seq(draft)
    got = base_at[: len(draft)]
    assert (got[np.arange(len(draft)) != 8] == want[np.arange(len(draft)) != 8]).all()


def test_pileup_insertion():
    draft = "ACGTACGTAGGTTCACACGGTT"
    # inserted base differs from both neighbours (draft[8]='A', draft[9]='G')
    # so the optimal alignment is unambiguous
    read = draft[:9] + "C" + draft[9:]  # insertion after draft position 8
    base_at, ins_cnt, ins_base = _pile_one(read, draft)
    np.testing.assert_array_equal(base_at[: len(draft)], encode.encode_seq(draft))
    hits = np.where(ins_cnt > 0)[0]
    np.testing.assert_array_equal(hits, [8])
    assert ins_base[8] == encode.encode_seq("C")[0]
    assert ins_cnt[8] == 1


def test_pileup_partial_coverage():
    draft = "ACGTACGTAGGTTCACACGGTT"
    read = draft[6:17]  # interior slice only
    base_at, _, _ = _pile_one(read, draft)
    got = base_at[: len(draft)]
    assert (got[:6] == pileup.UNCOVERED).all()
    assert (got[17:] == pileup.UNCOVERED).all()
    np.testing.assert_array_equal(got[6:17], encode.encode_seq(draft)[6:17])


def _noisy_reads(rng, template, n, sub, ins, dele):
    reads = []
    for _ in range(n):
        s, _ = simulator.mutate(rng, template, sub, ins, dele)
        reads.append(encode.encode_seq(s))
    return reads


def test_consensus_recovers_template():
    rng = np.random.default_rng(0)
    template = simulator._rand_seq(rng, 300)
    reads = _noisy_reads(rng, template, 12, 0.02, 0.01, 0.01)
    width = 512
    sub = np.stack([_pad(r, width) for r in reads])
    lens = np.array([len(r) for r in reads], np.int32)
    cons, clen = consensus.consensus_cluster(sub, lens, rounds=3, band_width=128, pad_to=width)
    got = encode.decode_seq(cons, clen)
    assert got == template


def test_consensus_full_amplicon():
    rng = np.random.default_rng(1)
    region = simulator._rand_seq(rng, 1500)
    umi_f = simulator.instantiate_iupac(rng, "TTTVVTTVVVVTTVVVVTTVVVVTTVVVVTTT")
    umi_r = simulator.instantiate_iupac(rng, "AAABBBBAABBBBAABBBBAABBBBAABBAAA")
    template = simulator.LEFT_FLANK + umi_f + region + umi_r + simulator.RIGHT_FLANK
    reads = _noisy_reads(rng, template, 8, 0.02, 0.01, 0.01)
    width = 2048
    sub = np.stack([_pad(r, width) for r in reads])
    lens = np.array([len(r) for r in reads], np.int32)
    cons, clen = consensus.consensus_cluster(sub, lens, rounds=3, band_width=128, pad_to=width)
    got = encode.decode_seq(cons, clen)
    assert got == template, f"consensus differs: len {len(got)} vs {len(template)}"


def test_consensus_low_depth_still_close():
    rng = np.random.default_rng(2)
    template = simulator._rand_seq(rng, 300)
    reads = _noisy_reads(rng, template, 4, 0.02, 0.01, 0.01)
    width = 512
    sub = np.stack([_pad(r, width) for r in reads])
    lens = np.array([len(r) for r in reads], np.int32)
    cons, clen = consensus.consensus_cluster(sub, lens, rounds=3, band_width=128, pad_to=width)
    got = encode.decode_seq(cons, clen)
    # at depth 4 a few residual errors are expected; identity must be high
    from ont_tcrconsensus_tpu.ops import sw_align

    res = sw_align.align_np(encode.encode_seq(got), encode.encode_seq(template))
    assert res.n_match / max(len(template), 1) > 0.99


def test_pileup_features_shape():
    draft = "ACGTACGTAGGTTCACACGGTT"
    base_at, ins_cnt, ins_base = _pile_one(draft, draft, width=128)
    feats = consensus.pileup_features(
        np.asarray(base_at)[None, :], np.asarray(ins_cnt)[None, :],
        np.asarray(ins_base)[None, :],
        _pad(encode.encode_seq(draft), 128),
    )
    assert feats.shape == (128, 15)
    assert bool(np.isfinite(np.asarray(feats)).all())


def test_pileup_pallas_forward_matches_xla():
    """Pallas pileup forward (interpreter) must emit planes/columns identical
    to the XLA scan path on realistic small clusters."""
    from ont_tcrconsensus_tpu.io import simulator
    from ont_tcrconsensus_tpu.ops import pileup

    rng = np.random.default_rng(3)
    C, S, W = 2, 3, 256
    sub = np.full((C, S, W), encode.PAD_CODE, np.uint8)
    lens = np.zeros((C, S), np.int32)
    drafts = np.full((C, W), encode.PAD_CODE, np.uint8)
    dlens = np.zeros((C,), np.int32)
    for c in range(C):
        template = simulator._rand_seq(rng, 180)
        for i in range(S):
            s, _ = simulator.mutate(rng, template, 0.02, 0.01, 0.01)
            e = encode.encode_seq(s)
            sub[c, i, : len(e)] = e
            lens[c, i] = len(e)
        t = encode.encode_seq(template)
        drafts[c, : len(t)] = t
        dlens[c] = len(t)
    # one padded (empty) cluster exercises the no-alignment path
    sub[1] = encode.PAD_CODE
    lens[1] = 0
    dlens[1] = 0

    ref = pileup.pileup_columns_batch(
        sub, lens, drafts, dlens, band_width=64, out_len=W
    )
    got = pileup.pileup_columns_batch_auto(
        sub, lens, drafts, dlens, band_width=64, out_len=W, force_pallas=True
    )
    for a, b, name in zip(ref, got, ("base_at", "ins_cnt", "ins_base", "pos_at", "spans")):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=name)


def test_scan_traceback_matches_while_loop():
    """The scan-log traceback (production path) must be bit-identical to
    the fused while_loop version on the same forward planes."""
    from ont_tcrconsensus_tpu.io import simulator
    from ont_tcrconsensus_tpu.ops import pileup

    rng = np.random.default_rng(9)
    C, S, W = 2, 5, 256
    sub = np.full((C, S, W), encode.PAD_CODE, np.uint8)
    lens = np.zeros((C, S), np.int32)
    drafts = np.full((C, W), encode.PAD_CODE, np.uint8)
    dlens = np.zeros((C,), np.int32)
    for c in range(C):
        template = simulator._rand_seq(rng, 190)
        for i in range(S):
            s, _ = simulator.mutate(rng, template, 0.03, 0.015, 0.015)
            e = encode.encode_seq(s)
            sub[c, i, : len(e)] = e
            lens[c, i] = len(e)
        t = encode.encode_seq(template)
        drafts[c, : len(t)] = t
        dlens[c] = len(t)

    ref = pileup.pileup_columns_batch(
        sub, lens, drafts, dlens, band_width=64, out_len=W
    )
    lanes = C * S
    reads = sub.reshape(lanes, W)
    best, planes = pileup._forward_batch(
        reads, lens.reshape(lanes),
        np.repeat(drafts, S, axis=0), np.repeat(dlens, S),
        band_width=64,
    )
    got = pileup._traceback_batch(best, planes, reads, 64, W)
    shapes = [(C, S, W), (C, S, W), (C, S, W), (C, S, W), (C, S, 4)]
    for a, b, shp, name in zip(
        ref, got, shapes, ("base_at", "ins_cnt", "ins_base", "pos_at", "spans")
    ):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b).reshape(shp), err_msg=name
        )


def test_pileup_pallas_full_width_draft():
    """Regression: drafts extending into the last band_width columns of the
    padded width must still produce exact planes (the pre-shifted ref chunk
    loads previously ran out of the block for ragged L + W)."""
    from ont_tcrconsensus_tpu.io import simulator
    from ont_tcrconsensus_tpu.ops import pileup

    rng = np.random.default_rng(21)
    C, S, W = 1, 3, 256
    sub = np.full((C, S, W), encode.PAD_CODE, np.uint8)
    lens = np.zeros((C, S), np.int32)
    drafts = np.full((C, W), encode.PAD_CODE, np.uint8)
    dlens = np.zeros((C,), np.int32)
    for c in range(C):
        template = simulator._rand_seq(rng, 250)  # within band/2 of W
        for i in range(S):
            s, _ = simulator.mutate(rng, template, 0.02, 0.005, 0.005)
            e = encode.encode_seq(s)[:W]
            sub[c, i, : len(e)] = e
            lens[c, i] = len(e)
        t = encode.encode_seq(template)
        drafts[c, : len(t)] = t
        dlens[c] = len(t)

    ref = pileup.pileup_columns_batch(
        sub, lens, drafts, dlens, band_width=64, out_len=W
    )
    got = pileup.pileup_columns_batch_auto(
        sub, lens, drafts, dlens, band_width=64, out_len=W, force_pallas=True
    )
    for a, b, name in zip(ref, got, ("base_at", "ins_cnt", "ins_base", "pos_at", "spans")):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=name)


def _sim_clusters(rng, C, S_range, W, template_len, rates=(0.03, 0.012, 0.012)):
    sub = np.full((C, max(S_range), W), encode.PAD_CODE, np.uint8)
    lens = np.zeros((C, max(S_range)), np.int32)
    drafts_true = []
    for c in range(C):
        template = simulator._rand_seq(rng, template_len)
        drafts_true.append(template)
        for i in range(int(rng.integers(S_range[0], S_range[1] + 1))):
            s, _ = simulator.mutate(rng, template, *rates)
            e = encode.encode_seq(s)[:W]
            sub[c, i, : len(e)] = e
            lens[c, i] = len(e)
    return sub, lens


def test_fused_pair_rounds_match_unfused():
    """The 2-rounds-per-dispatch fused pair program (vote -> extend ->
    vote -> extend in-program, ops/consensus._fused_pair_fn) must be
    bit-identical to the unfused per-round host loop — drafts, lengths AND
    the reused final pileup — across converge-early, converge-late, empty
    and end-erosion clusters."""
    rng = np.random.default_rng(23)
    C, W = 8, 256
    sub, lens = _sim_clusters(rng, C, (2, 6), W, 190)
    sub[3] = encode.PAD_CODE  # empty cluster: the no-alignment path
    lens[3] = 0
    for keep_pos in (True, False):
        ref = consensus.consensus_clusters_batch(
            sub, lens, rounds=4, band_width=64,
            keep_final_pileup=True, keep_pos=keep_pos,
        )
        got = consensus.consensus_clusters_batch(
            sub, lens, rounds=4, band_width=64,
            keep_final_pileup=True, keep_pos=keep_pos, force_fused=True,
        )
        np.testing.assert_array_equal(np.asarray(ref[0]), np.asarray(got[0]))
        np.testing.assert_array_equal(np.asarray(ref[1]), np.asarray(got[1]))
        assert (ref[2] is None) == (got[2] is None)
        if ref[2] is not None:
            names = ("base_at", "ins_cnt", "ins_base", "pos_at")
            for a, b, name in zip(ref[2], got[2], names):
                if a is None or b is None:
                    assert a is None and b is None, name
                    continue
                np.testing.assert_array_equal(
                    np.asarray(a), np.asarray(b), err_msg=name
                )


def test_fused_pair_odd_rounds_and_no_pileup():
    """Odd rounds caps exercise the trailing single-round program behind
    the pairs; keep_final_pileup=False exercises the plain return."""
    rng = np.random.default_rng(29)
    C, W = 4, 256
    sub, lens = _sim_clusters(rng, C, (3, 5), W, 180)
    for rounds in (1, 3):
        ref = consensus.consensus_clusters_batch(
            sub, lens, rounds=rounds, band_width=64
        )
        got = consensus.consensus_clusters_batch(
            sub, lens, rounds=rounds, band_width=64, force_fused=True
        )
        np.testing.assert_array_equal(np.asarray(ref[0]), np.asarray(got[0]))
        np.testing.assert_array_equal(np.asarray(ref[1]), np.asarray(got[1]))


def test_extend_ends_device_matches_batch():
    """The in-program end-extension (jnp) must mirror the host numpy
    version on synthetic span geometries, including the
    majority-at-boundary and width-cap gates."""
    import jax.numpy as jnp

    rng = np.random.default_rng(31)
    C, S, W = 6, 4, 64
    sub = rng.integers(0, 4, (C, S, W)).astype(np.uint8)
    slens = rng.integers(W // 2, W, (C, S)).astype(np.int32)
    drafts = rng.integers(0, 4, (C, W)).astype(np.uint8)
    dlens = rng.integers(W // 2, W - 1, (C,)).astype(np.int32)
    dlens[5] = W  # at the width cap: extension must be suppressed
    spans = np.zeros((C, S, 4), np.int32)
    spans[:, :, 0] = rng.integers(0, 3, (C, S))        # r_start
    spans[:, :, 1] = slens - rng.integers(0, 3, (C, S))  # r_end
    spans[:, :, 2] = rng.integers(0, 2, (C, S))        # f_start
    spans[:, :, 3] = dlens[:, None] - rng.integers(0, 2, (C, S))  # f_end
    aligned = dlens.copy()
    ref_d, ref_l = consensus._extend_ends_batch(
        drafts.copy(), dlens.copy(), sub, slens, spans, aligned
    )
    got_d, got_l = consensus._extend_ends_device(
        jnp.asarray(drafts), jnp.asarray(dlens), jnp.asarray(sub),
        jnp.asarray(slens), jnp.asarray(spans), jnp.asarray(aligned),
    )
    np.testing.assert_array_equal(ref_d, np.asarray(got_d))
    np.testing.assert_array_equal(ref_l, np.asarray(got_l))


def test_pileup_pallas_packed_layout_bands():
    """Direct plane-level parity of the lane-packed Pallas forward against
    the XLA forward, for BOTH supported bands (64 packs two reads per
    128-lane tile, 128 one) and a ragged lane count spanning multiple
    programs plus padding."""
    from ont_tcrconsensus_tpu.ops import pileup, pileup_pallas

    rng = np.random.default_rng(41)
    N, L = 18, 256  # > one 16-read program; pads to 32
    refs = rng.integers(0, 4, size=(N, L)).astype(np.uint8)
    reads = refs.copy()
    mut = rng.random(reads.shape) < 0.08
    reads = np.where(mut, (reads + 1) % 4, reads).astype(np.uint8)
    rlens = rng.integers(L // 2, L + 1, size=N).astype(np.int32)
    tlens = rng.integers(L // 2, L + 1, size=N).astype(np.int32)
    rlens[5] = 0  # dead lane
    for band in (64, 128):
        best_p, tdir_p, fjump_p = pileup_pallas.forward_planes_pallas(
            reads, rlens, refs, tlens, band_width=band, interpret=True
        )
        best_x, planes_x = pileup._forward_batch(
            reads, rlens, refs, tlens, band_width=band
        )
        tdir_x = (np.asarray(planes_x) & 15).astype(np.uint8)
        fjump_x = (np.asarray(planes_x) >> 4).astype(np.uint8)
        np.testing.assert_array_equal(
            np.asarray(tdir_p), tdir_x, err_msg=f"tdir band={band}"
        )
        np.testing.assert_array_equal(
            np.asarray(fjump_p), fjump_x, err_msg=f"fjump band={band}"
        )
        np.testing.assert_array_equal(
            np.asarray(best_p), np.asarray(best_x), err_msg=f"best band={band}"
        )
