import numpy as np
import pytest

from ont_tcrconsensus_tpu.ops import encode, fuzzy_match

UMI_FWD = "TTTVVTTVVVVTTVVVVTTVVVVTTVVVVTTT"  # configs/run_config.json:11
UMI_REV = "AAABBBBAABBBBAABBBBAABBBBAABBAAA"  # configs/run_config.json:12


def _umi_instance(rng, pattern):
    # random concrete realization of a degenerate pattern
    choices = {"V": "ACG", "B": "CGT", "T": "T", "A": "A"}
    return "".join(rng.choice(list(choices[c])) for c in pattern)


def _run_batch(pattern, texts):
    pm = encode.encode_mask(pattern)
    wm, lens = encode.encode_mask_batch(texts)
    d, s, e = fuzzy_match.fuzzy_find(pm, wm, lens)
    return np.asarray(d), np.asarray(s), np.asarray(e)


def test_exact_embedded_match():
    rng = np.random.default_rng(0)
    umi = _umi_instance(rng, UMI_FWD)
    text = "ACGTACGTAC" + umi + "GGTTGAC"
    d, s, e = _run_batch(UMI_FWD, [text])
    assert d[0] == 0
    assert text[s[0] : e[0]] == umi


def test_matches_python_reference_random():
    rng = np.random.default_rng(1)
    texts = []
    for _ in range(24):
        n = int(rng.integers(30, 81))
        texts.append("".join(rng.choice(list("ACGT")) for _ in range(n)))
    d, s, e = _run_batch(UMI_FWD, texts)
    for i, t in enumerate(texts):
        rd, rs, re_ = fuzzy_match.fuzzy_find_np(UMI_FWD, t)
        assert d[i] == rd, (i, t)
        assert e[i] == re_, (i, t)
        assert s[i] == rs, (i, t)


def test_single_errors_give_distance_one():
    rng = np.random.default_rng(2)
    umi = _umi_instance(rng, UMI_REV)
    # substitution of a fixed 'A' flank position to 'G' (A-flank only matches A)
    mutated = "C" + umi[:2] + "G" + umi[3:] + "TT"
    d, _, _ = _run_batch(UMI_REV, [mutated])
    assert d[0] == 1
    # deletion
    deleted = "GG" + umi[:10] + umi[11:] + "AACC"
    d, _, _ = _run_batch(UMI_REV, [deleted])
    assert d[0] == 1
    # insertion
    inserted = umi[:16] + "T" + umi[16:]
    d, _, _ = _run_batch(UMI_REV, [inserted])
    assert d[0] == 1


def test_k_threshold_contract():
    # caller-side k: reference treats dist > k as no-match
    # (extract_umis.py:89-98 returns None on editDistance == -1)
    d, _, _ = _run_batch("TTTT", ["GGGGGGGG"])
    assert d[0] > 3  # no decent match


def test_padding_is_inert():
    # the same window must give the same result at different pad widths
    rng = np.random.default_rng(3)
    umi = _umi_instance(rng, UMI_FWD)
    short = "AC" + umi  # well under either pad width
    pm = encode.encode_mask(UMI_FWD)
    results = []
    for pad_to in (128, 256):
        wm, lens = encode.encode_mask_batch([short], pad_to=pad_to)
        d, s, e = fuzzy_match.fuzzy_find(pm, wm, lens)
        results.append((int(d[0]), int(s[0]), int(e[0])))
    assert results[0] == results[1]
    d0, s0, e0 = results[0]
    assert d0 == 0 and short[s0:e0] == umi


@pytest.mark.parametrize("pattern", [UMI_FWD, UMI_REV])
def test_realistic_adapter_windows(pattern):
    # reference slices 81nt 5' / 76nt 3' windows (extract_umis.py:110-126)
    rng = np.random.default_rng(4)
    wins, truths = [], []
    for _ in range(16):
        umi = _umi_instance(rng, pattern)
        pre = "".join(rng.choice(list("ACGT")) for _ in range(rng.integers(5, 30)))
        post = "".join(rng.choice(list("ACGT")) for _ in range(10))
        win = (pre + umi + post)[:81]
        wins.append(win)
        truths.append((win, umi, len(pre)))
    d, s, e = _run_batch(pattern, wins)
    for i, (win, umi, pre_len) in enumerate(truths):
        assert d[i] == 0
        assert s[i] == pre_len
        assert win[s[i] : e[i]] == umi


def test_multi_pattern_matches_per_pattern_calls():
    """fuzzy_find_multi == one fuzzy_find per pattern, with padded
    variable-length patterns handled exactly."""
    rng = np.random.default_rng(5)
    patterns = [UMI_FWD, UMI_REV, "ACGTACGTACGTACGTACGT", "TTTTGGGGCCCCAAA"]
    texts = []
    for _ in range(16):
        n = int(rng.integers(20, 120))
        texts.append("".join(rng.choice(list("ACGT")) for _ in range(n)))
    wm, lens = encode.encode_mask_batch(texts)

    masks = [encode.encode_mask(p) for p in patterns]
    m = max(len(x) for x in masks)
    stack = np.zeros((len(masks), m), np.uint8)
    for i, x in enumerate(masks):
        stack[i, : len(x)] = x
    plens = np.array([len(x) for x in masks], np.int32)

    dm, sm, em = (np.asarray(a) for a in fuzzy_match.fuzzy_find_multi(
        stack, plens, wm, lens
    ))
    for i, p in enumerate(patterns):
        d, s, e = _run_batch(p, texts)
        np.testing.assert_array_equal(dm[i], d, err_msg=p)
        np.testing.assert_array_equal(sm[i], s, err_msg=p)
        np.testing.assert_array_equal(em[i], e, err_msg=p)
