"""Golden fixtures pinning REFERENCE-TOOL semantics (SURVEY §4(a), VERDICT r3 #4).

Every expected value in this module is derived ON PAPER from the reference
pipeline's documented tool parameters — not from running this framework —
so these tests can fail if our kernels drift from the reference contract:

- edlib.align(mode="HW", k, additionalEqualities=IUPAC)
  (/root/reference/ont_tcr_consensus/extract_umis.py:89-96): infix
  Levenshtein with degenerate-base equality.
- vsearch --cluster_fast --id <t> with --gapopen 0E/40I --mismatch -40
  --match 10 (/root/reference/ont_tcr_consensus/vsearch_umi_cluster.py:44-53):
  free terminal gaps, identity = matching columns / alignment columns
  excluding terminal gaps (vsearch --iddef 2), round-1 id 0.93 and
  round-2 id 0.97 (configs/run_config.json:15, vsearch_umi_cluster.py:94).
- minimap2 blast identity (/root/reference/ont_tcr_consensus/
  minimap2_align.py:13-18): cols = #(M|I|D CIGAR columns),
  blast_id = (cols - NM) / cols with NM = subs + inserted + deleted bases.
- vsearch --fastq_filter --fastq_maxee_rate
  (/root/reference/ont_tcr_consensus/preprocessing.py:104-159):
  sum(10^(-Q/10)) / len <= max_ee_rate.

DIVERGENCES.md consolidates the deliberate divergences these fixtures
skirt (tie-break policy, dovetail free-end budget, transitive closure).
"""

import numpy as np

from ont_tcrconsensus_tpu.cluster import umi as umi_cluster
from ont_tcrconsensus_tpu.ops import encode, ee_filter, fuzzy_match, sw_align

RNG = np.random.default_rng(20260731)
BASES = np.array(list("ACGT"))


def _rand_seq(n, rng=RNG):
    return "".join(rng.choice(BASES, size=n))


def _sub(seq: str, pos: int) -> str:
    """Substitute position ``pos`` with the 'next' base (deterministic)."""
    old = seq[pos]
    new = "ACGT"[("ACGT".index(old) + 1) % 4]
    return seq[:pos] + new + seq[pos + 1:]


def _fuzzy(pattern: str, texts: list[str]):
    pm = encode.encode_mask(pattern)
    wm, lens = encode.encode_mask_batch(texts)
    d, s, e = fuzzy_match.fuzzy_find(pm, wm, lens)
    return np.asarray(d), np.asarray(s), np.asarray(e)


# ---------------------------------------------------------------------------
# edlib HW-mode fixtures (extract_umis.py:89-96)


def test_edlib_hw_exact_iupac_match():
    """Degenerate pattern TTVVT (V={A,C,G}) embedded exactly.

    Paper: edlib HW with the IUPAC equalities finds 'TTACT' at distance 0
    (T=T, T=T, A in V, C in V, T=T); text prefix/suffix are free in HW
    mode. Same for B={C,G,T} via AABBA ~ 'AACTA'."""
    d, s, e = _fuzzy("TTVVT", ["GGGGTTACTGGGG"])
    assert d[0] == 0
    assert ("GGGGTTACTGGGG"[s[0]:e[0]]) == "TTACT"

    d, s, e = _fuzzy("AABBA", ["GGAACTAGG"])
    assert d[0] == 0
    assert ("GGAACTAGG"[s[0]:e[0]]) == "AACTA"


def test_edlib_hw_single_errors_cost_one():
    """One substitution / text-deletion / text-insertion => distance 1.

    Paper derivations against pattern TTVVT:
    - 'TTTCT': col 3 pairs T with V (T not in {A,C,G}) -> 1 sub; no
      alignment with gaps does better (every gap costs >= 1).
    - 'TTAT' (V-column base missing): T,T,A then gap for second V,
      then T -> 1 deletion.
    - 'TTAGCT': TTAG then an inserted C before the final T -> 1 insertion
      (A,G both in V, C consumed by the gap)."""
    d, _, _ = _fuzzy("TTVVT", ["GGGGTTTCTGGGG"])
    assert d[0] == 1
    d, _, _ = _fuzzy("TTVVT", ["GGGGTTATGGGG"])
    assert d[0] == 1
    d, _, _ = _fuzzy("TTVVT", ["GGGGTTAGCTGGGG"])
    assert d[0] == 1


def test_edlib_hw_k_reject_contract():
    """The reference rejects at editDistance > k=3 (edlib returns -1).

    Paper: the real fwd UMI pattern has 14 literal T positions
    (configs/run_config.json:11). Against an all-A window every T
    position costs >= 1 whether substituted or deleted, and V matches A
    for free, so the optimal distance is exactly 14 — far past
    max_pattern_dist=3, which the pipeline (like the reference's None
    return) must reject."""
    pattern = "TTTVVTTVVVVTTVVVVTTVVVVTTVVVVTTT"
    assert pattern.count("T") == 14
    d, _, _ = _fuzzy(pattern, ["A" * 80])
    assert d[0] == 14
    assert d[0] > 3  # reference: result["editDistance"] == -1 => (None, None)


def test_edlib_hw_tiebreak_is_leftmost():
    """Two optimal matches: our documented tie-break picks the smallest
    end (then smallest start). 'TT' in 'AATTATTAA' is exact at [2,4) and
    [5,7); we must return [2,4) deterministically. (edlib's own tie-break
    is undocumented — see DIVERGENCES.md #1.)"""
    d, s, e = _fuzzy("TT", ["AATTATTAA"])
    assert (d[0], s[0], e[0]) == (0, 2, 4)


# ---------------------------------------------------------------------------
# vsearch --cluster_fast fixtures (vsearch_umi_cluster.py:44-53)


def test_vsearch_round1_identity_threshold_093():
    """60-nt UMIs; round-1 threshold 0.93.

    Paper (vsearch iddef-2 identity = matching cols / alignment cols):
    - u vs u+2subs: gapless alignment, 58/60 = 0.9667 >= 0.93 -> joined.
    - u vs u+6subs: 54/60 = 0.90 < 0.93 -> split. (u+2subs vs u+6subs
      differ at up to 8 positions -> <= 52/60, also split, so transitive
      closure cannot bridge them either.)
    - exact duplicate joins trivially (vsearch dereplicates identical
      members into the centroid's cluster)."""
    u = _rand_seq(60)
    u_2subs = _sub(_sub(u, 10), 30)
    u_6subs = u
    for pos in (5, 15, 25, 35, 45, 55):
        u_6subs = _sub(u_6subs, pos)
    umis = [u, u_2subs, u_6subs, u]
    res = umi_cluster.cluster_umis(umis, identity_threshold=0.93)
    labels = res.labels
    assert labels[0] == labels[1] == labels[3]
    assert labels[2] != labels[0]
    assert res.num_clusters == 2


def test_vsearch_free_terminal_gaps_join_boundary_drift():
    """UMI-extraction boundary drift must not split a molecule.

    Paper: u (60 nt) vs u[2:] (58 nt) aligns with a 2-base terminal gap;
    vsearch scores end gaps free (--gapopen 0E) and iddef-2 identity
    excludes terminal gaps: 58 matching / 58 non-terminal cols = 1.0
    -> joined at any threshold. Our dovetail distance frees terminal
    gaps up to 8 nt (DIVERGENCES.md #2) -> identity 1.0 as well."""
    u = _rand_seq(60)
    res = umi_cluster.cluster_umis([u, u[2:]], identity_threshold=0.93)
    assert res.num_clusters == 1


def test_vsearch_round2_identity_threshold_097():
    """Round-2 consensus dedup at id 0.97 (vsearch_umi_cluster.py:71-97).

    Paper: 60-nt w vs 1 sub: 59/60 = 0.9833 >= 0.97 -> joined;
    w vs 2 subs: 58/60 = 0.9667 < 0.97 -> split (and 1-sub vs 2-subs
    differ at 3 positions -> 57/60 = 0.95 < 0.97, no transitive bridge)."""
    w = _rand_seq(60)
    w_1sub = _sub(w, 20)
    w_2subs = _sub(_sub(w, 40), 50)
    res = umi_cluster.cluster_umis([w, w_1sub, w_2subs], identity_threshold=0.97)
    assert res.labels[0] == res.labels[1]
    assert res.labels[2] != res.labels[0]
    assert res.num_clusters == 2


def test_vsearch_centroid_is_first_best_ranked_member():
    """cluster_fast processes length-desc then input order; the centroid
    of a cluster is its best-ranked member. With equal lengths, the first
    occurrence wins — for [u, u_2subs] the centroid must be index 0."""
    u = _rand_seq(60)
    res = umi_cluster.cluster_umis([u, _sub(_sub(u, 10), 30)],
                                   identity_threshold=0.93)
    assert res.num_clusters == 1
    assert res.centroid_of[res.labels[0]] == 0


# ---------------------------------------------------------------------------
# minimap2 blast-identity fixture (minimap2_align.py:13-18)


def test_blast_identity_matches_cigar_nm_arithmetic():
    """One sub + one deletion + one insertion in a 200-nt read.

    Paper (reference formula): alignment columns = M + I + D. The read
    aligns with 199 M columns (all ref positions except the deleted one),
    1 D column, 1 I column -> cols = 201. NM = 1 sub + 1 del + 1 ins = 3.
    matches = cols - NM = 198, blast_id = 198/201.

    The edits are well separated and flanked by exact matches, so under
    our scoring (match 2, mismatch -4, gap -4-2/base) the optimal local
    alignment is exactly the intended one: representing the sub as
    del+ins would cost 12 vs 4, merging gaps can't pay, and clipping
    matched ends only loses score."""
    ref = _rand_seq(200)
    read = _sub(ref, 50)                      # 1 substitution
    read = read[:100] + read[101:]            # delete ref position 100
    ins_base = "ACGT"[("ACGT".index(ref[150]) + 2) % 4]
    read = read[:150] + ins_base + read[150:]  # insert a non-matching base

    codes, lens = encode.encode_batch([read], pad_to=256)
    rcodes, rlens = encode.encode_batch([ref], pad_to=256)
    res = sw_align.align_banded(
        codes, lens, rcodes, rlens, np.zeros(1, np.int32), band_width=128
    )
    n_cols = int(res.n_cols[0])
    n_match = int(res.n_match[0])
    assert n_cols == 201
    assert n_match == 198
    # identical to the reference's (cols - NM) / cols with NM = 3
    assert abs(n_match / n_cols - (201 - 3) / 201) < 1e-12
    # full-span local alignment (nothing clipped)
    assert int(res.read_start[0]) == 0 and int(res.read_end[0]) == len(read)
    assert int(res.ref_start[0]) == 0 and int(res.ref_end[0]) == 200


# ---------------------------------------------------------------------------
# vsearch --fastq_filter fixture (preprocessing.py:104-159)


def test_ee_rate_formula_matches_reference_threshold():
    """Paper: EE rate = sum(10^(-Q/10)) / len.

    - 100 bases at Q10: sum = 100 * 0.1 = 10, rate 0.1  > 0.07 -> fail.
    - 100 bases at Q20: sum = 100 * 0.01 = 1, rate 0.01 <= 0.07 -> pass.
    - exact boundary: Q = -10*log10(0.07) ~ 11.549; integer Q12 gives
      rate 10^(-1.2) ~ 0.0631 <= 0.07 -> pass; Q11 gives 0.0794 -> fail."""
    quals = np.stack([
        np.full(100, 10.0, np.float32),
        np.full(100, 20.0, np.float32),
        np.full(100, 12.0, np.float32),
        np.full(100, 11.0, np.float32),
    ])
    lens = np.full(4, 100, np.int32)
    keep = np.asarray(ee_filter.ee_rate_mask(quals, lens, 0.07, 1))
    assert keep.tolist() == [False, True, True, False]
