"""Record-level validation + quarantine (io/validate.py) and the gzip
error-context satellite (io/fastx.py)."""

import gzip
import json

import numpy as np
import pytest

from ont_tcrconsensus_tpu.io import bucketing, fastx
from ont_tcrconsensus_tpu.io import validate as V


# --- tolerant parser unit behavior -----------------------------------------


def test_tolerant_parser_resyncs_and_keeps_clean_records(tmp_path):
    data = (b"@r1\nACGT\n+\nIIII\n"
            b"junk line that is not a record\n"
            b"@bad\nACG\n+\nIIII\n"          # len mismatch: 4-line quarantine
            b"@r2\nGG\n+\nII\n")
    p = tmp_path / "x.fastq"
    p.write_bytes(data)
    records, bads = V.parse_path_tolerant(p)
    assert [r.header for r in records] == [b"r1", b"r2"]
    assert [b.reason for b in bads] == [V.R_BAD_HEADER, V.R_LEN_MISMATCH]
    # offsets are absolute and raw bytes reconstruct the damage exactly
    assert data[bads[0].offset:].startswith(b"junk line")
    assert bads[1].raw == b"@bad\nACG\n+\nIIII\n"


def test_tolerant_parser_missing_plus_resync(tmp_path):
    # r1 truncated mid-record: its 'plus' slot holds r2's header, so the
    # parser must give r1 up WITHOUT eating r2
    p = tmp_path / "x.fastq"
    p.write_bytes(b"@r1\nACGT\n@r2\nGGCC\n+\nIIII\n")
    records, bads = V.parse_path_tolerant(p)
    assert [r.header for r in records] == [b"r2"]
    assert [b.reason for b in bads] == [V.R_MISSING_PLUS]


def test_tolerant_parser_truncated_final_record(tmp_path):
    p = tmp_path / "x.fastq"
    p.write_bytes(b"@r1\nACGT\n+\nIIII\n@r2\nACGT\n+")
    records, bads = V.parse_path_tolerant(p)
    assert [r.header for r in records] == [b"r1"]
    assert [b.reason for b in bads] == [V.R_TRUNCATED]
    assert bads[0].offset == len(b"@r1\nACGT\n+\nIIII\n")


def test_tolerant_parser_subphred_and_gzip_truncation(tmp_path):
    text = b"".join(b"@r%d\nACGTACGTAC\n+\nIIIIIIIIII\n" % i for i in range(50))
    full = gzip.compress(text)
    p = tmp_path / "x.fastq.gz"
    p.write_bytes(full[: len(full) // 2])
    records, bads = V.parse_path_tolerant(p)
    assert records, "decodable prefix lost"
    assert bads[-1].reason == V.R_GZIP
    # sub-Phred33 quarantines the record, clean neighbors survive
    p2 = tmp_path / "y.fastq"
    p2.write_bytes(b"@a\nAC\n+\n\x1f\x1f\n@b\nGG\n+\nII\n")
    records, bads = V.parse_path_tolerant(p2)
    assert [r.header for r in records] == [b"b"]
    assert [b.reason for b in bads] == [V.R_BAD_QUAL]


def test_code_lut_matches_ops_encode():
    """validate.CODE_LUT is a jax-free mirror of ops.encode._CODE_LUT; the
    two must never drift (the fuzzer encodes with the mirror)."""
    from ont_tcrconsensus_tpu.ops import encode

    np.testing.assert_array_equal(V.CODE_LUT, encode._CODE_LUT)


# --- IngestGuard ------------------------------------------------------------


def test_ingest_guard_quarantine_artifact_and_reset(tmp_path):
    qpath = str(tmp_path / "quarantine.fastq.gz")
    guard = V.IngestGuard("quarantine", source="lib.fastq", quarantine_path=qpath)
    guard.handle(V.BadRecord(0, V.R_LEN_MISMATCH, b"@bad\nACG\n+\nIIII\n", "lib.fastq"))
    guard.handle(V.BadRecord(40, V.R_BAD_HEADER, b"junk\n", "lib.fastq"))
    # retry semantics: reset truncates the artifact and zeroes counters
    guard.reset()
    assert guard.n_bad == 0
    guard.handle(V.BadRecord(0, V.R_LEN_MISMATCH, b"@bad\nACG\n+\nIIII\n", "lib.fastq"))

    class Rec:
        def __init__(self):
            self.events = []

        def record(self, site, **kw):
            self.events.append((site, kw))

    rec = Rec()
    summary = guard.finalize(rec)
    assert summary["n_bad"] == 1
    assert summary["by_reason"] == {V.R_LEN_MISMATCH: 1}
    assert gzip.open(qpath, "rb").read() == b"@bad\nACG\n+\nIIII\n"
    outcomes = [kw["outcome"] for _, kw in rec.events]
    assert outcomes == ["quarantined", "summary"]
    # finalize is idempotent: no duplicate report events
    guard.finalize(rec)
    assert len(rec.events) == 2


def test_ingest_guard_drop_policy_writes_no_artifact(tmp_path):
    guard = V.IngestGuard("drop", source="x",
                          quarantine_path=str(tmp_path / "q.gz"))
    assert guard.quarantine_path is None
    guard.handle(V.BadRecord(0, V.R_BAD_HEADER, b"junk\n", "x"))
    assert guard.finalize()["n_bad"] == 1
    assert not (tmp_path / "q.gz").exists()


# --- run_assign integration (guard + ingest contracts, engine-free) --------


def test_batches_from_source_quarantines_bad_records(tmp_path):
    """The ingest path (native chunked parser, or Python fallback) must
    yield only the clean records and route the damage to the guard."""
    from ont_tcrconsensus_tpu.pipeline.assign import _batches_from_source

    p = tmp_path / "lib.fastq"
    p.write_bytes(b"@r1\n" + b"A" * 100 + b"\n+\n" + b"I" * 100 + b"\n"
                  b"garbage here\n"
                  b"@r2\n" + b"C" * 100 + b"\n+\n" + b"I" * 99 + b"\n"
                  b"@r3\n" + b"G" * 100 + b"\n+\n" + b"I" * 100 + b"\n")
    guard = V.IngestGuard("quarantine", source=str(p),
                          quarantine_path=str(tmp_path / "q.gz"))
    counters = bucketing.IngestCounters()
    batches = list(_batches_from_source(
        str(p), batch_size=8, widths=(256,), subsample=None,
        counters=counters, guard=guard,
    ))
    ids = [i for b in batches for i, v in zip(b.ids, b.valid) if v]
    assert ids == ["r1", "r3"]
    assert counters.n_records == 2
    assert guard.n_bad == 2
    assert set(guard.by_reason) == {V.R_BAD_HEADER, V.R_LEN_MISMATCH}


def test_batches_from_source_fail_policy_still_raises(tmp_path):
    from ont_tcrconsensus_tpu.pipeline.assign import _batches_from_source

    p = tmp_path / "lib.fastq"
    p.write_bytes(b"@r1\nACGT\n+\nII\n")
    with pytest.raises(ValueError):
        list(_batches_from_source(str(p), batch_size=8, widths=(256,),
                                  subsample=None))


# --- gzip error-context satellite ------------------------------------------


def test_read_fastx_truncated_gzip_has_context(tmp_path):
    text = b"".join(b"@r%d\nACGTACGTAC\n+\nIIIIIIIIII\n" % i for i in range(200))
    full = gzip.compress(text)
    p = tmp_path / "trunc.fastq.gz"
    p.write_bytes(full[: len(full) // 2])
    with pytest.raises(ValueError) as ei:
        list(fastx.read_fastx(p))
    msg = str(ei.value)
    assert "trunc.fastq.gz" in msg
    assert "gzip" in msg and "offset" in msg


def test_read_fastx_empty_gzip_is_empty(tmp_path):
    # a ZERO-byte .gz reads as a valid empty archive (gzip module semantics,
    # matching the native parser's gzopen transparency): no records, no error
    p = tmp_path / "empty.fastq.gz"
    p.write_bytes(b"")
    assert list(fastx.read_fastx(p)) == []


def test_read_fastx_garbage_gzip_has_context(tmp_path):
    # a .gz whose member header is cut mid-way IS a decode error with context
    p = tmp_path / "garbage.fastq.gz"
    p.write_bytes(b"\x1f\x8b\x08")
    with pytest.raises(ValueError, match="gzip"):
        list(fastx.read_fastx(p))


# --- --validate dry-run -----------------------------------------------------


def _write_config(tmp_path, **overrides):
    ref = tmp_path / "reference.fa"
    fastx.write_fasta(ref, [("regionA", "ACGT" * 200), ("regionB", "GGCC" * 200)])
    fq_dir = tmp_path / "fastq_pass" / "barcode01"
    fq_dir.mkdir(parents=True, exist_ok=True)
    fastx.write_fastq(fq_dir / "barcode01.fastq.gz",
                      [("r1", "ACGT" * 100, "I" * 400)])
    cfg = {
        "reference_file": str(ref),
        "fastq_pass_dir": str(tmp_path / "fastq_pass"),
    }
    cfg.update(overrides)
    cfg_path = tmp_path / "config.json"
    cfg_path.write_text(json.dumps(cfg))
    return cfg_path, fq_dir


def test_validate_cli_ok(tmp_path, capsys):
    from ont_tcrconsensus_tpu.pipeline import cli

    cfg_path, _ = _write_config(tmp_path)
    assert cli.main([str(cfg_path), "--validate"]) == 0
    out = capsys.readouterr().out
    assert "validate: OK" in out
    assert "1 records" in out


def test_validate_cli_flags_bad_records(tmp_path, capsys):
    from ont_tcrconsensus_tpu.pipeline import cli

    cfg_path, fq_dir = _write_config(tmp_path)
    (fq_dir / "bad.fastq").write_bytes(b"@r1\nACGT\n+\nII\n")
    assert cli.main([str(cfg_path), "--validate"]) == 1
    out = capsys.readouterr().out
    assert "PROBLEM" in out and V.R_LEN_MISMATCH in out
    assert "validate: FAIL" in out


def test_validate_cli_flags_config_and_missing_inputs(tmp_path, capsys):
    from ont_tcrconsensus_tpu.pipeline import cli

    bad_cfg = tmp_path / "bad.json"
    bad_cfg.write_text(json.dumps({"reference_file": "r.fa"}))  # missing key
    assert cli.main([str(bad_cfg), "--validate"]) == 1
    assert "config failed" in capsys.readouterr().out

    cfg_path, _ = _write_config(tmp_path, reference_file=str(tmp_path / "nope.fa"))
    assert cli.main([str(cfg_path), "--validate"]) == 1
    assert "unreadable" in capsys.readouterr().out


def _mark_counts_done(tmp_path, content=b"TCR,Count\nregionA,3\n"):
    """A fake completed library under <fastq_pass>/nano_tcr/barcode01."""
    from ont_tcrconsensus_tpu.io import layout

    nano = tmp_path / "fastq_pass" / "nano_tcr"
    nano.mkdir(parents=True, exist_ok=True)
    lay = layout.init_library_dir("/x/barcode01.fastq.gz", nano, resume=True)
    art = nano / "barcode01" / "counts" / "umi_consensus_counts.csv"
    art.write_bytes(content)
    lay.mark_stage_done("counts", artifacts=[art])
    return lay, art


def test_validate_cli_audits_clean_v2_manifest(tmp_path, capsys):
    from ont_tcrconsensus_tpu.pipeline import cli

    cfg_path, _ = _write_config(tmp_path)
    _mark_counts_done(tmp_path)
    assert cli.main([str(cfg_path), "--validate"]) == 0
    out = capsys.readouterr().out
    assert "(v2): 1 stage(s), 1 verified" in out
    assert "validate: OK" in out


def test_validate_cli_flags_checksum_mismatch(tmp_path, capsys):
    """The dry-run twin of verify_resume=full: a size-preserving byte flip
    on a completed artifact is a PROBLEM, reported without starting a
    run."""
    from ont_tcrconsensus_tpu.pipeline import cli

    cfg_path, _ = _write_config(tmp_path)
    _, art = _mark_counts_done(tmp_path)
    data = bytearray(art.read_bytes())
    data[len(data) // 2] ^= 0x01
    art.write_bytes(bytes(data))  # same size: only sha256 can see this
    assert cli.main([str(cfg_path), "--validate"]) == 1
    out = capsys.readouterr().out
    assert "PROBLEM" in out and "sha256" in out
    assert "failed artifact verification" in out


def test_validate_cli_reports_torn_and_v1_manifests(tmp_path, capsys):
    from ont_tcrconsensus_tpu.io import validate as vmod
    from ont_tcrconsensus_tpu.pipeline import cli

    cfg_path, _ = _write_config(tmp_path)
    lay, _ = _mark_counts_done(tmp_path)

    # v1 (flat) manifest: informational, NOT an error — resume under
    # fast/full warns and re-runs; the operator just learns it's legacy
    lay_path = tmp_path / "fastq_pass" / "nano_tcr" / "barcode01" / \
        "stage_manifest.json"
    lay_path.write_text(json.dumps({"counts": 1700000000.0}))
    assert cli.main([str(cfg_path), "--validate"]) == 0
    assert "v1 (no checksums" in capsys.readouterr().out

    # torn manifest: a real problem (crash mid-write / disk fault)
    lay_path.write_text('{"version": 2, "stages": {"coun')
    assert cli.main([str(cfg_path), "--validate"]) == 1
    out = capsys.readouterr().out
    assert "TORN" in out and "PROBLEM" in out

    # a v2 header over a broken body is TORN, not "v2 with 0 clean stages"
    lay_path.write_text(json.dumps({"version": 2, "stages": [1, 2]}))
    assert cli.main([str(cfg_path), "--validate"]) == 1
    assert "TORN" in capsys.readouterr().out

    # a malformed individual v2 entry is reported, not silently undercounted
    lay_path.write_text(json.dumps({"version": 2, "stages": {
        "counts": {"t": None, "artifacts": None},
    }}))
    assert cli.main([str(cfg_path), "--validate"]) == 1
    assert "malformed manifest entry" in capsys.readouterr().out

    # ... and the identical damage inside a v1 manifest is flagged the same
    # way, not laundered into "v1, 0 stages, looks clean"
    lay_path.write_text(json.dumps({"counts": "not-a-time"}))
    assert cli.main([str(cfg_path), "--validate"]) == 1
    assert "malformed manifest entry" in capsys.readouterr().out

    # the scan API classifies all three shapes directly
    lay_path.write_text(json.dumps({"counts": 1700000000.0}))
    (report,) = vmod.scan_manifests(str(tmp_path / "fastq_pass"))
    assert report["status"] == "v1"
    assert report["stages"] == {"counts": "v1 entry — no checksums recorded"}


def test_validate_cli_mixed_version_manifest_is_not_an_error(tmp_path, capsys):
    """A v1 workdir resumed once holds a MIGRATED v2 manifest whose v1-era
    entries carry artifacts: null — legacy, not damage: --validate must
    stay exit 0 (same verdict as a pure-v1 manifest), not report 'failed
    artifact verification' on an uncorrupted workdir."""
    from ont_tcrconsensus_tpu.pipeline import cli

    cfg_path, _ = _write_config(tmp_path)
    lay, art = _mark_counts_done(tmp_path)
    mpath = tmp_path / "fastq_pass" / "nano_tcr" / "barcode01" / \
        "stage_manifest.json"
    # rebuild the exact migration state: a v1 file with a legacy stage,
    # re-marked on top (mark_stage_done migrates to v2, artifacts: null
    # for the old entry)
    mpath.write_text(json.dumps({"align": 1700000000.0}))
    lay.mark_stage_done("counts", artifacts=[art])
    assert json.loads(mpath.read_text())["version"] == 2
    assert cli.main([str(cfg_path), "--validate"]) == 0
    out = capsys.readouterr().out
    assert "v1-era entry" in out and "validate: OK" in out
