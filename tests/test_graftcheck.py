"""graftcheck (graph/check.py + tools/graftcheck): the semantic analyzer
proves liveness/donation/placement/sharding properties of built graphs.

Fixture graphs exercise each analysis against hand-computed expectations
(a diamond with an explicit byte model pins the exact live set and
high-water per step); the acceptance tests run the REAL production graph
and compare against the committed expected-findings list — the same
comparison tier-1 stage 0 makes — and prove the whole analysis imports
nothing from jax (a poisoned-import subprocess).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from ont_tcrconsensus_tpu.graph import check  # noqa: E402
from ont_tcrconsensus_tpu.graph import pipeline as graph_pipeline  # noqa: E402
from ont_tcrconsensus_tpu.graph.ir import GraphBuilder  # noqa: E402
from ont_tcrconsensus_tpu.pipeline.config import RunConfig  # noqa: E402
from tools.graftcheck.cli import DEFAULT_EXPECT  # noqa: E402
from tools.graftcheck.cli import main as graftcheck_main  # noqa: E402


# Fixture node names pass through variables, never literals: the
# graftlint graph/obs rules police name literals against the production
# registries, and kind comparisons use these constants for the same
# reason (the chaos-kind rule polices `x.kind == <literal>` shapes).
N_LOAD, N_LEFT, N_RIGHT, N_JOIN = "load", "left", "right", "join"
N_UP, N_DOWN, N_HOSTWORK, N_REUP, N_SINK = (
    "up", "down", "host_work", "re_up", "sink")
N_ONE, N_TWO, N_XFORM, N_USE, N_WORK = "one", "two", "xform", "use", "work"
K_DONATION = "donation-hazard"
K_TRIP = "placement-round-trip"
K_RESHARD = "reshard-site"


def _cfg(**kw) -> RunConfig:
    # placeholder paths: nothing in graph construction stats the filesystem
    return RunConfig(reference_file="reference.fasta",
                     fastq_pass_dir="fastq_pass", **kw)


def kinds_of(report) -> set[str]:
    return {f.kind for f in report.findings}


# ---------------------------------------------------------------------------
# liveness: diamond fixture with an explicit byte model


def diamond_spec():
    """load -> (left, right) -> join, all on hbm; `mid_l`/`mid_r` are the
    diamond arms, `out` the joined result (host so it may be a result)."""
    b = GraphBuilder("diamond")
    b.input("src", "disk")
    b.edge("base", "hbm")
    b.edge("mid_l", "hbm")
    b.edge("mid_r", "hbm")
    b.edge("out", "host")
    b.add_node(N_LOAD, inputs=("src",), outputs=("base",))
    b.add_node(N_LEFT, inputs=("base",), outputs=("mid_l",))
    b.add_node(N_RIGHT, inputs=("base",), outputs=("mid_r",))
    b.add_node(N_JOIN, inputs=("mid_l", "mid_r"), outputs=("out",))
    b.result("out")
    return b.build()


def test_diamond_liveness_and_high_water():
    model = {"base": 100, "mid_l": 30, "mid_r": 5}
    report = check.analyze(diamond_spec(), model)
    by_node = {row["node"]: row for row in report.liveness}
    # base lives until BOTH arms consumed it; the executor drops it at its
    # last consumer ('right', declaration order == schedule order)
    assert by_node[N_LOAD]["live_hbm"] == ["base"]
    assert by_node[N_LOAD]["hbm_bytes_est"] == 100
    assert by_node[N_LEFT]["live_hbm"] == ["base", "mid_l"]
    assert by_node[N_LEFT]["hbm_bytes_est"] == 130
    assert by_node[N_RIGHT]["live_hbm"] == ["base", "mid_l", "mid_r"]
    assert by_node[N_RIGHT]["hbm_bytes_est"] == 135
    assert by_node[N_JOIN]["live_hbm"] == ["mid_l", "mid_r"]
    assert report.hbm_high_water_bytes == 135
    assert report.hbm_high_water_node == N_RIGHT
    # donation: base's buffer may be donated into 'right' (its last
    # consumer), both arms into 'join'
    assert report.donation_eligible == {
        N_RIGHT: ["base"], N_JOIN: ["mid_l", "mid_r"],
    }
    # the diamond is donation-safe and device-resident end to end
    assert report.verdict == "clean"
    assert report.summary()["donation_safe"] is True


def test_liveness_zero_byte_model_still_tracks_sets():
    report = check.analyze(diamond_spec())
    assert [row["hbm_bytes_est"] for row in report.liveness] == [0, 0, 0, 0]
    assert {tuple(row["live_hbm"]) for row in report.liveness} == {
        ("base",), ("base", "mid_l"), ("base", "mid_l", "mid_r"),
        ("mid_l", "mid_r"),
    }


# ---------------------------------------------------------------------------
# donation hazards


def test_hbm_result_edge_is_donation_hazard():
    b = GraphBuilder("bad-result")
    b.input("src", "disk")
    b.edge("dev", "hbm")
    b.add_node(N_LOAD, inputs=("src",), outputs=("dev",))
    b.result("dev")
    report = check.analyze(b.build())
    assert report.verdict == "violations"
    (f,) = report.violations
    assert f.kind == K_DONATION and f.subject == "dev"
    assert "graph result" in f.message
    assert report.summary()["donation_safe"] is False


def test_unconsumed_hbm_edge_is_donation_hazard():
    b = GraphBuilder("bad-leak")
    b.input("src", "disk")
    b.edge("dev", "hbm")
    b.edge("leak", "hbm")
    b.edge("out", "host")
    b.add_node(N_LOAD, inputs=("src",), outputs=("dev", "leak"))
    b.add_node(N_USE, inputs=("dev",), outputs=("out",))
    b.result("out")
    report = check.analyze(b.build())
    hazards = [f for f in report.violations if f.kind == K_DONATION]
    assert [f.subject for f in hazards] == ["leak"]
    assert "no consumer" in hazards[0].message


# ---------------------------------------------------------------------------
# placement flow: hbm -> host -> hbm round-trips


def test_host_round_trip_named_with_full_path():
    b = GraphBuilder("trip")
    b.input("src", "disk")
    b.edge("dev_a", "hbm")
    b.edge("staged", "host")
    b.edge("massaged", "host")
    b.edge("dev_b", "hbm")
    b.edge("out", "host")
    b.add_node(N_UP, inputs=("src",), outputs=("dev_a",))
    b.add_node(N_DOWN, inputs=("dev_a",), outputs=("staged",))
    b.add_node(N_HOSTWORK, inputs=("staged",), outputs=("massaged",))
    b.add_node(N_REUP, inputs=("massaged",), outputs=("dev_b",))
    b.add_node(N_SINK, inputs=("dev_b",), outputs=("out",))
    b.result("out")
    report = check.analyze(b.build())
    trips = [f for f in report.advisories
             if f.kind == K_TRIP]
    # 'down' is a device node (touches dev_a); its host output flows
    # through the host-only 'host_work' into device node 're_up'
    assert [f.path for f in trips] == [
        (N_DOWN, "staged", N_HOSTWORK, "massaged", N_REUP),
    ]
    assert trips[0].severity == "advisory"
    assert N_REUP in trips[0].message
    # advisories alone never fail: verdict is non-clean but not violating
    assert report.verdict == "advisories"
    assert report.violations == []


def test_pure_host_flow_is_not_a_round_trip():
    b = GraphBuilder("hostonly")
    b.input("src", "disk")
    b.edge("a", "host")
    b.edge("b", "host")
    b.add_node(N_ONE, inputs=("src",), outputs=("a",))
    b.add_node(N_TWO, inputs=("a",), outputs=("b",))
    b.result("b")
    report = check.analyze(b.build())
    assert report.findings == []
    assert report.verdict == "clean"


# ---------------------------------------------------------------------------
# sharding pairing (ROADMAP-2 groundwork)


def test_sharding_mismatch_is_reshard_site():
    b = GraphBuilder("reshard")
    b.input("src", "disk")
    b.edge("ina", "hbm", sharding="data")
    b.edge("outa", "hbm", sharding="model")
    b.edge("res", "host")
    b.add_node(N_UP, inputs=("src",), outputs=("ina",))
    b.add_node(N_XFORM, inputs=("ina",), outputs=("outa",))
    b.add_node(N_DOWN, inputs=("outa",), outputs=("res",))
    b.result("res")
    report = check.analyze(b.build())
    sites = [f for f in report.violations if f.kind == K_RESHARD]
    assert [f.subject for f in sites] == [N_XFORM]
    assert "['data']" in sites[0].message and "['model']" in sites[0].message


def test_matching_or_undeclared_sharding_is_clean():
    b = GraphBuilder("sharded-ok")
    b.input("src", "disk")
    b.edge("ina", "hbm", sharding="data")
    b.edge("outa", "hbm", sharding="data")
    b.edge("bare", "hbm")  # undeclared sharding never pairs
    b.edge("res", "host")
    b.add_node(N_UP, inputs=("src",), outputs=("ina",))
    b.add_node(N_XFORM, inputs=("ina",), outputs=("outa", "bare"))
    b.add_node(N_DOWN, inputs=("outa", "bare"), outputs=("res",))
    b.result("res")
    report = check.analyze(b.build())
    assert [f for f in report.findings if f.kind == K_RESHARD] == []


# ---------------------------------------------------------------------------
# acceptance: the production graph


def test_production_graph_matches_committed_expected_list():
    cfg = _cfg()
    spec = graph_pipeline.build_library_graph(cfg)
    report = check.analyze(spec, check.production_byte_model(cfg))
    with open(DEFAULT_EXPECT, encoding="utf-8") as fh:
        expected = json.load(fh)
    want = {(d["kind"], d["subject"], tuple(d["path"]))
            for d in expected["findings"]}
    got = {f.key() for f in report.findings}
    assert got == want, (
        "production findings drifted from tools/graftcheck/"
        "expected_production.json — rerun `python -m tools.graftcheck "
        "--write-expect tools/graftcheck/expected_production.json` and "
        "review the diff"
    )
    # the ROADMAP-1 worklist is CLOSED: the data plane is device-resident
    # (meta-declared orchestration edges + the encoded round1->round2
    # hand-off), the committed expected list is empty, and ANY
    # reintroduced host round-trip is a new finding that fails --expect
    assert want == set() and report.findings == []
    assert report.verdict == "clean"
    assert not any("round2_fused_assign" in f.path for f in report.advisories)


def test_production_liveness_reports_high_water():
    cfg = _cfg()
    spec = graph_pipeline.build_library_graph(cfg)
    report = check.analyze(spec, check.production_byte_model(cfg, n_reads=8))
    assert len(report.liveness) == len(spec.schedule)
    # read_store (8 reads * 2 planes * max_read_length) dominates
    row = 2 * cfg.max_read_length
    assert report.hbm_high_water_bytes >= 8 * row
    assert report.hbm_high_water_node is not None
    # every step reports a sorted live set
    for step in report.liveness:
        assert step["live_hbm"] == sorted(step["live_hbm"])


def test_analysis_is_jax_free_under_poisoned_import():
    """The whole CLI path must run with jax IMPOSSIBLE to import."""
    code = (
        "import sys\n"
        "class _Poison:\n"
        "    def find_spec(self, name, *a, **k):\n"
        "        if name == 'jax' or name.startswith('jax.'):\n"
        "            raise ImportError('jax import poisoned by test')\n"
        "sys.meta_path.insert(0, _Poison())\n"
        "from tools.graftcheck.cli import main\n"
        "sys.exit(main(['--expect']))\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code], cwd=REPO,
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "internal error" not in proc.stderr


# ---------------------------------------------------------------------------
# CLI contract


def test_cli_human_and_json_agree(capsys):
    assert graftcheck_main([]) == 0
    human = capsys.readouterr().out
    assert "hbm high-water" in human
    assert "graftcheck:" in human
    assert graftcheck_main(["--json"]) == 0
    body = json.loads(capsys.readouterr().out)
    assert body["exit_code"] == 0
    assert body["summary"]["verdict"] == "clean"
    assert body["summary"]["violations"] == 0
    assert len(body["findings"]) == body["summary"]["advisories"]
    assert body["liveness"]


def _regressed_library_graph(cfg):
    """A stand-in production graph with one host materialization between
    device nodes — the exact regression the empty expected list exists
    to catch (the CLI re-imports the builder per call, so a monkeypatch
    on the pipeline module reaches it)."""
    b = GraphBuilder("library")
    b.input("src", "disk")
    b.edge("dev_a", "hbm")
    b.edge("host_mat", "host")
    b.edge("dev_b", "hbm")
    b.edge("res", "host")
    b.add_node(N_UP, inputs=("src",), outputs=("dev_a",))
    b.add_node(N_HOSTWORK, inputs=("dev_a",), outputs=("host_mat",))
    b.add_node(N_REUP, inputs=("host_mat",), outputs=("dev_b",))
    b.add_node(N_SINK, inputs=("dev_b",), outputs=("res",))
    b.result("res")
    return b.build()


def test_cli_expect_drift_fails(tmp_path, capsys, monkeypatch):
    # the committed list is empty (device-resident data plane); a stale
    # entry — e.g. a fixed round-trip someone left listed — must fail
    with open(DEFAULT_EXPECT, encoding="utf-8") as fh:
        expected = json.load(fh)
    assert expected["findings"] == [], "committed list expected clean"
    bogus = dict(expected)
    bogus["findings"] = [
        {"kind": K_TRIP, "subject": "ghost", "path": ["ghost"]}
    ]
    p = tmp_path / "expect.json"
    p.write_text(json.dumps(bogus))
    assert graftcheck_main(["--expect", str(p)]) == 1
    assert "no longer reported" in capsys.readouterr().err
    # ...and the direction CI actually guards: a reintroduced host
    # round-trip is a NEW finding vs the empty committed list and fails
    # BY NAME
    monkeypatch.setattr(
        graph_pipeline, "build_library_graph", _regressed_library_graph)
    p.write_text(json.dumps(expected))
    assert graftcheck_main(["--expect", str(p)]) == 1
    err = capsys.readouterr().err
    assert "NEW finding not in the expected list" in err
    assert N_REUP in err


def _resharding_library_graph(cfg):
    """A stand-in production graph where a declared "data" edge's consumer
    re-emits under "model" — the exact boundary-reshard regression the
    executor's hard gate and the --expect baseline both exist to catch."""
    b = GraphBuilder("library")
    b.input("src", "disk")
    b.edge("ina", "hbm", sharding="data")
    b.edge("outa", "hbm", sharding="model")
    b.edge("res", "host")
    b.add_node(N_UP, inputs=("src",), outputs=("ina",))
    b.add_node(N_XFORM, inputs=("ina",), outputs=("outa",))
    b.add_node(N_DOWN, inputs=("outa",), outputs=("res",))
    b.result("res")
    return b.build()


def test_cli_expect_seeded_reshard_drift_fails(tmp_path, capsys, monkeypatch):
    """ISSUE-18 permanence: reshard findings are hard under --expect. A
    newly-resharding declared edge in the production graph is a NEW
    violation vs the committed (empty) list and fails CI BY NAME — and
    the same findings surface through the public reshard_sites() wrapper
    the executor's sharded-run gate calls."""
    bad = check.reshard_sites(_resharding_library_graph(_cfg()))
    assert [f.kind for f in bad] == [K_RESHARD]
    assert bad[0].subject == N_XFORM
    assert bad[0].severity == "violation"
    # the shipped production graph has ZERO reshard sites (the executor
    # would refuse to run it sharded otherwise)
    assert check.reshard_sites(graph_pipeline.build_library_graph(_cfg())) == []
    monkeypatch.setattr(
        graph_pipeline, "build_library_graph", _resharding_library_graph)
    assert graftcheck_main(["--expect", DEFAULT_EXPECT]) == 1
    err = capsys.readouterr().err
    assert "NEW finding not in the expected list" in err
    assert N_XFORM in err and K_RESHARD in err


def test_cli_never_crashes_on_bad_inputs(tmp_path, capsys):
    assert graftcheck_main(["--config", str(tmp_path / "nope.json")]) == 2
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    assert graftcheck_main(["--config", str(bad)]) == 2
    err = capsys.readouterr().err
    assert "Traceback" not in err


def test_cli_write_expect_round_trips(tmp_path, capsys):
    out = tmp_path / "expect.json"
    assert graftcheck_main(["--write-expect", str(out)]) == 0
    capsys.readouterr()
    assert graftcheck_main(["--expect", str(out)]) == 0


# ---------------------------------------------------------------------------
# telemetry plumbing: summary -> telemetry.json -> history ledger


def test_summary_lands_in_telemetry_and_history_entry():
    from ont_tcrconsensus_tpu.obs import history, metrics

    cfg = _cfg()
    spec = graph_pipeline.build_library_graph(cfg)
    report = check.analyze(spec, check.production_byte_model(cfg))
    reg = metrics.arm()
    try:
        metrics.analysis_set("graftcheck", report.summary())
        telemetry = reg.summary()
    finally:
        metrics.disarm()
    assert telemetry["analysis"]["graftcheck"]["verdict"] == "clean"
    entry = history.build_entry("test", telemetry)
    assert entry["graftcheck"]["verdict"] == "clean"
    assert entry["graftcheck"]["violations"] == 0
    assert entry["graftcheck"]["hbm_high_water_node"] is not None


def test_analysis_set_is_noop_when_disarmed():
    from ont_tcrconsensus_tpu.obs import metrics

    metrics.disarm()
    metrics.analysis_set("graftcheck", {"verdict": "clean"})  # must not raise
    assert metrics.registry() is None


# ---------------------------------------------------------------------------
# builder guards that feed graftcheck's graph-invalid path


def test_edge_node_name_collision_is_named_problem():
    from ont_tcrconsensus_tpu.graph.ir import GraphValidationError

    b = GraphBuilder("clash")
    b.input("src", "disk")
    b.edge(N_WORK, "host")
    b.add_node(N_WORK, inputs=("src",), outputs=())
    with pytest.raises(GraphValidationError) as exc:
        b.build()
    assert any("collides with a node" in p for p in exc.value.problems)
