"""Region self-homology clustering and greedy UMI clustering."""

import numpy as np

from ont_tcrconsensus_tpu.cluster import regions, umi
from ont_tcrconsensus_tpu.io import simulator


def test_greedy_clustering_replicates_reference_semantics():
    tuples = [
        ("a", "b", 0.99),
        ("c", "d", 0.985),
        ("b", "c", 0.97),   # joins first cluster containing a/b
        ("e", "f", 0.5),    # below threshold, both unseen: skipped
    ]
    out = regions.greedy_most_similar_clustering(tuples, 0.96)
    assert out == [{"a", "b", "c"}, {"c", "d"}]  # reference quirk: c in both


def test_self_homology_groups_near_duplicates():
    rng = np.random.default_rng(2)
    ref = simulator.make_reference(
        rng, num_regions=5, num_similar_pairs=2, similar_divergence=0.005,
        num_negative_controls=1, region_len=(700, 900),
    )
    res = regions.self_homology_map(ref, cluster_threshold=0.93)
    # each _sim region must share a cluster with its source
    for name in ref:
        if "_sim" in name:
            src = name.split("_sim")[0]
            assert res.region_cluster[name] == res.region_cluster[src], name
    # unrelated regions get distinct clusters
    base = [n for n in ref if "_sim" not in n]
    assert len({res.region_cluster[n] for n in base}) == len(base)
    # precision bar reflects the near-duplicate similarity
    assert res.max_blast_id is not None and res.max_blast_id > 0.98
    # every region present
    assert set(res.region_cluster) == set(ref)


def test_self_homology_no_similar_pairs():
    rng = np.random.default_rng(3)
    ref = simulator.make_reference(rng, num_regions=5, region_len=(700, 900))
    res = regions.self_homology_map(ref, cluster_threshold=0.93)
    assert res.max_blast_id is None
    assert len({res.region_cluster[n] for n in ref}) == len(ref)


def _mutate_umi(rng, u, n_edits):
    s = list(u)
    for _ in range(n_edits):
        op = rng.integers(3)
        p = int(rng.integers(len(s)))
        if op == 0:
            s[p] = "ACGT"[rng.integers(4)]
        elif op == 1:
            s.insert(p, "ACGT"[rng.integers(4)])
        elif len(s) > 1:
            del s[p]
    return "".join(s)


def test_umi_clustering_groups_molecules():
    rng = np.random.default_rng(4)
    true_umis = [
        simulator.instantiate_iupac(rng, "TTTVVTTVVVVTTVVVVTTVVVVTTVVVVTTT")
        + simulator.instantiate_iupac(rng, "AAABBBBAABBBBAABBBBAABBBBAABBAAA")
        for _ in range(20)
    ]
    observed, truth = [], []
    for mi, u in enumerate(true_umis):
        for _ in range(int(rng.integers(3, 9))):
            observed.append(_mutate_umi(rng, u, int(rng.integers(0, 3))))
            truth.append(mi)
    out = umi.cluster_umis(observed, identity_threshold=0.93)
    # clusters must match ground-truth molecule partition exactly:
    # same molecule -> same cluster, different molecule -> different cluster
    label_of_mol = {}
    for lab, mol in zip(out.labels, truth):
        label_of_mol.setdefault(mol, set()).add(int(lab))
    for mol, labs in label_of_mol.items():
        assert len(labs) == 1, f"molecule {mol} split into {labs}"
    all_labels = [next(iter(labs)) for labs in label_of_mol.values()]
    assert len(set(all_labels)) == len(true_umis), "distinct molecules merged"
    assert out.num_clusters == len(true_umis)


def test_umi_clustering_deterministic_and_centroids_valid():
    rng = np.random.default_rng(5)
    base = simulator.instantiate_iupac(rng, "TTTVVTTVVVVTTVVVVTTVVVVTTVVVVTTT")
    umis = [base, _mutate_umi(rng, base, 1), base, _mutate_umi(rng, base, 2)]
    a = umi.cluster_umis(umis, identity_threshold=0.9)
    b = umi.cluster_umis(list(umis), identity_threshold=0.9)
    np.testing.assert_array_equal(a.labels, b.labels)
    assert a.num_clusters == 1
    # centroid index points at a member of the cluster
    assert a.labels[a.centroid_of[0]] == 0


def test_umi_clustering_empty_and_single():
    out = umi.cluster_umis([], identity_threshold=0.9)
    assert out.num_clusters == 0
    out1 = umi.cluster_umis(["ACGTACGT"], identity_threshold=0.9)
    assert out1.num_clusters == 1 and list(out1.labels) == [0]


def test_shortlist_miss_is_repaired():
    """A tiny shortlist must not found spurious clusters: results with
    shortlist_k=2 match the full-shortlist clustering on the same input
    (the centroid merge pass repairs per-UMI shortlist misses)."""
    import numpy as np

    from ont_tcrconsensus_tpu.cluster.umi import cluster_umis
    from ont_tcrconsensus_tpu.io import simulator

    rng = np.random.default_rng(5)
    # two true molecules, many noisy observations each
    bases = [simulator._rand_seq(rng, 60) for _ in range(4)]
    umis = []
    for b in bases:
        for _ in range(12):
            noisy, _ = simulator.mutate(rng, b, 0.01, 0.003, 0.003)
            umis.append(noisy)
    order = rng.permutation(len(umis))
    umis = [umis[i] for i in order]

    full = cluster_umis(umis, 0.9, shortlist_k=len(umis))
    tiny = cluster_umis(umis, 0.9, shortlist_k=2)
    # the merge pass repairs spurious FOUNDING: no extra clusters appear
    # with the tiny shortlist (member-level assignment may differ at the
    # margin, which the reference's vsearch heuristics also allow)
    assert full.num_clusters == 4
    assert tiny.num_clusters == 4


def test_grouped_clustering_matches_per_group():
    """cluster_umis_grouped == per-group cluster_umis on labels/centroids,
    across the full-matrix and shortlist regimes and empty/single groups."""
    rng = np.random.default_rng(9)
    groups = []
    # group 0: classic small molecule set (full-matrix regime alone, but the
    # CONCATENATED unique count crosses into the shortlist regime)
    for n_mols, reps in ((8, 6), (40, 8), (1, 1)):
        base_umis = [
            simulator.instantiate_iupac(rng, "TTTVVTTVVVVTTVVVVTTVVVVTTVVVVTTT")
            + simulator.instantiate_iupac(rng, "AAABBBBAABBBBAABBBBAABBBBAABBAAA")
            for _ in range(n_mols)
        ]
        obs = []
        for u in base_umis:
            for _ in range(reps):
                obs.append(_mutate_umi(rng, u, int(rng.integers(0, 3))))
        groups.append(obs)
    groups.append([])  # empty group

    grouped = umi.cluster_umis_grouped(groups, identity_threshold=0.93)
    assert len(grouped) == len(groups)
    for g, obs in enumerate(groups):
        solo = umi.cluster_umis(obs, identity_threshold=0.93)
        np.testing.assert_array_equal(
            grouped[g].labels, solo.labels,
            err_msg=f"group {g} labels diverge from per-group clustering",
        )
        assert grouped[g].num_clusters == solo.num_clusters
        np.testing.assert_array_equal(grouped[g].centroid_of, solo.centroid_of)


def test_grouped_clustering_never_merges_across_groups():
    """The SAME UMI set in two groups must produce two independent
    clusterings (cross-group identities are masked)."""
    rng = np.random.default_rng(11)
    base = simulator._rand_seq(rng, 60)
    obs = [base] + [_mutate_umi(rng, base, 1) for _ in range(5)]
    out = umi.cluster_umis_grouped([obs, list(obs)], identity_threshold=0.9)
    for g in range(2):
        assert out[g].num_clusters == 1
        assert len(out[g].labels) == len(obs)


def test_merge_close_centroids_unit():
    """Directly verify the centroid-merge repair: a centroid founded within
    the threshold of an earlier one is folded into it."""
    import numpy as np

    from ont_tcrconsensus_tpu.cluster.umi import _merge_close_centroids
    from ont_tcrconsensus_tpu.ops import encode

    seq_a = "ACGT" * 15                       # 60 nt
    seq_b = seq_a[:-1] + "A"                  # 1 edit from A -> identity ~0.983
    seq_c = "TTGG" * 15                       # far from both
    codes, lens = encode.encode_batch([seq_a, seq_b, seq_c], pad_to=64)
    # pretend the greedy pass founded all three as centroids (shortlist miss)
    labels = np.array([0, 1, 2], np.int32)
    centroids = np.array([0, 1, 2], np.int32)
    new_labels, new_centroids = _merge_close_centroids(
        labels, centroids, codes, lens, threshold=0.93,
        shortlist_k=2, kmer_k=4, pair_batch=1024,
    )
    assert list(new_centroids) == [0, 2]
    assert list(new_labels) == [0, 0, 1]


def test_error_rich_longest_read_does_not_fragment_molecule():
    """Star-policy regression (bench-scale counts bug): when the longest
    read of a molecule carries several errors, every member pair still
    clears 0.93 pairwise but a centroid-star anchored on the longest read
    splits the molecule. Component clustering must keep it whole."""
    import numpy as np

    from ont_tcrconsensus_tpu.cluster.umi import cluster_umis

    rng = np.random.default_rng(3)
    bases = "ACGT"
    center = "".join(rng.choice(list(bases)) for _ in range(64))

    def mutate(s, n_sub):
        s = list(s)
        for p in rng.choice(len(s), size=n_sub, replace=False):
            s[p] = bases[(bases.index(s[p]) + 1) % 4]
        return "".join(s)

    # longest read: 3 errors + an extra base (so it anchors the length sort)
    umis = [mutate(center, 3) + "A"]
    umis += [mutate(center, int(rng.integers(0, 3))) for _ in range(5)]
    other = "".join(rng.choice(list(bases)) for _ in range(64))
    umis += [mutate(other, 1) for _ in range(3)]

    res = cluster_umis(umis, 0.93)
    assert res.num_clusters == 2
    labels = np.asarray(res.labels)
    assert len(set(labels[:6])) == 1, "molecule fragmented"
    assert len(set(labels[6:])) == 1
    assert labels[0] != labels[6]


def test_umi_split_rescue_heals_2_1_1_fragmentation():
    """The LANE_SCALE_R4 loss chain, reproduced and healed (VERDICT r4 #3):
    a molecule's 4 reads carry combined UMIs eroded at the boundaries so
    far (13-14 nt, beyond the clustering pass's 8 nt free-end budget) that
    they split 2+1+1 across clusters; every fragment falls below
    min_reads_per_cluster=4 and the molecule vanishes. The second-chance
    pass re-tests sub-threshold centroids with the relaxed 16 nt budget
    and must reassemble exactly one 4-member cluster — while leaving an
    unrelated molecule's cluster untouched."""
    from ont_tcrconsensus_tpu.pipeline import stages

    rng = np.random.default_rng(42)
    base = "".join("ACGT"[i] for i in rng.integers(0, 4, 64))
    other = "".join("ACGT"[i] for i in rng.integers(0, 4, 64))

    def rec(name, combined, strand="+"):
        return stages.UmiRecord(
            name=name, strand=strand, umi_fwd_dist=0, umi_rev_dist=0,
            umi_fwd_seq=combined[:32], umi_rev_seq=combined[32:],
            combined=combined, block=0, row=0,
        )

    records = [
        rec("a", base), rec("b", base, "-"),          # intact pair
        rec("c", base[13:]),                          # 13 nt 5' erosion
        rec("d", base[:-14], "-"),                    # 14 nt 3' erosion
        # unrelated molecule, 4 intact reads: must stay its own cluster
        rec("e", other), rec("f", other, "-"),
        rec("g", other), rec("h", other, "-"),
    ]
    kw = dict(
        identity=0.93, min_umi_length=40, max_umi_length=70,
        min_reads_per_cluster=4, max_reads_per_cluster=20,
        balance_strands=False,
    )
    selected, stat_rows = stages.cluster_and_select(records, **kw)
    names = sorted(
        tuple(sorted(m.name for m in s.members)) for s in selected
    )
    assert names == [("a", "b", "c", "d"), ("e", "f", "g", "h")], names

    # control: without the rescue the split molecule is lost entirely
    eligible = [
        r for r in records if 40 <= len(r.combined) <= 70
    ]
    from ont_tcrconsensus_tpu.cluster import umi as umi_mod

    clusters = umi_mod.cluster_umis([r.combined for r in eligible], 0.93)
    sel_off, _ = stages._select_from_clusters(
        eligible, clusters, min_reads_per_cluster=4,
        max_reads_per_cluster=20, balance_strands=False,
        identity=0.93, rescue=False,
    )
    assert sorted(
        tuple(sorted(m.name for m in s.members)) for s in sel_off
    ) == [("e", "f", "g", "h")]


def test_umi_split_rescue_grouped_matches_per_group():
    """The grouped driver batches the rescue's device half across groups
    (one dispatch set); results must equal the per-group path exactly —
    including the healed 2+1+1 group — and cross-group UMIs must never
    merge even when identical."""
    from ont_tcrconsensus_tpu.pipeline import stages

    rng = np.random.default_rng(43)
    base = "".join("ACGT"[i] for i in rng.integers(0, 4, 64))
    other = "".join("ACGT"[i] for i in rng.integers(0, 4, 64))

    def rec(name, combined, strand="+"):
        return stages.UmiRecord(
            name=name, strand=strand, umi_fwd_dist=0, umi_rev_dist=0,
            umi_fwd_seq=combined[:32], umi_rev_seq=combined[32:],
            combined=combined, block=0, row=0,
        )

    g1 = [
        rec("a", base), rec("b", base, "-"),
        rec("c", base[13:]), rec("d", base[:-14], "-"),
        rec("e", other), rec("f", other, "-"),
        rec("g", other), rec("h", other, "-"),
    ]
    # group 2 carries the SAME eroded base UMI as g1's fragments: its
    # singletons must rescue only within their own group (here: no
    # survivor or sibling fragment close enough -> stays lost)
    g2 = [
        rec("x", base[13:]),
        rec("p", other), rec("q", other, "-"),
        rec("r", other), rec("s", other, "-"),
    ]
    kw = dict(
        identity=0.93, min_umi_length=40, max_umi_length=70,
        min_reads_per_cluster=4, max_reads_per_cluster=20,
        balance_strands=False,
    )
    grouped = stages.cluster_and_select_grouped(
        [("g1", g1), ("g2", g2)], **kw
    )
    sel1, _ = stages.cluster_and_select(g1, **kw)
    sel2, _ = stages.cluster_and_select(g2, **kw)

    def names(selected):
        return sorted(tuple(sorted(m.name for m in s.members)) for s in selected)

    assert names(grouped["g1"][0]) == names(sel1) == [
        ("a", "b", "c", "d"), ("e", "f", "g", "h")
    ]
    assert names(grouped["g2"][0]) == names(sel2) == [("p", "q", "r", "s")]
