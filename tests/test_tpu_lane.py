"""Real-TPU test lane (``-m tpu``).

Everything else in the suite pins itself to the 8-device virtual CPU mesh
(conftest.py), which exercises semantics but not the compiled Mosaic path —
a Mosaic-only bug would otherwise surface first in bench.py (VERDICT r1
weak #4). These tests run the compiled Pallas kernel and one pipeline slice
on the real chip; they are skipped unless a TPU is actually present.

Run with: ``pytest -m tpu tests/test_tpu_lane.py`` (no JAX_PLATFORMS=cpu).
The conftest CPU pin is process-wide, so this file spawns a fresh
subprocess without the pin — the in-process jax is already locked to CPU
when the full suite runs.
"""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.tpu

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _device_env() -> dict:
    """Subprocess env that can see the real chip.

    The conftest pins the in-process jax to CPU via jax.config (os.environ
    still carries the launch platform, e.g. JAX_PLATFORMS=axon for the TPU
    tunnel). Experimental platforms are only enabled when explicitly
    requested, so the var must be KEPT for the subprocess — dropping it
    makes jax fall back to CPU and the lane self-skips with a live chip.
    Only an explicit CPU pin is stripped so discovery can run.
    """
    env = dict(os.environ)
    platforms = [
        tok.strip() for tok in env.get("JAX_PLATFORMS", "").split(",")
        if tok.strip() and tok.strip().lower() != "cpu"
    ]
    if platforms:
        # composite pin like "cpu,axon": drop only the cpu token so the
        # experimental plugin request survives into the child
        env["JAX_PLATFORMS"] = ",".join(platforms)
    elif "JAX_PLATFORMS" in env:
        del env["JAX_PLATFORMS"]
    # The conftest's virtual-CPU-mesh flag breaks the tunnel plugin's
    # backend registration in a child process; it is CPU-suite-only.
    flags = [
        tok for tok in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in tok
    ]
    if flags:
        env["XLA_FLAGS"] = " ".join(flags)
    else:
        env.pop("XLA_FLAGS", None)
    # PREPEND the repo: the launch environment delivers the TPU tunnel's
    # jax plugin via PYTHONPATH, so overwriting the var severs the child
    # from the chip entirely (the r4 lane skips were exactly this).
    inherited = env.get("PYTHONPATH", "")
    env["PYTHONPATH"] = (
        _REPO + os.pathsep + inherited if inherited else _REPO
    )
    return env


def _tpu_present() -> bool:
    probe = (
        "import jax, json; "
        "print(json.dumps([d.platform for d in jax.devices()]))"
    )
    env = _device_env()
    try:
        out = subprocess.run(
            [sys.executable, "-c", probe], capture_output=True, text=True,
            timeout=120, env=env,
        )
        if out.returncode != 0:
            return False
        platforms = json.loads(out.stdout.strip().splitlines()[-1])
        return any(p != "cpu" for p in platforms)
    except Exception:
        return False


def _run_on_tpu(code: str, timeout: int = 600) -> str:
    env = _device_env()
    res = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=timeout, env=env, cwd=_REPO,
    )
    assert res.returncode == 0, f"stdout:\n{res.stdout}\nstderr:\n{res.stderr}"
    return res.stdout


needs_tpu = pytest.mark.skipif(not _tpu_present(), reason="no TPU attached")


@needs_tpu
def test_pallas_sw_matches_scan_kernel_on_tpu():
    """The compiled Mosaic SW kernel must agree cell-exactly with the XLA
    scan kernel on the same pairs — on the real chip, not interpret mode."""
    out = _run_on_tpu(r"""
import numpy as np, jax
from ont_tcrconsensus_tpu.ops import sw_align, sw_pallas
rng = np.random.default_rng(0)
B, L = 32, 512
reads = rng.integers(0, 4, size=(B, L)).astype(np.uint8)
refs = reads.copy()
# mutate refs lightly so alignments are nontrivial
mut = rng.random(refs.shape) < 0.05
refs = np.where(mut, (refs + 1) % 4, refs).astype(np.uint8)
lens = rng.integers(L // 2, L + 1, size=B).astype(np.int32)
offs = np.zeros(B, np.int32)
for W in (128, 256):  # 128 = production default (config.sw_band_width)
    res_p = sw_pallas.align_banded_pallas(reads, lens, refs, lens, offs, band_width=W)
    res_s = sw_align.align_banded(reads, lens, refs, lens, offs, band_width=W)
    for f in ("score", "read_start", "read_end", "ref_start", "ref_end", "n_match", "n_cols"):
        a, b = np.asarray(getattr(res_p, f)), np.asarray(getattr(res_s, f))
        assert (a == b).all(), (W, f, a[:5], b[:5])
print("PALLAS_OK")
""")
    assert "PALLAS_OK" in out


@needs_tpu
def test_fused_assign_slice_on_tpu():
    """One fused-pass slice (trim+EE+align+UMI) on the real chip yields the
    same survivors as the virtual-CPU path used by the rest of the suite."""
    out = _run_on_tpu(r"""
import numpy as np, os, json
from ont_tcrconsensus_tpu.io import fastx, simulator
from ont_tcrconsensus_tpu.cluster import regions as regions_mod
from ont_tcrconsensus_tpu.pipeline import stages
lib = simulator.simulate_library(seed=5, num_regions=2, molecules_per_region=(2, 2),
                                 reads_per_molecule=(4, 6), sub_rate=0.01,
                                 ins_rate=0.004, del_rate=0.004,
                                 region_len=(1500, 1700), with_adapters=True)
homology = regions_mod.self_homology_map(lib.reference, 0.93)
panel = stages.ReferencePanel.build(lib.reference, homology.region_cluster)
from ont_tcrconsensus_tpu.pipeline.config import RunConfig
cfg = RunConfig.from_dict({"reference_file": "x", "fastq_pass_dir": "y"})
engine = stages.AssignEngine(panel, cfg.umi_fwd, cfg.umi_rev,
                             primers=cfg.primer_sequences())
records = [fastx.FastxRecord(h.split()[0], "", s, q) for h, s, q in lib.reads]
store, stats = stages.run_assign(
    records, engine, max_ee_rate=0.07, min_len=1000,
    minimal_region_overlap=0.95, max_softclip_5_end=81, max_softclip_3_end=76,
    batch_size=64, max_read_length=4096)
assert stats.n_pass == len(records), (stats,)
assert stats.n_trimmed == len(records)
print("FUSED_OK", store.num_reads)
""")
    assert "FUSED_OK" in out


@needs_tpu
def test_pileup_paths_agree_on_tpu():
    """The production pileup (XLA forward + scan-log traceback) and the
    Pallas forward must both match the fused while_loop reference on the
    real chip."""
    out = _run_on_tpu(r"""
import numpy as np
from ont_tcrconsensus_tpu.io import simulator
from ont_tcrconsensus_tpu.ops import encode, pileup
rng = np.random.default_rng(11)
C, S, W = 4, 6, 512
sub = np.full((C, S, W), encode.PAD_CODE, np.uint8)
lens = np.zeros((C, S), np.int32)
drafts = np.full((C, W), encode.PAD_CODE, np.uint8)
dlens = np.zeros((C,), np.int32)
for c in range(C):
    template = simulator._rand_seq(rng, 430)
    for i in range(S):
        s, _ = simulator.mutate(rng, template, 0.02, 0.008, 0.008)
        e = encode.encode_seq(s)
        sub[c, i, :len(e)] = e
        lens[c, i] = len(e)
    t = encode.encode_seq(template)
    drafts[c, :len(t)] = t
    dlens[c] = len(t)
ref = pileup.pileup_columns_batch(sub, lens, drafts, dlens, band_width=64, out_len=W)
for force_pallas in (False, True):
    got = pileup.pileup_columns_batch_auto(
        sub, lens, drafts, dlens, band_width=64, out_len=W,
        force_pallas=force_pallas)
    for a, b, n in zip(ref, got, ("base_at", "ins_cnt", "ins_base", "pos_at", "spans")):
        assert (np.asarray(a) == np.asarray(b)).all(), (force_pallas, n)
print("PILEUP_OK")
""")
    assert "PILEUP_OK" in out


@needs_tpu
def test_targeted_round2_pass_on_tpu():
    """The round-2 targeted pass (Pallas SW against per-read candidate
    refs) must agree with the full fused pass's assignment on the real
    chip — same survivors, same regions, same blast-ids.

    The targeted pass's input contract is molecule-(+)-oriented sequence
    (the polish path orients subreads before the vote; assign.py
    _targeted_pass docstring), so minus-strand reads are oriented with
    the fused pass's strand call first — feeding raw reads puts the true
    diagonal outside the band and the pass rightly scores ~0 (this test's
    first on-chip run caught exactly that misuse)."""
    out = _run_on_tpu(r"""
import numpy as np
from ont_tcrconsensus_tpu.io import bucketing, fastx, simulator
from ont_tcrconsensus_tpu.cluster import regions as regions_mod
from ont_tcrconsensus_tpu.pipeline import assign
from ont_tcrconsensus_tpu.pipeline.config import RunConfig
lib = simulator.simulate_library(seed=7, num_regions=4, molecules_per_region=(1, 1),
                                 reads_per_molecule=(1, 1), sub_rate=0.0,
                                 ins_rate=0.0, del_rate=0.0,
                                 region_len=(1200, 1400))
homology = regions_mod.self_homology_map(lib.reference, 0.93)
panel = assign.ReferencePanel.build(lib.reference, homology.region_cluster)
cfg = RunConfig.from_dict({"reference_file": "x", "fastq_pass_dir": "y"})
eng = assign.AssignEngine(panel, cfg.umi_fwd, cfg.umi_rev, primers=[])
recs = [fastx.FastxRecord(h.split()[0], "", s, None) for h, s, _ in lib.reads]
batch = next(bucketing.batch_reads(recs, batch_size=64, with_quals=False))
full = eng.run_batch(batch, max_ee_rate=1.0, min_len=1)
# every row must be valid before compressing is_rev with the mask: a
# filtered read would silently zip-truncate and misalign the flags
assert batch.valid.all(), batch.valid
comp = str.maketrans("ACGT", "TGCA")
oriented = [
    fastx.FastxRecord(
        r.name, "",
        r.sequence.translate(comp)[::-1] if rev else r.sequence, None)
    for r, rev in zip(recs, full["is_rev"][batch.valid])
]
obatch = next(bucketing.batch_reads(oriented, batch_size=64, with_quals=False))
cand = np.full((len(obatch.ids), 1), -1, np.int32)
cand[obatch.valid, 0] = full["ridx"][batch.valid]
tgt = eng.run_batch_targeted_async(obatch, cand, min_len=1)
import jax
tgt = jax.device_get(tgt)
v = obatch.valid
assert (tgt["ridx"][v] == full["ridx"][batch.valid]).all()
assert (np.abs(tgt["blast_id"][v] - full["blast_id"][batch.valid]) < 1e-6).all()
assert (tgt["score"][v] == full["score"][batch.valid]).all()
print("TARGETED_OK")
""")
    assert "TARGETED_OK" in out
