"""Stage-boundary conservation contracts (robustness/contracts.py)."""

import pytest

from ont_tcrconsensus_tpu.robustness import contracts, retry


@pytest.fixture(autouse=True)
def _restore_mode():
    prev = contracts.mode()
    yield
    contracts.set_mode(prev)
    contracts.reset()


def test_warn_mode_records_violation_without_raising(capsys):
    contracts.set_mode("warn")
    contracts.reset()
    rec = retry.recorder()
    rec.reset()
    assert contracts.check_equal("ingest", "parsed", 10, "batched", 10)
    assert not contracts.check_equal("ingest", "parsed", 10, "batched", 9,
                                     detail={"source": "x.fastq"})
    assert "conservation contract 'ingest' violated" in capsys.readouterr().err
    s = contracts.summary()
    assert s["checked"]["ingest"] == 2
    assert s["violated"]["ingest"] == 1
    ev = [e for e in rec.events if e["site"] == "contracts.ingest"]
    assert len(ev) == 1 and ev[0]["outcome"] == "violation"
    assert ev[0]["detail"] == {"source": "x.fastq"}


def test_strict_mode_raises():
    contracts.set_mode("strict")
    contracts.reset()
    with pytest.raises(contracts.ContractViolation, match="counts"):
        contracts.check_equal("counts", "csv", {"a": 1}, "memory", {"a": 2})


def test_off_mode_skips_entirely():
    contracts.set_mode("off")
    contracts.reset()
    assert contracts.check_equal("umi", "lhs", 1, "rhs", 2)  # not even counted
    assert contracts.summary()["checked"] == {}


def test_unknown_mode_rejected():
    with pytest.raises(ValueError, match="contracts mode"):
        contracts.set_mode("loose")


def test_config_wires_policy_and_contract_keys():
    from ont_tcrconsensus_tpu.pipeline.config import RunConfig

    cfg = RunConfig.from_dict({
        "reference_file": "r", "fastq_pass_dir": "f",
        "on_bad_record": "quarantine", "contracts": "strict",
    })
    assert cfg.on_bad_record == "quarantine" and cfg.contracts == "strict"
    with pytest.raises(ValueError, match="on_bad_record"):
        RunConfig.from_dict({"reference_file": "r", "fastq_pass_dir": "f",
                             "on_bad_record": "ignore"})
    with pytest.raises(ValueError, match="contracts"):
        RunConfig.from_dict({"reference_file": "r", "fastq_pass_dir": "f",
                             "contracts": "paranoid"})
