"""Simulator ground-truth sanity: UMIs land in the expected adapter windows."""

import numpy as np

from ont_tcrconsensus_tpu.io import simulator


def test_reference_shapes():
    rng = np.random.default_rng(0)
    ref = simulator.make_reference(
        rng, num_regions=4, num_similar_pairs=1, num_negative_controls=1
    )
    assert len(ref) == 6
    assert any(n.endswith("_full_n") for n in ref)
    sim_names = [n for n in ref if "_sim" in n]
    assert len(sim_names) == 1
    src = sim_names[0].split("_sim")[0]
    a, b = ref[src], ref[sim_names[0]]
    assert len(a) == len(b)
    ident = sum(x == y for x, y in zip(a, b)) / len(a)
    assert 0.97 < ident < 1.0


def test_library_ground_truth():
    lib = simulator.simulate_library(seed=1, num_regions=3, sub_rate=0.0, ins_rate=0.0, del_rate=0.0)
    assert len(lib.reads) == sum(m.num_reads for m in lib.molecules)
    # with zero errors, each + read must contain its molecule's exact UMIs in
    # the head/tail windows the pipeline searches (81 / 76 nt)
    by_idx = {i: m for i, m in enumerate(lib.molecules)}
    checked = 0
    for header, seq, qual in lib.reads:
        mi = int(header.split("mol=")[1].split()[0])
        orient = header.split("orient=")[1].split()[0]
        mol = by_idx[mi]
        if orient == "-":
            seq = simulator.revcomp(seq)
        assert mol.umi_fwd in seq[:81]
        assert mol.umi_rev in seq[-76:]
        assert len(qual) == len(seq)
        checked += 1
    assert checked > 10


def test_error_model_changes_reads():
    lib0 = simulator.simulate_library(seed=2, num_regions=2, sub_rate=0.0, ins_rate=0.0, del_rate=0.0)
    lib1 = simulator.simulate_library(seed=2, num_regions=2, sub_rate=0.05, ins_rate=0.02, del_rate=0.02)
    assert lib0.reference == lib1.reference
    # same molecules, different read sequences
    assert [m.combined_umi for m in lib0.molecules] == [m.combined_umi for m in lib1.molecules]
    assert lib0.reads != lib1.reads


def test_qualities_reflect_error_rate():
    lo = simulator.simulate_library(seed=3, num_regions=2, sub_rate=0.001, ins_rate=0.0005, del_rate=0.0005)
    hi = simulator.simulate_library(seed=3, num_regions=2, sub_rate=0.05, ins_rate=0.02, del_rate=0.02)

    def mean_q(lib):
        tot = n = 0
        for _, _, q in lib.reads[:20]:
            tot += sum(ord(c) - 33 for c in q)
            n += len(q)
        return tot / n

    assert mean_q(lo) > mean_q(hi) + 5
