"""HBM budgeter: derived batch sizes are monotone, bounded, OOM-safe math."""

from ont_tcrconsensus_tpu.parallel.budget import BudgetModel, detect_hbm_gb


def test_detect_returns_positive():
    assert detect_hbm_gb() > 0


def test_read_batch_monotone_in_budget():
    small = BudgetModel(hbm_gb=2.0)
    big = BudgetModel(hbm_gb=16.0)
    assert big.read_batch(4096) >= small.read_batch(4096)


def test_read_batch_monotone_in_width():
    m = BudgetModel(hbm_gb=8.0)
    assert m.read_batch(1024) >= m.read_batch(4096)


def test_read_batch_power_of_two_and_bounded():
    for gb in (0.5, 2.0, 8.0, 32.0, 1000.0):
        b = BudgetModel(hbm_gb=gb).read_batch(4096)
        assert 128 <= b <= 16384
        assert (b & (b - 1)) == 0  # power of two


def test_cluster_batch_respects_budget():
    m = BudgetModel(hbm_gb=8.0)
    for s in (4, 16, 64):
        for w in (512, 2048, 4096):
            cb = m.cluster_batch(s, w)
            assert 1 <= cb <= 256
            assert (cb & (cb - 1)) == 0
            # the tile must actually fit the working budget
            assert cb * m.cluster_bytes(s, w) <= m.budget_bytes or cb == 1


def test_cluster_batch_shrinks_with_tile_size():
    m = BudgetModel(hbm_gb=8.0)
    assert m.cluster_batch(4, 512) >= m.cluster_batch(64, 4096)


def test_fused_batch_fits_budget():
    m = BudgetModel(hbm_gb=8.0)
    b = m.read_batch(4096, num_refs=1024)
    assert b * m.read_bytes(4096, num_refs=1024) <= m.budget_bytes


def test_cluster_batch_lane_cap():
    """cb * s_bucket never exceeds MAX_POLISH_LANES (pileup dispatch lanes)."""
    m = BudgetModel(hbm_gb=16.0)
    for s in (4, 8, 16, 32, 64):
        cb = m.cluster_batch(s, 2048, 64)
        assert cb * s <= BudgetModel.MAX_POLISH_LANES, (s, cb)
        assert (cb & (cb - 1)) == 0
