"""IO layer: fastx round-trips, bucketing, layout, config."""

import json
import os

import pytest

from ont_tcrconsensus_tpu.io import bucketing, fastx, layout
from ont_tcrconsensus_tpu.pipeline.config import RunConfig


def test_fastq_roundtrip(tmp_path):
    path = tmp_path / "r.fastq.gz"
    recs = [("r1 extra=1", "ACGT", "IIII"), ("r2", "GGTTAA", "!!!!!!")]
    assert fastx.write_fastq(path, recs) == 2
    back = list(fastx.read_fastx(path))
    assert [r.name for r in back] == ["r1", "r2"]
    assert back[0].comment == "extra=1"
    assert back[0].header == "r1 extra=1"
    assert [r.sequence for r in back] == ["ACGT", "GGTTAA"]
    assert [r.quality for r in back] == ["IIII", "!!!!!!"]


def test_fasta_roundtrip_multiline(tmp_path):
    path = tmp_path / "r.fasta"
    fastx.write_fasta(path, [("a", "ACGT" * 30), ("b", "TTTT")], width=17)
    d = fastx.read_fasta_dict(path)
    assert d == {"a": "ACGT" * 30, "b": "TTTT"}
    assert fastx.count_fasta_records(path) == 2


def test_fastq_stats(tmp_path):
    path = tmp_path / "r.fastq"
    fastx.write_fastq(path, [("a", "ACGT", "IIII"), ("b", "AC", "II")])
    st = fastx.fastq_stats(path)
    assert st["num_seqs"] == 2
    assert st["sum_len"] == 6
    assert st["min_len"] == 2 and st["max_len"] == 4
    assert st["avg_qual"] == pytest.approx(40.0)


def test_bucketing_widths_and_padding():
    recs = [
        fastx.FastxRecord("a", "", "A" * 100, "I" * 100),
        fastx.FastxRecord("b", "", "C" * 300, "I" * 300),
        fastx.FastxRecord("c", "", "G" * 100, "I" * 100),
    ]
    batches = list(bucketing.batch_reads(recs, batch_size=4))
    by_width = {b.width: b for b in batches}
    assert set(by_width) == {256, 512}
    b256 = by_width[256]
    assert b256.num_valid == 2
    assert b256.codes.shape == (4, 256)
    assert list(b256.lengths[:2]) == [100, 100]
    assert b256.ids[:2] == ["a", "c"]
    # padding rows are PAD everywhere; the qual filler is QUAL_FILL (the
    # in-distribution mid-range the polisher fallback/training use — inert
    # for quality-carrying rows since spans never reach padding, but a
    # quality-LESS row in a mixed stream exposes it, code-review r5)
    from ont_tcrconsensus_tpu.ops.consensus import QUAL_FILL

    assert (b256.codes[2:] == 5).all()
    assert (b256.quals[2:] == QUAL_FILL).all()


def test_bucketing_drops_out_of_range():
    recs = [
        fastx.FastxRecord("short", "", "A" * 3),
        fastx.FastxRecord("long", "", "A" * 10_000),
        fastx.FastxRecord("ok", "", "A" * 200),
    ]
    batches = list(bucketing.batch_reads(recs, batch_size=8, min_len=10, with_quals=False))
    assert sum(b.num_valid for b in batches) == 1
    assert batches[0].ids[0] == "ok"


def test_layout_resume(tmp_path):
    lay = layout.init_library_dir("/x/barcode01.fastq.gz", tmp_path)
    assert lay.library == "barcode01"
    for sub in layout.SUBDIRS:
        assert (tmp_path / "barcode01" / sub).is_dir()
    with pytest.raises(FileExistsError):
        layout.init_library_dir("/x/barcode01.fastq.gz", tmp_path)
    lay2 = layout.init_library_dir("/x/barcode01.fastq.gz", tmp_path, resume=True)
    lay2.mark_stage_done("align")
    assert lay2.stage_done("align")
    assert not lay2.stage_done("umi_extract")


def test_layout_manifest_corruption_tolerated(tmp_path, capsys):
    """A torn/invalid stage manifest must read as 'no stages done' (with a
    warning) instead of crashing resume with a JSONDecodeError — the
    preemption-mid-write case (ISSUE 2 satellite)."""
    lay = layout.init_library_dir("/x/barcode01.fastq.gz", tmp_path)
    lay.mark_stage_done("round1_consensus")
    assert lay.stage_done("round1_consensus")
    healthy = open(lay.manifest_path).read()

    # torn write: a strict prefix of valid JSON
    with open(lay.manifest_path, "w") as fh:
        fh.write(healthy[: len(healthy) // 2])
    assert lay.completed_stages() == {}
    assert not lay.stage_done("round1_consensus")
    assert "torn/corrupt" in capsys.readouterr().err

    # marking after corruption rewrites a fresh, valid manifest
    lay.mark_stage_done("counts")
    assert set(lay.completed_stages()) == {"counts"}

    # valid JSON of the wrong shape is tolerated the same way
    with open(lay.manifest_path, "w") as fh:
        fh.write("[1, 2, 3]")
    assert lay.completed_stages() == {}

    # empty file (fsync-less crash truncation) too
    open(lay.manifest_path, "w").close()
    assert lay.completed_stages() == {}


def _lib_with_artifact(tmp_path, content=b"TCR,Count\nregionA,3\n"):
    lay = layout.init_library_dir("/x/barcode01.fastq.gz", tmp_path)
    art = tmp_path / "barcode01" / "counts" / "umi_consensus_counts.csv"
    art.write_bytes(content)
    return lay, art


def test_manifest_v2_records_checksums_and_verifies(tmp_path):
    """mark_stage_done(artifacts=...) writes a v2 manifest whose entries
    carry sha256 + byte size, and verify_stage passes in every mode on an
    untouched artifact."""
    lay, art = _lib_with_artifact(tmp_path)
    lay.mark_stage_done("counts", artifacts=[art])

    raw = json.loads(open(lay.manifest_path).read())
    assert raw["version"] == layout.MANIFEST_VERSION
    rel = os.path.relpath(art, lay.library_dir)
    meta = raw["stages"]["counts"]["artifacts"][rel]
    want_sha, want_bytes = layout.sha256_file(art)
    assert meta == {"sha256": want_sha, "bytes": want_bytes}

    for mode in layout.VERIFY_MODES:
        ok, why = lay.verify_stage("counts", mode)
        assert ok and why is None, (mode, why)
    # an unmarked stage fails in every mode, including off
    ok, why = lay.verify_stage("polish", "off")
    assert not ok and "not marked done" in why
    with pytest.raises(ValueError, match="verify_resume"):
        lay.verify_stage("counts", "paranoid")


def test_manifest_verify_catches_truncation_missing_and_bit_rot(tmp_path):
    lay, art = _lib_with_artifact(tmp_path)
    lay.mark_stage_done("counts", artifacts=[art])

    # size-changing truncation: fast (and full) catch it; off trusts
    original = art.read_bytes()
    art.write_bytes(original[:-3])
    assert lay.verify_stage("counts", "off") == (True, None)
    ok, why = lay.verify_stage("counts", "fast")
    assert not ok and "size" in why
    assert not lay.verify_stage("counts", "full")[0]

    # size-preserving bit rot: ONLY full's sha256 catches it
    flipped = bytearray(original)
    flipped[len(flipped) // 2] ^= 0x01
    art.write_bytes(bytes(flipped))
    assert lay.verify_stage("counts", "fast") == (True, None)
    ok, why = lay.verify_stage("counts", "full")
    assert not ok and "sha256" in why

    # missing artifact: fast catches it
    art.unlink()
    ok, why = lay.verify_stage("counts", "fast")
    assert not ok and "missing" in why


def test_manifest_v1_read_path_and_v2_upgrade(tmp_path):
    """v1 -> v2 migration: a flat {stage: time} manifest (pre-checksum
    runs) still reads, its stages are unverifiable under fast/full (warn +
    re-run semantics live in run.py), and marking a NEW stage on top
    upgrades the file to v2 while keeping the v1 entries readable."""
    lay, art = _lib_with_artifact(tmp_path)
    with open(lay.manifest_path, "w") as fh:
        json.dump({"round1_consensus": 1700000000.0}, fh)  # a v1 file

    assert lay.stage_done("round1_consensus")
    assert lay.completed_stages() == {"round1_consensus": 1700000000.0}
    # off trusts the bare mark; fast/full refuse to trust it
    assert lay.verify_stage("round1_consensus", "off") == (True, None)
    for mode in ("fast", "full"):
        ok, why = lay.verify_stage("round1_consensus", mode)
        assert not ok and "unverifiable" in why

    # marking on top migrates the file to v2 (mixed-version manifest)
    lay.mark_stage_done("counts", artifacts=[art])
    raw = json.loads(open(lay.manifest_path).read())
    assert raw["version"] == layout.MANIFEST_VERSION
    assert raw["stages"]["round1_consensus"]["artifacts"] is None  # still v1-era
    assert raw["stages"]["counts"]["artifacts"]  # checksummed
    # the v2-era stage verifies; the v1-era stage stays unverifiable
    assert lay.verify_stage("counts", "full") == (True, None)
    assert not lay.verify_stage("round1_consensus", "fast")[0]
    assert set(lay.completed_stages()) == {"round1_consensus", "counts"}


def test_manifest_malformed_v2_entry_dropped(tmp_path, capsys):
    """One malformed stage entry (disk bit-flip inside valid JSON) drops
    that entry with a warning instead of poisoning the whole manifest."""
    lay, art = _lib_with_artifact(tmp_path)
    with open(lay.manifest_path, "w") as fh:
        json.dump({"version": 2, "stages": {
            "round1_consensus": "not-a-dict",
            "counts": {"t": 1700000000.0, "artifacts": None},
        }}, fh)
    assert set(lay.completed_stages()) == {"counts"}
    assert "malformed" in capsys.readouterr().err
    # v2 with a torn stages map reads as nothing done
    with open(lay.manifest_path, "w") as fh:
        json.dump({"version": 2, "stages": [1, 2]}, fh)
    assert lay.completed_stages() == {}
    assert "no valid 'stages'" in capsys.readouterr().err
    # valid-JSON-but-garbage VALUES never crash (the never-crash contract
    # covers bit rot inside the JSON too): v1 string time, v2 null time
    with open(lay.manifest_path, "w") as fh:
        json.dump({"counts": "x", "align": 1700000000.0}, fh)
    assert set(lay.completed_stages()) == {"align"}
    assert "malformed" in capsys.readouterr().err
    with open(lay.manifest_path, "w") as fh:
        json.dump({"version": 2, "stages": {
            "counts": {"t": None, "artifacts": None},
        }}, fh)
    assert lay.completed_stages() == {}


def test_config_defaults_and_validation(tmp_path):
    cfg = RunConfig.from_dict({"reference_file": "ref.fa", "fastq_pass_dir": "fq"})
    assert cfg.cluster_identity == pytest.approx(0.93)
    assert cfg.vsearch_identity == 0.93

    with pytest.raises(ValueError, match="unknown config key"):
        RunConfig.from_dict({"reference_file": "r", "fastq_pass_dir": "f", "typo_key": 1})
    with pytest.raises(ValueError, match="max_ee_rate_base"):
        RunConfig.from_dict(
            {"reference_file": "r", "fastq_pass_dir": "f", "max_ee_rate_base": 2.0}
        )
    # reference compat keys are accepted and ignored
    cfg2 = RunConfig.from_dict(
        {
            "reference_file": "r",
            "fastq_pass_dir": "f",
            "dorado_excutable": "/opt/dorado",
            "medaka_model": "r1041_e82_400bps_sup_v5.0.0",
        }
    )
    assert cfg2.reference_file == "r"


def test_config_json_roundtrip(tmp_path):
    import json

    p = tmp_path / "cfg.json"
    p.write_text(json.dumps({"reference_file": "r.fa", "fastq_pass_dir": "fq", "minimal_length": 99}))
    cfg = RunConfig.from_json(p)
    assert cfg.minimal_length == 99


def test_fasta_batches_have_no_quals():
    """FASTA records (quality=None) must yield batch.quals=None — an
    all-93 filler array would poison the v4 polisher's quality channels
    (code-review r5); FASTQ records keep their phred array."""
    from ont_tcrconsensus_tpu.io import bucketing, fastx

    fa = [fastx.FastxRecord(f"r{i}", "", "ACGT" * 50, None) for i in range(3)]
    fq = [fastx.FastxRecord(f"r{i}", "", "ACGT" * 50, "I" * 200) for i in range(3)]
    (b_fa,) = list(bucketing.batch_reads(fa, batch_size=8))
    (b_fq,) = list(bucketing.batch_reads(fq, batch_size=8))
    assert b_fa.quals is None
    assert b_fq.quals is not None and (b_fq.quals[0, :200] == ord("I") - 33).all()
