"""IO layer: fastx round-trips, bucketing, layout, config."""

import pytest

from ont_tcrconsensus_tpu.io import bucketing, fastx, layout
from ont_tcrconsensus_tpu.pipeline.config import RunConfig


def test_fastq_roundtrip(tmp_path):
    path = tmp_path / "r.fastq.gz"
    recs = [("r1 extra=1", "ACGT", "IIII"), ("r2", "GGTTAA", "!!!!!!")]
    assert fastx.write_fastq(path, recs) == 2
    back = list(fastx.read_fastx(path))
    assert [r.name for r in back] == ["r1", "r2"]
    assert back[0].comment == "extra=1"
    assert back[0].header == "r1 extra=1"
    assert [r.sequence for r in back] == ["ACGT", "GGTTAA"]
    assert [r.quality for r in back] == ["IIII", "!!!!!!"]


def test_fasta_roundtrip_multiline(tmp_path):
    path = tmp_path / "r.fasta"
    fastx.write_fasta(path, [("a", "ACGT" * 30), ("b", "TTTT")], width=17)
    d = fastx.read_fasta_dict(path)
    assert d == {"a": "ACGT" * 30, "b": "TTTT"}
    assert fastx.count_fasta_records(path) == 2


def test_fastq_stats(tmp_path):
    path = tmp_path / "r.fastq"
    fastx.write_fastq(path, [("a", "ACGT", "IIII"), ("b", "AC", "II")])
    st = fastx.fastq_stats(path)
    assert st["num_seqs"] == 2
    assert st["sum_len"] == 6
    assert st["min_len"] == 2 and st["max_len"] == 4
    assert st["avg_qual"] == pytest.approx(40.0)


def test_bucketing_widths_and_padding():
    recs = [
        fastx.FastxRecord("a", "", "A" * 100, "I" * 100),
        fastx.FastxRecord("b", "", "C" * 300, "I" * 300),
        fastx.FastxRecord("c", "", "G" * 100, "I" * 100),
    ]
    batches = list(bucketing.batch_reads(recs, batch_size=4))
    by_width = {b.width: b for b in batches}
    assert set(by_width) == {256, 512}
    b256 = by_width[256]
    assert b256.num_valid == 2
    assert b256.codes.shape == (4, 256)
    assert list(b256.lengths[:2]) == [100, 100]
    assert b256.ids[:2] == ["a", "c"]
    # padding rows are PAD everywhere; the qual filler is QUAL_FILL (the
    # in-distribution mid-range the polisher fallback/training use — inert
    # for quality-carrying rows since spans never reach padding, but a
    # quality-LESS row in a mixed stream exposes it, code-review r5)
    from ont_tcrconsensus_tpu.ops.consensus import QUAL_FILL

    assert (b256.codes[2:] == 5).all()
    assert (b256.quals[2:] == QUAL_FILL).all()


def test_bucketing_drops_out_of_range():
    recs = [
        fastx.FastxRecord("short", "", "A" * 3),
        fastx.FastxRecord("long", "", "A" * 10_000),
        fastx.FastxRecord("ok", "", "A" * 200),
    ]
    batches = list(bucketing.batch_reads(recs, batch_size=8, min_len=10, with_quals=False))
    assert sum(b.num_valid for b in batches) == 1
    assert batches[0].ids[0] == "ok"


def test_layout_resume(tmp_path):
    lay = layout.init_library_dir("/x/barcode01.fastq.gz", tmp_path)
    assert lay.library == "barcode01"
    for sub in layout.SUBDIRS:
        assert (tmp_path / "barcode01" / sub).is_dir()
    with pytest.raises(FileExistsError):
        layout.init_library_dir("/x/barcode01.fastq.gz", tmp_path)
    lay2 = layout.init_library_dir("/x/barcode01.fastq.gz", tmp_path, resume=True)
    lay2.mark_stage_done("align")
    assert lay2.stage_done("align")
    assert not lay2.stage_done("umi_extract")


def test_layout_manifest_corruption_tolerated(tmp_path, capsys):
    """A torn/invalid stage manifest must read as 'no stages done' (with a
    warning) instead of crashing resume with a JSONDecodeError — the
    preemption-mid-write case (ISSUE 2 satellite)."""
    lay = layout.init_library_dir("/x/barcode01.fastq.gz", tmp_path)
    lay.mark_stage_done("round1_consensus")
    assert lay.stage_done("round1_consensus")
    healthy = open(lay.manifest_path).read()

    # torn write: a strict prefix of valid JSON
    with open(lay.manifest_path, "w") as fh:
        fh.write(healthy[: len(healthy) // 2])
    assert lay.completed_stages() == {}
    assert not lay.stage_done("round1_consensus")
    assert "torn/corrupt" in capsys.readouterr().err

    # marking after corruption rewrites a fresh, valid manifest
    lay.mark_stage_done("counts")
    assert set(lay.completed_stages()) == {"counts"}

    # valid JSON of the wrong shape is tolerated the same way
    with open(lay.manifest_path, "w") as fh:
        fh.write("[1, 2, 3]")
    assert lay.completed_stages() == {}

    # empty file (fsync-less crash truncation) too
    open(lay.manifest_path, "w").close()
    assert lay.completed_stages() == {}


def test_config_defaults_and_validation(tmp_path):
    cfg = RunConfig.from_dict({"reference_file": "ref.fa", "fastq_pass_dir": "fq"})
    assert cfg.cluster_identity == pytest.approx(0.93)
    assert cfg.vsearch_identity == 0.93

    with pytest.raises(ValueError, match="unknown config key"):
        RunConfig.from_dict({"reference_file": "r", "fastq_pass_dir": "f", "typo_key": 1})
    with pytest.raises(ValueError, match="max_ee_rate_base"):
        RunConfig.from_dict(
            {"reference_file": "r", "fastq_pass_dir": "f", "max_ee_rate_base": 2.0}
        )
    # reference compat keys are accepted and ignored
    cfg2 = RunConfig.from_dict(
        {
            "reference_file": "r",
            "fastq_pass_dir": "f",
            "dorado_excutable": "/opt/dorado",
            "medaka_model": "r1041_e82_400bps_sup_v5.0.0",
        }
    )
    assert cfg2.reference_file == "r"


def test_config_json_roundtrip(tmp_path):
    import json

    p = tmp_path / "cfg.json"
    p.write_text(json.dumps({"reference_file": "r.fa", "fastq_pass_dir": "fq", "minimal_length": 99}))
    cfg = RunConfig.from_json(p)
    assert cfg.minimal_length == 99


def test_fasta_batches_have_no_quals():
    """FASTA records (quality=None) must yield batch.quals=None — an
    all-93 filler array would poison the v4 polisher's quality channels
    (code-review r5); FASTQ records keep their phred array."""
    from ont_tcrconsensus_tpu.io import bucketing, fastx

    fa = [fastx.FastxRecord(f"r{i}", "", "ACGT" * 50, None) for i in range(3)]
    fq = [fastx.FastxRecord(f"r{i}", "", "ACGT" * 50, "I" * 200) for i in range(3)]
    (b_fa,) = list(bucketing.batch_reads(fa, batch_size=8))
    (b_fq,) = list(bucketing.batch_reads(fq, batch_size=8))
    assert b_fa.quals is None
    assert b_fq.quals is not None and (b_fq.quals[0, :200] == ord("I") - 33).all()
