"""Multi-host distribution (parallel/distributed.py).

Unit tests cover the deterministic library sharding; the e2e test launches
TWO real processes wired through ``jax.distributed`` (gloo over localhost —
the CPU stand-in for DCN), each running the full pipeline on its library
shard of a shared dataset, and checks both end with the complete, identical
merged counts (SURVEY §2.3 multi-host story: shard-by-barcode).
"""

import json
import os
import socket
import subprocess
import sys
import textwrap

import pytest

from ont_tcrconsensus_tpu.parallel import distributed as dist

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_shard_libraries_partitions_and_is_deterministic():
    paths = [f"fastq_pass/barcode{i:02d}/x.fastq" for i in range(5)]
    shards = [dist.shard_libraries(paths, index=i, count=3) for i in range(3)]
    # disjoint, complete, deterministic under input order
    assert sorted(sum(shards, [])) == sorted(paths)
    assert all(
        dist.shard_libraries(list(reversed(paths)), index=i, count=3) == shards[i]
        for i in range(3)
    )


def test_shard_libraries_single_process_is_identity():
    paths = ["b", "a"]
    assert dist.shard_libraries(paths, index=0, count=1) == ["b", "a"]


def test_allgather_object_single_process():
    assert dist.allgather_object({"x": 1}) == [{"x": 1}]
    assert dist.merge_results({"lib": {"r": 2}}) == {"lib": {"r": 2}}


_WORKER = textwrap.dedent("""
    import json, os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    root, pid, port, out_path = sys.argv[1], int(sys.argv[2]), sys.argv[3], sys.argv[4]
    mesh_n = int(sys.argv[5]) if len(sys.argv) > 5 else 0
    jax.distributed.initialize(
        coordinator_address=f"localhost:{{port}}", num_processes=2, process_id=pid
    )
    sys.path.insert(0, {repo!r})
    from ont_tcrconsensus_tpu.pipeline.config import RunConfig
    from ont_tcrconsensus_tpu.pipeline.run import run_with_config
    cfg = RunConfig.from_dict({{
        "reference_file": os.path.join(root, "reference.fa"),
        "fastq_pass_dir": os.path.join(root, "fastq_pass"),
        "minimal_length": 1000,
        "min_reads_per_cluster": 4,
        "read_batch_size": 128,
        "polish_method": "poa",
        "delete_tmp_files": False,
        "distributed": True,
        **({{"mesh_shape": {{"data": mesh_n}}}} if mesh_n else {{}}),
    }})
    results = run_with_config(cfg)
    with open(out_path, "w") as fh:
        json.dump(results, fh)
""")


def _run_two_process_pipeline(tmp_path, devices_per_proc: int, mesh_n: int):
    from ont_tcrconsensus_tpu.io import fastx, simulator

    lib = simulator.simulate_library(
        seed=23,
        num_regions=3,
        molecules_per_region=(2, 3),
        reads_per_molecule=(5, 8),
        sub_rate=0.01,
        ins_rate=0.004,
        del_rate=0.004,
    )
    fastx.write_fasta(tmp_path / "reference.fa", lib.reference.items())
    for barcode in ("barcode01", "barcode02"):
        fq_dir = tmp_path / "fastq_pass" / barcode
        fq_dir.mkdir(parents=True)
        fastx.write_fastq(fq_dir / f"{barcode}.fastq.gz", lib.reads)

    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER.format(repo=REPO))
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    env = dict(os.environ)
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={devices_per_proc}"
    )
    procs, outs = [], []
    for pid in range(2):
        out = tmp_path / f"results_{pid}.json"
        outs.append(out)
        procs.append(subprocess.Popen(
            [sys.executable, str(worker), str(tmp_path), str(pid), str(port),
             str(out), str(mesh_n)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        ))
    for p in procs:
        _, err = p.communicate(timeout=900)
        assert p.returncode == 0, err.decode()[-3000:]

    want = {"barcode01": lib.true_counts, "barcode02": lib.true_counts}
    merged = [json.loads(o.read_text()) for o in outs]
    assert merged[0] == want and merged[1] == want

    # each process polished only its own shard (library dirs prove ownership)
    nano = tmp_path / "fastq_pass" / "nano_tcr"
    assert (nano / "barcode01" / "counts" / "umi_consensus_counts.csv").exists()
    assert (nano / "barcode02" / "counts" / "umi_consensus_counts.csv").exists()


@pytest.mark.slow
def test_two_process_pipeline_shards_and_merges(tmp_path):
    _run_two_process_pipeline(tmp_path, devices_per_proc=1, mesh_n=0)


@pytest.mark.slow
def test_two_process_pipeline_with_intra_host_mesh(tmp_path):
    """Multi-host x multi-chip (north-star configs #3/#5, VERDICT r2 #8):
    two processes sharding libraries over gloo/DCN, each running its shard
    on a 4-virtual-device intra-host mesh (fused pass + polish + UMI
    distances all shard_map over 'data'); exact merged counts on both."""
    _run_two_process_pipeline(tmp_path, devices_per_proc=4, mesh_n=4)
