"""Drive the installed package the way the pipeline will: encode a batch of
synthetic ONT-like reads, EE-filter them, locate degenerate UMIs, and compute
an identity matrix between extracted UMIs — on the default (TPU) backend."""
import numpy as np
import jax

from ont_tcrconsensus_tpu.ops import encode, ee_filter, fuzzy_match, edit_distance

print("devices:", jax.devices())
rng = np.random.default_rng(42)

UMI_FWD = "TTTVVTTVVVVTTVVVVTTVVVVTTVVVVTTT"
def realize(p): return "".join(rng.choice(list({"V":"ACG","B":"CGT","T":"T","A":"A"}[c])) for c in p)

# 64 reads: 5' = 20nt adapter + UMI + filler; half get a mutated UMI; 8 get junk quality
reads, quals, true_umis = [], [], []
for i in range(64):
    umi = realize(UMI_FWD)
    body = "".join(rng.choice(list("ACGT")) for _ in range(400))
    seq = "".join(rng.choice(list("ACGT")) for _ in range(20)) + umi + body
    q = "I" * len(seq) if i % 8 else "%" * len(seq)   # every 8th read low quality
    reads.append(seq); quals.append(q); true_umis.append(umi)

qb, qlens = encode.phred_batch(quals, pad_to=512)
keep = np.asarray(ee_filter.ee_rate_mask(qb, qlens, max_ee_rate=0.07, min_len=100))
print("EE filter kept", keep.sum(), "of", len(reads), "(expect 56)")

wins = [r[:81] for r, k in zip(reads, keep) if k]
wm, wl = encode.encode_mask_batch(wins)
pm = encode.encode_mask(UMI_FWD)
d, s, e = (np.asarray(x) for x in fuzzy_match.fuzzy_find(pm, wm, wl))
kept_truth = [u for u, k in zip(true_umis, keep) if k]
ok = sum(wins[i][s[i]:e[i]] == kept_truth[i] and d[i] == 0 for i in range(len(wins)))
print("UMI located exactly in", ok, "of", len(wins))

ub, ul = encode.encode_batch([wins[i][s[i]:e[i]] for i in range(len(wins))])
ident = np.asarray(edit_distance.identity_matrix(ub, ul, ub, ul))
print("identity diag all 1.0:", bool(np.allclose(np.diag(ident), 1.0)))
print("off-diag max identity:", float(np.max(ident - np.eye(len(ident)))))
