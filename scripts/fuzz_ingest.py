#!/usr/bin/env python
"""Differential ingest fuzzing: native C++ parser vs pure-Python twin.

The native fastx parser is ctypes into C++ — a segfault there kills the
whole process, making it the highest-risk untested surface in the repo
(it sits directly on the ingest path, pipeline/assign.py). This harness
drives seeded byte-level corpus mutations through BOTH parsers and asserts
they agree record-for-record AND rejection-for-rejection: no crash, no
hang, no divergence.

Per mutated corpus, four properties are checked:

1. native tolerant whole-file == Python tolerant: record count, raw
   headers, dense codes, phreds, and the (offset, reason, raw) bad list;
2. native tolerant CHUNKED (small chunk_bases, forcing many carry/resync
   boundaries) == native tolerant whole-file;
3. strict cross-check: the strict native parse raises ValueError IFF the
   tolerant parse found at least one bad region;
4. the strict native parse never crashes (any segfault kills the run).

Mutation operators (ISSUE 3): truncation, CRLF conversion, qual/seq length
mismatch, sub-Phred33 bytes, non-ACGTN bases, mid-stream gzip truncation,
empty files, pathological record sizes, junk splices, blank-line noise.

Usage:
    python scripts/fuzz_ingest.py [--seeds 5] [--cases 200] [--start-seed 0]
    python scripts/fuzz_ingest.py --sanitized [...]

``--sanitized`` (ISSUE 4) replays the same differential corpus through an
ASan/UBSan-instrumented build of the C++ parser: it compiles the library
with ``-fsanitize=address,undefined``, then re-execs itself under
``LD_PRELOAD=libasan.so`` with ``GRAFT_FASTX_LIB`` pointing the loader at
the instrumented artifact. PR 3's campaign caught an out-of-bounds read
only because the OOB happened to change parse output; under ASan the same
bug dies on the first touch, with a stack. Any sanitizer report aborts
the process (``abort_on_error=1`` / ``halt_on_error=1``) and fails the
run.

Exit status 1 on any divergence. Deterministic per (seed, case index).
Tier-1 runs a 5-seed smoke (tests/test_fuzz_ingest.py) plus a sanitized
smoke (scripts/tier1.sh); the >=1000-corpus campaigns (plain and
sanitized) are the slow-marked tests / manual runs of this script.
"""

from __future__ import annotations

import argparse
import gzip
import os
import random
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ont_tcrconsensus_tpu.io import native  # noqa: E402
from ont_tcrconsensus_tpu.io import validate as validate_mod  # noqa: E402


# ---------------------------------------------------------------------------
# corpus generation


def gen_clean_corpus(rng: random.Random) -> tuple[bytes, bool]:
    """A clean FASTA/FASTQ corpus; returns (text bytes, prefer_gzip)."""
    kind = rng.random()
    lines: list[bytes] = []
    if kind < 0.15:  # FASTA
        for i in range(rng.randrange(1, 30)):
            lines.append(b">rec%d some description %d\n" % (i, i))
            seq = bytes(rng.choice(b"ACGT") for _ in range(rng.randrange(0, 120)))
            width = rng.randrange(10, 61)
            for j in range(0, max(len(seq), 1), width):
                lines.append(seq[j:j + width] + b"\n")
    else:  # FASTQ
        n = rng.randrange(0, 40)
        for i in range(n):
            if rng.random() < 0.02:  # pathological record size
                ln = rng.randrange(50_000, 200_000)
            else:
                ln = rng.randrange(0, 300)
            seq = bytes(rng.choice(b"ACGTN") for _ in range(ln))
            qual = bytes(rng.randrange(33, 94) for _ in range(ln))
            lines.append(b"@read%d meta=%d\n" % (i, i))
            lines.append(seq + b"\n+\n" + qual + b"\n")
            if rng.random() < 0.1:
                lines.append(b"\n")  # blank separator noise (tolerated)
    return b"".join(lines), rng.random() < 0.4


# ---------------------------------------------------------------------------
# mutation operators (byte level, pre-compression)


def mut_truncate(rng, data):
    if not data:
        return data
    return data[: rng.randrange(len(data))]


def mut_crlf(rng, data):
    return data.replace(b"\n", b"\r\n")


def mut_len_mismatch(rng, data):
    # clip or grow a random qual line (line index 3 mod 4 in clean FASTQ)
    lines = data.split(b"\n")
    idx = [i for i in range(3, len(lines), 4) if lines[i]]
    if not idx:
        return data
    i = rng.choice(idx)
    lines[i] = lines[i][:-1] if rng.random() < 0.5 else lines[i] + b"II"
    return b"\n".join(lines)


def mut_subphred(rng, data):
    lines = data.split(b"\n")
    idx = [i for i in range(3, len(lines), 4) if lines[i]]
    if not idx:
        return data
    i = rng.choice(idx)
    q = bytearray(lines[i])
    q[rng.randrange(len(q))] = rng.randrange(0, 33)
    lines[i] = bytes(q)
    return b"\n".join(lines)


def mut_nonacgtn(rng, data):
    lines = data.split(b"\n")
    idx = [i for i in range(1, len(lines), 4) if lines[i]]
    if not idx:
        return data
    i = rng.choice(idx)
    s = bytearray(lines[i])
    for _ in range(rng.randrange(1, 4)):
        s[rng.randrange(len(s))] = rng.choice(b"XYZ*.-xyzRWSK")
    lines[i] = bytes(s)
    return b"\n".join(lines)


def mut_junk_splice(rng, data):
    junk = rng.choice([
        b"THIS IS NOT A RECORD\n",
        b"\x00\x01\x02 binary garbage \xff\xfe\n",
        b"+orphan plus line\n",
        b"@orphan_header_only\n",
        b"@frag\nACGT\n",
    ])
    pos = rng.randrange(len(data) + 1)
    # bias splices toward line boundaries (record-level damage); raw
    # mid-line splices still occur at 30%
    if rng.random() < 0.7:
        pos = data.rfind(b"\n", 0, pos) + 1
    return data[:pos] + junk + data[pos:]


def mut_byte_flip(rng, data):
    if not data:
        return data
    b = bytearray(data)
    b[rng.randrange(len(b))] = rng.randrange(256)
    return bytes(b)


def mut_empty(rng, data):
    return b""


def mut_blank_noise(rng, data):
    lines = data.split(b"\n")
    for _ in range(rng.randrange(1, 4)):
        lines.insert(rng.randrange(len(lines) + 1), b"")
    return b"\n".join(lines)


MUTATORS = [
    ("truncate", mut_truncate),
    ("crlf", mut_crlf),
    ("len_mismatch", mut_len_mismatch),
    ("subphred", mut_subphred),
    ("nonacgtn", mut_nonacgtn),
    ("junk_splice", mut_junk_splice),
    ("byte_flip", mut_byte_flip),
    ("empty", mut_empty),
    ("blank_noise", mut_blank_noise),
]


def mutate_corpus(rng: random.Random, data: bytes) -> tuple[bytes, list[str]]:
    names: list[str] = []
    for _ in range(rng.randrange(0, 3)):
        name, fn = rng.choice(MUTATORS)
        data = fn(rng, data)
        names.append(name)
    return data, names


# ---------------------------------------------------------------------------
# the differential check


def differential_check(data: bytes, tmp_dir: str, gz: bool,
                       gz_truncate_frac: float | None = None,
                       chunk_bases: int = 512) -> list[str]:
    """Run one corpus through both parsers; returns divergence descriptions
    (empty when the parsers agree on everything)."""
    problems: list[str] = []
    suffix = ".fastq.gz" if gz else ".fastq"
    payload = gzip.compress(data) if gz else data
    if gz and gz_truncate_frac is not None:
        payload = payload[: max(0, int(len(payload) * gz_truncate_frac))]
    fd, path = tempfile.mkstemp(suffix=suffix, dir=tmp_dir)
    with os.fdopen(fd, "wb") as fh:
        fh.write(payload)
    try:
        py_recs, py_bads = validate_mod.parse_path_tolerant(path)
        nat = native.parse_file(path, tolerant=True)
        if nat is None:
            return []  # no toolchain: nothing to differ against
        if nat.num_records != len(py_recs):
            problems.append(
                f"record count: native {nat.num_records} vs py {len(py_recs)}"
            )
        else:
            for i, rec in enumerate(py_recs):
                name, codes, quals = nat.record(i)
                if name != rec.header.decode("utf-8", "replace"):
                    problems.append(f"record {i} header mismatch")
                    break
                want = validate_mod.CODE_LUT[np.frombuffer(rec.seq, np.uint8)]
                if not np.array_equal(codes, want):
                    problems.append(f"record {i} codes mismatch")
                    break
                if rec.qual is not None:
                    wq = np.frombuffer(rec.qual, np.uint8) - 33
                    if quals is None or not np.array_equal(quals, wq):
                        problems.append(f"record {i} quals mismatch")
                        break
        nat_bads = [(o, r, raw) for o, r, raw in nat.bad]
        pyb = [(b.offset, b.reason, b.raw) for b in py_bads]
        if nat_bads != pyb:
            problems.append(
                f"bad-record lists differ: native {[(o, r) for o, r, _ in nat_bads]}"
                f" vs py {[(o, r) for o, r, _ in pyb]}"
            )
        # chunked vs whole-file native (carry/resync across boundaries)
        chunks = list(native.parse_chunks(path, chunk_bases=chunk_bases,
                                          tolerant=True))
        if sum(c.num_records for c in chunks) != nat.num_records:
            problems.append("chunked record count != whole-file")
        elif nat.num_records and not np.array_equal(
            np.concatenate([c.codes for c in chunks]) if chunks else np.array([]),
            nat.codes,
        ):
            problems.append("chunked codes != whole-file")
        if [t for c in chunks for t in c.bad] != nat_bads:
            problems.append("chunked bad list != whole-file")
        # strict cross-check: rejects IFF the tolerant parse found damage
        strict_raised = False
        try:
            native.parse_file(path)
        except ValueError:
            strict_raised = True
        if strict_raised != bool(pyb):
            problems.append(
                f"strict raised={strict_raised} but tolerant found "
                f"{len(pyb)} bad region(s)"
            )
    finally:
        os.remove(path)
    return problems


def run_case(seed: int, case: int, tmp_dir: str) -> list[str]:
    rng = random.Random(f"fuzz:{seed}:{case}")
    data, gz = gen_clean_corpus(rng)
    data, names = mutate_corpus(rng, data)
    gz_trunc = None
    if gz and rng.random() < 0.25:  # mid-stream gzip truncation
        gz_trunc = rng.random()
        names = names + ["gzip_truncate"]
    problems = differential_check(data, tmp_dir, gz, gz_truncate_frac=gz_trunc)
    return [f"seed={seed} case={case} muts={names}: {p}" for p in problems]


def run_campaign(seeds: list[int], cases: int, tmp_dir: str,
                 log=None) -> list[str]:
    failures: list[str] = []
    total = 0
    for seed in seeds:
        for case in range(cases):
            failures.extend(run_case(seed, case, tmp_dir))
            total += 1
        if log:
            log(f"fuzz: seed {seed} done ({total} corpora, "
                f"{len(failures)} divergences)")
    return failures


SANITIZE_FLAGS = "address,undefined"
_SAN_CHILD_ENV = "_GRAFT_SAN_CHILD"


def sanitized_lib_path() -> str:
    """Cache path of the instrumented build (gitignored like libfastx.so)."""
    return os.path.join(os.path.dirname(native._SRC), "libfastx_san.so")


def reexec_sanitized(argv: list[str]) -> int:
    """Build the ASan/UBSan parser and replay ``argv`` under the sanitizer.

    The ASan runtime must be in the process before the instrumented .so
    loads, and this Python is not ASan-linked — so the replay happens in a
    re-exec'd child with ``LD_PRELOAD=libasan.so``. Returns the child's
    exit status; build/toolchain unavailability is a skip (0) with a
    notice, matching the plain fuzzer's no-toolchain behavior.
    """
    lib = sanitized_lib_path()
    if (not os.path.exists(lib)
            or os.path.getmtime(lib) < os.path.getmtime(native._SRC)):
        ok, out = native.build_library(lib, sanitize=SANITIZE_FLAGS)
        if not ok:
            print(f"fuzz --sanitized: sanitized build failed/unavailable; "
                  f"skipping ({out.strip()[:200]})", file=sys.stderr)
            return 0
    asan = native.asan_runtime_path()
    if asan is None:
        print("fuzz --sanitized: libasan.so not found; skipping", file=sys.stderr)
        return 0
    env = dict(
        os.environ,
        LD_PRELOAD=asan,
        ASAN_OPTIONS="detect_leaks=0:abort_on_error=1",
        UBSAN_OPTIONS="print_stacktrace=1:halt_on_error=1:abort_on_error=1",
        **{native.LIB_OVERRIDE_ENV: lib, _SAN_CHILD_ENV: "1"},
    )
    # leak detection off on purpose: the interpreter + numpy leak-at-exit
    # noise would drown real reports; the fuzzer's own allocations are
    # handle-scoped (fastx_free) and OOB/UAF/UB all still abort
    import subprocess

    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), *argv], env=env,
    )
    if proc.returncode:
        print(f"fuzz --sanitized: FAIL (child exit {proc.returncode}; a "
              "sanitizer report aborts the replay)", file=sys.stderr)
    return proc.returncode


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seeds", type=int, default=5, help="number of seeds")
    ap.add_argument("--start-seed", type=int, default=0)
    ap.add_argument("--cases", type=int, default=200,
                    help="mutated corpora per seed")
    ap.add_argument("--sanitized", action="store_true",
                    help="replay through the ASan/UBSan parser build")
    args = ap.parse_args(argv)
    if args.sanitized and not os.environ.get(_SAN_CHILD_ENV):
        child_argv = [a for a in (argv if argv is not None else sys.argv[1:])
                      if a != "--sanitized"]
        return reexec_sanitized(["--sanitized", *child_argv])
    if args.sanitized:
        print(f"fuzz: sanitized replay (fsanitize={SANITIZE_FLAGS}, "
              f"lib={os.environ.get(native.LIB_OVERRIDE_ENV)})", file=sys.stderr)
    if not native.available():
        print("fuzz: native parser unavailable (no C++ toolchain); nothing "
              "to differ against", file=sys.stderr)
        return 0
    seeds = list(range(args.start_seed, args.start_seed + args.seeds))
    with tempfile.TemporaryDirectory(prefix="fuzz_ingest_") as tmp_dir:
        failures = run_campaign(seeds, args.cases, tmp_dir,
                                log=lambda m: print(m, file=sys.stderr))
    n = args.seeds * args.cases
    if failures:
        for f in failures[:50]:
            print(f"DIVERGENCE: {f}", file=sys.stderr)
        print(f"fuzz: FAIL — {len(failures)} divergence(s) over {n} corpora",
              file=sys.stderr)
        return 1
    print(f"fuzz: OK — {n} corpora, zero crashes, zero divergences")
    return 0


if __name__ == "__main__":
    sys.exit(main())
