#!/usr/bin/env bash
# Tier-1 gate (ROADMAP.md "Tier-1 verify") + a fast chaos smoke + a seeded
# ingest-fuzz smoke.
#
# Usage: scripts/tier1.sh [--no-chaos]
#
# Stage 1 is the exact ROADMAP tier-1 command: the full non-slow suite on
# the CPU backend (this already includes the non-slow chaos scenarios and
# the 5-seed fuzz smoke). Stage 2 re-runs ONLY the fast chaos subset
# (-m 'chaos and not slow') so a robustness regression is named explicitly
# in CI output instead of drowning in the full run; pass --no-chaos to
# skip it. Stage 3 re-runs the differential ingest fuzzer standalone
# (5 seeds; the >=1000-corpus campaign is the slow-marked test or
# `python scripts/fuzz_ingest.py --cases 250`).

set -o pipefail
cd "$(dirname "$0")/.."

rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly \
    2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)"
if [ "$rc" -ne 0 ]; then
    echo "tier-1 FAILED (rc=$rc)" >&2
    exit "$rc"
fi

if [ "${1:-}" != "--no-chaos" ]; then
    echo "--- chaos smoke (fault-injection e2e, non-slow subset) ---"
    timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
        -m 'chaos and not slow' -p no:cacheprovider -p no:xdist -p no:randomly
    crc=$?
    if [ "$crc" -ne 0 ]; then
        echo "chaos smoke FAILED (rc=$crc)" >&2
        exit "$crc"
    fi
fi

echo "--- ingest fuzz smoke (native vs Python differential, 5 seeds) ---"
timeout -k 10 300 python scripts/fuzz_ingest.py --seeds 5 --cases 20
frc=$?
if [ "$frc" -ne 0 ]; then
    echo "ingest fuzz smoke FAILED (rc=$frc)" >&2
    exit "$frc"
fi
echo "tier-1 OK"
